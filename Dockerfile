# drand_tpu node image (reference: /root/reference/Dockerfile).
#
# CPU-only by default; on a TPU VM swap the jax pin for the libtpu
# wheel (pip install 'jax[tpu]' -f
# https://storage.googleapis.com/jax-releases/libtpu_releases.html)
# and the daemon's `--backend auto` picks the device kernels up.

FROM python:3.12-slim

RUN apt-get update \
    && apt-get install -y --no-install-recommends g++ curl \
    && rm -rf /var/lib/apt/lists/*

RUN pip install --no-cache-dir \
    "jax[cpu]" \
    grpcio \
    protobuf \
    aiohttp \
    cryptography \
    numpy

WORKDIR /opt/drand_tpu
COPY drand_tpu/ drand_tpu/
COPY README.md .

# Build the native C++ crypto backend and pre-populate the persistent XLA
# compile cache for the daemon's standard kernel shapes at image build
# time, so the first verify of a fresh container is milliseconds, not a
# multi-minute cold compile (`drand-tpu warmup`).
RUN python -c "from drand_tpu.crypto import native_bls; \
    assert native_bls.available(), 'native BLS build failed'; \
    assert native_bls.selfcheck() == 0" \
    && python -m drand_tpu.cli warmup

# public gRPC port / REST gateway / localhost control
EXPOSE 8080 8081
VOLUME /data

ENTRYPOINT ["python", "-m", "drand_tpu.cli", "--folder", "/data"]
CMD ["start", "--listen", "0.0.0.0:8080", "--rest-port", "8081"]
