# drand_tpu node image (reference: /root/reference/Dockerfile).
#
# CPU-only by default; on a TPU VM swap the jax pin for the libtpu
# wheel (pip install 'jax[tpu]' -f
# https://storage.googleapis.com/jax-releases/libtpu_releases.html)
# and the daemon's `--backend auto` picks the device kernels up.

FROM python:3.12-slim

RUN apt-get update \
    && apt-get install -y --no-install-recommends g++ curl \
    && rm -rf /var/lib/apt/lists/*

RUN pip install --no-cache-dir \
    "jax[cpu]" \
    grpcio \
    protobuf \
    aiohttp \
    cryptography \
    numpy

WORKDIR /opt/drand_tpu
COPY drand_tpu/ drand_tpu/
COPY README.md .

# public gRPC port / REST gateway / localhost control
EXPOSE 8080 8081
VOLUME /data

ENTRYPOINT ["python", "-m", "drand_tpu.cli", "--folder", "/data"]
CMD ["start", "--listen", "0.0.0.0:8080", "--rest-port", "8081"]
