#!/usr/bin/env python
"""Integration bring-up: a 5-node subprocess network with REST checks.

The reference's integration tier boots a docker-compose network and
curl-asserts the REST API (/root/reference/test/test-integration/
run_local.sh, docker_test.sh).  This is the same tier over plain
subprocesses: real daemons, real gRPC mesh, real DKG, then `curl`
assertions against the REST gateway, a verified client fetch, and a
`check-group` probe.  One command, asserting fetched beacons:

    make integration        (or: python deploy/integration.py)

Exit code 0 = every assertion passed.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import tempfile
import time
from pathlib import Path

# the protocol tier is scheme-agnostic; default the subprocess daemons to
# the native C++ backend: no device-kernel compiles (the device path is
# covered by bench.py / tests) and millisecond verifies instead of the
# oracle's 10s-per-pairing (falls back to the oracle if the lib can't
# build)
os.environ.setdefault("DRAND_TPU_BACKEND", "native")

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from demo.orchestrator import Orchestrator  # noqa: E402

N = 5
# five pure-Python daemons share one core in CI; the reference's default
# period is 60s (core/constants.go:27) — 30s keeps honest headroom
PERIOD = 30


def log(msg: str) -> None:
    print(f"[integration +{time.strftime('%H:%M:%S')}] {msg}", flush=True)


def curl_json(url: str) -> dict:
    out = subprocess.run(
        ["curl", "-sSf", url], capture_output=True, timeout=30
    )
    if out.returncode != 0:
        raise RuntimeError(
            f"curl {url}: {out.stderr.decode(errors='replace')}"
        )
    return json.loads(out.stdout.decode())


def wait_round_rest(rest: str, rnd: int, period: int,
                    timeout: float = 420.0) -> dict:
    """Wait until the chain head reaches at least `rnd`, via cheap curl
    polling; returns the latest beacon.

    Polling with the python CLI would spawn a ~10s-CPU subprocess per
    attempt and starve the daemons' round production on a small host
    (the whole network shares one core); curl costs nothing.  Rounds are
    indexed by wall time (ticker is king) — a network whose DKG outlives
    the genesis window joins at the *current* round, so specific early
    round numbers may legitimately not exist."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        try:
            j = curl_json(f"{rest}/api/public")
            if j["round"] >= rnd:
                return j
        except RuntimeError:
            pass
        time.sleep(period / 2)
    raise TimeoutError(f"round {rnd} never appeared at {rest}")


def main() -> int:
    base = Path(tempfile.mkdtemp(prefix="drand-tpu-integration-"))
    # generous genesis window: five daemons boot serially on small hosts
    orch = Orchestrator(N, base, period=f"{PERIOD}s", genesis_delay=120)
    try:
        log(f"setting up {N} nodes (period {PERIOD}s) in {base}")
        orch.setup_keys()
        orch.create_group()
        orch.start_all()

        log("probing the mesh with check-group")
        node0 = orch.nodes[0]
        probe = node0.cli("check-group", str(orch.group_file))
        assert f"{N}/{N} nodes reachable" in probe.stdout, probe.stdout

        log("running the DKG")
        dist = orch.run_dkg(orch.nodes[0], orch.nodes)
        log(f"collective key {dist[:16]}…")

        # ---- REST assertions via curl (reference run_local.sh) ----------
        rest = f"http://127.0.0.1:{orch.nodes[0].rest_port}"
        j = wait_round_rest(rest, 1, PERIOD)
        first = j["round"]
        log(f"round {first} produced: randomness {j['randomness'][:16]}…")
        assert len(bytes.fromhex(j["signature"])) == 96
        assert len(bytes.fromhex(j["randomness"])) == 32
        by_round = curl_json(f"{rest}/api/public/{first}")
        assert by_round["signature"] == j["signature"]
        dk = curl_json(f"{rest}/api/info/distkey")
        assert dk["coefficients"][0] == dist, dk
        log("REST checks passed (latest, by-round, distkey)")

        # ---- one more round to prove liveness ---------------------------
        b2 = wait_round_rest(rest, first + 1, PERIOD)
        assert b2["round"] >= first + 1
        log(f"round {b2['round']} produced: "
            f"randomness {b2['randomness'][:16]}…")

        # ---- verified client fetch (refuses bad signatures) -------------
        got = orch.fetch_beacon(orch.nodes[2], round=first)
        assert got["Signature"] == j["signature"]
        log("verified client fetch (gRPC, another node) matches REST")

        log("INTEGRATION OK")
        return 0
    finally:
        orch.stop_all()
        orch.cleanup()


if __name__ == "__main__":
    sys.exit(main())
