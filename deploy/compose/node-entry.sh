#!/bin/bash
# Container entrypoint for the docker-compose integration networks
# (reference: test/test-integration/*/data/client-script.sh).
#
# Each node: generates its keypair, publishes its public key (and, in the
# TLS variant, a self-signed cert) onto the shared /shared volume, waits
# for the full committee, boots the daemon, and joins the DKG
# (followers first, leader last — reference core/control.go:20).
#
# Environment:
#   NODE_INDEX  1..N           this node's index (node1 is the leader)
#   NODES       N              committee size
#   PORT        gRPC port      (REST is PORT+1)
#   TLS         0|1            TLS-everywhere variant
set -euo pipefail

: "${NODE_INDEX:?}" "${NODES:?}" "${PORT:=8080}" "${TLS:=0}"
HOST="node${NODE_INDEX}"
ADDR="${HOST}:${PORT}"
SHARED=/shared
FOLDER=/data
REST_PORT=$((PORT + 1))
CLI=(python -m drand_tpu.cli --folder "$FOLDER")

log() { echo "[entry ${HOST}] $*"; }

mkdir -p "$SHARED/keys" "$SHARED/certs"

gen_tls_args=()
start_tls_args=()
if [ "$TLS" = "1" ]; then
    # self-signed cert with the service-name SAN; peers trust via the
    # shared certs dir (reference net/certs.go CertManager pool)
    python - <<PY
from drand_tpu.net.tls import generate_self_signed
cert, key = generate_self_signed("${HOST}")
open("${FOLDER}/tls.crt", "wb").write(cert)
open("${FOLDER}/tls.key", "wb").write(key)
open("${SHARED}/certs/${HOST}.pem", "wb").write(cert)
PY
    gen_tls_args=(--tls)
    start_tls_args=(--tls-cert "$FOLDER/tls.crt" --tls-key "$FOLDER/tls.key"
                    --certs-dir "$SHARED/certs")
fi

"${CLI[@]}" generate-keypair "${gen_tls_args[@]}" "$ADDR"
cp "$FOLDER/key/public.toml" "$SHARED/keys/${HOST}.toml"

log "waiting for $NODES public keys"
while [ "$(ls "$SHARED/keys" | wc -l)" -lt "$NODES" ]; do sleep 1; done
if [ "$TLS" = "1" ]; then
    while [ "$(ls "$SHARED/certs" | wc -l)" -lt "$NODES" ]; do sleep 1; done
fi

if [ "$NODE_INDEX" = "1" ]; then
    # leader assembles the group: genesis far enough out that the DKG
    # (CPU-bound deals on a shared host) lands inside the window
    "${CLI[@]}" group "$SHARED"/keys/*.toml \
        --period "${PERIOD:-30s}" --genesis "$(( $(date +%s) + 120 ))" \
        --out "$SHARED/group.toml.tmp"
    mv "$SHARED/group.toml.tmp" "$SHARED/group.toml"
else
    while [ ! -f "$SHARED/group.toml" ]; do sleep 1; done
fi

"${CLI[@]}" start --listen "0.0.0.0:${PORT}" --rest-port "$REST_PORT" \
    "${start_tls_args[@]}" &
DAEMON=$!
sleep 3

if [ "$NODE_INDEX" = "1" ]; then
    # leader last: give followers a head start to register
    sleep 6
    "${CLI[@]}" share "$SHARED/group.toml" --leader --timeout 240
else
    "${CLI[@]}" share "$SHARED/group.toml" --timeout 240
fi
log "DKG done; serving"
wait "$DAEMON"
