#!/bin/bash
# Containerised integration test driver
# (reference: test/test-integration/docker_test.sh + run_local.sh).
#
#   deploy/compose/run.sh notls     # plaintext network
#   deploy/compose/run.sh tls       # TLS-everywhere network
#
# Builds the node image, boots a 5-node compose network that performs its
# own DKG, then curl-asserts from the host that (a) the chain head
# advances across two successive rounds, (b) two nodes agree on the same
# randomness for the same round, and (c) the REST surface serves the
# group and dist key.  Requires docker + docker compose.
set -euo pipefail

VARIANT="${1:-notls}"
case "$VARIANT" in
  notls|tls) ;;
  *) echo "usage: $0 [notls|tls]" >&2; exit 2 ;;
esac
cd "$(dirname "$0")"
COMPOSE=(docker compose -f "docker-compose.${VARIANT}.yml" -p "drand-tpu-${VARIANT}")

fail() { echo "FAIL: $*" >&2; "${COMPOSE[@]}" logs --tail 50 || true; "${COMPOSE[@]}" down -v || true; exit 1; }

cleanup() { "${COMPOSE[@]}" down -v >/dev/null 2>&1 || true; }
trap cleanup EXIT

echo "[+] building node image"
"${COMPOSE[@]}" build
echo "[+] booting ${VARIANT} network"
"${COMPOSE[@]}" up -d

# In the tls variant REST is served over https with per-node self-signed
# certs; -k skips host-side verification (the nodes verify each other via
# the shared trust pool, which is what the variant exercises).
CURL=(curl -sSf)
SCHEME=http
if [ "$VARIANT" = "tls" ]; then CURL=(curl -sSfk); SCHEME=https; fi

api() { "${CURL[@]}" "${SCHEME}://127.0.0.1:$1/api/$2"; }

echo "[+] waiting for the DKG + first beacons (genesis T+120s)"
deadline=$(( $(date +%s) + 420 ))
round=""
while [ "$(date +%s)" -lt "$deadline" ]; do
    if out=$(api 18081 public 2>/dev/null); then
        round=$(echo "$out" | python3 -c 'import json,sys; print(json.load(sys.stdin)["round"])' 2>/dev/null || true)
        [ -n "$round" ] && [ "$round" -ge 1 ] && break
    fi
    sleep 10
done
[ -n "$round" ] && [ "$round" -ge 1 ] || fail "no beacon appeared within 420s"
echo "    head at round $round"

echo "[+] asserting the chain advances"
next=$(( round + 1 ))
deadline=$(( $(date +%s) + 120 ))
r2=0
while [ "$(date +%s)" -lt "$deadline" ]; do
    # guard every curl/parse: a transient REST hiccup must retry, not
    # abort through set -e without the fail() diagnostics
    if out=$(api 18081 public 2>/dev/null); then
        r2=$(echo "$out" | python3 -c 'import json,sys; print(json.load(sys.stdin)["round"])' 2>/dev/null || echo 0)
        [ "$r2" -ge "$next" ] && break
    fi
    sleep 5
done
[ "$r2" -ge "$next" ] || fail "chain stuck at round $round"
echo "    advanced to round $r2"

echo "[+] asserting two nodes agree on round $round"
a=$(api 18081 "public/$round" | python3 -c 'import json,sys; print(json.load(sys.stdin)["randomness"])' 2>/dev/null) || fail "fetch round $round from node1"
b=$(api 18083 "public/$round" | python3 -c 'import json,sys; print(json.load(sys.stdin)["randomness"])' 2>/dev/null) || fail "fetch round $round from node3"
[ -n "$a" ] && [ "$a" = "$b" ] || fail "nodes disagree: $a vs $b"
echo "    agreed: ${a:0:16}..."

echo "[+] asserting group + dist key are served"
api 18082 info/group >/dev/null || fail "info/group endpoint"
api 18082 info/distkey >/dev/null || fail "info/distkey endpoint"

echo "TESTS OK (${VARIANT})"
