"""Process-level orchestrator: real daemons, real clock, full lifecycle.

Mirrors /root/reference/demo/orchestrator.go + demo/node.go: spawn real
`drand_tpu.cli` daemons as subprocesses, build the group file, drive the
DKG through the control ports, fetch verified beacons each period, kill
and restart nodes, stop/restart the whole network, and reshare to a new
group — asserting chain continuity throughout (reference scenario
demo/main.go:28-109).

Usage:  python demo/main.py  (see main.py for the scenario).
"""

from __future__ import annotations

import os
import shutil
import socket
import subprocess
import sys
import time
from drand_tpu.utils import tomlcompat as tomllib
from pathlib import Path
from typing import Dict, List, Optional

REPO = Path(__file__).resolve().parent.parent


def free_ports(n: int) -> List[int]:
    socks = [socket.socket() for _ in range(n)]
    for s in socks:
        s.bind(("127.0.0.1", 0))
    ports = [s.getsockname()[1] for s in socks]
    for s in socks:
        s.close()
    return ports


class Node:
    """One drand-tpu daemon process (reference demo/node.go:42)."""

    def __init__(self, index: int, base: Path, port: int, ctrl: int,
                 rest_port: Optional[int] = None):
        self.index = index
        self.folder = base / f"node{index}"
        self.addr = f"127.0.0.1:{port}"
        self.ctrl = ctrl
        self.rest_port = rest_port
        self.proc: Optional[subprocess.Popen] = None
        self.log = base / f"node{index}.log"

    # -- CLI helpers ------------------------------------------------------

    def _env(self) -> Dict[str, str]:
        env = dict(os.environ)
        env["PYTHONPATH"] = str(REPO)
        env.setdefault("JAX_PLATFORMS", "cpu")
        return env

    def cli(self, *args: str, timeout: float = 180.0,
            check: bool = True) -> subprocess.CompletedProcess:
        cmd = [sys.executable, "-m", "drand_tpu.cli",
               "--folder", str(self.folder), "--control", str(self.ctrl),
               *args]
        try:
            r = subprocess.run(
                cmd, capture_output=True, text=True, timeout=timeout,
                env=self._env(),
            )
        except subprocess.TimeoutExpired as exc:
            if check:
                raise
            # tolerated probe timeout (loaded host): report as rc 124
            def _txt(v):
                if isinstance(v, bytes):
                    return v.decode(errors="replace")
                return v or ""

            r = subprocess.CompletedProcess(
                cmd, 124, stdout=_txt(exc.stdout), stderr=_txt(exc.stderr)
            )
        if check and r.returncode != 0:
            raise RuntimeError(
                f"node{self.index} cli {args} failed:\n"
                f"{r.stdout}\n{r.stderr}"
            )
        return r

    def cli_async(self, *args: str) -> subprocess.Popen:
        return subprocess.Popen(
            [sys.executable, "-m", "drand_tpu.cli",
             "--folder", str(self.folder), "--control", str(self.ctrl),
             *args],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env=self._env(),
        )

    # -- lifecycle --------------------------------------------------------

    def keygen(self) -> Path:
        self.cli("generate-keypair", self.addr)
        return self.folder / "key" / "public.toml"

    def start(self) -> None:
        assert self.proc is None or self.proc.poll() is not None
        args = [sys.executable, "-m", "drand_tpu.cli",
                "--folder", str(self.folder), "--control", str(self.ctrl)]
        if os.environ.get("DRAND_TPU_VERBOSE"):
            args.append("--verbose")
        args.append("start")
        if self.rest_port:
            args += ["--rest-port", str(self.rest_port)]
        logfh = open(self.log, "a")
        self.proc = subprocess.Popen(
            args, stdout=logfh, stderr=subprocess.STDOUT, text=True,
            env=self._env(),
        )

    def wait_ready(self, timeout: float = 240.0) -> None:
        """Generous: on a loaded 1-core host, N daemons booting plus the
        ping subprocess itself (each pays interpreter+import startup)
        easily exceed a minute."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            r = self.cli("ping", check=False, timeout=60)
            if r.returncode == 0:
                return
            time.sleep(1.0)
        raise TimeoutError(f"node{self.index} did not become ready")

    def stop(self, timeout: float = 30.0) -> None:
        """Graceful stop through the control port."""
        if self.proc is None:
            return
        self.cli("stop", check=False)
        try:
            self.proc.wait(timeout=timeout)
        except subprocess.TimeoutExpired:
            self.proc.terminate()
            self.proc.wait(timeout=10)
        self.proc = None

    def kill(self) -> None:
        """Hard kill (fault injection, reference demo/main.go:60-90)."""
        if self.proc is not None:
            self.proc.kill()
            self.proc.wait(timeout=10)
            self.proc = None

    def alive(self) -> bool:
        return self.proc is not None and self.proc.poll() is None


class Orchestrator:
    """Scenario driver (reference demo/orchestrator.go:44)."""

    def __init__(self, n: int, base: Path, period: str = "20s",
                 genesis_delay: int = 60):
        self.base = base
        self.period = period
        self.period_s = float(period.rstrip("s"))
        ports = free_ports(2 * n + 1)
        self.nodes = [
            Node(i, base, ports[i], ports[n + i],
                 rest_port=ports[2 * n] if i == 0 else None)
            for i in range(n)
        ]
        self.group_file = base / "group.toml"
        self.genesis_delay = genesis_delay
        self.genesis: Optional[int] = None
        self.dist_key_hex: Optional[str] = None

    # -- setup ------------------------------------------------------------

    def setup_keys(self) -> None:
        for node in self.nodes:
            node.keygen()

    def create_group(self, nodes: Optional[List[Node]] = None,
                     threshold: Optional[int] = None) -> None:
        nodes = nodes or self.nodes
        pubs = [str(n.folder / "key" / "public.toml") for n in nodes]
        self.genesis = int(time.time()) + self.genesis_delay
        args = ["group", *pubs, "--period", self.period,
                "--genesis", str(self.genesis),
                "--out", str(self.group_file)]
        if threshold:
            args += ["--threshold", str(threshold)]
        self.nodes[0].cli(*args)

    def start_all(self) -> None:
        # serial boot: concurrent interpreter+jax imports thrash small
        # hosts; each node is confirmed ready before the next launches
        for node in self.nodes:
            node.start()
            node.wait_ready()

    def run_dkg(self, leader: Node, members: List[Node],
                timeout: float = 300.0) -> str:
        """Followers first, leader last (reference control.go:20)."""
        # generous in-protocol DKG timeout: schnorr-authenticated
        # deals/responses cost real CPU on a shared-core host
        waits = [
            m.cli_async("share", str(self.group_file), "--timeout", "240")
            for m in members if m is not leader
        ]
        time.sleep(2)
        lead = leader.cli("share", str(self.group_file), "--leader",
                          "--timeout", "240", timeout=timeout)
        assert "distributed key:" in lead.stdout, lead.stdout
        self.dist_key_hex = lead.stdout.split("distributed key:")[1].strip()
        for p in waits:
            out, _ = p.communicate(timeout=timeout)
            if p.returncode != 0:
                raise RuntimeError(f"share failed: {out}")
        return self.dist_key_hex

    def run_reshare(self, leader: Node, members: List[Node],
                    new_group_file: Path, old_group_file: Path,
                    retiring: List[Node],
                    timeout: float = 300.0) -> None:
        """Resharing: every old ∪ new node runs `share --reshare`."""
        waits = []
        for m in members + retiring:
            if m is leader:
                continue
            waits.append(m.cli_async(
                "share", str(new_group_file), "--reshare",
                "--from-group", str(old_group_file), "--timeout", "240",
            ))
        time.sleep(2)
        leader.cli("share", str(new_group_file), "--leader", "--reshare",
                   "--from-group", str(old_group_file),
                   "--timeout", "240", timeout=timeout)
        for p in waits:
            out, _ = p.communicate(timeout=timeout)
            if p.returncode != 0:
                raise RuntimeError(f"reshare share failed: {out}")

    # -- assertions -------------------------------------------------------

    def fetch_beacon(self, via: Node, round: int = 0,
                     timeout: float = 60.0) -> dict:
        """Fetch + client-side-verify a beacon through a node."""
        deadline = time.monotonic() + timeout
        last_err = ""
        while time.monotonic() < deadline:
            r = via.cli(
                "get", "public", str(self.group_file),
                "--node", via.addr, "--round", str(round),
                "--distkey", self.dist_key_hex or "",
                check=False,
            )
            if r.returncode == 0 and "Randomness" in r.stdout:
                out = {}
                for line in r.stdout.splitlines():
                    if "=" in line:
                        k, v = line.split("=", 1)
                        out[k.strip()] = v.strip().strip('"')
                return out
            last_err = r.stdout + r.stderr
            time.sleep(2)
        raise TimeoutError(
            f"no beacon for round {round} via node{via.index}: {last_err}"
        )

    def wait_round(self, rnd: int, via: Node,
                   timeout: float = 300.0) -> dict:
        """Wait until `rnd` exists, verifying it on fetch."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            try:
                return self.fetch_beacon(via, rnd, timeout=10)
            except TimeoutError:
                time.sleep(self.period_s / 4)
        raise TimeoutError(f"round {rnd} never appeared")

    # -- teardown ---------------------------------------------------------

    def stop_all(self) -> None:
        for node in self.nodes:
            if node.alive():
                node.stop()

    def cleanup(self) -> None:
        self.stop_all()
        shutil.rmtree(self.base, ignore_errors=True)


def load_group_toml(path: Path) -> dict:
    with open(path, "rb") as fh:
        return tomllib.load(fh)
