"""Full-lifecycle demo scenario over real daemon processes.

Mirrors /root/reference/demo/main.go:28-109: boot a 5-node network, run
the DKG, fetch verified beacons each period, hard-kill a node and watch
the threshold absorb it, restart it and watch it catch up, stop and
restart the whole network, then reshare to a new group (one member
retires, one joins) and confirm the chain continues under the same
collective key.

Run:  python demo/main.py [--nodes 5] [--period 30] [--keep]
"""

from __future__ import annotations

import argparse
import sys
import tempfile
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from demo.orchestrator import (  # noqa: E402
    Node,
    Orchestrator,
    free_ports,
)


def log(msg: str) -> None:
    print(f"[demo +{time.strftime('%H:%M:%S')}] {msg}", flush=True)


def scenario(n: int, period: int, base: Path) -> None:
    orch = Orchestrator(
        n, base, period=f"{period}s", genesis_delay=max(45, period)
    )
    log(f"setting up {n} nodes, period {period}s")
    orch.setup_keys()
    orch.create_group()
    orch.start_all()
    log("daemons up; running DKG")
    dist = orch.run_dkg(orch.nodes[0], orch.nodes)
    log(f"DKG done, collective key {dist[:16]}…")

    via = orch.nodes[1]
    b1 = orch.wait_round(1, via)
    log(f"round 1: randomness {b1['Randomness'][:16]}…")
    b2 = orch.wait_round(2, via)
    log(f"round 2: randomness {b2['Randomness'][:16]}…")

    # -- fault injection: hard-kill one node ------------------------------
    victim = orch.nodes[-1]
    log(f"killing node{victim.index}")
    victim.kill()
    b = orch.wait_round(3, via)
    log(f"round 3 without node{victim.index}: "
        f"{b['Randomness'][:16]}… (threshold absorbed the fault)")

    log(f"restarting node{victim.index}")
    victim.start()
    victim.wait_ready()
    b = orch.wait_round(4, victim)
    log(f"node{victim.index} caught up and serves round 4: "
        f"{b['Randomness'][:16]}…")

    # -- full-network stop/restart ---------------------------------------
    log("stopping the whole network")
    orch.stop_all()
    time.sleep(2 * period)
    log("restarting the whole network")
    for node in orch.nodes:
        node.start()
    for node in orch.nodes:
        node.wait_ready()
    elapsed_rounds = int((time.time() - orch.genesis) / period) + 2
    b = orch.wait_round(elapsed_rounds, via)
    log(f"chain resumed after full restart at round {elapsed_rounds}: "
        f"{b['Randomness'][:16]}…")

    # -- resharing: node 0 retires, a brand-new node joins ----------------
    newcomer_ports = free_ports(2)
    newcomer = Node(n, base, newcomer_ports[0], newcomer_ports[1])
    newcomer.keygen()
    newcomer.start()
    newcomer.wait_ready()
    orch.nodes.append(newcomer)

    members = orch.nodes[1:]  # node0 retires
    pubs = [str(m.folder / "key" / "public.toml") for m in members]
    new_group_file = base / "group2.toml"
    head = int((time.time() - orch.genesis) / period) + 1
    transition = orch.genesis + (head + 3) * period
    orch.nodes[1].cli(
        "group", *pubs, "--period", f"{period}s",
        "--genesis", str(orch.genesis), "--out", str(new_group_file),
    )
    # patch transition time into the group file (operator step)
    text = new_group_file.read_text()
    text = text.replace(
        "TransitionTime = 0", f"TransitionTime = {transition}"
    )
    if "TransitionTime" not in text:
        text += f"\nTransitionTime = {transition}\n"
    new_group_file.write_text(text)

    log(f"resharing to {len(members)} nodes "
        f"(node0 retires, node{newcomer.index} joins); "
        f"transition at round {head + 3}")
    orch.run_reshare(
        members[0], members, new_group_file, orch.group_file,
        retiring=[orch.nodes[0]],
    )
    orch.group_file = new_group_file
    target = head + 4
    b = orch.wait_round(target, newcomer, timeout=(6 + 4) * period)
    log(f"post-reshare round {target} via the NEW member: "
        f"{b['Randomness'][:16]}… (same collective key)")
    log("scenario complete ✔")


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--nodes", type=int, default=5)
    ap.add_argument("--period", type=int, default=30)
    ap.add_argument("--keep", action="store_true",
                    help="keep the working directory")
    ap.add_argument("--workdir", default=None)
    args = ap.parse_args()

    base = Path(args.workdir or tempfile.mkdtemp(prefix="drand-tpu-demo-"))
    base.mkdir(parents=True, exist_ok=True)
    try:
        scenario(args.nodes, args.period, base)
        return 0
    finally:
        # best-effort teardown: stop every daemon whose log dir is here
        import subprocess
        subprocess.run(
            ["pkill", "-f", f"drand_tpu.cli.*{base}"],
            capture_output=True,
        )
        if not args.keep:
            import shutil
            shutil.rmtree(base, ignore_errors=True)
        else:
            print(f"workdir kept at {base}")


if __name__ == "__main__":
    sys.exit(main())
