# drand_tpu build/test targets (reference Makefile:6-13 equivalents).

PY ?= python

.PHONY: test test-slow test-native-san lint bench bench-suite \
	integration demo warmup compose-test compose-test-tls clean

# pre-compile device kernels into the persistent XLA cache
warmup:
	$(PY) -m drand_tpu.cli warmup

# containerised integration networks (reference
# test/test-integration/docker_test.sh: notls + tls variants)
compose-test:
	deploy/compose/run.sh notls

compose-test-tls:
	deploy/compose/run.sh tls

test:
	$(PY) -m pytest tests/ -x -q

test-slow:
	$(PY) -m pytest tests/ -x -q -m "slow or not slow"

# native C++ backends rebuilt with ASan+UBSan, test suites run with
# the sanitizer runtime preloaded (tools/native_san.py sets that up)
test-native-san:
	$(PY) tools/native_san.py

# static analysis: the drand-lint ratchet (tools/drandlint) + the
# mypy --strict beachhead (mypy.ini).  mypy is optional locally —
# CI always runs it.
lint:
	$(PY) -m tools.drandlint --baseline .drandlint-baseline.json
	@if $(PY) -c "import mypy" 2>/dev/null; then \
		$(PY) -m mypy; \
	else \
		echo "mypy not installed; skipping (the CI lint job runs it)"; \
	fi

bench:
	$(PY) bench.py

bench-suite:
	$(PY) bench_suite.py

# 5-node subprocess network with REST checks (reference
# test/test-integration/run_local.sh)
integration:
	$(PY) deploy/integration.py

# full lifecycle scenario: DKG, kill/restart, reshare
# (reference demo/main.go via make test-integration)
demo:
	$(PY) demo/main.py

clean:
	find . -name __pycache__ -type d -exec rm -rf {} +
	rm -rf .pytest_cache
