"""Regenerate drand_tpu/net/drand_tpu_pb2.py without protoc.

The container has `google.protobuf` but no `grpc_tools`/`protoc`, so
this script rebuilds the serialized FileDescriptorProto from scratch —
the authoritative schema is net/protos/drand_tpu.proto, and this file
must be kept in sync with it by hand (field names, numbers, types).
The emitted module matches protoc's layout: AddSerializedFile + builder
calls + the pure-python offsets block.

Run:  python tools/gen_proto.py
"""

from __future__ import annotations

import os

from google.protobuf import descriptor_pb2 as dp

F = dp.FieldDescriptorProto

OUT = os.path.join(os.path.dirname(__file__), os.pardir,
                   "drand_tpu", "net", "drand_tpu_pb2.py")


def field(name, number, ftype, label=F.LABEL_OPTIONAL, type_name=None,
          oneof_index=None):
    f = F(name=name, number=number, type=ftype, label=label)
    if type_name is not None:
        f.type_name = type_name
    if oneof_index is not None:
        f.oneof_index = oneof_index
    return f


def msg(name, *fields, oneofs=()):
    d = dp.DescriptorProto(name=name)
    d.field.extend(fields)
    for o in oneofs:
        d.oneof_decl.add(name=o)
    return d


def build_file() -> dp.FileDescriptorProto:
    fd = dp.FileDescriptorProto(
        name="drand_tpu.proto", package="drandtpu", syntax="proto3"
    )
    m = fd.message_type
    U64, U32, BYT, STR, BOO, DBL = (F.TYPE_UINT64, F.TYPE_UINT32,
                                    F.TYPE_BYTES, F.TYPE_STRING,
                                    F.TYPE_BOOL, F.TYPE_DOUBLE)
    REP = F.LABEL_REPEATED

    # -- public ---------------------------------------------------------
    m.append(msg("PublicRandRequest", field("round", 1, U64)))
    m.append(msg("PublicRandResponse",
                 field("round", 1, U64),
                 field("previous_round", 2, U64),
                 field("previous_signature", 3, BYT),
                 field("signature", 4, BYT),
                 field("randomness", 5, BYT)))
    m.append(msg("PrivateRandRequest", field("request", 1, BYT)))
    m.append(msg("PrivateRandResponse", field("response", 1, BYT)))
    m.append(msg("GroupRequest"))
    m.append(msg("GroupResponse", field("group_toml", 1, STR)))
    m.append(msg("HomeRequest"))
    m.append(msg("HomeResponse", field("status", 1, STR)))

    # -- protocol -------------------------------------------------------
    m.append(msg("BeaconPacketMsg",
                 field("from_address", 1, STR),
                 field("round", 2, U64),
                 field("previous_round", 3, U64),
                 field("previous_signature", 4, BYT),
                 field("partial_signature", 5, BYT),
                 field("trace_id", 6, STR),
                 field("sent_at", 7, DBL)))
    m.append(msg("Empty"))
    m.append(msg("SyncRequest", field("from_round", 1, U64)))
    m.append(msg("BeaconRecord",
                 field("round", 1, U64),
                 field("previous_round", 2, U64),
                 field("previous_signature", 3, BYT),
                 field("signature", 4, BYT)))
    m.append(msg("DealMsg",
                 field("dealer_index", 1, U32),
                 field("recipient_index", 2, U32),
                 field("commits", 3, BYT, REP),
                 field("encrypted_share", 4, BYT),
                 field("signature", 5, BYT)))
    m.append(msg("ResponseMsg",
                 field("dealer_index", 1, U32),
                 field("verifier_index", 2, U32),
                 field("approved", 3, BOO),
                 field("signature", 4, BYT)))
    m.append(msg("JustificationMsg",
                 field("dealer_index", 1, U32),
                 field("verifier_index", 2, U32),
                 field("share_value", 3, BYT),
                 field("commits", 4, BYT, REP),
                 field("signature", 5, BYT)))
    m.append(msg("DKGPacketMsg",
                 field("group_hash", 2, BYT),
                 field("deal", 3, F.TYPE_MESSAGE,
                       type_name=".drandtpu.DealMsg", oneof_index=0),
                 field("response", 4, F.TYPE_MESSAGE,
                       type_name=".drandtpu.ResponseMsg", oneof_index=0),
                 field("justification", 5, F.TYPE_MESSAGE,
                       type_name=".drandtpu.JustificationMsg",
                       oneof_index=0),
                 oneofs=("body",)))

    # -- verify (serve/ gateway) ---------------------------------------
    m.append(msg("VerifyBeaconRequest",
                 field("round", 1, U64),
                 field("previous_round", 2, U64),
                 field("previous_signature", 3, BYT),
                 field("signature", 4, BYT),
                 field("timeout_seconds", 5, DBL),
                 field("trace_id", 6, STR),
                 field("claim_id", 7, U64)))
    m.append(msg("VerifyBeaconResponse",
                 field("valid", 1, BOO),
                 field("cached", 2, BOO),
                 field("batch_size", 3, U32),
                 field("error", 4, STR),
                 field("claim_id", 5, U64)))
    m.append(msg("VerifyBeaconBatchRequest",
                 field("items", 1, F.TYPE_MESSAGE, REP,
                       type_name=".drandtpu.VerifyBeaconRequest"),
                 field("timeout_seconds", 2, DBL)))
    m.append(msg("VerifyBeaconBatchResponse",
                 field("items", 1, F.TYPE_MESSAGE, REP,
                       type_name=".drandtpu.VerifyBeaconResponse")))

    # -- control --------------------------------------------------------
    m.append(msg("PingRequest"))
    m.append(msg("PingResponse"))
    m.append(msg("InitDKGRequest",
                 field("group_toml", 1, STR),
                 field("is_leader", 2, BOO),
                 field("timeout_seconds", 3, DBL),
                 field("entropy", 4, BYT)))
    m.append(msg("InitReshareRequest",
                 field("old_group_toml", 1, STR),
                 field("new_group_toml", 2, STR),
                 field("is_leader", 3, BOO),
                 field("timeout_seconds", 4, DBL),
                 field("entropy", 5, BYT)))
    m.append(msg("InitResponse", field("dist_key_hex", 1, STR)))
    m.append(msg("ShareRequest"))
    m.append(msg("ShareResponse",
                 field("index", 1, U32),
                 field("share_hex", 2, STR)))
    m.append(msg("KeyRequest"))
    m.append(msg("KeyResponse", field("key_hex", 1, STR)))
    m.append(msg("CollectiveKeyResponse",
                 field("coefficients_hex", 1, STR, REP)))
    m.append(msg("GroupFileRequest"))
    m.append(msg("ShutdownRequest"))
    m.append(msg("ShutdownResponse"))
    return fd


HEADER = '''# -*- coding: utf-8 -*-
# Generated by tools/gen_proto.py (no protoc in the toolchain).
# Schema source of truth: drand_tpu/net/protos/drand_tpu.proto
"""Generated protocol buffer code."""
from google.protobuf.internal import builder as _builder
from google.protobuf import descriptor as _descriptor
from google.protobuf import descriptor_pool as _descriptor_pool
from google.protobuf import symbol_database as _symbol_database
# @@protoc_insertion_point(imports)

_sym_db = _symbol_database.Default()




DESCRIPTOR = _descriptor_pool.Default().AddSerializedFile({blob!r})

_builder.BuildMessageAndEnumDescriptors(DESCRIPTOR, globals())
_builder.BuildTopDescriptorsAndMessages(DESCRIPTOR, 'drand_tpu_pb2', globals())
if _descriptor._USE_C_DESCRIPTORS == False:

  DESCRIPTOR._options = None
{offsets}# @@protoc_insertion_point(module_scope)
'''


def main() -> None:
    fd = build_file()
    blob = fd.SerializeToString()
    offsets = []
    for m in fd.message_type:
        sub = m.SerializeToString()
        start = blob.find(sub)
        assert start >= 0, m.name
        offsets.append(f"  _{m.name.upper()}._serialized_start={start}\n"
                       f"  _{m.name.upper()}._serialized_end="
                       f"{start + len(sub)}\n")
    out = HEADER.format(blob=blob, offsets="".join(offsets))
    with open(OUT, "w") as fh:
        fh.write(out)
    print(f"wrote {os.path.normpath(OUT)} "
          f"({len(fd.message_type)} messages, {len(blob)} descriptor "
          f"bytes)")


if __name__ == "__main__":
    main()
