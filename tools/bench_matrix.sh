#!/bin/sh
# On-chip conv-mode/batch ranking for the Pallas verify kernel.
# Writes one JSON line per config to bench_matrix.jsonl, each tagged
# with {"cfg": ...}; a config that fails still emits a line with
# {"cfg": ..., "failed": true, "rc": N} so rows never misalign with
# configs (ADVICE r4).  Output files are truncated at start so reruns
# never mix stale results.
# Usage: tools/bench_matrix.sh [outfile]
OUT=${1:-bench_matrix.jsonl}
: > "$OUT"
: > "$OUT.log"
TMP=$(mktemp)
trap 'rm -f "$TMP"' EXIT
run () {
  desc=$1; shift
  echo "### $desc ($(date -u +%H:%M:%S))" >> "$OUT.log"
  env "$@" BENCH_PROBE_TIMEOUT=120 timeout 1800 \
    python bench.py > "$TMP" 2>> "$OUT.log"
  rc=$?
  line=$(tail -1 "$TMP")
  CFG="$desc" LINE="$line" RC="$rc" python - >> "$OUT" <<'EOF'
import json, os
cfg, line, rc = os.environ["CFG"], os.environ["LINE"], int(os.environ["RC"])
try:
    rec = json.loads(line)
    if not isinstance(rec, dict):
        raise ValueError
except Exception:
    rec = {"failed": True, "rc": rc, "raw": line[:200]}
if rc != 0:
    rec.setdefault("failed", True)
    rec["rc"] = rc
print(json.dumps({"cfg": cfg, **rec}))
EOF
}
run "vpu e2e b1024"         DRAND_TPU_PALLAS_CONV=vpu
run "mxu e2e b1024"         DRAND_TPU_PALLAS_CONV=mxu
run "kara e2e b1024"        DRAND_TPU_PALLAS_CONV=kara
run "mxu+kara e2e b1024"    DRAND_TPU_PALLAS_CONV=mxu+kara
run "vpu shared-miller e2e b1024" DRAND_TPU_PALLAS_CONV=vpu DRAND_TPU_MILLER=shared
run "vpu device-only b1024" DRAND_TPU_PALLAS_CONV=vpu BENCH_DEVICE_ONLY=1
run "vpu e2e b2048"         DRAND_TPU_PALLAS_CONV=vpu BENCH_BATCH=2048 BENCH_ITERS=2
run "vpu e2e b4096"         DRAND_TPU_PALLAS_CONV=vpu BENCH_BATCH=4096 BENCH_ITERS=2
