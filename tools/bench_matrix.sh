#!/bin/sh
# On-chip conv-mode/batch ranking for the Pallas verify kernel.
# Appends one bench.py JSON line per config to bench_matrix.jsonl.
# Usage: tools/bench_matrix.sh [outfile]
OUT=${1:-bench_matrix.jsonl}
run () {
  desc=$1; shift
  echo "### $desc" >> "$OUT.log"
  env "$@" BENCH_PROBE_TIMEOUT=120 timeout 3600 \
    python bench.py 2>> "$OUT.log" | tail -1 >> "$OUT"
}
run "mxu e2e b1024"       DRAND_TPU_PALLAS_CONV=mxu
run "kara e2e b1024"      DRAND_TPU_PALLAS_CONV=kara
run "mxu+kara e2e b1024"  DRAND_TPU_PALLAS_CONV=mxu+kara
run "vpu device-only b1024" BENCH_DEVICE_ONLY=1
run "vpu e2e b2048"       BENCH_BATCH=2048 BENCH_ITERS=2
