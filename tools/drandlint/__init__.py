"""drand-lint: project-invariant static analysis for the drand_tpu tree.

The reference drand is Go and gets `go vet`, the race detector and the
compiler for free; this Python/asyncio/JAX port re-discovered the same
invariant classes by hand across five PRs (dispatch budget, sim replay
determinism, two asyncio liveness races).  drand-lint turns those
conventions into machine-checked rules:

* **hot-path purity** (`hp-*`) — device syncs only through the timed
  `kernel_span` idiom, `jax.jit` only in the kernel layers;
* **sim determinism** (`sim-*`) — no wall clock or ambient entropy
  inside `drand_tpu/sim/`;
* **asyncio discipline** (`aio-*`) — no slow awaits under a lock, no
  blocking calls on the event loop, no orphaned tasks, no handlers that
  can swallow cancellation;
* **registry drift** (`reg-*`) — flight-event kinds, metric names, shed
  reasons and `degraded_reason` literals resolve against their single
  source of truth, and the deploy dashboards/alerts reference only
  metrics the code actually emits.

Dependency-free (stdlib `ast` only).  Run as ``python -m tools.drandlint``
or ``python -m drand_tpu.cli lint``.  Violations are suppressed inline
with ``# drandlint: allow[rule-id] <reason>`` and ratcheted by a
committed baseline whose counts may only decrease.
"""

from tools.drandlint.engine import (  # noqa: F401
    ALL_RULES,
    LintConfig,
    Report,
    Violation,
    compare_baseline,
    run_lint,
)
