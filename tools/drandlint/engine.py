"""Core of drand-lint: source model, suppression syntax, rule protocol,
baseline ratchet and report rendering.

Everything here is deliberately boring: plain `ast` walks over a list of
`Source` objects, a `Project` that lazily extracts the canonical name
registries (EVENT_KINDS / METRIC_NAMES / SHED_REASONS / DEGRADED_REASONS)
*from the scanned tree's own AST* — the linter never imports the code it
checks, so it runs identically on the real tree and on the throwaway
fixture trees the unit tests build.
"""

from __future__ import annotations

import ast
import dataclasses
import json
import re
from pathlib import Path
from typing import Dict, Iterable, Iterator, List, Optional, Set, Tuple

BASELINE_SCHEMA = "drand-tpu.lint-baseline.v1"
REPORT_SCHEMA = "drand-tpu.lint.v1"

# -- source model --------------------------------------------------------

#: `# drandlint: allow[rule-id] reason` or `allow[rule-a,rule-b] reason`
_ALLOW_RE = re.compile(
    r"#\s*drandlint:\s*allow\[([A-Za-z0-9_,\s-]*)\]\s*(.*?)\s*$"
)


@dataclasses.dataclass
class Violation:
    rule: str
    path: str          # posix path relative to the lint root
    line: int
    col: int
    message: str
    suppressed: bool = False
    suppress_reason: str = ""

    def key(self) -> Tuple[str, int, str]:
        return (self.path, self.line, self.rule)

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


@dataclasses.dataclass
class Suppression:
    line: int          # line the suppression *covers*
    comment_line: int  # line the comment itself is on
    rules: Tuple[str, ...]
    reason: str


class Source:
    """One parsed python file plus its inline suppressions."""

    def __init__(self, path: Path, rel: str, text: str):
        self.path = path
        self.rel = rel
        self.text = text
        self.lines = text.splitlines()
        self.tree: Optional[ast.Module] = None
        self.parse_error: Optional[SyntaxError] = None
        try:
            self.tree = ast.parse(text)
        except SyntaxError as exc:
            self.parse_error = exc
        self.suppressions: List[Suppression] = self._parse_allows()

    def _parse_allows(self) -> List[Suppression]:
        out: List[Suppression] = []
        for i, line in enumerate(self.lines, start=1):
            m = _ALLOW_RE.search(line)
            if m is None:
                continue
            rules = tuple(
                r.strip() for r in m.group(1).split(",") if r.strip()
            )
            # a comment-only line covers the line below it; a trailing
            # comment covers its own line
            covers = i + 1 if line.lstrip().startswith("#") else i
            out.append(Suppression(line=covers, comment_line=i,
                                   rules=rules, reason=m.group(2).strip()))
        return out

    def allow_for(self, rule: str, line: int) -> Optional[Suppression]:
        for s in self.suppressions:
            if s.line == line and (rule in s.rules or "*" in s.rules):
                return s
        return None


# -- AST helpers shared by the rule packs --------------------------------

def dotted(node: ast.AST) -> Optional[str]:
    """'a.b.c' for Name/Attribute chains, None for anything fancier."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def first_str_arg(call: ast.Call) -> Optional[str]:
    if call.args and isinstance(call.args[0], ast.Constant) \
            and isinstance(call.args[0].value, str):
        return call.args[0].value
    return None


def kwarg_str(call: ast.Call, name: str) -> Optional[Tuple[str, ast.AST]]:
    for kw in call.keywords:
        if kw.arg == name and isinstance(kw.value, ast.Constant) \
                and isinstance(kw.value.value, str):
            return kw.value.value, kw.value
    return None


def str_elements(node: ast.AST) -> Iterator[str]:
    """String constants inside a (frozen)set/tuple/list literal, seeing
    through a `frozenset({...})` / `tuple((...))` wrapper call."""
    if isinstance(node, ast.Call) and node.args:
        fn = dotted(node.func)
        if fn in ("frozenset", "set", "tuple", "list"):
            node = node.args[0]
    if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
        for elt in node.elts:
            if isinstance(elt, ast.Constant) and isinstance(elt.value, str):
                yield elt.value


def imports_jax(tree: ast.Module) -> bool:
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            if any(a.name == "jax" or a.name.startswith("jax.")
                   for a in node.names):
                return True
        elif isinstance(node, ast.ImportFrom):
            if node.module and (node.module == "jax"
                                or node.module.startswith("jax.")):
                return True
    return False


# -- configuration -------------------------------------------------------

@dataclasses.dataclass
class LintConfig:
    """Path conventions the rule packs encode.  Everything is relative
    to the lint root so fixture trees in tests get the same treatment as
    the real repository."""

    #: the package all package-relative conventions anchor to
    package: str = "drand_tpu"
    #: the one sanctioned raw-sync file (kernel_span / block live here)
    sync_allowed: Tuple[str, ...] = ("obs/kernels.py",)
    #: where `jax.jit` declarations may live (dirs end with /)
    jit_allowed: Tuple[str, ...] = ("ops/", "parallel/", "crypto/tbls.py")
    #: kernel-definition land: host/device staging is the point, the
    #: untimed-sync heuristic does not apply
    untimed_sync_exempt: Tuple[str, ...] = ("ops/",)
    #: deterministic-simulation subtree
    sim_dirs: Tuple[str, ...] = ("sim/",)
    #: deploy artifacts cross-checked against emitted metrics
    deploy_files: Tuple[str, ...] = (
        "deploy/prometheus-alerts.yml",
        "deploy/grafana-dashboard.json",
    )
    #: drand_* tokens in deploy files that are not metric names
    deploy_token_allowlist: Tuple[str, ...] = ("drand_tpu",)

    def pkg_rel(self, rel: str) -> Optional[str]:
        """Path relative to the package root, or None if outside it."""
        prefix = self.package + "/"
        return rel[len(prefix):] if rel.startswith(prefix) else None


# -- project (cross-file state) ------------------------------------------

#: canonical registry constants the drift pack resolves literals against
_REGISTRY_NAMES = (
    "EVENT_KINDS", "METRIC_NAMES", "SHED_REASONS", "DEGRADED_REASONS",
)


class Project:
    def __init__(self, root: Path, config: LintConfig,
                 sources: List[Source]):
        self.root = root
        self.config = config
        self.sources = sources
        self._registries: Optional[Dict[str, Set[str]]] = None
        self._emitted_metrics: Optional[Set[str]] = None

    def registry(self, name: str) -> Set[str]:
        """String members of a canonical registry constant (for example
        ``EVENT_KINDS``), collected from plain assignments anywhere in
        the scanned tree."""
        if self._registries is None:
            regs: Dict[str, Set[str]] = {n: set() for n in _REGISTRY_NAMES}
            for src in self.sources:
                if src.tree is None:
                    continue
                for node in ast.walk(src.tree):
                    targets: List[ast.AST] = []
                    if isinstance(node, ast.Assign):
                        targets, value = node.targets, node.value
                    elif isinstance(node, ast.AnnAssign) and node.value:
                        targets, value = [node.target], node.value
                    else:
                        continue
                    for t in targets:
                        if isinstance(t, ast.Name) and t.id in regs:
                            regs[t.id].update(str_elements(value))
            self._registries = regs
        return self._registries.get(name, set())

    def emitted_metrics(self) -> Set[str]:
        """Metric names registered anywhere in the tree (literal first
        args of counter/gauge/histogram calls)."""
        if self._emitted_metrics is None:
            out: Set[str] = set()
            for src in self.sources:
                if src.tree is None:
                    continue
                for node in ast.walk(src.tree):
                    if isinstance(node, ast.Call):
                        name = metric_call_name(node)
                        if name is not None:
                            out.add(name)
            self._emitted_metrics = out
        return self._emitted_metrics


def metric_call_name(call: ast.Call) -> Optional[str]:
    """The literal metric name if `call` registers a metric series."""
    fn = call.func
    attr = fn.attr if isinstance(fn, ast.Attribute) else (
        fn.id if isinstance(fn, ast.Name) else None)
    if attr not in ("counter", "gauge", "histogram"):
        return None
    name = first_str_arg(call)
    if name is not None and name.startswith("drand_"):
        return name
    return None


# -- rule protocol -------------------------------------------------------

class Rule:
    id: str = ""
    pack: str = ""
    rationale: str = ""

    def check(self, src: Source, project: Project) -> Iterator[Violation]:
        return iter(())

    def check_project(self, project: Project) -> Iterator[Violation]:
        """Cross-file rules (the drift pack) override this instead."""
        for src in project.sources:
            if src.tree is not None:
                yield from self.check(src, project)

    def violation(self, src: Source, node: ast.AST,
                  message: str) -> Violation:
        return Violation(
            rule=self.id, path=src.rel,
            line=getattr(node, "lineno", 1),
            col=getattr(node, "col_offset", 0),
            message=message,
        )


class SuppressionRule(Rule):
    """The suppression syntax itself is checked: an allow with no reason
    or an unknown rule id is a violation, so the escape hatch cannot rot
    into an unreviewed ignore list."""

    id = "lint-suppression"
    pack = "lint"
    rationale = ("`# drandlint: allow[rule-id] <reason>` must name a real "
                 "rule and justify itself")

    def check_project(self, project: Project) -> Iterator[Violation]:
        known = {r.id for r in ALL_RULES} | {"*"}
        for src in project.sources:
            for s in src.suppressions:
                bad: List[str] = []
                if not s.rules:
                    bad.append("no rule id")
                for r in s.rules:
                    if r not in known:
                        bad.append(f"unknown rule {r!r}")
                if not s.reason:
                    bad.append("missing reason")
                if bad:
                    yield Violation(
                        rule=self.id, path=src.rel, line=s.comment_line,
                        col=0,
                        message=("malformed suppression ("
                                 + "; ".join(bad) + ")"),
                    )


class ParseErrorRule(Rule):
    id = "lint-parse-error"
    pack = "lint"
    rationale = "every linted file must parse"

    def check_project(self, project: Project) -> Iterator[Violation]:
        for src in project.sources:
            if src.parse_error is not None:
                yield Violation(
                    rule=self.id, path=src.rel,
                    line=src.parse_error.lineno or 1, col=0,
                    message=f"syntax error: {src.parse_error.msg}",
                )


# -- running -------------------------------------------------------------

@dataclasses.dataclass
class Report:
    root: str
    violations: List[Violation]

    @property
    def active(self) -> List[Violation]:
        return [v for v in self.violations if not v.suppressed]

    @property
    def suppressed(self) -> List[Violation]:
        return [v for v in self.violations if v.suppressed]

    def counts(self, suppressed: bool = False) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for v in self.violations:
            if v.suppressed == suppressed:
                out[v.rule] = out.get(v.rule, 0) + 1
        return dict(sorted(out.items()))

    def to_dict(self) -> dict:
        return {
            "schema": REPORT_SCHEMA,
            "root": self.root,
            "violations": [v.to_dict() for v in self.violations],
            "counts": self.counts(),
            "suppressed_counts": self.counts(suppressed=True),
        }


def collect_sources(root: Path, paths: Iterable[Path]) -> List[Source]:
    files: List[Path] = []
    for p in paths:
        if p.is_dir():
            files.extend(sorted(p.rglob("*.py")))
        elif p.suffix == ".py":
            files.append(p)
    out: List[Source] = []
    seen: Set[Path] = set()
    for f in files:
        f = f.resolve()
        if f in seen or "__pycache__" in f.parts:
            continue
        seen.add(f)
        try:
            rel = f.relative_to(root).as_posix()
        except ValueError:
            rel = f.as_posix()
        out.append(Source(f, rel, f.read_text(encoding="utf-8")))
    return out


def run_lint(root: Path, paths: Optional[Iterable[Path]] = None,
             config: Optional[LintConfig] = None,
             rules: Optional[Iterable[Rule]] = None) -> Report:
    root = root.resolve()
    config = config or LintConfig()
    if paths is None:
        paths = [root / config.package]
    sources = collect_sources(root, paths)
    project = Project(root, config, sources)
    by_rel = {s.rel: s for s in sources}
    violations: List[Violation] = []
    for rule in (rules if rules is not None else ALL_RULES):
        for v in rule.check_project(project):
            src = by_rel.get(v.path)
            if src is not None and v.rule != "lint-suppression":
                sup = src.allow_for(v.rule, v.line)
                if sup is not None and sup.reason:
                    v.suppressed = True
                    v.suppress_reason = sup.reason
            violations.append(v)
    violations.sort(key=Violation.key)
    return Report(root=str(root), violations=violations)


# -- baseline ratchet ----------------------------------------------------

def load_baseline(path: Path) -> Dict[str, int]:
    doc = json.loads(path.read_text())
    if doc.get("schema") != BASELINE_SCHEMA:
        raise ValueError(f"unrecognised baseline schema in {path}")
    return {str(k): int(v) for k, v in doc.get("counts", {}).items()}


def write_baseline(path: Path, report: Report) -> None:
    doc = {"schema": BASELINE_SCHEMA, "counts": report.counts()}
    path.write_text(json.dumps(doc, indent=2, sort_keys=True) + "\n")


def compare_baseline(report: Report,
                     baseline: Dict[str, int]) -> Tuple[bool, List[str]]:
    """Ratchet: per rule, the unsuppressed count may only decrease.
    Returns (ok, human-readable messages)."""
    counts = report.counts()
    ok = True
    msgs: List[str] = []
    for rule in sorted(set(counts) | set(baseline)):
        cur, base = counts.get(rule, 0), baseline.get(rule, 0)
        if cur > base:
            ok = False
            msgs.append(
                f"{rule}: {cur} violation(s), baseline allows {base} "
                f"— fix them (or suppress with a reason)"
            )
        elif cur < base:
            msgs.append(
                f"{rule}: improved {base} -> {cur}; tighten the ratchet "
                f"with --write-baseline"
            )
    return ok, msgs


# -- rendering -----------------------------------------------------------

def render_text(report: Report, verbose_suppressed: bool = False) -> str:
    lines: List[str] = []
    for v in report.active:
        lines.append(f"{v.path}:{v.line}:{v.col}: {v.rule}: {v.message}")
    if verbose_suppressed:
        for v in report.suppressed:
            lines.append(
                f"{v.path}:{v.line}:{v.col}: {v.rule}: suppressed "
                f"({v.suppress_reason}): {v.message}"
            )
    n_active, n_sup = len(report.active), len(report.suppressed)
    lines.append(
        f"drand-lint: {n_active} violation(s), {n_sup} suppressed"
    )
    return "\n".join(lines)


def rule_catalog() -> List[dict]:
    return [
        {"id": r.id, "pack": r.pack, "rationale": r.rationale}
        for r in ALL_RULES
    ]


# populated at import time by the rule packs (kept at the bottom so the
# packs can import the helpers above without a cycle)
from tools.drandlint import (  # noqa: E402
    rules_asyncio,
    rules_hotpath,
    rules_registry,
    rules_simdet,
)

ALL_RULES: List[Rule] = [
    *rules_hotpath.RULES,
    *rules_simdet.RULES,
    *rules_asyncio.RULES,
    *rules_registry.RULES,
    SuppressionRule(),
    ParseErrorRule(),
]
