"""Sim-determinism rules (`sim-*`).

`drand_tpu/sim/` promises byte-identical seeded replay, cross-process and
cross-PYTHONHASHSEED (the committed fork_stall watch fixture depends on
it).  One wall-clock read or one draw from ambient entropy silently
breaks that promise in a way only the nightly fuzz sweep would catch —
so inside the sim subtree, time comes from the FakeClock and randomness
from the fabric's string-seeded `random.Random` streams, full stop.
"""

from __future__ import annotations

import ast
from typing import Iterator, List

from tools.drandlint.engine import Project, Rule, Source, Violation, dotted

_WALLCLOCK = frozenset({
    "time.time", "time.time_ns", "time.monotonic", "time.monotonic_ns",
    "time.perf_counter", "time.perf_counter_ns", "time.sleep",
    "datetime.now", "datetime.utcnow", "datetime.today",
    "datetime.datetime.now", "datetime.datetime.utcnow",
    "datetime.datetime.today", "date.today", "datetime.date.today",
})

#: module-level `random.*` draws share one ambient stream; seeded
#: `random.Random(...)` instances are the sanctioned replacement
_ENTROPY_EXACT = frozenset({
    "os.urandom", "uuid.uuid4", "uuid.uuid1",
    "random.random", "random.randint", "random.randrange",
    "random.choice", "random.choices", "random.shuffle", "random.sample",
    "random.uniform", "random.gauss", "random.seed",
    "random.getrandbits", "random.randbytes", "random.expovariate",
    "random.betavariate", "random.triangular", "random.normalvariate",
})

_ENTROPY_PREFIXES = ("secrets.", "np.random.", "numpy.random.",
                     "jax.random.")


def _in_sim(src: Source, project: Project) -> bool:
    pkg_rel = project.config.pkg_rel(src.rel)
    return pkg_rel is not None and any(
        pkg_rel.startswith(d) for d in project.config.sim_dirs
    )


class SimWallClockRule(Rule):
    id = "sim-wallclock"
    pack = "simdet"
    rationale = ("sim code reads time from the schedulable FakeClock; a "
                 "wall-clock read makes seeded replay diverge")

    def check(self, src: Source, project: Project) -> Iterator[Violation]:
        if not _in_sim(src, project):
            return
        for node in ast.walk(src.tree):
            if isinstance(node, ast.Call):
                name = dotted(node.func)
                if name in _WALLCLOCK:
                    yield self.violation(
                        src, node,
                        f"wall-clock call `{name}` in sim code — use the "
                        f"FakeClock (clock.now()/clock.sleep())",
                    )


class SimEntropyRule(Rule):
    id = "sim-entropy"
    pack = "simdet"
    rationale = ("sim randomness comes from string-seeded random.Random "
                 "streams (PYTHONHASHSEED-proof); ambient entropy breaks "
                 "byte-identical replay")

    def check(self, src: Source, project: Project) -> Iterator[Violation]:
        if not _in_sim(src, project):
            return
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.Call):
                continue
            name = dotted(node.func)
            if name is None:
                continue
            if name in _ENTROPY_EXACT or \
                    any(name.startswith(p) for p in _ENTROPY_PREFIXES):
                yield self.violation(
                    src, node,
                    f"ambient entropy `{name}` in sim code — draw from a "
                    f"string-seeded random.Random stream instead",
                )


RULES: List[Rule] = [SimWallClockRule(), SimEntropyRule()]
