"""Hot-path purity rules (`hp-*`).

The honest-round budget is <=2 device dispatches with a runtime sentinel
guarding it (obs/perf.py); these rules catch the *static* half of the
invariant: a stray host<->device sync or an unsanctioned `jax.jit`
compiles/syncs on a path the sentinel only notices after it has already
paged someone.  `obs/kernels.py` is the single sanctioned sync point —
every device pull elsewhere must run inside its timed `kernel_span`
context so it is counted, traced and budgeted.
"""

from __future__ import annotations

import ast
from typing import Iterator, List

from tools.drandlint.engine import (
    Project,
    Rule,
    Source,
    Violation,
    dotted,
    imports_jax,
)

#: raw sync entry points that bypass the timed wrapper entirely
_RAW_SYNC_ATTRS = ("block_until_ready", "device_get")

#: `np.asarray(<call>)` spellings that pull a device value to host
_ASARRAY = ("np.asarray", "numpy.asarray", "onp.asarray")


def _in_sync_allowed(rule_src_rel: str, project: Project) -> bool:
    pkg_rel = project.config.pkg_rel(rule_src_rel)
    return pkg_rel is not None and pkg_rel in project.config.sync_allowed


class RawSyncRule(Rule):
    id = "hp-sync-call"
    pack = "hotpath"
    rationale = ("`block_until_ready`/`device_get` bypass the timed "
                 "kernel_span sync point; obs/kernels.py is the only "
                 "file allowed to touch them")

    def check(self, src: Source, project: Project) -> Iterator[Violation]:
        if _in_sync_allowed(src.rel, project):
            return
        if project.config.pkg_rel(src.rel) is None:
            return
        for node in ast.walk(src.tree):
            if isinstance(node, ast.Attribute) \
                    and node.attr in _RAW_SYNC_ATTRS:
                yield self.violation(
                    src, node,
                    f"raw device sync `{dotted(node) or node.attr}` — "
                    f"route it through obs/kernels.py "
                    f"(kernel_span / kernels.block)",
                )


class UntimedSyncRule(Rule):
    """`np.asarray(f(...))` / `float(f(...))` on a jax value forces the
    device to finish — outside a `with kernel_span(...)` block that wait
    is invisible to the dispatch budget and the kernel baselines."""

    id = "hp-untimed-sync"
    pack = "hotpath"
    rationale = ("host pulls of device values must happen inside "
                 "`with kernel_span(...)` so they are timed and counted")

    def check(self, src: Source, project: Project) -> Iterator[Violation]:
        cfg = project.config
        pkg_rel = cfg.pkg_rel(src.rel)
        if pkg_rel is None or pkg_rel in cfg.sync_allowed:
            return
        if any(pkg_rel.startswith(d) for d in cfg.untimed_sync_exempt):
            return
        if not imports_jax(src.tree):
            return
        yield from self._walk(src, src.tree, in_span=False)

    def _walk(self, src: Source, node: ast.AST,
              in_span: bool) -> Iterator[Violation]:
        if isinstance(node, (ast.With, ast.AsyncWith)):
            entered = in_span or any(
                isinstance(item.context_expr, ast.Call)
                and (dotted(item.context_expr.func) or "").endswith(
                    "kernel_span")
                for item in node.items
            )
            for child in ast.iter_child_nodes(node):
                yield from self._walk(src, child, entered)
            return
        if isinstance(node, ast.Call) and not in_span:
            name = dotted(node.func)
            pulls = (
                name in _ASARRAY
                or (isinstance(node.func, ast.Name)
                    and node.func.id == "float")
            )
            if pulls and node.args \
                    and isinstance(node.args[0], ast.Call):
                yield self.violation(
                    src, node,
                    f"`{name or 'float'}(<call>)` pulls a device value "
                    f"to host outside `with kernel_span(...)` — the sync "
                    f"is untimed and uncounted",
                )
        for child in ast.iter_child_nodes(node):
            yield from self._walk(src, child, in_span)


class JitScopeRule(Rule):
    id = "hp-jit-scope"
    pack = "hotpath"
    rationale = ("`jax.jit` only in ops/, parallel/ and crypto/tbls.py — "
                 "a jit declared elsewhere is a new compile surface the "
                 "recompile-storm detector and warmup path don't know")

    def check(self, src: Source, project: Project) -> Iterator[Violation]:
        cfg = project.config
        pkg_rel = cfg.pkg_rel(src.rel)
        if pkg_rel is None:
            return
        if any(pkg_rel.startswith(d) if d.endswith("/") else pkg_rel == d
               for d in cfg.jit_allowed):
            return
        for node in ast.walk(src.tree):
            if isinstance(node, ast.Attribute) and dotted(node) == "jax.jit":
                yield self.violation(
                    src, node,
                    "`jax.jit` outside the kernel layers (ops/, "
                    "parallel/, crypto/tbls.py)",
                )


RULES: List[Rule] = [RawSyncRule(), UntimedSyncRule(), JitScopeRule()]
