"""``python -m tools.drandlint`` — run the suite from a repo checkout.

Exit codes: 0 clean (or within baseline), 1 violations, 2 bad input.
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

from tools.drandlint import engine


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="drandlint",
        description="project-invariant static analysis for drand_tpu",
    )
    p.add_argument("paths", nargs="*",
                   help="files/directories to lint "
                        "(default: <root>/drand_tpu)")
    p.add_argument("--root", default=".",
                   help="repository root all paths and conventions are "
                        "relative to (default: cwd)")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="machine-readable report on stdout")
    p.add_argument("--baseline", metavar="FILE",
                   help="ratchet file: per-rule violation counts may "
                        "only decrease relative to it")
    p.add_argument("--write-baseline", action="store_true",
                   help="rewrite --baseline with the current counts "
                        "(tightening the ratchet)")
    p.add_argument("--show-suppressed", action="store_true",
                   help="also print suppressed violations")
    p.add_argument("--list-rules", action="store_true",
                   help="print the rule catalog and exit")
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    if args.list_rules:
        for row in engine.rule_catalog():
            print(f"{row['id']:22s} [{row['pack']}] {row['rationale']}")
        return 0
    root = Path(args.root).resolve()
    if not root.is_dir():
        print(f"drand-lint: root {root} is not a directory",
              file=sys.stderr)
        return 2
    paths = [Path(p) if Path(p).is_absolute() else root / p
             for p in args.paths] or None
    report = engine.run_lint(root, paths)

    if args.baseline:
        bpath = Path(args.baseline)
        if not bpath.is_absolute():
            bpath = root / bpath
        if args.write_baseline:
            engine.write_baseline(bpath, report)
            print(f"drand-lint: wrote baseline {bpath} "
                  f"({len(report.active)} violation(s))")
            return 0
        try:
            baseline = engine.load_baseline(bpath)
        except (OSError, ValueError, json.JSONDecodeError) as exc:
            print(f"drand-lint: cannot read baseline {bpath}: {exc}",
                  file=sys.stderr)
            return 2
        ok, msgs = engine.compare_baseline(report, baseline)
        if args.as_json:
            doc = report.to_dict()
            doc["baseline"] = {"path": str(bpath), "ok": ok,
                               "messages": msgs}
            print(json.dumps(doc, indent=2))
        else:
            if not ok:
                print(engine.render_text(report, args.show_suppressed))
            for m in msgs:
                print(f"drand-lint: {m}")
            print(f"drand-lint: baseline "
                  f"{'OK' if ok else 'EXCEEDED'} ({bpath.name})")
        return 0 if ok else 1

    if args.as_json:
        print(json.dumps(report.to_dict(), indent=2))
    else:
        print(engine.render_text(report, args.show_suppressed))
    return 0 if not report.active else 1


if __name__ == "__main__":
    sys.exit(main())
