"""Asyncio discipline rules (`aio-*`).

The chaos gate has already paid for two of these the hard way (the
SyncSuperseded TOCTOU and the pinned-link round task were both liveness
races found at runtime); the cheap half of each class is statically
visible:

* awaiting something slow while holding an `asyncio.Lock` serialises
  the protocol behind one peer's RTT (and invites lock-order deadlock);
* a blocking call on the event loop (sqlite, native BLS, file I/O,
  `time.sleep`) stalls every handler in the process;
* `asyncio.create_task(...)` whose result is dropped can be
  garbage-collected mid-flight (the asyncio docs warn explicitly) and
  its exception is silently lost — and nothing cancels it on shutdown;
* a bare `except:` / `except BaseException:` in an `async def` that does
  not re-raise swallows `CancelledError`, making the task uncancellable.
"""

from __future__ import annotations

import ast
from typing import Iterator, List, Optional

from tools.drandlint.engine import Project, Rule, Source, Violation, dotted

#: receiver-name fragments that mark an awaited call as "slow" (network,
#: storage, device, scheduled time) for the under-lock rule
_SLOW_SEGMENTS = frozenset({
    "net", "client", "transport", "http", "session", "rpc", "sock",
    "clock", "store", "scheme",
})
_SLOW_METHODS = frozenset({
    "send", "recv", "request", "fetch", "connect", "new_beacon",
    "send_dkg", "sync_chain", "gather", "wait", "wait_for", "sleep",
    "to_thread", "run_in_executor",
})

_BLOCKING_EXACT = frozenset({
    "time.sleep", "sqlite3.connect", "os.fsync",
    "socket.create_connection", "subprocess.run", "subprocess.call",
    "subprocess.check_call", "subprocess.check_output",
})

_TASK_SPAWNERS = ("asyncio.create_task", "asyncio.ensure_future")


def _is_task_spawn(call: ast.Call) -> bool:
    name = dotted(call.func)
    if name in _TASK_SPAWNERS:
        return True
    # loop.create_task(...) on any *loop-named* receiver
    if isinstance(call.func, ast.Attribute) \
            and call.func.attr == "create_task":
        recv = dotted(call.func.value) or ""
        return "loop" in recv.lower()
    return False


def _lockish(expr: ast.AST) -> bool:
    # locks and mutexes serialise — holding one across a slow await is
    # the hazard.  Semaphores deliberately bound *concurrent* slow work
    # (the gossip sender holds one across its RPC by design), so they
    # are not flagged.
    name = dotted(expr)
    if name is None and isinstance(expr, ast.Call):
        name = dotted(expr.func)
    low = (name or "").lower()
    return any(s in low for s in ("lock", "mutex"))


def _slow_await(value: ast.AST) -> Optional[str]:
    if not isinstance(value, ast.Call):
        return None
    name = dotted(value.func)
    if name is None:
        return None
    segments = name.split(".")
    method = segments[-1]
    if method in _SLOW_METHODS:
        return name
    if any(seg in _SLOW_SEGMENTS for seg in segments[:-1]):
        return name
    return None


class LockAwaitRule(Rule):
    id = "aio-lock-await"
    pack = "asyncio"
    rationale = ("awaiting network/scheme/store/clock calls while holding "
                 "an asyncio lock serialises the protocol behind one "
                 "peer's latency and invites lock-order deadlock")

    def check(self, src: Source, project: Project) -> Iterator[Violation]:
        yield from self._walk(src, src.tree, holding=None)

    def _walk(self, src: Source, node: ast.AST,
              holding: Optional[str]) -> Iterator[Violation]:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            # a nested function body runs later, not under this lock
            for child in ast.iter_child_nodes(node):
                yield from self._walk(src, child, holding=None)
            return
        if isinstance(node, ast.AsyncWith):
            held = holding
            for item in node.items:
                if _lockish(item.context_expr):
                    held = ast.unparse(item.context_expr)
            for child in ast.iter_child_nodes(node):
                yield from self._walk(src, child, held)
            return
        if isinstance(node, ast.Await) and holding is not None:
            slow = _slow_await(node.value)
            if slow is not None:
                yield self.violation(
                    src, node,
                    f"`await {slow}(...)` while holding `{holding}` — "
                    f"snapshot under the lock, await outside it",
                )
        for child in ast.iter_child_nodes(node):
            yield from self._walk(src, child, holding)


class BlockingCallRule(Rule):
    id = "aio-blocking-call"
    pack = "asyncio"
    rationale = ("blocking work (sqlite, native BLS, subprocess, "
                 "time.sleep) directly in an `async def` stalls every "
                 "coroutine in the process — offload via "
                 "asyncio.to_thread/run_in_executor")

    def check(self, src: Source, project: Project) -> Iterator[Violation]:
        yield from self._walk(src, src.tree, in_async=False)

    def _walk(self, src: Source, node: ast.AST,
              in_async: bool) -> Iterator[Violation]:
        if isinstance(node, ast.AsyncFunctionDef):
            for child in ast.iter_child_nodes(node):
                yield from self._walk(src, child, in_async=True)
            return
        if isinstance(node, (ast.FunctionDef, ast.Lambda)):
            for child in ast.iter_child_nodes(node):
                yield from self._walk(src, child, in_async=False)
            return
        if in_async and isinstance(node, ast.Call):
            name = dotted(node.func)
            if name is not None:
                blocking = (
                    name in _BLOCKING_EXACT
                    or "native_bls" in name.split(".")
                )
                if blocking:
                    yield self.violation(
                        src, node,
                        f"blocking call `{name}` on the event loop — "
                        f"wrap in asyncio.to_thread/run_in_executor",
                    )
        for child in ast.iter_child_nodes(node):
            yield from self._walk(src, child, in_async)


class OrphanTaskRule(Rule):
    id = "aio-orphan-task"
    pack = "asyncio"
    rationale = ("a task whose reference is dropped can be GC'd "
                 "mid-flight, loses its exception, and is invisible to "
                 "shutdown — retain it and discard on completion")

    def check(self, src: Source, project: Project) -> Iterator[Violation]:
        for node in ast.walk(src.tree):
            if isinstance(node, ast.Expr) \
                    and isinstance(node.value, ast.Call) \
                    and _is_task_spawn(node.value):
                yield self.violation(
                    src, node.value,
                    "fire-and-forget task: retain the "
                    "create_task/ensure_future result (e.g. a task set "
                    "with a done-callback discard) and cancel it on stop",
                )


class SwallowCancelRule(Rule):
    id = "aio-swallow-cancel"
    pack = "asyncio"
    rationale = ("`except:`/`except BaseException:` in an `async def` "
                 "without re-raise swallows CancelledError — the task "
                 "becomes uncancellable and shutdown hangs")

    def check(self, src: Source, project: Project) -> Iterator[Violation]:
        yield from self._walk(src, src.tree, in_async=False)

    def _walk(self, src: Source, node: ast.AST,
              in_async: bool) -> Iterator[Violation]:
        if isinstance(node, ast.AsyncFunctionDef):
            in_async = True
        elif isinstance(node, (ast.FunctionDef, ast.Lambda)):
            in_async = False
        if in_async and isinstance(node, ast.ExceptHandler):
            if self._too_broad(node.type) and not self._reraises(node):
                caught = ("bare except" if node.type is None
                          else f"except {ast.unparse(node.type)}")
                yield self.violation(
                    src, node,
                    f"`{caught}` in async code without re-raise can "
                    f"swallow CancelledError — catch `Exception` (plus "
                    f"`asyncio.CancelledError` explicitly if intended), "
                    f"or re-raise",
                )
        for child in ast.iter_child_nodes(node):
            yield from self._walk(src, child, in_async)

    @staticmethod
    def _too_broad(typ: Optional[ast.AST]) -> bool:
        if typ is None:
            return True
        names = [dotted(t) for t in typ.elts] \
            if isinstance(typ, ast.Tuple) else [dotted(typ)]
        return any(n is not None and n.split(".")[-1] == "BaseException"
                   for n in names)

    @staticmethod
    def _reraises(handler: ast.ExceptHandler) -> bool:
        def scan(n: ast.AST) -> bool:
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda)):
                return False  # a nested def's raise is not a re-raise
            if isinstance(n, ast.Raise):
                return True
            return any(scan(c) for c in ast.iter_child_nodes(n))

        return any(scan(stmt) for stmt in handler.body)


RULES: List[Rule] = [LockAwaitRule(), BlockingCallRule(),
                     OrphanTaskRule(), SwallowCancelRule()]
