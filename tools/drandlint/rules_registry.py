"""Registry-drift rules (`reg-*`).

Observability names are string-coupled across layers: a flight-event
kind recorded in beacon/handler.py is grepped for by `cli doctor`, a
metric name registered in obs/watch.py is regex-matched by
deploy/prometheus-alerts.yml (PR 11's `DrandDeepReorg` depth-regex alert
is exactly this), a shed reason recorded by the gateway is a label the
grafana dashboard pivots on.  None of that coupling is visible to the
interpreter — a rename silently breaks the alert, not the test suite.

These rules resolve every such literal against a canonical registry
constant in the owning module:

* flight-event kinds   -> ``EVENT_KINDS``      (drand_tpu/obs/flight.py)
* metric names         -> ``METRIC_NAMES``     (drand_tpu/utils/metrics.py)
* gateway shed reasons -> ``SHED_REASONS``     (drand_tpu/serve/gateway.py)
* degraded_reason      -> ``DEGRADED_REASONS`` (drand_tpu/obs/perf.py)

and cross-check the deploy artifacts against the metrics the code
actually registers.  The registries are extracted from the scanned
tree's AST, never imported — fixture trees in tests define their own.
"""

from __future__ import annotations

import ast
import re
from typing import Iterator, List, Optional, Set

from tools.drandlint.engine import (
    Project,
    Rule,
    Source,
    Violation,
    dotted,
    first_str_arg,
    metric_call_name,
)

#: call spellings that record a flight event with a literal kind
_RECORD_ATTRS = ("record", "_event")

_METRIC_TOKEN_RE = re.compile(r"\bdrand_[a-z0-9_]+\b")
_HISTO_SUFFIXES = ("_bucket", "_sum", "_count")


def _record_kind(call: ast.Call) -> Optional[str]:
    """Literal event kind if `call` looks like a flight-event record."""
    fn = call.func
    attr = fn.attr if isinstance(fn, ast.Attribute) else (
        fn.id if isinstance(fn, ast.Name) else None)
    if attr not in _RECORD_ATTRS:
        return None
    return first_str_arg(call)


class FlightEventRule(Rule):
    id = "reg-flight-event"
    pack = "registry"
    rationale = ("every flight-event kind must be declared in "
                 "obs/flight.py EVENT_KINDS — doctor, `cli trace` and "
                 "the sim lens dispatch on these strings")

    def check_project(self, project: Project) -> Iterator[Violation]:
        kinds = project.registry("EVENT_KINDS")
        for src in project.sources:
            if src.tree is None or \
                    project.config.pkg_rel(src.rel) is None:
                continue
            for node in ast.walk(src.tree):
                if not isinstance(node, ast.Call):
                    continue
                kind = _record_kind(node)
                if kind is not None and kind not in kinds:
                    yield self.violation(
                        src, node,
                        f"flight event kind {kind!r} is not in "
                        f"EVENT_KINDS (obs/flight.py) — register it or "
                        f"fix the typo",
                    )


class MetricNameRule(Rule):
    id = "reg-metric-name"
    pack = "registry"
    rationale = ("every drand_* metric name must be declared in "
                 "utils/metrics.py METRIC_NAMES — alerts and dashboards "
                 "match on the exact string")

    def check_project(self, project: Project) -> Iterator[Violation]:
        names = project.registry("METRIC_NAMES")
        for src in project.sources:
            if src.tree is None or \
                    project.config.pkg_rel(src.rel) is None:
                continue
            for node in ast.walk(src.tree):
                if not isinstance(node, ast.Call):
                    continue
                name = metric_call_name(node)
                if name is not None and name not in names:
                    yield self.violation(
                        src, node,
                        f"metric {name!r} is not in METRIC_NAMES "
                        f"(utils/metrics.py) — register it or fix the "
                        f"typo",
                    )


class ShedReasonRule(Rule):
    id = "reg-shed-reason"
    pack = "registry"
    rationale = ("gateway shed reasons are a closed vocabulary "
                 "(SHED_REASONS in serve/gateway.py); dashboards and the "
                 "fleet aggregator pivot on the label value")

    def check_project(self, project: Project) -> Iterator[Violation]:
        reasons = project.registry("SHED_REASONS")
        for src in project.sources:
            if src.tree is None or \
                    project.config.pkg_rel(src.rel) is None:
                continue
            for node in ast.walk(src.tree):
                lit: Optional[str] = None
                where: Optional[ast.AST] = None
                if isinstance(node, ast.Call) \
                        and _record_kind(node) == "shed":
                    for kw in node.keywords:
                        if kw.arg == "reason" \
                                and isinstance(kw.value, ast.Constant) \
                                and isinstance(kw.value.value, str):
                            lit, where = kw.value.value, kw.value
                elif isinstance(node, ast.Subscript):
                    recv = dotted(node.value) or ""
                    if recv.split(".")[-1] == "_shed" \
                            and isinstance(node.slice, ast.Constant) \
                            and isinstance(node.slice.value, str):
                        lit, where = node.slice.value, node
                if lit is not None and lit not in reasons:
                    yield self.violation(
                        src, where,
                        f"shed reason {lit!r} is not in SHED_REASONS "
                        f"(serve/gateway.py)",
                    )


class DegradedReasonRule(Rule):
    id = "reg-degraded-reason"
    pack = "registry"
    rationale = ("`degraded_reason` is a closed infra|code vocabulary "
                 "(DEGRADED_REASONS in obs/perf.py) validated at "
                 "artifact construction; a third value would silently "
                 "pass the bench lineage checks")

    def check_project(self, project: Project) -> Iterator[Violation]:
        vocab = project.registry("DEGRADED_REASONS")
        for src in project.sources:
            if src.tree is None or \
                    project.config.pkg_rel(src.rel) is None:
                continue
            for node in ast.walk(src.tree):
                for lit, where in self._literals(node):
                    if lit not in vocab:
                        yield self.violation(
                            src, where,
                            f"degraded_reason {lit!r} is outside "
                            f"DEGRADED_REASONS (obs/perf.py)",
                        )

    @staticmethod
    def _names_degraded(expr: ast.AST) -> bool:
        if isinstance(expr, (ast.Name, ast.Attribute)):
            d = dotted(expr)
            return d is not None and \
                d.split(".")[-1] == "degraded_reason"
        if isinstance(expr, ast.Subscript) \
                and isinstance(expr.slice, ast.Constant):
            return expr.slice.value == "degraded_reason"
        if isinstance(expr, ast.Call):
            # d.get("degraded_reason")
            fn = expr.func
            return isinstance(fn, ast.Attribute) and fn.attr == "get" \
                and first_str_arg(expr) == "degraded_reason"
        return False

    def _literals(self, node: ast.AST):
        """(literal, node) pairs where a string is bound to / compared
        with degraded_reason.  `None` is always allowed (not a string)."""
        if isinstance(node, ast.Call):
            for kw in node.keywords:
                if kw.arg == "degraded_reason" \
                        and isinstance(kw.value, ast.Constant) \
                        and isinstance(kw.value.value, str):
                    yield kw.value.value, kw.value
        elif isinstance(node, ast.Compare):
            sides = [node.left] + list(node.comparators)
            if any(self._names_degraded(s) for s in sides):
                for s in sides:
                    if isinstance(s, ast.Constant) \
                            and isinstance(s.value, str):
                        yield s.value, s
                    elif isinstance(s, (ast.Tuple, ast.List, ast.Set)):
                        for elt in s.elts:
                            if isinstance(elt, ast.Constant) \
                                    and isinstance(elt.value, str):
                                yield elt.value, elt
        elif isinstance(node, ast.Dict):
            for k, v in zip(node.keys, node.values):
                if isinstance(k, ast.Constant) \
                        and k.value == "degraded_reason" \
                        and isinstance(v, ast.Constant) \
                        and isinstance(v.value, str):
                    yield v.value, v
        elif isinstance(node, ast.Assign):
            if any(self._names_degraded(t) for t in node.targets) \
                    and isinstance(node.value, ast.Constant) \
                    and isinstance(node.value.value, str):
                yield node.value.value, node.value


class DeployMetricRule(Rule):
    id = "reg-deploy-metric"
    pack = "registry"
    rationale = ("deploy/prometheus-alerts.yml and "
                 "deploy/grafana-dashboard.json must reference only "
                 "metrics the code registers — a rename otherwise rots "
                 "the alert silently")

    def check_project(self, project: Project) -> Iterator[Violation]:
        emitted = project.emitted_metrics()
        if not emitted:
            return  # tree registers no metrics: nothing to cross-check
        allow = set(project.config.deploy_token_allowlist)
        for rel in project.config.deploy_files:
            path = project.root / rel
            if not path.exists():
                continue
            text = path.read_text(encoding="utf-8")
            seen: Set[str] = set()
            for i, line in enumerate(text.splitlines(), start=1):
                for tok in _METRIC_TOKEN_RE.findall(line):
                    if tok in seen or tok in allow:
                        continue
                    seen.add(tok)
                    if not self._resolves(tok, emitted):
                        yield Violation(
                            rule=self.id, path=rel, line=i, col=0,
                            message=(f"{tok!r} does not match any metric "
                                     f"registered in the code"),
                        )

    @staticmethod
    def _resolves(token: str, emitted: Set[str]) -> bool:
        if token in emitted:
            return True
        for suf in _HISTO_SUFFIXES:
            if token.endswith(suf) and token[: -len(suf)] in emitted:
                return True
        return False


RULES: List[Rule] = [FlightEventRule(), MetricNameRule(), ShedReasonRule(),
                     DegradedReasonRule(), DeployMetricRule()]
