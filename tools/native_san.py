"""Run the native-backend test suites under ASan+UBSan.

The C++ backends (native/bls.cc, native/chainstore.cc) are normally
built -O2 and loaded via ctypes; memory corruption there shows up as a
flaky segfault three tests later, not a diagnosable failure.  This
runner rebuilds them with -fsanitize=address,undefined (see
drand_tpu.native.sanitize_enabled) and re-runs the native suites with
the environment the sanitizer runtime needs:

* LD_PRELOAD=libasan.so — the python binary is not instrumented, so the
  ASan runtime must be the first DSO in the process or dlopen of the
  instrumented .so aborts with "ASan runtime does not come first";
* ASAN_OPTIONS=detect_leaks=0 — leak checking an uninstrumented CPython
  drowns real findings in interpreter-lifetime allocations;
* UBSAN_OPTIONS=print_stacktrace=1 plus -fno-sanitize-recover at build
  time: any UB finding aborts the run.

Usage: python tools/native_san.py [pytest args...]
(defaults to the native suites; exit code is pytest's, or 3 when no
usable libasan/g++ exists — CI treats that as a hard failure, local
dev machines without gcc just report it.)
"""

from __future__ import annotations

import os
import subprocess
import sys

NATIVE_SUITES = ["tests/test_native_bls.py", "tests/test_native_store.py"]


def find_libasan(cxx: str = "g++") -> str | None:
    """Ask the compiler driver where its ASan runtime lives."""
    try:
        out = subprocess.run(
            [cxx, "-print-file-name=libasan.so"],
            capture_output=True, text=True, timeout=30,
        )
    except OSError:
        return None
    path = out.stdout.strip()
    # an unknown file echoes back unchanged ("libasan.so", no directory)
    if out.returncode == 0 and os.path.sep in path \
            and os.path.exists(path):
        return path
    return None


def main(argv: list[str] | None = None) -> int:
    args = list(sys.argv[1:] if argv is None else argv)
    cxx = os.environ.get("CXX", "g++")
    libasan = find_libasan(cxx)
    if libasan is None:
        print(f"native-san: no usable libasan via {cxx} "
              f"(-print-file-name=libasan.so)", file=sys.stderr)
        return 3

    env = dict(os.environ)
    env["DRAND_NATIVE_SAN"] = "1"
    env["LD_PRELOAD"] = ":".join(
        p for p in (libasan, env.get("LD_PRELOAD")) if p
    )
    env.setdefault("ASAN_OPTIONS", "detect_leaks=0:abort_on_error=1")
    env.setdefault("UBSAN_OPTIONS", "print_stacktrace=1")
    # the native suites don't touch jax, but transitive imports might —
    # keep them off any accelerator so the run is pure host memory
    env.setdefault("JAX_PLATFORMS", "cpu")

    cmd = [sys.executable, "-m", "pytest", "-q",
           *(args or NATIVE_SUITES)]
    print(f"native-san: LD_PRELOAD={libasan}")
    print(f"native-san: {' '.join(cmd)}")
    return subprocess.run(cmd, env=env).returncode


if __name__ == "__main__":
    sys.exit(main())
