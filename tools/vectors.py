"""Emit deterministic interop test vectors as JSON.

Analog of the reference's cross-repo vector emitter
(/root/reference/test/test-integration/json_output.go, used for drandjs
interop): deterministic keypairs, a group file, the chained beacon
message derivation, partial signatures, the recovered group signature,
and the final randomness — everything another implementation needs to
check byte-for-byte compatibility with this framework.

Run:  python tools/vectors.py [--out vectors.json]
"""

from __future__ import annotations

import argparse
import hashlib
import json
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from drand_tpu.beacon.chain import beacon_message, randomness  # noqa: E402
from drand_tpu.crypto import refimpl as ref  # noqa: E402
from drand_tpu.crypto import tbls  # noqa: E402
from drand_tpu.crypto.poly import PriPoly, lagrange_basis_at_zero  # noqa: E402
from drand_tpu.key import Group, Pair  # noqa: E402
from drand_tpu.utils import toml_dumps  # noqa: E402


class _DetRng:
    """Deterministic byte stream: SHA-256 counter mode over a seed."""

    def __init__(self, seed: bytes):
        self.seed = seed
        self.ctr = 0

    def __call__(self, n: int) -> bytes:
        out = b""
        while len(out) < n:
            out += hashlib.sha256(
                self.seed + self.ctr.to_bytes(8, "big")
            ).digest()
            self.ctr += 1
        return out[:n]


def build_vectors() -> dict:
    rng = _DetRng(b"drand-tpu-interop-v1")
    n, t = 4, 3

    pairs = [
        Pair.generate(f"127.0.0.1:{8000 + i}", rng=rng) for i in range(n)
    ]
    group = Group(
        nodes=[p.public for p in pairs],
        threshold=t,
        period=30.0,
        genesis_time=1_700_000_000,
    )
    poly = PriPoly.random(t, rng=rng)
    shares = [poly.eval(i) for i in range(n)]
    commits = poly.commit().commits
    dist_key = commits[0]

    scheme = tbls.RefScheme()

    # round 1 signs over the genesis seed chain link
    genesis_seed = group.get_genesis_seed()
    msg1 = beacon_message(genesis_seed, 0, 1)
    partials = [
        scheme.partial_sign(s, msg1) for s in shares
    ]
    from drand_tpu.crypto.poly import PubPoly

    pub = PubPoly(commits)
    sig1 = scheme.recover(pub, msg1, partials[:t], t, n)
    scheme.verify_recovered(dist_key, msg1, sig1)

    # round 2 chains over round 1
    msg2 = beacon_message(sig1, 1, 2)
    partials2 = [scheme.partial_sign(s, msg2) for s in shares]
    sig2 = scheme.recover(pub, msg2, partials2[1 : 1 + t], t, n)
    scheme.verify_recovered(dist_key, msg2, sig2)

    lam = lagrange_basis_at_zero(list(range(t)))

    return {
        "suite": "BLS12-381, keys in G1 (48B), sigs in G2 (96B), "
                 "tbls partial = 2B BE index || 96B sig",
        "hash_to_curve": "SVDW map, SHA-256 expand (refimpl)",
        "keypairs": [
            {
                "address": p.public.address,
                "private": format(p.private, "064x"),
                "public": p.public.key_hex,
            }
            for p in pairs
        ],
        "group_toml": toml_dumps(group.to_dict()),
        "group_hash": group.hash().hex(),
        "genesis_seed": genesis_seed.hex(),
        "distributed": {
            "secret": format(poly.secret(), "064x"),
            "commits": [ref.g1_to_bytes(c).hex() for c in commits],
            "shares": [
                {"index": s.index, "value": format(s.value, "064x")}
                for s in shares
            ],
            "lagrange_basis_at_zero_0..2": [
                format(lam[i], "064x") for i in range(t)
            ],
        },
        "round1": {
            "message": msg1.hex(),
            "partials": [p.hex() for p in partials],
            "signature": sig1.hex(),
            "randomness": randomness(sig1).hex(),
        },
        "round2": {
            "message": msg2.hex(),
            "partials": [p.hex() for p in partials2],
            "signature": sig2.hex(),
            "randomness": randomness(sig2).hex(),
        },
    }


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default=None)
    args = ap.parse_args()
    v = build_vectors()
    text = json.dumps(v, indent=2)
    if args.out:
        Path(args.out).write_text(text)
        print(f"wrote {args.out}")
    else:
        print(text)
    return 0


if __name__ == "__main__":
    sys.exit(main())
