"""Single-round protocol-plane latency: device vs native backend at small
batch.

The per-round path (1 partial-sign + t-collect + 1 recover, SURVEY §7.10
hard part #3) lives or dies on SMALL-batch latency, not throughput; the
reference budgets 300 ms of slack for it (core/constants.go:45).  This
records verify_partials_batch and recover wall latency at batch {1, 8,
128} for the JaxScheme (device) and NativeScheme (C++ host) backends so
the dispatch-threshold choice in `tbls.JaxScheme._bucket` is justified by
data, not vibes (VERDICT r4 next #7).

Writes one JSON line per (backend, op, batch) to stdout; run with the
repo root on sys.path:  python tools/bench_latency.py
Compile/warmup is excluded; each cell reports the median of BENCH_REPEATS
(default 5) timed calls.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402


def _cells(scheme, name, batches, repeats):
    from drand_tpu.beacon.chain import beacon_message
    from drand_tpu.crypto.poly import PriPoly

    for b in batches:
        # verify runs at exactly batch b; recovery needs t >= 2 partials
        t, n = max(2, b), max(2, b) + 1
        poly = PriPoly.random(t, secret=0xA11CE + b)
        shares = [poly.eval(i) for i in range(n)]
        pub = poly.commit()
        msg = beacon_message(b"latency-bench", 6, 7)
        partials = [scheme.partial_sign(s, msg) for s in shares]

        # warmup both ops (compiles excluded from timing)
        assert all(scheme.verify_partials_batch(pub, msg, partials[:b]))
        sig = scheme.recover(pub, msg, partials[:t], t, n)
        scheme.verify_recovered(pub.commits[0], msg, sig)

        medians = {}
        for op, fn in (
            ("sign", lambda: scheme.partial_sign(shares[0], msg)),
            ("verify_partials",
             lambda: scheme.verify_partials_batch(pub, msg, partials[:b])),
            ("recover",
             lambda: scheme.recover(pub, msg, partials[:t], t, n)),
        ):
            times = []
            for _ in range(repeats):
                t0 = time.perf_counter()
                fn()
                times.append(time.perf_counter() - t0)
            times.sort()
            med = float(np.median(times))
            medians[op] = med
            yield {
                "backend": name, "op": op,
                "batch": b if op == "verify_partials" else
                         (1 if op == "sign" else t),
                "median_ms": round(1e3 * med, 2),
                "min_ms": round(1e3 * times[0], 2),
                "max_ms": round(1e3 * times[-1], 2),
                "repeats": repeats,
            }
        # the budget applies to the whole per-round path (sign + verify
        # the flood + recover), not each op in isolation
        total = sum(medians.values())
        yield {
            "backend": name, "op": "round_path", "batch": b,
            "median_ms": round(1e3 * total, 2),
            "components": {k: round(1e3 * v, 2) for k, v in medians.items()},
            "within_300ms_budget": total < 0.300,
        }


def main() -> None:
    from drand_tpu.crypto import native_bls, tbls

    repeats = int(os.environ.get("BENCH_REPEATS", "5"))
    batches = [int(x) for x in
               os.environ.get("BENCH_BATCHES", "1,8,128").split(",")]
    # batch 1 still needs t >= 2 for a meaningful recovery
    schemes = []
    if native_bls.available():
        schemes.append((tbls.NativeScheme(), "native-cpp"))
    schemes.append((tbls.JaxScheme(), "jax"))
    rows = []
    for scheme, name in schemes:
        for row in _cells(scheme, name, batches, repeats):
            rows.append(row)
            print(json.dumps(row), flush=True)
    out = os.environ.get("BENCH_LATENCY_OUT")
    if out:
        with open(out, "w") as f:
            for r in rows:
                f.write(json.dumps(r) + "\n")


if __name__ == "__main__":
    main()
