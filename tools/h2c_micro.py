"""Microbenchmark: device H2C + fused verify stage timings (real TPU).

Usage: python tools/h2c_micro.py [batch]
"""

import sys
import pathlib
import time

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

import numpy as np
import jax

from drand_tpu.crypto import refimpl as ref
from drand_tpu.ops import curve, fp, h2c as opg
from drand_tpu.ops import pallas_h2c as ph


def timeit(name, fn, items, iters=4):
    out = fn()
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn()
    jax.block_until_ready(out)
    dt = (time.perf_counter() - t0) / iters
    print(f"{name}: {dt*1000:.1f} ms/call ({items/dt:.0f} items/s)",
          flush=True)
    return dt


def main():
    batch = int(sys.argv[1]) if len(sys.argv) > 1 else 1024
    msgs = [b"micro-%d" % i for i in range(batch)]

    timeit("host hash_to_field + encode",
           lambda: opg.hash_to_field_device(msgs), batch)
    u0, u1 = opg.hash_to_field_device(msgs)
    timeit("pallas hash_to_g2", lambda: ph.hash_to_g2(u0, u1), batch)

    # fused end-to-end verify kernel
    sk = 0x5EED % ref.R
    pk = ref.g1_mul(ref.G1_GEN, sk)
    neg_g = ref.g1_neg(ref.G1_GEN)
    import jax.numpy as jnp

    h_aff = ph.hash_to_g2(u0, u1)
    one = jnp.broadcast_to(
        fp.to_mont(jnp.asarray(np.stack(
            [fp.int_to_limbs(1), fp.int_to_limbs(0)]
        ))), (batch, 1, 2, fp.NLIMB))
    h_proj = jnp.concatenate([h_aff, one], axis=1)
    skb = jnp.broadcast_to(jnp.asarray(curve.scalar_to_bits(sk)),
                           (batch, 256))
    sig = curve.g2_scalar_mul(h_proj, skb)
    sx, sy = curve.g2_to_affine(sig)
    q1 = jnp.stack([sx, sy], axis=1)
    ends = curve.g1_affine_encode_batch([neg_g, pk])
    p1 = jnp.broadcast_to(ends[0], (batch, 2, fp.NLIMB))
    p2 = jnp.broadcast_to(ends[1], (batch, 2, fp.NLIMB))

    ok = np.asarray(ph.pairing_product_check_hashed(p1, q1, p2, u0, u1))
    assert ok.all(), "fused verify failed"
    timeit("fused check_hashed (kernel only)",
           lambda: ph.pairing_product_check_hashed(p1, q1, p2, u0, u1),
           batch)

    def e2e():
        a, b = opg.hash_to_field_device(msgs)
        return ph.pairing_product_check_hashed(p1, q1, p2, a, b)

    timeit("end-to-end bytes -> verified", e2e, batch)


if __name__ == "__main__":
    main()
