"""A fake drand-tpu node serving a canned, deterministic chain.

Analog of the reference's client-interop fixture
(/root/reference/test/api/serve.go): stands up the REAL public gRPC
service and REST gateway, but backed by a deterministic in-memory chain
generated from the interop vectors (tools/vectors.py) instead of a live
network — so client implementations can be tested against stable data.

Run:  python tools/fake_server.py [--port 8080] [--rest 8081] [--rounds 5]
"""

from __future__ import annotations

import argparse
import asyncio
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from drand_tpu.beacon.chain import Beacon, beacon_message  # noqa: E402
from drand_tpu.beacon.store import BeaconStore  # noqa: E402
from drand_tpu.crypto import refimpl as ref  # noqa: E402
from drand_tpu.crypto import tbls  # noqa: E402
from drand_tpu.crypto.poly import PriPoly, PubPoly  # noqa: E402
from drand_tpu.key import Group, Pair  # noqa: E402
from drand_tpu.utils import toml_dumps  # noqa: E402
from tools.vectors import _DetRng  # noqa: E402


class FakeDaemon:
    """Duck-typed core.Drand surface for the public server + REST."""

    def __init__(self, rounds: int):
        rng = _DetRng(b"drand-tpu-interop-v1")
        n, t = 4, 3
        pairs = [
            Pair.generate(f"127.0.0.1:{8000 + i}", rng=rng)
            for i in range(n)
        ]
        self.group = Group(
            nodes=[p.public for p in pairs], threshold=t, period=30.0,
            genesis_time=1_700_000_000,
        )
        poly = PriPoly.random(t, rng=rng)
        self.shares = [poly.eval(i) for i in range(n)]
        self.pub = PubPoly(poly.commit().commits)
        self.dist_key = self.pub.commits[0]
        self.scheme = tbls.RefScheme()
        self.store = BeaconStore()

        seed = self.group.get_genesis_seed()
        self.store.put(Beacon(0, 0, b"", seed))
        prev_sig, prev_round = seed, 0
        for r in range(1, rounds + 1):
            msg = beacon_message(prev_sig, prev_round, r)
            partials = [
                self.scheme.partial_sign(s, msg) for s in self.shares[:t]
            ]
            sig = self.scheme.recover(self.pub, msg, partials, t, n)
            self.store.put(Beacon(r, prev_round, prev_sig, sig))
            prev_sig, prev_round = sig, r

    # -- public surface ---------------------------------------------------

    def fetch_public_rand(self, round: int) -> Beacon:
        b = self.store.last() if round == 0 else self.store.get(round)
        if b is None:
            raise KeyError(f"no beacon for round {round}")
        return b

    def serve_private_rand(self, blob: bytes) -> bytes:
        raise ValueError("fake server holds no private key material")

    def subscribe_beacons(self):
        return asyncio.Queue()  # canned chain: stream never fires

    def unsubscribe_beacons(self, q) -> None:
        pass

    def group_toml(self) -> str:
        return toml_dumps(self.group.to_dict())

    def home_status(self) -> str:
        return "fake drand-tpu node serving canned interop data"

    def collective_key_hex(self):
        return [ref.g1_to_bytes(c).hex() for c in self.pub.commits]

    def serve_sync_chain(self, from_round: int):
        return self.store.range_from(from_round)

    async def process_beacon_packet(self, packet) -> None:
        raise ValueError("fake server accepts no protocol traffic")


async def amain(port: int, rest_port: int, rounds: int) -> None:
    from drand_tpu.net.rest import build_rest_app, start_rest
    from drand_tpu.net.transport import build_public_server

    daemon = FakeDaemon(rounds)
    server, _ = build_public_server(daemon, f"127.0.0.1:{port}")
    await server.start()
    runner, _ = await start_rest(
        build_rest_app(daemon), rest_port, host="127.0.0.1"
    )
    print(f"fake drand-tpu node: gRPC 127.0.0.1:{port}, "
          f"REST http://127.0.0.1:{rest_port}/api/public "
          f"({rounds} canned rounds)")
    print(f"collective key: {daemon.collective_key_hex()[0]}")
    try:
        await asyncio.Event().wait()
    finally:
        await server.stop(1)
        await runner.cleanup()


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--port", type=int, default=8080)
    ap.add_argument("--rest", type=int, default=8081)
    ap.add_argument("--rounds", type=int, default=5)
    args = ap.parse_args()
    try:
        asyncio.run(amain(args.port, args.rest, args.rounds))
    except KeyboardInterrupt:
        pass
    return 0


if __name__ == "__main__":
    sys.exit(main())
