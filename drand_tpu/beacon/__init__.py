"""Beacon chain: types, verification, storage, and the round-loop handler.

Equivalent of the reference's `beacon/` package — the protocol hot path
(/root/reference/beacon/beacon.go, beacon/chain.go, beacon/store.go,
beacon/round_cache.go)."""

from drand_tpu.beacon.chain import (  # noqa: F401
    Beacon,
    beacon_message,
    current_round,
    genesis_beacon,
    next_round,
    randomness,
    time_of_round,
    verify_beacon,
)
from drand_tpu.beacon.store import (  # noqa: F401
    BeaconStore,
    CallbackStore,
    RollbackDepthExceeded,
    open_store,
)
from drand_tpu.beacon.handler import BeaconHandler, BeaconConfig  # noqa: F401
