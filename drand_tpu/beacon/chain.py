"""Beacon chain types, message derivation, round/time math, verification.

Mirrors /root/reference/beacon/chain.go:
* `Beacon{Round, PrevRound, PrevSig, Signature}`  (:16-28)
* randomness = SHA-256(signature)                  (:48-55)
* message = SHA-256(be8(prevRound) || prevSig || be8(round))  (:86-94)
* round 0 is a deterministic genesis beacon whose signature is the group's
  genesis seed (beacon.go:105-113)
* round<->time math                                (:97-119)
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass
from typing import Optional, Tuple

from drand_tpu.crypto import tbls


@dataclass(frozen=True)
class Beacon:
    round: int
    prev_round: int
    prev_sig: bytes
    signature: bytes

    def randomness(self) -> bytes:
        return randomness(self.signature)

    def to_dict(self) -> dict:
        return {
            "round": self.round,
            "prev_round": self.prev_round,
            "prev_sig": self.prev_sig.hex(),
            "signature": self.signature.hex(),
        }

    @classmethod
    def from_dict(cls, d: dict) -> "Beacon":
        return cls(
            round=int(d["round"]),
            prev_round=int(d["prev_round"]),
            prev_sig=bytes.fromhex(d["prev_sig"]),
            signature=bytes.fromhex(d["signature"]),
        )


def randomness(signature: bytes) -> bytes:
    return hashlib.sha256(signature).digest()


def round_to_bytes(r: int) -> bytes:
    return int(r).to_bytes(8, "big")


def beacon_message(prev_sig: bytes, prev_round: int, round: int) -> bytes:
    """The message each node threshold-signs for a round."""
    h = hashlib.sha256()
    h.update(round_to_bytes(prev_round))
    h.update(prev_sig)
    h.update(round_to_bytes(round))
    return h.digest()


def genesis_beacon(genesis_seed: bytes) -> Beacon:
    """Round 0: deterministic from the group's genesis seed."""
    return Beacon(round=0, prev_round=0, prev_sig=b"", signature=genesis_seed)


def verify_beacon(scheme: tbls.Scheme, pub_key, beacon: Beacon) -> None:
    """Raise if the beacon's signature is not the group's tBLS signature
    over the chained message (reference VerifyBeacon chain.go:65)."""
    msg = beacon_message(beacon.prev_sig, beacon.prev_round, beacon.round)
    scheme.verify_recovered(pub_key, msg, beacon.signature)


def time_of_round(period: float, genesis_time: int, round: int) -> float:
    """Scheduled wall time of a round (round 1 happens at genesis)."""
    if round == 0:
        return float(genesis_time)
    return genesis_time + (round - 1) * period


def current_round(now: float, period: float, genesis_time: int) -> int:
    """The round whose scheduled time is the latest not after `now`."""
    if now < genesis_time:
        return 0
    return int((now - genesis_time) // period) + 1


def next_round(now: float, period: float,
               genesis_time: int) -> Tuple[int, float]:
    """The upcoming round and its scheduled time (chain.go:108-119)."""
    if now < genesis_time:
        return 1, float(genesis_time)
    nxt = current_round(now, period, genesis_time) + 1
    return nxt, time_of_round(period, genesis_time, nxt)
