"""The beacon round loop — the protocol hot path.

Mirrors /root/reference/beacon/beacon.go semantics:

* a period ticker drives rounds; **the ticker is king** (:390-399): when a
  new round's time arrives the previous round attempt is abandoned, the
  new round always targets the chain head we actually have;
* each round: sign own partial over the chained message, broadcast to all
  peers, collect partials until the threshold, Lagrange-recover the unique
  group signature, verify it against the distributed key, store it
  (:429-526);
* catch-up pulls the missing chain segment from peers, verifying every
  link (:529-601) — here in device-sized batches via the scheme's
  `verify_chain_batch` (the TPU replacement for the reference's
  one-pairing-per-iteration loop);
* resharing uses `stop_at` (old group stops at transition-1,
  beacon.go:626) and `transition` (new group syncs then joins, :244).

The handler is asyncio-native; time is injectable (utils.clock) so tests
drive rounds deterministically, mirroring the reference's clockwork usage.
"""

from __future__ import annotations

import asyncio
import random
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Set, Tuple

from drand_tpu.beacon.chain import (
    Beacon,
    beacon_message,
    current_round,
    genesis_beacon,
    next_round,
    time_of_round,
)
from drand_tpu.beacon.round_cache import RoundManager
from drand_tpu.beacon.store import (
    BeaconStore,
    CallbackStore,
    RollbackDepthExceeded,
)
from drand_tpu.crypto import tbls
# BeaconPacket/ProtocolClient live in net/interface.py (transport
# interface extraction); re-exported here because this was their
# historical home and every transport/test imports them from it
from drand_tpu.net.interface import (  # noqa: F401
    BeaconPacket,
    ProtocolClient,
)
from drand_tpu.key import Group, Identity, Share
from drand_tpu.obs import flight as obs_flight
from drand_tpu.obs import kernels as obs_kernels
from drand_tpu.obs import peers as obs_peers
from drand_tpu.obs import perf as obs_perf
from drand_tpu.obs import slo as obs_slo
from drand_tpu.obs import trace as obs_trace
from drand_tpu.utils import metrics
from drand_tpu.utils.clock import Clock
from drand_tpu.utils.logging import get_logger

log = get_logger("beacon")

_rounds_total = metrics.counter(
    "drand_beacon_rounds_total", "beacon rounds stored by this node"
)
_rounds_failed = metrics.counter(
    "drand_beacon_rounds_failed_total",
    "round attempts abandoned (ticker advanced or recovery failed)",
)
_partials_in = metrics.counter(
    "drand_beacon_partials_received_total",
    "partial signatures accepted from peers",
)
_partials_rejected = metrics.counter(
    "drand_beacon_partials_rejected_total",
    "inbound partial signatures rejected (window or verification)",
)
_sync_verified = metrics.counter(
    "drand_beacon_sync_rounds_verified_total",
    "historical rounds batch-verified during catch-up sync",
)
_optimistic_fallbacks = metrics.counter(
    "drand_beacon_optimistic_fallbacks_total",
    "optimistic finalizes that failed the recovered-signature check and "
    "fell back to the batched blame pass",
)
_round_seconds = metrics.histogram(
    "drand_beacon_round_seconds",
    "wall time from round start to stored beacon",
)
_head_gauge = metrics.gauge(
    "drand_beacon_head_round", "chain head round of this node"
)


def _reorg_counter(depth: int):
    return metrics.counter(
        "drand_chain_reorgs_total",
        "chain reorgs adopted (highest-round fully-verified chain wins)",
        labels={"depth": str(depth)},
    )


def _sync_failure_counter(reason: str):
    return metrics.counter(
        "drand_sync_failures_total",
        "per-peer catch-up sync attempts that failed, by reason",
        labels={"reason": reason},
    )


class ChainLinkBroken(ValueError):
    """A peer's synced segment does not link onto our chain head —
    either the peer is corrupt or we are on different fork branches.
    Carries the first offending round so fork resolution can start
    from it."""

    def __init__(self, round: int, detail: str = ""):
        super().__init__(
            detail or f"chain link broken at round {round}"
        )
        self.round = round


class ChainSignatureInvalid(ValueError):
    """A synced segment failed the batched threshold-signature check."""

    def __init__(self, rounds: List[int]):
        super().__init__(f"invalid signatures at rounds {rounds}")
        self.rounds = rounds


class ForkRejected(RuntimeError):
    """A competing branch was examined and NOT adopted (lower or equal
    head, missing anchor, or internally broken) — the local chain is
    untouched."""


class SyncSuperseded(RuntimeError):
    """The local chain advanced while a sync batch was in flight; the
    batch no longer extends the real head and was discarded.  Storing
    it anyway would write beacons UNDER the new head — if a finalize
    moved the head onto a different branch meanwhile (fork_stall's
    round 7), that silently plants a broken link in the store."""

#: how many sync'd beacons to verify per device batch
SYNC_BATCH = 64

#: gossip fan-out bound: sends launch healthy-peers-first and at most
#: this many fly at once, so the priority order controls who hears us
#: first even on large groups
GOSSIP_CONCURRENCY = 8

#: pause before the single gossip retry (transient-failure absorption)
GOSSIP_RETRY_DELAY = 0.1

#: optimistic finalize: bounded blame/evict/retry rounds before the
#: quorum is declared unrecoverable and the attempt abandoned
FINALIZE_ATTEMPTS = 8


def _sync_failure_reason(exc: BaseException) -> str:
    """Label value for drand_sync_failures_total."""
    if isinstance(exc, RollbackDepthExceeded):
        return "reorg_beyond_cap"
    if isinstance(exc, ForkRejected):
        return "fork_not_better"
    if isinstance(exc, ChainSignatureInvalid):
        return "bad_signature"
    if isinstance(exc, ChainLinkBroken):
        return "chain_link"
    if isinstance(exc, SyncSuperseded):
        return "superseded"
    if isinstance(exc, (ConnectionError, OSError, TimeoutError,
                        asyncio.TimeoutError)):
        return "transport"
    return "other"


def _counted(fn, *args):
    """Run `fn` and return `(result, device-dispatch delta)`.

    The delta is measured synchronously around the call — inside the
    offload runner, against the CALLING THREAD's dispatch counter — so
    it is exact under the simulator's inline runner and in production,
    and stays exact when several handlers share one process (their
    concurrent finalizes dispatch from different threads).  This is
    what feeds the perf observatory's dispatch-budget sentinel.
    """
    before = obs_kernels.thread_dispatches()
    out = fn(*args)
    return out, obs_kernels.thread_dispatches() - before


@dataclass
class BeaconConfig:
    group: Group
    public: Identity
    share: Share
    scheme: tbls.Scheme
    clock: Clock = field(default_factory=Clock)
    wait_time: float = 0.3  # reference core/constants.go:45
    #: beacons verified per device batch during catch-up; the pipelined
    #: sync prefetches the next batch while this one is on device
    sync_batch: int = SYNC_BATCH
    #: hard cap on reorg depth: a competing branch whose divergence
    #: point is more than this many rounds behind our head is refused
    #: (typed error + flight event, chain untouched).  Deep reorgs on a
    #: randomness beacon mean consumers already acted on orphaned
    #: values — that needs an operator, not an automatic rewrite.
    reorg_depth: int = 64
    #: "optimistic" (default): inbound partials are admitted with cheap
    #: structural checks only and the quorum is verified via ONE
    #: recovered-signature check, falling back to the batched blame pass
    #: when it fails; "eager": every inbound partial pays a pairing
    #: check at arrival time (the pre-optimization behavior)
    partial_verify: str = "optimistic"
    #: how heavy crypto leaves the event loop: None = asyncio.to_thread
    #: (production).  The simulator injects an inline awaitable runner so
    #: the whole network is single-threaded and cooperatively scheduled —
    #: thread wake-up order is the one nondeterminism a seeded replay
    #: cannot pin down.
    offload: Optional[Callable] = None
    #: source of protocol-level randomness (peer shuffle order during
    #: catch-up).  None = the process-global `random` module; the
    #: simulator injects a per-node seeded random.Random.
    rng: Optional[random.Random] = None

    def __post_init__(self):
        # fail at configuration time, not mid-round: a bad SLO override
        # in the group file must surface when the group is loaded
        obs_slo.parse_overrides(getattr(self.group, "slo", None) or [],
                                period=self.group.period)


class BeaconHandler:
    def __init__(self, cfg: BeaconConfig, store: BeaconStore,
                 client: ProtocolClient):
        self.cfg = cfg
        self.group = cfg.group
        self.scheme = cfg.scheme
        self.clock = cfg.clock
        self.client = client
        self.store = CallbackStore(store)
        idx = cfg.group.index(cfg.public)
        if idx is None:
            raise ValueError("this node is not part of the group")
        self.index = idx
        self.log = log.bind(node=idx, addr=cfg.public.address)
        if cfg.partial_verify not in ("eager", "optimistic"):
            raise ValueError(
                "partial_verify must be 'eager' or 'optimistic', "
                f"got {cfg.partial_verify!r}"
            )
        self._optimistic = cfg.partial_verify == "optimistic"
        #: heavy crypto runs through this (default: a worker thread); the
        #: simulator injects an inline runner for determinism
        self._offload = cfg.offload or asyncio.to_thread
        #: protocol randomness (sync peer order); the module-level random
        #: in production, a per-node seeded Random in the simulator
        self._rng = cfg.rng or random
        self._gossip_sem = asyncio.Semaphore(GOSSIP_CONCURRENCY)
        #: in-flight gossip sends: asyncio keeps only a weak reference to
        #: running tasks, so a dropped handle can be collected mid-send —
        #: retained here and cancelled by stop()
        self._gossip_tasks: Set[asyncio.Task] = set()
        self.pub_poly = cfg.share.pub_poly()
        self.dist_key = cfg.share.public().key()
        self.manager = RoundManager(self.scheme.index_of)
        #: peer address -> clock time of last VALID partial (liveness
        #: view for /v1/status; never pruned — group size is small)
        self.peer_seen: Dict[str, float] = {}
        #: per-signer contribution accounting (latency, misses, skew)
        self.peer_ledger = obs_peers.PeerLedger(
            (n.address for n in cfg.group.nodes),
            cfg.public.address, cfg.group.period,
        )
        # group-file SLO overrides land first: ENGINE.objective is
        # first-registration-wins, so whatever the group TOML declares
        # beats the built-in defaults below (and any other node module's)
        for name, kw in obs_slo.parse_overrides(
                getattr(cfg.group, "slo", None) or [],
                period=cfg.group.period).items():
            obs_slo.ENGINE.objective(name, **kw)
        # SLO: the chain's reason to exist is randomness on schedule, so
        # the objective is phrased against the round's own deadline
        obs_slo.ENGINE.objective(
            obs_slo.ROUND_FINALIZE,
            target=0.99,
            threshold=0.5 * cfg.group.period,
            describe="99% of rounds finalize within half the period",
        )
        #: round -> peer address that SERVED us the beacon (synced or
        #: reorg-adopted; self-finalized rounds have no entry).  When a
        #: reorg orphans a round, its *sender* — never the claimed
        #: signer indices — takes the soft ledger charge.
        self._beacon_sources: Dict[int, str] = {}
        #: observers notified on every adopted reorg with a dict of
        #: deterministic fields (the simulator's event log taps this)
        self._reorg_callbacks: List[Callable[[dict], None]] = []
        #: edge triggers: one starvation event per outage, one refusal
        #: event per (peer, divergence) fork
        self._sync_starved = False
        self._refused_forks: set = set()
        #: lifetime reorg summary surfaced at GET /v1/status
        self.reorg_stats: dict = {"total": 0, "max_depth": 0,
                                  "last": None}
        #: the chain link the ACTIVE round task signed against, so a
        #: catch-up that moves the head mid-round can tell the task is
        #: pinned to a stale link and restart it (_refresh_round_task)
        self._round_link: Optional[Tuple[int, bytes]] = None
        self._running = False
        self._stop_at: Optional[int] = None
        self._loop_task: Optional[asyncio.Task] = None
        self._round_task: Optional[asyncio.Task] = None
        self._resync_task: Optional[asyncio.Task] = None
        self._stopped = asyncio.Event()

    # -- public control ---------------------------------------------------

    async def start(self) -> None:
        """Start at genesis (fails if genesis already passed;
        reference beacon.go:205)."""
        if self.clock.now() > self.group.genesis_time + self.group.period:
            raise RuntimeError(
                "genesis time already passed — use catchup()"
            )
        self._ensure_genesis()
        self._launch()

    async def catchup(self) -> None:
        """Join a running chain: sync from peers, then enter the loop."""
        self._ensure_genesis()
        await self.sync()
        self._launch()

    async def transition(self) -> None:
        """New-group node during resharing: sync the old chain up to the
        transition round, then run (reference Transition beacon.go:244)."""
        self._ensure_genesis()
        await self.sync()
        self._launch()

    async def transition_with_peers(self, peers) -> None:
        """Transition, syncing the existing chain from the OLD group's
        nodes (a brand-new member knows no new-group chain yet)."""
        self._ensure_genesis()
        await self.sync(peers=peers)
        self._launch()

    def stop_at(self, round: int) -> None:
        """Stop producing after storing `round` (old nodes at reshare)."""
        self._stop_at = round

    async def stop(self) -> None:
        self._running = False
        for t in (self._round_task, self._loop_task, self._resync_task):
            if t is not None:
                t.cancel()
        for t in list(self._gossip_tasks):
            t.cancel()
        await asyncio.sleep(0)
        self._stopped.set()

    def add_callback(self, cb: Callable[[Beacon], None]) -> None:
        self.store.add_callback(cb)

    def add_reorg_callback(self, cb: Callable[[dict], None]) -> None:
        """`cb(event)` after every adopted reorg; `event` carries only
        deterministic fields (node, peer, divergence_round, depth,
        old_head, new_head)."""
        self._reorg_callbacks.append(cb)

    # -- internals --------------------------------------------------------

    def _ensure_genesis(self) -> None:
        if self.store.get(0) is None:
            self.store.put(genesis_beacon(self.group.get_genesis_seed()))

    def _launch(self) -> None:
        if self._running:
            return
        self._running = True
        self._loop_task = asyncio.create_task(self._run_loop())

    async def _run_loop(self) -> None:
        period = self.group.period
        genesis = self.group.genesis_time
        while self._running:
            now = self.clock.now()
            if now < genesis:
                await self.clock.sleep(genesis - now)
                continue
            head = self.store.last()
            cur = current_round(now, period, genesis)
            if head is not None and head.round >= cur:
                # head is fresh: just wait for the next scheduled round
                _, t_next = next_round(now, period, genesis)
                await self.clock.sleep(t_next - self.clock.now())
                continue
            if self._stop_at is not None and cur > self._stop_at:
                self._running = False
                self._stopped.set()
                return
            # ticker is king: abandon any unfinished previous round and
            # work on the round the clock says is current
            if self._round_task is not None and not self._round_task.done():
                self._round_task.cancel()
            self._round_task = asyncio.create_task(self._run_round(cur))
            _, t_next = next_round(now, period, genesis)
            await self.clock.sleep(t_next - self.clock.now())

    async def _run_round(self, round: int) -> None:
        try:
            await self._run_round_inner(round)
        except asyncio.CancelledError:
            _rounds_failed.inc()  # ticker-is-king abandonment
            if self._running:
                # an abandoned round burned budget; a shutdown didn't
                obs_slo.ENGINE.record_bad(obs_slo.ROUND_FINALIZE,
                                          ts=self.clock.now())
            raise
        except Exception:
            _rounds_failed.inc()  # recovery/verification failure
            obs_slo.ENGINE.record_bad(obs_slo.ROUND_FINALIZE,
                                      ts=self.clock.now())
            self.log.exception("round failed", round=round)

    async def _run_round_inner(self, round: int) -> None:
        t_start = asyncio.get_running_loop().time()
        head = self.store.last()
        if head is None or head.round >= round:
            return
        # every group member derives the same trace id for this round, so
        # the per-node span trees stitch into one distributed trace
        tid = obs_trace.round_trace_id(
            self.group.get_genesis_seed(), round
        ) if obs_trace.TRACER.enabled else ""
        with obs_trace.TRACER.span(
            "beacon.round", trace_id=tid or None,
            attrs={"round": round, "node": self.cfg.public.address},
        ) as round_span:
            await self._run_round_traced(round, head, t_start, tid)
            round_span.set_attr("head", round)

    async def _run_round_traced(self, round: int, head: Beacon,
                                t_start: float, tid: str) -> None:
        prev_round, prev_sig = head.round, head.signature
        self._round_link = (prev_round, prev_sig)
        msg = beacon_message(prev_sig, prev_round, round)
        # sign OFF the event loop (reference: the round goroutine,
        # beacon.go:433).  A synchronous sign blocks every ingest task
        # for ~1s of crypto; on a loaded host the whole network then
        # starves itself: each node's inbound partials only get CPU
        # after the next tick's signs, so every round is abandoned with
        # its partials still queued behind the loop.
        # (asyncio.to_thread copies the contextvars context, so kernel
        # spans opened inside the scheme parent to the stage span.)
        with obs_trace.TRACER.span(
            "beacon.sign",
            attrs={"round": round, "node": self.cfg.public.address},
        ):
            own = await self._offload(
                self.scheme.partial_sign, self.cfg.share.share, msg
            )
        queue = self.manager.new_round(round, prev_round, prev_sig)
        self.manager.add_partial(round, own, prev_round, prev_sig,
                                 sender=self.cfg.public.address)
        packet = BeaconPacket(
            from_address=self.cfg.public.address,
            round=round,
            prev_round=prev_round,
            prev_sig=prev_sig,
            partial_sig=own,
            trace_id=tid,
            sent_at=self.clock.now(),
        )
        with obs_trace.TRACER.span(
            "beacon.gossip",
            attrs={"round": round, "peers": len(self.group) - 1,
                   "node": self.cfg.public.address},
        ):
            peers = [n for n in self.group.nodes
                     if n.address != self.cfg.public.address]
            # healthy peers first: the quorum should assemble from
            # responsive signers before any bandwidth goes to peers the
            # contribution ledger already suspects — sends launch in
            # this order and _send_packet's semaphore bounds how many
            # fly at once, so the ordering actually bites
            rank = {s["peer"]: s["score"]
                    for s in self.peer_ledger.suspects(self.clock.now())}
            peers.sort(key=lambda n: rank.get(n.address, 0.0))
            for node in peers:
                self._spawn_gossip(node, packet)

        with obs_trace.TRACER.span(
            "beacon.aggregate",
            attrs={"round": round, "threshold": self.group.threshold,
                   "node": self.cfg.public.address},
        ) as agg_span:
            partials: Dict[int, bytes] = {self.index: own}
            while len(partials) < self.group.threshold:
                # the manager only queues partials matching our chain link
                # (mismatches don't consume the signer's dedup slot)
                blob, _, _ = await queue.get()
                partials[self.scheme.index_of(blob)] = blob
            agg_span.set_attr("partials", len(partials))

        # finalize: recover the group signature and check it against the
        # distributed key (optimistic: ONE fused dispatch over the first
        # t admitted partials, blame fallback on a red check; eager: the
        # fused per-partial verify + recover).  Off-loop like sign — the
        # pairing math must not starve inbound partials.
        try:
            sig = await self._finalize_quorum(round, msg, partials, queue)
        except tbls.ThresholdError as exc:
            # unrecoverable partial set (all-bad quorum, attempts
            # exhausted, or a red check no partial explains): abandon
            # THIS attempt gracefully — the loop's next tick retargets
            # the round fresh instead of the exception tearing through
            # the traced span as a crash
            _rounds_failed.inc()
            obs_slo.ENGINE.record_bad(obs_slo.ROUND_FINALIZE,
                                      ts=self.clock.now())
            self.log.error("round unrecoverable, abandoning attempt",
                           round=round, err=str(exc))
            return
        beacon = Beacon(round=round, prev_round=prev_round,
                        prev_sig=prev_sig, signature=sig)
        # the head may have advanced while we were collecting — a benign
        # sync race, not a failure (the chain moved on without us)
        cur_head = self.store.last()
        if cur_head is not None and cur_head.round >= round:
            return
        if cur_head is not None and (
                cur_head.round != prev_round
                or cur_head.signature != prev_sig):
            # a sync landed mid-round and moved the head onto a branch
            # DIFFERENT from the link this quorum signed.  The quorum's
            # beacon carries a valid threshold signature and its round
            # is higher than the new head, so highest-round-wins says
            # the quorum's branch is the chain: roll back to the signed
            # link and adopt.  Storing it blind instead would write a
            # broken link into the store (the fork_stall bug's second
            # half); refusing would wedge us off the branch the rest of
            # the quorum is extending.
            try:
                adopted = self._adopt_reorg(
                    base_round=prev_round, base_sig=prev_sig,
                    suffix=[beacon], source="", via="quorum",
                    put_suffix=False,  # the span below does the put
                )
            except RollbackDepthExceeded:
                adopted = False
            if not adopted:
                _rounds_failed.inc()
                obs_slo.ENGINE.record_bad(obs_slo.ROUND_FINALIZE,
                                          ts=self.clock.now())
                self.log.warning(
                    "abandoning finalized round: head moved to a branch "
                    "this quorum's link cannot extend",
                    round=round, head=cur_head.round,
                )
                return
        with obs_trace.TRACER.span(
            "beacon.store",
            attrs={"round": round, "node": self.cfg.public.address},
        ):
            self.store.put(beacon)
        _rounds_total.inc()
        _head_gauge.set(round)
        _round_seconds.observe(
            asyncio.get_running_loop().time() - t_start
        )
        now = self.clock.now()
        # SLO event: latency measured against the round's SCHEDULED open,
        # not our attempt start — a late start is also a late round
        obs_slo.ENGINE.observe(
            obs_slo.ROUND_FINALIZE,
            now - time_of_round(self.group.period,
                                self.group.genesis_time, round),
            ts=now,
        )
        # contribution accounting: every signer whose partial is NOT in
        # the recovered set missed this round
        self.peer_ledger.round_complete(round, (
            self.group.nodes[i].address for i in partials
            if i < len(self.group.nodes)
        ))
        self.log.debug("round stored", round=round)
        if self._stop_at is not None and round >= self._stop_at:
            self._running = False
            self._stopped.set()

    async def _finalize_quorum(self, round: int, msg: bytes,
                               partials: Dict[int, bytes],
                               queue: asyncio.Queue) -> bytes:
        """Turn the collected quorum into the round's group signature.

        Eager mode is the single fused `finalize_round` call.  Optimistic
        mode verifies ONLY the recovered signature (one device dispatch
        on JaxScheme); when that check comes back red, one fused batched
        pairing pass identifies the forged partials, each is charged to
        the peer that SENT it (`record_invalid` on the sender address —
        the claimed signer index proves nothing and must not frame its
        honest owner), evicted from the round pool, and the quorum is
        refilled from the queue before the next bounded attempt.
        Raises ThresholdError when no clean quorum is recoverable.
        """
        t = self.group.threshold
        if not self._optimistic:
            with obs_trace.TRACER.span(
                "beacon.verify",
                attrs={"round": round, "partials": len(partials),
                       "fused": True,
                       "node": self.cfg.public.address},
            ):
                sig, spent = await self._offload(
                    _counted, self.scheme.finalize_round,
                    self.pub_poly, msg, list(partials.values()),
                    t, len(self.group),
                )
                # eager mode has no <=2 contract: account the round but
                # keep it exempt from the budget sentinel
                obs_perf.note_round(round, spent, fallback=True,
                                    now=self.clock.now())
                return sig
        spent = 0
        used_fallback = False
        for attempt in range(FINALIZE_ATTEMPTS):
            # refill after evictions; the manager's standby buffer may
            # already hold another sender's copy of an evicted index.
            # If the network has nothing more to offer, this waits until
            # the ticker cancels the attempt (ticker is king, as ever).
            while len(partials) < t:
                blob, _, _ = await queue.get()
                partials[self.scheme.index_of(blob)] = blob
            with obs_trace.TRACER.span(
                "beacon.verify",
                attrs={"round": round, "partials": len(partials),
                       "fused": True, "optimistic": True,
                       "attempt": attempt,
                       "node": self.cfg.public.address},
            ):
                try:
                    sig, d = await self._offload(
                        _counted, self.scheme.finalize_round_optimistic,
                        self.pub_poly, msg, list(partials.values()),
                        t, len(self.group),
                    )
                    # dispatch-budget sentinel: an HONEST finalize (no
                    # blame fallback) must fit the <=2-dispatch budget;
                    # fallback retries legitimately re-dispatch and are
                    # accounted but exempt from the alarm
                    obs_perf.note_round(
                        round, spent + d, fallback=used_fallback,
                        now=self.clock.now(),
                    )
                    return sig
                except tbls.ThresholdError:
                    used_fallback = True
                    _optimistic_fallbacks.inc()
                    ok, d = await self._offload(
                        _counted, self.scheme.verify_partials_batch,
                        self.pub_poly, msg, list(partials.values()),
                    )
                    spent += d
                    bad = [i for i, good in zip(list(partials), ok)
                           if not good]
                    if not bad:
                        # red recovered check but every partial verifies:
                        # a device fault — never publish the signature
                        raise tbls.ThresholdError(
                            "recovered check failed with all partials "
                            "valid"
                        )
                    now = self.clock.now()
                    for idx in bad:
                        sender = self.manager.sender_of(idx)
                        if sender:
                            # revoking the round contribution too keeps
                            # the liar out of round_complete's credit
                            self.peer_ledger.record_invalid(
                                sender, now, round=round
                            )
                        _partials_rejected.inc()
                        del partials[idx]
                        self.manager.evict(idx)
                    self.log.warning(
                        "optimistic finalize fell back",
                        round=round, evicted=len(bad), attempt=attempt,
                    )
        raise tbls.ThresholdError(
            f"no clean quorum after {FINALIZE_ATTEMPTS} attempts"
        )

    def _schedule_resync(self) -> None:
        """Fire-and-forget chain sync (at most one in flight)."""
        if not self._running:
            return  # shutting down: don't orphan a sync on a closing store
        if self._resync_task is None or self._resync_task.done():
            self._resync_task = asyncio.create_task(self.sync())

    def _refresh_round_task(self) -> None:
        """A catch-up advanced the head while a round was in flight.

        The active round task pinned its chain link to the PRE-sync
        head, so the majority's partials (linking the fresh head) were
        screened out and it can never finalize — a healed node would
        trail the fleet by exactly one round forever, re-syncing round
        n-1 at every round-n open.  Restart the task against the fresh
        head: the round manager re-offers the mislinked partials it
        kept, and the quorum that was already on the wire counts."""
        if not self._running:
            return
        task = self._round_task
        if task is None or task.done():
            return
        head = self.store.last()
        cur = current_round(self.clock.now(), self.group.period,
                            self.group.genesis_time)
        if head is None or head.round >= cur:
            return  # at/past the scheduled round: nothing to re-run
        link = self._round_link
        if link is None or link[0] == head.round:
            return  # the active round already signs the fresh link
        self.log.info("restarting round against caught-up head",
                      round=cur, old_link=link[0], new_link=head.round)
        task.cancel()
        self._round_task = asyncio.create_task(self._run_round(cur))

    def _spawn_gossip(self, node: Identity,
                      packet: BeaconPacket) -> asyncio.Task:
        """Launch one gossip send, retaining the task so it survives GC
        and stop() can cancel stragglers mid-RPC."""
        t = asyncio.create_task(self._send_packet(node, packet))
        self._gossip_tasks.add(t)
        t.add_done_callback(self._gossip_tasks.discard)
        return t

    async def _send_packet(self, node: Identity,
                           packet: BeaconPacket) -> None:
        async with self._gossip_sem:
            try:
                await self.client.new_beacon(node, packet)
                return
            except Exception as exc:
                self.log.debug("broadcast failed", to=node.address,
                               err=exc)
            # one short retry: a transient hiccup (peer mid-restart,
            # dropped stream) shouldn't cost the round this signer's
            # partial; a genuinely down peer is absorbed by the
            # threshold exactly as before.  Clock-driven so simulated
            # networks retry on the simulated timeline, not wall time.
            await self.clock.sleep(GOSSIP_RETRY_DELAY)
            try:
                await self.client.new_beacon(node, packet)
            except Exception as exc:
                self.log.debug("broadcast retry failed",
                               to=node.address, err=exc)

    # -- inbound RPCs ------------------------------------------------------

    def check_packet_window(self, packet: BeaconPacket) -> None:
        """Cheap sanity gate: round must be near the clock's current round
        (reference ProcessBeacon round checks, beacon.go:128-144)."""
        now = self.clock.now()
        cur = current_round(now, self.group.period, self.group.genesis_time)
        if packet.round < cur - 1 or packet.round > cur + 1:
            raise ValueError(
                f"round {packet.round} out of window (current {cur})"
            )

    async def process_beacon(self, packet: BeaconPacket) -> None:
        """Inbound partial signature (reference ProcessBeacon :124-160)."""
        # join the sender's round trace: prefer the propagated id, else
        # re-derive it (both sides compute the same value)
        tid = None
        if obs_trace.TRACER.enabled:
            tid = packet.trace_id or obs_trace.round_trace_id(
                self.group.get_genesis_seed(), packet.round
            )
        with obs_trace.TRACER.span(
            "beacon.partial_admit" if self._optimistic
            else "beacon.partial_verify", trace_id=tid,
            attrs={"round": packet.round, "from": packet.from_address,
                   "node": self.cfg.public.address},
        ):
            try:
                self.check_packet_window(packet)
            except Exception:
                # stale/ahead packet, not a forged signature: reject it
                # without charging the sender an "invalid partial"
                _partials_rejected.inc()
                raise
            try:
                if self._optimistic:
                    # structural admit only — length, point decode,
                    # identity rejection; NO pairing, zero device
                    # dispatches.  Validity is settled at quorum by the
                    # recovered-signature check (blame fallback evicts
                    # and charges forgeries to this sender's address).
                    self.scheme.check_partial_structure(
                        packet.partial_sig
                    )
                else:
                    msg = beacon_message(packet.prev_sig,
                                         packet.prev_round, packet.round)
                    # heavy pairing math runs off the event loop so the
                    # gRPC server keeps answering during verification
                    await self._offload(
                        self.scheme.verify_partial, self.pub_poly, msg,
                        packet.partial_sig,
                    )
            except Exception:
                _partials_rejected.inc()
                self.peer_ledger.record_invalid(
                    packet.from_address, self.clock.now()
                )
                raise
        now = self.clock.now()
        self.peer_seen[packet.from_address] = now
        self.peer_ledger.record_partial(
            packet.from_address, packet.round, ts=now,
            round_open=time_of_round(self.group.period,
                                     self.group.genesis_time,
                                     packet.round),
            sent_at=packet.sent_at or None,
        )
        # a valid partial referencing a chain link AHEAD of our head means
        # we missed a round: pull the gap from peers (the reference's
        # recovery is pull-based catch-up, SURVEY §5) so the next round's
        # message matches the majority's again
        head = self.store.last()
        if head is not None and packet.prev_round > head.round:
            self._schedule_resync()
        idx = self.scheme.index_of(packet.partial_sig)
        if idx == self.index:
            return
        _partials_in.inc()
        # the sender rides along so a forged partial discovered at
        # finalize is blamed on the peer that DELIVERED it
        self.manager.add_partial(
            packet.round, packet.partial_sig,
            packet.prev_round, packet.prev_sig,
            sender=packet.from_address,
        )

    def sync_chain_from(self, from_round: int) -> List[Beacon]:
        """Serve our chain from a round (reference SyncChain :170-194)."""
        return self.store.range_from(from_round)

    # -- catch-up ----------------------------------------------------------

    async def sync(self, peers=None) -> None:
        """Pull missing beacons from peers, batch-verifying each segment.

        The reference verifies one pairing per synced round in a serial
        loop (beacon.go:557-601); here segments of `cfg.sync_batch`
        rounds are verified in a single batched device call, with the
        next segment prefetched while the current one verifies
        (see `_sync_from`).  Large segments route through the multi-chip
        sharded pairing kernel when the scheme has a >1-device mesh
        (tbls.JaxScheme._maybe_sharded).
        """
        peers = [n for n in (peers or self.group.nodes)
                 if n.address != self.cfg.public.address]
        self._rng.shuffle(peers)
        attempted = 0
        for peer in peers:
            attempted += 1
            try:
                await self._sync_from(peer)
            except Exception as exc:
                reason = _sync_failure_reason(exc)
                _sync_failure_counter(reason).inc()
                self.log.debug("sync failed", peer=peer.address,
                               reason=reason, err=exc)
            head = self.store.last()
            now = self.clock.now()
            cur = current_round(now, self.group.period,
                                self.group.genesis_time)
            if head is not None and head.round >= cur - 1:
                self._sync_starved = False  # recovered: re-arm the edge
                self._refresh_round_task()
                return  # caught up enough to join
        if attempted and not self._sync_starved:
            # every peer failed (or served too little) and we are still
            # behind — catch-up starvation.  Edge-triggered: one flight
            # event per outage, not one per resync attempt, so `cli
            # doctor` sees the incident without the ring buffer
            # drowning in repeats.
            self._sync_starved = True
            head = self.store.last()
            obs_flight.RECORDER.record(
                "sync_starved",
                node=self.cfg.public.address,
                peers_tried=attempted,
                head_round=head.round if head else None,
                current_round=current_round(
                    self.clock.now(), self.group.period,
                    self.group.genesis_time),
            )
            self.log.warning("catch-up starved: every peer failed",
                             peers_tried=attempted)

    async def _sync_from(self, peer: Identity) -> None:
        """Double-buffered catch-up from one peer: while batch k sits on
        the device (`_verify_and_store` runs the pairing check in a
        worker thread), batch k+1 is already streaming from the peer in
        a prefetch task — network pull and device verify overlap instead
        of strictly alternating, so a slow peer no longer idles the chip
        (and a busy chip no longer idles the socket)."""
        head = self.store.last()
        assert head is not None
        stream = self.client.sync_chain(peer, head.round + 1)
        limit = max(1, self.cfg.sync_batch)

        async def next_batch() -> List[Beacon]:
            batch: List[Beacon] = []
            async for b in stream:
                batch.append(b)
                if len(batch) >= limit:
                    break
            return batch

        broken: Optional[ChainLinkBroken] = None
        try:
            batch = await next_batch()
            batch_index = 0
            while batch:
                prefetch = asyncio.create_task(next_batch())
                # one span per device batch: the catch-up path becomes a
                # sequence of beacon.sync spans whose prefetch_overlap
                # attr says whether the pipeline actually hid the pull
                with obs_trace.TRACER.span(
                    "beacon.sync",
                    attrs={"peer": peer.address, "batch": batch_index,
                           "size": len(batch),
                           "from_round": batch[0].round,
                           "to_round": batch[-1].round,
                           "node": self.cfg.public.address},
                ) as sync_span:
                    try:
                        head = await self._verify_and_store(
                            head, batch, source=peer.address
                        )
                    except BaseException:
                        # a broken link / bad signature must not orphan
                        # the in-flight prefetch (or leak its exception)
                        prefetch.cancel()
                        try:
                            await prefetch
                        except (Exception, asyncio.CancelledError):
                            pass
                        raise
                    # prefetch already done == the next pull fully
                    # overlapped this batch's device verify
                    sync_span.set_attr("prefetch_overlap",
                                       prefetch.done())
                batch_index += 1
                batch = await prefetch
        except ChainLinkBroken as exc:
            # the peer's chain does not extend ours: this is a fork,
            # not a plain gap — resolution happens below on a fresh
            # stream (the finally closes this one first)
            broken = exc
        finally:
            aclose = getattr(stream, "aclose", None)
            if aclose is not None:
                try:
                    await aclose()
                except Exception:
                    pass
        if broken is not None:
            await self._resolve_fork(peer, broken)

    async def _verify_and_store(self, head: Beacon, batch: List[Beacon],
                                source: str = "") -> Beacon:
        # chain-link checks (cheap, host side)
        prev = head
        for b in batch:
            if b.prev_round != prev.round or b.prev_sig != prev.signature \
                    or b.round <= prev.round:
                raise ChainLinkBroken(b.round)
            prev = b
        msgs = [
            beacon_message(b.prev_sig, b.prev_round, b.round)
            for b in batch
        ]
        sigs = [b.signature for b in batch]
        # mid-run resyncs share the event loop with live round intake:
        # the batched pairing check runs off-loop like process_beacon's
        ok = await self._offload(
            self.scheme.verify_chain_batch, self.dist_key, msgs, sigs
        )
        if not all(ok):
            bad = [batch[i].round for i, v in enumerate(ok) if not v]
            raise ChainSignatureInvalid(bad)
        # the pairing check yielded the event loop: a concurrent
        # finalize may have moved the head off the snapshot this batch
        # links onto (possibly onto ANOTHER BRANCH — fork_stall's B
        # finalizes 7-on-5 while its resync still holds a verified
        # [6]).  Storing the batch then would plant beacons under the
        # new head and break linkage; discard it and let the next sync
        # restart from the real head.
        cur = self.store.last()
        if cur is not None and (cur.round != head.round
                                or cur.signature != head.signature):
            raise SyncSuperseded(
                f"head moved {head.round}->{cur.round} while a sync "
                f"batch ending at {batch[-1].round} was on device"
            )
        _sync_verified.inc(len(batch))
        for b in batch:
            self.store.put(b)
            if source:
                self._beacon_sources[b.round] = source
        self._prune_sources(batch[-1].round)
        _head_gauge.set(batch[-1].round)
        return batch[-1]

    def _prune_sources(self, head_round: int) -> None:
        # sender bookkeeping only matters within reorg reach of the head
        cap = max(1, self.cfg.reorg_depth)
        if len(self._beacon_sources) <= 8 * cap:
            return
        horizon = head_round - 4 * cap
        for r in [r for r in self._beacon_sources if r < horizon]:
            del self._beacon_sources[r]

    # -- fork resolution ---------------------------------------------------

    async def _resolve_fork(self, peer: Identity,
                            broken: ChainLinkBroken) -> None:
        """Highest-round fully-verified chain wins — the reorg policy.

        Called when `peer`'s chain breaks linkage against ours: both
        branches may carry valid threshold signatures (a partition
        fork — fork_stall's exact shape).  Pull the peer's branch from
        inside the reorg window, find the divergence point against our
        store, verify the competitor suffix end-to-end through the
        batched/mesh pairing path, and adopt it iff its verified head
        is STRICTLY higher than ours.  Anything else raises with the
        local chain untouched: :class:`ForkRejected` (lower/equal head,
        broken branch, nothing divergent), :class:`RollbackDepthExceeded`
        (divergence beyond the cap), :class:`ChainSignatureInvalid`
        (forged branch — the sender is charged `record_invalid`).
        """
        cap = max(1, self.cfg.reorg_depth)
        head = self.store.last()
        assert head is not None
        lo = max(1, head.round - cap)
        # bound the pull: enough shared prefix to locate the divergence
        # plus enough suffix to beat our head by whole batches — a peer
        # further ahead than this is finished off by the next regular
        # sync, which continues from the adopted head
        max_pull = cap + 2 * max(1, self.cfg.sync_batch)
        branch: List[Beacon] = []
        stream = self.client.sync_chain(peer, lo)
        try:
            async for b in stream:
                branch.append(b)
                if len(branch) >= max_pull:
                    break
        finally:
            aclose = getattr(stream, "aclose", None)
            if aclose is not None:
                try:
                    await aclose()
                except Exception:
                    pass
        # drop the shared prefix (beacons byte-identical to ours); what
        # remains is the competitor suffix
        suffix: List[Beacon] = []
        for b in branch:
            if not suffix:
                ours = self.store.get(b.round)
                if ours is not None and ours == b:
                    continue
            suffix.append(b)
        if not suffix:
            raise ForkRejected(
                f"{peer.address} served nothing divergent from round "
                f"{lo} on (link broke at {broken.round} but the "
                "re-pull matched our chain)"
            )
        first = suffix[0]
        prev = first
        for b in suffix[1:]:
            if b.prev_round != prev.round \
                    or b.prev_sig != prev.signature \
                    or b.round <= prev.round:
                raise ForkRejected(
                    f"competitor branch from {peer.address} is itself "
                    f"broken at round {b.round}"
                )
            prev = b
        new_head = suffix[-1]
        # the policy gate: a competitor that cannot strictly beat our
        # head is noise, not a reorg (equal heads keep paging as a
        # fork at the watchdog until one branch outgrows the other)
        if new_head.round <= head.round:
            raise ForkRejected(
                f"competitor head {new_head.round} from {peer.address} "
                f"does not beat ours ({head.round})"
            )
        # the divergence base must be a beacon we hold byte-identically;
        # a deeper divergence than the pulled window is beyond the cap
        # by construction
        anchor = self.store.get(first.prev_round)
        if anchor is None or anchor.signature != first.prev_sig:
            depth = max(head.round - first.prev_round, cap + 1)
            self._note_reorg_refused(peer.address, first.prev_round,
                                     depth, cap)
            raise RollbackDepthExceeded(first.prev_round, depth, cap)
        # end-to-end threshold verification of the competitor suffix —
        # same batched/mesh pairing path as regular catch-up, off-loop
        msgs = [beacon_message(b.prev_sig, b.prev_round, b.round)
                for b in suffix]
        sigs = [b.signature for b in suffix]
        ok = await self._offload(
            self.scheme.verify_chain_batch, self.dist_key, msgs, sigs
        )
        if not all(ok):
            bad = [suffix[i].round for i, v in enumerate(ok) if not v]
            # a forged competitor is proof of misbehavior by the SENDER
            # (unlike an orphaned-but-valid branch, which is not)
            self.peer_ledger.record_invalid(peer.address,
                                            self.clock.now())
            raise ChainSignatureInvalid(bad)
        _sync_verified.inc(len(suffix))
        if not self._adopt_reorg(
            base_round=first.prev_round, base_sig=first.prev_sig,
            suffix=suffix, source=peer.address, via="sync",
        ):
            raise ForkRejected(
                f"divergence base {first.prev_round} moved while "
                "resolving the fork — retrying on the next sync"
            )

    def _adopt_reorg(self, base_round: int, base_sig: bytes,
                     suffix: List[Beacon], source: str, via: str,
                     put_suffix: bool = True) -> bool:
        """Atomically switch to a verified competitor branch.

        Rolls the store back to `(base_round, base_sig)` (bounded by
        `cfg.reorg_depth` — raises :class:`RollbackDepthExceeded`, store
        untouched, when the cap refuses), re-applies `suffix`, charges
        the orphaned beacons' *senders* (`record_orphaned`, soft —
        never the claimed signer indices), invalidates the round
        manager + scheme round caches, and emits the `chain.reorg`
        flight event / `drand_chain_reorgs_total{depth}` metric /
        registered reorg callbacks.  Returns False (nothing changed)
        when the anchor no longer matches.
        """
        anchor = self.store.get(base_round)
        if anchor is None or anchor.signature != base_sig:
            return False
        cap = max(1, self.cfg.reorg_depth)
        old_head = self.store.last()
        try:
            dropped = self.store.rollback_to(base_round, max_depth=cap)
        except RollbackDepthExceeded as exc:
            self._note_reorg_refused(source or via, base_round,
                                     exc.depth, exc.cap)
            raise
        now = self.clock.now()
        orphan_senders: Dict[str, int] = {}
        for b in dropped:
            src = self._beacon_sources.pop(b.round, "")
            if src:
                orphan_senders[src] = orphan_senders.get(src, 0) + 1
        if put_suffix:
            for b in suffix:
                self.store.put(b)
                if source:
                    self._beacon_sources[b.round] = source
        for src in sorted(orphan_senders):
            self.peer_ledger.record_orphaned(src, now,
                                             rounds=orphan_senders[src])
        depth = len(dropped)
        new_head = suffix[-1].round if suffix else base_round
        _head_gauge.set(new_head)
        _reorg_counter(depth).inc()
        # the active round collected partials against an orphaned link:
        # poison — drop it so the next tick signs the adopted head.
        # (The quorum path calls this from INSIDE the round task, which
        # is about to store its own beacon — never cancel that.)
        if via != "quorum" and self._round_task is not None \
                and not self._round_task.done():
            self._round_task.cancel()
        self.manager.invalidate()
        invalidate = getattr(self.scheme, "invalidate_round_caches",
                             None)
        if invalidate is not None:
            invalidate()
        ev = {
            "node": self.cfg.public.address,
            "peer": source,
            "via": via,
            "divergence_round": base_round,
            "depth": depth,
            "old_head": old_head.round if old_head else base_round,
            "new_head": new_head,
        }
        self.reorg_stats["total"] += 1
        self.reorg_stats["max_depth"] = max(
            self.reorg_stats["max_depth"], depth)
        self.reorg_stats["last"] = dict(ev, ts=now)
        obs_flight.RECORDER.record("chain.reorg", **ev)
        for cb in list(self._reorg_callbacks):
            try:
                cb(dict(ev))
            except Exception:  # observers must never break the chain
                pass
        self.log.warning("chain reorg", **ev)
        return True

    def _note_reorg_refused(self, peer: str, base: int, depth: int,
                            cap: int) -> None:
        """Edge-triggered beyond-cap refusal: one flight event per
        (peer, divergence) fork, however many syncs re-encounter it."""
        key = (peer, base)
        if key in self._refused_forks:
            return
        self._refused_forks.add(key)
        obs_flight.RECORDER.record(
            "chain.reorg_refused",
            node=self.cfg.public.address, peer=peer,
            divergence_round=base, depth=depth, cap=cap,
        )
        self.log.error(
            "reorg refused: competitor diverges beyond the depth cap",
            peer=peer, divergence_round=base, depth=depth, cap=cap,
        )
