"""Beacon persistence: an embedded K/V store with cursor iteration.

Mirrors /root/reference/beacon/store.go (boltdb keyed by big-endian round;
`Store{Len,Put,Last,Get,Cursor,Close}`, `Cursor{First,Next,Seek,Last}`,
plus the callback-decorated store :234).  Backed by sqlite3 — embedded,
transactional, ubiquitous; ":memory:" gives the test store.
"""

from __future__ import annotations

import sqlite3
import threading
from typing import Callable, Iterator, List, Optional

from drand_tpu.beacon.chain import Beacon


class RollbackDepthExceeded(RuntimeError):
    """A rollback would drop more rounds than the configured cap.

    Raised by every backend's ``rollback_to`` with the store untouched —
    a competitor chain that diverges deeper than the cap must be refused,
    not partially adopted."""

    def __init__(self, target: int, depth: int, cap: int):
        super().__init__(
            f"rollback to round {target} would drop {depth} rounds "
            f"(depth cap {cap}) — refusing, chain untouched"
        )
        self.target = target
        self.depth = depth
        self.cap = cap


class BeaconStore:
    def __init__(self, path: str = ":memory:"):
        self._db = sqlite3.connect(path, check_same_thread=False)
        self._lock = threading.Lock()
        with self._lock:
            self._db.execute(
                "CREATE TABLE IF NOT EXISTS beacons ("
                " round INTEGER PRIMARY KEY,"
                " prev_round INTEGER NOT NULL,"
                " prev_sig BLOB NOT NULL,"
                " signature BLOB NOT NULL)"
            )
            self._db.commit()

    def __len__(self) -> int:
        with self._lock:
            (n,) = self._db.execute(
                "SELECT COUNT(*) FROM beacons"
            ).fetchone()
        return int(n)

    def put(self, b: Beacon) -> None:
        with self._lock:
            self._db.execute(
                "INSERT OR REPLACE INTO beacons VALUES (?,?,?,?)",
                (b.round, b.prev_round, b.prev_sig, b.signature),
            )
            self._db.commit()

    @staticmethod
    def _row_to_beacon(row) -> Beacon:
        return Beacon(
            round=int(row[0]),
            prev_round=int(row[1]),
            prev_sig=bytes(row[2]),
            signature=bytes(row[3]),
        )

    def get(self, round: int) -> Optional[Beacon]:
        with self._lock:
            row = self._db.execute(
                "SELECT * FROM beacons WHERE round=?", (round,)
            ).fetchone()
        return self._row_to_beacon(row) if row else None

    def last(self) -> Optional[Beacon]:
        with self._lock:
            row = self._db.execute(
                "SELECT * FROM beacons ORDER BY round DESC LIMIT 1"
            ).fetchone()
        return self._row_to_beacon(row) if row else None

    def cursor(self) -> "Cursor":
        return Cursor(self)

    def range_from(self, from_round: int,
                   limit: Optional[int] = None) -> List[Beacon]:
        """All beacons with round >= from_round, ascending (sync streams)."""
        q = "SELECT * FROM beacons WHERE round>=? ORDER BY round ASC"
        args: tuple = (from_round,)
        if limit is not None:
            q += " LIMIT ?"
            args = (from_round, limit)
        with self._lock:
            rows = self._db.execute(q, args).fetchall()
        return [self._row_to_beacon(r) for r in rows]

    def rollback_to(self, round: int,
                    max_depth: Optional[int] = None) -> List[Beacon]:
        """Drop every beacon with round > `round` (chain reorg).

        Returns the dropped beacons in ascending round order.  Raises
        :class:`RollbackDepthExceeded` (store untouched) when more than
        `max_depth` rounds would be dropped; `max_depth=None` is
        unbounded.  Count + delete run under one lock so a concurrent
        put cannot slip between the cap check and the delete."""
        with self._lock:
            rows = self._db.execute(
                "SELECT * FROM beacons WHERE round>? ORDER BY round ASC",
                (round,),
            ).fetchall()
            if max_depth is not None and len(rows) > max_depth:
                raise RollbackDepthExceeded(round, len(rows), max_depth)
            if rows:
                self._db.execute(
                    "DELETE FROM beacons WHERE round>?", (round,)
                )
                self._db.commit()
        return [self._row_to_beacon(r) for r in rows]

    def close(self) -> None:
        with self._lock:
            self._db.close()


def open_store(path: str = ":memory:", backend: str = "auto",
               fsync_puts: bool = True):
    """Open a chain store: 'native' (C++ append-log), 'sqlite', or 'auto'
    (native when the shared library builds, sqlite otherwise).

    `fsync_puts` defaults on for durability parity with the sqlite
    backend (and the reference's transactional boltdb Put,
    beacon/store.go:103); pass False for throwaway test stores."""
    if backend not in ("auto", "native", "sqlite"):
        raise ValueError(f"unknown store backend {backend!r}")
    if backend in ("auto", "native"):
        try:
            from drand_tpu.beacon.native_store import NativeBeaconStore

            return NativeBeaconStore(path, fsync_puts=fsync_puts)
        except (RuntimeError, OSError):
            if backend == "native":
                raise
    # refuse to garble an existing native-format chain through sqlite
    if path != ":memory:":
        try:
            with open(path, "rb") as fh:
                if fh.read(8) == b"DTCSTOR1":
                    why = (
                        "the native backend is unavailable "
                        "(no C++ toolchain?)"
                        if backend == "auto"
                        else "backend='sqlite' was requested — open it "
                        "with backend='native' or 'auto'"
                    )
                    raise RuntimeError(
                        f"{path} holds a native-format chain but {why}"
                    )
        except FileNotFoundError:
            pass
    return BeaconStore(path)


class Cursor:
    """Iteration over the chain in round order (reference store.go:40-45)."""

    def __init__(self, store: BeaconStore):
        self._store = store
        self._round: Optional[int] = None

    def _fetch(self, q: str, args=()) -> Optional[Beacon]:
        with self._store._lock:
            row = self._store._db.execute(q, args).fetchone()
        if row is None:
            return None
        b = BeaconStore._row_to_beacon(row)
        self._round = b.round
        return b

    def first(self) -> Optional[Beacon]:
        return self._fetch("SELECT * FROM beacons ORDER BY round ASC LIMIT 1")

    def last(self) -> Optional[Beacon]:
        return self._fetch("SELECT * FROM beacons ORDER BY round DESC LIMIT 1")

    def seek(self, round: int) -> Optional[Beacon]:
        return self._fetch(
            "SELECT * FROM beacons WHERE round>=? ORDER BY round ASC LIMIT 1",
            (round,),
        )

    def next(self) -> Optional[Beacon]:
        if self._round is None:
            return self.first()
        return self._fetch(
            "SELECT * FROM beacons WHERE round>? ORDER BY round ASC LIMIT 1",
            (self._round,),
        )


class CallbackStore:
    """Store decorator invoking callbacks on every new beacon
    (reference NewCallbackStore store.go:234)."""

    def __init__(self, inner: BeaconStore):
        self._inner = inner
        self._callbacks: List[Callable[[Beacon], None]] = []
        self._rollback_callbacks: List[
            Callable[[int, List[Beacon]], None]
        ] = []

    def add_callback(self, cb: Callable[[Beacon], None]) -> None:
        self._callbacks.append(cb)

    def add_rollback_callback(
        self, cb: Callable[[int, List[Beacon]], None]
    ) -> None:
        """cb(target_round, dropped_beacons) after every rollback."""
        self._rollback_callbacks.append(cb)

    def put(self, b: Beacon) -> None:
        self._inner.put(b)
        for cb in list(self._callbacks):
            try:
                cb(b)
            except Exception:  # callbacks must never break the chain
                pass

    def rollback_to(self, round: int,
                    max_depth: Optional[int] = None) -> List[Beacon]:
        dropped = self._inner.rollback_to(round, max_depth=max_depth)
        if not dropped:  # no-op rollback: nothing for listeners to undo
            return dropped
        for cb in list(self._rollback_callbacks):
            try:
                cb(round, dropped)
            except Exception:  # callbacks must never break the chain
                pass
        return dropped

    def __getattr__(self, name):
        return getattr(self._inner, name)

    def __len__(self):
        return len(self._inner)
