"""ctypes binding for the native C++ chain store (native/chainstore.cc).

Same interface as :class:`drand_tpu.beacon.store.BeaconStore` (the
reference's boltdb store surface, /root/reference/beacon/store.go:22-45):
``__len__ / put / get / last / cursor / range_from / close``.  Use
:func:`available` to test whether the shared library could be built, and
:func:`drand_tpu.beacon.store.open_store` to pick a backend.
"""

from __future__ import annotations

import ctypes
import threading
from typing import List, Optional

from drand_tpu import native
from drand_tpu.beacon.chain import Beacon
from drand_tpu.beacon.store import RollbackDepthExceeded

_CAP = 4096  # signature buffer capacity (sigs are 96B; headroom is free)

_lib = None
_lib_lock = threading.Lock()


def _load():
    global _lib
    if _lib is not None:
        return _lib
    with _lib_lock:
        if _lib is not None:
            return _lib
        path = native.shared_lib("chainstore")
        if path is None:
            raise RuntimeError(
                f"native chainstore unavailable: {native.build_error()}"
            )
        lib = ctypes.CDLL(path)
        lib.dtcs_open.restype = ctypes.c_void_p
        lib.dtcs_open.argtypes = [ctypes.c_char_p, ctypes.c_int]
        lib.dtcs_close.argtypes = [ctypes.c_void_p]
        lib.dtcs_count.restype = ctypes.c_int64
        lib.dtcs_count.argtypes = [ctypes.c_void_p]
        lib.dtcs_put.argtypes = [
            ctypes.c_void_p, ctypes.c_uint64, ctypes.c_uint64,
            ctypes.c_char_p, ctypes.c_uint32,
            ctypes.c_char_p, ctypes.c_uint32,
        ]
        lookup = [
            ctypes.c_void_p, ctypes.c_uint64,
            ctypes.POINTER(ctypes.c_uint64),
            ctypes.POINTER(ctypes.c_uint64),
            ctypes.c_char_p, ctypes.POINTER(ctypes.c_uint32),
            ctypes.c_char_p, ctypes.POINTER(ctypes.c_uint32),
        ]
        nolookup = lookup[:1] + lookup[2:]
        lib.dtcs_get.argtypes = lookup
        lib.dtcs_seek.argtypes = lookup
        lib.dtcs_first.argtypes = nolookup
        lib.dtcs_last.argtypes = nolookup
        lib.dtcs_rollback.restype = ctypes.c_int64
        lib.dtcs_rollback.argtypes = [
            ctypes.c_void_p, ctypes.c_uint64, ctypes.c_int64,
        ]
        _lib = lib
    return _lib


def available() -> bool:
    try:
        _load()
        return True
    except (RuntimeError, OSError):
        # OSError: a stale/foreign shared object that CDLL refuses
        return False


class NativeBeaconStore:
    def __init__(self, path: str = ":memory:", fsync_puts: bool = False):
        lib = _load()
        cpath = b"" if path == ":memory:" else path.encode()
        self._h = lib.dtcs_open(cpath, 1 if fsync_puts else 0)
        if not self._h:
            raise RuntimeError(f"cannot open native chain store at {path}")
        self._lib = lib

    def __len__(self) -> int:
        return int(self._lib.dtcs_count(self._h))

    def put(self, b: Beacon) -> None:
        rc = self._lib.dtcs_put(
            self._h, b.round, b.prev_round,
            b.prev_sig, len(b.prev_sig), b.signature, len(b.signature),
        )
        if rc != 0:
            raise IOError(f"native store put failed (rc={rc})")

    def _lookup(self, fn, *args) -> Optional[Beacon]:
        rnd = ctypes.c_uint64()
        prev = ctypes.c_uint64()
        psl = ctypes.c_uint32(_CAP)
        sl = ctypes.c_uint32(_CAP)
        pbuf = ctypes.create_string_buffer(_CAP)
        sbuf = ctypes.create_string_buffer(_CAP)
        rc = fn(self._h, *args, ctypes.byref(rnd), ctypes.byref(prev),
                pbuf, ctypes.byref(psl), sbuf, ctypes.byref(sl))
        if rc == -1:
            return None
        if rc != 0:
            raise IOError(f"native store lookup failed (rc={rc})")
        return Beacon(
            round=rnd.value, prev_round=prev.value,
            prev_sig=pbuf.raw[: psl.value], signature=sbuf.raw[: sl.value],
        )

    def get(self, round: int) -> Optional[Beacon]:
        return self._lookup(self._lib.dtcs_get, ctypes.c_uint64(round))

    def _seek(self, round: int) -> Optional[Beacon]:
        return self._lookup(self._lib.dtcs_seek, ctypes.c_uint64(round))

    def first(self) -> Optional[Beacon]:
        return self._lookup(self._lib.dtcs_first)

    def last(self) -> Optional[Beacon]:
        return self._lookup(self._lib.dtcs_last)

    def cursor(self) -> "NativeCursor":
        return NativeCursor(self)

    def range_from(self, from_round: int,
                   limit: Optional[int] = None) -> List[Beacon]:
        out: List[Beacon] = []
        rnd = from_round
        while limit is None or len(out) < limit:
            b = self._seek(rnd)
            if b is None:
                break
            out.append(b)
            rnd = b.round + 1
        return out

    def rollback_to(self, round: int,
                    max_depth: Optional[int] = None) -> List[Beacon]:
        """Drop every beacon with round > `round` (chain reorg).

        Durable via a truncate record appended to the log (see
        chainstore.cc) — a crash mid-rollback replays to either the
        pre- or post-rollback chain, never a mix.  Raises
        :class:`RollbackDepthExceeded` (store untouched) beyond the cap."""
        dropped = self.range_from(round + 1)
        cap = -1 if max_depth is None else max_depth
        rc = int(self._lib.dtcs_rollback(
            self._h, ctypes.c_uint64(round), ctypes.c_int64(cap)))
        if rc == -3:
            raise RollbackDepthExceeded(round, len(dropped), cap)
        if rc < 0:
            raise IOError(f"native store rollback failed (rc={rc})")
        return dropped

    def close(self) -> None:
        if self._h:
            self._lib.dtcs_close(self._h)
            self._h = None

    def __del__(self):  # pragma: no cover - GC ordering
        try:
            self.close()
        except Exception:
            pass


class NativeCursor:
    """Round-ordered cursor (reference store.go Cursor:40-45)."""

    def __init__(self, store: NativeBeaconStore):
        self._store = store
        self._round: Optional[int] = None

    def _note(self, b: Optional[Beacon]) -> Optional[Beacon]:
        if b is not None:
            self._round = b.round
        return b

    def first(self) -> Optional[Beacon]:
        return self._note(self._store.first())

    def last(self) -> Optional[Beacon]:
        return self._note(self._store.last())

    def seek(self, round: int) -> Optional[Beacon]:
        return self._note(self._store._seek(round))

    def next(self) -> Optional[Beacon]:
        if self._round is None:
            return self.first()
        return self._note(self._store._seek(self._round + 1))
