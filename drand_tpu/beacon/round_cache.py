"""Per-round partial-signature collection with dedup and look-ahead.

Mirrors /root/reference/beacon/round_cache.go: the reference serializes all
partial handling through one goroutine with a 1024-slot look-ahead buffer
for future-round partials (:33) and dedups by signer index (:113-118).
Here the asyncio event loop provides the serialization; the manager keeps
one queue for the active round and buffers bounded future-round partials.

Optimistic finalization (lazy partial verification) adds two duties:

* every admitted partial remembers WHICH peer delivered it
  (`sender_of`), because blame for a forged partial must land on the
  sender's address, never on the claimed signer index — a malicious
  peer must not be able to frame an honest signer;
* a blamed signer slot can be `evict`ed, which frees the dedup slot and
  re-offers a standby duplicate if one arrived — so a liar squatting an
  honest signer's index (its garbage won the dedup race) cannot block
  that signer's real partial from counting toward a clean quorum.
"""

from __future__ import annotations

import asyncio
from typing import Dict, List, Optional, Tuple

MAX_LOOKAHEAD = 1024

#: deduped duplicates kept per signer index for the active round, so an
#: evicted (blamed) slot can be refilled from a second sender
MAX_STANDBY = 4

#: partials kept aside per round whose chain link doesn't match the
#: active round's — if WE are the desynced side, a catch-up restarts the
#: round against the majority link and these are re-offered
MAX_MISLINKED = 64


class RoundManager:
    """Entries are (partial_bytes, prev_round, prev_sig): recovery must
    only combine partials that sign the SAME chain link — mixing a
    lagging node's link with the majority's yields garbage signatures."""

    def __init__(self, index_of):
        self._index_of = index_of          # partial bytes -> signer index
        self._round: Optional[int] = None
        self._queue: Optional[asyncio.Queue] = None
        self._seen: set = set()
        self._link: Optional[Tuple[int, bytes]] = None
        # internal buffers carry the sender as a 4th element; the public
        # queue keeps the historical 3-tuple shape
        self._future: Dict[int, List[tuple]] = {}
        self._buffered = 0
        self._senders: Dict[int, str] = {}   # signer idx -> sender address
        self._standby: Dict[int, List[tuple]] = {}
        #: round -> partials whose (prev_round, prev_sig) mismatched the
        #: active link when they arrived (see _offer)
        self._mislinked: Dict[int, List[tuple]] = {}

    def new_round(self, round: int, prev_round: Optional[int] = None,
                  prev_sig: Optional[bytes] = None) -> asyncio.Queue:
        """Activate a round; flush any buffered partials for it.

        When (prev_round, prev_sig) is given, only partials signing that
        exact chain link are accepted."""
        self._round = round
        self._queue = asyncio.Queue()
        self._seen = set()
        self._senders = {}
        self._standby = {}
        self._link = (
            (prev_round, prev_sig) if prev_sig is not None else None
        )
        for entry in self._future.pop(round, []):
            self._buffered -= 1
            self._offer(entry)
        # a round RE-opened against a fresh link (catch-up advanced the
        # head mid-round): partials that mismatched the stale link get a
        # second screening — the majority's quorum may be among them
        for entry in self._mislinked.pop(round, []):
            self._offer(entry)
        # drop stale buffered rounds
        for r in [r for r in self._future if r <= round]:
            self._buffered -= len(self._future.pop(r))
        for r in [r for r in self._mislinked if r < round]:
            del self._mislinked[r]
        return self._queue

    def _offer(self, entry: tuple) -> None:
        if self._link is not None and (entry[1], entry[2]) != self._link:
            # wrong chain link: ONE side of this exchange is desynced
            # and its partial signs a different message.  The signer's
            # dedup slot is not consumed (a corrected partial re-sent
            # after a resync still counts) and the entry is kept aside:
            # if WE turn out to be the stale side, the handler restarts
            # this round against the caught-up head and `new_round`
            # re-screens these against the majority link.
            if self._round is not None:
                aside = self._mislinked.setdefault(self._round, [])
                if len(aside) < MAX_MISLINKED:
                    aside.append(entry)
            return
        idx = self._index_of(entry[0])
        if idx in self._seen:
            # keep a few alternates: if the queued partial turns out
            # forged and gets evicted, a second sender's copy takes over
            standby = self._standby.setdefault(idx, [])
            if len(standby) < MAX_STANDBY:
                standby.append(entry)
            return
        self._seen.add(idx)
        self._senders[idx] = entry[3] if len(entry) > 3 else ""
        assert self._queue is not None
        self._queue.put_nowait(entry[:3])

    def add_partial(self, round: int, blob: bytes,
                    prev_round: int, prev_sig: bytes,
                    sender: str = "") -> None:
        entry = (blob, prev_round, prev_sig, sender)
        if self._round is not None and round == self._round:
            self._offer(entry)
        elif (self._round is None or round > self._round) and \
                self._buffered < MAX_LOOKAHEAD:
            self._future.setdefault(round, []).append(entry)
            self._buffered += 1
        # else: stale round — drop

    def invalidate(self) -> None:
        """A chain reorg moved the head under the active round: every
        queued/standby partial signs the orphaned link, so the active
        round state is poison — drop it.  Future-round lookahead stays:
        `new_round`'s link filter re-screens it against the adopted
        head when the next round opens."""
        self._round = None
        self._queue = None
        self._seen = set()
        self._senders = {}
        self._standby = {}
        self._link = None

    def sender_of(self, idx: int) -> str:
        """Address of the peer whose partial currently holds signer slot
        `idx` ("" when unknown) — the blame target for a forged partial."""
        return self._senders.get(idx, "")

    def evict(self, idx: int) -> None:
        """A blamed partial is removed from the round pool: free the
        signer's dedup slot and re-offer the next standby duplicate (a
        different sender's copy of the same index), if any arrived."""
        self._seen.discard(idx)
        self._senders.pop(idx, None)
        standby = self._standby.get(idx)
        if standby:
            self._offer(standby.pop(0))
