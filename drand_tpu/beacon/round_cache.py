"""Per-round partial-signature collection with dedup and look-ahead.

Mirrors /root/reference/beacon/round_cache.go: the reference serializes all
partial handling through one goroutine with a 1024-slot look-ahead buffer
for future-round partials (:33) and dedups by signer index (:113-118).
Here the asyncio event loop provides the serialization; the manager keeps
one queue for the active round and buffers bounded future-round partials.
"""

from __future__ import annotations

import asyncio
from typing import Dict, List, Optional, Tuple

MAX_LOOKAHEAD = 1024


class RoundManager:
    """Entries are (partial_bytes, prev_round, prev_sig): recovery must
    only combine partials that sign the SAME chain link — mixing a
    lagging node's link with the majority's yields garbage signatures."""

    def __init__(self, index_of):
        self._index_of = index_of          # partial bytes -> signer index
        self._round: Optional[int] = None
        self._queue: Optional[asyncio.Queue] = None
        self._seen: set = set()
        self._link: Optional[Tuple[int, bytes]] = None
        self._future: Dict[int, List[Tuple[bytes, int, bytes]]] = {}
        self._buffered = 0

    def new_round(self, round: int, prev_round: Optional[int] = None,
                  prev_sig: Optional[bytes] = None) -> asyncio.Queue:
        """Activate a round; flush any buffered partials for it.

        When (prev_round, prev_sig) is given, only partials signing that
        exact chain link are accepted."""
        self._round = round
        self._queue = asyncio.Queue()
        self._seen = set()
        self._link = (
            (prev_round, prev_sig) if prev_sig is not None else None
        )
        for entry in self._future.pop(round, []):
            self._buffered -= 1
            self._offer(entry)
        # drop stale buffered rounds
        for r in [r for r in self._future if r <= round]:
            self._buffered -= len(self._future.pop(r))
        return self._queue

    def _offer(self, entry: Tuple[bytes, int, bytes]) -> None:
        if self._link is not None and (entry[1], entry[2]) != self._link:
            # wrong chain link: the signer is desynced and its partial
            # signs a different message.  Dropped WITHOUT consuming the
            # signer's dedup slot, so a corrected partial re-sent after
            # the peer resyncs can still count toward this round.
            return
        idx = self._index_of(entry[0])
        if idx in self._seen:
            return
        self._seen.add(idx)
        assert self._queue is not None
        self._queue.put_nowait(entry)

    def add_partial(self, round: int, blob: bytes,
                    prev_round: int, prev_sig: bytes) -> None:
        entry = (blob, prev_round, prev_sig)
        if self._round is not None and round == self._round:
            self._offer(entry)
        elif (self._round is None or round > self._round) and \
                self._buffered < MAX_LOOKAHEAD:
            self._future.setdefault(round, []).append(entry)
            self._buffered += 1
        # else: stale round — drop
