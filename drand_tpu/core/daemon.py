"""The drand_tpu daemon.

Mirrors /root/reference/core/drand.go + drand_control.go + drand_public.go:

* boot: load keypair, start the public gateway (gRPC), the localhost
  control server, and optionally the REST gateway (`NewDrand`/`LoadDrand`,
  core/drand.go:62,114);
* `init_dkg` / `init_reshare`: the control-plane entry points that
  validate the group, run the DKG handler, persist share/group/distkey and
  start (or transition) the beacon (`InitDKG` core/drand_control.go:27,
  `InitReshare` :91, `WaitDKG` core/drand.go:150, `transition` :234);
* public services: current/old beacons, streaming, ECIES private
  randomness (`PublicRand` core/drand_public.go:78, `PrivateRand` :132);
* protocol services: partial-signature intake and chain-sync serving.
"""

from __future__ import annotations

import asyncio
import os
import secrets
from dataclasses import dataclass, field
from pathlib import Path
from typing import Callable, Dict, List, Optional, Set

from drand_tpu.beacon import (
    Beacon,
    BeaconConfig,
    BeaconHandler,
    BeaconStore,
    open_store,
    current_round,
    time_of_round,
)
from drand_tpu.beacon.handler import BeaconPacket
from drand_tpu.crypto import ecies
from drand_tpu.crypto import refimpl as ref
from drand_tpu.crypto import tbls
from drand_tpu.dkg import DKGConfig, DKGHandler
from drand_tpu.key import (
    DistPublic,
    FileStore,
    Group,
    Identity,
    Pair,
    Share,
)
from drand_tpu.key.store import KeyNotFound, MemStore
from drand_tpu.net import (
    CertManager,
    GrpcClient,
    build_control_server,
    build_public_server,
)
from drand_tpu.utils import toml_dumps
from drand_tpu.utils.clock import Clock

from drand_tpu.utils.logging import get_logger

log = get_logger("core")

MIN_GROUP_SIZE = 4          # reference core/drand_control.go:356
DEFAULT_CONTROL_PORT = 8888  # reference net/control.go:21
DEFAULT_DKG_TIMEOUT = 60.0


@dataclass
class Config:
    """Daemon configuration (reference core/config.go functional options,
    flattened into a dataclass)."""

    base_folder: str = "~/.drand-tpu"
    listen_addr: str = "127.0.0.1:0"     # bind address for the gateway
    public_addr: Optional[str] = None    # address peers dial (default: listen)
    control_port: int = DEFAULT_CONTROL_PORT
    rest_port: Optional[int] = None      # REST gateway (None = disabled)
    rest_host: str = "0.0.0.0"           # REST bind host
    #: ONE public port serving both gRPC and REST (the reference's cmux
    #: listener, net/listener_grpc.go:23-97); backends move to loopback
    #: and TLS — when configured — terminates at the mux
    mux_port: Optional[int] = None
    tls_cert: Optional[bytes] = None     # PEM (with tls_key enables TLS)
    tls_key: Optional[bytes] = None
    cert_manager: CertManager = field(default_factory=CertManager)
    clock: Clock = field(default_factory=Clock)
    scheme: Optional[tbls.Scheme] = None
    dkg_timeout: float = DEFAULT_DKG_TIMEOUT
    insecure: bool = True                # no TLS (tests / local demos)
    in_memory: bool = False              # MemStore + in-memory beacon db
    # verification gateway (serve/): batch/backpressure policy for the
    # VerifyBeacon RPCs and POST /v1/verify
    verify_max_batch: int = 128          # one Pallas block per tick
    verify_max_wait: float = 0.005       # flush latency bound (s)
    verify_max_queue: int = 1024         # admission bound, then shed
    verify_cache_size: int = 4096        # LRU verified-round entries
    #: inbound-partial policy: "optimistic" (structural admit + one
    #: recovered-signature check at quorum, blame fallback on failure)
    #: or "eager" (pairing check per partial at arrival — the fallback
    #: knob if optimistic finalization misbehaves in the field)
    partial_verify: str = "optimistic"
    #: outbound protocol transport; None = the gRPC client.  Injectable
    #: (net/interface.ProtocolClient) so a simulated daemon talks over
    #: an in-memory fabric instead of sockets.  Must also provide
    #: `close()` and, for DKG flows, `send_dkg`/`dkg_context`.
    protocol_client: Optional[object] = None
    #: entropy source for private-randomness replies; injectable so
    #: deterministic simulations never touch the OS CSPRNG
    entropy_fn: "Callable[[int], bytes]" = secrets.token_bytes


class Drand:
    """One daemon process (reference core/drand.go:23-58)."""

    def __init__(self, cfg: Config, pair: Pair):
        self.cfg = cfg
        self.pair = pair
        self.clock = cfg.clock
        self.scheme = cfg.scheme or tbls.default_scheme()
        if cfg.in_memory:
            self.key_store = MemStore(pair)
        else:
            base = os.path.expanduser(cfg.base_folder)
            self.key_store = FileStore(base)
            self.key_store.save_key_pair(pair)
        self.group: Optional[Group] = None
        self.share: Optional[Share] = None
        self.dist: Optional[DistPublic] = None
        self.beacon: Optional[BeaconHandler] = None
        self._beacon_store: Optional[BeaconStore] = None
        self.dkg: Optional[DKGHandler] = None
        self._dkg_group: Optional[Group] = None
        self._client = cfg.protocol_client or GrpcClient(cfg.cert_manager)
        self._verify_gateway = None
        self._servers: List = []
        self._subscribers: Set[asyncio.Queue] = set()
        #: background work (partial ingest, stop-from-signal): asyncio
        #: holds only a weak reference to running tasks, so a dropped
        #: handle can be collected mid-flight and its exception lost —
        #: everything spawned via _spawn() lives here until done
        self._bg_tasks: Set[asyncio.Task] = set()
        self._exit = asyncio.Event()
        self._listen_port: Optional[int] = None

    # ------------------------------------------------------------------ boot

    @classmethod
    async def new(cls, cfg: Config, pair: Optional[Pair] = None) -> "Drand":
        """Fresh daemon: keypair only, waiting for a DKG."""
        if pair is None:
            store = (
                MemStore() if cfg.in_memory
                else FileStore(os.path.expanduser(cfg.base_folder))
            )
            pair = store.load_key_pair()
        d = cls(cfg, pair)
        await d._start_listeners()
        return d

    @classmethod
    async def load(cls, cfg: Config,
                   pair: Optional[Pair] = None) -> "Drand":
        """Existing daemon: restore group/share/distkey and catch up
        (reference LoadDrand core/drand.go:114 + daemon.go:42)."""
        d = await cls.new(cfg, pair)
        d.group = d.key_store.load_group()
        d.share = d.key_store.load_share()
        d.dist = d.key_store.load_dist_public()
        await d.start_beacon(catchup=True)
        return d

    async def _start_listeners(self) -> None:
        tls = None
        if not self.cfg.insecure:
            if not (self.cfg.tls_cert and self.cfg.tls_key):
                raise ValueError("TLS requires tls_cert and tls_key")
            tls = (self.cfg.tls_cert, self.cfg.tls_key)
        if self.cfg.mux_port is not None:
            # single-port mode: gRPC + REST on loopback, spliced behind
            # one public port; TLS terminates at the mux (reference
            # net/listener_grpc.go:108 NewTLSGrpcListener)
            from drand_tpu.net.mux import start_mux
            from drand_tpu.net.rest import build_rest_app, start_rest

            # the mux replaces the listen_addr listener, so the port
            # peers dial (the one in the group TOML) must be the mux's —
            # a silent mismatch would refuse every inbound DKG/beacon RPC
            adv = self.cfg.listen_addr.rsplit(":", 1)
            if len(adv) == 2 and adv[1] not in ("0", str(self.cfg.mux_port)):
                raise ValueError(
                    f"mux_port {self.cfg.mux_port} differs from the "
                    f"advertised port in listen_addr {self.cfg.listen_addr}"
                )

            server, gport = build_public_server(
                self, "127.0.0.1:0", tls=None
            )
            await server.start()
            self._servers.append(server)
            runner, rport = await start_rest(
                build_rest_app(self), 0, host="127.0.0.1"
            )
            self._servers.append(runner)
            host = self.cfg.listen_addr.rsplit(":", 1)[0] or "0.0.0.0"
            ssl_ctx = (self._server_ssl_context(*tls)
                       if tls is not None else None)
            mux = await start_mux(self.cfg.mux_port, gport, rport,
                                  host=host, ssl_context=ssl_ctx)
            self._servers.append(mux)
        else:
            server, _ = build_public_server(
                self, self.cfg.listen_addr, tls=tls
            )
            await server.start()
            self._servers.append(server)
        control = build_control_server(self, self.cfg.control_port)
        await control.start()
        self._servers.append(control)
        if self.cfg.rest_port is not None:
            from drand_tpu.net.rest import build_rest_app, start_rest

            ssl_ctx = None
            if tls is not None:
                ssl_ctx = self._server_ssl_context(*tls)
            runner, _ = await start_rest(
                build_rest_app(self), self.cfg.rest_port,
                host=self.cfg.rest_host, ssl_context=ssl_ctx,
            )
            self._servers.append(runner)

    def _server_ssl_context(self, cert_pem: bytes, key_pem: bytes):
        """ssl.SSLContext from PEM bytes (the ssl module only loads from
        files, so the material lands in the daemon folder, 0600)."""
        import ssl
        import tempfile

        ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
        if self.cfg.in_memory:
            tmpdir = tempfile.mkdtemp(prefix="drand-tls-")
            base = tmpdir
        else:
            tmpdir = None
            base = os.path.expanduser(self.cfg.base_folder)
        cpath = os.path.join(base, ".rest-cert.pem")
        kpath = os.path.join(base, ".rest-key.pem")
        for path, blob in ((cpath, cert_pem), (kpath, key_pem)):
            fd = os.open(path, os.O_WRONLY | os.O_CREAT | os.O_TRUNC,
                         0o600)
            with os.fdopen(fd, "wb") as fh:
                fh.write(blob)
        try:
            ctx.load_cert_chain(cpath, kpath)
        finally:
            if tmpdir is not None:
                # in-memory daemons must not leave key material on disk
                import shutil

                shutil.rmtree(tmpdir, ignore_errors=True)
        return ctx

    async def verify_gateway(self):
        """The lazily-started verification gateway (serve/).  Raises
        RuntimeError until the node knows the distributed key — there is
        nothing to verify against before the DKG finishes."""
        if self._verify_gateway is None:
            dist = self.dist
            if dist is None:
                try:
                    dist = self.key_store.load_dist_public()
                except Exception:
                    dist = None
            if dist is None:
                raise RuntimeError(
                    "no distributed key yet (run the DKG first)"
                )
            from drand_tpu.serve import VerifyGateway

            self._verify_gateway = VerifyGateway(
                dist.key(), self.scheme,
                max_batch=self.cfg.verify_max_batch,
                max_wait=self.cfg.verify_max_wait,
                max_queue=self.cfg.verify_max_queue,
                cache_size=self.cfg.verify_cache_size,
            )
            await self._verify_gateway.start()
        return self._verify_gateway

    def status_json(self) -> dict:
        """The /v1/status health document (obs/introspect.py)."""
        from drand_tpu.obs.introspect import daemon_status

        return daemon_status(self)

    def slo_json(self) -> dict:
        """The /v1/slo document, evaluated against the daemon's clock
        (injectable, so FakeClock tests cross breach boundaries)."""
        from drand_tpu.obs import slo

        return slo.ENGINE.snapshot(now=self.clock.now())

    def _dump_flight(self) -> None:
        """Best-effort flight-recorder dump into the daemon folder, so a
        crash or SIGTERM leaves post-mortem evidence next to the keys.
        The filename carries this node's identity: several in-process
        daemons (integration tests, the simulator) stopping at once must
        not overwrite each other's dump."""
        if self.cfg.in_memory:
            return
        from drand_tpu.obs import flight

        try:
            base = os.path.expanduser(self.cfg.base_folder)
            flight.RECORDER.dump_to(os.path.join(
                base, flight.dump_filename(self.pair.public.address)
            ))
        except Exception as exc:
            log.debug("flight dump failed", err=exc)

    def _spawn(self, coro) -> asyncio.Task:
        """create_task with retention: the task set keeps the handle
        alive and stop() can cancel whatever is still in flight."""
        task = asyncio.get_event_loop().create_task(coro)
        self._bg_tasks.add(task)
        task.add_done_callback(self._bg_tasks.discard)
        return task

    async def stop(self) -> None:
        # in-flight ingests race the teardown below (they reach into the
        # beacon handler and chain store); stop() itself may be a _spawn'd
        # task when shutdown came from a signal, so skip the current one
        cur = asyncio.current_task()
        for t in list(self._bg_tasks):
            if t is not cur:
                t.cancel()
        self._dump_flight()
        if self.beacon is not None:
            await self.beacon.stop()
        if self._verify_gateway is not None:
            await self._verify_gateway.close()
            self._verify_gateway = None
        for s in self._servers:
            if hasattr(s, "stop"):
                await s.stop(grace=0.1)
            else:  # aiohttp runner
                await s.cleanup()
        await self._client.close()
        # release the chain store LAST — only after the servers are down
        # can no in-flight RPC reach it (the native backend would pass a
        # NULL handle into C); closing it at all matters because the
        # native backend holds the single-writer flock until closed, so
        # a same-process restart (Drand.load) would otherwise be locked
        # out
        self.beacon = None
        if self._beacon_store is not None:
            self._beacon_store.close()
            self._beacon_store = None
        self._exit.set()

    def request_shutdown(self) -> None:
        self._spawn(self.stop())

    async def wait_exit(self) -> None:
        await self._exit.wait()

    # ------------------------------------------------------------ DKG (ctrl)

    def _check_group(self, group: Group) -> None:
        if len(group) < MIN_GROUP_SIZE:
            raise ValueError(
                f"group too small: {len(group)} < {MIN_GROUP_SIZE}"
            )
        if not group.contains(self.pair.public):
            raise ValueError("this node is not in the group")

    async def init_dkg(self, group_toml: str, is_leader: bool,
                       timeout: Optional[float] = None,
                       entropy: Optional[bytes] = None) -> str:
        """Control-plane fresh DKG (reference InitDKG
        core/drand_control.go:27-85)."""
        from drand_tpu.utils import tomlcompat as tomllib

        group = Group.from_dict(tomllib.loads(group_toml))
        self._check_group(group)
        if group.genesis_time <= self.clock.now():
            raise ValueError("genesis time must be in the future")
        self._dkg_group = group
        self._client.dkg_context = (False, group.hash())
        cfg = DKGConfig(
            pair=self.pair,
            new_group=group,
            timeout=timeout or self.cfg.dkg_timeout,
            clock=self.clock,
            entropy=entropy,
        )
        self.dkg = DKGHandler(cfg, self._client)
        if is_leader:
            await self.dkg.start()
        else:
            self.dkg._arm_timer()
        share = await self.dkg.wait_share()
        return await self._finish_dkg(group, share)

    async def _finish_dkg(self, group: Group,
                          share: Optional[Share]) -> str:
        """Persist DKG output and start the beacon (reference WaitDKG
        core/drand.go:150-188)."""
        self.dkg = None
        self._dkg_group = None
        if share is None:
            # old-only node in a reshare: retire at the transition round
            return ""
        self.group = group
        self.share = share
        self.dist = share.public()
        self.key_store.save_group(group)
        self.key_store.save_share(share)
        self.key_store.save_dist_public(self.dist)
        # a slow DKG can outlive the genesis window (small hosts, many
        # daemons); join via catch-up instead of refusing to start
        late = self.clock.now() > group.genesis_time + group.period
        await self.start_beacon(catchup=late)
        return ref.g1_to_bytes(self.dist.key()).hex()

    async def init_reshare(self, new_group_toml: str, is_leader: bool,
                           old_group_toml: Optional[str] = None,
                           timeout: Optional[float] = None,
                           entropy: Optional[bytes] = None) -> str:
        """Control-plane resharing (reference InitReshare
        core/drand_control.go:91-205): same collective key and chain, new
        membership/threshold, beacon handover at the transition round."""
        from drand_tpu.utils import tomlcompat as tomllib

        if old_group_toml:
            old_group = Group.from_dict(tomllib.loads(old_group_toml))
        else:
            old_group = self.group or self.key_store.load_group()
        if old_group is None:
            raise ValueError("no old group for resharing")
        new_group = Group.from_dict(tomllib.loads(new_group_toml))
        if len(new_group) < MIN_GROUP_SIZE:
            raise ValueError("new group too small")
        # chain continuity requirements (reference :111-151)
        if new_group.genesis_time != old_group.genesis_time:
            raise ValueError("genesis time must be preserved")
        if new_group.period != old_group.period:
            raise ValueError("period change during resharing not supported")
        new_group.genesis_seed = old_group.get_genesis_seed()
        if new_group.transition_time <= self.clock.now():
            raise ValueError("transition time must be in the future")

        in_old = old_group.contains(self.pair.public)
        in_new = new_group.contains(self.pair.public)
        if not in_old and not in_new:
            raise ValueError("node is in neither old nor new group")
        old_share = self.share if in_old else None

        self._dkg_group = new_group
        self._client.dkg_context = (True, new_group.hash())
        cfg = DKGConfig(
            pair=self.pair,
            new_group=new_group,
            old_group=old_group,
            old_share=old_share,
            timeout=timeout or self.cfg.dkg_timeout,
            clock=self.clock,
            entropy=entropy,
        )
        self.dkg = DKGHandler(cfg, self._client)
        if is_leader:
            await self.dkg.start()
        else:
            self.dkg._arm_timer()
        share = await self.dkg.wait_share()
        return await self._finish_reshare(
            old_group, new_group, share, in_new
        )

    async def _finish_reshare(self, old_group: Group, new_group: Group,
                              share: Optional[Share],
                              in_new: bool) -> str:
        """Beacon handover (reference transition core/drand.go:234-289)."""
        self.dkg = None
        self._dkg_group = None
        transition_round = current_round(
            new_group.transition_time + 0.001,
            new_group.period, new_group.genesis_time,
        )
        if not in_new:
            # retiring node: stop producing just before the transition
            if self.beacon is not None:
                self.beacon.stop_at(transition_round - 1)
            return ""
        assert share is not None
        old_beacon = self.beacon
        self.group = new_group
        self.share = share
        self.dist = share.public()
        self.key_store.save_group(new_group)
        self.key_store.save_share(share)
        self.key_store.save_dist_public(self.dist)
        if old_beacon is not None:
            # existing member: same store, swap handler at transition
            await old_beacon.stop()
            await self.start_beacon(catchup=False, transition=True,
                                    sync_peers=old_group.nodes)
        else:
            # brand-new member: sync the old chain then join
            await self.start_beacon(catchup=False, transition=True,
                                    sync_peers=old_group.nodes)
        return ref.g1_to_bytes(self.dist.key()).hex()

    # --------------------------------------------------------------- beacon

    def _beacon_store_path(self) -> str:
        if self.cfg.in_memory:
            return ":memory:"
        base = Path(os.path.expanduser(self.cfg.base_folder)) / "db"
        base.mkdir(parents=True, exist_ok=True)
        return str(base / "beacon.db")

    async def start_beacon(self, catchup: bool,
                           transition: bool = False,
                           sync_peers: Optional[List[Identity]] = None
                           ) -> None:
        assert self.group is not None and self.share is not None
        public = self._self_identity()
        bcfg = BeaconConfig(
            group=self.group,
            public=public,
            share=self.share,
            scheme=self.scheme,
            clock=self.clock,
            partial_verify=self.cfg.partial_verify,
        )
        # the chain store survives handler swaps (resharing must keep the
        # already-produced chain, especially for in-memory stores)
        if self._beacon_store is None:
            self._beacon_store = open_store(self._beacon_store_path())
        self.beacon = BeaconHandler(bcfg, self._beacon_store, self._client)
        self.beacon.add_callback(self._fanout_beacon)
        if transition:
            await self.beacon.transition_with_peers(
                sync_peers or self.group.nodes
            )
        elif catchup:
            await self.beacon.catchup()
        else:
            await self.beacon.start()

    def _self_identity(self) -> Identity:
        """Our identity as listed in the group (the group's Key/addr is
        canonical; ports may differ from the bind address)."""
        assert self.group is not None
        idx = self.group.index(self.pair.public)
        if idx is None:
            for i, n in enumerate(self.group.nodes):
                if n.key == self.pair.public.key:
                    return n
            raise ValueError("node missing from group")
        return self.group.nodes[idx]

    def _fanout_beacon(self, b: Beacon) -> None:
        for q in list(self._subscribers):
            try:
                q.put_nowait(b)
            except asyncio.QueueFull:
                pass

    # --------------------------------------- service facade (net/transport)

    def fetch_public_rand(self, round: int) -> Beacon:
        if self.beacon is None:
            raise KeyError("beacon not running")
        b = (self.beacon.store.last() if round == 0
             else self.beacon.store.get(round))
        if b is None:
            raise KeyError(f"no beacon for round {round}")
        if round == 0 and b.round == 0:
            # the genesis beacon's "signature" is the chain seed, not a
            # BLS signature — serving it as "latest randomness" hands a
            # verifying client bytes that can never verify
            raise KeyError("no signed beacon yet (chain at genesis)")
        return b

    def subscribe_beacons(self) -> asyncio.Queue:
        q: asyncio.Queue = asyncio.Queue(maxsize=64)
        self._subscribers.add(q)
        return q

    def unsubscribe_beacons(self, q: asyncio.Queue) -> None:
        self._subscribers.discard(q)

    def serve_private_rand(self, blob: bytes) -> bytes:
        """ECIES round-trip: decrypt the requester's ephemeral public key,
        reply with 32 fresh random bytes encrypted to it (reference
        core/drand_public.go:132-157)."""
        plain = ecies.decrypt(self.pair.private, blob)
        eph_pub = ref.g1_from_bytes(plain)
        if eph_pub is None:
            raise ValueError("identity ephemeral key")
        return ecies.encrypt(eph_pub, self.cfg.entropy_fn(32))

    def group_toml(self) -> Optional[str]:
        g = self.group or self._dkg_group
        if g is None:
            try:
                g = self.key_store.load_group()
            except KeyNotFound:
                return None
        return toml_dumps(g.to_dict())

    def home_status(self) -> str:
        state = "running" if self.beacon is not None else "waiting for DKG"
        return f"drand_tpu node {self.pair.public.address} ({state})"

    async def process_beacon_packet(self, packet: BeaconPacket) -> None:
        """Inbound partial: cheap window check inline, then ACK and verify
        asynchronously.  Partial verification is ~pairing-level work; doing
        it inside the RPC would blow the sender's deadline whenever several
        partials land at once (the reference leans on goroutines here —
        beacon.go:124 runs inside the per-RPC goroutine)."""
        if self.beacon is None:
            raise ValueError("beacon not running")
        self.beacon.check_packet_window(packet)

        async def _ingest():
            try:
                await self.beacon.process_beacon(packet)
            except Exception as exc:
                log.debug("dropping partial", frm=packet.from_address,
                          err=exc)

        self._spawn(_ingest())

    def serve_sync_chain(self, from_round: int) -> List[Beacon]:
        if self.beacon is None:
            return []
        return self.beacon.sync_chain_from(from_round)

    async def process_dkg_packet(self, payload: dict, reshare: bool,
                                 group_hash: bytes) -> None:
        """Inbound Setup/Reshare packet.  The group-hash gate mirrors
        core/drand_public.go:41-43; a first packet reaching a node whose
        operator already ran init_dkg/init_reshare triggers its dealing
        (the reference's :45-49 behavior lives in DKGHandler.process)."""
        if self.dkg is None:
            raise ValueError("no DKG in progress on this node")
        expected = self._dkg_group.hash() if self._dkg_group else b""
        if group_hash and expected and group_hash != expected:
            raise ValueError("group hash mismatch")
        await self.dkg.process(payload)

    # ------------------------------------------------------- control facade

    def share_info(self):
        share = self.share or self.key_store.load_share()
        return share.share.index, share.share.value.to_bytes(32, "big").hex()

    def public_key_hex(self) -> str:
        return self.pair.public.key_hex

    def private_key_hex(self) -> str:
        return self.pair.private.to_bytes(32, "big").hex()

    def collective_key_hex(self) -> List[str]:
        dist = self.dist or self.key_store.load_dist_public()
        return [ref.g1_to_bytes(c).hex() for c in dist.coefficients]
