"""Verifying client library.

Mirrors /root/reference/core/client_public.go: fetch public randomness
(latest or by round) over gRPC, verify the threshold-BLS signature against
the distributed key and check randomness == SHA-256(signature) (:107-127);
ECIES private-randomness round trip (:78-94).
"""

from __future__ import annotations

from typing import Optional

from drand_tpu.beacon.chain import Beacon, beacon_message, randomness
from drand_tpu.crypto import ecies
from drand_tpu.crypto import refimpl as ref
from drand_tpu.crypto import tbls
from drand_tpu.crypto.poly import rand_scalar
from drand_tpu.key import Identity
from drand_tpu.net import CertManager, GrpcClient


class VerificationError(Exception):
    """The fetched randomness failed cryptographic verification."""


class FetchError(Exception):
    """Transport-level failure (unreachable node, missing round, …) —
    retryable, unlike VerificationError."""


class DrandClient:
    """Client that refuses to return unverified randomness."""

    def __init__(self, dist_key, scheme: Optional[tbls.Scheme] = None,
                 certs: Optional[CertManager] = None):
        self.dist_key = dist_key          # collective G1 public key
        self.scheme = scheme or tbls.default_scheme()
        self._net = GrpcClient(certs)

    async def close(self):
        await self._net.close()

    def _verify(self, resp) -> Beacon:
        b = Beacon(
            round=resp.round,
            prev_round=resp.previous_round,
            prev_sig=resp.previous_signature,
            signature=resp.signature,
        )
        msg = beacon_message(b.prev_sig, b.prev_round, b.round)
        try:
            self.scheme.verify_recovered(self.dist_key, msg, b.signature)
        except tbls.ThresholdError as exc:
            raise VerificationError(str(exc)) from exc
        if resp.randomness and resp.randomness != randomness(b.signature):
            raise VerificationError("randomness != SHA-256(signature)")
        return b

    async def last_public(self, peer: Identity) -> Beacon:
        return self._verify(await self._net.public_rand(peer, 0))

    async def public(self, peer: Identity, round: int) -> Beacon:
        b = self._verify(await self._net.public_rand(peer, round))
        # a validly-signed but *older* beacon must not satisfy a
        # specific-round request (a misbehaving node could replay one)
        if round != 0 and b.round != round:
            raise VerificationError(
                f"node answered round {b.round} for requested {round}"
            )
        return b

    async def private(self, peer: Identity) -> bytes:
        """Private randomness: send an ECIES-wrapped ephemeral key, get
        32 bytes encrypted back to it."""
        eph = rand_scalar()
        eph_pub = ref.g1_mul(ref.G1_GEN, eph)
        request = ecies.encrypt(peer.key, ref.g1_to_bytes(eph_pub))
        blob = await self._net.private_rand(peer, request)
        out = ecies.decrypt(eph, blob)
        if len(out) != 32:
            raise VerificationError("expected 32 bytes of randomness")
        return out

    async def group(self, peer: Identity) -> str:
        return await self._net.group(peer)

    # -- remote verification (serve/ gateway on the peer) ------------------

    async def verify_remote(self, peer: Identity, b: Beacon,
                            timeout: Optional[float] = None) -> bool:
        """Offload one chain-link verification to the peer's batching
        gateway (VerifyBeacon RPC).  Trust model is the opposite of
        `public()`: the PEER's TPU does the pairing, so only use it
        against nodes you already trust or for load-shedding hints.
        Raises FetchError on shed/timeout (the peer rejects explicitly
        rather than serving late)."""
        import grpc

        try:
            resp = await self._net.verify_beacon(
                peer, round=b.round, prev_round=b.prev_round,
                prev_sig=b.prev_sig, signature=b.signature,
                timeout=timeout,
            )
        except grpc.aio.AioRpcError as exc:
            raise FetchError(
                f"VerifyBeacon: {exc.code().name}: {exc.details()}"
            ) from exc
        return resp.valid

    async def verify_remote_batch(self, peer: Identity, beacons,
                                  timeout: Optional[float] = None
                                  ) -> list:
        """Batch variant: list of Optional[bool] in order (None where
        the gateway shed that item)."""
        import grpc

        items = [
            {"round": b.round, "prev_round": b.prev_round,
             "prev_sig": b.prev_sig, "signature": b.signature}
            for b in beacons
        ]
        try:
            resp = await self._net.verify_beacon_batch(
                peer, items, timeout=timeout
            )
        except grpc.aio.AioRpcError as exc:
            raise FetchError(
                f"VerifyBeaconBatch: {exc.code().name}: {exc.details()}"
            ) from exc
        return [None if r.error else r.valid for r in resp]


class RestClient:
    """Verifying client over the JSON REST gateway.

    Mirrors /root/reference/net/client_rest.go (`restClient:20`,
    `PublicRand:45`): same hex-JSON surface, same refusal to return
    unverified randomness as the gRPC client."""

    def __init__(self, dist_key, base_url: str,
                 scheme: Optional[tbls.Scheme] = None, ssl=None):
        #: ssl.SSLContext trusting the node's cert (https base_url), or
        #: None for plain http / system roots
        self._ssl = ssl
        self.dist_key = dist_key
        self.base_url = base_url.rstrip("/")
        self.scheme = scheme or tbls.default_scheme()
        self._session = None

    async def _http(self):
        import aiohttp

        if self._session is None:
            self._session = aiohttp.ClientSession()
        return self._session

    async def close(self):
        if self._session is not None:
            await self._session.close()
            self._session = None

    def _verify_json(self, j: dict) -> Beacon:
        b = Beacon(
            round=int(j["round"]),
            prev_round=int(j.get("previous_round", 0)),
            prev_sig=bytes.fromhex(j.get("previous", "")),
            signature=bytes.fromhex(j["signature"]),
        )
        msg = beacon_message(b.prev_sig, b.prev_round, b.round)
        try:
            self.scheme.verify_recovered(self.dist_key, msg, b.signature)
        except tbls.ThresholdError as exc:
            raise VerificationError(str(exc)) from exc
        rnd = j.get("randomness")
        if rnd and bytes.fromhex(rnd) != randomness(b.signature):
            raise VerificationError("randomness != SHA-256(signature)")
        return b

    async def _get_json(self, path: str) -> dict:
        http = await self._http()
        async with http.get(f"{self.base_url}{path}",
                            ssl=self._ssl) as resp:
            if resp.status != 200:
                raise FetchError(f"GET {path}: HTTP {resp.status}")
            return await resp.json()

    async def last_public(self) -> Beacon:
        return self._verify_json(await self._get_json("/api/public"))

    async def public(self, round: int) -> Beacon:
        b = self._verify_json(
            await self._get_json(f"/api/public/{round}")
        )
        if round != 0 and b.round != round:
            raise VerificationError(
                f"node answered round {b.round} for requested {round}"
            )
        return b

    async def private(self, peer_key) -> bytes:
        """Private randomness over REST (POST /api/private)."""
        eph = rand_scalar()
        eph_pub = ref.g1_mul(ref.G1_GEN, eph)
        request = ecies.encrypt(peer_key, ref.g1_to_bytes(eph_pub))
        http = await self._http()
        async with http.post(
            f"{self.base_url}/api/private",
            json={"request": request.hex()},
            ssl=self._ssl,
        ) as resp:
            if resp.status != 200:
                raise FetchError(f"HTTP {resp.status}")
            j = await resp.json()
        out = ecies.decrypt(eph, bytes.fromhex(j["response"]))
        if len(out) != 32:
            raise VerificationError("expected 32 bytes of randomness")
        return out

    async def distkey(self) -> list:
        j = await self._get_json("/api/info/distkey")
        return j["coefficients"]

    # -- remote verification (POST /v1/verify) -----------------------------

    @staticmethod
    def _claim_json(b: Beacon) -> dict:
        return {"round": b.round, "previous_round": b.prev_round,
                "previous": b.prev_sig.hex(),
                "signature": b.signature.hex()}

    async def verify_remote(self, b: Beacon,
                            timeout: Optional[float] = None) -> bool:
        """Offload one verification to the node's batching gateway.
        429/504 (explicit shed) surface as FetchError — retryable."""
        body = self._claim_json(b)
        if timeout is not None:
            body["timeout"] = timeout
        http = await self._http()
        async with http.post(f"{self.base_url}/v1/verify", json=body,
                             ssl=self._ssl) as resp:
            if resp.status != 200:
                raise FetchError(
                    f"POST /v1/verify: HTTP {resp.status}: "
                    f"{await resp.text()}"
                )
            j = await resp.json()
        return bool(j["valid"])

    async def verify_remote_batch(self, beacons,
                                  timeout: Optional[float] = None
                                  ) -> list:
        """Batch variant: list of Optional[bool] in order (None where
        the gateway shed that item)."""
        body = {"items": [self._claim_json(b) for b in beacons]}
        if timeout is not None:
            body["timeout"] = timeout
        http = await self._http()
        async with http.post(f"{self.base_url}/v1/verify", json=body,
                             ssl=self._ssl) as resp:
            if resp.status != 200:
                raise FetchError(
                    f"POST /v1/verify: HTTP {resp.status}: "
                    f"{await resp.text()}"
                )
            j = await resp.json()
        return [
            None if "error" in item else bool(item["valid"])
            for item in j["items"]
        ]
