"""Daemon orchestration: ties keys, DKG, beacon, networking together.

Equivalent of the reference's `core/` package (/root/reference/core/):
the `Drand` daemon, its control-plane handlers, the verifying client
library, and configuration."""

from drand_tpu.core.daemon import Config, Drand  # noqa: F401
from drand_tpu.core.client import (  # noqa: F401
    DrandClient,
    RestClient,
)
