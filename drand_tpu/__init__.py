"""drand_tpu: a TPU-native distributed randomness beacon framework.

A ground-up rebuild of the capabilities of drand (threshold-BLS randomness
beacon, reference at /root/reference) with the BLS12-381 hot path — pairings,
partial-signature batch verification, Lagrange-interpolation MSM, chain
batch-verification — executed on TPU via JAX (jit/vmap/pjit, Pallas kernels),
and the protocol plane (DKG, beacon rounds, gRPC mesh, CLI) on the host.
"""

__version__ = "0.1.0"
