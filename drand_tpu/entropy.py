"""Entropy sources for key generation / DKG.

Mirrors /root/reference/entropy/entropy.go: `GetRandom` reads from a
user-supplied executable's stdout, falling back to the OS CSPRNG when the
script fails or returns short output (:15-30); `ScriptReader` wraps the
exec (:32-67).
"""

from __future__ import annotations

import os
import subprocess
from typing import Optional


def get_random(n: int, source: Optional[str] = None) -> bytes:
    """n random bytes from `source` (an executable path) or os.urandom."""
    if source:
        try:
            out = subprocess.run(
                [source], capture_output=True, timeout=10, check=True
            ).stdout
            if len(out) >= n:
                return out[:n]
        except (OSError, subprocess.SubprocessError):
            pass
    return os.urandom(n)


class ScriptReader:
    """Reader interface over a user executable (DKG user entropy)."""

    def __init__(self, path: str):
        self.path = path

    def read(self, n: int) -> bytes:
        return get_random(n, self.path)
