"""Threshold BLS signatures on BLS12-381 (keys in G1, signatures in G2).

This is the framework's equivalent of `tbls.NewThresholdSchemeOnG2` — the
`key.Scheme` the whole reference daemon is parameterized over
(/root/reference/key/curve.go:30, consumed at
/root/reference/beacon/beacon.go:148,154,433,488,494).  Two interchangeable
backends sit behind one interface:

* :class:`RefScheme` — pure-Python oracle arithmetic; correctness baseline
  and the low-latency single-op path for the protocol plane.
* :class:`JaxScheme` — batched TPU kernels (vmapped pairing product checks,
  MSM-based recovery); the throughput path for partial-signature floods and
  chain catch-up verification.

Wire formats match the reference's group files: 48-byte compressed G1
public keys, 96-byte compressed G2 signatures; a partial signature is a
2-byte big-endian signer index followed by the 96-byte signature.
"""

from __future__ import annotations

import hashlib
import threading
from collections import OrderedDict
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from drand_tpu.crypto import refimpl as ref
from drand_tpu.crypto.poly import (
    PriShare,
    PubPoly,
    lagrange_basis_at_zero,
)
# kernel_span wraps every device dispatch: same per-op
# drand_device_kernel_seconds histograms as before, plus trace spans
# (parented to the calling round/batch) and flight-recorder events
from drand_tpu.obs.kernels import kernel_span

INDEX_LEN = 2
SIG_LEN = 96


class ThresholdError(Exception):
    pass


def hash_to_sig_group(msg: bytes):
    """H(m) in G2 — the signature group (beacon messages land here)."""
    return ref.hash_to_g2(msg)


def _pack_partial(index: int, sig_point) -> bytes:
    return index.to_bytes(INDEX_LEN, "big") + ref.g2_to_bytes(sig_point)


def _unpack_partial(blob: bytes):
    if len(blob) != INDEX_LEN + SIG_LEN:
        raise ThresholdError(
            f"partial must be {INDEX_LEN + SIG_LEN} bytes, got {len(blob)}"
        )
    index = int.from_bytes(blob[:INDEX_LEN], "big")
    try:
        pt = ref.g2_from_bytes(blob[INDEX_LEN:])
    except ValueError as e:
        # malformed wire bytes (bad flags / not on curve / wrong subgroup)
        # are an invalid partial, not an internal error — keep the Scheme
        # contract: ThresholdError for anything a peer could send us
        raise ThresholdError(f"malformed partial: {e}") from None
    if pt is None:
        raise ThresholdError("identity signature rejected")
    return index, pt


class Scheme:
    """sign.ThresholdScheme equivalent (plus batch APIs)."""

    # -- single-op protocol-plane API ------------------------------------

    def partial_sign(self, share: PriShare, msg: bytes) -> bytes:
        raise NotImplementedError

    def index_of(self, partial: bytes) -> int:
        idx = int.from_bytes(partial[:INDEX_LEN], "big")
        return idx

    def verify_partial(self, pub: PubPoly, msg: bytes,
                       partial: bytes) -> None:
        """Raise ThresholdError if the partial is invalid."""
        raise NotImplementedError

    def check_partial_structure(self, partial: bytes) -> int:
        """Cheap structural admit gate for the optimistic ingest path:
        length, point decode (on curve, right subgroup) and the identity
        rejection — everything EXCEPT the pairing.  Returns the claimed
        signer index; raises ThresholdError on anything a peer could
        forge for free.  Zero device dispatches by contract — the test
        suite asserts it against `obs.kernels.counters()`."""
        idx, _ = _unpack_partial(partial)
        return idx

    def recover(self, pub: PubPoly, msg: bytes,
                partials: Sequence[bytes], t: int, n: int) -> bytes:
        raise NotImplementedError

    def verify_recovered(self, pub_key, msg: bytes, sig: bytes) -> None:
        raise NotImplementedError

    def finalize_round(self, pub: PubPoly, msg: bytes,
                       partials: Sequence[bytes], t: int, n: int) -> bytes:
        """One logical round finalize: recover the group signature from
        the partials and verify it against the committee key
        (`pub.commit()`).  Returns the signature bytes or raises
        ThresholdError — the single call the beacon round loop makes
        after the aggregation threshold is met.

        The base implementation composes `recover` + `verify_recovered`;
        `JaxScheme` overrides it with a fused device pipeline (batched
        partial check + MSM recovery + recovered-signature check in at
        most two dispatches).
        """
        sig = self.recover(pub, msg, partials, t, n)
        self.verify_recovered(pub.commit(), msg, sig)
        return sig

    def finalize_round_optimistic(self, pub: PubPoly, msg: bytes,
                                  partials: Sequence[bytes], t: int,
                                  n: int) -> bytes:
        """Optimistic round finalize: Lagrange-recover from the first t
        admitted partials and verify ONLY the recovered signature against
        the collective key — no per-partial pairing anywhere.  Partials
        here were admitted by `check_partial_structure` only, so a wrong
        share surfaces as a red recovered check (`ThresholdError`); the
        caller then runs `verify_partials_batch` over the same subset to
        identify and evict the liars (the blame fallback).

        BLS recovery from ANY t valid shares of the same message yields
        the one group signature, so a successful optimistic finalize is
        byte-identical to the eager `finalize_round` output.

        The base implementation composes `recover` + `verify_recovered`
        (Ref/Native: one MSM + one pairing); `JaxScheme` overrides it
        with the single fused MSM→affine→check dispatch.
        """
        sig = self.recover(pub, msg, partials, t, n)
        self.verify_recovered(pub.commit(), msg, sig)
        return sig

    def invalidate_round_caches(self) -> None:
        """Drop any cached per-round-message operands.  Called by the
        beacon handler after a chain reorg: messages derived from the
        orphaned branch (H(prev_sig||...) rows) can never be asked for
        again, so holding them only wastes cache slots.  Key-content
        caches are CORRECT either way (the adopted branch's messages
        simply miss); this is hygiene, not a safety requirement.
        Default: nothing cached, nothing to drop."""

    # -- batch throughput API (the TPU value-add) ------------------------

    def verify_partials_batch(self, pub: PubPoly, msg: bytes,
                              partials: Sequence[bytes]) -> List[bool]:
        raise NotImplementedError

    def verify_chain_batch(self, pub_key, msgs: Sequence[bytes],
                           sigs: Sequence[bytes]) -> List[bool]:
        """Verify many (message, signature) pairs under one public key."""
        raise NotImplementedError

    # -- shared helpers ---------------------------------------------------

    def _recover_indices(self, partials: Sequence[bytes], t: int):
        seen = {}
        for blob in partials:
            idx, pt = _unpack_partial(blob)
            if idx not in seen:
                seen[idx] = pt
        if len(seen) < t:
            raise ThresholdError(
                f"not enough distinct partials: {len(seen)} < {t}"
            )
        chosen = sorted(seen.items())[:t]
        return chosen


class RefScheme(Scheme):
    """Pure-Python oracle backend."""

    def partial_sign(self, share: PriShare, msg: bytes) -> bytes:
        h = hash_to_sig_group(msg)
        return _pack_partial(share.index, ref.g2_mul(h, share.value))

    def verify_partial(self, pub: PubPoly, msg: bytes,
                       partial: bytes) -> None:
        idx, sig_pt = _unpack_partial(partial)
        pk_i = pub.eval(idx)
        h = hash_to_sig_group(msg)
        lhs = ref.pairing(ref.G1_GEN, sig_pt)
        rhs = ref.pairing(pk_i, h)
        if lhs != rhs:
            raise ThresholdError(f"invalid partial signature from {idx}")

    def recover(self, pub: PubPoly, msg: bytes,
                partials: Sequence[bytes], t: int, n: int) -> bytes:
        chosen = self._recover_indices(partials, t)
        lam = lagrange_basis_at_zero([i for i, _ in chosen])
        acc = None
        for i, pt in chosen:
            acc = ref.g2_add(acc, ref.g2_mul(pt, lam[i]))
        return ref.g2_to_bytes(acc)

    def verify_recovered(self, pub_key, msg: bytes, sig: bytes) -> None:
        try:
            sig_pt = ref.g2_from_bytes(sig)
        except ValueError as e:
            raise ThresholdError(f"malformed signature: {e}") from None
        if sig_pt is None:
            raise ThresholdError("identity signature rejected")
        h = hash_to_sig_group(msg)
        if ref.pairing(pub_key, h) != ref.pairing(ref.G1_GEN, sig_pt):
            raise ThresholdError("invalid recovered signature")

    def verify_partials_batch(self, pub, msg, partials):
        out = []
        for blob in partials:
            try:
                self.verify_partial(pub, msg, blob)
                out.append(True)
            except (ThresholdError, ValueError):
                out.append(False)
        return out

    def verify_chain_batch(self, pub_key, msgs, sigs):
        out = []
        for msg, sig in zip(msgs, sigs):
            try:
                self.verify_recovered(pub_key, msg, sig)
                out.append(True)
            except (ThresholdError, ValueError):
                out.append(False)
        return out


class NativeScheme(Scheme):
    """C++ host backend (native/bls.cc via crypto/native_bls.py).

    The no-accelerator fast path SURVEY §2 mandates: the reference daemon
    runs native crypto everywhere (/root/reference/key/curve.go:12); a
    CPU-only drand_tpu node uses this backend so one partial verify costs
    ~10 ms, not the pure-Python oracle's 10-30 s.  All points cross the
    boundary in the wire encodings the protocol already uses, and the
    semantics are byte-identical to RefScheme (tests/test_native_bls.py).
    """

    def __init__(self):
        from drand_tpu.crypto import native_bls as nb

        if not nb.available():
            from drand_tpu import native

            raise RuntimeError(
                f"native BLS backend unavailable: {native.build_error()}"
            )
        self._nb = nb

    # -- helpers ----------------------------------------------------------

    _IDENT96 = bytes([0xC0]) + bytes(95)

    def _pub_commits(self, pub: PubPoly) -> List[bytes]:
        """Serialized commitment points, validated once per PubPoly."""
        cached = getattr(pub, "_nb_commits", None)
        if cached is not None:
            return cached
        blobs = [ref.g1_to_bytes(c) for c in pub.commits]
        for b in blobs:
            if self._nb.g1_check(b) != 0:
                raise ThresholdError("invalid commitment point")
        pub._nb_commits = blobs
        return blobs

    def _eval_pub(self, pub: PubPoly, index: int) -> bytes:
        """base^{f(index+1)} as 48 bytes via native G1 MSM (Horner weights
        x^j are cheap host scalars; commits validated by _pub_commits).

        Results are memoized per PubPoly: a daemon verifies the same
        committee's partials every round, and the degree-t MSM per signer
        — not the pairing — dominated the flood without the cache."""
        cache = getattr(pub, "_nb_eval_cache", None)
        if cache is None:
            cache = pub._nb_eval_cache = {}
        hit = cache.get(index)
        if hit is not None:
            return hit
        blobs = self._pub_commits(pub)
        x = index + 1
        scalars, acc = [], 1
        for _ in blobs:
            scalars.append(acc)
            acc = acc * x % ref.R
        out = self._nb.g1_msm(blobs, scalars, check=False)
        cache[index] = out
        return out

    def _sig_bytes(self, sig) -> bytes:
        if isinstance(sig, (bytes, bytearray)):
            return bytes(sig)
        return ref.g2_to_bytes(sig)

    # -- single-op protocol-plane API -------------------------------------

    def partial_sign(self, share: PriShare, msg: bytes) -> bytes:
        with kernel_span("g2_sign", backend="native", batch=1):
            sig = self._nb.sign(msg, share.value)
        return share.index.to_bytes(INDEX_LEN, "big") + sig

    def check_partial_structure(self, partial: bytes) -> int:
        # bytes-level C++ subgroup check instead of the base class's
        # pure-Python point decode: same acceptance set, microseconds
        if len(partial) != INDEX_LEN + SIG_LEN:
            raise ThresholdError(
                f"partial must be {INDEX_LEN + SIG_LEN} bytes, "
                f"got {len(partial)}"
            )
        idx = int.from_bytes(partial[:INDEX_LEN], "big")
        sig = partial[INDEX_LEN:]
        if sig == self._IDENT96:
            raise ThresholdError("identity signature rejected")
        if self._nb.g2_check(sig) != 0:
            raise ThresholdError("malformed partial: bad G2 point")
        return idx

    def verify_partial(self, pub: PubPoly, msg: bytes,
                       partial: bytes) -> None:
        if len(partial) != INDEX_LEN + SIG_LEN:
            raise ThresholdError(
                f"partial must be {INDEX_LEN + SIG_LEN} bytes, "
                f"got {len(partial)}"
            )
        idx = int.from_bytes(partial[:INDEX_LEN], "big")
        sig = partial[INDEX_LEN:]
        if sig == self._IDENT96:
            raise ThresholdError("identity signature rejected")
        pk_i = self._eval_pub(pub, idx)
        with kernel_span("pairing_check", backend="native", batch=1):
            rc = self._nb.verify(pk_i, msg, sig)
        if rc != 1:
            raise ThresholdError(f"invalid partial signature from {idx}")

    def recover(self, pub: PubPoly, msg: bytes,
                partials: Sequence[bytes], t: int, n: int) -> bytes:
        seen = {}
        for blob in partials:
            if len(blob) != INDEX_LEN + SIG_LEN:
                raise ThresholdError(
                    f"partial must be {INDEX_LEN + SIG_LEN} bytes, "
                    f"got {len(blob)}"
                )
            idx = int.from_bytes(blob[:INDEX_LEN], "big")
            sig = blob[INDEX_LEN:]
            if sig == self._IDENT96 or self._nb.g2_check(sig) != 0:
                raise ThresholdError("identity signature rejected")
            if idx not in seen:
                seen[idx] = sig
        if len(seen) < t:
            raise ThresholdError(
                f"not enough distinct partials: {len(seen)} < {t}"
            )
        chosen = sorted(seen.items())[:t]
        lam = lagrange_basis_at_zero([i for i, _ in chosen])
        with kernel_span("msm_recover", backend="native",
                         batch=len(chosen)):
            return self._nb.g2_msm(
                [sig for _, sig in chosen],
                [lam[i] for i, _ in chosen],
                check=False,  # validated above
            )

    def verify_recovered(self, pub_key, msg: bytes, sig: bytes) -> None:
        sb = self._sig_bytes(sig)
        if sb == self._IDENT96:
            raise ThresholdError("identity signature rejected")
        pk = ref.g1_to_bytes(pub_key)
        with kernel_span("pairing_check", backend="native", batch=1):
            rc = self._nb.verify(pk, msg, sb)
        if rc != 1:
            raise ThresholdError("invalid recovered signature")

    # -- batch API (sequential native ops; still ~1000x the oracle) -------

    def verify_partials_batch(self, pub: PubPoly, msg: bytes,
                              partials: Sequence[bytes]) -> List[bool]:
        hm = self._nb.hash_to_g2(msg)  # hash once for the whole flood
        out = []
        with kernel_span("pairing_check", backend="native",
                         batch=len(partials)):
            for blob in partials:
                if len(blob) != INDEX_LEN + SIG_LEN:
                    out.append(False)
                    continue
                idx = int.from_bytes(blob[:INDEX_LEN], "big")
                sig = blob[INDEX_LEN:]
                if sig == self._IDENT96:
                    out.append(False)
                    continue
                try:
                    pk_i = self._eval_pub(pub, idx)
                except (ThresholdError, ValueError):
                    out.append(False)
                    continue
                out.append(self._nb.verify_pre(pk_i, hm, sig) == 1)
        return out

    def verify_chain_batch(self, pub_key, msgs, sigs):
        pk = ref.g1_to_bytes(pub_key)
        out = []
        with kernel_span("pairing_check", backend="native",
                         batch=len(msgs)):
            for msg, sig in zip(msgs, sigs):
                try:
                    sb = self._sig_bytes(sig)
                except (ThresholdError, ValueError):
                    out.append(False)
                    continue
                if sb == self._IDENT96:
                    out.append(False)
                    continue
                out.append(self._nb.verify(pk, msg, sb) == 1)
        return out


class _CommitteePlan:
    """Device-resident operand plan for ONE committee (one `PubPoly`).

    Everything the per-round hot path needs that depends only on the
    committee — not on the round — lives here, encoded once: the −G row
    and the collective-key row every pairing check broadcasts, the
    per-signer `pk_i` rows (host polynomial evaluation + Montgomery limb
    encoding both paid once per signer, ever), and the stacked row
    batches keyed by the exact signer layout so a steady-state round
    re-encodes NOTHING.

    The plan hangs off the `PubPoly` itself (``pub._jax_plan``, the same
    idiom as NativeScheme's ``pub._nb_eval_cache``): a reshare hands the
    daemon a fresh `PubPoly`, so the old committee's operands are
    invalidated by object lifetime, never by explicit flushing.
    """

    MAX_STACKS = 32  # distinct signer layouts kept (FIFO evicted)

    __slots__ = ("neg_g_row", "pk_row", "pk_rows", "stacks", "lock",
                 "encode_calls", "host_evals", "stack_hits")

    def __init__(self):
        self.neg_g_row = None          # encoded −G          (2, NLIMB)
        self.pk_row = None             # encoded pub.commit() (2, NLIMB)
        self.pk_rows: Dict[int, object] = {}   # signer idx -> (2, NLIMB)
        self.stacks: "OrderedDict[Tuple[int, ...], object]" = OrderedDict()
        self.lock = threading.Lock()
        # bookkeeping the plan-cache tests assert on: a warm round must
        # add zero to encode_calls/host_evals and only bump stack_hits
        self.encode_calls = 0
        self.host_evals = 0
        self.stack_hits = 0


class JaxScheme(Scheme):
    """TPU backend: batched pairing checks and MSM recovery.

    Boundary convention: points cross the host/device seam as oracle
    affine tuples and come back the same way — the device kernels are the
    batch oracle behind the reference's plugin boundary, exactly where
    `key.Pairing` sat (/root/reference/key/curve.go:12).

    Round hot-path plan: committee operands are cached device-side per
    `PubPoly` (:class:`_CommitteePlan`), the round message hash H(m) is
    computed once and shared by sign / partial verify / finalize
    (``_msg_q2``), and `finalize_round` fuses verify→recover→re-verify
    into at most two device dispatches.
    """

    def __init__(self):
        # deferred heavy imports so pure-protocol users never pay for jax
        import os

        import jax
        import jax.numpy as jnp

        from drand_tpu import ops as ops_pkg
        from drand_tpu.ops import curve, fp, h2c, msm, pairing, tower  # noqa

        # honor DRAND_TPU_COMPILE_CACHE even when it was set after the
        # ops package was first imported (cli.py --compile-cache path)
        ops_pkg.configure_compile_cache()

        self._curve, self._msm, self._pairing = curve, msm, pairing
        self._h2c = h2c
        self._jnp = jnp
        self._tower = tower
        self._nlimb = fp.NLIMB
        self._one2 = tower.fp2_encode((1, 0))  # projective Z constant
        #: per-round-message H(m) cache: msg -> affine (1, 2, 2, L) on
        #: device.  sign, partial verify, finalize and verify_recovered
        #: all consume the same round message, so the hash is computed
        #: once per round instead of once per call site.
        self._msg_cache: "OrderedDict[bytes, object]" = OrderedDict()
        self._msg_lock = threading.Lock()
        self._msg_hits = 0
        self._MSG_CACHE_MAX = 16
        #: chain-verify operand rows keyed by collective key (−G row,
        #: pk row) — catch-up re-verifies thousands of rounds under one
        #: key; encode its operands once
        self._chain_ops: "OrderedDict[object, tuple]" = OrderedDict()
        #: fused finalize program (MSM -> affine -> pairing check),
        #: built lazily on the first finalize
        self._finalize_jit = None
        # multi-chip catch-up routing: batches >= DRAND_TPU_SHARD_MIN
        # padded rows go through parallel/shard.sharded_pairing_check
        # when a mesh with >1 device exists (DRAND_TPU_SHARD=off kills)
        self._shard_min = int(os.environ.get("DRAND_TPU_SHARD_MIN", "256"))
        self._shard_enabled = os.environ.get(
            "DRAND_TPU_SHARD", "auto") != "off"
        self._mesh = None
        self._sharded_check = None
        # pairing backend: the Pallas mega-kernel on real accelerators,
        # the op-graph path on CPU (Pallas-TPU doesn't lower there).
        # Override with DRAND_TPU_PAIRING=opgraph|pallas.
        choice = os.environ.get("DRAND_TPU_PAIRING", "auto")
        # auto: Mosaic kernels lower on TPU targets only — never pick
        # them for GPU/CPU backends
        backend = jax.default_backend().lower()
        is_tpu = "tpu" in backend or backend == "axon"
        use_pallas = (choice == "pallas") or (
            choice == "auto" and is_tpu
        )
        if use_pallas:
            from drand_tpu.ops import pallas_h2c, pallas_pairing

            self._check = pallas_pairing.pairing_product_check
            # end-to-end kernel: H(m) computed in-kernel, straight into
            # the Miller loops (one device op per verified batch)
            self._check_hashed = pallas_h2c.pairing_product_check_hashed
            self._hash_pallas = pallas_h2c.hash_to_g2
        else:
            self._check = pairing.pairing_product_check
            self._check_hashed = None
            self._hash_pallas = None

    # -- encode helpers ---------------------------------------------------

    def _bucket(self, n: int) -> int:
        """Round a batch size up so XLA compiles the pairing pipeline for
        few distinct shapes, not one per size.

        Pallas backend: multiples of the kernel block (128) — every
        batch <= 128 shares ONE compiled shape (a fresh Mosaic compile
        costs tens of minutes on small hosts, so shape reuse matters
        more than padded work; the kernel pads to the block anyway).
        Op-graph backend: powers of two (min 8) — padded lanes cost real
        FLOPs there, so tighter buckets win."""
        if self._check_hashed is not None:
            return ((n + 127) // 128) * 128
        b = 8
        while b < n:
            b *= 2
        return b

    def _hash_msgs(self, msgs):
        """Batched device H(m), affine (B, 2, 2, L) — the Pallas kernel
        when available (it pads any batch <= its block into ONE compile
        shape; the op-graph path pays a fresh multi-minute XLA compile
        per batch bucket), the op-graph path otherwise."""
        if self._hash_pallas is not None:
            # pad to the kernel block on the HOST (cheap SHA) so every
            # batch <= 128 presents the same jit shape
            n = len(msgs)
            with kernel_span("h2c", backend="jax", batch=n,
                             padded=n + ((-n) % 128)):
                padded = list(msgs) + [msgs[0]] * ((-n) % 128)
                u0, u1 = self._h2c.hash_to_field_device(padded)
                return self._hash_pallas(u0, u1)[:n]
        with kernel_span("h2c", backend="jax", batch=len(msgs)):
            return self._h2c.hash_to_g2_batch(msgs)

    def _hash_msgs_proj(self, msgs):
        """Same, projective (B, 3, 2, L) for scalar-mult consumers."""
        aff = self._hash_msgs(msgs)
        one = self._jnp.broadcast_to(
            self._one2, (len(msgs), 1, 2, self._nlimb)
        )
        return self._jnp.concatenate([aff, one], axis=1)

    # -- committee plan + per-round hash caches ---------------------------

    def _eval_pub(self, pub: PubPoly, index: int):
        """Memoized host evaluation of the committee public polynomial —
        NativeScheme's per-PubPoly `_eval_pub` cache ported here: the
        daemon verifies the same committee every round and the degree-t
        Horner walk per signer is pure-Python oracle math.  Independent
        of the operand plan so even plan-miss paths never re-evaluate."""
        cache = getattr(pub, "_jax_eval_cache", None)
        if cache is None:
            cache = pub._jax_eval_cache = {}
        pt = cache.get(index)
        if pt is None:
            pt = cache[index] = pub.eval(index)
        return pt

    def _plan(self, pub: PubPoly) -> _CommitteePlan:
        """The committee's device operand plan, built on first touch."""
        plan = getattr(pub, "_jax_plan", None)
        if plan is None:
            plan = _CommitteePlan()
            ends = self._curve.g1_affine_encode_batch(
                [ref.g1_neg(ref.G1_GEN), pub.commit()]
            )
            plan.neg_g_row = ends[0]
            plan.pk_row = ends[1]
            plan.encode_calls += 1
            pub._jax_plan = plan
        return plan

    def _pk_stack(self, pub: PubPoly, plan: _CommitteePlan, rows):
        """Stacked encoded pk rows for `rows` (signer indices including
        padding duplicates), shape (len(rows), 2, L).

        Steady state — the same committee flooding the same signer
        layout — is a dict hit: zero host polynomial evaluations, zero
        limb encoding, zero stacking."""
        key = tuple(rows)
        with plan.lock:
            arr = plan.stacks.get(key)
            if arr is not None:
                plan.stacks.move_to_end(key)
                plan.stack_hits += 1
                return arr
            eval_cache = getattr(pub, "_jax_eval_cache", None) or {}
            missing = sorted({i for i in rows if i not in plan.pk_rows})
            if missing:
                plan.host_evals += sum(
                    1 for i in missing if i not in eval_cache
                )
                pts = [self._eval_pub(pub, i) for i in missing]
                enc = self._curve.g1_affine_encode_batch(pts)
                plan.encode_calls += 1
                for j, i in enumerate(missing):
                    plan.pk_rows[i] = enc[j]
            arr = self._jnp.stack([plan.pk_rows[i] for i in rows])
            while len(plan.stacks) >= plan.MAX_STACKS:
                plan.stacks.popitem(last=False)
            plan.stacks[key] = arr
            return arr

    def _msg_q2(self, msg: bytes):
        """Device-resident affine H(m), (1, 2, 2, L), computed at most
        once per round message and shared by every consumer (sign,
        partial verify, fused finalize)."""
        with self._msg_lock:
            q2 = self._msg_cache.get(msg)
            if q2 is not None:
                self._msg_cache.move_to_end(msg)
                self._msg_hits += 1
                return q2
        q2 = self._hash_msgs([msg])  # its own `h2c` kernel span
        with self._msg_lock:
            cur = self._msg_cache.get(msg)
            if cur is not None:
                return cur  # a racing thread hashed it first
            while len(self._msg_cache) >= self._MSG_CACHE_MAX:
                self._msg_cache.popitem(last=False)
            self._msg_cache[msg] = q2
        return q2

    # -- single-op API (device scalar mult / single pairing check) -------

    def partial_sign(self, share: PriShare, msg: bytes) -> bytes:
        # H(m) on device too (reference: Sign includes hash-to-curve,
        # /root/reference/beacon/beacon.go:433) — via the per-round hash
        # cache, so the verify/finalize calls that follow in the same
        # round reuse this hash instead of re-dispatching h2c
        aff = self._msg_q2(msg)
        with kernel_span("g2_sign", backend="jax", batch=1):
            hq = self._jnp.concatenate(
                [aff[0], self._one2[None]], axis=0
            )
            bits = self._jnp.asarray(
                self._curve.scalar_to_bits(share.value)
            )
            sig = self._curve.g2_decode(
                self._curve.g2_scalar_mul(hq, bits)
            )
        return _pack_partial(share.index, sig)

    def verify_partial(self, pub: PubPoly, msg: bytes,
                       partial: bytes) -> None:
        idx, _ = _unpack_partial(partial)
        ok = self.verify_partials_batch(pub, msg, [partial])[0]
        if not ok:
            raise ThresholdError(f"invalid partial signature from {idx}")

    def recover(self, pub: PubPoly, msg: bytes,
                partials: Sequence[bytes], t: int, n: int) -> bytes:
        chosen = self._recover_indices(partials, t)
        lam = lagrange_basis_at_zero([i for i, _ in chosen])
        pts = self._curve.g2_encode_batch([pt for _, pt in chosen])
        bits = self._jnp.asarray(
            np.stack(
                [self._curve.scalar_to_bits(lam[i]) for i, _ in chosen]
            )
        )
        with kernel_span("msm_recover", backend="jax",
                         batch=len(chosen)):
            acc = self._msm.g2_msm(pts, bits)
            out = self._curve.g2_decode(acc)
        return ref.g2_to_bytes(out)

    def verify_recovered(self, pub_key, msg: bytes, sig: bytes) -> None:
        ok = self.verify_chain_batch(pub_key, [msg], [sig])[0]
        if not ok:
            raise ThresholdError("invalid recovered signature")

    # -- batched device paths --------------------------------------------

    def _check_rows(self, pub: PubPoly, plan: _CommitteePlan, msg: bytes,
                    sig_pts, indices) -> np.ndarray:
        """ONE padded pairing-product dispatch verifying `sig_pts[j]` as
        the partial of signer `indices[j]` over `msg`; returns a bool
        array of len(sig_pts).  All committee operands come from the
        plan (device-resident), H(m) from the per-round cache — the only
        fresh upload is the signatures themselves."""
        n = len(sig_pts)
        nb = self._bucket(n)
        rows = list(indices) + [indices[0]] * (nb - n)
        p1 = self._jnp.broadcast_to(
            plan.neg_g_row, (nb, 2, self._nlimb)
        )
        q1 = self._curve.g2_affine_encode_batch(
            list(sig_pts) + [sig_pts[0]] * (nb - n)
        )
        p2 = self._pk_stack(pub, plan, rows)
        h1 = self._msg_q2(msg)                  # (1, 2, 2, L) on device
        q2 = self._jnp.broadcast_to(h1[0], (nb, *h1.shape[1:]))
        with kernel_span("pairing_check", backend="jax",
                         batch=n, padded=nb):
            ok = np.asarray(self._check(p1, q1, p2, q2))
        return ok[:n]

    def verify_partials_batch(self, pub: PubPoly, msg: bytes,
                              partials: Sequence[bytes]) -> List[bool]:
        plan = self._plan(pub)
        sigs, idxs, valid = [], [], []
        for blob in partials:
            try:
                idx, pt = _unpack_partial(blob)
                sigs.append(pt)
                idxs.append(idx)
                valid.append(True)
            except (ThresholdError, ValueError):
                sigs.append(None)
                idxs.append(None)
                valid.append(False)
        live = [i for i, v in enumerate(valid) if v]
        if not live:
            return [False] * len(partials)
        ok = self._check_rows(pub, plan, msg,
                              [sigs[i] for i in live],
                              [idxs[i] for i in live])
        out = [False] * len(partials)
        for j, i in enumerate(live):
            out[i] = bool(ok[j])
        return out

    def _build_finalize(self):
        """Fused recovery program: Lagrange-weighted G2 MSM over the
        chosen partials, conversion to affine, and the recovered-
        signature pairing check — one jitted dispatch, one host sync."""
        import jax

        jnp, curve, msm, check = (
            self._jnp, self._curve, self._msm, self._check
        )

        def fused(pts, bits, neg_row, pk_row, q2):
            acc = msm.g2_msm(pts, bits)             # (3, 2, L)
            x, y = curve.to_affine(acc, curve.F2)
            sig_aff = jnp.stack([x, y], axis=0)     # (2, 2, L)
            ok = check(neg_row[None], sig_aff[None], pk_row[None], q2)
            return sig_aff, ok[0]

        return jax.jit(fused)

    def finalize_round(self, pub: PubPoly, msg: bytes,
                       partials: Sequence[bytes], t: int, n: int) -> bytes:
        """Fused round finalize: ≤ 2 device dispatches on the happy path
        (was ≥ 4: h2c + partial pairing check + MSM + recovered check).

        Dispatch 1 (`pairing_check`): one padded pairing-product check
        over every parseable partial, on plan-cached committee operands
        and the cached per-round H(m).
        Dispatch 2 (`msm_recover`): one jitted program applying the
        host-precomputed Lagrange weights over the first t valid rows
        (G2 MSM), converting to affine, and re-checking the recovered
        signature against the collective key — the `verify_recovered`
        that used to be its own dispatch rides the same program.

        Output is byte-identical to `RefScheme.recover` over the valid
        subset (first occurrence per signer index wins, then the t
        lowest indices), and a signature is only ever returned with the
        in-program check green.
        """
        plan = self._plan(pub)
        parsed = []
        for blob in partials:
            try:
                parsed.append(_unpack_partial(blob))
            except (ThresholdError, ValueError):
                continue
        seen = {}
        if parsed:
            ok = self._check_rows(pub, plan, msg,
                                  [pt for _, pt in parsed],
                                  [idx for idx, _ in parsed])
            for (idx, pt), good in zip(parsed, ok):
                if good and idx not in seen:
                    seen[idx] = pt
        if len(seen) < t:
            raise ThresholdError(
                f"not enough distinct valid partials: {len(seen)} < {t}"
            )
        chosen = sorted(seen.items())[:t]
        lam = lagrange_basis_at_zero([i for i, _ in chosen])
        pts = self._curve.g2_encode_batch([pt for _, pt in chosen])
        bits = self._jnp.asarray(
            np.stack(
                [self._curve.scalar_to_bits(lam[i]) for i, _ in chosen]
            )
        )
        q2 = self._msg_q2(msg)
        if self._finalize_jit is None:
            self._finalize_jit = self._build_finalize()
        with kernel_span("msm_recover", backend="jax",
                         batch=len(chosen), fused_verify=True):
            sig_aff, good = self._finalize_jit(
                pts, bits, plan.neg_g_row, plan.pk_row, q2
            )
            good = bool(np.asarray(good))
            sig_host = np.asarray(sig_aff)
        if not good:
            # mathematically unreachable when the t inputs passed the
            # row check above; kept as defense in depth (a device fault
            # must never publish a bad beacon)
            raise ThresholdError("invalid recovered signature")
        out = (self._tower.fp2_decode(sig_host[0]),
               self._tower.fp2_decode(sig_host[1]))
        return ref.g2_to_bytes(out)

    def finalize_round_optimistic(self, pub: PubPoly, msg: bytes,
                                  partials: Sequence[bytes], t: int,
                                  n: int) -> bytes:
        """ONE device dispatch: the fused MSM→affine→recovered-check
        program over the first t admitted partials, skipping the
        per-partial pairing batch entirely.  With the per-round H(m)
        already cached by `partial_sign`, the whole honest round costs
        two dispatches total (g2_sign + msm_recover) and zero pairing
        work at ingest.  A red in-program check means at least one
        admitted partial was forged — raised as ThresholdError so the
        handler can run the `verify_partials_batch` blame pass."""
        plan = self._plan(pub)
        chosen = self._recover_indices(partials, t)
        lam = lagrange_basis_at_zero([i for i, _ in chosen])
        pts = self._curve.g2_encode_batch([pt for _, pt in chosen])
        bits = self._jnp.asarray(
            np.stack(
                [self._curve.scalar_to_bits(lam[i]) for i, _ in chosen]
            )
        )
        q2 = self._msg_q2(msg)
        if self._finalize_jit is None:
            self._finalize_jit = self._build_finalize()
        with kernel_span("msm_recover", backend="jax",
                         batch=len(chosen), fused_verify=True,
                         optimistic=True):
            sig_aff, good = self._finalize_jit(
                pts, bits, plan.neg_g_row, plan.pk_row, q2
            )
            good = bool(np.asarray(good))
            sig_host = np.asarray(sig_aff)
        if not good:
            raise ThresholdError("invalid recovered signature")
        out = (self._tower.fp2_decode(sig_host[0]),
               self._tower.fp2_decode(sig_host[1]))
        return ref.g2_to_bytes(out)

    def invalidate_round_caches(self) -> None:
        # committee plans and chain-operand rows are keyed by committee /
        # collective key and survive a reorg unchanged; only the
        # round-message H(m) cache holds orphaned-branch entries
        with self._msg_lock:
            self._msg_cache.clear()

    def _chain_rows(self, pub_key):
        """Encoded (−G, pk) rows for chain verification, cached per
        collective key — catch-up re-verifies thousands of rounds under
        one key, so its operands are encoded once, not per batch."""
        try:
            rows = self._chain_ops.get(pub_key)
        except TypeError:            # unhashable key form: skip cache
            ends = self._curve.g1_affine_encode_batch(
                [ref.g1_neg(ref.G1_GEN), pub_key]
            )
            return ends[0], ends[1]
        if rows is None:
            ends = self._curve.g1_affine_encode_batch(
                [ref.g1_neg(ref.G1_GEN), pub_key]
            )
            rows = (ends[0], ends[1])
            while len(self._chain_ops) >= 8:
                self._chain_ops.popitem(last=False)
            self._chain_ops[pub_key] = rows
        else:
            self._chain_ops.move_to_end(pub_key)
        return rows

    def _maybe_sharded(self, nb: int):
        """The mesh-sharded pairing check for a padded batch of `nb`
        rows, or None when the single-device path should run (small
        batch, single chip, mesh-indivisible shape, or disabled)."""
        if not self._shard_enabled or nb < self._shard_min:
            return None
        if self._sharded_check is None:
            try:
                import jax

                from drand_tpu.parallel import shard

                devices = jax.devices()
                if len(devices) < 2:
                    self._shard_enabled = False
                    return None
                self._mesh = shard.device_mesh(len(devices))
                self._sharded_check = shard.sharded_pairing_check(
                    self._mesh
                )
            except Exception:        # mesh construction is best-effort
                self._shard_enabled = False
                return None
        if nb % self._mesh.devices.size:
            return None
        return self._sharded_check

    def verify_chain_batch(self, pub_key, msgs, sigs):
        pts, valid = [], []
        for sig in sigs:
            try:
                pt = (ref.g2_from_bytes(sig)
                      if isinstance(sig, (bytes, bytearray)) else sig)
                if pt is None:
                    raise ThresholdError("identity signature")
                pts.append(pt)
                valid.append(True)
            except (ThresholdError, ValueError):
                pts.append(None)
                valid.append(False)
        live = [i for i, v in enumerate(valid) if v]
        if not live:
            return [False] * len(sigs)
        nb = self._bucket(len(live))
        rows = live + [live[0]] * (nb - len(live))
        neg_row, pk_row = self._chain_rows(pub_key)
        p1 = self._jnp.broadcast_to(neg_row, (nb, 2, self._nlimb))
        q1 = self._curve.g2_affine_encode_batch([pts[i] for i in rows])
        p2 = self._jnp.broadcast_to(pk_row, (nb, 2, self._nlimb))
        # messages hashed on device, batched (round 1 paid 0.6 s of host
        # Python per row here — the whole point of ops/h2c.py)
        row_msgs = [msgs[i] for i in rows]
        sharded = self._maybe_sharded(nb)
        with kernel_span("pairing_check", backend="jax",
                         batch=len(live), padded=nb,
                         devices=(self._mesh.devices.size
                                  if sharded is not None else 1)):
            if sharded is not None:
                # multi-chip catch-up: hash on the default device, check
                # with the batch axis sharded across the mesh
                u0, u1 = self._h2c.hash_to_field_device(row_msgs)
                q2 = self._h2c.map_and_clear_g2_affine(u0, u1)
                ok = np.asarray(sharded(p1, q1, p2, q2))
            elif self._check_hashed is not None:
                u0, u1 = self._h2c.hash_to_field_device(row_msgs)
                ok = np.asarray(self._check_hashed(p1, q1, p2, u0, u1))
            else:
                q2 = self._h2c.hash_to_g2_batch(row_msgs)
                ok = np.asarray(self._check(p1, q1, p2, q2))
        out = [False] * len(sigs)
        for j, i in enumerate(live):
            out[i] = bool(ok[j])
        return out

    # -- explicit gateway mesh (serve/ mesh-sharded batch scheduler) ------

    def configure_mesh(self, n_devices: int) -> str:
        """Build the explicit `n_devices` mesh used by
        `verify_chain_batch_mesh` and return the platform actually
        backing it ("cpu", "tpu", ...) — callers surface that in status
        so virtual-CPU numbers can't masquerade as TPU numbers.

        Distinct from `_maybe_sharded`: that one opportunistically
        shards LARGE single batches over every visible device; this one
        is the gateway's fixed-width mesh whose lane assembly the
        scheduler controls."""
        from drand_tpu.parallel import shard

        mesh = shard.device_mesh(n_devices)
        self._gw_mesh = mesh
        self._gw_sharded_check = shard.sharded_pairing_check(mesh)
        self._gw_mesh_backend = shard.mesh_backend(mesh)
        return self._gw_mesh_backend

    def verify_chain_batch_mesh(self, pub_key, lane_msgs, lane_sigs):
        """ONE mesh-sharded pairing dispatch over per-device lanes.

        `lane_msgs` / `lane_sigs` hold one list per mesh device (empty
        lanes allowed).  Every lane pads to the SHARED per-device bucket
        — `_bucket(longest lane)` — so the concatenated batch is one
        fixed shape whose leading axis NamedSharding splits contiguously:
        lane k lands wholly on device k.  Returns per-lane verdict lists
        mirroring the input shapes."""
        mesh = getattr(self, "_gw_mesh", None)
        if mesh is None:
            raise RuntimeError(
                "verify_chain_batch_mesh requires configure_mesh()"
            )
        ndev = mesh.devices.size
        if len(lane_msgs) != ndev or len(lane_sigs) != ndev:
            raise ValueError(
                f"expected {ndev} lanes, got {len(lane_msgs)}"
            )
        lane_pts, lane_live = [], []
        for sigs in lane_sigs:
            pts, live = [], []
            for i, sig in enumerate(sigs):
                try:
                    pt = (ref.g2_from_bytes(sig)
                          if isinstance(sig, (bytes, bytearray)) else sig)
                    if pt is None:
                        raise ThresholdError("identity signature")
                    pts.append(pt)
                    live.append(i)
                except (ThresholdError, ValueError):
                    pts.append(None)
            lane_pts.append(pts)
            lane_live.append(live)
        total_live = sum(len(l) for l in lane_live)
        if not total_live:
            return [[False] * len(sigs) for sigs in lane_sigs]
        per_dev = self._bucket(max(len(l) for l in lane_live))
        nb = per_dev * ndev
        # lanes with no live rows re-check the first live row found
        # anywhere (same padding idiom as verify_chain_batch)
        fk = next(k for k, l in enumerate(lane_live) if l)
        fb_pt = lane_pts[fk][lane_live[fk][0]]
        fb_msg = lane_msgs[fk][lane_live[fk][0]]
        row_pts, row_msgs = [], []
        for k in range(ndev):
            live = lane_live[k]
            if live:
                rows = live + [live[0]] * (per_dev - len(live))
                row_pts.extend(lane_pts[k][i] for i in rows)
                row_msgs.extend(lane_msgs[k][i] for i in rows)
            else:
                row_pts.extend([fb_pt] * per_dev)
                row_msgs.extend([fb_msg] * per_dev)
        neg_row, pk_row = self._chain_rows(pub_key)
        p1 = self._jnp.broadcast_to(neg_row, (nb, 2, self._nlimb))
        q1 = self._curve.g2_affine_encode_batch(row_pts)
        p2 = self._jnp.broadcast_to(pk_row, (nb, 2, self._nlimb))
        with kernel_span("pairing_check", backend="jax",
                         batch=total_live, padded=nb, devices=ndev,
                         mesh=True):
            u0, u1 = self._h2c.hash_to_field_device(row_msgs)
            q2 = self._h2c.map_and_clear_g2_affine(u0, u1)
            ok = np.asarray(self._gw_sharded_check(p1, q1, p2, q2))
        out = []
        for k in range(ndev):
            verdicts = [False] * len(lane_sigs[k])
            base = k * per_dev
            for j, i in enumerate(lane_live[k]):
                verdicts[i] = bool(ok[base + j])
            out.append(verdicts)
        return out


_DEFAULT: Optional[Scheme] = None


def _accelerator_present() -> bool:
    """True when JAX's default backend is a real accelerator (the axon
    tunnel reports itself as its own platform name)."""
    try:
        import jax

        backend = jax.default_backend().lower()
    except Exception:
        return False
    return "tpu" in backend or "gpu" in backend or backend == "axon"


def _native_scheme_or_ref() -> Scheme:
    try:
        return NativeScheme()
    except RuntimeError as e:
        # degrading to the oracle costs ~1000x per pairing; a daemon that
        # then misses its round deadlines must have a visible cause
        from drand_tpu.utils.logging import get_logger

        get_logger("tbls").warning(
            "native BLS backend unavailable; falling back to the "
            "pure-Python oracle", error=str(e),
        )
        return RefScheme()


def default_scheme(backend: Optional[str] = None) -> Scheme:
    """Process-wide scheme selection.

    'jax'    — device batched kernels;
    'native' — C++ host backend (native/bls.cc);
    'ref'    — pure-Python oracle;
    'auto'   — JaxScheme when an accelerator is present, NativeScheme
               otherwise (the reference always runs its native crypto
               suite, /root/reference/key/curve.go:12 — a daemon booted
               on a TPU host should use the device path with no flags,
               and a CPU-only daemon the C++ path, never the oracle).

    Bare default (no argument, first call) is the native C++ backend when
    it builds, the oracle otherwise: library users who never asked for a
    device shouldn't pay a JAX initialization, but they still deserve
    millisecond verifies.
    """
    global _DEFAULT
    if backend == "auto":
        backend = "jax" if _accelerator_present() else "native"
    if backend == "jax":
        _DEFAULT = JaxScheme()
    elif backend == "native":
        _DEFAULT = _native_scheme_or_ref()
    elif backend == "ref":
        _DEFAULT = RefScheme()
    elif backend is not None:
        raise ValueError(
            f"unknown crypto backend {backend!r}: "
            "expected auto, jax, native or ref"
        )
    elif _DEFAULT is None:
        _DEFAULT = _native_scheme_or_ref()
    return _DEFAULT


def randomness(sig: bytes) -> bytes:
    """The beacon's public randomness: SHA-256 of the signature
    (/root/reference/beacon/chain.go:52-55)."""
    return hashlib.sha256(sig).digest()
