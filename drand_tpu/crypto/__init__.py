"""BLS12-381 crypto: pure-Python oracle (refimpl) + JAX/TPU execution path."""
