"""Schnorr signatures over G1 with the long-term node keys.

The reference's kyber vss signs every DKG message (Deal/Response/
Justification carry signatures — /root/reference/protobuf/crypto/vss/
vss.proto) so that a peer cannot forge complaints or justifications in
someone else's name.  Without this, a forged complaint tricks an honest
dealer into publicly revealing the named verifier's sub-share (a secret
leak), and a forged "invalid justification" convicts an honest dealer
(a one-packet DoS).

Schnorr (not BLS) because DKG-plane verification should not cost a
pairing: sign = 1 scalar mult, verify = 2.  Deterministic nonce (RFC
6979 flavor: k = H(sk ‖ msg)) — no RNG failure modes.

    sign(sk, msg)   -> 96 bytes:  R (48-byte compressed G1) ‖ s (32)
    verify(pk, msg, sig) -> bool:  s·G == R + e·pk,
                                   e = H(R ‖ pk ‖ msg) mod r
"""

from __future__ import annotations

import hashlib

from drand_tpu.crypto import refimpl as ref

SIG_LEN = 48 + 32
_DST = b"drand-tpu-schnorr-v1"


def _wide_reduce(h: bytes) -> int:
    """Reduce a 64-byte digest mod R.  A 256-bit digest into the
    ~255-bit order leaves some residues ~1.5x more likely (2^256/R ≈
    2.2); 512 bits makes the bias < 2^-255 (RFC 9380 hash_to_field
    practice, L >= 48 bytes)."""
    return int.from_bytes(h, "big") % ref.R


def _challenge(r_bytes: bytes, pk_bytes: bytes, msg: bytes) -> int:
    return _wide_reduce(
        hashlib.sha512(_DST + r_bytes + pk_bytes + msg).digest())


_PK_CACHE: dict = {}


def sign(sk: int, msg: bytes) -> bytes:
    # the long-term pk never changes; deriving it is a full scalar mult
    pk_bytes = _PK_CACHE.get(sk)
    if pk_bytes is None:
        pk_bytes = ref.g1_to_bytes(ref.g1_mul(ref.G1_GEN, sk))
        _PK_CACHE[sk] = pk_bytes
    k = _wide_reduce(hashlib.sha512(
        _DST + sk.to_bytes(32, "big") + msg).digest())
    if k == 0:
        k = 1
    r_bytes = ref.g1_to_bytes(ref.g1_mul(ref.G1_GEN, k))
    e = _challenge(r_bytes, pk_bytes, msg)
    s = (k + e * sk) % ref.R
    return r_bytes + s.to_bytes(32, "big")


def verify(pk, msg: bytes, sig: bytes) -> bool:
    """pk: oracle affine G1 point (a node's long-term public key)."""
    if len(sig) != SIG_LEN:
        return False
    try:
        r_pt = ref.g1_from_bytes(sig[:48])
    except ValueError:
        return False
    if r_pt is None:
        return False
    s = int.from_bytes(sig[48:], "big")
    if s >= ref.R:
        return False
    e = _challenge(sig[:48], ref.g1_to_bytes(pk), msg)
    lhs = ref.g1_mul(ref.G1_GEN, s)
    rhs = ref.g1_add(r_pt, ref.g1_mul(pk, e))
    return lhs == rhs
