"""ctypes wrapper for the native C++ BLS12-381 backend (native/bls.cc).

This is the host-side fast path the blueprint mandates (SURVEY.md §2: a C++
equivalent, not a Python stand-in, wherever the TPU can't run).  It mirrors
the reference daemon's native crypto suite (/root/reference/key/curve.go:12)
for the no-accelerator case: single partial verify ~10 ms instead of the
pure-Python oracle's 10-30 s.

Everything crosses the boundary as the wire formats the protocol already
uses (48/96-byte compressed points, 32-byte big-endian scalars), so there
is no per-op bignum marshalling.  Semantics are byte-identical to
crypto/refimpl.py — enforced by tests/test_native_bls.py.
"""

from __future__ import annotations

import ctypes
import threading
from typing import Optional

_LOCK = threading.Lock()
_LIB: Optional[ctypes.CDLL] = None
_LOAD_FAILED = False


def load() -> Optional[ctypes.CDLL]:
    """The shared library, built on demand; None if unavailable."""
    global _LIB, _LOAD_FAILED
    if _LIB is not None or _LOAD_FAILED:
        return _LIB
    with _LOCK:
        if _LIB is not None or _LOAD_FAILED:
            return _LIB
        from drand_tpu import native

        path = native.shared_lib("bls")
        if path is None:
            _LOAD_FAILED = True
            return None
        try:
            lib = ctypes.CDLL(path)
        except OSError:
            _LOAD_FAILED = True
            return None
        u8p = ctypes.POINTER(ctypes.c_uint8)
        c = ctypes.c_char_p
        u64 = ctypes.c_uint64
        i32 = ctypes.c_int
        lib.dbls_init.restype = i32
        lib.dbls_selfcheck.restype = i32
        lib.dbls_hash_to_g2.argtypes = [c, u64, u8p]
        lib.dbls_hash_to_g1.argtypes = [c, u64, u8p]
        lib.dbls_sign.argtypes = [c, u64, c, u8p]
        lib.dbls_verify.argtypes = [c, c, u64, c]
        lib.dbls_verify_pre.argtypes = [c, c, c]
        lib.dbls_g1_msm.argtypes = [c, c, u64, i32, u8p]
        lib.dbls_g2_msm.argtypes = [c, c, u64, i32, u8p]
        lib.dbls_g1_mul.argtypes = [c, c, u8p]
        lib.dbls_g2_mul.argtypes = [c, c, u8p]
        lib.dbls_g1_check.argtypes = [c]
        lib.dbls_g2_check.argtypes = [c]
        lib.dbls_g1_add.argtypes = [c, c, u8p]
        lib.dbls_g2_add.argtypes = [c, c, u8p]
        lib.dbls_pairing.argtypes = [c, c, u8p]
        for fn in ("dbls_hash_to_g2", "dbls_hash_to_g1", "dbls_sign",
                   "dbls_verify", "dbls_verify_pre", "dbls_g1_msm",
                   "dbls_g2_msm", "dbls_g1_mul", "dbls_g2_mul",
                   "dbls_g1_check", "dbls_g2_check", "dbls_g1_add",
                   "dbls_g2_add", "dbls_pairing"):
            getattr(lib, fn).restype = i32
        if lib.dbls_init() != 0:
            _LOAD_FAILED = True
            return None
        _LIB = lib
    return _LIB


def available() -> bool:
    return load() is not None


# -- thin typed helpers (bytes in, bytes out) --------------------------------


def _buf(n: int):
    return (ctypes.c_uint8 * n)()


def hash_to_g2(msg: bytes) -> bytes:
    lib = load()
    out = _buf(96)
    rc = lib.dbls_hash_to_g2(msg, len(msg), out)
    if rc != 0:
        raise RuntimeError(f"dbls_hash_to_g2: {rc}")
    return bytes(out)


def hash_to_g1(msg: bytes) -> bytes:
    lib = load()
    out = _buf(48)
    rc = lib.dbls_hash_to_g1(msg, len(msg), out)
    if rc != 0:
        raise RuntimeError(f"dbls_hash_to_g1: {rc}")
    return bytes(out)


def sign(msg: bytes, scalar: int) -> bytes:
    lib = load()
    out = _buf(96)
    rc = lib.dbls_sign(msg, len(msg), scalar.to_bytes(32, "big"), out)
    if rc != 0:
        raise RuntimeError(f"dbls_sign: {rc}")
    return bytes(out)


def verify(pk48: bytes, msg: bytes, sig96: bytes) -> int:
    """1 valid, 0 invalid, <0 malformed encodings."""
    return load().dbls_verify(pk48, msg, len(msg), sig96)


def verify_pre(pk48: bytes, hm96: bytes, sig96: bytes) -> int:
    return load().dbls_verify_pre(pk48, hm96, sig96)


def g1_msm(points48: list, scalars: list, check: bool = True) -> bytes:
    lib = load()
    out = _buf(48)
    sc = b"".join(s.to_bytes(32, "big") for s in scalars)
    rc = lib.dbls_g1_msm(b"".join(points48), sc, len(points48),
                         1 if check else 0, out)
    if rc != 0:
        raise ValueError(f"dbls_g1_msm: {rc}")
    return bytes(out)


def g2_msm(points96: list, scalars: list, check: bool = True) -> bytes:
    lib = load()
    out = _buf(96)
    sc = b"".join(s.to_bytes(32, "big") for s in scalars)
    rc = lib.dbls_g2_msm(b"".join(points96), sc, len(points96),
                         1 if check else 0, out)
    if rc != 0:
        raise ValueError(f"dbls_g2_msm: {rc}")
    return bytes(out)


def g1_mul(point48: Optional[bytes], scalar: int) -> bytes:
    """scalar * point (None -> G1 generator)."""
    lib = load()
    out = _buf(48)
    rc = lib.dbls_g1_mul(point48, scalar.to_bytes(32, "big"), out)
    if rc != 0:
        raise ValueError(f"dbls_g1_mul: {rc}")
    return bytes(out)


def g2_mul(point96: Optional[bytes], scalar: int) -> bytes:
    lib = load()
    out = _buf(96)
    rc = lib.dbls_g2_mul(point96, scalar.to_bytes(32, "big"), out)
    if rc != 0:
        raise ValueError(f"dbls_g2_mul: {rc}")
    return bytes(out)


def g1_add(a48: bytes, b48: bytes) -> bytes:
    lib = load()
    out = _buf(48)
    rc = lib.dbls_g1_add(a48, b48, out)
    if rc != 0:
        raise ValueError(f"dbls_g1_add: {rc}")
    return bytes(out)


def g2_add(a96: bytes, b96: bytes) -> bytes:
    lib = load()
    out = _buf(96)
    rc = lib.dbls_g2_add(a96, b96, out)
    if rc != 0:
        raise ValueError(f"dbls_g2_add: {rc}")
    return bytes(out)


def g1_check(p48: bytes) -> int:
    return load().dbls_g1_check(p48)


def g2_check(p96: bytes) -> int:
    return load().dbls_g2_check(p96)


def pairing_bytes(p48: bytes, q96: bytes) -> bytes:
    """Canonical 576-byte GT — refimpl cross-check hook."""
    lib = load()
    out = _buf(576)
    rc = lib.dbls_pairing(p48, q96, out)
    if rc != 0:
        raise ValueError(f"dbls_pairing: {rc}")
    return bytes(out)


def selfcheck() -> int:
    return load().dbls_selfcheck()
