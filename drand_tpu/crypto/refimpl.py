"""Pure-Python reference implementation of BLS12-381 (the correctness oracle).

This module is the host-side / test-side ground truth for the TPU crypto
path. It mirrors the role of ``drand/kyber`` + ``drand/bls12-381`` in the
reference (selected at /root/reference/key/curve.go:12-30): pairing suite,
G1 = key group, G2 = signature group.

Everything here is *self-verifying*: the curve constants, twist order,
Frobenius coefficients and hash-to-curve parameters are checked (or derived)
numerically in ``selfcheck()`` / tests, because this build environment has no
network access for official test vectors. The checks performed (primality of
p and r, BLS polynomial identities p = (x-1)^2 (x^4-x^2+1)/3 + x and
r = x^4 - x^2 + 1, generators on-curve and of order r, pairing bilinearity
and non-degeneracy) uniquely pin down the scheme.

Conventions:
  * Fp2 = Fp[u]/(u^2+1), Fp6 = Fp2[v]/(v^3 - xi), xi = 1+u,
    Fp12 = Fp6[w]/(w^2 - v)  (the standard tower).
  * G1: E(Fp): y^2 = x^3 + 4.  G2: E'(Fp2): y^2 = x^3 + 4(1+u)  (M-twist).
  * Points are affine tuples (x, y); None is the point at infinity.
  * Serialization follows the 48/96-byte compressed big-endian form with
    3 flag bits (compressed / infinity / y-sign), as used by the group files
    the reference ships (/root/reference/deploy/latest/group.toml).
"""

from __future__ import annotations

import hashlib
from typing import Optional, Sequence, Tuple

# ---------------------------------------------------------------------------
# Base field constants (checked in selfcheck()).
# ---------------------------------------------------------------------------

#: BLS parameter (negative): x = -(2^63 + 2^62 + 2^60 + 2^57 + 2^48 + 2^16)
X_PARAM = -0xD201000000010000

#: Base field modulus p = (x-1)^2 (x^4 - x^2 + 1)/3 + x
P = 0x1A0111EA397FE69A4B1BA7B6434BACD764774B84F38512BF6730D2A0F6B0F6241EABFFFEB153FFFFB9FEFFFFFFFFAAAB

#: Scalar field modulus r = x^4 - x^2 + 1 (order of G1/G2/GT)
R = 0x73EDA753299D7D483339D80809A1D80553BDA402FFFE5BFEFFFFFFFF00000001

#: G1 cofactor h1 = (x-1)^2 / 3
H1 = ((X_PARAM - 1) ** 2) // 3

# Curve coefficients: E: y^2 = x^3 + 4 ; E': y^2 = x^3 + 4(1+u)
B1 = 4
B2 = (4, 4)  # 4 * (1 + u)

# Standard generators (checked on-curve + order r in selfcheck()).
G1_GEN = (
    0x17F1D3A73197D7942695638C4FA9AC0FC3688C4F9774B905A14E3A3F171BAC586C55E83FF97A1AEFFB3AF00ADB22C6BB,
    0x08B3F481E3AAA0F1A09E30ED741D8AE4FCF5E095D5D00AF600DB18CB2C04B3EDD03CC744A2888AE40CAA232946C5E7E1,
)
G2_GEN = (
    (
        0x024AA2B2F08F0A91260805272DC51051C6E47AD4FA403B02B4510B647AE3D1770BAC0326A805BBEFD48056C8C121BDB8,
        0x13E02B6052719F607DACD3A088274F65596BD0D09920B61AB5DA61BBDC7F5049334CF11213945D57E5AC7D055D042B7E,
    ),
    (
        0x0CE5D527727D6E118CC9CDC6DA2E351AADFD9BAA8CBDD3A76D429A695160D12C923AC9CC3BACA289E193548608B82801,
        0x0606C4A02EA734CC32ACD2B02BC28B99CB3E287E85A763AF267492AB572E99AB3F370D275CEC1DA1AAA9075FF05F79BE,
    ),
)

# ---------------------------------------------------------------------------
# Fp
# ---------------------------------------------------------------------------


def fp_inv(a: int) -> int:
    return pow(a, P - 2, P)


def fp_sqrt(a: int) -> Optional[int]:
    """Square root in Fp (p = 3 mod 4), or None if a is not a square."""
    if a == 0:
        return 0
    s = pow(a, (P + 1) // 4, P)
    return s if s * s % P == a % P else None


def fp_is_square(a: int) -> bool:
    return a % P == 0 or pow(a, (P - 1) // 2, P) == 1


def fp_sgn0(a: int) -> int:
    return a & 1


# ---------------------------------------------------------------------------
# Fp2 = Fp[u] / (u^2 + 1)
# ---------------------------------------------------------------------------

Fp2 = Tuple[int, int]

FP2_ZERO: Fp2 = (0, 0)
FP2_ONE: Fp2 = (1, 0)
XI: Fp2 = (1, 1)  # 1 + u, the Fp6 non-residue


def fp2_add(a: Fp2, b: Fp2) -> Fp2:
    return ((a[0] + b[0]) % P, (a[1] + b[1]) % P)


def fp2_sub(a: Fp2, b: Fp2) -> Fp2:
    return ((a[0] - b[0]) % P, (a[1] - b[1]) % P)


def fp2_neg(a: Fp2) -> Fp2:
    return ((-a[0]) % P, (-a[1]) % P)


def fp2_mul(a: Fp2, b: Fp2) -> Fp2:
    a0, a1 = a
    b0, b1 = b
    return ((a0 * b0 - a1 * b1) % P, (a0 * b1 + a1 * b0) % P)


def fp2_muls(a: Fp2, s: int) -> Fp2:
    return (a[0] * s % P, a[1] * s % P)


def fp2_sqr(a: Fp2) -> Fp2:
    a0, a1 = a
    return ((a0 + a1) * (a0 - a1) % P, 2 * a0 * a1 % P)


def fp2_conj(a: Fp2) -> Fp2:
    return (a[0], (-a[1]) % P)


def fp2_inv(a: Fp2) -> Fp2:
    a0, a1 = a
    n = fp_inv((a0 * a0 + a1 * a1) % P)
    return (a0 * n % P, (-a1) * n % P)


def fp2_pow(a: Fp2, e: int) -> Fp2:
    result = FP2_ONE
    base = a
    while e > 0:
        if e & 1:
            result = fp2_mul(result, base)
        base = fp2_sqr(base)
        e >>= 1
    return result


def fp2_is_square(a: Fp2) -> bool:
    # norm(a) = a * a^p = a0^2 + a1^2 in Fp; a is a QR in Fp2 iff its norm is
    # a QR in Fp (norm map is surjective onto Fp*).
    return fp_is_square((a[0] * a[0] + a[1] * a[1]) % P)


def fp2_sqrt(a: Fp2) -> Optional[Fp2]:
    """Square root in Fp2 via the 'complex' method; None if not a square."""
    a0, a1 = a[0] % P, a[1] % P
    if a1 == 0:
        s = fp_sqrt(a0)
        if s is not None:
            return (s, 0)
        s = fp_sqrt((-a0) % P)
        if s is None:
            return None
        return (0, s)
    n = (a0 * a0 + a1 * a1) % P
    s = fp_sqrt(n)
    if s is None:
        return None
    inv2 = fp_inv(2)
    x0sq = (a0 + s) * inv2 % P
    x0 = fp_sqrt(x0sq)
    if x0 is None:
        x0sq = (a0 - s) * inv2 % P
        x0 = fp_sqrt(x0sq)
        if x0 is None:
            return None
    x1 = a1 * fp_inv(2 * x0 % P) % P
    cand = (x0, x1)
    return cand if fp2_sqr(cand) == (a0, a1) else None


def fp2_sgn0(a: Fp2) -> int:
    # RFC 9380 sgn0 for m=2.
    s0 = a[0] & 1
    z0 = a[0] == 0
    s1 = a[1] & 1
    return s0 | (int(z0) & s1)


# ---------------------------------------------------------------------------
# Fp6 = Fp2[v] / (v^3 - xi)   elements: (c0, c1, c2)
# ---------------------------------------------------------------------------

Fp6 = Tuple[Fp2, Fp2, Fp2]

FP6_ZERO: Fp6 = (FP2_ZERO, FP2_ZERO, FP2_ZERO)
FP6_ONE: Fp6 = (FP2_ONE, FP2_ZERO, FP2_ZERO)


def fp6_add(a: Fp6, b: Fp6) -> Fp6:
    return (fp2_add(a[0], b[0]), fp2_add(a[1], b[1]), fp2_add(a[2], b[2]))


def fp6_sub(a: Fp6, b: Fp6) -> Fp6:
    return (fp2_sub(a[0], b[0]), fp2_sub(a[1], b[1]), fp2_sub(a[2], b[2]))


def fp6_neg(a: Fp6) -> Fp6:
    return (fp2_neg(a[0]), fp2_neg(a[1]), fp2_neg(a[2]))


def _mul_xi(a: Fp2) -> Fp2:
    # (a0 + a1 u)(1 + u) = (a0 - a1) + (a0 + a1) u
    return ((a[0] - a[1]) % P, (a[0] + a[1]) % P)


def fp6_mul(a: Fp6, b: Fp6) -> Fp6:
    a0, a1, a2 = a
    b0, b1, b2 = b
    t00 = fp2_mul(a0, b0)
    t11 = fp2_mul(a1, b1)
    t22 = fp2_mul(a2, b2)
    c0 = fp2_add(t00, _mul_xi(fp2_add(fp2_mul(a1, b2), fp2_mul(a2, b1))))
    c1 = fp2_add(fp2_add(fp2_mul(a0, b1), fp2_mul(a1, b0)), _mul_xi(t22))
    c2 = fp2_add(fp2_add(fp2_mul(a0, b2), fp2_mul(a2, b0)), t11)
    return (c0, c1, c2)


def fp6_sqr(a: Fp6) -> Fp6:
    return fp6_mul(a, a)


def fp6_mul_by_v(a: Fp6) -> Fp6:
    # (c0 + c1 v + c2 v^2) * v = xi*c2 + c0 v + c1 v^2
    return (_mul_xi(a[2]), a[0], a[1])


def fp6_inv(a: Fp6) -> Fp6:
    a0, a1, a2 = a
    t0 = fp2_sub(fp2_sqr(a0), _mul_xi(fp2_mul(a1, a2)))
    t1 = fp2_sub(_mul_xi(fp2_sqr(a2)), fp2_mul(a0, a1))
    t2 = fp2_sub(fp2_sqr(a1), fp2_mul(a0, a2))
    norm = fp2_add(
        fp2_mul(a0, t0),
        _mul_xi(fp2_add(fp2_mul(a2, t1), fp2_mul(a1, t2))),
    )
    ninv = fp2_inv(norm)
    return (fp2_mul(t0, ninv), fp2_mul(t1, ninv), fp2_mul(t2, ninv))


# ---------------------------------------------------------------------------
# Fp12 = Fp6[w] / (w^2 - v)   elements: (c0, c1)
# ---------------------------------------------------------------------------

Fp12 = Tuple[Fp6, Fp6]

FP12_ZERO: Fp12 = (FP6_ZERO, FP6_ZERO)
FP12_ONE: Fp12 = (FP6_ONE, FP6_ZERO)


def fp12_add(a: Fp12, b: Fp12) -> Fp12:
    return (fp6_add(a[0], b[0]), fp6_add(a[1], b[1]))


def fp12_sub(a: Fp12, b: Fp12) -> Fp12:
    return (fp6_sub(a[0], b[0]), fp6_sub(a[1], b[1]))


def fp12_mul(a: Fp12, b: Fp12) -> Fp12:
    a0, a1 = a
    b0, b1 = b
    t0 = fp6_mul(a0, b0)
    t1 = fp6_mul(a1, b1)
    c0 = fp6_add(t0, fp6_mul_by_v(t1))
    c1 = fp6_sub(fp6_mul(fp6_add(a0, a1), fp6_add(b0, b1)), fp6_add(t0, t1))
    return (c0, c1)


def fp12_sqr(a: Fp12) -> Fp12:
    return fp12_mul(a, a)


def fp12_conj(a: Fp12) -> Fp12:
    """a^(p^6): the nontrivial automorphism of Fp12/Fp6."""
    return (a[0], fp6_neg(a[1]))


def fp12_inv(a: Fp12) -> Fp12:
    a0, a1 = a
    norm = fp6_sub(fp6_sqr(a0), fp6_mul_by_v(fp6_sqr(a1)))
    ninv = fp6_inv(norm)
    return (fp6_mul(a0, ninv), fp6_mul(fp6_neg(a1), ninv))


def fp12_pow(a: Fp12, e: int) -> Fp12:
    if e < 0:
        return fp12_pow(fp12_inv(a), -e)
    result = FP12_ONE
    base = a
    while e > 0:
        if e & 1:
            result = fp12_mul(result, base)
        base = fp12_sqr(base)
        e >>= 1
    return result


# Frobenius p^2 on Fp12: Fp2 coefficients are fixed; basis element v^i w^j
# picks up xi^((p^2-1)(2i+j)/6), a 6th root of unity in Fp.


def _compute_gamma2() -> int:
    g = fp2_pow(XI, (P * P - 1) // 6)
    assert g[1] == 0, "xi^((p^2-1)/6) expected in Fp"
    return g[0]


_GAMMA2 = _compute_gamma2()
_GAMMA2_POWERS = [pow(_GAMMA2, k, P) for k in range(6)]


def fp12_frob2(a: Fp12) -> Fp12:
    """a^(p^2)."""
    (c00, c01, c02), (c10, c11, c12) = a
    g = _GAMMA2_POWERS
    return (
        (fp2_muls(c00, g[0]), fp2_muls(c01, g[2]), fp2_muls(c02, g[4])),
        (fp2_muls(c10, g[1]), fp2_muls(c11, g[3]), fp2_muls(c12, g[5])),
    )


#: Hard-part exponent of the final exponentiation: (p^4 - p^2 + 1) / r
FINAL_EXP_HARD = (P**4 - P**2 + 1) // R


def final_exponentiation(f: Fp12) -> Fp12:
    """f^((p^12-1)/r) — easy part via Frobenius, hard part naive pow."""
    # easy part: f^(p^6 - 1) then ^(p^2 + 1)
    t = fp12_mul(fp12_conj(f), fp12_inv(f))
    t = fp12_mul(fp12_frob2(t), t)
    # hard part (naive; optimized x-chain lives in the JAX path)
    return fp12_pow(t, FINAL_EXP_HARD)


# ---------------------------------------------------------------------------
# Generic short-Weierstrass affine arithmetic, parameterized by field ops.
# ---------------------------------------------------------------------------


class _Field:
    """Field op bundle so one EC implementation covers Fp, Fp2 and Fp12."""

    def __init__(self, add, sub, mul, sqr, inv, neg, zero, one, muls):
        self.add, self.sub, self.mul, self.sqr = add, sub, mul, sqr
        self.inv, self.neg, self.zero, self.one = inv, neg, zero, one
        self.muls = muls  # multiply by small int


FP_OPS = _Field(
    add=lambda a, b: (a + b) % P,
    sub=lambda a, b: (a - b) % P,
    mul=lambda a, b: a * b % P,
    sqr=lambda a: a * a % P,
    inv=fp_inv,
    neg=lambda a: (-a) % P,
    zero=0,
    one=1,
    muls=lambda a, s: a * s % P,
)

FP2_OPS = _Field(
    add=fp2_add,
    sub=fp2_sub,
    mul=fp2_mul,
    sqr=fp2_sqr,
    inv=fp2_inv,
    neg=fp2_neg,
    zero=FP2_ZERO,
    one=FP2_ONE,
    muls=fp2_muls,
)

FP12_OPS = _Field(
    add=fp12_add,
    sub=fp12_sub,
    mul=fp12_mul,
    sqr=fp12_sqr,
    inv=fp12_inv,
    neg=lambda a: (fp6_neg(a[0]), fp6_neg(a[1])),
    zero=FP12_ZERO,
    one=FP12_ONE,
    muls=lambda a, s: fp12_mul(a, ((( s % P, 0), FP2_ZERO, FP2_ZERO), FP6_ZERO)),
)


def ec_add(F: _Field, p1, p2):
    if p1 is None:
        return p2
    if p2 is None:
        return p1
    x1, y1 = p1
    x2, y2 = p2
    if x1 == x2:
        if y1 == y2:
            return ec_double(F, p1)
        return None
    lam = F.mul(F.sub(y2, y1), F.inv(F.sub(x2, x1)))
    x3 = F.sub(F.sub(F.sqr(lam), x1), x2)
    y3 = F.sub(F.mul(lam, F.sub(x1, x3)), y1)
    return (x3, y3)


def ec_double(F: _Field, p1):
    if p1 is None:
        return None
    x1, y1 = p1
    if y1 == F.zero:
        return None
    lam = F.mul(F.muls(F.sqr(x1), 3), F.inv(F.muls(y1, 2)))
    x3 = F.sub(F.sqr(lam), F.muls(x1, 2))
    y3 = F.sub(F.mul(lam, F.sub(x1, x3)), y1)
    return (x3, y3)


def ec_neg(F: _Field, p1):
    if p1 is None:
        return None
    return (p1[0], F.neg(p1[1]))


def ec_mul(F: _Field, p1, k: int):
    if k < 0:
        return ec_mul(F, ec_neg(F, p1), -k)
    result = None
    addend = p1
    while k > 0:
        if k & 1:
            result = ec_add(F, result, addend)
        addend = ec_double(F, addend)
        k >>= 1
    return result


def ec_is_on_curve(F: _Field, p1, b) -> bool:
    if p1 is None:
        return True
    x, y = p1
    return F.sqr(y) == F.add(F.mul(F.sqr(x), x), b)


# G1 convenience wrappers -----------------------------------------------------

def g1_add(p1, p2):
    return ec_add(FP_OPS, p1, p2)


def g1_mul(p1, k: int):
    return ec_mul(FP_OPS, p1, k)


def g1_neg(p1):
    return ec_neg(FP_OPS, p1)


def g1_is_on_curve(p1) -> bool:
    return ec_is_on_curve(FP_OPS, p1, B1)


def g2_add(p1, p2):
    return ec_add(FP2_OPS, p1, p2)


def g2_mul(p1, k: int):
    return ec_mul(FP2_OPS, p1, k)


def g2_neg(p1):
    return ec_neg(FP2_OPS, p1)


def g2_is_on_curve(p1) -> bool:
    return ec_is_on_curve(FP2_OPS, p1, B2)


# ---------------------------------------------------------------------------
# Twist / untwist and the pairing.
# ---------------------------------------------------------------------------

# Untwist E'(Fp2) -> E(Fp12): (x', y') -> (x'/w^2, y'/w^3), w^6 = xi.
# 1/w^2 = v^2 w^0 / xi ... compute the two constant Fp12 factors once.


def _fp2_to_fp12(a: Fp2) -> Fp12:
    return ((a, FP2_ZERO, FP2_ZERO), FP6_ZERO)


_W = (FP6_ZERO, FP6_ONE)  # w
_W2_INV = fp12_inv(fp12_mul(_W, _W))
_W3_INV = fp12_inv(fp12_mul(fp12_mul(_W, _W), _W))


def untwist(q):
    """Map a G2 (twist) point to E(Fp12)."""
    if q is None:
        return None
    x, y = q
    return (
        fp12_mul(_fp2_to_fp12(x), _W2_INV),
        fp12_mul(_fp2_to_fp12(y), _W3_INV),
    )


def _line(F: _Field, a, b, px, py):
    """Evaluate the line through a,b (or tangent if a==b) at (px, py).

    Points live on E(Fp12); returns an Fp12 value. Handles the vertical
    cases exactly (needed only at the very last add of the Miller loop in
    degenerate situations; cheap insurance in a reference impl).
    """
    xa, ya = a
    xb, yb = b
    if xa == xb and ya != yb:
        # vertical line x - xa
        return F.sub(px, xa)
    if a == b:
        lam = F.mul(F.muls(F.sqr(xa), 3), F.inv(F.muls(ya, 2)))
    else:
        lam = F.mul(F.sub(yb, ya), F.inv(F.sub(xb, xa)))
    # l(P) = (py - ya) - lam (px - xa)
    return F.sub(F.sub(py, ya), F.mul(lam, F.sub(px, xa)))


def miller_loop(p_g1, q_g2) -> Fp12:
    """Optimal ate Miller loop f_{|x|,Q}(P) with the final conjugation for x<0.

    Reference behavior: kyber `Pairing` interface (key/curve.go:12); this is
    the standard BLS12 optimal-ate construction, kept deliberately naive
    (affine + generic Fp12 lines) for auditability.
    """
    if p_g1 is None or q_g2 is None:
        return FP12_ONE
    F = FP12_OPS
    qq = untwist(q_g2)
    px = _fp2_to_fp12((p_g1[0], 0))
    py = _fp2_to_fp12((p_g1[1], 0))
    t = qq
    f = FP12_ONE
    e = -X_PARAM  # positive loop count
    bits = bin(e)[3:]  # skip the leading 1
    for bit in bits:
        f = F.mul(F.sqr(f), _line(F, t, t, px, py))
        t = ec_double(F, t)
        if bit == "1":
            f = F.mul(f, _line(F, t, qq, px, py))
            t = ec_add(F, t, qq)
    # x < 0: conjugate (the (p^6-1) factor of the final exp makes
    # conjugation equivalent to inversion)
    return fp12_conj(f)


def pairing(p_g1, q_g2) -> Fp12:
    """Full pairing e(P, Q) with final exponentiation."""
    return final_exponentiation(miller_loop(p_g1, q_g2))


def multi_pairing(pairs) -> Fp12:
    """prod e(Pi, Qi) sharing one final exponentiation."""
    f = FP12_ONE
    for p_g1, q_g2 in pairs:
        f = fp12_mul(f, miller_loop(p_g1, q_g2))
    return final_exponentiation(f)


# ---------------------------------------------------------------------------
# G2 cofactor (derived, then verified in selfcheck()).
# ---------------------------------------------------------------------------


def _derive_twist_order() -> int:
    """#E'(Fp2) for the M-twist, derived from CM theory and verified on points."""
    t = X_PARAM + 1  # trace of E/Fp
    f2 = (4 * P - t * t) // 3
    f = _isqrt(f2)
    assert f * f == f2, "4p - t^2 must be -3 f^2 for CM discriminant -3"
    t2 = t * t - 2 * P  # trace of E/Fp2
    g = t * f  # t2^2 - 4p^2 = -3 g^2
    assert t2 * t2 - 4 * P * P == -3 * g * g
    candidates = [
        P * P + 1 - (t2 + 3 * g) // 2,
        P * P + 1 - (t2 - 3 * g) // 2,
        P * P + 1 + t2,
    ]
    # Pick the candidate that annihilates an actual twist point and is
    # divisible by r.
    pt = _twist_point_from_x(5)
    for n in candidates:
        if n % R == 0 and ec_mul(FP2_OPS, pt, n) is None:
            return n
    raise AssertionError("no valid twist order found")


def _isqrt(n: int) -> int:
    import math

    return math.isqrt(n)


def _twist_point_from_x(start_x: int):
    """Find some point on E'(Fp2) by incrementing x (test helper)."""
    x0 = start_x
    while True:
        x: Fp2 = (x0, 1)
        rhs = fp2_add(fp2_mul(fp2_sqr(x), x), B2)
        y = fp2_sqrt(rhs)
        if y is not None:
            return (x, y)
        x0 += 1


G2_ORDER = _derive_twist_order()
H2 = G2_ORDER // R  # G2 cofactor


def g1_clear_cofactor(p):
    return ec_mul(FP_OPS, p, H1)


# -- psi endomorphism + fast G2 cofactor clearing ---------------------------
#
# psi = twist ∘ frobenius ∘ untwist maps the twist to itself:
# psi(x, y) = (cx * conj(x), cy * conj(y)).  The constants fall out of the
# twist embedding: untwist multiplies coordinates by 1/w^2, 1/w^3, and
# Frobenius on Fp12 is a -> a^p, so cx = (1/w^2)^p / (1/w^2) restricted to
# Fp2 (same for cy with w^3).  No magic tables — derived and then verified
# in selfcheck().


def _psi_const(a: Fp12) -> Fp2:
    f = fp12_mul(fp12_pow(a, P), fp12_inv(a))
    (c00, c01, c02), c1 = f
    assert c01 == FP2_ZERO and c02 == FP2_ZERO and c1 == FP6_ZERO, (
        "psi constant does not lie in Fp2"
    )
    return c00


PSI_CX = _psi_const(_W2_INV)
PSI_CY = _psi_const(_W3_INV)


def g2_psi(p):
    if p is None:
        return None
    x, y = p
    return (fp2_mul(PSI_CX, fp2_conj(x)), fp2_mul(PSI_CY, fp2_conj(y)))


def _g2_mul_x(p):
    """[x]P for the (negative) BLS parameter x."""
    return g2_neg(ec_mul(FP2_OPS, p, -X_PARAM))


def g2_clear_cofactor(p):
    """Budroni–Pintore fast clearing:
    h_eff·P = [x^2-x-1]·P + [x-1]·psi(P) + psi(psi([2]P)).

    Replaces multiplication by the 507-bit cofactor H2 with three 64-bit
    ladders + two psi applications; the device kernel
    (drand_tpu/ops/h2c.py) implements the identical formula, so host and
    device hashes agree by construction.
    """
    xp = _g2_mul_x(p)                  # [x]P
    x2p = _g2_mul_x(xp)                # [x^2]P
    part1 = g2_add(x2p, g2_neg(g2_add(xp, p)))
    psip = g2_psi(p)
    part2 = g2_add(_g2_mul_x(psip), g2_neg(psip))
    part3 = g2_psi(g2_psi(ec_double(FP2_OPS, p)))
    return g2_add(g2_add(part1, part2), part3)


def g2_clear_cofactor_mulh(p):
    """Textbook clearing by the full cofactor (selfcheck cross-check)."""
    return ec_mul(FP2_OPS, p, H2)


# ---------------------------------------------------------------------------
# hash-to-field / map-to-curve (Shallue–van de Woestijne) / hash-to-curve.
#
# We use the SVDW map (RFC 9380 §6.6.1) rather than the SSWU+isogeny map:
# it needs no 3-isogeny constant tables and works directly on j=0 curves.
# The resulting hash differs from the ciphersuite the reference's kyber fork
# used, which is fine: the framework is self-consistent, and the map is
# uniform + constant-shape (TPU-friendly). DSTs below pin our ciphersuite.
# ---------------------------------------------------------------------------

DST_G2 = b"DRANDTPU-V01-CS01-BLS12381G2_XMD:SHA-256_SVDW_RO_"
DST_G1 = b"DRANDTPU-V01-CS01-BLS12381G1_XMD:SHA-256_SVDW_RO_"


def expand_message_xmd(msg: bytes, dst: bytes, len_in_bytes: int) -> bytes:
    """expand_message_xmd with SHA-256 (RFC 9380 §5.3.1)."""
    b_in_bytes = 32
    s_in_bytes = 64
    ell = -(-len_in_bytes // b_in_bytes)
    assert ell <= 255 and len(dst) <= 255
    dst_prime = dst + bytes([len(dst)])
    z_pad = bytes(s_in_bytes)
    l_i_b_str = len_in_bytes.to_bytes(2, "big")
    msg_prime = z_pad + msg + l_i_b_str + b"\x00" + dst_prime
    b0 = hashlib.sha256(msg_prime).digest()
    bvals = [hashlib.sha256(b0 + b"\x01" + dst_prime).digest()]
    for i in range(2, ell + 1):
        prev = bvals[-1]
        xored = bytes(x ^ y for x, y in zip(b0, prev))
        bvals.append(hashlib.sha256(xored + bytes([i]) + dst_prime).digest())
    return b"".join(bvals)[:len_in_bytes]


_L = 64  # bytes per field element draw: ceil((381 + 128) / 8)


def hash_to_field_fp(msg: bytes, count: int, dst: bytes) -> list:
    uniform = expand_message_xmd(msg, dst, count * _L)
    return [
        int.from_bytes(uniform[i * _L : (i + 1) * _L], "big") % P
        for i in range(count)
    ]


def hash_to_field_fp2(msg: bytes, count: int, dst: bytes) -> list:
    uniform = expand_message_xmd(msg, dst, count * 2 * _L)
    out = []
    for i in range(count):
        base = i * 2 * _L
        c0 = int.from_bytes(uniform[base : base + _L], "big") % P
        c1 = int.from_bytes(uniform[base + _L : base + 2 * _L], "big") % P
        out.append((c0, c1))
    return out


def _find_svdw_z(F: _Field, b, is_square, from_small):
    """Smallest-magnitude Z satisfying the SVDW sanity conditions."""

    def g(x):
        return F.add(F.mul(F.sqr(x), x), b)

    half = F.inv(F.muls(F.one, 2))
    for mag in range(1, 200):
        for z in from_small(mag):
            gz = g(z)
            if gz == F.zero:
                continue
            h = F.muls(F.sqr(z), 3)  # 3Z^2 (+4A, A=0)
            if h == F.zero:
                continue
            # need sqrt(-g(Z) * (3Z^2)) to exist
            if not is_square(F.neg(F.mul(gz, h))):
                continue
            # need g(Z) or g(-Z/2) square (ensures the map is total)
            neg_half_z = F.neg(F.mul(z, half))
            if is_square(gz) or is_square(g(neg_half_z)):
                return z
    raise AssertionError("no SVDW Z found")


def _fp_candidates(mag):
    yield mag % P
    yield (-mag) % P


def _fp2_candidates(mag):
    for a in range(0, mag + 1):
        for b in range(0, mag + 1):
            if max(a, b) != mag:
                continue
            for sa in (1, -1):
                for sb in (1, -1):
                    yield ((sa * a) % P, (sb * b) % P)


class _SVDW:
    """Precomputed Shallue–van de Woestijne map for one curve."""

    def __init__(self, F: _Field, b, is_square, sqrt, sgn0, z):
        self.F, self.b = F, b
        self.is_square, self.sqrt, self.sgn0 = is_square, sqrt, sgn0
        self.Z = z
        gz = F.add(F.mul(F.sqr(z), z), b)
        self.c1 = gz
        self.c2 = F.neg(F.mul(z, F.inv(F.muls(F.one, 2))))  # -Z/2
        h = F.muls(F.sqr(z), 3)  # 3Z^2
        c3 = sqrt(F.neg(F.mul(gz, h)))
        assert c3 is not None
        if sgn0(c3) == 1:
            c3 = F.neg(c3)
        self.c3 = c3
        self.c4 = F.mul(F.neg(F.muls(gz, 4)), F.inv(h))  # -4 g(Z) / (3Z^2)

    def map_to_curve(self, u):
        F, b = self.F, self.b

        def g(x):
            return F.add(F.mul(F.sqr(x), x), b)

        def inv0(x):
            return F.zero if x == F.zero else F.inv(x)

        tv1 = F.mul(F.sqr(u), self.c1)
        tv2 = F.add(F.one, tv1)
        tv1 = F.sub(F.one, tv1)
        tv3 = inv0(F.mul(tv1, tv2))
        tv4 = F.mul(F.mul(F.mul(u, tv1), tv3), self.c3)
        x1 = F.sub(self.c2, tv4)
        x2 = F.add(self.c2, tv4)
        x3 = F.add(F.mul(F.sqr(F.mul(F.sqr(tv2), tv3)), self.c4), self.Z)
        if self.is_square(g(x1)):
            x = x1
        elif self.is_square(g(x2)):
            x = x2
        else:
            x = x3
        y = self.sqrt(g(x))
        assert y is not None, "SVDW: g(x) must be square by construction"
        if self.sgn0(u) != self.sgn0(y):
            y = F.neg(y)
        return (x, y)


SVDW_G1 = _SVDW(
    FP_OPS, B1, fp_is_square, fp_sqrt, fp_sgn0,
    _find_svdw_z(FP_OPS, B1, fp_is_square, _fp_candidates),
)
SVDW_G2 = _SVDW(
    FP2_OPS, B2, fp2_is_square, fp2_sqrt, fp2_sgn0,
    _find_svdw_z(FP2_OPS, B2, fp2_is_square, _fp2_candidates),
)


def hash_to_g2(msg: bytes, dst: bytes = DST_G2):
    """Hash arbitrary bytes to a point of order r in G2 (random oracle)."""
    u0, u1 = hash_to_field_fp2(msg, 2, dst)
    q0 = SVDW_G2.map_to_curve(u0)
    q1 = SVDW_G2.map_to_curve(u1)
    return g2_clear_cofactor(g2_add(q0, q1))


def hash_to_g1(msg: bytes, dst: bytes = DST_G1):
    u0, u1 = hash_to_field_fp(msg, 2, dst)
    q0 = SVDW_G1.map_to_curve(u0)
    q1 = SVDW_G1.map_to_curve(u1)
    return g1_clear_cofactor(g1_add(q0, q1))


# ---------------------------------------------------------------------------
# Serialization: 48-byte G1 / 96-byte G2 compressed (flags in top 3 bits).
# ---------------------------------------------------------------------------

_FLAG_COMPRESSED = 0x80
_FLAG_INFINITY = 0x40
_FLAG_SIGN = 0x20


def g1_to_bytes(p) -> bytes:
    if p is None:
        out = bytearray(48)
        out[0] = _FLAG_COMPRESSED | _FLAG_INFINITY
        return bytes(out)
    x, y = p
    out = bytearray(x.to_bytes(48, "big"))
    out[0] |= _FLAG_COMPRESSED
    if y > (P - 1) // 2:
        out[0] |= _FLAG_SIGN
    return bytes(out)


def g1_from_bytes(data: bytes, subgroup_check: bool = True):
    if len(data) != 48:
        raise ValueError("G1 compressed point must be 48 bytes")
    flags = data[0]
    if not flags & _FLAG_COMPRESSED:
        raise ValueError("only compressed encoding supported")
    if flags & _FLAG_INFINITY:
        if any(data[1:]) or flags & ~( _FLAG_COMPRESSED | _FLAG_INFINITY):
            raise ValueError("malformed infinity encoding")
        return None
    x = int.from_bytes(bytes([flags & 0x1F]) + data[1:], "big")
    if x >= P:
        raise ValueError("x out of range")
    y = fp_sqrt((x * x % P * x + B1) % P)
    if y is None:
        raise ValueError("x not on curve")
    if bool(flags & _FLAG_SIGN) != (y > (P - 1) // 2):
        y = P - y
    point = (x, y)
    if subgroup_check and g1_mul(point, R) is not None:
        raise ValueError("point not in r-torsion subgroup")
    return point


def g2_to_bytes(p) -> bytes:
    if p is None:
        out = bytearray(96)
        out[0] = _FLAG_COMPRESSED | _FLAG_INFINITY
        return bytes(out)
    (x0, x1), (y0, y1) = p
    out = bytearray(x1.to_bytes(48, "big") + x0.to_bytes(48, "big"))
    out[0] |= _FLAG_COMPRESSED
    if _fp2_is_larger((y0, y1)):
        out[0] |= _FLAG_SIGN
    return bytes(out)


def _fp2_is_larger(y: Fp2) -> bool:
    """Lexicographically-largest test on (c1, c0)."""
    neg = fp2_neg(y)
    return (y[1], y[0]) > (neg[1], neg[0])


def g2_from_bytes(data: bytes, subgroup_check: bool = True):
    if len(data) != 96:
        raise ValueError("G2 compressed point must be 96 bytes")
    flags = data[0]
    if not flags & _FLAG_COMPRESSED:
        raise ValueError("only compressed encoding supported")
    if flags & _FLAG_INFINITY:
        if any(data[1:]) or flags & ~(_FLAG_COMPRESSED | _FLAG_INFINITY):
            raise ValueError("malformed infinity encoding")
        return None
    x1 = int.from_bytes(bytes([flags & 0x1F]) + data[1:48], "big")
    x0 = int.from_bytes(data[48:96], "big")
    if x0 >= P or x1 >= P:
        raise ValueError("x out of range")
    x: Fp2 = (x0, x1)
    y = fp2_sqrt(fp2_add(fp2_mul(fp2_sqr(x), x), B2))
    if y is None:
        raise ValueError("x not on curve")
    if bool(flags & _FLAG_SIGN) != _fp2_is_larger(y):
        y = fp2_neg(y)
    point = (x, y)
    if subgroup_check and g2_mul(point, R) is not None:
        raise ValueError("point not in r-torsion subgroup")
    return point


# ---------------------------------------------------------------------------
# Self-check: run at import in tests (tests/test_refimpl.py) — validates all
# constants without external vectors.
# ---------------------------------------------------------------------------


def _miller_rabin(n: int, rounds: int = 24) -> bool:
    import random

    if n < 4:
        return n in (2, 3)
    d, s = n - 1, 0
    while d % 2 == 0:
        d //= 2
        s += 1
    rng = random.Random(0xD12A)
    for _ in range(rounds):
        a = rng.randrange(2, n - 1)
        x = pow(a, d, n)
        if x in (1, n - 1):
            continue
        for _ in range(s - 1):
            x = x * x % n
            if x == n - 1:
                break
        else:
            return False
    return True


def selfcheck() -> None:
    x = X_PARAM
    assert P == (x - 1) ** 2 * (x**4 - x**2 + 1) // 3 + x, "p/x mismatch"
    assert R == x**4 - x**2 + 1, "r/x mismatch"
    assert _miller_rabin(P), "p not prime"
    assert _miller_rabin(R), "r not prime"
    assert P % 4 == 3 and P % 6 == 1
    # u^2 = -1 must be a non-residue; xi = 1+u a non-residue in Fp2
    assert not fp_is_square(P - 1)
    assert not fp2_is_square(XI)
    # generators on curve, right order
    assert g1_is_on_curve(G1_GEN)
    assert g2_is_on_curve(G2_GEN)
    assert ec_mul(FP_OPS, G1_GEN, R) is None
    assert ec_mul(FP2_OPS, G2_GEN, R) is None
    assert (P + 1 - (x + 1)) == H1 * R, "G1 cofactor identity"
    assert G2_ORDER % R == 0
    # psi endomorphism: maps the twist to itself; acts as [p mod r] on the
    # r-torsion (so psi(G) = [x]G since p = x + (x-1)^2(x^4-x^2+1)/3 and
    # p ≡ t - 1 ≡ x mod r)
    psig = g2_psi(G2_GEN)
    assert g2_is_on_curve(psig), "psi leaves the twist"
    assert psig == g2_mul(G2_GEN, x % R), "psi eigenvalue"
    # fast cofactor clearing lands in the r-torsion and matches the
    # endomorphism decomposition on subgroup points
    q = SVDW_G2.map_to_curve(hash_to_field_fp2(b"selfcheck", 1, DST_G2)[0])
    fast = g2_clear_cofactor(q)
    assert g2_is_on_curve(fast)
    assert ec_mul(FP2_OPS, fast, R) is None, "fast clearing not in subgroup"
    h_eff_mod_r = ((x * x - x - 1) + (x - 1) * (P % R) + 2 * P * P) % R
    assert g2_clear_cofactor(G2_GEN) == g2_mul(G2_GEN, h_eff_mod_r), (
        "fast clearing disagrees with [h_eff] on subgroup points"
    )
    assert h_eff_mod_r != 0, "degenerate effective cofactor"
