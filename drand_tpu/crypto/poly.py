"""Secret-sharing polynomials over the BLS12-381 scalar field (host side).

Equivalent of kyber's ``share/poly`` module, which the reference uses for
DKG shares and threshold recovery (`share.PriShare`/`share.PubPoly`,
/root/reference/key/keys.go:164-175).  Scalar arithmetic is plain python
ints mod r — committee sizes are <= ~1000, so this is never a hot path;
the hot exponentiations/MSMs live on the device.
"""

from __future__ import annotations

import secrets
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from drand_tpu.crypto import refimpl as ref

R = ref.R


def rand_scalar(rng: Optional[Callable[[int], bytes]] = None) -> int:
    """Uniform nonzero scalar; rng(nbytes) may inject external entropy."""
    reader = rng or secrets.token_bytes
    while True:
        v = int.from_bytes(reader(48), "big") % R
        if v != 0:
            return v


@dataclass(frozen=True)
class PriShare:
    """One private share: the polynomial evaluated at x = index + 1."""

    index: int
    value: int


class PriPoly:
    """Secret-sharing polynomial f of degree t-1 with f(0) = secret."""

    def __init__(self, coeffs: Sequence[int]):
        assert len(coeffs) >= 1
        self.coeffs = [c % R for c in coeffs]

    @classmethod
    def random(cls, t: int, secret: Optional[int] = None,
               rng: Optional[Callable[[int], bytes]] = None) -> "PriPoly":
        coeffs = [rand_scalar(rng) for _ in range(t)]
        if secret is not None:
            coeffs[0] = secret % R
        return cls(coeffs)

    @property
    def threshold(self) -> int:
        return len(self.coeffs)

    def secret(self) -> int:
        return self.coeffs[0]

    def eval(self, index: int) -> PriShare:
        x = index + 1  # x = 0 is the secret; shares start at 1
        acc = 0
        for c in reversed(self.coeffs):
            acc = (acc * x + c) % R
        return PriShare(index, acc)

    def shares(self, n: int) -> List[PriShare]:
        return [self.eval(i) for i in range(n)]

    def add(self, other: "PriPoly") -> "PriPoly":
        assert self.threshold == other.threshold
        return PriPoly([
            (a + b) % R for a, b in zip(self.coeffs, other.coeffs)
        ])

    def commit(self, base=None) -> "PubPoly":
        base = base if base is not None else ref.G1_GEN
        return PubPoly(
            [ref.g1_mul(base, c) for c in self.coeffs], base=base
        )


class PubPoly:
    """Public commitments F_j = base^{a_j} to a PriPoly's coefficients."""

    def __init__(self, commits: Sequence, base=None):
        self.commits = list(commits)
        self.base = base if base is not None else ref.G1_GEN

    @property
    def threshold(self) -> int:
        return len(self.commits)

    def commit(self):
        """The committed secret: base^{f(0)} — the distributed public key."""
        return self.commits[0]

    def eval(self, index: int):
        """base^{f(index+1)} via Horner in the exponent."""
        x = index + 1
        acc = None
        for c in reversed(self.commits):
            acc = ref.g1_add(ref.g1_mul(acc, x), c)
        return acc

    def add(self, other: "PubPoly") -> "PubPoly":
        assert self.threshold == other.threshold
        return PubPoly(
            [ref.g1_add(a, b)
             for a, b in zip(self.commits, other.commits)],
            base=self.base,
        )

    def __eq__(self, other) -> bool:
        return (
            isinstance(other, PubPoly)
            and self.base == other.base
            and self.commits == other.commits
        )


def lagrange_basis_at_zero(indices: Sequence[int]) -> Dict[int, int]:
    """lambda_i such that f(0) = sum_i lambda_i f(x_i), x_i = index + 1."""
    lambdas: Dict[int, int] = {}
    xs = [(i, i + 1) for i in indices]
    for i, xi in xs:
        num, den = 1, 1
        for j, xj in xs:
            if j == i:
                continue
            num = num * xj % R
            den = den * (xj - xi) % R
        lambdas[i] = num * pow(den, -1, R) % R
    return lambdas


def recover_secret(shares: Sequence[PriShare], t: int) -> int:
    """Lagrange-interpolate f(0) from any t shares (kyber RecoverSecret)."""
    if len(shares) < t:
        raise ValueError(f"need {t} shares, have {len(shares)}")
    use = list(shares)[:t]
    lam = lagrange_basis_at_zero([s.index for s in use])
    return sum(lam[s.index] * s.value for s in use) % R


def recover_commit_g2(points: Sequence[Tuple[int, object]], t: int):
    """Lagrange-combine G2 group elements (oracle path; device uses MSM).

    points: sequence of (index, G2 point).  Returns sum lambda_i * P_i.
    """
    if len(points) < t:
        raise ValueError(f"need {t} points, have {len(points)}")
    use = list(points)[:t]
    lam = lagrange_basis_at_zero([i for i, _ in use])
    acc = None
    for i, pt in use:
        acc = ref.g2_add(acc, ref.g2_mul(pt, lam[i]))
    return acc
