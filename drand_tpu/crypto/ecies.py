"""ECIES: ephemeral-static DH on G1 -> HKDF-SHA256 -> AES-256-GCM.

Mirrors /root/reference/ecies/ecies.go (Encrypt :28-79, Decrypt :84-119).
Used for (a) the private-randomness API and (b) encrypting DKG deal shares
to their recipients.

Wire format: 48-byte compressed ephemeral G1 point || 12-byte nonce ||
ciphertext+tag.
"""

from __future__ import annotations

import os

from cryptography.hazmat.primitives.ciphers.aead import AESGCM
from cryptography.hazmat.primitives.kdf.hkdf import HKDF
from cryptography.hazmat.primitives import hashes

from drand_tpu.crypto import refimpl as ref
from drand_tpu.crypto.poly import rand_scalar

NONCE_LEN = 12
KEY_LEN = 32


class EciesError(Exception):
    pass


def _derive_key(shared_point) -> bytes:
    return HKDF(
        algorithm=hashes.SHA256(),
        length=KEY_LEN,
        salt=None,
        info=b"drand-tpu-ecies-v1",
    ).derive(ref.g1_to_bytes(shared_point))


def encrypt(recipient_pub, plaintext: bytes,
            associated_data: bytes = b"") -> bytes:
    """Encrypt to a G1 public key."""
    eph = rand_scalar()
    r_point = ref.g1_mul(ref.G1_GEN, eph)
    shared = ref.g1_mul(recipient_pub, eph)
    key = _derive_key(shared)
    nonce = os.urandom(NONCE_LEN)
    ct = AESGCM(key).encrypt(nonce, plaintext, associated_data or None)
    return ref.g1_to_bytes(r_point) + nonce + ct


def decrypt(private_scalar: int, blob: bytes,
            associated_data: bytes = b"") -> bytes:
    """Decrypt with the recipient's secret scalar."""
    if len(blob) < 48 + NONCE_LEN + 16:
        raise EciesError("ciphertext too short")
    try:
        r_point = ref.g1_from_bytes(blob[:48])
    except ValueError as exc:
        raise EciesError(f"bad ephemeral point: {exc}") from exc
    if r_point is None:
        raise EciesError("identity ephemeral point rejected")
    nonce = blob[48 : 48 + NONCE_LEN]
    ct = blob[48 + NONCE_LEN :]
    shared = ref.g1_mul(r_point, private_scalar)
    key = _derive_key(shared)
    try:
        return AESGCM(key).decrypt(nonce, ct, associated_data or None)
    except Exception as exc:
        raise EciesError("decryption failed") from exc
