"""ECIES: ephemeral-static DH on G1 -> HKDF-SHA256 -> AES-256-GCM.

Mirrors /root/reference/ecies/ecies.go (Encrypt :28-79, Decrypt :84-119).
Used for (a) the private-randomness API and (b) encrypting DKG deal shares
to their recipients.

Wire format: 48-byte compressed ephemeral G1 point || 12-byte nonce ||
ciphertext+tag.
"""

from __future__ import annotations

import hashlib
import hmac
import os

try:  # preferred AEAD; absent on minimal containers
    from cryptography.hazmat.primitives.ciphers.aead import AESGCM
except ModuleNotFoundError:
    AESGCM = None

from drand_tpu.crypto import refimpl as ref
from drand_tpu.crypto.poly import rand_scalar

NONCE_LEN = 12
KEY_LEN = 32


class EciesError(Exception):
    pass


class _StdlibAEAD:
    """Fallback AEAD when `cryptography` is unavailable: SHA-256 counter
    keystream + truncated HMAC-SHA256 tag (encrypt-then-MAC over
    nonce || aad || ciphertext).  Same call shape as AESGCM but NOT
    wire-compatible with it — both peers must run the same fallback, so
    it only suits single-toolchain deployments like this container.
    """

    TAG_LEN = 16

    def __init__(self, key: bytes):
        self._enc_key = hashlib.sha256(b"enc" + key).digest()
        self._mac_key = hashlib.sha256(b"mac" + key).digest()

    def _keystream(self, nonce: bytes, n: int) -> bytes:
        out = b""
        ctr = 0
        while len(out) < n:
            out += hashlib.sha256(
                self._enc_key + nonce + ctr.to_bytes(4, "big")
            ).digest()
            ctr += 1
        return out[:n]

    def _tag(self, nonce: bytes, aad: bytes, ct: bytes) -> bytes:
        mac = hmac.new(self._mac_key, digestmod=hashlib.sha256)
        for part in (nonce, aad, ct):
            mac.update(len(part).to_bytes(8, "big"))
            mac.update(part)
        return mac.digest()[: self.TAG_LEN]

    def encrypt(self, nonce: bytes, data: bytes, aad) -> bytes:
        ks = self._keystream(nonce, len(data))
        ct = bytes(a ^ b for a, b in zip(data, ks))
        return ct + self._tag(nonce, aad or b"", ct)

    def decrypt(self, nonce: bytes, blob: bytes, aad) -> bytes:
        if len(blob) < self.TAG_LEN:
            raise EciesError("ciphertext too short")
        ct, tag = blob[: -self.TAG_LEN], blob[-self.TAG_LEN :]
        if not hmac.compare_digest(self._tag(nonce, aad or b"", ct), tag):
            raise EciesError("authentication failed")
        ks = self._keystream(nonce, len(ct))
        return bytes(a ^ b for a, b in zip(ct, ks))


_AEAD = AESGCM if AESGCM is not None else _StdlibAEAD

_warned_fallback = False


def _warn_fallback_once() -> None:
    """One-time operator warning when the stdlib AEAD fallback is live:
    its ciphertexts are NOT wire-compatible with AES-GCM, so a node
    running it can only exchange private randomness / DKG deal shares
    with peers on the same fallback.  Emitted at first use (the module
    import happens long before anyone knows ECIES will be exercised)."""
    global _warned_fallback
    if _warned_fallback or AESGCM is not None:
        return
    _warned_fallback = True
    from drand_tpu.utils.logging import get_logger

    get_logger("ecies").warning(
        "cryptography package unavailable: using the stdlib AEAD "
        "fallback, which is NOT wire-compatible with AES-GCM — every "
        "peer in the fleet must run the same fallback (install "
        "'cryptography' everywhere for mixed deployments)"
    )


def _hkdf_sha256(ikm: bytes, length: int, info: bytes) -> bytes:
    """RFC 5869 HKDF-SHA256 (salt = zeros) via stdlib hmac — bit-exact
    with the cryptography package's HKDF this module used before."""
    prk = hmac.new(b"\x00" * 32, ikm, hashlib.sha256).digest()
    okm = b""
    block = b""
    counter = 1
    while len(okm) < length:
        block = hmac.new(
            prk, block + info + bytes([counter]), hashlib.sha256
        ).digest()
        okm += block
        counter += 1
    return okm[:length]


def _derive_key(shared_point) -> bytes:
    return _hkdf_sha256(
        ref.g1_to_bytes(shared_point), KEY_LEN, b"drand-tpu-ecies-v1"
    )


def encrypt(recipient_pub, plaintext: bytes,
            associated_data: bytes = b"") -> bytes:
    """Encrypt to a G1 public key."""
    _warn_fallback_once()
    eph = rand_scalar()
    r_point = ref.g1_mul(ref.G1_GEN, eph)
    shared = ref.g1_mul(recipient_pub, eph)
    key = _derive_key(shared)
    nonce = os.urandom(NONCE_LEN)
    ct = _AEAD(key).encrypt(nonce, plaintext, associated_data or None)
    return ref.g1_to_bytes(r_point) + nonce + ct


def decrypt(private_scalar: int, blob: bytes,
            associated_data: bytes = b"") -> bytes:
    """Decrypt with the recipient's secret scalar."""
    _warn_fallback_once()
    if len(blob) < 48 + NONCE_LEN + 16:
        raise EciesError("ciphertext too short")
    try:
        r_point = ref.g1_from_bytes(blob[:48])
    except ValueError as exc:
        raise EciesError(f"bad ephemeral point: {exc}") from exc
    if r_point is None:
        raise EciesError("identity ephemeral point rejected")
    nonce = blob[48 : 48 + NONCE_LEN]
    ct = blob[48 + NONCE_LEN :]
    shared = ref.g1_mul(r_point, private_scalar)
    key = _derive_key(shared)
    try:
        return _AEAD(key).decrypt(nonce, ct, associated_data or None)
    except Exception as exc:
        raise EciesError("decryption failed") from exc
