"""Performance observatory: runtime baselines + dispatch-budget sentinel.

The repo's perf story so far lives in hand-committed bench artifacts and
test-only assertions; nothing *running* notices when the hot path gets
slower.  This module turns the existing kernel spans and round stages
into continuously tracked, regression-detecting telemetry:

* `StreamingQuantiles` — fixed-memory streaming p50/p95/p99 (one P²
  marker set per quantile, 15 floats total) so a node can keep latency
  baselines for every stage and kernel forever without unbounded
  buffers.
* `PerfObservatory` — per-stage and per-kernel latency registries fed
  from the span sink (`beacon.*`, `dkg.*`, `gateway.*`) and from
  `obs.kernels` dispatch hooks, plus per-round dispatch accounting.
  The **dispatch-budget sentinel** makes the PR-5 invariant ("honest
  optimistic round <= 2 device dispatches") a production alarm: an
  honest round over budget edge-triggers a `perf.dispatch_budget`
  flight event and bumps `drand_perf_dispatch_budget_exceeded_total`;
  the alarm clears on the next honest round back within budget.  A
  kernel dispatch far above its own steady-state p50 *after* warmup is
  counted as a suspected jit recompile; several inside one window is a
  recompile storm.
* Bench lineage + diff: `lineage()` stamps artifacts with provenance
  (git rev, backend/device, env knobs, degraded flags),
  `classify_failure()` keeps the bench retry path honest about
  infra-vs-code degradation, and `extract_stages()`/`diff_stages()`
  power `cli bench diff` — stage-by-stage comparison with tolerance,
  where dispatch-count regressions fail regardless of tolerance
  (they are backend-independent).

Everything here is stdlib-only so the protocol import path stays
feather-weight; the snapshot is served at `GET /v1/perf`, folded into
`/v1/status`, aggregated by `obs.fleet` and diagnosed by `cli doctor`.
"""

from __future__ import annotations

import bisect
import os
import platform
import subprocess
import threading
import time
from collections import deque
from typing import Any, Callable, Deque, Dict, List, Optional

from drand_tpu.obs import flight
from drand_tpu.utils import metrics

PERF_SCHEMA = "drand-tpu.perf.v1"
LINEAGE_SCHEMA = "drand-tpu.lineage.v1"

#: Closed degraded_reason vocabulary: is a degraded artifact the
#: environment's fault or ours?  `lineage()` validates against it at
#: construction and drand-lint's `reg-degraded-reason` rule holds every
#: literal in the tree to it — a third value would otherwise slip past
#: the bench-lineage coherence tests unvalidated.
DEGRADED_REASONS = ("infra", "code")

#: honest optimistic round budget: one fused partial-admit-free finalize
#: dispatch + one sign dispatch (PR 5's invariant)
DISPATCH_BUDGET = 2

_QUANTILES = (0.5, 0.95, 0.99)


# -- streaming quantiles (P^2 algorithm, Jain & Chlamtac 1985) ------------


class _P2:
    """Single-quantile P² estimator: five markers, O(1) per observation.

    Exact until five observations; afterwards the middle marker tracks
    the target quantile by piecewise-parabolic adjustment."""

    __slots__ = ("p", "q", "n", "npos", "dn", "count")

    def __init__(self, p: float) -> None:
        self.p = p
        self.q: List[float] = []            # marker heights
        self.n: List[int] = [0, 1, 2, 3, 4]  # marker positions (0-based)
        self.npos: List[float] = [0.0, 2 * p, 4 * p, 2 + 2 * p, 4.0]
        self.dn: List[float] = [0.0, p / 2, p, (1 + p) / 2, 1.0]
        self.count = 0

    def observe(self, x: float) -> None:
        self.count += 1
        if len(self.q) < 5:
            bisect.insort(self.q, x)
            return
        q, n, npos = self.q, self.n, self.npos
        if x < q[0]:
            q[0] = x
            k = 0
        elif x >= q[4]:
            q[4] = x
            k = 3
        else:
            k = 0
            for i in range(1, 4):
                if x >= q[i]:
                    k = i
        for i in range(k + 1, 5):
            n[i] += 1
        for i in range(5):
            npos[i] += self.dn[i]
        for i in (1, 2, 3):
            d = npos[i] - n[i]
            if ((d >= 1 and n[i + 1] - n[i] > 1)
                    or (d <= -1 and n[i - 1] - n[i] < -1)):
                step = 1 if d > 0 else -1
                qn = self._parabolic(i, step)
                if not (q[i - 1] < qn < q[i + 1]):
                    qn = self._linear(i, step)
                q[i] = qn
                n[i] += step

    def _parabolic(self, i: int, d: int) -> float:
        q, n = self.q, self.n
        return q[i] + d / (n[i + 1] - n[i - 1]) * (
            (n[i] - n[i - 1] + d) * (q[i + 1] - q[i]) / (n[i + 1] - n[i])
            + (n[i + 1] - n[i] - d) * (q[i] - q[i - 1]) / (n[i] - n[i - 1])
        )

    def _linear(self, i: int, d: int) -> float:
        q, n = self.q, self.n
        return q[i] + d * (q[i + d] - q[i]) / (n[i + d] - n[i])

    def value(self) -> Optional[float]:
        if not self.q:
            return None
        if self.count < 5:
            # exact small-sample quantile (nearest-rank interpolation)
            s = self.q
            idx = self.p * (len(s) - 1)
            lo = int(idx)
            hi = min(lo + 1, len(s) - 1)
            return s[lo] + (s[hi] - s[lo]) * (idx - lo)
        return self.q[2]

    def marker_count(self) -> int:
        return len(self.q) + len(self.n) + len(self.npos)


def _round6(v: Optional[float]) -> Optional[float]:
    return None if v is None else round(v, 6)


class StreamingQuantiles:
    """p50/p95/p99 + count/min/max/mean over a stream, fixed memory."""

    __slots__ = ("_est", "count", "vmin", "vmax", "total", "last")

    def __init__(self) -> None:
        self._est: Dict[float, _P2] = {p: _P2(p) for p in _QUANTILES}
        self.count = 0
        self.vmin: Optional[float] = None
        self.vmax: Optional[float] = None
        self.total = 0.0
        self.last: Optional[float] = None

    def observe(self, x: float) -> None:
        x = float(x)
        self.count += 1
        self.total += x
        self.last = x
        self.vmin = x if self.vmin is None else min(self.vmin, x)
        self.vmax = x if self.vmax is None else max(self.vmax, x)
        for est in self._est.values():
            est.observe(x)

    def quantile(self, p: float) -> Optional[float]:
        est = self._est.get(p)
        return est.value() if est is not None else None

    def marker_count(self) -> int:
        """Total floats held by the quantile markers — pinned by a test
        so the estimator provably stays fixed-memory."""
        return sum(est.marker_count() for est in self._est.values())

    def snapshot(self) -> Dict[str, Any]:
        if self.count == 0:
            return {"count": 0}
        return {
            "count": self.count,
            "p50": _round6(self.quantile(0.5)),
            "p95": _round6(self.quantile(0.95)),
            "p99": _round6(self.quantile(0.99)),
            "min": _round6(self.vmin),
            "max": _round6(self.vmax),
            "mean": _round6(self.total / self.count),
            "last": _round6(self.last),
        }


# -- the observatory ------------------------------------------------------


class PerfObservatory:
    """Per-stage/per-kernel latency baselines + dispatch-budget sentinel.

    Edge-trigger semantics mirror `obs.slo`: the flight-recorder page
    fires once on the False->True transition of each alarm and once
    again on recovery; the `*_total` counters count every offending
    event.  All entry points take an optional timestamp so tests drive
    the sentinel on a FakeClock."""

    def __init__(self, *, budget: int = DISPATCH_BUDGET,
                 now_fn: Callable[[], float] = time.time,
                 recorder: Optional[flight.FlightRecorder] = None,
                 warmup_dispatches: int = 3,
                 recompile_factor: float = 20.0,
                 recompile_min_seconds: float = 0.05,
                 storm_threshold: int = 3,
                 storm_window: float = 60.0) -> None:
        self.budget = budget
        self.now_fn = now_fn
        self.recorder = recorder  # None -> the process flight recorder
        self.warmup_dispatches = warmup_dispatches
        self.recompile_factor = recompile_factor
        self.recompile_min_seconds = recompile_min_seconds
        self.storm_threshold = storm_threshold
        self.storm_window = storm_window
        self._lock = threading.Lock()
        self._stages: Dict[str, StreamingQuantiles] = {}
        self._kernels: Dict[str, StreamingQuantiles] = {}
        self._breaching: Dict[str, bool] = {}
        self._recompile_ts: Deque[float] = deque(maxlen=64)
        self._rounds: Dict[str, Any] = {
            "observed": 0, "honest": 0, "fallback": 0,
            "last_round": None, "last_dispatches": None,
            "exceeded_total": 0, "episodes": 0,
        }
        self._recompiles_suspected = 0
        self._exceeded_counter = metrics.counter(
            "drand_perf_dispatch_budget_exceeded_total",
            "Honest rounds that exceeded their device-dispatch budget",
        )
        self._episodes_counter = metrics.counter(
            "drand_perf_dispatch_budget_episodes_total",
            "Edge-triggered dispatch-budget breach episodes",
        )
        self._recompile_counter = metrics.counter(
            "drand_perf_recompiles_suspected_total",
            "Kernel dispatches far above steady-state after warmup "
            "(suspected jit recompiles)",
        )
        self._dispatch_gauge = metrics.gauge(
            "drand_perf_round_dispatches",
            "Device dispatches consumed by the last observed round",
        )

    # -- feeds -----------------------------------------------------------

    def observe_stage(self, stage: str, seconds: float) -> None:
        with self._lock:
            est = self._stages.get(stage)
            if est is None:
                est = self._stages[stage] = StreamingQuantiles()
            est.observe(seconds)
            p99 = est.quantile(0.99)
        if p99 is not None:
            metrics.gauge(
                "drand_perf_stage_p99_seconds",
                "Streaming p99 latency per pipeline stage",
                labels={"stage": stage},
            ).set(p99)

    def observe_kernel(self, op: str, seconds: float,
                       now: Optional[float] = None) -> None:
        now = self.now_fn() if now is None else now
        suspect = False
        with self._lock:
            est = self._kernels.get(op)
            if est is None:
                est = self._kernels[op] = StreamingQuantiles()
            # recompile check against the *previous* steady state, so
            # the offending sample can't drag its own baseline up first
            if est.count >= self.warmup_dispatches:
                p50 = est.quantile(0.5)
                if (p50 is not None and p50 > 0.0
                        and seconds >= max(self.recompile_factor * p50,
                                           self.recompile_min_seconds)):
                    suspect = True
            est.observe(seconds)
            if suspect:
                self._recompiles_suspected += 1
                self._recompile_ts.append(now)
            storm = self._storm_active(now)
        if suspect:
            self._recompile_counter.inc()
        self._edge("recompile_storm", storm, kind="perf.recompile_storm",
                   op=op, now=now,
                   suspected_total=self._recompiles_suspected)

    def note_round(self, round: int, dispatches: int, *,
                   fallback: bool = False,
                   now: Optional[float] = None) -> None:
        """Per-round dispatch accounting.  `fallback` marks rounds that
        are exempt from the budget (blame-fallback retries legitimately
        re-dispatch; eager mode has no <=2 contract) — they neither
        trip nor clear the alarm."""
        now = self.now_fn() if now is None else now
        exceeded = False
        with self._lock:
            self._rounds["observed"] += 1
            self._rounds["last_round"] = round
            self._rounds["last_dispatches"] = dispatches
            if fallback:
                self._rounds["fallback"] += 1
            else:
                self._rounds["honest"] += 1
                exceeded = dispatches > self.budget
                if exceeded:
                    self._rounds["exceeded_total"] += 1
        self._dispatch_gauge.set(dispatches)
        if fallback:
            return
        if exceeded:
            self._exceeded_counter.inc()
        fired = self._edge(
            "dispatch_budget", exceeded, kind="perf.dispatch_budget",
            now=now, round=round, dispatches=dispatches,
            budget=self.budget,
        )
        if fired and exceeded:
            with self._lock:
                self._rounds["episodes"] += 1
            self._episodes_counter.inc()

    # -- alarms ----------------------------------------------------------

    def _edge(self, alarm: str, active: bool, *, kind: str,
              now: float, **fields: Any) -> bool:
        """Record a flight event only on alarm transitions; returns True
        when this call was a transition."""
        with self._lock:
            was = self._breaching.get(alarm, False)
            if active == was:
                return False
            self._breaching[alarm] = active
        rec = self.recorder if self.recorder is not None else flight.RECORDER
        rec.record(kind, status=("breach" if active else "clear"),
                   time=now, **fields)
        return True

    def _storm_active(self, now: float) -> bool:
        cutoff = now - self.storm_window
        while self._recompile_ts and self._recompile_ts[0] < cutoff:
            self._recompile_ts.popleft()
        return len(self._recompile_ts) >= self.storm_threshold

    def breaching(self, alarm: str) -> bool:
        with self._lock:
            return self._breaching.get(alarm, False)

    # -- views -----------------------------------------------------------

    def snapshot(self, now: Optional[float] = None) -> Dict[str, Any]:
        now = self.now_fn() if now is None else now
        with self._lock:
            storm = self._storm_active(now)
            recent = len(self._recompile_ts)
            doc: Dict[str, Any] = {
                "schema": PERF_SCHEMA,
                "time": now,
                "stages": {name: est.snapshot()
                           for name, est in sorted(self._stages.items())},
                "kernels": {op: est.snapshot()
                            for op, est in sorted(self._kernels.items())},
                "rounds": dict(self._rounds,
                               budget=self.budget,
                               breaching=self._breaching.get(
                                   "dispatch_budget", False)),
                "recompiles": {
                    "suspected_total": self._recompiles_suspected,
                    "recent": recent,
                    "storm": storm,
                    "window_seconds": self.storm_window,
                    "warmup_dispatches": self.warmup_dispatches,
                },
            }
        return doc

    def reset(self) -> None:
        with self._lock:
            self._stages.clear()
            self._kernels.clear()
            self._breaching.clear()
            self._recompile_ts.clear()
            self._recompiles_suspected = 0
            self._rounds = {
                "observed": 0, "honest": 0, "fallback": 0,
                "last_round": None, "last_dispatches": None,
                "exceeded_total": 0, "episodes": 0,
            }


#: process-wide observatory (handler, gateway, kernels and the span sink
#: all feed it; /v1/perf serves it)
OBSERVATORY = PerfObservatory()

observe_stage = OBSERVATORY.observe_stage
observe_kernel = OBSERVATORY.observe_kernel
note_round = OBSERVATORY.note_round
snapshot = OBSERVATORY.snapshot
reset = OBSERVATORY.reset

#: span-name prefixes routed into the stage registry by the span sink
_STAGE_PREFIXES = ("beacon.", "dkg.", "gateway.")


def span_sink(span_dict: Dict[str, Any]) -> None:
    """Tracer sink: finished pipeline-stage spans become stage samples.
    Kernel spans are skipped — `obs.kernels` feeds the kernel registry
    directly (and still counts with tracing off)."""
    name = span_dict.get("name") or ""
    duration = span_dict.get("duration")
    if duration is None or name.startswith("kernel."):
        return
    if name.startswith(_STAGE_PREFIXES):
        OBSERVATORY.observe_stage(name, duration)


# -- bench lineage --------------------------------------------------------

_ENV_KEYS = ("JAX_PLATFORMS", "XLA_FLAGS")
_ENV_PREFIXES = ("DRAND_TPU_", "BENCH_", "LOADGEN_")


def git_revision() -> Optional[str]:
    """Short git rev of the working tree, None outside a checkout."""
    root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    try:
        out = subprocess.run(
            ["git", "rev-parse", "--short", "HEAD"],
            cwd=root, capture_output=True, text=True, timeout=5,
        )
        rev = out.stdout.strip()
        return rev if out.returncode == 0 and rev else None
    except Exception:
        return None


def lineage(*, backend: Optional[str] = None,
            device: Optional[str] = None,
            degraded: bool = False,
            degraded_reason: Optional[str] = None,
            extra: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
    """Provenance block stamped into every bench/loadgen artifact, so a
    committed number can always answer "measured where, on what, with
    which knobs, and did anything fall back"."""
    if degraded_reason is not None and \
            degraded_reason not in DEGRADED_REASONS:
        raise ValueError(
            f"degraded_reason must be infra|code|None, got {degraded_reason!r}"
        )
    env = {k: v for k, v in sorted(os.environ.items())
           if k in _ENV_KEYS or k.startswith(_ENV_PREFIXES)}
    doc: Dict[str, Any] = {
        "schema": LINEAGE_SCHEMA,
        "git_rev": git_revision(),
        "python": platform.python_version(),
        "platform": platform.platform(),
        "backend": backend,
        "device": device,
        "degraded": bool(degraded),
        "degraded_reason": degraded_reason,
        "env": env,
    }
    if extra:
        doc.update(extra)
    return doc


_INFRA_MARKERS = (
    "remote compile", "compile cache", "connection", "unavailable",
    "deadline", "timed out", "timeout", "socket", "dns",
    "resource exhausted", "out of memory", "sigsegv", "sigill",
    "sigbus", "signal", "bus error", "failed to initialize",
    "backend", "rpc", "tunnel", "preempt",
)


def classify_failure(text: str) -> str:
    """infra|code: is a bench failure the environment's fault or ours?
    The ROADMAP carry-over: BENCH_r05 died on remote-compile infra and
    the artifact must never blur that into a code regression."""
    low = (text or "").lower()
    return "infra" if any(m in low for m in _INFRA_MARKERS) else "code"


# -- bench diff (artifact comparison) ------------------------------------

#: kinds: latency (lower better, tolerance applies), throughput (higher
#: better, tolerance applies), dispatch (lower better, ZERO tolerance —
#: dispatch counts are backend-independent)
_LOWER, _HIGHER, _DISPATCH = "latency", "throughput", "dispatch"


def _num(v: object) -> Optional[float]:
    return float(v) if isinstance(v, (int, float)) \
        and not isinstance(v, bool) else None


def _put(out: Dict[str, Dict[str, Any]], name: str, value: object,
         kind: str, unit: str = "") -> None:
    num = _num(value)
    if num is not None:
        out[name] = {"value": num, "kind": kind, "unit": unit}


def _pct_stages(out: Dict[str, Dict[str, Any]], prefix: str,
                doc: object, kind: str = _LOWER) -> None:
    if not isinstance(doc, dict):
        return
    for q in ("p50", "p95", "p99"):
        _put(out, f"{prefix}.{q}", doc.get(q), kind, "s")


def extract_stages(doc: Dict[str, Any]) -> Dict[str, Dict[str, Any]]:
    """Flatten any of the repo's artifact shapes (bench.py line,
    bench_suite payload, loadgen report) into comparable stage scalars."""
    out: Dict[str, Dict[str, Any]] = {}
    if not isinstance(doc, dict):
        return out

    # bench.py single-line artifact
    if "metric" in doc and "value" in doc:
        unit = str(doc.get("unit", ""))
        kind = _HIGHER if ("/s" in unit or "per_sec" in unit) else _LOWER
        _put(out, str(doc["metric"]), doc.get("value"), kind, unit)
        detail = doc.get("detail") or {}
        rf = detail.get("round_finalize") or {}
        _put(out, "round_finalize.dispatches",
             rf.get("device_dispatches_per_finalize"), _DISPATCH)
        _put(out, "round_finalize.finalizes_per_sec",
             rf.get("finalizes_per_sec"), _HIGHER, "/s")
        _pct_stages(out, "round_finalize",
                    rf.get("finalize_seconds_percentiles"))
        opt = rf.get("optimistic") or {}
        _put(out, "round_finalize.optimistic.dispatches",
             opt.get("device_dispatches_per_finalize"), _DISPATCH)
        _put(out, "round_finalize.optimistic.finalizes_per_sec",
             opt.get("finalizes_per_sec"), _HIGHER, "/s")
        _pct_stages(out, "round_finalize.optimistic",
                    opt.get("finalize_seconds_percentiles"))
        kq = rf.get("kernel_seconds_percentiles") or {}
        if isinstance(kq, dict):
            for op, pcts in kq.items():
                if isinstance(pcts, dict):
                    _pct_stages(out, f"kernel.{op}", pcts)
        pi = detail.get("partial_ingest") or {}
        for mode in ("eager", "lazy"):
            _pct_stages(out, f"partial_ingest.{mode}", pi.get(mode))

    # bench_suite payload (rows from bench_suite._emit: config/value/
    # unit/seconds; "_"-prefixed rows are run markers, not measurements)
    for row in (doc.get("results") or []):
        if not isinstance(row, dict) or row.get("degraded") \
                or "skipped" in row:
            continue
        name = str(row.get("config") or row.get("name") or "?")
        if name.startswith("_"):
            continue
        unit = str(row.get("unit", ""))
        _put(out, f"suite.{name}.per_sec", row.get("value"),
             _HIGHER, unit)
        _put(out, f"suite.{name}.seconds", row.get("seconds"),
             _LOWER, "s")

    # loadgen reports
    bench = doc.get("benchmark")
    if bench == "serve-gateway-throughput":
        _put(out, "gateway.batched_rps", doc.get("batched_rps"),
             _HIGHER, "/s")
        _put(out, "gateway.sequential_rps", doc.get("sequential_rps"),
             _HIGHER, "/s")
        _put(out, "gateway.speedup", doc.get("speedup"), _HIGHER, "x")
    elif bench == "serve-mesh-gateway":
        scaling = doc.get("mesh_scaling") or {}
        _put(out, "mesh.scaling_x", scaling.get("scaling_x"), _HIGHER, "x")
        hot = doc.get("hot_round") or {}
        _put(out, "mesh.hit_rate", hot.get("hit_rate"), _HIGHER, "")
    return out


def diff_stages(old: Dict[str, Dict[str, Any]],
                new: Dict[str, Dict[str, Any]],
                tolerance: float = 0.25) -> List[Dict[str, Any]]:
    """Stage-by-stage comparison.  Returns one row per stage seen in
    either artifact; `verdict` is ok|regression|improved|new|gone.
    Dispatch-count stages regress on ANY increase (tolerance ignored)."""
    rows: List[Dict[str, Any]] = []
    for name in sorted(set(old) | set(new)):
        o, n = old.get(name), new.get(name)
        if o is None or n is None:
            present = o if o is not None else n
            if present is None:    # unreachable: name came from old|new
                continue
            rows.append({"stage": name, "kind": present["kind"],
                         "old": None if o is None else o["value"],
                         "new": None if n is None else n["value"],
                         "delta_pct": None,
                         "verdict": "new" if o is None else "gone"})
            continue
        ov, nv, kind = o["value"], n["value"], n["kind"]
        delta = None if ov == 0 else (nv - ov) / abs(ov) * 100.0
        if kind == _DISPATCH:
            verdict = ("regression" if nv > ov
                       else "improved" if nv < ov else "ok")
        elif kind == _HIGHER:
            verdict = ("regression" if nv < ov * (1.0 - tolerance)
                       else "improved" if nv > ov * (1.0 + tolerance)
                       else "ok")
        else:
            verdict = ("regression" if nv > ov * (1.0 + tolerance)
                       else "improved" if nv < ov * (1.0 - tolerance)
                       else "ok")
        rows.append({"stage": name, "kind": kind, "old": ov, "new": nv,
                     "delta_pct": (None if delta is None
                                   else round(delta, 1)),
                     "verdict": verdict})
    return rows


def load_artifact(path: str) -> Dict[str, Any]:
    """Parse a bench/loadgen artifact file.  bench.py output may carry
    retry-marker lines before the final artifact; keep the LAST line
    that parses as a recognisable document."""
    import json

    text = open(path).read()
    try:
        doc = json.loads(text)
        if isinstance(doc, dict):
            return doc
    except ValueError:
        pass
    best: Optional[Dict[str, Any]] = None
    for line in text.splitlines():
        line = line.strip()
        if not line.startswith("{"):
            continue
        try:
            doc = json.loads(line)
        except ValueError:
            continue
        if isinstance(doc, dict) and (
                "metric" in doc or "results" in doc or "benchmark" in doc):
            best = doc
    if best is None:
        raise ValueError(f"no parseable bench artifact in {path}")
    return best
