"""On-demand device profiling: `POST /debug/profile?seconds=N`.

The static `DRAND_TPU_PROFILE_DIR` knob (utils/profiling.py) must be set
before boot and traces the whole process lifetime — useless on a live
node that started misbehaving an hour ago.  This module is the
ML-serving answer: an operator asks a *running* daemon for an N-second
XLA profiler capture and gets back the trace directory to pull into
xprof/TensorBoard.

Design constraints, in order:

* **Single-flight.**  The JAX profiler is process-global; two
  overlapping captures corrupt each other.  Concurrent requests
  coalesce onto the one in-flight capture and all receive the same
  result (the second caller marked `coalesced`), so under any burst the
  device is traced exactly once.
* **Bounded.**  `seconds` is clamped to `MAX_SECONDS`; a capture cannot
  be left running by a disconnecting client because the timer, not the
  request, ends it.
* **Degrades, never breaks.**  On a host without a working jax profiler
  the capture still produces a non-empty directory: a JSON fallback
  carrying the kernel dispatch counters and the recent flight-recorder
  events — less detail, same workflow.  Every capture additionally
  writes a `capture.json` manifest (params, backend, kernel counters
  observed during the window).

Auth is the REST layer's concern (`net/rest.py` gates the route to
loopback callers or an explicit `DRAND_TPU_PROFILE_TOKEN`); this module
only enforces the single-flight and bounds.
"""

from __future__ import annotations

import asyncio
import json
import os
import tempfile
import time
from typing import List, Optional

from drand_tpu.obs import flight, kernels
from drand_tpu.utils import profiling
from drand_tpu.utils.logging import get_logger

log = get_logger("obs.profile")

#: hard cap on one capture; profiling is not free and an operator typo
#: ("seconds=3600") must not degrade the beacon for an hour
MAX_SECONDS = 60.0
DEFAULT_SECONDS = 2.0


def _list_files(tdir: str) -> List[str]:
    out = []
    for root, _dirs, files in os.walk(tdir):
        for f in files:
            out.append(os.path.relpath(os.path.join(root, f), tdir))
    return sorted(out)


class ProfileCapture:
    """Single-flight on-demand capture manager (one per process)."""

    def __init__(self, base_dir: Optional[str] = None):
        self.base_dir = base_dir
        self._inflight: Optional[asyncio.Future] = None
        self._last: Optional[dict] = None

    @property
    def running(self) -> bool:
        return self._inflight is not None and not self._inflight.done()

    async def capture(self, seconds: float = DEFAULT_SECONDS,
                      base_dir: Optional[str] = None) -> dict:
        """Capture a device trace for ~`seconds`; returns the result
        document.  Concurrent calls coalesce onto the in-flight capture
        (their result carries ``coalesced: true``)."""
        if self.running:
            res = dict(await asyncio.shield(self._inflight))
            res["coalesced"] = True
            return res
        seconds = min(MAX_SECONDS, max(0.0, float(seconds)))
        loop = asyncio.get_running_loop()
        self._inflight = loop.create_future()
        try:
            res = await self._capture_once(seconds, base_dir)
        except BaseException as exc:
            if not self._inflight.done():
                self._inflight.set_exception(exc)
                # coalesced waiters saw it; nobody else will
                self._inflight.exception()
            raise
        else:
            if not self._inflight.done():
                self._inflight.set_result(res)
            self._last = res
            return dict(res)

    async def _capture_once(self, seconds: float,
                            base_dir: Optional[str]) -> dict:
        tdir = tempfile.mkdtemp(
            prefix="drand-profile-",
            dir=base_dir or self.base_dir or None,
        )
        started = time.time()
        kernels_before = kernels.counters()
        flight.RECORDER.record("profile_start", dir=tdir,
                               seconds=seconds)
        device_traced = profiling.start_device_trace(tdir)
        try:
            if seconds > 0:
                await asyncio.sleep(seconds)
        finally:
            if device_traced:
                # stop_trace serializes the xplane protobufs — blocking
                # work that must not stall the event loop
                try:
                    await asyncio.to_thread(profiling.stop_device_trace)
                except Exception as exc:
                    log.warning("profiler stop failed", err=exc)
                    device_traced = False
        kernels_after = kernels.counters()
        window = {
            op: (st["dispatches"]
                 - kernels_before.get(op, {}).get("dispatches", 0))
            for op, st in kernels_after.items()
        }
        manifest = {
            "dir": tdir,
            "seconds": seconds,
            "started_unix": started,
            "device_traced": device_traced,
            "kernel_dispatches_in_window": window,
            "kernel_counters": kernels_after,
        }
        if not device_traced:
            # fallback payload: the capture still says something useful
            with open(os.path.join(tdir, "profile_fallback.json"),
                      "w") as fh:
                json.dump({
                    "note": "jax profiler unavailable; kernel counters "
                            "and flight events only",
                    "kernel_counters": kernels_after,
                    "flight_events": flight.RECORDER.snapshot()[-256:],
                }, fh, default=repr)
        with open(os.path.join(tdir, "capture.json"), "w") as fh:
            json.dump(manifest, fh)
        result = dict(manifest)
        result["files"] = _list_files(tdir)
        result["coalesced"] = False
        flight.RECORDER.record("profile_done", dir=tdir,
                               files=len(result["files"]),
                               device_traced=device_traced)
        return result

    def status(self) -> dict:
        """GET /debug/profile document: capture state + the live
        compile/dispatch counters from the kernel spans."""
        return {
            "running": self.running,
            "last": self._last,
            "max_seconds": MAX_SECONDS,
            "kernels": kernels.counters(),
        }


#: process-wide capture manager (profiler state is process-global too)
CAPTURE = ProfileCapture()
