"""Fleet view: aggregate N nodes' observability documents into one.

Every node already serves `/v1/status` (chain head, suspects, gateway
pressure) and `/v1/slo` (error budgets, burn rates) — but a network-wide
problem only shows up by diffing those documents ACROSS nodes: a fork is
two nodes with irreconcilable heads, quorum risk is "how many nodes can
we lose before threshold", and a suspect is only credible when several
peers independently rank it.  `aggregate()` is that diff, pure over
captured documents (tests, the CLI and the REST endpoint all share it);
`FleetAggregator` does the polling and exports `drand_fleet_*` gauges;
`GET /v1/fleet` (net/rest.py) and `cli fleet` serve the result.

An optional `ChainWatcher` snapshot folds the *verified* third-party
view in: self-reported heads that run ahead of what actually verifies
against the distributed key become `disputes` — a Byzantine node can lie
in its own status document, but not to the pairing check.
"""

from __future__ import annotations

import time
from typing import Awaitable, Callable, Dict, Optional

from drand_tpu.utils import metrics

#: a source returns {"status": dict, "slo": dict} for one node; raising
#: marks the node unreachable in the fleet view
Source = Callable[[], Awaitable[dict]]

_spread_gauge = metrics.gauge(
    "drand_fleet_head_spread",
    "max - min chain head across reachable fleet nodes")
_margin_gauge = metrics.gauge(
    "drand_fleet_quorum_margin",
    "healthy nodes minus group threshold (negative = below quorum)")
_burn_gauge = metrics.gauge(
    "drand_fleet_worst_burn_rate",
    "worst SLO long-window burn rate across the fleet")
_reach_gauge = metrics.gauge(
    "drand_fleet_nodes_reachable", "nodes that answered the last poll")
_worst_p99_gauge = metrics.gauge(
    "drand_fleet_worst_stage_p99_seconds",
    "worst per-stage p99 latency across reachable fleet nodes")
_budget_breach_gauge = metrics.gauge(
    "drand_fleet_dispatch_budget_breaching",
    "fleet nodes currently breaching their round dispatch budget")


def _worst_burn(slo_doc: Optional[dict]) -> Optional[dict]:
    """Largest long-window burn rate in one node's SLO document."""
    worst = None
    for name, obj in sorted(((slo_doc or {}).get("objectives")
                             or {}).items()):
        for window, rate in sorted((obj.get("burn_rates") or {}).items()):
            try:
                rate = float(rate)
            except (TypeError, ValueError):
                continue
            if worst is None or rate > worst["rate"]:
                worst = {"objective": name, "window": window, "rate": rate}
    return worst


def _min_budget(slo_doc: Optional[dict]) -> Optional[dict]:
    worst = None
    for name, obj in sorted(((slo_doc or {}).get("objectives")
                             or {}).items()):
        rem = obj.get("budget_remaining")
        if rem is None:
            continue
        if worst is None or rem < worst["remaining"]:
            worst = {"objective": name, "remaining": rem}
    return worst


def aggregate(node_docs: Dict[str, dict], watch: Optional[dict] = None,
              now: Optional[float] = None) -> dict:
    """Fold per-node documents into the fleet view.

    `node_docs` maps node name -> {"status": dict|None, "slo":
    dict|None[, "error": str]}; an "error" entry marks the node
    unreachable (its stale documents, if any, are ignored).  `watch` is
    an optional `ChainWatcher.snapshot()` supplying the independently
    VERIFIED heads.
    """
    from drand_tpu.cli import diagnose  # lazy: cli imports are heavy-ish

    now = time.time() if now is None else now
    nodes = {}
    heads, healthy, threshold = {}, [], None
    worst_burn, min_budget = None, None
    suspect_votes: Dict[str, list] = {}
    # perf observatory fold: worst per-stage p99 across the fleet, plus
    # dispatch-budget sentinel state (who is breaching, total overruns)
    worst_stages: Dict[str, dict] = {}
    budget_breaching: list = []
    budget_exceeded_total = 0
    # fork-resolution fold: fleet-wide reorg count + the deepest one,
    # named — churn here means partitions keep manufacturing branches
    reorg_total = 0
    deepest_reorg: Optional[dict] = None

    for name in sorted(node_docs):
        doc = node_docs[name] or {}
        err = doc.get("error")
        status = doc.get("status") if not err else None
        slo_doc = doc.get("slo") if not err else None
        chain = (status or {}).get("chain") or {}
        head = chain.get("head_round")
        expected = chain.get("expected_round")
        running = bool(chain.get("running"))
        if head is not None:
            heads[name] = head
        if threshold is None:
            threshold = chain.get("threshold")

        burn = _worst_burn(slo_doc)
        budget = _min_budget(slo_doc)
        if burn and (worst_burn is None or burn["rate"] > worst_burn["rate"]):
            worst_burn = dict(burn, node=name)
        if budget and (min_budget is None
                       or budget["remaining"] < min_budget["remaining"]):
            min_budget = dict(budget, node=name)

        for s in (status or {}).get("suspects") or []:
            peer = s.get("peer")
            if peer:
                suspect_votes.setdefault(peer, []).append(
                    (name, s.get("score")))

        perf_doc = (status or {}).get("perf") or {}
        for kind in ("stages", "kernels"):
            for stage, est in sorted((perf_doc.get(kind) or {}).items()):
                p99 = est.get("p99") if isinstance(est, dict) else None
                if not isinstance(p99, (int, float)):
                    continue
                key = stage if kind == "stages" else f"kernel.{stage}"
                cur = worst_stages.get(key)
                if cur is None or p99 > cur["p99"]:
                    worst_stages[key] = {
                        "p99": p99, "node": name,
                        "count": est.get("count"),
                    }
        rounds = perf_doc.get("rounds") or {}
        if rounds.get("breaching"):
            budget_breaching.append(name)
        budget_exceeded_total += int(rounds.get("exceeded_total") or 0)

        reorgs = chain.get("reorgs") or {}
        reorg_total += int(reorgs.get("total") or 0)
        depth = int(reorgs.get("max_depth") or 0)
        if depth > 0 and (deepest_reorg is None
                          or depth > deepest_reorg["depth"]):
            deepest_reorg = {"node": name, "depth": depth,
                             "last": reorgs.get("last")}

        findings = diagnose(status, slo_doc, []) if status else []
        nodes[name] = {
            "reachable": not err,
            **({"error": err} if err else {}),
            "head": head,
            "expected": expected,
            "running": running,
            "lag": (expected - head
                    if head is not None and expected is not None else None),
            "worst_burn": burn,
            "min_budget": budget,
            "findings": [f for f in findings if f["kind"] != "healthy"],
        }

    top = max(heads.values(), default=None)
    low = min(heads.values(), default=None)
    for name, head in heads.items():
        # healthy = reachable, loop running, head within one round of
        # the fleet max: the set the threshold can still count on
        if nodes[name]["running"] and head >= (top or 0) - 1:
            healthy.append(name)

    # a suspect only makes the fleet view when >1 node independently
    # ranks it (one accuser could itself be the problem)
    consensus = []
    for peer in sorted(suspect_votes):
        votes = suspect_votes[peer]
        scores = [s for _, s in votes if isinstance(s, (int, float))]
        consensus.append({
            "peer": peer,
            "reported_by": sorted(n for n, _ in votes),
            "score": (round(sum(scores) / len(scores), 3)
                      if scores else None),
        })
    consensus.sort(key=lambda c: (-len(c["reported_by"]), c["peer"]))

    doc = {
        "time": now,
        "nodes": nodes,
        "reachable": sum(1 for n in nodes.values() if n["reachable"]),
        "head": {"max": top, "min": low,
                 "spread": (top - low
                            if top is not None and low is not None
                            else None)},
        "quorum": {
            "threshold": threshold,
            "healthy": sorted(healthy),
            "margin": (len(healthy) - threshold
                       if threshold is not None else None),
        },
        "slo": {"worst_burn_rate": worst_burn,
                "min_budget_remaining": min_budget},
        "perf": {
            # worst per-stage p99 across the fleet: the node dragging
            # each stage down is named so `cli fleet` can point at it
            "worst_stage_p99": {k: worst_stages[k]
                                for k in sorted(worst_stages)},
            "dispatch_budget": {
                "breaching": sorted(budget_breaching),
                "exceeded_total": budget_exceeded_total,
            },
        },
        "reorgs": {"total": reorg_total, "deepest": deepest_reorg},
        "suspects": consensus,
    }

    if watch is not None:
        verified = {p: v.get("head", 0)
                    for p, v in (watch.get("peers") or {}).items()}
        disputes = []
        for name, claimed in sorted(heads.items()):
            v = verified.get(name)
            # one round of slack: the node may have finalized since the
            # watcher's last poll — beyond that the claim is unbacked
            if v is not None and claimed > v + 1:
                disputes.append({"node": name, "claimed_head": claimed,
                                 "verified_head": v})
        doc["watch"] = {
            "max_verified_head": watch.get("max_head"),
            "stalled": watch.get("stalled"),
            "forks": watch.get("forks"),
            "verified_heads": verified,
            "disputes": disputes,
        }
    return doc


class FleetAggregator:
    """Polls every source and folds the answers through `aggregate`.

    `sources` maps node name -> async callable returning {"status": ...,
    "slo": ...}; `watch` is an optional `ChainWatcher` whose verified
    snapshot joins each poll.
    """

    def __init__(self, sources: Dict[str, Source], watch=None,
                 now_fn=time.time):
        self.sources = dict(sources)
        self.watch = watch
        self.now_fn = now_fn
        self.last: Optional[dict] = None

    async def poll(self) -> dict:
        docs: Dict[str, dict] = {}
        for name in sorted(self.sources):
            try:
                docs[name] = await self.sources[name]()
            except Exception as exc:
                docs[name] = {"error": str(exc)[:160]}
        watch_snap = self.watch.snapshot() if self.watch is not None else None
        doc = aggregate(docs, watch=watch_snap, now=self.now_fn())
        spread = doc["head"]["spread"]
        if spread is not None:
            _spread_gauge.set(spread)
        margin = doc["quorum"]["margin"]
        if margin is not None:
            _margin_gauge.set(margin)
        burn = doc["slo"]["worst_burn_rate"]
        if burn is not None:
            _burn_gauge.set(burn["rate"])
        _reach_gauge.set(doc["reachable"])
        perf_doc = doc.get("perf") or {}
        stages = perf_doc.get("worst_stage_p99") or {}
        if stages:
            _worst_p99_gauge.set(
                max(s["p99"] for s in stages.values()))
        _budget_breach_gauge.set(
            len((perf_doc.get("dispatch_budget") or {})
                .get("breaching") or []))
        self.last = doc
        return doc


def render_fleet(doc: dict) -> str:
    """One fleet document as a TTY table (cli fleet / cli watch)."""
    lines = []
    head = doc.get("head") or {}
    quorum = doc.get("quorum") or {}
    lines.append(
        f"fleet: {doc.get('reachable')}/{len(doc.get('nodes') or {})} "
        f"reachable   head max={head.get('max')} "
        f"spread={head.get('spread')}   "
        f"quorum margin={quorum.get('margin')} "
        f"(threshold={quorum.get('threshold')})")
    burn = (doc.get("slo") or {}).get("worst_burn_rate")
    if burn:
        lines.append(
            f"worst burn: {burn['rate']}x ({burn.get('node')} "
            f"{burn.get('objective')}/{burn.get('window')})")
    perf_doc = doc.get("perf") or {}
    breaching = (perf_doc.get("dispatch_budget") or {}).get(
        "breaching") or []
    if breaching:
        lines.append(
            f"dispatch budget BREACH: {', '.join(breaching)}")
    lines.append(f"{'node':20s} {'head':>6s} {'lag':>4s} "
                 f"{'run':>3s} {'findings'}")
    for name in sorted(doc.get("nodes") or {}):
        n = doc["nodes"][name]
        if not n.get("reachable"):
            lines.append(f"{name:20s} {'-':>6s} {'-':>4s} {'-':>3s} "
                         f"UNREACHABLE: {n.get('error', '')}")
            continue
        finds = ", ".join(
            f"{f['severity']}:{f['kind']}" for f in n.get("findings") or []
        ) or "-"
        lines.append(
            f"{name:20s} {str(n.get('head')):>6s} "
            f"{str(n.get('lag')):>4s} "
            f"{'y' if n.get('running') else 'N':>3s} {finds}")
    watch = doc.get("watch")
    if watch:
        lines.append(
            f"watch: verified head={watch.get('max_verified_head')} "
            f"stalled={watch.get('stalled')} "
            f"forks={len(watch.get('forks') or [])}")
        for d in watch.get("disputes") or []:
            lines.append(
                f"  DISPUTE {d['node']}: claims round "
                f"{d['claimed_head']} but only {d['verified_head']} "
                f"verified")
        for f in watch.get("forks") or []:
            lines.append(
                f"  FORK at round {f.get('divergence_round')} "
                f"({f.get('peer')}): {f.get('detail')}")
    for s in doc.get("suspects") or []:
        lines.append(
            f"suspect {s['peer']} reported by "
            f"{len(s['reported_by'])} node(s), mean score {s['score']}")
    return "\n".join(lines)
