"""Dependency-free span tracer for the beacon pipeline.

The reference operates on logs alone; a multi-stage distributed pipeline
(sign partial -> gossip -> collect -> recover -> verify -> store) needs
spans to show *where* a round's time went, per node and per kernel
dispatch.  This is the minimal OpenTelemetry-shaped core the daemon
needs, with zero third-party dependencies so the pure-protocol path
stays importable without jax or otel wheels:

* `Span`: monotonic-clock interval with trace/span ids, attributes and a
  parent link; a context manager that marks itself errored when the body
  raises (including the round loop's ticker-is-king cancellation).
* `Tracer`: bounded in-memory store of finished spans grouped by trace
  id, with a contextvar "current span" so nested spans auto-link — the
  context flows through `asyncio.to_thread` (it copies the context), so
  kernel spans recorded from worker threads still attach to the round.
* Deterministic round trace ids: every node derives the SAME id for a
  round from the chain identity (genesis seed), so the partial-verify
  spans of all nodes stitch into one distributed trace without any
  coordination; the id additionally rides the `trace_id` proto field and
  gRPC metadata so out-of-group observers can join too.
* Sampling switch: with tracing disabled, `span()` hands back a shared
  no-op singleton — no allocation, no clock reads, no storage — which a
  test pins down (tracer overhead must be bounded).

`DRAND_TPU_TRACE=off` disables the process-wide tracer at import.
"""

from __future__ import annotations

import contextvars
import hashlib
import os
import secrets
import threading
import time
from collections import OrderedDict
from typing import Callable, Dict, List, Optional

_current_span: "contextvars.ContextVar[Optional[Span]]" = (
    contextvars.ContextVar("drand_tpu_span", default=None)
)


def _new_id() -> str:
    return secrets.token_hex(8)


def derive_trace_id(kind: str, seed: bytes) -> str:
    """Deterministic 16-hex-char trace id from a protocol identity."""
    h = hashlib.sha256(b"drand-tpu-trace:" + kind.encode() + b":" + seed)
    return h.hexdigest()[:16]


def round_trace_id(genesis_seed: bytes, round: int) -> str:
    """The trace id of one beacon round: every group member derives the
    same value, so one round = one distributed trace across all nodes."""
    return derive_trace_id(
        "round", genesis_seed + round.to_bytes(8, "big")
    )


def dkg_trace_id(session_id: bytes) -> str:
    """One trace per DKG run, derived from its session id (group hash)."""
    return derive_trace_id("dkg", session_id)


class _NoopSpan:
    """Shared do-nothing span returned when sampling is off."""

    __slots__ = ()

    trace_id: Optional[str] = None
    span_id: Optional[str] = None
    parent_id: Optional[str] = None
    name = ""
    attrs: dict = {}
    status = "ok"
    duration = 0.0

    def set_attr(self, key, value) -> None:
        pass

    def finish(self) -> None:
        pass

    def __enter__(self) -> "_NoopSpan":
        return self

    def __exit__(self, *exc) -> bool:
        return False


NOOP_SPAN = _NoopSpan()


class Span:
    """One timed interval.  Use as a context manager; attributes are
    free-form JSON-safe values.  Durations come from the monotonic
    clock; `start_unix` is wall time for display only."""

    __slots__ = ("trace_id", "span_id", "parent_id", "name", "attrs",
                 "status", "start", "start_unix", "end", "_tracer",
                 "_token")

    def __init__(self, tracer: "Tracer", name: str, trace_id: str,
                 parent_id: Optional[str], attrs: dict):
        self.trace_id = trace_id
        self.span_id = _new_id()
        self.parent_id = parent_id
        self.name = name
        self.attrs = attrs
        self.status = "ok"
        self.start = time.monotonic()
        self.start_unix = time.time()
        self.end: Optional[float] = None
        self._tracer = tracer
        self._token = None

    @property
    def duration(self) -> Optional[float]:
        return None if self.end is None else self.end - self.start

    def set_attr(self, key: str, value) -> None:
        self.attrs[key] = value

    def finish(self) -> None:
        if self.end is not None:
            return
        self.end = time.monotonic()
        if self._token is not None:
            try:
                _current_span.reset(self._token)
            except ValueError:
                pass  # finished from a different context — harmless
            self._token = None
        self._tracer._record(self)

    def to_dict(self) -> dict:
        return {
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "name": self.name,
            "start": self.start,
            "start_unix": self.start_unix,
            "duration": self.duration,
            "status": self.status,
            "attrs": dict(self.attrs),
        }

    def __enter__(self) -> "Span":
        self._token = _current_span.set(self)
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        if exc_type is not None:
            self.status = "error"
            self.attrs.setdefault("error", repr(exc))
        self.finish()
        return False


class Tracer:
    """Thread-safe bounded store of finished spans, grouped by trace.

    Old traces are evicted FIFO past `max_traces`; one trace keeps at
    most `max_spans_per_trace` spans (overflow counts in `dropped`).
    Sinks (e.g. the flight recorder) see every finished span dict.
    """

    def __init__(self, max_traces: int = 256,
                 max_spans_per_trace: int = 512,
                 enabled: Optional[bool] = None):
        if enabled is None:
            enabled = os.environ.get(
                "DRAND_TPU_TRACE", "on"
            ).lower() not in ("off", "0", "false")
        self.max_traces = max_traces
        self.max_spans_per_trace = max_spans_per_trace
        self._enabled = enabled
        self._lock = threading.Lock()
        self._traces: "OrderedDict[str, List[dict]]" = OrderedDict()
        self._sinks: List[Callable[[dict], None]] = []
        self.dropped = 0

    # -- sampling ----------------------------------------------------------

    @property
    def enabled(self) -> bool:
        return self._enabled

    def set_enabled(self, on: bool) -> None:
        self._enabled = bool(on)

    # -- span creation -----------------------------------------------------

    def span(self, name: str, *, trace_id: Optional[str] = None,
             parent: Optional[Span] = None, attrs: Optional[dict] = None):
        """Open a span.  Parent defaults to the context's current span;
        trace id defaults to the parent's (fresh otherwise)."""
        if not self._enabled:
            return NOOP_SPAN
        if parent is None:
            parent = _current_span.get()
        if parent is NOOP_SPAN:
            parent = None
        parent_id = parent.span_id if parent is not None else None
        if trace_id is None:
            trace_id = (parent.trace_id if parent is not None
                        else _new_id())
        return Span(self, name, trace_id, parent_id,
                    dict(attrs) if attrs else {})

    def current(self) -> Optional[Span]:
        cur = _current_span.get()
        return None if cur is None or cur is NOOP_SPAN else cur

    def current_trace_id(self) -> Optional[str]:
        cur = self.current()
        return None if cur is None else cur.trace_id

    # -- storage -----------------------------------------------------------

    def _record(self, span: Span) -> None:
        d = span.to_dict()
        with self._lock:
            spans = self._traces.get(span.trace_id)
            if spans is None:
                spans = self._traces[span.trace_id] = []
                while len(self._traces) > self.max_traces:
                    self._traces.popitem(last=False)
            if len(spans) < self.max_spans_per_trace:
                spans.append(d)
            else:
                self.dropped += 1
            self._traces.move_to_end(span.trace_id)
        for sink in self._sinks:
            try:
                sink(d)
            except Exception:
                pass  # a broken sink must never break the traced code

    def add_sink(self, fn: Callable[[dict], None]) -> None:
        self._sinks.append(fn)

    def remove_sink(self, fn: Callable[[dict], None]) -> None:
        """Detach a sink added with `add_sink`; unknown sinks are a
        no-op (scoped consumers like the sim's span lens detach on
        teardown without caring whether setup got that far)."""
        try:
            self._sinks.remove(fn)
        except ValueError:
            pass

    def get_trace(self, trace_id: str) -> Optional[dict]:
        with self._lock:
            spans = self._traces.get(trace_id)
            if spans is None:
                return None
            return {"trace_id": trace_id, "spans": [dict(s) for s in spans]}

    def recent(self, n: int = 20) -> List[dict]:
        """The n most recently updated traces, newest first.

        Ordering is part of the `/debug/traces` contract: a trace moves
        to the front every time one of its spans finishes, so index 0 is
        always the trace that last saw activity."""
        if n <= 0:
            return []
        with self._lock:
            ids = list(self._traces.keys())[-n:][::-1]
            return [
                {"trace_id": tid,
                 "spans": [dict(s) for s in self._traces[tid]]}
                for tid in ids
            ]

    def find_round(self, round: int) -> List[dict]:
        """Traces containing a span tagged with this beacon round."""
        with self._lock:
            out = []
            for tid, spans in reversed(self._traces.items()):
                if any(s["attrs"].get("round") == round for s in spans):
                    out.append({"trace_id": tid,
                                "spans": [dict(s) for s in spans]})
            return out

    def trace_count(self) -> int:
        with self._lock:
            return len(self._traces)

    def reset(self) -> None:
        with self._lock:
            self._traces.clear()
            self.dropped = 0


#: process-wide tracer (the daemon, gateway and kernels all feed it)
TRACER = Tracer()

span = TRACER.span
current_trace_id = TRACER.current_trace_id
