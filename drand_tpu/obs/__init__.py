"""Observability plane: span tracing, flight recorder, kernel timings.

The triad any serving stack needs before it can be operated:

* `obs.trace`  — dependency-free span tracer; one distributed trace per
  beacon round (deterministic trace ids stitch all nodes) and per DKG
  run, plus per-request gateway traces.
* `obs.flight` — bounded ring buffer of the last N structured events
  (finished spans, sheds, kernel dispatches, errors), dumped to disk on
  crash/SIGTERM and served live at `GET /debug/flight`.
* `obs.kernels` — `kernel_span(op, batch=...)` wraps every device
  dispatch with block-until-ready wall timings feeding the tracer, the
  `drand_device_kernel_seconds` histograms and the flight recorder.
* `obs.introspect` — the `GET /v1/status` health document.
* `obs.slo`     — SLO engine: error budgets and multi-window burn-rate
  alerting over the round-finalize and gateway-verify latencies, served
  at `GET /v1/slo`.
* `obs.peers`   — per-signer contribution ledger: arrival latency,
  missed/invalid partials, clock-skew estimates and suspect ranking.
* `obs.profile` — single-flight on-demand device profiling behind
  `POST /debug/profile`.
* `obs.perf`    — performance observatory: streaming per-stage/kernel
  latency quantiles, per-round dispatch accounting, the dispatch-budget
  sentinel (honest round <= 2 dispatches) and bench lineage/diff
  helpers, served at `GET /v1/perf`.
* `obs.watch`   — external chain watchdog: follow nodes as an untrusted
  third party, verify every fetched beacon against the distributed key,
  edge-trigger fork/stall/lag events (`drand_watch_*` metrics).
* `obs.fleet`   — cross-node aggregation of status/SLO documents into
  one fleet view (head spread, quorum margin, worst burn rate), served
  at `GET /v1/fleet`.

Import cost is trivially small (stdlib only), so protocol modules import
this unconditionally; sampling off (`DRAND_TPU_TRACE=off` or
`TRACER.set_enabled(False)`) reduces every span to a shared no-op.
`obs.watch` and `obs.fleet` are deliberately NOT re-exported here: they
import `beacon.chain` / `cli` respectively, and this package must stay
feather-weight on the protocol import path.
"""

from drand_tpu.obs.flight import RECORDER, FlightRecorder, install_crash_handler
from drand_tpu.obs.kernels import block, kernel_span
from drand_tpu.obs.peers import PeerLedger
from drand_tpu.obs.perf import OBSERVATORY, PerfObservatory
from drand_tpu.obs.profile import CAPTURE, ProfileCapture
from drand_tpu.obs.slo import (
    ENGINE,
    ROUND_FINALIZE,
    VERIFY_LATENCY,
    Objective,
    SLOEngine,
)
from drand_tpu.obs.trace import (
    NOOP_SPAN,
    TRACER,
    Span,
    Tracer,
    derive_trace_id,
    dkg_trace_id,
    round_trace_id,
)

__all__ = [
    "CAPTURE",
    "ENGINE",
    "FlightRecorder",
    "NOOP_SPAN",
    "OBSERVATORY",
    "Objective",
    "PeerLedger",
    "PerfObservatory",
    "ProfileCapture",
    "RECORDER",
    "ROUND_FINALIZE",
    "SLOEngine",
    "Span",
    "TRACER",
    "Tracer",
    "VERIFY_LATENCY",
    "block",
    "derive_trace_id",
    "dkg_trace_id",
    "install_crash_handler",
    "kernel_span",
    "round_trace_id",
]


def _span_to_flight(span_dict: dict) -> None:
    RECORDER.record(
        "span",
        name=span_dict["name"],
        trace_id=span_dict["trace_id"],
        duration=span_dict["duration"],
        status=span_dict["status"],
    )


# finished spans become flight-recorder events, so a crash dump carries
# the recent span history even though the tracer itself is in-memory
TRACER.add_sink(_span_to_flight)

# pipeline-stage spans (beacon.*, dkg.*, gateway.*) also feed the
# performance observatory's streaming latency baselines (GET /v1/perf)
from drand_tpu.obs import perf as _perf  # noqa: E402

TRACER.add_sink(_perf.span_sink)
