"""Health introspection: one JSON document describing a live node.

`daemon_status` is duck-typed against `core.Drand` (everything is
guarded with getattr), so a partially-assembled daemon — or a test stub
carrying just a beacon handler — still renders a useful document instead
of raising.  Served at `GET /v1/status` and pretty-printed by
`cli.py status`.
"""

from __future__ import annotations

import time
from typing import Optional

from drand_tpu.obs import flight, kernels, perf, trace


def _chain_status(beacon, now: float) -> Optional[dict]:
    if beacon is None:
        return None
    head = beacon.store.last()
    group = beacon.group
    return {
        "head_round": head.round if head is not None else None,
        "genesis_time": group.genesis_time,
        "period": group.period,
        "threshold": group.threshold,
        "nodes": len(group),
        "running": bool(getattr(beacon, "_running", False)),
        "expected_round": (
            # what round the clock says the network should be on
            max(0, int((now - group.genesis_time) // group.period) + 1)
            if now >= group.genesis_time else 0
        ),
        # fork-resolution summary: how often this node rolled back for
        # a higher verified branch (details ride the chain.reorg
        # flight events; None when the handler predates the field)
        "reorgs": getattr(beacon, "reorg_stats", None),
    }


def _peer_status(beacon, now: float) -> dict:
    if beacon is None:
        return {}
    out = {
        addr: {"last_seen": ts, "seconds_ago": round(now - ts, 3)}
        for addr, ts in sorted(beacon.peer_seen.items())
    }
    # merge the contribution ledger (latency/missed/invalid/skew/suspect
    # scoring) when the handler carries one — liveness keys stay intact
    ledger = getattr(beacon, "peer_ledger", None)
    if ledger is not None:
        for addr, doc in ledger.snapshot(now).items():
            merged = out.setdefault(addr, {})
            merged.update(doc)
    return out


def _suspects(beacon, now: float) -> list:
    ledger = getattr(beacon, "peer_ledger", None)
    if ledger is None:
        return []
    return ledger.suspects(now)


def _dkg_status(dkg) -> dict:
    if dkg is None:
        return {"state": "idle"}
    # per-phase wall-time accounting rides along in every non-idle
    # state: after `done` it is the record of where the run's time went
    phases = getattr(dkg, "phase_seconds", None) or {}
    if getattr(dkg, "_done", False):
        out = {"state": "done"}
    else:
        out = {
            "state": "in_progress",
            "dealt": bool(getattr(dkg, "_sent_deals", False)),
        }
    if phases:
        out["phases"] = {
            name: {k: (round(v, 6) if isinstance(v, float) else v)
                   for k, v in st.items()}
            for name, st in sorted(phases.items())
        }
    return out


def daemon_status(d) -> dict:
    """Snapshot of a daemon's health (all fields best-effort)."""
    clock = getattr(d, "clock", None)
    now = clock.now() if clock is not None else time.time()
    beacon = getattr(d, "beacon", None)
    gateway = getattr(d, "_verify_gateway", None)
    pair = getattr(d, "pair", None)
    scheme = getattr(d, "scheme", None)
    return {
        "address": (pair.public.address if pair is not None else None),
        "state": ("running" if beacon is not None
                  else "waiting for DKG"),
        "backend": (type(scheme).__name__ if scheme is not None
                    else None),
        "time": now,
        "chain": _chain_status(beacon, now),
        "dkg": _dkg_status(getattr(d, "dkg", None)),
        "peers": _peer_status(beacon, now),
        "suspects": _suspects(beacon, now),
        "serve": (gateway.stats() if gateway is not None else None),
        "kernels": kernels.counters(),
        "perf": perf.snapshot(now),
        "trace": {
            "enabled": trace.TRACER.enabled,
            "traces": trace.TRACER.trace_count(),
            "dropped_spans": trace.TRACER.dropped,
        },
        "flight": {
            "events": len(flight.RECORDER),
            "capacity": flight.RECORDER.capacity,
        },
    }
