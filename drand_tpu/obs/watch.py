"""External chain watchdog: follow beacon nodes as an untrusted third
party.

The paper's core promise is that anyone holding the distributed public
key can verify the chain — this module is that promise turned into an
operational tool.  A `ChainWatcher` polls one or more nodes' chains
through pluggable fetchers (the sim fabric, a node's public REST API, a
test stub), verifies everything it fetches through the SAME
batched/sharded pairing path the nodes use (`scheme.verify_chain_batch`
against the distributed key), and maintains a per-peer map of *verified*
heads.  Nothing a peer merely claims enters the watcher's world view:
a forged beacon fails the pairing check and is dropped at the door, so
a Byzantine node can at worst under-report its own progress.

On top of the verified view the watcher edge-triggers typed events —
each fires once per state change, into the local event list and an
injectable flight recorder:

* ``watch_fork``         — two verified branches disagree AND neither
  wins: carries the divergence round (the first round where the
  histories conflict: either two different beacons for one round, or
  one chain *bridging over* a round another chain finalized).  Pages
  only for unresolved conflicts — equal heads, or a branch the watcher
  cannot root in its canonical chain.
* ``watch_reorg``        — a verified conflicting branch whose head
  STRICTLY exceeds the canonical head was adopted (the same
  highest-round-fully-verified-chain-wins policy the nodes run, see
  `beacon.handler._resolve_fork`): the canonical chain rolled back to
  the divergence round and took the branch; fork entries the adoption
  resolves are cleared, so `drand_watch_fork_detected` falls back to 0
  instead of paging forever on a self-healed fork.
* ``watch_stalled`` / ``watch_resumed`` — no verified head progress for
  `stall_periods` beacon periods while the schedule marched >= 2
  rounds ahead.
* ``watch_head_lag`` / ``watch_catchup`` — a peer fell `lag_rounds`
  behind the fleet's verified head / progressed while lagging (with
  from/to rounds) or caught back up.
* ``watch_bad_beacon`` / ``watch_bad_chain`` — a fetched beacon failed
  the pairing check / a peer's own chain did not link.
* ``watch_peer_unreachable`` / ``watch_peer_ok`` — fetch transport
  failed / recovered.

Prometheus series (``drand_watch_*``) mirror the events so the alert
rules in deploy/prometheus-alerts.yml can page on a fork or stall that
NO in-node exporter would ever admit to.
"""

from __future__ import annotations

import json
import time
import urllib.request
from typing import Awaitable, Callable, Dict, List, Optional

from drand_tpu.beacon.chain import Beacon, beacon_message, current_round
from drand_tpu.utils import metrics

#: a fetcher returns the peer's chain from `from_round` (inclusive),
#: oldest first; raising means the peer is unreachable this poll
Fetcher = Callable[[int], Awaitable[List[Beacon]]]

_polls = metrics.counter(
    "drand_watch_polls_total", "observation passes the watcher ran")
_verified = metrics.counter(
    "drand_watch_verified_rounds_total",
    "beacons that passed the pairing check against the distributed key")
_bad_beacons = metrics.counter(
    "drand_watch_bad_beacons_total",
    "fetched beacons that FAILED the pairing check (forgeries)")
_forks_total = metrics.counter(
    "drand_watch_forks_total", "distinct chain divergences detected")
_reorgs_total = metrics.counter(
    "drand_watch_reorgs_total",
    "verified higher-head branches the watcher's canonical chain "
    "adopted (followed reorgs)")
_fork_gauge = metrics.gauge(
    "drand_watch_fork_detected",
    "number of distinct verified-chain divergences currently known "
    "(alert on > 0)")
_stalled_gauge = metrics.gauge(
    "drand_watch_stalled",
    "1 while the verified chain head is stalled behind the schedule")
_head_gauge = metrics.gauge(
    "drand_watch_head_round", "maximum verified head across watched peers")


class ChainWatcher:
    """Read-only third-party chain follower over untrusted peers.

    `dist_key`/`scheme` do the trust: every fetched beacon must carry a
    valid group threshold signature over its chained message before the
    watcher believes anything about it.  `clock` is injectable (an
    object with ``now()``) so the simulator can drive stall detection on
    simulated time; `recorder` (a `FlightRecorder`) receives every typed
    event alongside the local ``events`` list.
    """

    def __init__(self, dist_key, scheme, period: float, genesis_time: int,
                 sources: Optional[Dict[str, Fetcher]] = None, *,
                 clock=None, recorder=None, stall_periods: int = 3,
                 lag_rounds: int = 2, fetch_limit: int = 256,
                 max_events: int = 4096):
        self.dist_key = dist_key
        self.scheme = scheme
        self.period = float(period)
        self.genesis_time = genesis_time
        self.clock = clock
        self.recorder = recorder
        self.stall_periods = stall_periods
        self.lag_rounds = lag_rounds
        self.fetch_limit = fetch_limit
        self.max_events = max_events

        self.sources: Dict[str, Fetcher] = {}
        #: per-peer verified state: head round, chain tail beacon,
        #: transport status, lagging edge
        self.peers: Dict[str, dict] = {}
        #: the canonical verified chain: first fully-verified beacon
        #: seen for each round wins (detection only — no reorg policy)
        self.chain: Dict[int, Beacon] = {}
        #: round -> bridging beacon's round, for every round some
        #: adopted beacon's link asserts was skipped
        self._skipped: Dict[int, int] = {}
        self.forks: List[dict] = []
        self._fork_keys: set = set()
        self.stalled = False
        self.max_head = 0
        self._last_progress_at: Optional[float] = None
        self.events: List[dict] = []

        for addr, fetch in sorted((sources or {}).items()):
            self.add_source(addr, fetch)

    # -- wiring ------------------------------------------------------------

    def add_source(self, addr: str, fetch: Fetcher) -> None:
        self.sources[addr] = fetch
        self.peers.setdefault(addr, {
            "head": 0, "tail": None, "status": "unknown",
            "lagging": False, "bad": 0,
            # verified-but-unadopted branch beacons: kept so a branch
            # that outgrows the canonical head across SEVERAL polls can
            # still be rooted at its divergence point and adopted
            "branch": [],
        })

    def _now(self) -> float:
        return self.clock.now() if self.clock is not None else time.time()

    def _event(self, kind: str, **fields) -> dict:
        ev = {"kind": kind, "ts": self._now()}
        ev.update(fields)
        self.events.append(ev)
        if len(self.events) > self.max_events:
            del self.events[: len(self.events) - self.max_events]
        if self.recorder is not None:
            self.recorder.record(kind, **fields)
        return ev

    # -- observation pass --------------------------------------------------

    async def poll(self) -> dict:
        """One observation pass over every source (sorted, so replays
        are deterministic); returns `snapshot()`."""
        _polls.inc()
        for addr in sorted(self.sources):
            await self._poll_peer(addr)
        self._update_lag()
        self._update_stall()
        self._update_metrics()
        return self.snapshot()

    async def _poll_peer(self, addr: str) -> None:
        st = self.peers[addr]
        try:
            batch = await self.sources[addr](st["head"] + 1)
        except Exception as exc:
            if st["status"] != "unreachable":
                self._event("watch_peer_unreachable", peer=addr,
                            error=str(exc)[:160])
            st["status"] = "unreachable"
            return
        if st["status"] == "unreachable":
            self._event("watch_peer_ok", peer=addr)
        st["status"] = "ok"
        batch = [b for b in batch if b.round > st["head"]]
        batch = batch[: self.fetch_limit]
        if not batch:
            return

        # the peer's own chain must link before we spend pairings on it;
        # a beacon that instead links some OTHER verified round (e.g. a
        # round-7 with prev_round=5 while round 6 is finalized) is a
        # fork branch, not garbage — anchor it against the canonical
        # chain and let `_observe` name the divergence round
        linked: List[Beacon] = []
        prev = st["tail"]
        for b in batch:
            if prev is not None and (b.prev_round != prev.round
                                     or b.prev_sig != prev.signature):
                anchor = self.chain.get(b.prev_round)
                if anchor is None or anchor.signature != b.prev_sig:
                    st["bad"] += 1
                    self._event(
                        "watch_bad_chain", peer=addr, round=b.round,
                        detail=f"links prev_round={b.prev_round} after "
                               f"verified head {prev.round}")
                    break
            linked.append(b)
            prev = b
        if not linked:
            return

        # the trust boundary: one batched pairing check over the whole
        # fetched segment (sharded across devices when the scheme can)
        msgs = [beacon_message(b.prev_sig, b.prev_round, b.round)
                for b in linked]
        sigs = [b.signature for b in linked]
        ok = self.scheme.verify_chain_batch(self.dist_key, msgs, sigs)
        good: List[Beacon] = []
        for b, valid in zip(linked, ok):
            if not valid:
                st["bad"] += 1
                _bad_beacons.inc()
                self._event("watch_bad_beacon", peer=addr, round=b.round)
                break  # everything after chains onto a forgery
            good.append(b)
        if not good:
            return

        old_head = st["head"]
        st["tail"] = good[-1]
        st["head"] = good[-1].round
        _verified.inc(len(good))
        self._fold(addr, good)
        if st["lagging"] and st["head"] > old_head:
            self._event("watch_catchup", peer=addr,
                        from_round=old_head, to_round=st["head"])

    # -- fork detection / resolution ---------------------------------------

    def _fold(self, addr: str, good: List[Beacon]) -> None:
        """Fold a verified segment into the canonical chain.

        Beacons that agree with (or cleanly extend) the canonical chain
        are adopted one by one.  From the FIRST conflicting beacon on,
        the rest of the segment is treated as one competing branch; the
        same policy the nodes run then decides: a branch whose verified
        head strictly exceeds the canonical head is ADOPTED as a reorg
        (``watch_reorg``), anything else pages ``watch_fork``."""
        st = self.peers[addr]
        suffix: List[Beacon] = []
        divergence, detail = 0, ""
        for b in good:
            if suffix:
                suffix.append(b)  # the rest of the batch rides the branch
                continue
            conflict = self._observe(addr, b)
            if conflict is not None:
                divergence, detail = conflict
                suffix = [b]
        if not suffix:
            st["branch"] = []  # peer is back on the canonical chain
            return
        # a conflicting branch may take several polls to outgrow the
        # canonical head: stitch this poll's run onto the unadopted
        # branch kept from the last one when they link
        branch = st.get("branch") or []
        if branch and (branch[-1].round == suffix[0].prev_round
                       and branch[-1].signature == suffix[0].prev_sig):
            branch = branch + suffix
        else:
            branch = suffix
        st["branch"] = branch
        cmax = max(self.chain, default=0)
        if branch[-1].round > cmax and self._reorg(addr, branch):
            st["branch"] = []
        else:
            self._fork(addr, divergence, detail)

    def _observe(self, addr: str, b: Beacon):
        """Fold one VERIFIED beacon into the canonical chain.  Returns
        ``None`` on agreement/extension, else ``(divergence_round,
        detail)`` for a beacon that conflicts with canonical history
        (nothing is adopted in that case — `_fold` decides whether the
        conflict resolves as a reorg or pages as a fork)."""
        have = self.chain.get(b.round)
        if have is not None:
            if (have.signature, have.prev_round, have.prev_sig) != \
                    (b.signature, b.prev_round, b.prev_sig):
                return (b.round,
                        f"{addr} holds a different beacon for round "
                        f"{b.round} than the canonical chain")
            return None
        # the incoming link bridges over rounds the canonical chain has
        for r in range(b.prev_round + 1, b.round):
            if r in self.chain:
                return (r,
                        f"{addr}'s chain bridges over round {r} "
                        f"({b.prev_round}->{b.round}) but the "
                        f"canonical chain finalized it")
        # a previously-adopted link bridged over THIS round
        bridger = self._skipped.get(b.round)
        if bridger is not None:
            return (b.round,
                    f"{addr} finalized round {b.round}, which the "
                    f"canonical chain bridged over "
                    f"(link into round {bridger})")
        prev = self.chain.get(b.prev_round)
        if prev is not None and prev.signature != b.prev_sig:
            return (b.round,
                    f"{addr}'s round {b.round} links a different "
                    f"round-{b.prev_round} signature than the "
                    f"canonical chain")
        self.chain[b.round] = b
        for r in range(b.prev_round + 1, b.round):
            self._skipped[r] = b.round
        return None

    def _reorg(self, addr: str, branch: List[Beacon]) -> bool:
        """Adopt a verified competing branch: highest round wins.

        The branch must root at a beacon the canonical chain agrees on
        (its first link's (prev_round, prev_sig) matches canonical) and
        link internally; the watcher then drops every canonical round
        past the divergence point, takes the branch, and clears fork
        entries the adoption resolves.  Returns False — canonical chain
        untouched — when the branch cannot be rooted."""
        base = branch[0].prev_round
        anchor = self.chain.get(base)
        if base > 0 and (anchor is None
                         or anchor.signature != branch[0].prev_sig):
            return False  # cannot root the branch in canonical history
        for p, b in zip(branch, branch[1:]):
            if b.prev_round != p.round or b.prev_sig != p.signature:
                return False  # stitched branch does not link
        old_head = max(self.chain, default=0)
        dropped = sorted(r for r in self.chain if r > base)
        for r in dropped:
            del self.chain[r]
        for r in [r for r, br in self._skipped.items() if br > base]:
            del self._skipped[r]
        for b in branch:
            self.chain[b.round] = b
            for r in range(b.prev_round + 1, b.round):
                self._skipped[r] = b.round
        # fork entries rooted past the divergence point are resolved by
        # the adoption: clear them so drand_watch_fork_detected drops
        # back to 0 instead of paging on a healed fork forever
        resolved = [f for f in self.forks
                    if f["divergence_round"] > base]
        self.forks = [f for f in self.forks
                      if f["divergence_round"] <= base]
        for f in resolved:
            self._fork_keys.discard((f["peer"], f["divergence_round"]))
        _reorgs_total.inc()
        self._event("watch_reorg", peer=addr, divergence_round=base,
                    depth=len(dropped), old_head=old_head,
                    new_head=branch[-1].round)
        return True

    def _fork(self, peer: str, divergence_round: int, detail: str) -> None:
        key = (peer, divergence_round)
        if key in self._fork_keys:
            return  # edge-triggered: one event per distinct divergence
        self._fork_keys.add(key)
        info = {"peer": peer, "divergence_round": divergence_round,
                "detail": detail}
        self.forks.append(info)
        _forks_total.inc()
        self._event("watch_fork", peer=peer,
                    divergence_round=divergence_round, detail=detail)

    # -- stall / lag -------------------------------------------------------

    def expected_round(self, now: Optional[float] = None) -> int:
        return current_round(self._now() if now is None else now,
                             self.period, self.genesis_time)

    def _update_lag(self) -> None:
        heads = [st["head"] for st in self.peers.values()]
        top = max(heads, default=0)
        for addr in sorted(self.peers):
            st = self.peers[addr]
            behind = top - st["head"]
            if behind >= self.lag_rounds and not st["lagging"]:
                st["lagging"] = True
                self._event("watch_head_lag", peer=addr,
                            head=st["head"], behind=behind)
            elif behind < self.lag_rounds and st["lagging"]:
                st["lagging"] = False
                self._event("watch_caught_up", peer=addr, head=st["head"])

    def _update_stall(self) -> None:
        now = self._now()
        top = max((st["head"] for st in self.peers.values()), default=0)
        if self._last_progress_at is None or top > self.max_head:
            self.max_head = max(self.max_head, top)
            self._last_progress_at = now
        expected = self.expected_round(now)
        idle = now - self._last_progress_at
        stalled = (expected - self.max_head >= 2
                   and idle >= self.stall_periods * self.period)
        if stalled and not self.stalled:
            self._event("watch_stalled", head=self.max_head,
                        expected=expected,
                        behind=expected - self.max_head,
                        idle_seconds=idle)
        elif self.stalled and not stalled:
            self._event("watch_resumed", head=self.max_head,
                        expected=expected)
        self.stalled = stalled

    def _update_metrics(self) -> None:
        _fork_gauge.set(len(self._fork_keys))
        _stalled_gauge.set(1.0 if self.stalled else 0.0)
        _head_gauge.set(self.max_head)
        for addr in sorted(self.peers):
            st = self.peers[addr]
            metrics.gauge(
                "drand_watch_peer_head_round",
                "per-peer verified chain head",
                labels={"peer": addr}).set(st["head"])
            metrics.gauge(
                "drand_watch_peer_head_lag",
                "rounds the peer's verified head trails the fleet max",
                labels={"peer": addr}).set(
                    max(0, self.max_head - st["head"]))

    # -- views -------------------------------------------------------------

    def heads(self) -> Dict[str, int]:
        """Per-peer VERIFIED head rounds (claims never enter this map)."""
        return {addr: st["head"] for addr, st in sorted(self.peers.items())}

    def snapshot(self) -> dict:
        now = self._now()
        return {
            "time": now,
            "period": self.period,
            "genesis_time": self.genesis_time,
            "expected_round": self.expected_round(now),
            "max_head": self.max_head,
            "stalled": self.stalled,
            "forks": [dict(f) for f in self.forks],
            "peers": {
                addr: {
                    "head": st["head"],
                    "lag": max(0, self.max_head - st["head"]),
                    "status": st["status"],
                    "lagging": st["lagging"],
                    "bad": st["bad"],
                }
                for addr, st in sorted(self.peers.items())
            },
            "events_total": len(self.events),
        }


def rest_source(base_url: str, timeout: float = 5.0) -> Fetcher:
    """Chain fetcher over a node's public REST API (`/api/public[...]`).

    Blocking urllib under the hood — meant for the CLI watch loop, not
    for serving threads.  The node is untrusted: whatever it returns
    still has to pass the watcher's pairing check.
    """
    base = base_url.rstrip("/")

    def _get(path: str) -> dict:
        with urllib.request.urlopen(base + path, timeout=timeout) as r:
            return json.loads(r.read().decode("utf-8"))

    def _beacon(j: dict) -> Beacon:
        return Beacon(
            round=int(j["round"]),
            prev_round=int(j["previous_round"]),
            prev_sig=bytes.fromhex(j["previous"]),
            signature=bytes.fromhex(j["signature"]),
        )

    async def fetch(from_round: int) -> List[Beacon]:
        head = _beacon(_get("/api/public"))
        if head.round < from_round:
            return []
        out = [_beacon(_get(f"/api/public/{r}"))
               for r in range(from_round, head.round)]
        out.append(head)
        return out

    return fetch
