"""Bounded ring-buffer flight recorder.

A drand node that crashes mid-round leaves no evidence: the metrics
registry resets with the process and the trace store lives in memory.
The flight recorder keeps the last N structured events — finished spans,
gateway sheds, kernel dispatches, errors — in a lock-protected deque so
a crash dump (`dump_to`) or the live `/debug/flight` endpoint can show
the seconds leading up to an incident.

Everything is plain dicts + `json.dumps(default=repr)`, so `dump()` is
valid JSON even when concurrent writers are appending mid-serialise
(the snapshot is taken under the lock).
"""

from __future__ import annotations

import itertools
import json
import os
import re
import sys
import threading
import time
from collections import deque
from typing import List, Optional

#: distinguishes concurrent dump_to calls within one process — the pid
#: alone collides when several in-process nodes dump at once
_TMP_SEQ = itertools.count()

#: Canonical vocabulary of flight-event kinds.  `cli doctor`, the sim
#: timeline lens and the watchdog tests all dispatch on these strings,
#: so a typo at a `record(...)` call site silently drops the event from
#: every consumer.  drand-lint's `reg-flight-event` rule resolves every
#: literal kind in the tree against this set — add the kind here FIRST,
#: then record it.
EVENT_KINDS = frozenset({
    # process lifecycle / incidents
    "crash", "signal",
    # tracer sink + kernel dispatches + gateway sheds
    "span", "kernel", "shed",
    # SLO engine and on-demand profiler
    "slo_breach", "profile_start", "profile_done",
    # performance observatory edge-triggered alarms (passed through
    # PerfObservatory._edge's `kind` parameter)
    "perf.dispatch_budget", "perf.recompile_storm",
    # chain fork resolution
    "chain.reorg", "chain.reorg_refused", "sync_starved",
    # external chain watchdog
    "watch_fork", "watch_reorg", "watch_stalled", "watch_resumed",
    "watch_head_lag", "watch_catchup", "watch_caught_up",
    "watch_bad_beacon", "watch_bad_chain",
    "watch_peer_unreachable", "watch_peer_ok",
    # simulation harness event log
    "sim_start", "sim_end", "node_crash", "node_restart", "node_span",
    "round_stored", "chain_reorg", "action_failed", "fault_event",
    "invariant_check",
})


class FlightRecorder:
    """Fixed-capacity event ring; thread-safe, allocation-light.

    `now_fn` is injectable so a simulated network's recorder stamps
    events with simulated time — a seeded replay then produces a
    byte-identical dump, wall clock be damned."""

    def __init__(self, capacity: int = 2048, now_fn=time.time):
        self.capacity = capacity
        self._now_fn = now_fn
        self._lock = threading.Lock()
        self._events: deque = deque(maxlen=capacity)
        self._seq = 0

    def record(self, kind: str, **fields) -> None:
        ev = {"seq": 0, "ts": self._now_fn(), "kind": kind}
        ev.update(fields)
        with self._lock:
            self._seq += 1
            ev["seq"] = self._seq
            self._events.append(ev)

    def snapshot(self) -> List[dict]:
        with self._lock:
            return [dict(ev) for ev in self._events]

    def dump(self) -> str:
        """All buffered events as a JSON document (oldest first)."""
        snap = self.snapshot()
        return json.dumps(
            {"capacity": self.capacity, "events": snap},
            default=repr,
        )

    def dump_to(self, path: str) -> None:
        """Atomic write (tmp + rename) so a crash mid-dump never leaves
        a truncated file where the post-mortem evidence should be.  The
        tmp name carries a process-unique sequence number on top of the
        pid: in-process multi-node runs (tests, the simulator) dump
        concurrently from ONE pid."""
        tmp = f"{path}.tmp.{os.getpid()}.{next(_TMP_SEQ)}"
        with open(tmp, "w") as f:
            f.write(self.dump())
        os.replace(tmp, path)

    def clear(self) -> None:
        with self._lock:
            self._events.clear()

    def __len__(self) -> int:
        with self._lock:
            return len(self._events)


#: process-wide recorder (tracer sink + gateway + kernels feed it)
RECORDER = FlightRecorder()


def dump_filename(identity: str = "") -> str:
    """Flight-dump filename, namespaced by node identity so in-process
    multi-node runs (two daemons sharing a folder in tests, simulator
    nodes) don't clobber each other's post-mortem evidence.  An empty
    identity keeps the historical `flight_dump.json` name."""
    if not identity:
        return "flight_dump.json"
    safe = re.sub(r"[^A-Za-z0-9._-]", "_", identity)
    return f"flight_dump.{safe}.json"


def install_crash_handler(path: str,
                          recorder: Optional[FlightRecorder] = None):
    """Chain onto sys.excepthook: on an unhandled exception, record it
    and write the flight buffer to `path` before the process dies.
    Returns the installed hook (handy for tests to uninstall)."""
    rec = recorder if recorder is not None else RECORDER
    prev = sys.excepthook

    def hook(exc_type, exc, tb):
        try:
            rec.record("crash", error=repr(exc),
                       type=getattr(exc_type, "__name__", str(exc_type)))
            rec.dump_to(path)
        except Exception:
            pass  # never mask the original crash
        prev(exc_type, exc, tb)

    sys.excepthook = hook
    return hook
