"""Bounded ring-buffer flight recorder.

A drand node that crashes mid-round leaves no evidence: the metrics
registry resets with the process and the trace store lives in memory.
The flight recorder keeps the last N structured events — finished spans,
gateway sheds, kernel dispatches, errors — in a lock-protected deque so
a crash dump (`dump_to`) or the live `/debug/flight` endpoint can show
the seconds leading up to an incident.

Everything is plain dicts + `json.dumps(default=repr)`, so `dump()` is
valid JSON even when concurrent writers are appending mid-serialise
(the snapshot is taken under the lock).
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time
from collections import deque
from typing import List, Optional


class FlightRecorder:
    """Fixed-capacity event ring; thread-safe, allocation-light."""

    def __init__(self, capacity: int = 2048):
        self.capacity = capacity
        self._lock = threading.Lock()
        self._events: deque = deque(maxlen=capacity)
        self._seq = 0

    def record(self, kind: str, **fields) -> None:
        ev = {"seq": 0, "ts": time.time(), "kind": kind}
        ev.update(fields)
        with self._lock:
            self._seq += 1
            ev["seq"] = self._seq
            self._events.append(ev)

    def snapshot(self) -> List[dict]:
        with self._lock:
            return [dict(ev) for ev in self._events]

    def dump(self) -> str:
        """All buffered events as a JSON document (oldest first)."""
        snap = self.snapshot()
        return json.dumps(
            {"capacity": self.capacity, "events": snap},
            default=repr,
        )

    def dump_to(self, path: str) -> None:
        """Atomic write (tmp + rename) so a crash mid-dump never leaves
        a truncated file where the post-mortem evidence should be."""
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            f.write(self.dump())
        os.replace(tmp, path)

    def clear(self) -> None:
        with self._lock:
            self._events.clear()

    def __len__(self) -> int:
        with self._lock:
            return len(self._events)


#: process-wide recorder (tracer sink + gateway + kernels feed it)
RECORDER = FlightRecorder()


def install_crash_handler(path: str,
                          recorder: Optional[FlightRecorder] = None):
    """Chain onto sys.excepthook: on an unhandled exception, record it
    and write the flight buffer to `path` before the process dies.
    Returns the installed hook (handy for tests to uninstall)."""
    rec = recorder if recorder is not None else RECORDER
    prev = sys.excepthook

    def hook(exc_type, exc, tb):
        try:
            rec.record("crash", error=repr(exc),
                       type=getattr(exc_type, "__name__", str(exc_type)))
            rec.dump_to(path)
        except Exception:
            pass  # never mask the original crash
        prev(exc_type, exc, tb)

    sys.excepthook = hook
    return hook
