"""SLO engine: judgment on top of the PR 2 measurement plane.

The tracer/metrics/flight triad records *what happened*; this module
decides *whether the service is healthy*.  Each `Objective` states a
latency bound and a target fraction ("99% of rounds finalize within 50%
of the period"); the engine turns every observation into a good/bad
event, accumulates them in coarse time buckets, and computes the two
figures SRE-style alerting is built on (Google SRE workbook ch. 5):

* **error-budget remaining** over a rolling budget window — the
  fraction of the allowed bad events not yet spent;
* **multi-window burn rates** — for each (long, short) window pair,
  the observed bad fraction divided by the budget fraction (1-target).
  A burn rate of 1.0 spends the budget exactly at the sustainable pace;
  a breach fires only when BOTH windows of a pair exceed the pair's
  factor, so a brief spike (short window only) or an old stain (long
  window only) cannot page anyone.

Breach transitions are recorded as `slo_breach` flight-recorder events
and counted in `drand_slo_breaches_total`; live burn/budget figures are
exported as `drand_slo_*` gauges and the whole document is served at
`GET /v1/slo`.

Time is injectable end to end: callers stamp events with their own
clock (`ts=clock.now()`) and snapshots take an explicit `now`, so a
`FakeClock` test can drive the engine across a breach boundary without
a single wall-clock sleep.  Like the tracer, everything is stdlib-only.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from drand_tpu.obs import flight
from drand_tpu.utils import metrics

#: default multi-window burn-rate alert pairs: (long, short, factor),
#: the SRE-workbook page/ticket ladder scaled to a 24h budget window
DEFAULT_BURN_WINDOWS: Tuple[Tuple[float, float, float], ...] = (
    (3600.0, 300.0, 14.4),     # page: 1h + 5m both burning >= 14.4x
    (6 * 3600.0, 1800.0, 6.0),  # ticket: 6h + 30m both burning >= 6x
)

DEFAULT_BUDGET_WINDOW = 24 * 3600.0
DEFAULT_BUCKET_SECONDS = 60.0


def _win_label(seconds: float) -> str:
    if seconds % 3600 == 0:
        return f"{int(seconds // 3600)}h"
    if seconds % 60 == 0:
        return f"{int(seconds // 60)}m"
    return f"{int(seconds)}s"


@dataclass
class Objective:
    """One service-level objective: `target` fraction of events must be
    good, where good means `value <= threshold` (seconds for latency
    objectives).  `describe` is free text for operators."""

    name: str
    target: float = 0.99
    threshold: float = 1.0
    describe: str = ""
    budget_window: float = DEFAULT_BUDGET_WINDOW
    burn_windows: Tuple[Tuple[float, float, float], ...] = (
        DEFAULT_BURN_WINDOWS
    )
    bucket_seconds: float = DEFAULT_BUCKET_SECONDS
    #: bucket index -> [good, bad] counts (pruned past budget_window)
    _buckets: Dict[int, List[int]] = field(default_factory=dict)
    #: pair label -> currently-breaching flag (edge detection)
    _breaching: Dict[str, bool] = field(default_factory=dict)
    breaches: int = 0
    last_ts: float = 0.0

    # -- recording ---------------------------------------------------------

    def record(self, good: bool, ts: float) -> None:
        idx = int(ts // self.bucket_seconds)
        b = self._buckets.get(idx)
        if b is None:
            b = self._buckets[idx] = [0, 0]
            self._prune(ts)
        b[0 if good else 1] += 1
        self.last_ts = max(self.last_ts, ts)

    def _prune(self, now: float) -> None:
        floor = int((now - self.budget_window) // self.bucket_seconds)
        for idx in [i for i in self._buckets if i < floor]:
            del self._buckets[idx]

    # -- queries -----------------------------------------------------------

    def _counts(self, now: float, window: float) -> Tuple[int, int]:
        lo = int((now - window) // self.bucket_seconds)
        good = bad = 0
        # list(): gauge export reads outside the engine lock while the
        # hot path appends — a snapshot must not trip on a resize
        for idx, (g, b) in list(self._buckets.items()):
            if idx > lo:
                good += g
                bad += b
        return good, bad

    def bad_fraction(self, now: float, window: float) -> float:
        good, bad = self._counts(now, window)
        total = good + bad
        return (bad / total) if total else 0.0

    def burn_rate(self, now: float, window: float) -> float:
        """Observed bad fraction relative to the budget fraction: 1.0
        spends the error budget exactly over the budget window."""
        budget = 1.0 - self.target
        if budget <= 0.0:
            return float("inf") if self.bad_fraction(now, window) else 0.0
        return self.bad_fraction(now, window) / budget

    def budget_remaining(self, now: float) -> float:
        """Fraction of the error budget left over the budget window
        (1.0 = untouched, 0.0 = exhausted, negative = overspent)."""
        good, bad = self._counts(now, self.budget_window)
        total = good + bad
        if total == 0:
            return 1.0
        allowed = (1.0 - self.target) * total
        if allowed <= 0.0:
            return 1.0 if bad == 0 else float("-inf")
        return 1.0 - bad / allowed

    def check_breaches(self, now: float) -> List[dict]:
        """Evaluate every burn-window pair; returns newly-fired breaches
        (edge-triggered: active pairs report once per transition)."""
        fired = []
        for long_w, short_w, factor in self.burn_windows:
            label = f"{_win_label(long_w)}/{_win_label(short_w)}"
            long_burn = self.burn_rate(now, long_w)
            short_burn = self.burn_rate(now, short_w)
            active = long_burn >= factor and short_burn >= factor
            if active and not self._breaching.get(label):
                self.breaches += 1
                fired.append({
                    "slo": self.name, "window": label, "factor": factor,
                    "long_burn": round(long_burn, 3),
                    "short_burn": round(short_burn, 3),
                })
            self._breaching[label] = active
        return fired

    def snapshot(self, now: float) -> dict:
        good, bad = self._counts(now, self.budget_window)
        burn = {}
        alerts = []
        for long_w, short_w, factor in self.burn_windows:
            label = f"{_win_label(long_w)}/{_win_label(short_w)}"
            lb = self.burn_rate(now, long_w)
            sb = self.burn_rate(now, short_w)
            burn[_win_label(long_w)] = round(lb, 4)
            burn[_win_label(short_w)] = round(sb, 4)
            if self._breaching.get(label):
                alerts.append({"window": label, "factor": factor,
                               "long_burn": round(lb, 4),
                               "short_burn": round(sb, 4)})
        return {
            "target": self.target,
            "threshold_seconds": self.threshold,
            "description": self.describe,
            "budget_window_seconds": self.budget_window,
            "good": good,
            "bad": bad,
            "budget_remaining": round(self.budget_remaining(now), 4),
            "burn_rates": burn,
            "breaching": alerts,
            "breaches_total": self.breaches,
            "last_event_ts": self.last_ts or None,
        }


class SLOEngine:
    """Registry of objectives + the shared recording/alerting path.

    `objective()` is idempotent (first registration wins) so call sites
    can declare their objective at import/boot without coordinating.
    """

    def __init__(self, now_fn=time.time):
        self._now_fn = now_fn
        self._lock = threading.Lock()
        self._objectives: Dict[str, Objective] = {}

    # -- registration ------------------------------------------------------

    def objective(self, name: str, *, target: float = 0.99,
                  threshold: float = 1.0, describe: str = "",
                  budget_window: float = DEFAULT_BUDGET_WINDOW,
                  burn_windows=DEFAULT_BURN_WINDOWS,
                  bucket_seconds: float = DEFAULT_BUCKET_SECONDS
                  ) -> Objective:
        with self._lock:
            obj = self._objectives.get(name)
            if obj is None:
                obj = self._objectives[name] = Objective(
                    name=name, target=target, threshold=threshold,
                    describe=describe, budget_window=budget_window,
                    burn_windows=tuple(burn_windows),
                    bucket_seconds=bucket_seconds,
                )
            return obj

    def get(self, name: str) -> Optional[Objective]:
        with self._lock:
            return self._objectives.get(name)

    # -- recording ---------------------------------------------------------

    def observe(self, name: str, value: float,
                ts: Optional[float] = None) -> bool:
        """Record one latency observation against `name`; the event is
        good iff value <= the objective's threshold.  Returns goodness.
        Unknown objectives are dropped (a misconfigured caller must not
        crash the hot path)."""
        obj = self.get(name)
        if obj is None:
            return True
        good = value <= obj.threshold
        self._record(obj, good, ts)
        return good

    def record_good(self, name: str, ts: Optional[float] = None) -> None:
        obj = self.get(name)
        if obj is not None:
            self._record(obj, True, ts)

    def record_bad(self, name: str, ts: Optional[float] = None) -> None:
        """An event that failed outright (abandoned round, shed request)
        — always burns budget regardless of the latency threshold."""
        obj = self.get(name)
        if obj is not None:
            self._record(obj, False, ts)

    def _record(self, obj: Objective, good: bool,
                ts: Optional[float]) -> None:
        if ts is None:
            ts = self._now_fn()
        with self._lock:
            obj.record(good, ts)
            fired = obj.check_breaches(ts)
        _events(obj.name, "good" if good else "bad").inc()
        for breach in fired:
            _breaches(obj.name).inc()
            flight.RECORDER.record("slo_breach", **breach)
        self._export(obj, ts)

    # -- export ------------------------------------------------------------

    def _export(self, obj: Objective, now: float) -> None:
        """Refresh the Prometheus gauges for one objective."""
        metrics.gauge(
            "drand_slo_error_budget_remaining",
            "fraction of the SLO error budget left (1 = untouched)",
            labels={"slo": obj.name},
        ).set(obj.budget_remaining(now))
        seen = set()
        for long_w, short_w, _ in obj.burn_windows:
            for w in (long_w, short_w):
                if w in seen:
                    continue
                seen.add(w)
                metrics.gauge(
                    "drand_slo_burn_rate",
                    "error-budget burn rate over a rolling window "
                    "(1 = sustainable pace)",
                    labels={"slo": obj.name, "window": _win_label(w)},
                ).set(obj.burn_rate(now, w))

    def snapshot(self, now: Optional[float] = None) -> dict:
        """The GET /v1/slo document."""
        if now is None:
            now = self._now_fn()
        with self._lock:
            objectives = dict(self._objectives)
        doc = {}
        for name, obj in sorted(objectives.items()):
            with self._lock:
                doc[name] = obj.snapshot(now)
            self._export(obj, now)
        return {"time": now, "objectives": doc}

    def reset(self) -> None:
        with self._lock:
            self._objectives.clear()


#: keys accepted in a group-file [[SLO]] table (key/group.py round-trips
#: them verbatim; anything else is a typo the operator must hear about)
_OVERRIDE_KEYS = {
    "Name", "Target", "ThresholdSeconds", "PeriodFraction",
    "BudgetWindow", "BucketSeconds", "Describe",
}


def parse_overrides(entries, period: Optional[float] = None
                    ) -> Dict[str, dict]:
    """Validate group-file SLO overrides into `ENGINE.objective` kwargs.

    `entries` is the group TOML's `[[SLO]]` array (list of dicts); the
    returned mapping is objective name -> keyword arguments.  Because
    `objective()` is first-registration-wins, a caller that registers
    these BEFORE its built-in defaults makes the group file
    authoritative.  Raises ValueError on any malformed entry — callers
    (BeaconConfig) validate at configuration time, not mid-round.

    Keys: `Name` (required), `Target` (good fraction in (0, 1]),
    `ThresholdSeconds` OR `PeriodFraction` (latency bound, absolute or
    as a fraction of the beacon period — the fraction form needs
    `period`), `BudgetWindow` (duration string, e.g. "24h"),
    `BucketSeconds`, `Describe`.
    """
    out: Dict[str, dict] = {}
    for i, entry in enumerate(entries):
        if not isinstance(entry, dict):
            raise ValueError(f"SLO override #{i}: expected a table")
        unknown = sorted(set(entry) - _OVERRIDE_KEYS)
        if unknown:
            raise ValueError(
                f"SLO override #{i}: unknown key(s) {unknown} "
                f"(accepted: {sorted(_OVERRIDE_KEYS)})"
            )
        name = entry.get("Name")
        if not name or not isinstance(name, str):
            raise ValueError(f"SLO override #{i}: Name is required")
        if name in out:
            raise ValueError(f"SLO override {name!r} declared twice")
        kw: dict = {}
        if "Target" in entry:
            target = float(entry["Target"])
            if not 0.0 < target <= 1.0:
                raise ValueError(
                    f"SLO {name!r}: Target must be in (0, 1], "
                    f"got {target}"
                )
            kw["target"] = target
        if "ThresholdSeconds" in entry and "PeriodFraction" in entry:
            raise ValueError(
                f"SLO {name!r}: give ThresholdSeconds OR PeriodFraction,"
                " not both"
            )
        if "ThresholdSeconds" in entry:
            thr = float(entry["ThresholdSeconds"])
            if thr <= 0:
                raise ValueError(
                    f"SLO {name!r}: ThresholdSeconds must be > 0"
                )
            kw["threshold"] = thr
        if "PeriodFraction" in entry:
            frac = float(entry["PeriodFraction"])
            if frac <= 0:
                raise ValueError(
                    f"SLO {name!r}: PeriodFraction must be > 0"
                )
            if period is None:
                raise ValueError(
                    f"SLO {name!r}: PeriodFraction needs a beacon period"
                )
            kw["threshold"] = frac * period
        if "BudgetWindow" in entry:
            from drand_tpu.utils import parse_duration

            window = parse_duration(entry["BudgetWindow"])
            if window <= 0:
                raise ValueError(
                    f"SLO {name!r}: BudgetWindow must be > 0"
                )
            kw["budget_window"] = window
        if "BucketSeconds" in entry:
            bucket = float(entry["BucketSeconds"])
            if bucket <= 0:
                raise ValueError(
                    f"SLO {name!r}: BucketSeconds must be > 0"
                )
            kw["bucket_seconds"] = bucket
        if "Describe" in entry:
            kw["describe"] = str(entry["Describe"])
        out[name] = kw
    return out


def _events(slo: str, result: str):
    return metrics.counter(
        "drand_slo_events_total", "SLO events judged good or bad",
        labels={"slo": slo, "result": result},
    )


def _breaches(slo: str):
    return metrics.counter(
        "drand_slo_breaches_total",
        "multi-window burn-rate breach transitions",
        labels={"slo": slo},
    )


#: process-wide engine (the beacon handler and gateway both feed it; the
#: REST layer serves its snapshot at /v1/slo)
ENGINE = SLOEngine()

#: canonical objective names used across the codebase
ROUND_FINALIZE = "round_finalize"
VERIFY_LATENCY = "verify_latency"
