"""Device-kernel timing hooks.

The crypto backends dispatch four kernel families — pairing checks,
MSM/Lagrange recovery, G2 signing and hash-to-curve — and asynchronous
dispatch means naive `time.time()` around a jax call measures trace
time, not device time.  `kernel_span` gives every call site one idiom:

    with kernel_span("pairing_check", batch=len(msgs)):
        ok = bool(np.asarray(jitted(...)))   # forces sync

and feeds three consumers at once:

* the per-op `drand_device_kernel_seconds` histogram (same metric name
  and labels the backends used before, so dashboards keep working),
* a `kernel.<op>` span under whatever round/batch span is current in
  the calling context (kernel attribution inside a round trace),
* a flight-recorder event, so the crash dump shows the last dispatches.

`block()` is for call sites whose return value does NOT already force a
device sync — it calls `jax.block_until_ready` when jax is importable
and degrades to identity otherwise (pure-Python backends).
"""

from __future__ import annotations

import contextlib
import threading
import time
from typing import Dict

from drand_tpu.obs import flight, perf, trace
from drand_tpu.utils import metrics

_hists: Dict[str, object] = {}

# per-op dispatch statistics beyond the histogram: first/max dispatch
# wall time distinguishes a cold XLA compile (first dispatch orders of
# magnitude slower) from steady-state dispatch — the signal `cli doctor`
# and GET /debug/profile use
_stats_lock = threading.Lock()
_stats: Dict[str, Dict[str, float]] = {}

# per-thread dispatch count: a kernel dispatch runs synchronously on the
# thread that issued it, so diffing this around a call attributes
# dispatches to THAT call even when several handlers (or offload worker
# threads) dispatch concurrently in one process — the process-global
# `dispatch_total()` cannot make that distinction
_tls = threading.local()


def _hist(op: str):
    h = _hists.get(op)
    if h is None:
        h = _hists[op] = metrics.histogram(
            "drand_device_kernel_seconds",
            "Wall time of device kernel dispatches (block_until_ready)",
            labels={"op": op},
        )
    return h


def _note_dispatch(op: str, dt: float) -> None:
    with _stats_lock:
        st = _stats.get(op)
        if st is None:
            st = _stats[op] = {
                "dispatches": 0, "seconds_total": 0.0,
                "first_seconds": dt, "max_seconds": dt,
            }
        st["dispatches"] += 1
        st["seconds_total"] += dt
        st["max_seconds"] = max(st["max_seconds"], dt)
    _tls.dispatches = getattr(_tls, "dispatches", 0) + 1
    # feed the performance observatory directly (not via the span sink)
    # so kernel baselines and recompile detection survive tracing off
    perf.observe_kernel(op, dt)


def counters() -> Dict[str, dict]:
    """Per-op dispatch counters (count, total/first/max wall seconds)
    for /v1/status and the profile endpoint — the compile/dispatch view
    the kernel spans already carry, aggregated."""
    with _stats_lock:
        return {
            op: {
                "dispatches": int(st["dispatches"]),
                "seconds_total": round(st["seconds_total"], 6),
                "first_seconds": round(st["first_seconds"], 6),
                "max_seconds": round(st["max_seconds"], 6),
            }
            for op, st in sorted(_stats.items())
        }


def dispatch_total() -> int:
    """Total device dispatches across all ops since the last reset."""
    with _stats_lock:
        return int(sum(st["dispatches"] for st in _stats.values()))


def thread_dispatches() -> int:
    """Dispatches issued by the CALLING thread, monotonic for the
    thread's lifetime — the per-round budget accounting diffs this
    around the finalize so concurrent handlers can't inflate each
    other's counts.  Unaffected by `reset_counters` (deltas only)."""
    return int(getattr(_tls, "dispatches", 0))


def reset_counters() -> None:
    with _stats_lock:
        _stats.clear()


def block(x):
    """Force device completion when `x` is a jax value; no-op for
    host-side values (Ref/Native backends)."""
    try:
        import jax

        return jax.block_until_ready(x)
    except Exception:
        return x


@contextlib.contextmanager
def kernel_span(op: str, **attrs):
    """Time one kernel dispatch: histogram + trace span + flight event.

    The span parents to the caller's current span (context flows through
    `asyncio.to_thread`), so kernel time shows up inside round traces.
    """
    span = trace.TRACER.span(f"kernel.{op}", attrs=attrs)
    span.__enter__()
    t0 = time.perf_counter()
    try:
        yield span
    except BaseException as exc:
        dt = time.perf_counter() - t0
        _hist(op).observe(dt)
        _note_dispatch(op, dt)
        flight.RECORDER.record("kernel", op=op, seconds=dt,
                               error=repr(exc), **attrs)
        span.__exit__(type(exc), exc, exc.__traceback__)
        raise
    else:
        dt = time.perf_counter() - t0
        _hist(op).observe(dt)
        _note_dispatch(op, dt)
        span.set_attr("seconds", dt)
        flight.RECORDER.record("kernel", op=op, seconds=dt, **attrs)
        span.__exit__(None, None, None)
