"""Per-signer contribution ledger: accountability for every group member.

`beacon.peer_seen` (PR 2) answers "is the peer alive?"; operating a
threshold network needs the sharper question "is the peer *pulling its
weight*?".  The ledger watches every inbound partial and every completed
round and keeps, per signer:

* **arrival latency** relative to the round's open time (`time_of_round`)
  — bucketed histogram plus running min/max/EWMA, so a peer that signs
  late every round is visible even while rounds still finalize;
* **missed contributions** — rounds this node finalized without a valid
  partial from that signer (the threshold absorbed the absence, but the
  margin shrank).  With t < n the slowest healthy signer loses this race
  *every* round, so a partial that arrives after its round finalized
  credits the miss back and counts as **late** instead — chronic
  lateness still surfaces through the latency EWMA, but a healthy peer
  no longer drifts into the suspect list just for finishing last;
* **invalid partials** — partials that failed signature verification
  (round-window rejects are counted in the rejected-packets metric but
  not charged here: a stale packet is a timing symptom, not forgery);
* **clock-skew estimate** — from the `sent_at` stamp beacon packets
  carry: `recv - sent` is skew plus network delay, so the MINIMUM over
  samples upper-bounds the skew tightly on any reasonable network, and
  an EWMA tracks drift.

`suspects()` ranks peers by a composite score so `/v1/status` (and
`cli doctor`) can say not just "something is late" but "node X is the
likely cause".  All timestamps come from the caller's clock, so a
`FakeClock` test drives staleness and skew deterministically.
"""

from __future__ import annotations

import threading
from typing import Dict, Iterable, List, Optional, Tuple

from drand_tpu.utils import metrics

#: latency histogram bucket edges as fractions of the beacon period
_LATENCY_FRACTIONS = (0.1, 0.25, 0.5, 0.75, 1.0, 2.0)

#: EWMA smoothing for latency/skew trends
_ALPHA = 0.2

#: rounds of miss bookkeeping kept for late-arrival credit
_RECENT_ROUNDS = 32

#: suspect-score weights (unitless; tuned so one chronic signal ~ 1.0)
_W_MISSED = 1.0
_W_INVALID = 0.5
_W_LATE = 1.0
_W_STALE = 1.0
_W_SKEW = 0.5
#: deliberately soft: serving a branch that later lost a reorg is NOT
#: forgery — an honest node on the wrong side of a partition does it
#: too.  The weight only makes a peer that *keeps* feeding us orphaned
#: branches drift up the ranking, it can never clear min_score alone.
_W_ORPHANED = 0.2


class PeerStats:
    """Mutable per-signer record (lock held by the owning ledger)."""

    __slots__ = (
        "address", "partials", "invalid", "missed", "late", "orphaned",
        "last_seen",
        "last_round", "latency_buckets", "latency_last", "latency_ewma",
        "latency_min", "latency_max", "skew_min", "skew_ewma",
        "skew_samples",
    )

    def __init__(self, address: str):
        self.address = address
        self.partials = 0
        self.invalid = 0
        self.missed = 0
        self.late = 0
        self.orphaned = 0
        self.last_seen: Optional[float] = None
        self.last_round: Optional[int] = None
        self.latency_buckets = [0] * (len(_LATENCY_FRACTIONS) + 1)
        self.latency_last: Optional[float] = None
        self.latency_ewma: Optional[float] = None
        self.latency_min: Optional[float] = None
        self.latency_max: Optional[float] = None
        self.skew_min: Optional[float] = None
        self.skew_ewma: Optional[float] = None
        self.skew_samples = 0


class PeerLedger:
    """Contribution accounting for one group, fed by the beacon handler.

    `addresses` is the full group membership; `self_address` is excluded
    from missed-contribution accounting (our own partial is always
    counted by construction).
    """

    def __init__(self, addresses: Iterable[str], self_address: str,
                 period: float):
        self.period = float(period)
        self.self_address = self_address
        self._lock = threading.Lock()
        self._peers: Dict[str, PeerStats] = {
            a: PeerStats(a) for a in addresses if a != self_address
        }
        self._bounds = tuple(f * self.period for f in _LATENCY_FRACTIONS)
        # round -> signers whose valid partial arrived, kept for a few
        # rounds: finalize snapshots its partial set at threshold, so a
        # partial landing during the recovery math would otherwise be
        # marked missed even though it arrived before round_complete
        self._round_partials: Dict[int, set] = {}
        # round -> signers marked missed at finalize, kept for a few
        # rounds so a straggling partial can convert its miss to "late"
        self._recent_missed: Dict[int, set] = {}

    def _get(self, address: str) -> PeerStats:
        st = self._peers.get(address)
        if st is None:
            # out-of-group sender (reshare transition, misconfig): track
            # it anyway — an unknown signer flooding partials is exactly
            # what an operator wants surfaced
            st = self._peers[address] = PeerStats(address)
        return st

    # -- recording (handler hot path: O(1), one small lock) ---------------

    def record_partial(self, address: str, round: int, *, ts: float,
                       round_open: float,
                       sent_at: Optional[float] = None) -> None:
        """A VALID partial from `address` for `round` arrived at `ts`;
        `round_open` is the round's scheduled start, `sent_at` the
        sender's own clock stamp (0/None when not carried)."""
        latency = max(0.0, ts - round_open)
        with self._lock:
            st = self._get(address)
            st.partials += 1
            contributed = self._round_partials.setdefault(round, set())
            contributed.add(address)
            while len(self._round_partials) > _RECENT_ROUNDS:
                self._round_partials.pop(next(iter(self._round_partials)))
            marked = self._recent_missed.get(round)
            if marked is not None and address in marked:
                # lost the race to the threshold, not absent: credit the
                # miss back (the latency EWMA still records the lateness)
                marked.discard(address)
                st.missed -= 1
                st.late += 1
                _late_counter(address).inc()
            st.last_seen = ts
            st.last_round = (round if st.last_round is None
                             else max(st.last_round, round))
            for i, b in enumerate(self._bounds):
                if latency <= b:
                    st.latency_buckets[i] += 1
                    break
            else:
                st.latency_buckets[-1] += 1
            st.latency_last = latency
            st.latency_ewma = (
                latency if st.latency_ewma is None
                else (1 - _ALPHA) * st.latency_ewma + _ALPHA * latency
            )
            st.latency_min = (latency if st.latency_min is None
                              else min(st.latency_min, latency))
            st.latency_max = (latency if st.latency_max is None
                              else max(st.latency_max, latency))
            if sent_at:
                skew = ts - sent_at
                st.skew_samples += 1
                st.skew_min = (skew if st.skew_min is None
                               else min(st.skew_min, skew))
                st.skew_ewma = (
                    skew if st.skew_ewma is None
                    else (1 - _ALPHA) * st.skew_ewma + _ALPHA * skew
                )
        _latency_hist(address).observe(latency)

    def record_invalid(self, address: str, ts: float,
                       round: Optional[int] = None) -> None:
        """A partial from `address` failed signature verification.

        With `round` given, the peer's optimistically-recorded
        contribution for that round is revoked too: the lazy admit path
        counts a partial on arrival, so a forgery unmasked by the
        finalize blame pass must also lose its round credit — otherwise
        the liar never accrues misses and its suspect score stays soft.
        """
        with self._lock:
            st = self._get(address)
            st.invalid += 1
            if round is not None:
                got = self._round_partials.get(round)
                if got is not None:
                    got.discard(address)
        _invalid_counter(address).inc()

    def record_orphaned(self, address: str, ts: float,
                        rounds: int = 1) -> None:
        """`address` served us `rounds` beacons that a reorg later
        orphaned.  This charges the *sender* of the losing branch —
        never the claimed signer indices inside its beacons (both
        branches carry valid threshold signatures; blaming signers
        would frame honest nodes, the same stance as the finalize
        blame pass).  Kept separate from `invalid`: the fork invariant
        and the `honest_blamed` check treat invalid as proof of
        forgery, which an orphaned branch is not."""
        with self._lock:
            st = self._get(address)
            st.orphaned += rounds
        _orphaned_counter(address).inc(rounds)

    def round_complete(self, round: int,
                       contributors: Iterable[str]) -> None:
        """A round finalized; every known signer NOT in `contributors`
        missed it (the threshold margin absorbed their absence)."""
        got = set(contributors)
        with self._lock:
            # union with partials the ledger saw directly: the finalize
            # path snapshots its set at threshold, the ledger keeps
            # counting arrivals through the recovery math
            got |= self._round_partials.get(round, set())
            marked = set()
            for addr, st in self._peers.items():
                if addr not in got:
                    st.missed += 1
                    marked.add(addr)
                    _missed_counter(addr).inc()
            self._recent_missed[round] = marked
            while len(self._recent_missed) > _RECENT_ROUNDS:
                self._recent_missed.pop(next(iter(self._recent_missed)))

    # -- queries -----------------------------------------------------------

    def _score(self, st: PeerStats,
               now: float) -> Tuple[float, List[str]]:
        """Composite suspicion score + human-readable reasons."""
        score = 0.0
        reasons: List[str] = []
        seen = st.partials + st.missed
        if seen:
            miss_ratio = st.missed / seen
            if miss_ratio > 0.0:
                score += _W_MISSED * miss_ratio
            if miss_ratio >= 0.25:
                reasons.append(
                    f"missed {st.missed}/{seen} rounds"
                )
        elif st.invalid == 0 and st.last_seen is None:
            # never heard from at all: maximally suspect once the
            # chain is moving
            score += _W_MISSED
            reasons.append("no valid partial ever received")
        if st.invalid:
            score += _W_INVALID * min(1.0, st.invalid / 10.0)
            reasons.append(f"{st.invalid} invalid partials")
        if st.orphaned:
            score += _W_ORPHANED * min(1.0, st.orphaned / 10.0)
            reasons.append(
                f"served {st.orphaned} beacons orphaned by reorgs"
            )
        if st.latency_ewma is not None and self.period > 0:
            late = st.latency_ewma / self.period
            if late > 0.5:
                score += _W_LATE * min(1.0, late)
                reasons.append(
                    f"partials arrive {st.latency_ewma:.2f}s after "
                    f"round open ({late:.0%} of the period)"
                )
        if st.last_seen is not None and self.period > 0:
            stale = (now - st.last_seen) / self.period
            if stale > 2.0:
                score += _W_STALE * min(1.0, stale / 10.0)
                reasons.append(
                    f"last valid partial {now - st.last_seen:.0f}s ago"
                )
        if st.skew_min is not None and self.period > 0:
            skew = abs(st.skew_min) / self.period
            if skew > 0.25:
                score += _W_SKEW * min(1.0, skew)
                reasons.append(
                    f"clock skew ~{st.skew_min:+.2f}s"
                )
        return score, reasons

    def snapshot(self, now: float) -> Dict[str, dict]:
        """Per-peer document merged into /v1/status."""
        out = {}
        with self._lock:
            peers = dict(self._peers)
        for addr, st in sorted(peers.items()):
            score, reasons = self._score(st, now)
            out[addr] = {
                "partials": st.partials,
                "invalid": st.invalid,
                "missed": st.missed,
                "late": st.late,
                "orphaned": st.orphaned,
                "last_seen": st.last_seen,
                "seconds_ago": (round(now - st.last_seen, 3)
                                if st.last_seen is not None else None),
                "last_round": st.last_round,
                "latency": {
                    "last": _r(st.latency_last),
                    "ewma": _r(st.latency_ewma),
                    "min": _r(st.latency_min),
                    "max": _r(st.latency_max),
                    "buckets": {
                        **{f"le_{f}p": st.latency_buckets[i]
                           for i, f in enumerate(_LATENCY_FRACTIONS)},
                        "inf": st.latency_buckets[-1],
                    },
                },
                "clock_skew": {
                    "estimate": _r(st.skew_min),
                    "ewma": _r(st.skew_ewma),
                    "samples": st.skew_samples,
                },
                "suspect_score": round(score, 3),
                "suspect_reasons": reasons,
            }
        return out

    def suspects(self, now: float, min_score: float = 0.25) -> List[dict]:
        """Peers ranked most-suspect first (score >= min_score)."""
        ranked = []
        with self._lock:
            peers = dict(self._peers)
        for addr, st in peers.items():
            score, reasons = self._score(st, now)
            if score >= min_score:
                ranked.append({
                    "peer": addr,
                    "score": round(score, 3),
                    "reasons": reasons,
                })
        ranked.sort(key=lambda d: -d["score"])
        return ranked


def _r(v: Optional[float]) -> Optional[float]:
    return None if v is None else round(v, 4)


def _latency_hist(peer: str):
    return metrics.histogram(
        "drand_peer_partial_latency_seconds",
        "arrival latency of valid partials relative to round open",
        labels={"peer": peer},
    )


def _invalid_counter(peer: str):
    return metrics.counter(
        "drand_peer_invalid_partials_total",
        "partials that failed signature verification",
        labels={"peer": peer},
    )


def _orphaned_counter(peer: str):
    return metrics.counter(
        "drand_peer_orphaned_beacons_total",
        "beacons served by this peer that a chain reorg later orphaned",
        labels={"peer": peer},
    )


def _missed_counter(peer: str):
    return metrics.counter(
        "drand_peer_missed_rounds_total",
        "rounds finalized without this signer's partial",
        labels={"peer": peer},
    )


def _late_counter(peer: str):
    # counters are monotonic, so a credited miss stays in
    # drand_peer_missed_rounds_total; genuine absences are the
    # difference between the two series
    return metrics.counter(
        "drand_peer_late_partials_total",
        "partials that arrived after their round had already finalized",
        labels={"peer": peer},
    )
