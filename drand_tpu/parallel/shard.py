"""Mesh construction + sharded verification / MSM kernels.

Replaces the reference's scale-out story (goroutine-per-RPC unicast mesh,
/root/reference/net/client_grpc.go) for the *compute* plane: on TPU the
batch axes are sharded over a `jax.sharding.Mesh` and XLA inserts the
collectives.  The host-side gRPC protocol plane is unchanged.
"""

from __future__ import annotations

from functools import partial

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:  # jax >= 0.6 exports shard_map at top level (kwarg: check_vma)
    shard_map = jax.shard_map
except AttributeError:  # 0.4.x ships it as experimental (kwarg: check_rep)
    from jax.experimental.shard_map import shard_map as _shard_map_exp

    def shard_map(f, *, mesh, in_specs, out_specs, check_vma=True):
        return _shard_map_exp(f, mesh=mesh, in_specs=in_specs,
                              out_specs=out_specs, check_rep=check_vma)

from drand_tpu.ops import pairing
from drand_tpu.ops.curve import (
    F1,
    F2,
    FieldOps,
    point_add,
    point_identity,
)
from drand_tpu.ops.msm import _msm as msm_local
from drand_tpu.utils.logging import get_logger

log = get_logger("parallel.shard")

CHAIN_AXIS = "chains"

# one-time guard for the CPU-fallback warning: a silent fallback lets a
# loadgen artifact masquerade virtual-CPU numbers as TPU numbers
_warned_fallback = False


def mesh_backend(mesh: Mesh) -> str:
    """Platform name of the devices backing `mesh` ("cpu", "tpu", ...)."""
    return mesh.devices.flat[0].platform


def device_mesh(n_devices: int, axis: str = CHAIN_AXIS) -> Mesh:
    """1-D mesh over the first `n_devices` available devices.

    Prefers the default backend's devices; falls back to the virtual CPU
    pool (``--xla_force_host_platform_device_count``) when the default
    backend is a single chip.  The fallback logs a one-time warning
    naming the backend actually used — artifacts must record it (see
    `mesh_backend`), never assume the default backend was honored.
    """
    global _warned_fallback
    devices = jax.devices()
    default_platform = devices[0].platform if devices else "none"
    if len(devices) < n_devices:
        devices = jax.devices("cpu")
        if not _warned_fallback:
            _warned_fallback = True
            log.warning(
                "default backend has too few devices; mesh falls back "
                "to the virtual CPU pool — numbers from this mesh are "
                "CPU numbers",
                default_backend=default_platform,
                default_devices=len(jax.devices()),
                mesh_backend="cpu",
                requested=n_devices,
            )
    if len(devices) < n_devices:
        raise RuntimeError(
            f"need {n_devices} devices, have {len(devices)}"
        )
    return Mesh(np.asarray(devices[:n_devices]), axis_names=(axis,))


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """Shard the leading batch axis across the mesh."""
    return NamedSharding(mesh, P(mesh.axis_names[0]))


def sharded_pairing_check(mesh: Mesh):
    """Data-parallel batched pairing product check over the mesh.

    Returns a jitted ``(p1, q1, p2, q2) -> bool[B]`` with the batch axis
    sharded across devices — the kernel for multi-chip chain catch-up
    (reference: the sequential verify loop at
    /root/reference/beacon/beacon.go:557-601).
    Batch size must be a multiple of the mesh size.
    """
    shard = batch_sharding(mesh)
    return jax.jit(
        pairing.pairing_product_check,
        in_shardings=(shard, shard, shard, shard),
        out_shardings=shard,
    )


def _sharded_msm(points, bits, *, mesh: Mesh, F: FieldOps,
                 per_device: bool = False):
    axis = mesh.axis_names[0]

    def local(points, bits):
        # windowed MSM (shared doublings) on the local shard
        acc = msm_local(points, bits, F)
        gathered = jax.lax.all_gather(acc, axis)  # (n_dev, 3, ...)
        out = gathered[0]
        for i in range(1, gathered.shape[0]):
            out = point_add(out, gathered[i], F)
        return out[None] if per_device else out

    # check_vma=False: after all_gather every device holds the same sum,
    # but the varying-axis checker cannot prove replication of a value
    # computed from gathered shards.  The replication claim is instead
    # EVIDENCED by tests/test_shard.py::test_sharded_msm_replication,
    # which runs this same body with per_device=True (out_specs sharded,
    # one combined sum per device) and asserts all devices agree.
    return shard_map(
        local,
        mesh=mesh,
        in_specs=(P(axis), P(axis)),
        out_specs=P(axis) if per_device else P(),
        check_vma=False,
    )(points, bits)


def sharded_msm(mesh: Mesh, points, bits, F: FieldOps = F2,
                per_device: bool = False):
    """sum_i bits_i * points_i with points sharded across the mesh.

    points: (B, 3, *field_shape), bits: (B, 256) MSB-first; B is padded
    up to a multiple of the mesh size with identity points (scalar 0), so
    any committee size t works on any mesh.  Each device computes a local
    partial group sum; the partials are combined via `all_gather` + tree
    add on every device (tensor-parallel Lagrange recovery — reference:
    kyber `share.RecoverCommit` consumed at
    /root/reference/beacon/beacon.go:488).

    per_device=True returns the (n_dev, 3, ...) per-device combined sums
    instead of the replicated value — the test hook proving every device
    computed the same answer.
    """
    n = mesh.devices.size
    b = points.shape[0]
    rem = (-b) % n
    if rem:
        pad_pts = jnp.broadcast_to(
            point_identity(F), (rem, *points.shape[1:])
        )
        points = jnp.concatenate([points, pad_pts], axis=0)
        bits = jnp.concatenate(
            [bits, jnp.zeros((rem, bits.shape[1]), bits.dtype)], axis=0
        )
    shard = batch_sharding(mesh)
    points = jax.device_put(points, shard)
    bits = jax.device_put(bits, shard)
    key = (mesh, F.name, per_device)
    fn = _MSM_CACHE.get(key)
    if fn is None:
        # jit caches by function identity — a fresh partial per call
        # would recompile every invocation
        fn = jax.jit(
            partial(_sharded_msm, mesh=mesh, F=F, per_device=per_device)
        )
        _MSM_CACHE[key] = fn
    return fn(points, bits)


_MSM_CACHE: dict = {}
