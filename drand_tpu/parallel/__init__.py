"""Multi-chip sharding for the batch crypto kernels.

The reference scales by replicating protocol work across *nodes* (t-of-n
threshold parallelism, /root/reference/beacon/beacon.go:473-488) and has
no intra-node parallel compute at all.  The TPU framework's scaling axis
is the device mesh: batches of independent pairing checks are sharded
across chips (data parallel over the `chains` axis — the 256-chain /
1M-round catch-up configs), and large Lagrange recoveries shard their
points across chips with an `all_gather` combine (the 667-of-1000 MSM
config).  All collectives ride ICI via `jax.shard_map`; nothing here
ever falls back to host gathers.

Used by `__graft_entry__.dryrun_multichip` (the driver contract) and by
`tests/test_shard.py` on the virtual 8-device CPU mesh, so the sharded
path is covered on every CI run.
"""

from drand_tpu.parallel.shard import (
    device_mesh,
    sharded_msm,
    sharded_pairing_check,
)

__all__ = [
    "device_mesh",
    "sharded_msm",
    "sharded_pairing_check",
]
