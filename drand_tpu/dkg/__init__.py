"""Distributed key generation (Pedersen) — fresh and resharing modes.

Equivalent of /root/reference/dkg/ (which wraps kyber's dkg/pedersen):
:mod:`pedersen` is the pure cryptographic state machine,
:mod:`handler` the network protocol around it (leader sends deals,
responses broadcast, threshold certification on timeout)."""

from drand_tpu.dkg.pedersen import (  # noqa: F401
    Deal,
    DistKeyGenerator,
    DKGError,
    Justification,
    Response,
)
from drand_tpu.dkg.handler import DKGConfig, DKGHandler  # noqa: F401
