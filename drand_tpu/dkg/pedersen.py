"""Pedersen DKG state machine (pure crypto, no networking).

The math mirrors kyber `dkg/pedersen` as consumed by the reference
(/root/reference/dkg/dkg.go:62,115):

* every dealer d samples a secret polynomial g_d of degree t-1 (fresh mode:
  random secret; reshare mode: g_d(0) = d's existing share value), commits
  to its coefficients in G1, and sends participant j the evaluation
  g_d(j+1) encrypted to j's long-term key (ECIES);
* each participant verifies every received sub-share against the dealer's
  commitments (G^s == sum_k C_{d,k} (j+1)^k) and broadcasts an
  approve/complaint response;
* a dealer is *certified* once at least t participants approved it; the
  qualified set QUAL is the certified dealers;
* final share for j:  sum_{d in QUAL} w_d * s_{d,j}, where w_d = 1 in
  fresh mode and the Lagrange weight at zero of d's old index in reshare
  mode — so the collective secret (and hence the distributed public key
  and the beacon chain) is preserved across resharing;
* final commitments: coefficient-wise  sum_{d in QUAL} w_d * C_{d,k}.

Complaint handling is exclusion-based: a dealer that fails to reach t
approvals is simply left out of QUAL (the reference's timeout path
dkg/dkg.go:383-426 behaves the same for non-answering dealers; kyber's
justification round-trip is not reproduced).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence

from drand_tpu.crypto import ecies
from drand_tpu.crypto import refimpl as ref
from drand_tpu.crypto.poly import (
    PriPoly,
    PriShare,
    lagrange_basis_at_zero,
)
from drand_tpu.key import Identity, Pair, Share


class DKGError(Exception):
    pass


@dataclass(frozen=True)
class Deal:
    dealer_index: int
    recipient_index: int
    commits_bytes: tuple          # tuple of 48-byte G1 commitments
    encrypted_share: bytes

    def commits(self) -> List[tuple]:
        return [ref.g1_from_bytes(b) for b in self.commits_bytes]

    def to_dict(self) -> dict:
        return {
            "dealer_index": self.dealer_index,
            "recipient_index": self.recipient_index,
            "commits": [b.hex() for b in self.commits_bytes],
            "encrypted_share": self.encrypted_share.hex(),
        }

    @classmethod
    def from_dict(cls, d: dict) -> "Deal":
        return cls(
            dealer_index=int(d["dealer_index"]),
            recipient_index=int(d["recipient_index"]),
            commits_bytes=tuple(bytes.fromhex(h) for h in d["commits"]),
            encrypted_share=bytes.fromhex(d["encrypted_share"]),
        )


@dataclass(frozen=True)
class Response:
    dealer_index: int
    verifier_index: int
    approved: bool

    def to_dict(self) -> dict:
        return {
            "dealer_index": self.dealer_index,
            "verifier_index": self.verifier_index,
            "approved": self.approved,
        }

    @classmethod
    def from_dict(cls, d: dict) -> "Response":
        return cls(
            dealer_index=int(d["dealer_index"]),
            verifier_index=int(d["verifier_index"]),
            approved=bool(d["approved"]),
        )


class DistKeyGenerator:
    """One participant's DKG state.

    fresh:    participants = the group; every participant deals.
    reshare:  dealers = the old group (must supply old_share); share
              verification/aggregation uses Lagrange weights over old
              indices so the collective key is unchanged.
    """

    def __init__(
        self,
        pair: Pair,
        participants: Sequence[Identity],
        threshold: int,
        old_participants: Optional[Sequence[Identity]] = None,
        old_share: Optional[Share] = None,
        old_threshold: Optional[int] = None,
        old_dist_commits: Optional[Sequence[tuple]] = None,
        entropy: Optional[bytes] = None,
    ):
        self.pair = pair
        self.participants = list(participants)
        self.threshold = threshold
        self.reshare = old_participants is not None
        self.old_participants = list(old_participants or participants)
        self.old_threshold = old_threshold or threshold
        #: reshare only: the old collective commitments, used to check each
        #: dealer actually re-shares its existing share (C_{d,0} must equal
        #: the old public polynomial evaluated at the dealer's index)
        self.old_dist_commits = (
            list(old_dist_commits) if old_dist_commits else None
        )

        self.index = self._find_index(self.participants, pair.public)
        self.dealer_index = self._find_index(
            self.old_participants, pair.public
        )
        if self.index is None and self.dealer_index is None:
            raise DKGError("not a participant of this DKG")
        self.is_dealer = self.dealer_index is not None

        self._poly: Optional[PriPoly] = None
        if self.is_dealer:
            secret = None
            if self.reshare:
                if old_share is None:
                    raise DKGError("resharing requires the old share")
                secret = old_share.share.value
            rng = None
            if entropy:
                rng = _entropy_rng(entropy)
            self._poly = PriPoly.random(threshold, secret=secret, rng=rng)
            self._commits = [
                ref.g1_to_bytes(c) for c in self._poly.commit().commits
            ]

        # receiving state
        self._received: Dict[int, PriShare] = {}      # dealer -> sub-share
        self._commits_seen: Dict[int, tuple] = {}     # dealer -> commits
        self._approvals: Dict[int, set] = {}          # dealer -> verifiers
        self._complaints: Dict[int, set] = {}

    @staticmethod
    def _find_index(nodes: Sequence[Identity],
                    me: Identity) -> Optional[int]:
        for i, n in enumerate(nodes):
            if n.address == me.address and n.key == me.key:
                return i
        return None

    # -- dealing ----------------------------------------------------------

    def deals(self) -> List[Deal]:
        """Encrypted deals, one per participant (self-deal processed
        directly by the caller via process_deal)."""
        if not self.is_dealer:
            raise DKGError("not a dealer in this DKG")
        out = []
        for j, node in enumerate(self.participants):
            share = self._poly.eval(j)
            blob = share.value.to_bytes(32, "big")
            enc = ecies.encrypt(node.key, blob,
                                associated_data=self._ad(j))
            out.append(
                Deal(
                    dealer_index=self.dealer_index,
                    recipient_index=j,
                    commits_bytes=tuple(self._commits),
                    encrypted_share=enc,
                )
            )
        return out

    def _ad(self, recipient_index: int) -> bytes:
        return b"drand-tpu-dkg-deal-%d" % recipient_index

    # -- processing -------------------------------------------------------

    def process_deal(self, deal: Deal) -> Response:
        """Verify a deal addressed to us; produce our response."""
        if self.index is None:
            raise DKGError("only group members process deals")
        if deal.recipient_index != self.index:
            raise DKGError("deal not addressed to this node")
        d = deal.dealer_index
        if not (0 <= d < len(self.old_participants)):
            raise DKGError("unknown dealer index")
        if d in self._received:
            raise DKGError("duplicate deal")
        approved = False
        try:
            commits = deal.commits()
            if len(commits) != self.threshold:
                raise DKGError("bad commitment count")
            if self.reshare and self.old_dist_commits is not None:
                expect0 = _eval_commits(self.old_dist_commits, d)
                if commits[0] != expect0:
                    raise DKGError("dealer does not re-share its share")
            blob = ecies.decrypt(
                self.pair.private, deal.encrypted_share,
                associated_data=self._ad(self.index),
            )
            value = int.from_bytes(blob, "big") % ref.R
            # G^s must equal the commitment polynomial at our index
            expect = _eval_commits(commits, self.index)
            if ref.g1_mul(ref.G1_GEN, value) == expect:
                self._received[d] = PriShare(self.index, value)
                self._commits_seen[d] = tuple(commits)
                approved = True
        except (ecies.EciesError, ValueError, DKGError):
            approved = False
        resp = Response(dealer_index=d, verifier_index=self.index,
                        approved=approved)
        self.process_response(resp)
        return resp

    def process_response(self, resp: Response) -> None:
        if not (0 <= resp.dealer_index < len(self.old_participants)):
            raise DKGError("unknown dealer index in response")
        if not (0 <= resp.verifier_index < len(self.participants)):
            raise DKGError("unknown verifier index in response")
        target = (self._approvals if resp.approved
                  else self._complaints)
        target.setdefault(resp.dealer_index, set()).add(
            resp.verifier_index
        )

    # -- certification ----------------------------------------------------

    def _have_deal(self, d: int) -> bool:
        """Whether we hold dealer d's sub-share — vacuously true for an
        old-only resharing node (index None): it receives no deals at all
        and certifies purely from the response broadcast, like the
        reference's retiring nodes."""
        return self.index is None or d in self._received

    def _certified_dealers(self) -> List[int]:
        out = []
        for d, verifiers in self._approvals.items():
            if len(verifiers) >= self.threshold and self._have_deal(d):
                out.append(d)
        return sorted(out)

    def certified(self) -> bool:
        """Fully certified: every dealer approved by every participant."""
        n = len(self.participants)
        dealers = range(len(self.old_participants))
        return all(
            len(self._approvals.get(d, ())) >= n and self._have_deal(d)
            for d in dealers
        )

    def threshold_certified(self) -> bool:
        """Enough certified dealers to fix the collective secret."""
        need = (self.old_threshold if self.reshare else self.threshold)
        return len(self._certified_dealers()) >= need

    def qual(self) -> List[int]:
        return self._certified_dealers()

    # -- finalization -----------------------------------------------------

    def dist_key_share(self) -> Share:
        if not self.threshold_certified():
            raise DKGError("not enough certified dealers")
        qual = self.qual()
        if self.reshare:
            weights = lagrange_basis_at_zero(qual)
        else:
            weights = {d: 1 for d in qual}
        value = 0
        commits = [None] * self.threshold
        for d in qual:
            w = weights[d]
            value = (value + w * self._received[d].value) % ref.R
            for k, c in enumerate(self._commits_seen[d]):
                commits[k] = ref.g1_add(commits[k], ref.g1_mul(c, w))
        return Share(
            commits=commits,
            share=PriShare(self.index, value),
        )


def _eval_commits(commits: Sequence[tuple], index: int):
    """sum_k C_k * (index+1)^k via Horner in the exponent."""
    x = index + 1
    acc = None
    for c in reversed(list(commits)):
        acc = ref.g1_add(ref.g1_mul(acc, x), c)
    return acc


def _entropy_rng(entropy: bytes):
    """Deterministic byte stream seeded from user entropy + os randomness
    (reference mixes user entropy with crypto/rand; dkg/dkg.go:43)."""
    import hashlib
    import os

    seed = hashlib.sha256(entropy + os.urandom(32)).digest()
    counter = [0]

    def read(n: int) -> bytes:
        out = b""
        while len(out) < n:
            out += hashlib.sha256(
                seed + counter[0].to_bytes(8, "big")
            ).digest()
            counter[0] += 1
        return out[:n]

    return read
