"""Pedersen DKG state machine (pure crypto, no networking).

The math mirrors kyber `dkg/pedersen` as consumed by the reference
(/root/reference/dkg/dkg.go:62,115):

* every dealer d samples a secret polynomial g_d of degree t-1 (fresh mode:
  random secret; reshare mode: g_d(0) = d's existing share value), commits
  to its coefficients in G1, and sends participant j the evaluation
  g_d(j+1) encrypted to j's long-term key (ECIES);
* each participant verifies every received sub-share against the dealer's
  commitments (G^s == sum_k C_{d,k} (j+1)^k) and broadcasts an
  approve/complaint response;
* a dealer is *certified* once at least t participants approved it; the
  qualified set QUAL is the certified dealers;
* final share for j:  sum_{d in QUAL} w_d * s_{d,j}, where w_d = 1 in
  fresh mode and the Lagrange weight at zero of d's old index in reshare
  mode — so the collective secret (and hence the distributed public key
  and the beacon chain) is preserved across resharing;
* final commitments: coefficient-wise  sum_{d in QUAL} w_d * C_{d,k}.

Complaints trigger a justification round (kyber vss semantics,
/root/reference/protobuf/crypto/vss/vss.proto:60-69, consumed at
dkg/dkg.go:319-426): a complained-against dealer publishes the disputed
plaintext sub-share; everyone re-verifies it against the dealer's
commitments.  A valid justification neutralizes the complaint (the
complainer adopts the now-public sub-share), so a lying verifier cannot
knock an honest dealer out of QUAL; an invalid justification proves the
dealer cheated and excludes it outright.  A dealer that never answers a
complaint simply fails to reach certification, as in the reference's
timeout path (dkg/dkg.go:383-426).
"""

from __future__ import annotations

from dataclasses import dataclass, replace
from typing import Dict, List, Optional, Sequence

from drand_tpu.crypto import ecies
from drand_tpu.crypto import refimpl as ref
from drand_tpu.crypto import schnorr
from drand_tpu.crypto.poly import (
    PriPoly,
    PriShare,
    lagrange_basis_at_zero,
)
from drand_tpu.key import Identity, Pair, Share


class DKGError(Exception):
    pass


@dataclass(frozen=True)
class Deal:
    """signature: Schnorr by the dealer's long-term key — unauthenticated
    deals would let anyone induce complaints (and hence public sub-share
    justifications) in a dealer's name (kyber signs its vss messages,
    /root/reference/protobuf/crypto/vss/vss.proto)."""

    dealer_index: int
    recipient_index: int
    commits_bytes: tuple          # tuple of 48-byte G1 commitments
    encrypted_share: bytes
    signature: bytes = b""

    def commits(self) -> List[tuple]:
        return [ref.g1_from_bytes(b) for b in self.commits_bytes]

    def signed_payload(self, session_id: bytes) -> bytes:
        return (b"drand-tpu-dkg-deal" + session_id
                + self.dealer_index.to_bytes(4, "big")
                + self.recipient_index.to_bytes(4, "big")
                + b"".join(self.commits_bytes)
                + self.encrypted_share)

    def to_dict(self) -> dict:
        return {
            "dealer_index": self.dealer_index,
            "recipient_index": self.recipient_index,
            "commits": [b.hex() for b in self.commits_bytes],
            "encrypted_share": self.encrypted_share.hex(),
            "signature": self.signature.hex(),
        }

    @classmethod
    def from_dict(cls, d: dict) -> "Deal":
        return cls(
            dealer_index=int(d["dealer_index"]),
            recipient_index=int(d["recipient_index"]),
            commits_bytes=tuple(bytes.fromhex(h) for h in d["commits"]),
            encrypted_share=bytes.fromhex(d["encrypted_share"]),
            signature=bytes.fromhex(d.get("signature", "")),
        )


@dataclass(frozen=True)
class Response:
    """signature: Schnorr by the verifier — a forged complaint would
    otherwise trick the dealer into publicly revealing the named
    verifier's sub-share via the justification round."""

    dealer_index: int
    verifier_index: int
    approved: bool
    signature: bytes = b""

    def signed_payload(self, session_id: bytes) -> bytes:
        return (b"drand-tpu-dkg-resp" + session_id
                + self.dealer_index.to_bytes(4, "big")
                + self.verifier_index.to_bytes(4, "big")
                + (b"\x01" if self.approved else b"\x00"))

    def to_dict(self) -> dict:
        return {
            "dealer_index": self.dealer_index,
            "verifier_index": self.verifier_index,
            "approved": self.approved,
            "signature": self.signature.hex(),
        }

    @classmethod
    def from_dict(cls, d: dict) -> "Response":
        return cls(
            dealer_index=int(d["dealer_index"]),
            verifier_index=int(d["verifier_index"]),
            approved=bool(d["approved"]),
            signature=bytes.fromhex(d.get("signature", "")),
        )


@dataclass(frozen=True)
class Justification:
    """A dealer's public answer to a complaint: the disputed plaintext
    sub-share, verifiable by anyone against the commitments (which ride
    along so old-only resharing nodes — who receive no deals — can check
    it too)."""

    dealer_index: int
    verifier_index: int           # the complainer
    share_value: int              # revealed sub-share (mod R)
    commits_bytes: tuple          # dealer's commitment polynomial
    #: Schnorr by the dealer: only a justification provably FROM the
    #: dealer may convict it (an unsigned garbage justification must
    #: never mark an honest dealer bad)
    signature: bytes = b""

    def commits(self) -> List[tuple]:
        return [ref.g1_from_bytes(b) for b in self.commits_bytes]

    def signed_payload(self, session_id: bytes) -> bytes:
        return (b"drand-tpu-dkg-just" + session_id
                + self.dealer_index.to_bytes(4, "big")
                + self.verifier_index.to_bytes(4, "big")
                + self.share_value.to_bytes(32, "big")
                + b"".join(self.commits_bytes))

    def to_dict(self) -> dict:
        return {
            "dealer_index": self.dealer_index,
            "verifier_index": self.verifier_index,
            "share_value": "%064x" % self.share_value,
            "commits": [b.hex() for b in self.commits_bytes],
            "signature": self.signature.hex(),
        }

    @classmethod
    def from_dict(cls, d: dict) -> "Justification":
        return cls(
            dealer_index=int(d["dealer_index"]),
            verifier_index=int(d["verifier_index"]),
            share_value=int(d["share_value"], 16),
            commits_bytes=tuple(bytes.fromhex(h) for h in d["commits"]),
            signature=bytes.fromhex(d.get("signature", "")),
        )


class DistKeyGenerator:
    """One participant's DKG state.

    fresh:    participants = the group; every participant deals.
    reshare:  dealers = the old group (must supply old_share); share
              verification/aggregation uses Lagrange weights over old
              indices so the collective key is unchanged.
    """

    def __init__(
        self,
        pair: Pair,
        participants: Sequence[Identity],
        threshold: int,
        old_participants: Optional[Sequence[Identity]] = None,
        old_share: Optional[Share] = None,
        old_threshold: Optional[int] = None,
        old_dist_commits: Optional[Sequence[tuple]] = None,
        entropy: Optional[bytes] = None,
        session_id: bytes = b"",
    ):
        self.pair = pair
        #: domain-separates signatures across DKG runs (the group hash)
        self.session_id = session_id
        self.participants = list(participants)
        self.threshold = threshold
        self.reshare = old_participants is not None
        self.old_participants = list(old_participants or participants)
        self.old_threshold = old_threshold or threshold
        #: reshare only: the old collective commitments, used to check each
        #: dealer actually re-shares its existing share (C_{d,0} must equal
        #: the old public polynomial evaluated at the dealer's index)
        self.old_dist_commits = (
            list(old_dist_commits) if old_dist_commits else None
        )

        self.index = self._find_index(self.participants, pair.public)
        self.dealer_index = self._find_index(
            self.old_participants, pair.public
        )
        if self.index is None and self.dealer_index is None:
            raise DKGError("not a participant of this DKG")
        self.is_dealer = self.dealer_index is not None

        self._poly: Optional[PriPoly] = None
        if self.is_dealer:
            secret = None
            if self.reshare:
                if old_share is None:
                    raise DKGError("resharing requires the old share")
                secret = old_share.share.value
            rng = None
            if entropy:
                rng = _entropy_rng(entropy)
            self._poly = PriPoly.random(threshold, secret=secret, rng=rng)
            self._commits = [
                ref.g1_to_bytes(c) for c in self._poly.commit().commits
            ]

        # receiving state
        self._received: Dict[int, PriShare] = {}      # dealer -> sub-share
        self._commits_seen: Dict[int, tuple] = {}     # dealer -> commits
        self._approvals: Dict[int, set] = {}          # dealer -> verifiers
        self._complaints: Dict[int, set] = {}
        #: dealers proven malicious (invalid justification) — never QUAL
        self._bad_dealers: set = set()
        #: complaints we (as dealer) already answered, (dealer, verifier)
        self._justified: set = set()
        #: justifications that arrived before the complaint they answer
        #: (async networks may invert the order), (dealer, verifier) -> J
        self._early_justs: Dict = {}

    @staticmethod
    def _find_index(nodes: Sequence[Identity],
                    me: Identity) -> Optional[int]:
        for i, n in enumerate(nodes):
            if n.address == me.address and n.key == me.key:
                return i
        return None

    # -- dealing ----------------------------------------------------------

    def deals(self) -> List[Deal]:
        """Encrypted deals, one per participant (self-deal processed
        directly by the caller via process_deal)."""
        if not self.is_dealer:
            raise DKGError("not a dealer in this DKG")
        out = []
        for j, node in enumerate(self.participants):
            share = self._poly.eval(j)
            blob = share.value.to_bytes(32, "big")
            enc = ecies.encrypt(node.key, blob,
                                associated_data=self._ad(j))
            deal = Deal(
                dealer_index=self.dealer_index,
                recipient_index=j,
                commits_bytes=tuple(self._commits),
                encrypted_share=enc,
            )
            out.append(replace(deal, signature=schnorr.sign(
                self.pair.private, deal.signed_payload(self.session_id)
            )))
        return out

    def _ad(self, recipient_index: int) -> bytes:
        return b"drand-tpu-dkg-deal-%d" % recipient_index

    # -- processing -------------------------------------------------------

    def process_deal(self, deal: Deal) -> Response:
        """Verify a deal addressed to us; produce our response."""
        if self.index is None:
            raise DKGError("only group members process deals")
        if deal.recipient_index != self.index:
            raise DKGError("deal not addressed to this node")
        d = deal.dealer_index
        if not (0 <= d < len(self.old_participants)):
            raise DKGError("unknown dealer index")
        # authenticate BEFORE judging content: a forged deal must be
        # dropped outright, never answered with a complaint (the
        # complaint would trigger a public sub-share justification)
        if not schnorr.verify(
            self.old_participants[d].key,
            deal.signed_payload(self.session_id),
            deal.signature,
        ):
            raise DKGError("deal signature invalid")
        if d in self._received:
            raise DKGError("duplicate deal")
        approved = False
        try:
            commits = deal.commits()
            if len(commits) != self.threshold:
                raise DKGError("bad commitment count")
            if self.reshare and self.old_dist_commits is not None:
                expect0 = _eval_commits(self.old_dist_commits, d)
                if commits[0] != expect0:
                    raise DKGError("dealer does not re-share its share")
            blob = ecies.decrypt(
                self.pair.private, deal.encrypted_share,
                associated_data=self._ad(self.index),
            )
            value = int.from_bytes(blob, "big") % ref.R
            # G^s must equal the commitment polynomial at our index
            expect = _eval_commits(commits, self.index)
            if ref.g1_mul(ref.G1_GEN, value) == expect:
                self._received[d] = PriShare(self.index, value)
                self._commits_seen[d] = tuple(commits)
                approved = True
        except (ecies.EciesError, ValueError, DKGError):
            approved = False
        resp = Response(dealer_index=d, verifier_index=self.index,
                        approved=approved)
        resp = replace(resp, signature=schnorr.sign(
            self.pair.private, resp.signed_payload(self.session_id)
        ))
        self.process_response(resp)
        return resp

    def process_response(self, resp: Response) -> None:
        """One response per (dealer, verifier): the first wins (kyber
        rejects duplicate responses, so a late forged complaint cannot
        override an already-recorded approval)."""
        d, v = resp.dealer_index, resp.verifier_index
        if not (0 <= d < len(self.old_participants)):
            raise DKGError("unknown dealer index in response")
        if not (0 <= v < len(self.participants)):
            raise DKGError("unknown verifier index in response")
        if not schnorr.verify(
            self.participants[v].key,
            resp.signed_payload(self.session_id),
            resp.signature,
        ):
            raise DKGError("response signature invalid")
        if (v in self._approvals.get(d, ())
                or v in self._complaints.get(d, ())):
            return
        target = (self._approvals if resp.approved
                  else self._complaints)
        target.setdefault(d, set()).add(v)
        if not resp.approved:
            early = self._early_justs.pop((d, v), None)
            if early is not None:
                self.process_justification(early)

    # -- justification round ----------------------------------------------

    def pending_complaints(self) -> List[Response]:
        """Complaints against OUR dealing that we have not yet answered."""
        if not self.is_dealer:
            return []
        d = self.dealer_index
        return [
            Response(dealer_index=d, verifier_index=v, approved=False)
            for v in sorted(self._complaints.get(d, ()))
            if (d, v) not in self._justified
        ]

    def justify(self, complaint: Response) -> Justification:
        """Answer a complaint against our dealing by revealing the
        disputed plaintext sub-share (it becomes public; the dealing
        stays certified).  Mirrors kyber vss Justification
        (/root/reference/protobuf/crypto/vss/vss.proto:60-69)."""
        if not self.is_dealer:
            raise DKGError("not a dealer in this DKG")
        if complaint.dealer_index != self.dealer_index:
            raise DKGError("complaint is not about our dealing")
        if complaint.approved:
            raise DKGError("response is not a complaint")
        v = complaint.verifier_index
        if not (0 <= v < len(self.participants)):
            raise DKGError("unknown verifier index")
        self._justified.add((self.dealer_index, v))
        just = Justification(
            dealer_index=self.dealer_index,
            verifier_index=v,
            share_value=self._poly.eval(v).value,
            commits_bytes=tuple(self._commits),
        )
        return replace(just, signature=schnorr.sign(
            self.pair.private, just.signed_payload(self.session_id)
        ))

    def process_justification(self, just: Justification) -> None:
        """Re-verify a revealed sub-share against the dealer's
        commitments.  Valid: the complaint is neutralized (counts as the
        complainer's approval; the complainer — if us — adopts the
        now-public sub-share).  Invalid: the dealer is proven malicious
        and excluded from QUAL outright."""
        d = just.dealer_index
        v = just.verifier_index
        if not (0 <= d < len(self.old_participants)):
            raise DKGError("unknown dealer index in justification")
        if not (0 <= v < len(self.participants)):
            raise DKGError("unknown verifier index in justification")
        # authenticity gate: only a justification provably signed by the
        # dealer may count AGAINST it — an unsigned forgery is dropped
        # here (raising), never recorded in _bad_dealers
        if not schnorr.verify(
            self.old_participants[d].key,
            just.signed_payload(self.session_id),
            just.signature,
        ):
            raise DKGError("justification signature invalid")
        # the proof-of-cheating check runs UNCONDITIONALLY: a dealer that
        # signs an invalid justification convicts itself on every node,
        # whether or not that node happens to have recorded the matching
        # complaint (the complainer itself may hold an approval instead —
        # first response wins — and must still convict)
        try:
            commits = just.commits()
            if len(commits) != self.threshold:
                raise DKGError("bad commitment count")
            if any(c is None for c in commits):
                raise DKGError("invalid commitment point")
            # commits must be THE dealer's commits: match what our own
            # deal carried (when we got one), and in a reshare the free
            # coefficient must still re-share the dealer's old share
            seen = self._commits_seen.get(d)
            if seen is not None and tuple(commits) != tuple(seen):
                raise DKGError("justification commits differ from deal")
            if self.reshare and self.old_dist_commits is not None:
                if commits[0] != _eval_commits(self.old_dist_commits, d):
                    raise DKGError("dealer does not re-share its share")
            value = just.share_value % ref.R
            if ref.g1_mul(ref.G1_GEN, value) != _eval_commits(commits, v):
                raise DKGError("revealed sub-share fails commitments")
        except (DKGError, ValueError):
            # provably cheating: an honest dealer can always produce a
            # valid justification for its own dealing.  ValueError covers
            # malformed commit encodings (wrong length / off-curve), the
            # same provable-garbage class process_deal treats as invalid.
            self._bad_dealers.add(d)
            self._approvals.pop(d, None)
            return
        # a VALID justification only NEUTRALIZES a recorded complaint
        # (kyber's aggregator rejects unsolicited ones): without this
        # gate a rogue dealer could self-certify by publishing
        # justifications for every verifier, bypassing genuine approvals
        # entirely.  If the complaint simply hasn't arrived yet (async
        # ordering), buffer the justification and replay it from
        # process_response.
        if v not in self._complaints.get(d, ()):
            self._early_justs[(d, v)] = just
            return
        # valid: neutralize the complaint
        self._complaints.get(d, set()).discard(v)
        self._approvals.setdefault(d, set()).add(v)
        if v == self.index and d not in self._received:
            # we were the complainer (e.g. undecryptable deal): adopt the
            # now-public sub-share so QUAL membership of d stays usable
            self._received[d] = PriShare(self.index, value)
            self._commits_seen[d] = tuple(commits)

    # -- certification ----------------------------------------------------

    def _have_deal(self, d: int) -> bool:
        """Whether we hold dealer d's sub-share — vacuously true for an
        old-only resharing node (index None): it receives no deals at all
        and certifies purely from the response broadcast, like the
        reference's retiring nodes."""
        return self.index is None or d in self._received

    def _dealer_ok(self, d: int) -> bool:
        """Not proven malicious and no unanswered complaint (kyber's
        DealCertified: a standing complaint excludes the dealer until a
        valid justification clears it)."""
        return d not in self._bad_dealers and not self._complaints.get(d)

    def _certified_dealers(self) -> List[int]:
        out = []
        for d, verifiers in self._approvals.items():
            if not self._dealer_ok(d):
                continue
            if len(verifiers) >= self.threshold and self._have_deal(d):
                out.append(d)
        return sorted(out)

    def certified(self) -> bool:
        """Fully certified: every dealer approved by every participant."""
        n = len(self.participants)
        dealers = range(len(self.old_participants))
        return all(
            self._dealer_ok(d)
            and len(self._approvals.get(d, ())) >= n
            and self._have_deal(d)
            for d in dealers
        )

    def threshold_certified(self) -> bool:
        """Enough certified dealers to fix the collective secret."""
        need = (self.old_threshold if self.reshare else self.threshold)
        return len(self._certified_dealers()) >= need

    def qual(self) -> List[int]:
        return self._certified_dealers()

    # -- finalization -----------------------------------------------------

    def dist_key_share(self) -> Share:
        if not self.threshold_certified():
            raise DKGError("not enough certified dealers")
        qual = self.qual()
        if self.reshare:
            weights = lagrange_basis_at_zero(qual)
        else:
            weights = {d: 1 for d in qual}
        value = 0
        commits = [None] * self.threshold
        for d in qual:
            w = weights[d]
            value = (value + w * self._received[d].value) % ref.R
            for k, c in enumerate(self._commits_seen[d]):
                commits[k] = ref.g1_add(commits[k], ref.g1_mul(c, w))
        return Share(
            commits=commits,
            share=PriShare(self.index, value),
        )


def _eval_commits(commits: Sequence[tuple], index: int):
    """sum_k C_k * (index+1)^k via Horner in the exponent."""
    x = index + 1
    acc = None
    for c in reversed(list(commits)):
        acc = ref.g1_add(ref.g1_mul(acc, x), c)
    return acc


def _entropy_rng(entropy: bytes):
    """Deterministic byte stream seeded from user entropy + os randomness
    (reference mixes user entropy with crypto/rand; dkg/dkg.go:43)."""
    import hashlib
    import os

    seed = hashlib.sha256(entropy + os.urandom(32)).digest()
    counter = [0]

    def read(n: int) -> bytes:
        out = b""
        while len(out) < n:
            out += hashlib.sha256(
                seed + counter[0].to_bytes(8, "big")
            ).digest()
            counter[0] += 1
        return out[:n]

    return read
