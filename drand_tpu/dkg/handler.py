"""DKG network protocol: deals out, responses broadcast, timeout certify.

Mirrors /root/reference/dkg/dkg.go behavior:
* the leader starts by sending deals (`Start` :183 -> `sendDeals` :431);
  every other dealer sends its own deals upon first contact (:164-182);
* deals go to new-group members only, responses are broadcast to both old
  and new groups (:495-499);
* full certification finalizes immediately; otherwise a timer fires and
  threshold certification is accepted (`startTimer` :236-252,
  `checkCertified` :383-426);
* `wait_share()` resolves with the final Share (or None for old-only
  nodes in a reshare), `wait_error()` with a failure.
"""

from __future__ import annotations

import asyncio
import contextlib
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Set

from drand_tpu.dkg.pedersen import (
    Deal,
    DistKeyGenerator,
    DKGError,
    Justification,
    Response,
)
from drand_tpu.key import Group, Identity, Pair, Share
from drand_tpu.obs import trace as obs_trace
from drand_tpu.utils import metrics
from drand_tpu.utils.clock import Clock

from drand_tpu.utils.logging import get_logger

log = get_logger("dkg")

DEFAULT_TIMEOUT = 60.0  # reference core/constants.go:34


class DKGNetwork:
    """Outbound transport for DKG packets."""

    async def send_dkg(self, peer: Identity, packet: dict) -> None:
        raise NotImplementedError


@dataclass
class DKGConfig:
    pair: Pair
    new_group: Group
    old_group: Optional[Group] = None          # reshare only
    old_share: Optional[Share] = None          # reshare, old nodes only
    timeout: float = DEFAULT_TIMEOUT
    clock: Clock = field(default_factory=Clock)
    entropy: Optional[bytes] = None


class DKGHandler:
    def __init__(self, cfg: DKGConfig, net: DKGNetwork):
        self.cfg = cfg
        self.net = net
        old_group = cfg.old_group
        old_commits = None
        if old_group is not None and cfg.old_share is not None:
            old_commits = cfg.old_share.commits
        self.dkg = DistKeyGenerator(
            pair=cfg.pair,
            participants=cfg.new_group.nodes,
            threshold=cfg.new_group.threshold,
            old_participants=old_group.nodes if old_group else None,
            old_share=cfg.old_share,
            old_threshold=old_group.threshold if old_group else None,
            old_dist_commits=old_commits,
            entropy=cfg.entropy,
            # signatures are domain-separated by the group hash so a
            # message from one DKG run cannot be replayed into another
            session_id=cfg.new_group.hash(),
        )
        # one distributed trace per DKG run: the id derives from the
        # session id (new-group hash), so all participants stitch
        self._trace_id = (
            obs_trace.dkg_trace_id(cfg.new_group.hash())
            if obs_trace.TRACER.enabled else None
        )
        self._sent_deals = False
        self._done = False
        self._share_fut: asyncio.Future = (
            asyncio.get_event_loop().create_future()
        )
        self._timer_task: Optional[asyncio.Task] = None
        #: in-flight outbound sends — retained so asyncio's weak task
        #: reference can't collect a deal/response mid-RPC
        self._send_tasks: Set[asyncio.Task] = set()
        self._lock = asyncio.Lock()
        #: per-phase wall-time accounting (deal verification is the
        #: slowest protocol phase — ROADMAP direction 3 batches it);
        #: surfaced in /v1/status and the drand_dkg_phase_seconds metric
        self.phase_seconds: Dict[str, dict] = {}

    def _span(self, name: str, **attrs):
        """Per-phase span inside this DKG run's distributed trace."""
        attrs.setdefault("addr", self.cfg.pair.public.address)
        return obs_trace.TRACER.span(
            name, trace_id=self._trace_id, attrs=attrs
        )

    @contextlib.contextmanager
    def _phase(self, name: str, **attrs):
        """`_span` plus phase timing: accumulates into `phase_seconds`
        and the per-phase histogram even when tracing is off."""
        with self._span(name, **attrs) as span:
            t0 = time.perf_counter()
            try:
                yield span
            finally:
                dt = time.perf_counter() - t0
                phase = name.split(".", 1)[-1]
                st = self.phase_seconds.setdefault(phase, {
                    "count": 0, "seconds_total": 0.0, "max_seconds": 0.0,
                    "last_seconds": 0.0,
                })
                st["count"] += 1
                st["seconds_total"] += dt
                st["max_seconds"] = max(st["max_seconds"], dt)
                st["last_seconds"] = dt
                metrics.histogram(
                    "drand_dkg_phase_seconds",
                    "Wall time of DKG protocol phases (deal generation/"
                    "verification, responses, justifications, finalize)",
                    labels={"phase": phase},
                ).observe(dt)

    # -- control ----------------------------------------------------------

    async def start(self) -> None:
        """Leader entry point: send deals and arm the timeout."""
        self._arm_timer()
        await self._send_deals()

    def wait_share(self) -> asyncio.Future:
        return self._share_fut

    # -- outbound ---------------------------------------------------------

    async def _send_deals(self) -> None:
        async with self._lock:
            if self._sent_deals or not self.dkg.is_dealer:
                return
            self._sent_deals = True
        with self._phase("dkg.deal_out") as span:
            deals = self.dkg.deals()
            span.set_attr("deals", len(deals))
            for deal in deals:
                target = self.cfg.new_group.nodes[deal.recipient_index]
                if self._is_self(target):
                    resp = self.dkg.process_deal(deal)
                    await self._broadcast_response(resp)
                else:
                    await self._send(
                        target, {"dkg_deal": deal.to_dict()}
                    )

    async def _broadcast_response(self, resp: Response) -> None:
        packet = {"dkg_response": resp.to_dict()}
        for node in self._all_nodes():
            if self._is_self(node):
                continue
            await self._send(node, packet)
        self._check_done()

    def _all_nodes(self) -> List[Identity]:
        nodes = list(self.cfg.new_group.nodes)
        if self.cfg.old_group is not None:
            seen = {(n.address, n.key) for n in nodes}
            for n in self.cfg.old_group.nodes:
                if (n.address, n.key) not in seen:
                    nodes.append(n)
        return nodes

    def _is_self(self, node: Identity) -> bool:
        return (node.address == self.cfg.pair.public.address
                and node.key == self.cfg.pair.public.key)

    async def _send(self, peer: Identity, packet: dict) -> None:
        """Fire-and-forget (the reference uses a goroutine per send,
        dkg/dkg.go:452-473): awaiting peers inline would nest RPC chains
        across nodes and deadlock the mesh."""

        async def _go():
            try:
                await self.net.send_dkg(peer, packet)
            except Exception as exc:
                log.debug("dkg send failed", to=peer.address, err=exc)

        t = asyncio.create_task(_go())
        self._send_tasks.add(t)
        t.add_done_callback(self._send_tasks.discard)

    # -- inbound ----------------------------------------------------------

    async def process(self, packet: dict) -> None:
        """Inbound DKG packet (reference Process dkg/dkg.go:164)."""
        if self._done:
            return
        # ANY first contact triggers our own dealing (non-leader path).
        # Responses count too: in a reshare, old-only nodes never receive
        # deals (deals go to new members only) yet must deal themselves —
        # the reference starts their DKG on the first reshare packet of
        # any kind (core/drand_public.go:45-49).
        self._arm_timer()
        await self._send_deals()
        if "dkg_deal" in packet:
            with self._phase("dkg.deal"):
                deal = Deal.from_dict(packet["dkg_deal"])
                try:
                    resp = self.dkg.process_deal(deal)
                except DKGError as exc:
                    log.warning("bad deal", err=exc)
                    return
                await self._broadcast_response(resp)
        elif "dkg_response" in packet:
            with self._phase("dkg.response"):
                try:
                    self.dkg.process_response(
                        Response.from_dict(packet["dkg_response"])
                    )
                except DKGError as exc:
                    log.warning("bad response", err=exc)
                    return
                # a complaint against OUR dealing: answer it publicly by
                # revealing the disputed sub-share (kyber justification,
                # vss.proto:60-69) so a false complaint cannot exclude us
                await self._broadcast_justifications()
                self._check_done()
        elif "dkg_justification" in packet:
            with self._phase("dkg.justification"):
                try:
                    self.dkg.process_justification(
                        Justification.from_dict(
                            packet["dkg_justification"]
                        )
                    )
                except DKGError as exc:
                    log.warning("bad justification", err=exc)
                    return
                self._check_done()

    async def _broadcast_justifications(self) -> None:
        for complaint in self.dkg.pending_complaints():
            just = self.dkg.justify(complaint)
            log.info(
                "justifying complaint",
                verifier=complaint.verifier_index,
                dealer=complaint.dealer_index,
            )
            # apply locally too (we don't receive our own broadcast):
            # neutralizes the complaint in our own certification state
            self.dkg.process_justification(just)
            packet = {"dkg_justification": just.to_dict()}
            for node in self._all_nodes():
                if self._is_self(node):
                    continue
                await self._send(node, packet)

    # -- certification ----------------------------------------------------

    def _arm_timer(self) -> None:
        if self._timer_task is None:
            self._timer_task = asyncio.create_task(self._timer())

    async def _timer(self) -> None:
        await self.cfg.clock.sleep(self.cfg.timeout)
        if self._done:
            return
        if self.dkg.threshold_certified():
            log.info("dkg timeout: accepting threshold certification")
            self._finalize()
        else:
            self._fail(DKGError(
                "dkg timed out without threshold certification"
            ))

    def _check_done(self) -> None:
        if not self._done and self.dkg.certified():
            self._finalize()

    def _finalize(self) -> None:
        self._done = True
        if self._timer_task is not None:
            self._timer_task.cancel()
        with self._phase("dkg.finalize") as span:
            try:
                if self.dkg.index is None:
                    # old-only node in a reshare: participates as dealer
                    # but gets no share in the new group
                    result = None
                else:
                    result = self.dkg.dist_key_share()
            except DKGError as exc:
                span.set_attr("error", repr(exc))
                self._fail(exc)
                return
            span.set_attr("has_share", result is not None)
        if not self._share_fut.done():
            self._share_fut.set_result(result)

    def _fail(self, exc: Exception) -> None:
        self._done = True
        if not self._share_fut.done():
            self._share_fut.set_exception(exc)

    def qualified_group(self) -> Group:
        """The new group (QUAL applies to dealers; new membership is the
        configured new group — reference QualifiedGroup dkg/dkg.go:222)."""
        return self.cfg.new_group
