"""Group descriptor: node list, threshold, period, genesis.

Mirrors /root/reference/key/group.go: ordered node identities, the signing
threshold, beacon period, genesis/transition times, and the genesis seed.
The blake2b group hash pins the exact configuration; the genesis seed (used
to derive round 0's beacon) defaults to the hash of the group *without* a
seed (key/group.go:83-102, 201).
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

from drand_tpu.crypto import refimpl as ref
from drand_tpu.key.keys import Identity, minimum_threshold
from drand_tpu.utils import format_duration, parse_duration


@dataclass
class Group:
    nodes: List[Identity]
    threshold: int
    period: float = 60.0           # seconds
    genesis_time: int = 0          # unix seconds
    transition_time: int = 0       # unix seconds (resharing)
    genesis_seed: bytes = b""
    #: per-objective SLO overrides ([[SLO]] tables in the group file;
    #: keys validated by obs/slo.parse_overrides, applied
    #: first-registration-wins by the beacon handler).  Operational
    #: config only: deliberately EXCLUDED from the group hash so adding
    #: an alerting tweak doesn't change the chain's identity.
    slo: List[Dict] = field(default_factory=list)

    def __post_init__(self):
        n = len(self.nodes)
        if self.threshold < minimum_threshold(n):
            raise ValueError(
                f"threshold {self.threshold} below minimum "
                f"{minimum_threshold(n)} for {n} nodes"
            )
        if self.threshold > n:
            raise ValueError("threshold larger than group size")

    def __len__(self) -> int:
        return len(self.nodes)

    def index(self, identity: Identity) -> Optional[int]:
        for i, node in enumerate(self.nodes):
            if node.address == identity.address and \
                    node.key == identity.key:
                return i
        return None

    def contains(self, identity: Identity) -> bool:
        return self.index(identity) is not None

    def public_keys(self) -> List[tuple]:
        return [n.key for n in self.nodes]

    def hash(self) -> bytes:
        """blake2b-256 digest over the canonical group description."""
        h = hashlib.blake2b(digest_size=32)
        for i, node in enumerate(self.nodes):
            h.update(i.to_bytes(4, "little"))
            h.update(ref.g1_to_bytes(node.key))
        h.update(self.threshold.to_bytes(4, "little"))
        h.update(int(self.genesis_time).to_bytes(8, "little"))
        if self.transition_time:
            h.update(int(self.transition_time).to_bytes(8, "little"))
        return h.digest()

    def get_genesis_seed(self) -> bytes:
        """The chain's genesis seed; defaults to the group hash."""
        if not self.genesis_seed:
            self.genesis_seed = self.hash()
        return self.genesis_seed

    # -- TOML ------------------------------------------------------------

    def to_dict(self) -> Dict:
        d = {
            "Threshold": self.threshold,
            "Period": format_duration(self.period),
            "GenesisTime": int(self.genesis_time),
            "TransitionTime": int(self.transition_time),
            "Nodes": [n.to_dict() for n in self.nodes],
        }
        if self.genesis_seed:
            d["GenesisSeed"] = self.genesis_seed.hex()
        if self.slo:
            d["SLO"] = [dict(e) for e in self.slo]
        return d

    @classmethod
    def from_dict(cls, d: Dict) -> "Group":
        return cls(
            nodes=[Identity.from_dict(n) for n in d["Nodes"]],
            threshold=int(d["Threshold"]),
            period=parse_duration(d.get("Period", 60.0)),
            genesis_time=int(d.get("GenesisTime", 0)),
            transition_time=int(d.get("TransitionTime", 0)),
            genesis_seed=bytes.fromhex(d["GenesisSeed"])
            if d.get("GenesisSeed") else b"",
            slo=[dict(e) for e in d.get("SLO", [])],
        )


def merge_groups(old_nodes: Sequence[Identity],
                 new_nodes: Sequence[Identity]) -> List[Identity]:
    """Union for resharing: new nodes first, then old ones not in new
    (reference key/group.go:221 MergeGroup)."""
    seen = {(n.address, n.key) for n in new_nodes}
    merged = list(new_nodes)
    for n in old_nodes:
        if (n.address, n.key) not in seen:
            merged.append(n)
    return merged
