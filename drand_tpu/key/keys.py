"""Keypairs, identities, shares, distributed public keys.

Mirrors /root/reference/key/keys.go: `Pair` (long-term BLS keypair on G1),
`Identity` (public key + dial address + TLS flag), `Share` (one node's DKG
output: public commitments + private share), `DistPublic` (the collective
key's coefficient commitments).  Encodings: 48-byte compressed G1 hex.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional

from drand_tpu.crypto import refimpl as ref
from drand_tpu.crypto.poly import PriShare, PubPoly, rand_scalar


def default_threshold(n: int) -> int:
    """floor(n/2) + 1 (reference key/keys.go:367)."""
    return n // 2 + 1


def minimum_threshold(n: int) -> int:
    """Smallest sound threshold (vss.MinimumT): floor((n+1)/2)."""
    return (n + 1) // 2


@dataclass(frozen=True)
class Identity:
    """A node's public identity: G1 key + reachable address (+ TLS)."""

    address: str
    #: affine G1 point; None for address-only identities (the replica
    #: ring forwards by address and never needs the peer's key)
    key: Optional[tuple] = None
    tls: bool = False

    @property
    def key_hex(self) -> str:
        return ref.g1_to_bytes(self.key).hex()

    def to_dict(self) -> Dict:
        return {"Address": self.address, "Key": self.key_hex,
                "TLS": self.tls}

    @classmethod
    def from_dict(cls, d: Dict) -> "Identity":
        return cls(
            address=d["Address"],
            key=ref.g1_from_bytes(bytes.fromhex(d["Key"])),
            tls=bool(d.get("TLS", False)),
        )


@dataclass
class Pair:
    """Long-term keypair: secret scalar + public identity."""

    private: int
    public: Identity

    @classmethod
    def generate(cls, address: str, tls: bool = False,
                 rng=None) -> "Pair":
        sk = rand_scalar(rng)
        pk = ref.g1_mul(ref.G1_GEN, sk)
        return cls(private=sk, public=Identity(address, pk, tls))

    def to_dict(self) -> Dict:
        return {
            "Key": self.private.to_bytes(32, "big").hex(),
            "Public": self.public.to_dict(),
        }

    @classmethod
    def from_dict(cls, d: Dict) -> "Pair":
        return cls(
            private=int.from_bytes(bytes.fromhex(d["Key"]), "big"),
            public=Identity.from_dict(d["Public"]),
        )


@dataclass
class DistPublic:
    """Distributed public key: commitments to the collective polynomial."""

    coefficients: List[tuple]

    def key(self) -> tuple:
        """The collective public key (coefficient 0)."""
        return self.coefficients[0]

    def pub_poly(self) -> PubPoly:
        return PubPoly(self.coefficients)

    def to_dict(self) -> Dict:
        return {
            "Coefficients": [
                ref.g1_to_bytes(c).hex() for c in self.coefficients
            ]
        }

    @classmethod
    def from_dict(cls, d: Dict) -> "DistPublic":
        return cls(
            coefficients=[
                ref.g1_from_bytes(bytes.fromhex(h))
                for h in d["Coefficients"]
            ]
        )

    def equal(self, other: "DistPublic") -> bool:
        return self.coefficients == other.coefficients


@dataclass
class Share:
    """One node's DKG result: commitments + its private share."""

    commits: List[tuple]
    share: PriShare

    def public(self) -> DistPublic:
        return DistPublic(list(self.commits))

    def pub_poly(self) -> PubPoly:
        return PubPoly(list(self.commits))

    def to_dict(self) -> Dict:
        return {
            "Commits": [ref.g1_to_bytes(c).hex() for c in self.commits],
            "Index": self.share.index,
            "Share": self.share.value.to_bytes(32, "big").hex(),
        }

    @classmethod
    def from_dict(cls, d: Dict) -> "Share":
        return cls(
            commits=[
                ref.g1_from_bytes(bytes.fromhex(h)) for h in d["Commits"]
            ],
            share=PriShare(
                index=int(d["Index"]),
                value=int.from_bytes(bytes.fromhex(d["Share"]), "big"),
            ),
        )
