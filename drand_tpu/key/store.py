"""Durable key-material store (TOML files, restrictive permissions).

Mirrors /root/reference/key/store.go: a file store rooted at the node's
base folder, with `key/` (0700) holding the private material and `groups/`
(0740) the shared descriptors.  Everything is TOML: write with the minimal
serializer, read with stdlib tomllib.  A MemStore mirrors the reference's
test key store (/root/reference/test/key_store.go).
"""

from __future__ import annotations

import os
from drand_tpu.utils import tomlcompat as tomllib
from pathlib import Path
from typing import Optional

from drand_tpu.key.group import Group
from drand_tpu.key.keys import DistPublic, Pair, Share
from drand_tpu.utils import toml_dumps

KEY_FOLDER = "key"
GROUP_FOLDER = "groups"
PAIR_FILE = "drand_id.toml"
SHARE_FILE = "dist_key.private.toml"
DIST_FILE = "dist_key.public.toml"
GROUP_FILE = "drand_group.toml"


class KeyNotFound(Exception):
    pass


class FileStore:
    def __init__(self, base_dir: str):
        self.base = Path(base_dir)
        self.key_dir = self.base / KEY_FOLDER
        self.group_dir = self.base / GROUP_FOLDER
        self.key_dir.mkdir(parents=True, exist_ok=True)
        self.group_dir.mkdir(parents=True, exist_ok=True)
        os.chmod(self.base, 0o740)
        os.chmod(self.key_dir, 0o700)
        os.chmod(self.group_dir, 0o740)

    # -- private write helper --------------------------------------------

    def _write(self, path: Path, data: dict, mode: int) -> None:
        tmp = path.with_suffix(".tmp")
        tmp.write_text(toml_dumps(data))
        os.chmod(tmp, mode)
        tmp.replace(path)

    def _read(self, path: Path) -> dict:
        if not path.exists():
            raise KeyNotFound(str(path))
        with open(path, "rb") as fh:
            return tomllib.load(fh)

    # -- keypair ----------------------------------------------------------

    def save_key_pair(self, pair: Pair) -> None:
        self._write(self.key_dir / PAIR_FILE, pair.to_dict(), 0o600)

    def load_key_pair(self) -> Pair:
        return Pair.from_dict(self._read(self.key_dir / PAIR_FILE))

    # -- DKG share --------------------------------------------------------

    def save_share(self, share: Share) -> None:
        self._write(self.key_dir / SHARE_FILE, share.to_dict(), 0o600)

    def load_share(self) -> Share:
        return Share.from_dict(self._read(self.key_dir / SHARE_FILE))

    # -- distributed public key ------------------------------------------

    def save_dist_public(self, dist: DistPublic) -> None:
        self._write(self.group_dir / DIST_FILE, dist.to_dict(), 0o644)

    def load_dist_public(self) -> DistPublic:
        return DistPublic.from_dict(self._read(self.group_dir / DIST_FILE))

    # -- group ------------------------------------------------------------

    def save_group(self, group: Group) -> None:
        self._write(self.group_dir / GROUP_FILE, group.to_dict(), 0o644)

    def load_group(self) -> Group:
        return Group.from_dict(self._read(self.group_dir / GROUP_FILE))


class MemStore:
    """In-memory store with the same surface (for tests/daemon harness)."""

    def __init__(self, pair: Optional[Pair] = None):
        self._pair = pair
        self._share: Optional[Share] = None
        self._dist: Optional[DistPublic] = None
        self._group: Optional[Group] = None

    def save_key_pair(self, pair):
        self._pair = pair

    def load_key_pair(self):
        if self._pair is None:
            raise KeyNotFound("keypair")
        return self._pair

    def save_share(self, share):
        self._share = share

    def load_share(self):
        if self._share is None:
            raise KeyNotFound("share")
        return self._share

    def save_dist_public(self, dist):
        self._dist = dist

    def load_dist_public(self):
        if self._dist is None:
            raise KeyNotFound("dist public")
        return self._dist

    def save_group(self, group):
        self._group = group

    def load_group(self):
        if self._group is None:
            raise KeyNotFound("group")
        return self._group
