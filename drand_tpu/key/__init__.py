"""Key material and group model (host side).

Equivalent of the reference's `key/` package: long-term keypairs, node
identities, DKG share wrappers, the distributed public key, and the group
descriptor (/root/reference/key/keys.go, key/group.go)."""

from drand_tpu.key.keys import (  # noqa: F401
    DistPublic,
    Identity,
    Pair,
    Share,
    default_threshold,
    minimum_threshold,
)
from drand_tpu.key.group import Group  # noqa: F401
from drand_tpu.key.store import FileStore, MemStore  # noqa: F401
