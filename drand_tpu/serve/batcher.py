"""Batch scheduler: coalesce queued requests into device-sized batches.

The continuous-batching core of the gateway, shaped like a model
server's request scheduler: a bounded queue feeds a single consumer
loop that flushes a batch when EITHER it holds `max_batch` items OR
`max_wait` has elapsed since the first item arrived — so p50 latency
stays one tick under light load while batches fill (and throughput
saturates) under heavy load.  While a flush is executing, the next
batch accumulates in the queue; there is never more than one batch in
flight, which keeps the device stream serialized and the jitted kernel
on one compiled shape bucket (tbls.JaxScheme._bucket pads the rest).

Admission control is the queue bound: `submit` raises
`asyncio.QueueFull` (translated to an explicit 429/RESOURCE_EXHAUSTED
by the gateway) instead of queueing unbounded latency.

Fairness: with a `key_of` callable the scheduler keeps one FIFO lane
per key (per client) and assembles batches by round-robin over the
lanes — a client flooding a thousand requests no longer pushes every
other caller's work to the back of one global FIFO; the bounded queue
then only enforces the TOTAL backlog (per-key bounds are the gateway's
in-flight cap).
"""

from __future__ import annotations

import asyncio
from collections import OrderedDict, deque
from dataclasses import dataclass
from typing import Awaitable, Callable, List, Optional

from drand_tpu.utils.logging import get_logger

log = get_logger("serve.batcher")


@dataclass
class BatchItem:
    """One queued verification unit.

    `deadline` is an absolute event-loop time; the flush callback drops
    items already past it (reject-at-pop, never serve-late).  `payload`
    is opaque to the scheduler — the gateway stores its request there.

    `future` stays None until `submit` binds one on the RUNNING loop:
    a default factory calling `asyncio.get_event_loop()` would bind
    whatever loop (or fresh implicit loop) is current on the
    CONSTRUCTING thread, so an item built on a worker thread would
    carry a future no running loop ever resolves.
    """

    payload: object
    deadline: Optional[float] = None
    future: Optional["asyncio.Future"] = None
    #: the submitter's request span (obs.trace.Span or None) — the flush
    #: callback stamps batch links onto it so a request's trace shows
    #: which kernel batch served it
    span: object = None
    #: opaque caller identity (None for anonymous in-process callers);
    #: the scheduler's `key_of` and the gateway's per-client in-flight
    #: accounting both read it
    client: Optional[str] = None


def assemble_lanes(items: List[BatchItem],
                   n_lanes: int) -> List[List[BatchItem]]:
    """Deal one flush's items into per-device lanes, round-robin.

    The mesh scheduler's batch-assembly policy: every lane (device)
    receives within one item of every other, so the shared per-device
    bucket shape — every lane pads to the LARGEST lane's bucket — wastes
    at most one real row per device.  Empty lanes are kept (a 3-item
    batch on an 8-device mesh still dispatches one 8-way program; the
    padding lanes re-check the first row, same idiom as the batch
    padding in tbls.JaxScheme)."""
    if n_lanes < 1:
        raise ValueError("n_lanes must be >= 1")
    lanes: List[List[BatchItem]] = [[] for _ in range(n_lanes)]
    for i, item in enumerate(items):
        lanes[i % n_lanes].append(item)
    return lanes


class BatchScheduler:
    """Bounded queue + flush loop.  `flush(items)` is an async callback
    that must resolve every item's future (verdict or exception).

    `lanes` declares how many device lanes a flush will be dealt into
    (`assemble_lanes`); the scheduler itself still collects ONE batch of
    up to `max_batch` items — with lanes > 1 that budget is the TOTAL
    across the mesh, so single- and multi-device schedulers are compared
    at equal batch budget."""

    def __init__(self, flush: Callable[[List[BatchItem]], Awaitable[None]],
                 *, max_batch: int = 128, max_wait: float = 0.005,
                 max_queue: int = 1024,
                 key_of: Optional[Callable[[BatchItem], object]] = None,
                 lanes: int = 1):
        if max_batch < 1:
            raise ValueError("max_batch must be >= 1")
        if max_queue < 1:
            raise ValueError("max_queue must be >= 1")
        if lanes < 1:
            raise ValueError("lanes must be >= 1")
        self.lanes = lanes
        self._flush = flush
        self.max_batch = max_batch
        self.max_wait = max_wait
        # With key_of, the asyncio.Queue holds one token per queued item
        # (preserving the bounded-admission and wakeup semantics) while
        # the items themselves sit in per-key lanes consumed round-robin.
        self._key_of = key_of
        self._lanes: "OrderedDict[object, deque]" = OrderedDict()
        self._queue: "asyncio.Queue[Optional[BatchItem]]" = asyncio.Queue(
            maxsize=max_queue
        )
        self._task: Optional[asyncio.Task] = None
        self._closed = False

    # -- producer side ----------------------------------------------------

    @property
    def depth(self) -> int:
        return self._queue.qsize()

    def submit(self, item: BatchItem) -> None:
        """Enqueue or raise asyncio.QueueFull (shed) synchronously —
        admission must never itself wait behind the backlog."""
        if self._closed:
            raise RuntimeError("scheduler is closed")
        if item.future is None:
            # bind the future here, on the loop that will resolve it —
            # items may be CONSTRUCTED off-loop (worker threads, tests)
            item.future = asyncio.get_running_loop().create_future()
        if self._key_of is None:
            self._queue.put_nowait(item)
            return
        # reserve a slot in the bounded queue first — QueueFull sheds
        # here before the item touches any lane
        self._queue.put_nowait(None)
        self._lanes.setdefault(self._key_of(item), deque()).append(item)

    # -- consumer loop -----------------------------------------------------

    def start(self) -> None:
        if self._task is None:
            self._task = asyncio.get_event_loop().create_task(self._run())

    async def close(self) -> None:
        """Stop the loop and fail everything still queued."""
        self._closed = True
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None
        while not self._queue.empty():
            item = self._queue.get_nowait()
            if item is not None and not item.future.done():
                item.future.set_exception(
                    RuntimeError("scheduler closed")
                )
        for lane in self._lanes.values():
            for item in lane:
                if not item.future.done():
                    item.future.set_exception(
                        RuntimeError("scheduler closed")
                    )
        self._lanes.clear()

    def _pop_lane(self) -> BatchItem:
        """Take the head of the least-recently-served lane and rotate it
        to the back — one item per lane per turn is the whole fairness
        policy.  Invariant: tokens in the queue == items across lanes,
        so a lane item always exists here."""
        while True:
            key, lane = next(iter(self._lanes.items()))
            if not lane:  # defensive: drop empty lane, keep looking
                del self._lanes[key]
                continue
            item = lane.popleft()
            if lane:
                self._lanes.move_to_end(key)
            else:
                del self._lanes[key]
            return item

    def _take(self, token: Optional[BatchItem]) -> BatchItem:
        return token if self._key_of is None else self._pop_lane()

    async def _collect(self) -> List[BatchItem]:
        """One batch: first item blocks; then fill until max_batch or
        max_wait past the first arrival, whichever comes first."""
        loop = asyncio.get_event_loop()
        first = await self._queue.get()
        batch = [self._take(first)]
        flush_at = loop.time() + self.max_wait
        while len(batch) < self.max_batch:
            # drain whatever is already queued without touching timers
            try:
                batch.append(self._take(self._queue.get_nowait()))
                continue
            except asyncio.QueueEmpty:
                pass
            remaining = flush_at - loop.time()
            if remaining <= 0:
                break
            try:
                batch.append(self._take(
                    await asyncio.wait_for(self._queue.get(), remaining)
                ))
            except asyncio.TimeoutError:
                break
        return batch

    async def _run(self) -> None:
        while True:
            batch = await self._collect()
            try:
                await self._flush(batch)
            except asyncio.CancelledError:
                for item in batch:
                    if not item.future.done():
                        item.future.set_exception(
                            RuntimeError("scheduler closed")
                        )
                raise
            except Exception as exc:  # noqa: BLE001 — keep serving
                # a backend fault must fail THIS batch loudly, not kill
                # the loop for every future request
                log.error("batch flush failed", error=repr(exc))
                for item in batch:
                    if not item.future.done():
                        item.future.set_exception(exc)
