"""Consistent-hash replica ring: round number -> owning gateway replica.

A beacon emits ONE new round per period, so the verification read path
is overwhelmingly cacheable — the limiting resource across N gateway
replicas is not kernel throughput but CACHE capacity and hit rate.  A
plain replica pool caches every hot round N times and still misses on
the long tail; a consistent-hash ring keyed on round number gives every
round exactly one owner, so the per-replica verified-round LRUs compose
into one distributed cache whose capacity scales with N (CDN-style
request routing, vLLM/Orca-style only in spirit: admission stays local).

Forwarding is best-effort by design: a replica receiving an off-owner
request forwards ONCE to the owner and serves locally when the forward
fails — replicas never hard-depend on each other, and a dead owner is
evicted from the local ring view after `fail_evict` consecutive
transport failures so its rounds are re-owned by the survivors
(minimal-movement property of the ring: only the dead replica's rounds
move).

`HashRing` is the pure data structure (deterministic across processes:
SHA-256 points, no PYTHONHASHSEED exposure); `ReplicaRing` wires it to a
gateway with a pluggable async `forward(owner, req, timeout, client)`
callable — gRPC in production (`grpc_forwarder`), in-process for
loadgen and the chaos scenarios.
"""

from __future__ import annotations

import bisect
import hashlib
import threading
from typing import (
    Any,
    Awaitable,
    Callable,
    Dict,
    List,
    Optional,
    Sequence,
    Set,
)

from drand_tpu.utils import metrics
from drand_tpu.utils.logging import get_logger

log = get_logger("serve.ring")

_forwarded = metrics.counter(
    "drand_serve_ring_forwarded_total",
    "off-owner requests forwarded to the ring owner",
)
_forward_failures = metrics.counter(
    "drand_serve_ring_forward_failures_total",
    "forwards that failed at the transport (served locally instead)",
)
_local_fallback = metrics.counter(
    "drand_serve_ring_local_fallback_total",
    "off-owner requests served locally (owner shed or unreachable)",
)
_evicted = metrics.counter(
    "drand_serve_ring_evicted_total",
    "replicas evicted from the local ring view after repeated "
    "forward failures",
)


def _point(data: bytes) -> int:
    """64-bit ring position: stable across processes and hash seeds."""
    return int.from_bytes(hashlib.sha256(data).digest()[:8], "big")


class HashRing:
    """Consistent hashing of round numbers onto replica ids.

    Each replica contributes `vnodes` virtual points so ownership spreads
    evenly; `owner(round)` walks clockwise from the round's point.  Two
    properties the tests pin down: assignment is STABLE (same members ->
    same owner map, in any construction order, in any process) and
    membership changes move only the joining/leaving replica's rounds.
    """

    def __init__(self, replicas: Sequence[str] = (),
                 vnodes: int = 64) -> None:
        if vnodes < 1:
            raise ValueError("vnodes must be >= 1")
        self._vnodes = vnodes
        self._hashes: List[int] = []     # sorted ring positions
        self._owners: List[str] = []     # owner at each position
        self._members: Set[str] = set()
        for r in replicas:
            self.add(r)

    def __len__(self) -> int:
        return len(self._members)

    def __contains__(self, replica: str) -> bool:
        return replica in self._members

    def members(self) -> List[str]:
        return sorted(self._members)

    def add(self, replica: str) -> None:
        if replica in self._members:
            return
        self._members.add(replica)
        for v in range(self._vnodes):
            h = _point(f"{replica}#{v}".encode())
            i = bisect.bisect(self._hashes, h)
            self._hashes.insert(i, h)
            self._owners.insert(i, replica)

    def remove(self, replica: str) -> None:
        if replica not in self._members:
            return
        self._members.discard(replica)
        keep = [(h, o) for h, o in zip(self._hashes, self._owners)
                if o != replica]
        self._hashes = [h for h, _ in keep]
        self._owners = [o for _, o in keep]

    def owner(self, round: int) -> Optional[str]:
        """The replica owning `round`, or None for an empty ring."""
        if not self._hashes:
            return None
        h = _point(b"round:%d" % round)
        i = bisect.bisect(self._hashes, h)
        if i == len(self._hashes):       # wrap past the last point
            i = 0
        return self._owners[i]


#: async forward(owner_id, req, timeout, client) -> serve.VerifyResult
#: (req/result stay Any: the ring is transport plumbing and must not
#: import the gateway's request/result types — that would be a cycle)
Forwarder = Callable[[str, Any, Optional[float], Optional[str]],
                     Awaitable[Any]]


class ReplicaRing:
    """One gateway replica's view of the ring + its forwarding policy.

    Failure accounting is per-peer and CONSECUTIVE: any successful
    forward resets the strike count; `fail_evict` transport failures in
    a row evict the peer from this replica's ring view (its rounds are
    re-owned by the survivors).  An owner that answers with a shed
    (Overloaded and friends) is alive — it never accrues strikes.
    """

    def __init__(self, self_id: str, peers: Sequence[str] = (), *,
                 forward: Optional[Forwarder] = None, vnodes: int = 64,
                 fail_evict: int = 3) -> None:
        if fail_evict < 1:
            raise ValueError("fail_evict must be >= 1")
        self.self_id = self_id
        self.ring = HashRing([self_id, *peers], vnodes=vnodes)
        self._forward = forward
        self.fail_evict = fail_evict
        self._strikes: Dict[str, int] = {}
        self._evicted: List[str] = []
        self._lock = threading.Lock()
        # per-view counters for stats()/loadgen (the module counters are
        # process-wide and shared by every replica in one process)
        self.forwarded = 0
        self.forward_failures = 0
        self.local_fallbacks = 0

    # -- ownership ---------------------------------------------------------

    def owner(self, round: int) -> str:
        own = self.ring.owner(round)
        return self.self_id if own is None else own

    def owns(self, round: int) -> bool:
        return self.owner(round) == self.self_id

    # -- forwarding --------------------------------------------------------

    @property
    def can_forward(self) -> bool:
        return self._forward is not None

    async def forward(self, owner: str, req: Any,
                      timeout: Optional[float],
                      client: Optional[str]) -> Any:
        """One forward attempt to `owner`; raises whatever the transport
        or the remote gateway raises.  Callers decide the fallback."""
        if self._forward is None:
            raise RuntimeError("ring has no forwarder configured")
        self.forwarded += 1
        _forwarded.inc()
        return await self._forward(owner, req, timeout, client)

    def note_alive(self, peer: str) -> None:
        with self._lock:
            self._strikes.pop(peer, None)

    def note_failure(self, peer: str) -> None:
        """One transport failure; evict the peer at `fail_evict`
        consecutive strikes so its rounds re-home to live replicas."""
        self.forward_failures += 1
        _forward_failures.inc()
        with self._lock:
            strikes = self._strikes.get(peer, 0) + 1
            self._strikes[peer] = strikes
            if strikes >= self.fail_evict and peer in self.ring:
                self.ring.remove(peer)
                self._evicted.append(peer)
                _evicted.inc()
                log.warning("ring peer evicted after repeated forward "
                            "failures; its rounds re-owned locally",
                            peer=peer, strikes=strikes)

    def note_local_fallback(self) -> None:
        self.local_fallbacks += 1
        _local_fallback.inc()

    def stats(self) -> Dict[str, Any]:
        """Ring topology + forwarding counters for /v1/status."""
        return {
            "self": self.self_id,
            "replicas": self.ring.members(),
            "evicted": list(self._evicted),
            "forwarded": self.forwarded,
            "forward_failures": self.forward_failures,
            "local_fallbacks": self.local_fallbacks,
        }


def inprocess_forwarder(replicas: Dict[str, Any]) -> Forwarder:
    """Forward by direct await on a sibling gateway in this process —
    the loadgen / chaos-scenario transport.  `replicas` maps replica id
    -> VerifyGateway (a closed gateway raises GatewayClosed like a dead
    network peer would)."""

    async def forward(owner: str, req: Any, timeout: Optional[float],
                      client: Optional[str]) -> Any:
        import dataclasses

        from drand_tpu.serve import gateway as gw_mod

        gw = replicas.get(owner)
        if gw is None:
            raise gw_mod.GatewayClosed(f"no such replica {owner!r}")
        res = await gw.verify(req, timeout, client=client, forwarded=True)
        return dataclasses.replace(res, forwarded=True)

    return forward


def grpc_forwarder(client: Any, *, tls: bool = False) -> Forwarder:
    """Forward over the existing gRPC public API (`VerifyBeacon`),
    mapping the peer's explicit shed codes back onto GatewayErrors so
    the caller can tell "owner alive but shedding" (serve locally, no
    eviction strike) from "owner unreachable" (strike)."""

    async def forward(owner: str, req: Any, timeout: Optional[float],
                      fwd_client: Optional[str]) -> Any:
        import grpc

        from drand_tpu.key.keys import Identity
        from drand_tpu.serve import gateway as gw_mod

        peer = Identity(address=owner, key=None, tls=tls)
        try:
            resp = await client.verify_beacon(
                peer, round=req.round, prev_round=req.prev_round,
                prev_sig=req.prev_sig, signature=req.signature,
                timeout=timeout, forwarded=True,
            )
        except grpc.aio.AioRpcError as exc:
            code = exc.code()
            if code == grpc.StatusCode.RESOURCE_EXHAUSTED:
                raise gw_mod.Overloaded(exc.details()) from None
            if code == grpc.StatusCode.DEADLINE_EXCEEDED:
                raise gw_mod.DeadlineExceeded(exc.details()) from None
            if code == grpc.StatusCode.INVALID_ARGUMENT:
                raise gw_mod.Oversize(0, 0) from None
            raise  # UNAVAILABLE etc.: a transport failure -> strike
        return gw_mod.VerifyResult(
            valid=resp.valid, cached=resp.cached,
            batch_size=resp.batch_size, forwarded=True,
        )

    return forward
