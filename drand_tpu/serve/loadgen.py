"""Load generator for the verification gateway.

Measures the one number the gateway exists for: verified claims per
second, batched vs sequential, ON THE SAME BACKEND —

  sequential: one client awaits each verdict before sending the next
              claim, so every kernel call carries a batch of 1;
  batched:    N concurrent clients share the gateway, so the scheduler
              coalesces their claims into large batches and the fixed
              per-dispatch cost is amortized.

Backends:

  sim     (default) a simulated-dispatch scheme: each kernel call costs
          a fixed dispatch latency plus a small per-item cost — the
          shape of a real TPU dispatch (PCIe hop + fixed-grid Pallas
          launch dominates; marginal rows are almost free).  Verdicts
          are computed host-side, so the run is fast and portable; the
          artifact is honestly labeled "backend": "sim".
  ref / native / jax    the real tbls schemes (real keys, real
          signatures).  `native` shows little speedup — the C++ host
          backend does sequential pairings per item, so there is no
          fixed cost to amortize; that contrast is the point of the
          sim model and the TPU rows.

Run:  python -m drand_tpu.serve.loadgen --requests 512 --clients 64 \
          --out loadgen_gateway.json
"""

from __future__ import annotations

import argparse
import asyncio
import json
import sys
import time
from typing import List, Optional

from drand_tpu.serve.gateway import VerifyGateway, VerifyRequest


class SimDispatchScheme:
    """Simulated device dispatch: wall-clock cost = dispatch_ms fixed +
    per_item_us per claim, burned in the gateway's executor thread like
    a real blocking device call.  Verdict = signature[0] == 1."""

    def __init__(self, dispatch_ms: float = 4.0, per_item_us: float = 40.0):
        self.dispatch_ms = dispatch_ms
        self.per_item_us = per_item_us
        self.calls = 0

    def verify_chain_batch(self, pub, msgs, sigs) -> List[bool]:
        self.calls += 1
        time.sleep(self.dispatch_ms / 1e3
                   + len(msgs) * self.per_item_us / 1e6)
        return [len(s) > 0 and s[0] == 1 for s in sigs]


def _sim_requests(n: int) -> List[VerifyRequest]:
    return [
        VerifyRequest(round=r, prev_round=r - 1, prev_sig=b"\x01" * 96,
                      signature=bytes([1]) + r.to_bytes(8, "big"))
        for r in range(1, n + 1)
    ]


def _real_requests(n: int):
    """(dist_key, requests) with genuinely signed chain links."""
    from drand_tpu.crypto import refimpl as ref
    from drand_tpu.crypto.poly import rand_scalar

    sk = rand_scalar()
    pk = ref.g1_mul(ref.G1_GEN, sk)
    reqs = []
    for r in range(1, n + 1):
        probe = VerifyRequest(round=r, prev_round=r - 1,
                              prev_sig=b"\x01" * 96, signature=b"")
        sig = ref.g2_to_bytes(ref.g2_mul(ref.hash_to_g2(probe.message()),
                                         sk))
        reqs.append(VerifyRequest(round=r, prev_round=r - 1,
                                  prev_sig=b"\x01" * 96, signature=sig))
    return pk, reqs


async def _run_sequential(gw: VerifyGateway,
                          reqs: List[VerifyRequest]) -> float:
    t0 = time.perf_counter()
    for req in reqs:
        res = await gw.verify(req, timeout=120.0)
        assert res.valid, req
    return time.perf_counter() - t0


async def _run_batched(gw: VerifyGateway, reqs: List[VerifyRequest],
                       clients: int) -> float:
    queue: "asyncio.Queue[VerifyRequest]" = asyncio.Queue()
    for req in reqs:
        queue.put_nowait(req)

    async def client():
        while True:
            try:
                req = queue.get_nowait()
            except asyncio.QueueEmpty:
                return
            res = await gw.verify(req, timeout=120.0)
            assert res.valid, req

    t0 = time.perf_counter()
    await asyncio.gather(*(client() for _ in range(clients)))
    return time.perf_counter() - t0


async def run(backend: str, requests: int, clients: int,
              max_batch: int, max_wait: float,
              dispatch_ms: float, per_item_us: float,
              metrics_port: Optional[int]) -> dict:
    if backend == "sim":
        scheme = SimDispatchScheme(dispatch_ms, per_item_us)
        dist_key = object()
        seq_reqs = _sim_requests(requests)
        bat_reqs = _sim_requests(requests)
    else:
        from drand_tpu.crypto import tbls

        scheme = tbls.default_scheme(backend)
        dist_key, seq_reqs = _real_requests(requests)
        bat_reqs = seq_reqs

    report = {
        "benchmark": "serve-gateway-throughput",
        "backend": backend,
        "backend_class": type(scheme).__name__,
        "simulated_dispatch": backend == "sim",
        "requests": requests,
        "clients": clients,
        "max_batch": max_batch,
        "max_wait_s": max_wait,
    }
    if backend == "sim":
        report["sim_dispatch_ms"] = dispatch_ms
        report["sim_per_item_us"] = per_item_us

    # sequential: fresh gateway so its cache cannot leak into the
    # batched phase (claims differ per phase for sim; identical claims
    # WOULD be cache hits, which is the production win but not the
    # batching number this artifact reports)
    async with VerifyGateway(dist_key, scheme, max_batch=max_batch,
                             max_wait=max_wait,
                             max_queue=max(1024, requests)) as gw:
        gw.cache.capacity = 0  # measure kernels, not the cache
        seq_s = await _run_sequential(gw, seq_reqs)

    async with VerifyGateway(dist_key, scheme, max_batch=max_batch,
                             max_wait=max_wait,
                             max_queue=max(1024, requests)) as gw:
        gw.cache.capacity = 0
        bat_s = await _run_batched(gw, bat_reqs, clients)

        report["sequential_s"] = round(seq_s, 4)
        report["sequential_rps"] = round(requests / seq_s, 1)
        report["batched_s"] = round(bat_s, 4)
        report["batched_rps"] = round(requests / bat_s, 1)
        report["speedup"] = round(seq_s / bat_s, 2)

        from drand_tpu.utils import metrics

        sample = [
            line for line in metrics.render().splitlines()
            if line.startswith("drand_serve_") and "_bucket" not in line
        ]
        report["metrics_sample"] = sample

        if metrics_port is not None:
            # leave an inspectable /metrics endpoint up briefly so the
            # run demonstrably exposes its counters over HTTP
            from drand_tpu.net.rest import build_verify_app, start_rest

            runner, port = await start_rest(build_verify_app(gw),
                                            metrics_port)
            report["metrics_url"] = f"http://127.0.0.1:{port}/metrics"
            print(f"metrics on {report['metrics_url']} for 5s ...",
                  file=sys.stderr)
            await asyncio.sleep(5)
            await runner.cleanup()

    return report


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--backend", default="sim",
                    choices=["sim", "ref", "native", "jax", "auto"])
    ap.add_argument("--requests", type=int, default=512)
    ap.add_argument("--clients", type=int, default=64)
    ap.add_argument("--max-batch", type=int, default=128)
    ap.add_argument("--max-wait", type=float, default=0.005)
    ap.add_argument("--dispatch-ms", type=float, default=4.0,
                    help="sim backend: fixed cost per kernel dispatch")
    ap.add_argument("--per-item-us", type=float, default=40.0,
                    help="sim backend: marginal cost per batched claim")
    ap.add_argument("--metrics-port", type=int, default=None,
                    help="also serve /metrics on this port for 5s")
    ap.add_argument("--out", help="write the JSON artifact here")
    args = ap.parse_args(argv)

    report = asyncio.run(run(
        args.backend, args.requests, args.clients, args.max_batch,
        args.max_wait, args.dispatch_ms, args.per_item_us,
        args.metrics_port,
    ))
    text = json.dumps(report, indent=2)
    print(text)
    if args.out:
        with open(args.out, "w") as fh:
            fh.write(text + "\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())
