"""Load generator for the verification gateway.

Measures the one number the gateway exists for: verified claims per
second, batched vs sequential, ON THE SAME BACKEND —

  sequential: one client awaits each verdict before sending the next
              claim, so every kernel call carries a batch of 1;
  batched:    N concurrent clients share the gateway, so the scheduler
              coalesces their claims into large batches and the fixed
              per-dispatch cost is amortized.

Backends:

  sim     (default) a simulated-dispatch scheme: each kernel call costs
          a fixed dispatch latency plus a small per-item cost — the
          shape of a real TPU dispatch (PCIe hop + fixed-grid Pallas
          launch dominates; marginal rows are almost free).  Verdicts
          are computed host-side, so the run is fast and portable; the
          artifact is honestly labeled "backend": "sim".
  ref / native / jax    the real tbls schemes (real keys, real
          signatures).  `native` shows little speedup — the C++ host
          backend does sequential pairings per item, so there is no
          fixed cost to amortize; that contrast is the point of the
          sim model and the TPU rows.

Run:  python -m drand_tpu.serve.loadgen --requests 512 --clients 64 \
          --out loadgen_gateway.json
"""

from __future__ import annotations

import argparse
import asyncio
import json
import random
import sys
import time
from typing import List, Optional

from drand_tpu.serve.gateway import VerifyGateway, VerifyRequest


class SimDispatchScheme:
    """Simulated device dispatch: wall-clock cost = dispatch_ms fixed +
    per_item_us per claim, burned in the gateway's executor thread like
    a real blocking device call.  Verdict = signature[0] == 1.

    The mesh contract mirrors tbls.JaxScheme: `configure_mesh(n)` fixes
    the lane count, and one `verify_chain_batch_mesh` dispatch costs the
    SAME fixed dispatch latency plus per-item cost on the LONGEST lane
    only — the data-parallel shape of one shard_map program, where every
    device works its own slice concurrently."""

    def __init__(self, dispatch_ms: float = 4.0, per_item_us: float = 40.0):
        self.dispatch_ms = dispatch_ms
        self.per_item_us = per_item_us
        self.calls = 0
        self.devices = 1

    def verify_chain_batch(self, pub, msgs, sigs) -> List[bool]:
        self.calls += 1
        time.sleep(self.dispatch_ms / 1e3
                   + len(msgs) * self.per_item_us / 1e6)
        return [len(s) > 0 and s[0] == 1 for s in sigs]

    def configure_mesh(self, n_devices: int) -> str:
        self.devices = n_devices
        return "sim"

    def verify_chain_batch_mesh(self, pub, lane_msgs, lane_sigs
                                ) -> List[List[bool]]:
        self.calls += 1
        widest = max((len(lane) for lane in lane_sigs), default=0)
        time.sleep(self.dispatch_ms / 1e3
                   + widest * self.per_item_us / 1e6)
        return [[len(s) > 0 and s[0] == 1 for s in lane]
                for lane in lane_sigs]


def _sim_requests(n: int) -> List[VerifyRequest]:
    return [
        VerifyRequest(round=r, prev_round=r - 1, prev_sig=b"\x01" * 96,
                      signature=bytes([1]) + r.to_bytes(8, "big"))
        for r in range(1, n + 1)
    ]


def _real_requests(n: int):
    """(dist_key, requests) with genuinely signed chain links."""
    from drand_tpu.crypto import refimpl as ref
    from drand_tpu.crypto.poly import rand_scalar

    sk = rand_scalar()
    pk = ref.g1_mul(ref.G1_GEN, sk)
    reqs = []
    for r in range(1, n + 1):
        probe = VerifyRequest(round=r, prev_round=r - 1,
                              prev_sig=b"\x01" * 96, signature=b"")
        sig = ref.g2_to_bytes(ref.g2_mul(ref.hash_to_g2(probe.message()),
                                         sk))
        reqs.append(VerifyRequest(round=r, prev_round=r - 1,
                                  prev_sig=b"\x01" * 96, signature=sig))
    return pk, reqs


async def _run_sequential(gw: VerifyGateway,
                          reqs: List[VerifyRequest]) -> float:
    t0 = time.perf_counter()
    for req in reqs:
        res = await gw.verify(req, timeout=120.0)
        assert res.valid, req
    return time.perf_counter() - t0


async def _run_batched(gw: VerifyGateway, reqs: List[VerifyRequest],
                       clients: int) -> float:
    queue: "asyncio.Queue[VerifyRequest]" = asyncio.Queue()
    for req in reqs:
        queue.put_nowait(req)

    async def client():
        while True:
            try:
                req = queue.get_nowait()
            except asyncio.QueueEmpty:
                return
            res = await gw.verify(req, timeout=120.0)
            assert res.valid, req

    t0 = time.perf_counter()
    await asyncio.gather(*(client() for _ in range(clients)))
    return time.perf_counter() - t0


async def run(backend: str, requests: int, clients: int,
              max_batch: int, max_wait: float,
              dispatch_ms: float, per_item_us: float,
              metrics_port: Optional[int]) -> dict:
    if backend == "sim":
        scheme = SimDispatchScheme(dispatch_ms, per_item_us)
        dist_key = object()
        seq_reqs = _sim_requests(requests)
        bat_reqs = _sim_requests(requests)
    else:
        from drand_tpu.crypto import tbls

        scheme = tbls.default_scheme(backend)
        dist_key, seq_reqs = _real_requests(requests)
        bat_reqs = seq_reqs

    report = {
        "benchmark": "serve-gateway-throughput",
        "backend": backend,
        "backend_class": type(scheme).__name__,
        "simulated_dispatch": backend == "sim",
        "requests": requests,
        "clients": clients,
        "max_batch": max_batch,
        "max_wait_s": max_wait,
    }
    if backend == "sim":
        report["sim_dispatch_ms"] = dispatch_ms
        report["sim_per_item_us"] = per_item_us

    # sequential: fresh gateway so its cache cannot leak into the
    # batched phase (claims differ per phase for sim; identical claims
    # WOULD be cache hits, which is the production win but not the
    # batching number this artifact reports)
    async with VerifyGateway(dist_key, scheme, max_batch=max_batch,
                             max_wait=max_wait,
                             max_queue=max(1024, requests)) as gw:
        gw.cache.capacity = 0  # measure kernels, not the cache
        seq_s = await _run_sequential(gw, seq_reqs)

    async with VerifyGateway(dist_key, scheme, max_batch=max_batch,
                             max_wait=max_wait,
                             max_queue=max(1024, requests)) as gw:
        gw.cache.capacity = 0
        bat_s = await _run_batched(gw, bat_reqs, clients)

        report["sequential_s"] = round(seq_s, 4)
        report["sequential_rps"] = round(requests / seq_s, 1)
        report["batched_s"] = round(bat_s, 4)
        report["batched_rps"] = round(requests / bat_s, 1)
        report["speedup"] = round(seq_s / bat_s, 2)

        from drand_tpu.utils import metrics

        sample = [
            line for line in metrics.render().splitlines()
            if line.startswith("drand_serve_") and "_bucket" not in line
        ]
        report["metrics_sample"] = sample

        if metrics_port is not None:
            # leave an inspectable /metrics endpoint up briefly so the
            # run demonstrably exposes its counters over HTTP
            from drand_tpu.net.rest import build_verify_app, start_rest

            runner, port = await start_rest(build_verify_app(gw),
                                            metrics_port)
            report["metrics_url"] = f"http://127.0.0.1:{port}/metrics"
            print(f"metrics on {report['metrics_url']} for 5s ...",
                  file=sys.stderr)
            await asyncio.sleep(5)
            await runner.cleanup()

    return report


# -- mesh / multi-replica suite -------------------------------------------
#
# Three phases, one artifact (loadgen_mesh_gateway.json):
#   mesh_scaling  flush throughput (items per second of flush wall-clock,
#                 gateway-side so Python client overhead cannot flatten
#                 the curve) of the mesh scheduler vs the single-device
#                 scheduler at EQUAL total batch budget.
#   hot_round     N replicas + consistent-hash ring on a skewed workload:
#                 90% of requests hit a handful of hot rounds, the rest a
#                 long tail — the distributed-cache hit rate is the point.
#   overload      a 10x burst against a small queue and short deadline:
#                 explicit shed only, and NO success blows its deadline.


def _round_claim(r: int) -> VerifyRequest:
    """One canonical sim claim per round — byte-identical across callers
    so replica caches key on it."""
    return VerifyRequest(round=r, prev_round=r - 1, prev_sig=b"\x01" * 96,
                         signature=bytes([1]) + r.to_bytes(8, "big"))


def _skewed_requests(n: int, *, hot_rounds: int, rounds: int,
                     hot_frac: float, seed: int) -> List[VerifyRequest]:
    """Hot-head workload: `hot_frac` of requests land on the first
    `hot_rounds` rounds, the rest spread over the tail."""
    rng = random.Random(seed)
    out = []
    for _ in range(n):
        if rng.random() < hot_frac:
            r = rng.randrange(1, hot_rounds + 1)
        else:
            r = rng.randrange(hot_rounds + 1, rounds + 1)
        out.append(_round_claim(r))
    return out


async def _flush_throughput(scheme, mesh_devices: int, requests: int,
                            max_batch: int) -> dict:
    """Feed `requests` unique claims through one gateway and report the
    scheduler's flush throughput (items / flush wall-seconds)."""
    async with VerifyGateway(object(), scheme, max_batch=max_batch,
                             max_wait=0.05,
                             max_queue=requests + max_batch,
                             mesh_devices=mesh_devices) as gw:
        gw.cache.capacity = 0  # measure the scheduler, not the cache
        reqs = _sim_requests(requests)
        t0 = time.perf_counter()
        results = await asyncio.gather(
            *(gw.verify(req, timeout=120.0) for req in reqs)
        )
        wall = time.perf_counter() - t0
        assert all(r.valid for r in results)
        stats = gw.stats()
    return {
        "devices": mesh_devices,
        "mesh_backend": stats["mesh"]["backend"],
        "sharded_batches": stats["mesh"]["sharded_batches"],
        "flush_s": round(stats["flush_seconds"], 4),
        "flush_items": stats["flush_items"],
        "flush_rps": round(stats["flush_items"]
                           / max(stats["flush_seconds"], 1e-9), 1),
        "wall_s": round(wall, 4),
    }


async def _hot_round_phase(make_scheme, *, replicas: int, requests: int,
                           hot_rounds: int, rounds: int, hot_frac: float,
                           clients: int, seed: int) -> dict:
    """Skewed workload over a replica ring; every replica receives a
    share of the traffic and forwards off-owner rounds once."""
    from drand_tpu.serve.ring import ReplicaRing, inprocess_forwarder

    ids = [f"replica-{i}" for i in range(replicas)]
    pool = {}
    forward = inprocess_forwarder(pool)
    gws = []
    for rid in ids:
        ring = ReplicaRing(rid, [p for p in ids if p != rid],
                           forward=forward)
        gw = VerifyGateway(object(), make_scheme(), max_batch=128,
                           max_wait=0.002, max_queue=8192, ring=ring)
        pool[rid] = gw
        gws.append(gw)
    for gw in gws:
        await gw.start()
    try:
        reqs = _skewed_requests(requests, hot_rounds=hot_rounds,
                                rounds=rounds, hot_frac=hot_frac,
                                seed=seed)
        rng = random.Random(seed + 1)
        jobs: "asyncio.Queue" = asyncio.Queue()
        for i, req in enumerate(reqs):
            jobs.put_nowait((i, req))
        cached = valid = 0

        async def client(cid: int):
            nonlocal cached, valid
            while True:
                try:
                    i, req = jobs.get_nowait()
                except asyncio.QueueEmpty:
                    return
                gw = pool[ids[rng.randrange(replicas)]]
                res = await gw.verify(req, timeout=120.0,
                                      client=f"c{cid}")
                valid += int(res.valid)
                cached += int(res.cached)

        t0 = time.perf_counter()
        await asyncio.gather(*(client(c) for c in range(clients)))
        wall = time.perf_counter() - t0
        ring_stats = [gw.ring.stats() for gw in gws]
        return {
            "replicas": replicas,
            "requests": requests,
            "clients": clients,
            "hot_rounds": hot_rounds,
            "rounds": rounds,
            "hot_frac": hot_frac,
            "valid": valid,
            "cache_hits": cached,
            "hit_rate": round(cached / max(requests, 1), 4),
            "forwarded": sum(s["forwarded"] for s in ring_stats),
            "forward_failures": sum(s["forward_failures"]
                                    for s in ring_stats),
            "local_fallbacks": sum(s["local_fallbacks"]
                                   for s in ring_stats),
            "wall_s": round(wall, 4),
            "rps": round(requests / wall, 1),
        }
    finally:
        for gw in gws:
            await gw.close()


async def _overload_phase(scheme, *, requests: int, max_batch: int,
                          max_queue: int, timeout: float,
                          overload_x: float = 10.0) -> dict:
    """Offer ~`overload_x` times the gateway's serving capacity at a
    short deadline; every non-served claim must shed EXPLICITLY and no
    served claim may come back after its deadline.

    Arrival is paced in small waves (not one mega-burst): a single
    gather of thousands of coroutines would monopolize the event loop
    and starve the batcher itself, measuring asyncio scheduling rather
    than the gateway's shed policy."""
    async with VerifyGateway(object(), scheme, max_batch=max_batch,
                             max_wait=0.002,
                             max_queue=max_queue) as gw:
        from drand_tpu.serve.gateway import (DeadlineExceeded, Overloaded)

        gw.cache.capacity = 0
        reqs = _sim_requests(requests)
        loop = asyncio.get_event_loop()
        ok = shed_queue = shed_deadline = blown = 0

        async def one(req):
            nonlocal ok, shed_queue, shed_deadline, blown
            t0 = loop.time()
            try:
                res = await gw.verify(req, timeout=timeout)
            except Overloaded:
                shed_queue += 1
            except DeadlineExceeded:
                shed_deadline += 1
            else:
                assert res.valid
                ok += 1
                # serve-late = a success delivered past its deadline;
                # the gateway promises this NEVER happens (reject at
                # pop).  10 ms grace for event-loop scheduling jitter.
                if loop.time() - t0 > timeout + 0.010:
                    blown += 1

        # capacity (claims/s) from the sim cost model; offer waves at
        # overload_x times that rate
        per_flush = (scheme.dispatch_ms / 1e3
                     + max_batch * scheme.per_item_us / 1e6)
        capacity_rps = max_batch / per_flush
        wave_every = 0.005
        wave_size = max(1, int(capacity_rps * overload_x * wave_every))
        tasks = []
        offered = 0
        while offered < requests:
            wave = reqs[offered:offered + wave_size]
            offered += len(wave)
            tasks.extend(asyncio.ensure_future(one(r)) for r in wave)
            await asyncio.sleep(wave_every)
        await asyncio.gather(*tasks)
    return {
        "offered": requests,
        "max_batch": max_batch,
        "max_queue": max_queue,
        "timeout_s": timeout,
        "sim_dispatch_ms": scheme.dispatch_ms,
        "sim_per_item_us": scheme.per_item_us,
        "capacity_rps": round(capacity_rps, 1),
        "offered_rps": round(wave_size / wave_every, 1),
        "overload_factor": round((wave_size / wave_every)
                                 / capacity_rps, 1),
        "served": ok,
        "shed_queue_full": shed_queue,
        "shed_deadline": shed_deadline,
        "deadline_blown_successes": blown,
    }


async def run_mesh(backend: str, *, mesh_devices: int, replicas: int,
                   requests: int, clients: int, max_batch: int,
                   dispatch_ms: float, per_item_us: float,
                   seed: int = 7) -> dict:
    """The mesh + multi-replica proof-under-load suite."""
    if backend != "sim":
        raise SystemExit(
            "the mesh suite models dispatch cost explicitly; run it "
            "with --backend sim (real-kernel mesh correctness is "
            "covered by tests/test_shard.py and tests/test_serve.py)"
        )

    def make_scheme():
        return SimDispatchScheme(dispatch_ms, per_item_us)

    report = {
        "benchmark": "serve-mesh-gateway",
        "backend": backend,
        "backend_class": "SimDispatchScheme",
        "simulated_dispatch": True,
        "devices": mesh_devices,
        "replicas": replicas,
        "sim_dispatch_ms": dispatch_ms,
        "sim_per_item_us": per_item_us,
    }

    # phase 1: flush throughput, single device vs mesh, equal budget.
    # Per-item cost must dominate the fixed dispatch for scaling to
    # show, exactly as on hardware — so the budget wants to be BIG
    # (2048 via the mesh-suite --max-batch default); enough requests
    # are fed to fill several full-budget flushes.
    p1_requests = max(requests, 8 * max_batch)
    single = await _flush_throughput(make_scheme(), 1, p1_requests,
                                     max_batch)
    mesh = await _flush_throughput(make_scheme(), mesh_devices,
                                   p1_requests, max_batch)
    scaling = mesh["flush_rps"] / max(single["flush_rps"], 1e-9)
    report["mesh_scaling"] = {
        "batch_budget": max_batch,
        "requests": p1_requests,
        "single": single,
        "mesh": mesh,
        "scaling_x": round(scaling, 2),
    }
    report["mesh_backend"] = mesh["mesh_backend"]

    # phase 2: hot-round distributed cache across the replica ring
    hot = await _hot_round_phase(
        make_scheme, replicas=replicas, requests=max(requests, 4000),
        hot_rounds=8, rounds=256, hot_frac=0.9, clients=clients,
        seed=seed,
    )
    report["hot_round"] = hot

    # phase 3: 10x overload against a small queue + short deadline.
    # Its OWN slower cost model (heavier dispatch): 10x a fast kernel's
    # capacity would mean ~100k coroutine arrivals/s, which saturates
    # the single-threaded event loop and measures asyncio instead of
    # the shed policy; 10x a ~800 rps kernel keeps the arrival side
    # honest while the ratio — the thing under test — stays 10x.
    # timeout sits ABOVE the worst honest queue-drain latency (a full
    # queue is max_queue/max_batch + 1 flushes ≈ 235 ms here): the
    # gateway's promise is reject-at-POP, so an item popped just before
    # a too-tight deadline would legitimately finish just after it —
    # that is a mis-sized timeout, not a serve-late bug.  Excess load
    # then sheds where it should: at admission.
    over = await _overload_phase(
        SimDispatchScheme(dispatch_ms=40.0, per_item_us=600.0),
        requests=2000, max_batch=64, max_queue=128, timeout=0.4,
    )
    report["overload"] = over

    report["degraded"] = not (
        scaling >= 4.0
        and hot["hit_rate"] >= 0.90
        and over["deadline_blown_successes"] == 0
        and over["shed_queue_full"] + over["shed_deadline"] > 0
    )
    return report


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--backend", default="sim",
                    choices=["sim", "ref", "native", "jax", "auto"])
    ap.add_argument("--requests", type=int, default=None,
                    help="claims to feed (default 512; mesh suite 16384 "
                         "so several full-budget flushes amortize)")
    ap.add_argument("--clients", type=int, default=64)
    ap.add_argument("--max-batch", type=int, default=None,
                    help="total batch budget per flush (default 128; "
                         "mesh suite 2048 — per-item cost must dominate "
                         "the fixed dispatch for mesh scaling to show, "
                         "exactly as on hardware)")
    ap.add_argument("--max-wait", type=float, default=0.005)
    ap.add_argument("--dispatch-ms", type=float, default=4.0,
                    help="sim backend: fixed cost per kernel dispatch")
    ap.add_argument("--per-item-us", type=float, default=40.0,
                    help="sim backend: marginal cost per batched claim")
    ap.add_argument("--metrics-port", type=int, default=None,
                    help="also serve /metrics on this port for 5s")
    ap.add_argument("--mesh-devices", type=int, default=1,
                    help="run the mesh/multi-replica suite with this "
                         "many device lanes (sim backend)")
    ap.add_argument("--replicas", type=int, default=1,
                    help="gateway replicas for the hot-round ring phase")
    ap.add_argument("--out", help="write the JSON artifact here")
    args = ap.parse_args(argv)

    mesh_suite = args.mesh_devices > 1 or args.replicas > 1
    # the mesh suite defaults to artifact-grade sizes: with the generic
    # 128/512 the fixed dispatch cost swamps the per-item cost and the
    # scaling phase reports ~1x no matter how well the mesh works
    requests = (args.requests if args.requests is not None
                else (16384 if mesh_suite else 512))
    max_batch = (args.max_batch if args.max_batch is not None
                 else (2048 if mesh_suite else 128))
    if mesh_suite:
        report = asyncio.run(run_mesh(
            args.backend,
            mesh_devices=max(args.mesh_devices, 1),
            replicas=max(args.replicas, 2),
            requests=requests, clients=args.clients,
            max_batch=max_batch,
            dispatch_ms=args.dispatch_ms,
            per_item_us=args.per_item_us,
        ))
    else:
        report = asyncio.run(run(
            args.backend, requests, args.clients, max_batch,
            args.max_wait, args.dispatch_ms, args.per_item_us,
            args.metrics_port,
        ))
    # provenance: where these numbers came from (git rev, backend, env
    # knobs) and whether the run is degraded — a mesh suite that missed
    # its acceptance gates is a `code` degradation of the measured path
    from drand_tpu.obs import perf

    degraded = bool(report.get("degraded"))
    report["lineage"] = perf.lineage(
        backend=args.backend,
        device=report.get("mesh_backend") or report.get("backend_class"),
        degraded=degraded,
        degraded_reason="code" if degraded else None,
    )
    text = json.dumps(report, indent=2)
    print(text)
    if args.out:
        with open(args.out, "w") as fh:
            fh.write(text + "\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())
