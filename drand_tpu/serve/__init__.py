"""Verification gateway: serve many concurrent beacon-verify requests
from one TPU-batched crypto backend.

The crypto plane only hits its measured throughput when fed large
batches (bench.py: the Pallas pairing kernel does 12-21k pairings/s at
batch >= 128, but a single-row dispatch pays the same kernel latency).
Nothing in the tree served that shape of traffic: every PublicRand /
REST request verified one signature at a time.  This package is the
inference-server-shaped front end over the batch API:

  client requests -> admission control -> bounded queue -> batcher
    -> ONE padded fixed-shape device batch per tick -> demux verdicts

plus an LRU verified-round cache (repeat requests never touch the
kernel) and explicit shedding (429 / RESOURCE_EXHAUSTED) instead of
unbounded queueing latency.  See README.md "Verification gateway".
"""

from drand_tpu.serve.batcher import (
    BatchItem,
    BatchScheduler,
    assemble_lanes,
)
from drand_tpu.serve.cache import VerifiedRoundCache
from drand_tpu.serve.gateway import (
    ClientQuota,
    DeadlineExceeded,
    GatewayClosed,
    GatewayError,
    Overloaded,
    Oversize,
    VerifyGateway,
    VerifyRequest,
    VerifyResult,
)
from drand_tpu.serve.ring import (
    HashRing,
    ReplicaRing,
    grpc_forwarder,
    inprocess_forwarder,
)

__all__ = [
    "BatchItem",
    "BatchScheduler",
    "ClientQuota",
    "DeadlineExceeded",
    "GatewayClosed",
    "GatewayError",
    "HashRing",
    "Overloaded",
    "Oversize",
    "ReplicaRing",
    "VerifiedRoundCache",
    "VerifyGateway",
    "VerifyRequest",
    "VerifyResult",
    "assemble_lanes",
    "grpc_forwarder",
    "inprocess_forwarder",
]
