"""The verification gateway: many concurrent callers, one batched kernel.

`VerifyGateway.verify` is the whole public surface: await it with a
(round, prev_round, prev_sig, signature) claim and get a verdict.
Internally a request flows

  cache probe -> in-flight coalescing -> admission control (bounded
  queue, else shed) -> BatchScheduler tick -> one padded
  `verify_chain_batch` device call -> per-request demux

The crypto backend is any `tbls.Scheme`: JaxScheme turns each tick into
a single fixed-shape Pallas/op-graph dispatch (its `_bucket` padding
means the jitted kernel never re-traces); NativeScheme/RefScheme serve
the same contract off-TPU.  The kernel call runs in a one-thread
executor so the event loop keeps admitting (and shedding) while the
device is busy.

Failure semantics are explicit, never silent latency:
* queue full            -> `Overloaded`       (REST 429 / gRPC
                           RESOURCE_EXHAUSTED)
* per-client in-flight
  cap reached           -> `ClientQuota`      (an Overloaded subtype:
                           one flooding identity can no longer
                           monopolise the queue)
* deadline passed while
  queued                -> `DeadlineExceeded` (rejected at batch
                           assembly — a late verdict is never served)
* gateway closed        -> `GatewayClosed`

Identified clients additionally get round-robin batch assembly (one
lane per client in the BatchScheduler), so a burst from one caller
interleaves with — instead of serializing ahead of — everyone else.
"""

from __future__ import annotations

import asyncio
import contextvars
import time
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Set

from drand_tpu.beacon.chain import Beacon, beacon_message
from drand_tpu.crypto import refimpl as ref
from drand_tpu.crypto import tbls
from drand_tpu.obs import flight as obs_flight
from drand_tpu.obs import perf as obs_perf
from drand_tpu.obs import slo as obs_slo
from drand_tpu.obs import trace as obs_trace
from drand_tpu.serve.batcher import (
    BatchItem,
    BatchScheduler,
    assemble_lanes,
)
from drand_tpu.serve.cache import VerifiedRoundCache
from drand_tpu.serve.ring import ReplicaRing
from drand_tpu.utils import metrics
from drand_tpu.utils.logging import get_logger

log = get_logger("serve.gateway")

#: batch occupancy is size-shaped, not latency-shaped
_BATCH_BUCKETS = (1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0, 128.0, 256.0,
                  512.0, 1024.0)

_queue_depth = metrics.gauge(
    "drand_serve_queue_depth", "verification requests waiting for a batch"
)
_batch_size = metrics.histogram(
    "drand_serve_batch_size", "requests per kernel batch",
    buckets=_BATCH_BUCKETS,
)
_batch_seconds = metrics.histogram(
    "drand_serve_batch_seconds", "wall time of one batched verify call"
)
_cache_hits = metrics.counter(
    "drand_serve_cache_hits_total", "requests served from the "
    "verified-round cache without touching the kernel"
)
_coalesced = metrics.counter(
    "drand_serve_coalesced_total", "requests attached to an identical "
    "in-flight verification"
)
_device_occupancy = metrics.histogram(
    "drand_serve_device_occupancy",
    "live requests assigned to one device lane per mesh flush",
    buckets=_BATCH_BUCKETS,
)
_mesh_batches = metrics.counter(
    "drand_serve_mesh_batches_total",
    "flushes dispatched as one mesh-sharded pairing program",
)
#: Closed vocabulary of shed reasons.  The label value rides the
#: drand_serve_shed_total series, the REST/gRPC error bodies and the
#: fleet aggregator's pressure view; drand-lint's `reg-shed-reason`
#: resolves every literal in the tree against this tuple.
SHED_REASONS = ("queue_full", "deadline", "oversize", "client_quota")

_shed = {
    reason: metrics.counter(
        "drand_serve_shed_total",
        "requests rejected instead of served late",
        labels={"reason": reason},
    )
    for reason in SHED_REASONS
}
_requests = {
    result: metrics.counter(
        "drand_serve_requests_total", "verification verdicts returned",
        labels={"result": result},
    )
    for result in ("valid", "invalid")
}

#: cap the per-client label cardinality: past this many distinct clients
#: new ones aggregate under "_other" (a flooding scraper must not be able
#: to blow up the registry)
_MAX_CLIENT_SERIES = 256
_client_series: Set[str] = set()


def _count_client_request(client: Optional[str]) -> None:
    """Per-client request counts — the raw data the ROADMAP's per-client
    fairness follow-up needs before any shedding policy can use it."""
    name = client or "unknown"
    if name not in _client_series:
        if len(_client_series) >= _MAX_CLIENT_SERIES:
            name = "_other"
        _client_series.add(name)
    metrics.counter(
        "drand_serve_client_requests_total",
        "verification requests by client identity",
        labels={"client": name},
    ).inc()


#: gateway SLO: fraction of verifies that must finish within the bound.
#: 100ms covers a full batch tick + one kernel dispatch with margin; a
#: shed/timeout/closed error burns budget regardless of latency.
VERIFY_SLO_TARGET = 0.99
VERIFY_SLO_THRESHOLD = 0.1


def _consume_exception(fut: "asyncio.Future") -> None:
    if not fut.cancelled():
        fut.exception()


class GatewayError(Exception):
    """Base class for explicit gateway rejections.

    `trace_id` is stamped by `verify()` with the request span's id
    before the exception leaves the gateway, so a shed/timeout response
    can point its caller at `/debug/traces` — a rejection should never
    be anonymous."""

    trace_id: Optional[str] = None


class Overloaded(GatewayError):
    """Admission control shed the request (queue at capacity)."""


class ClientQuota(Overloaded):
    """One client exceeded its in-flight cap.  Subclasses Overloaded so
    the REST 429 / gRPC RESOURCE_EXHAUSTED mappings apply unchanged —
    the distinction is visible in the shed counters (`client_quota`) and
    the message, which tells the caller THEY are the source of load."""

    def __init__(self, client: str, cap: int):
        super().__init__(
            f"client {client!r} has {cap} verifications in flight "
            f"(per-client cap); retry after some complete"
        )
        self.client = client
        self.cap = cap


class DeadlineExceeded(GatewayError):
    """The request's deadline passed before its batch was assembled."""


class Oversize(GatewayError):
    """A signature exceeds the BLS encoding bound — rejected at
    admission so a garbage blob never occupies a kernel slot (REST 413 /
    gRPC INVALID_ARGUMENT)."""

    def __init__(self, limit: int, actual: int):
        super().__init__(
            f"signature of {actual} bytes exceeds the "
            f"{limit}-byte BLS bound"
        )
        self.limit = limit
        self.actual = actual


class GatewayClosed(GatewayError):
    """The gateway is shut down."""


@dataclass(frozen=True)
class VerifyRequest:
    """One beacon-verification claim (the chain link + its signature)."""

    round: int
    prev_round: int
    prev_sig: bytes
    signature: bytes

    @classmethod
    def from_beacon(cls, b: Beacon) -> "VerifyRequest":
        return cls(round=b.round, prev_round=b.prev_round,
                   prev_sig=b.prev_sig, signature=b.signature)

    def message(self) -> bytes:
        return beacon_message(self.prev_sig, self.prev_round, self.round)

    def key(self) -> tuple:
        """Cache/coalescing identity: the full claim, so a forged
        signature for a cached round can never alias a real verdict."""
        return (self.round, self.prev_round, self.prev_sig,
                self.signature)


@dataclass(frozen=True)
class VerifyResult:
    valid: bool
    cached: bool = False
    #: live size of the kernel batch that produced the verdict (0 when
    #: the cache answered)
    batch_size: int = 0
    #: the verdict came from the ring owner, not this replica
    forwarded: bool = False


class VerifyGateway:
    """Dynamic-batching verification front end over one `tbls.Scheme`.

    `dist_key` is the collective G1 public key — an oracle affine point
    or its 48-byte compressed encoding.
    """

    def __init__(self, dist_key, scheme: Optional[tbls.Scheme] = None, *,
                 max_batch: int = 128, max_wait: float = 0.005,
                 max_queue: int = 1024, cache_size: int = 4096,
                 default_timeout: float = 5.0,
                 client_max_inflight: Optional[int] = None,
                 mesh_devices: int = 1,
                 ring: Optional[ReplicaRing] = None):
        if isinstance(dist_key, (bytes, bytearray)):
            dist_key = ref.g1_from_bytes(bytes(dist_key))
        if mesh_devices < 1:
            raise ValueError("mesh_devices must be >= 1")
        self.dist_key = dist_key
        self.scheme = scheme or tbls.default_scheme()
        self.default_timeout = default_timeout
        self.cache = VerifiedRoundCache(cache_size)
        # mesh scheduler: with > 1 device lanes a flush is dealt into
        # per-device lanes and dispatched as ONE sharded pairing program
        # (scheme.verify_chain_batch_mesh); max_batch stays the TOTAL
        # budget so single- and mesh-sharded runs compare like-for-like.
        # Default (1) keeps the single-device scheduler byte-identical.
        self.mesh_devices = mesh_devices
        self._mesh_backend: Optional[str] = None
        self._mesh_batch_count = 0
        if mesh_devices > 1 and not hasattr(self.scheme,
                                            "verify_chain_batch_mesh"):
            log.warning("scheme has no mesh support; falling back to "
                        "the single-device scheduler",
                        scheme=type(self.scheme).__name__,
                        mesh_devices=mesh_devices)
            self.mesh_devices = 1
        # replica ring: off-owner requests forward once to the round's
        # owner and serve locally on failure (never a hard dependency)
        self.ring = ring
        # anonymous callers share only the global queue bound; identified
        # clients additionally get this in-flight cap (default: 3/4 of
        # the queue, so one identity can never fill it alone)
        self.client_max_inflight = (
            client_max_inflight if client_max_inflight is not None
            else max(1, max_queue * 3 // 4)
        )
        self._client_inflight: Dict[str, int] = {}
        self._batcher = BatchScheduler(
            self._flush, max_batch=max_batch, max_wait=max_wait,
            max_queue=max_queue, key_of=lambda item: item.client,
            lanes=self.mesh_devices,
        )
        #: key -> BatchItem for claims already queued: identical claims
        #: share one kernel slot and one verdict
        self._inflight: Dict[tuple, BatchItem] = {}
        self._executor: Optional[ThreadPoolExecutor] = None
        self._started = False
        self._closed = False
        # per-instance cache accounting for /v1/status hit rate
        self._hits = 0
        self._misses = 0
        # per-instance flush accounting: the scheduler-throughput number
        # (items per second of flush wall-clock) the loadgen artifact
        # compares across mesh sizes, free of client-side overhead
        self._flush_seconds = 0.0
        self._flush_items = 0
        obs_slo.ENGINE.objective(
            obs_slo.VERIFY_LATENCY,
            target=VERIFY_SLO_TARGET,
            threshold=VERIFY_SLO_THRESHOLD,
            describe=f"{VERIFY_SLO_TARGET:.0%} of gateway verifies "
                     f"answer within {VERIFY_SLO_THRESHOLD * 1000:.0f}ms "
                     "(sheds and timeouts always burn budget)",
        )

    # -- lifecycle ---------------------------------------------------------

    async def start(self) -> None:
        if self._started:
            return
        self._started = True
        # one worker: the device stream is serial anyway (the mesh path
        # too — it is ONE sharded program, XLA spreads it), and a second
        # concurrent dispatch would only fight for the same chips
        self._executor = ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="verify-gateway"
        )
        if self.mesh_devices > 1:
            # let the scheme build its mesh up front so a mesh that
            # cannot be constructed fails at start, not mid-flush
            configure = getattr(self.scheme, "configure_mesh", None)
            if configure is not None:
                self._mesh_backend = configure(self.mesh_devices)
        self._batcher.start()
        log.info("verification gateway started",
                 max_batch=self._batcher.max_batch,
                 max_wait=self._batcher.max_wait,
                 backend=type(self.scheme).__name__,
                 mesh_devices=self.mesh_devices,
                 mesh_backend=self._mesh_backend,
                 ring=(self.ring.stats()["replicas"]
                       if self.ring is not None else None))

    async def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        await self._batcher.close()
        for item in list(self._inflight.values()):
            if not item.future.done():
                item.future.set_exception(GatewayClosed("gateway closed"))
        self._inflight.clear()
        self._client_inflight.clear()
        if self._executor is not None:
            self._executor.shutdown(wait=False)
            self._executor = None
        _queue_depth.set(0)

    async def __aenter__(self) -> "VerifyGateway":
        await self.start()
        return self

    async def __aexit__(self, *exc) -> None:
        await self.close()

    # -- request path ------------------------------------------------------

    async def verify(self, req: VerifyRequest,
                     timeout: Optional[float] = None, *,
                     client: Optional[str] = None,
                     trace_id: Optional[str] = None,
                     forwarded: bool = False) -> VerifyResult:
        """Verify one claim; returns a verdict or raises a GatewayError.

        `client` is an opaque caller identity (peer address / header) for
        the per-client request counters; `trace_id` joins the caller's
        distributed trace when propagated.  `forwarded` marks a claim
        relayed by a sibling ring replica: it is always served here
        (forward exactly once, even when ring views disagree)."""
        if self._closed or not self._started:
            raise GatewayClosed("gateway is not serving")
        _count_client_request(client)
        attrs = {"round": req.round}
        if client:
            attrs["client"] = client
        loop = asyncio.get_running_loop()
        t0 = loop.time()
        with obs_trace.TRACER.span(
            "gateway.verify", trace_id=trace_id or None, attrs=attrs,
        ) as span:
            try:
                res = await self._verify_inner(req, timeout, span, client,
                                               forwarded=forwarded)
            except GatewayError as exc:
                # a request we refused or lost IS an SLO event: the
                # caller asked and was not answered — but not anonymous:
                # the response carries the span id for /debug/traces
                exc.trace_id = span.trace_id
                obs_slo.ENGINE.record_bad(obs_slo.VERIFY_LATENCY)
                raise
            obs_slo.ENGINE.observe(obs_slo.VERIFY_LATENCY,
                                   loop.time() - t0)
            return res

    async def _verify_inner(self, req: VerifyRequest,
                            timeout: Optional[float],
                            span, client: Optional[str] = None,
                            forwarded: bool = False) -> VerifyResult:
        n = max(len(req.signature), len(req.prev_sig))
        if n > tbls.SIG_LEN:
            _shed["oversize"].inc()
            obs_flight.RECORDER.record("shed", reason="oversize",
                                       round=req.round, bytes=n)
            raise Oversize(limit=tbls.SIG_LEN, actual=n)
        key = req.key()
        if self.cache.hit(key):
            self._hits += 1
            _cache_hits.inc()
            _requests["valid"].inc()
            span.set_attr("cached", True)
            return VerifyResult(valid=True, cached=True)
        self._misses += 1

        if self.ring is not None and not forwarded:
            res = await self._ring_forward(req, timeout, span, client)
            if res is not None:
                return res

        loop = asyncio.get_event_loop()
        timeout = self.default_timeout if timeout is None else timeout
        deadline = loop.time() + timeout
        item = self._inflight.get(key)
        if item is not None and not item.future.done():
            # identical claim already queued: ride its kernel slot, and
            # keep the slot alive to the LATEST interested deadline
            if item.deadline is not None:
                item.deadline = max(item.deadline, deadline)
            _coalesced.inc()
            span.set_attr("coalesced", True)
        else:
            if timeout <= 0:
                _shed["deadline"].inc()
                raise DeadlineExceeded("deadline expired before admission")
            if (client is not None
                    and self._client_inflight.get(client, 0)
                    >= self.client_max_inflight):
                _shed["client_quota"].inc()
                obs_flight.RECORDER.record("shed", reason="client_quota",
                                           round=req.round, client=client)
                raise ClientQuota(client, self.client_max_inflight)
            item = BatchItem(payload=req, deadline=deadline,
                             future=loop.create_future(),
                             span=obs_trace.TRACER.current(),
                             client=client)
            # every waiter may abandon the slot (wait_for timeout); mark
            # a late exception as retrieved so GC never logs noise
            item.future.add_done_callback(_consume_exception)
            try:
                self._batcher.submit(item)
            except asyncio.QueueFull:
                _shed["queue_full"].inc()
                obs_flight.RECORDER.record("shed", reason="queue_full",
                                           round=req.round)
                raise Overloaded(
                    f"verification queue full "
                    f"({self._batcher._queue.maxsize} deep); retry later"
                ) from None
            if client is not None:
                self._client_inflight[client] = (
                    self._client_inflight.get(client, 0) + 1
                )
                # "in flight" ends when the verdict (or error) lands —
                # tying the release to future resolution covers every
                # path: demux, deadline drop, flush fault, close
                item.future.add_done_callback(
                    lambda _f, c=client: self._dec_client(c)
                )
            self._inflight[key] = item
            _queue_depth.inc()
        # outer wait_for is a backstop for coalesced waiters whose own
        # deadline is earlier than the slot's extended one
        try:
            return await asyncio.wait_for(
                asyncio.shield(item.future), timeout
            )
        except asyncio.TimeoutError:
            _shed["deadline"].inc()
            obs_flight.RECORDER.record("shed", reason="deadline",
                                       round=req.round)
            raise DeadlineExceeded(
                f"no verdict within {timeout:.3f}s"
            ) from None

    async def _ring_forward(self, req: VerifyRequest,
                            timeout: Optional[float], span,
                            client: Optional[str]
                            ) -> Optional[VerifyResult]:
        """Route an off-owner claim to its ring owner; None means "serve
        locally" (we own it, no forwarder, or the forward failed — a
        replica never hard-depends on its siblings)."""
        owner = self.ring.owner(req.round)
        if owner == self.ring.self_id or not self.ring.can_forward:
            return None
        span.set_attr("ring_owner", owner)
        try:
            res = await self.ring.forward(owner, req, timeout, client)
        except GatewayClosed:
            # dead or closing owner: a strike (eviction re-owns its
            # rounds after fail_evict in a row), then serve locally
            self.ring.note_failure(owner)
            self.ring.note_local_fallback()
            span.set_attr("ring_fallback", "owner_closed")
            return None
        except GatewayError:
            # the owner answered with an explicit shed: it is alive
            # (no strike), but this replica still owes a verdict
            self.ring.note_alive(owner)
            self.ring.note_local_fallback()
            span.set_attr("ring_fallback", "owner_shed")
            return None
        except Exception as exc:  # noqa: BLE001 — transport failure
            self.ring.note_failure(owner)
            self.ring.note_local_fallback()
            span.set_attr("ring_fallback", "transport")
            log.warning("ring forward failed; serving locally",
                        owner=owner, round=req.round, error=repr(exc))
            return None
        self.ring.note_alive(owner)
        span.set_attr("forwarded", True)
        return res

    async def verify_many(self, reqs: Sequence[VerifyRequest],
                          timeout: Optional[float] = None, *,
                          client: Optional[str] = None
                          ) -> List[VerifyResult]:
        """Concurrent verify of several claims (they share batches);
        per-item GatewayErrors come back in-place as exceptions."""
        return await asyncio.gather(
            *(self.verify(r, timeout, client=client) for r in reqs),
            return_exceptions=True,
        )

    def stats(self) -> dict:
        """Live gateway state for /v1/status."""
        total = self._hits + self._misses
        return {
            "backend": type(self.scheme).__name__,
            "queue_depth": self._batcher.depth,
            "max_queue": self._batcher._queue.maxsize,
            "max_batch": self._batcher.max_batch,
            "max_wait": self._batcher.max_wait,
            "inflight": len(self._inflight),
            "client_max_inflight": self.client_max_inflight,
            "clients_inflight": dict(self._client_inflight),
            "cache_entries": len(self.cache),
            "cache_hit_rate": (self._hits / total) if total else None,
            "closed": self._closed,
            # shard/ring visibility: loadgen artifacts and operators read
            # the mesh BACKEND here, so a CPU-pool fallback can never
            # masquerade as TPU numbers
            "mesh": {
                "devices": self.mesh_devices,
                "backend": self._mesh_backend,
                "sharded_batches": self._mesh_batch_count,
            },
            "ring": (self.ring.stats() if self.ring is not None
                     else None),
            "flush_seconds": round(self._flush_seconds, 6),
            "flush_items": self._flush_items,
        }

    # -- batch flush (BatchScheduler callback) -----------------------------

    def _dec_client(self, client: Optional[str]) -> None:
        """Release one unit of a client's in-flight quota (no-op for
        anonymous items)."""
        if client is None:
            return
        left = self._client_inflight.get(client, 0) - 1
        if left <= 0:
            self._client_inflight.pop(client, None)
        else:
            self._client_inflight[client] = left

    # the flush-throughput clocks run INSIDE the (single) executor
    # thread, right around the backend call: event-loop backlog while
    # thousands of client coroutines churn must not pollute the
    # scheduler-throughput number the loadgen artifact compares across
    # mesh sizes

    def _run_kernel(self, msgs: List[bytes],
                    sigs: List[bytes]) -> List[bool]:
        t0 = time.perf_counter()
        try:
            return self.scheme.verify_chain_batch(
                self.dist_key, msgs, sigs
            )
        finally:
            dt = time.perf_counter() - t0
            self._flush_seconds += dt
            self._flush_items += len(msgs)
            # gateway flush latency joins the perf observatory's stage
            # baselines (same registry the round stages feed)
            obs_perf.observe_stage("gateway.flush", dt)

    def _run_kernel_mesh(self, lane_msgs: List[List[bytes]],
                         lane_sigs: List[List[bytes]]
                         ) -> List[List[bool]]:
        t0 = time.perf_counter()
        try:
            return self.scheme.verify_chain_batch_mesh(
                self.dist_key, lane_msgs, lane_sigs
            )
        finally:
            dt = time.perf_counter() - t0
            self._flush_seconds += dt
            self._flush_items += sum(len(l) for l in lane_msgs)
            obs_perf.observe_stage("gateway.flush_mesh", dt)

    async def _flush(self, items: List[BatchItem]) -> None:
        loop = asyncio.get_event_loop()
        # popped off the queue: locked dec mirrors the per-submit inc
        _queue_depth.dec(float(len(items)))
        now = loop.time()
        live: List[BatchItem] = []
        for item in items:
            req = item.payload
            self._inflight.pop(req.key(), None)
            if item.deadline is not None and now > item.deadline:
                _shed["deadline"].inc()
                obs_flight.RECORDER.record("shed", reason="deadline",
                                           round=req.round)
                if not item.future.done():
                    item.future.set_exception(DeadlineExceeded(
                        "deadline passed while queued"
                    ))
                continue
            live.append(item)
        if not live:
            return
        mesh = (self.mesh_devices > 1)
        _batch_size.observe(float(len(live)))
        attrs = {"requests": len(live)}
        if mesh:
            lanes = assemble_lanes(live, self.mesh_devices)
            for lane in lanes:
                _device_occupancy.observe(float(len(lane)))
            _mesh_batches.inc()
            self._mesh_batch_count += 1
            attrs["devices"] = self.mesh_devices
        with obs_trace.TRACER.span("gateway.batch", attrs=attrs) as bspan:
            # link every request span to the batch that served it (and
            # vice versa the batch id is enough to find all riders)
            if bspan.span_id is not None:
                for item in live:
                    if item.span is not None:
                        item.span.set_attr("batch_span", bspan.span_id)
                        item.span.set_attr("batch_trace", bspan.trace_id)
            with _batch_seconds.time():
                # run_in_executor does NOT copy the contextvars context
                # (unlike asyncio.to_thread) — carry it explicitly so the
                # backend's kernel spans parent to this batch span
                ctx = contextvars.copy_context()
                if mesh:
                    lane_msgs = [[i.payload.message() for i in lane]
                                 for lane in lanes]
                    lane_sigs = [[i.payload.signature for i in lane]
                                 for lane in lanes]
                    lane_verdicts = await loop.run_in_executor(
                        self._executor, ctx.run, self._run_kernel_mesh,
                        lane_msgs, lane_sigs,
                    )
                    live = [i for lane in lanes for i in lane]
                    verdicts = [v for lane in lane_verdicts for v in lane]
                else:
                    msgs = [item.payload.message() for item in live]
                    sigs = [item.payload.signature for item in live]
                    verdicts = await loop.run_in_executor(
                        self._executor, ctx.run, self._run_kernel,
                        msgs, sigs,
                    )
        for item, ok in zip(live, verdicts):
            ok = bool(ok)
            _requests["valid" if ok else "invalid"].inc()
            if ok:
                self.cache.add(item.payload.key())
            if not item.future.done():
                item.future.set_result(
                    VerifyResult(valid=ok, batch_size=len(live))
                )
