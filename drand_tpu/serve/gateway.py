"""The verification gateway: many concurrent callers, one batched kernel.

`VerifyGateway.verify` is the whole public surface: await it with a
(round, prev_round, prev_sig, signature) claim and get a verdict.
Internally a request flows

  cache probe -> in-flight coalescing -> admission control (bounded
  queue, else shed) -> BatchScheduler tick -> one padded
  `verify_chain_batch` device call -> per-request demux

The crypto backend is any `tbls.Scheme`: JaxScheme turns each tick into
a single fixed-shape Pallas/op-graph dispatch (its `_bucket` padding
means the jitted kernel never re-traces); NativeScheme/RefScheme serve
the same contract off-TPU.  The kernel call runs in a one-thread
executor so the event loop keeps admitting (and shedding) while the
device is busy.

Failure semantics are explicit, never silent latency:
* queue full            -> `Overloaded`       (REST 429 / gRPC
                           RESOURCE_EXHAUSTED)
* deadline passed while
  queued                -> `DeadlineExceeded` (rejected at batch
                           assembly — a late verdict is never served)
* gateway closed        -> `GatewayClosed`
"""

from __future__ import annotations

import asyncio
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence

from drand_tpu.beacon.chain import Beacon, beacon_message
from drand_tpu.crypto import refimpl as ref
from drand_tpu.crypto import tbls
from drand_tpu.serve.batcher import BatchItem, BatchScheduler
from drand_tpu.serve.cache import VerifiedRoundCache
from drand_tpu.utils import metrics
from drand_tpu.utils.logging import get_logger

log = get_logger("serve.gateway")

#: batch occupancy is size-shaped, not latency-shaped
_BATCH_BUCKETS = (1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0, 128.0, 256.0,
                  512.0, 1024.0)

_queue_depth = metrics.gauge(
    "drand_serve_queue_depth", "verification requests waiting for a batch"
)
_batch_size = metrics.histogram(
    "drand_serve_batch_size", "requests per kernel batch",
    buckets=_BATCH_BUCKETS,
)
_batch_seconds = metrics.histogram(
    "drand_serve_batch_seconds", "wall time of one batched verify call"
)
_cache_hits = metrics.counter(
    "drand_serve_cache_hits_total", "requests served from the "
    "verified-round cache without touching the kernel"
)
_coalesced = metrics.counter(
    "drand_serve_coalesced_total", "requests attached to an identical "
    "in-flight verification"
)
_shed = {
    reason: metrics.counter(
        "drand_serve_shed_total",
        "requests rejected instead of served late",
        labels={"reason": reason},
    )
    for reason in ("queue_full", "deadline")
}
_requests = {
    result: metrics.counter(
        "drand_serve_requests_total", "verification verdicts returned",
        labels={"result": result},
    )
    for result in ("valid", "invalid")
}


def _consume_exception(fut: "asyncio.Future") -> None:
    if not fut.cancelled():
        fut.exception()


class GatewayError(Exception):
    """Base class for explicit gateway rejections."""


class Overloaded(GatewayError):
    """Admission control shed the request (queue at capacity)."""


class DeadlineExceeded(GatewayError):
    """The request's deadline passed before its batch was assembled."""


class GatewayClosed(GatewayError):
    """The gateway is shut down."""


@dataclass(frozen=True)
class VerifyRequest:
    """One beacon-verification claim (the chain link + its signature)."""

    round: int
    prev_round: int
    prev_sig: bytes
    signature: bytes

    @classmethod
    def from_beacon(cls, b: Beacon) -> "VerifyRequest":
        return cls(round=b.round, prev_round=b.prev_round,
                   prev_sig=b.prev_sig, signature=b.signature)

    def message(self) -> bytes:
        return beacon_message(self.prev_sig, self.prev_round, self.round)

    def key(self) -> tuple:
        """Cache/coalescing identity: the full claim, so a forged
        signature for a cached round can never alias a real verdict."""
        return (self.round, self.prev_round, self.prev_sig,
                self.signature)


@dataclass(frozen=True)
class VerifyResult:
    valid: bool
    cached: bool = False
    #: live size of the kernel batch that produced the verdict (0 when
    #: the cache answered)
    batch_size: int = 0


class VerifyGateway:
    """Dynamic-batching verification front end over one `tbls.Scheme`.

    `dist_key` is the collective G1 public key — an oracle affine point
    or its 48-byte compressed encoding.
    """

    def __init__(self, dist_key, scheme: Optional[tbls.Scheme] = None, *,
                 max_batch: int = 128, max_wait: float = 0.005,
                 max_queue: int = 1024, cache_size: int = 4096,
                 default_timeout: float = 5.0):
        if isinstance(dist_key, (bytes, bytearray)):
            dist_key = ref.g1_from_bytes(bytes(dist_key))
        self.dist_key = dist_key
        self.scheme = scheme or tbls.default_scheme()
        self.default_timeout = default_timeout
        self.cache = VerifiedRoundCache(cache_size)
        self._batcher = BatchScheduler(
            self._flush, max_batch=max_batch, max_wait=max_wait,
            max_queue=max_queue,
        )
        #: key -> BatchItem for claims already queued: identical claims
        #: share one kernel slot and one verdict
        self._inflight: Dict[tuple, BatchItem] = {}
        self._executor: Optional[ThreadPoolExecutor] = None
        self._started = False
        self._closed = False

    # -- lifecycle ---------------------------------------------------------

    async def start(self) -> None:
        if self._started:
            return
        self._started = True
        # one worker: the device stream is serial anyway, and a second
        # concurrent dispatch would only fight for the same chip
        self._executor = ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="verify-gateway"
        )
        self._batcher.start()
        log.info("verification gateway started",
                 max_batch=self._batcher.max_batch,
                 max_wait=self._batcher.max_wait,
                 backend=type(self.scheme).__name__)

    async def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        await self._batcher.close()
        for item in list(self._inflight.values()):
            if not item.future.done():
                item.future.set_exception(GatewayClosed("gateway closed"))
        self._inflight.clear()
        if self._executor is not None:
            self._executor.shutdown(wait=False)
            self._executor = None

    async def __aenter__(self) -> "VerifyGateway":
        await self.start()
        return self

    async def __aexit__(self, *exc) -> None:
        await self.close()

    # -- request path ------------------------------------------------------

    async def verify(self, req: VerifyRequest,
                     timeout: Optional[float] = None) -> VerifyResult:
        """Verify one claim; returns a verdict or raises a GatewayError."""
        if self._closed or not self._started:
            raise GatewayClosed("gateway is not serving")
        key = req.key()
        if self.cache.hit(key):
            _cache_hits.inc()
            _requests["valid"].inc()
            return VerifyResult(valid=True, cached=True)

        loop = asyncio.get_event_loop()
        timeout = self.default_timeout if timeout is None else timeout
        deadline = loop.time() + timeout
        item = self._inflight.get(key)
        if item is not None and not item.future.done():
            # identical claim already queued: ride its kernel slot, and
            # keep the slot alive to the LATEST interested deadline
            if item.deadline is not None:
                item.deadline = max(item.deadline, deadline)
            _coalesced.inc()
        else:
            if timeout <= 0:
                _shed["deadline"].inc()
                raise DeadlineExceeded("deadline expired before admission")
            item = BatchItem(payload=req, deadline=deadline,
                             future=loop.create_future())
            # every waiter may abandon the slot (wait_for timeout); mark
            # a late exception as retrieved so GC never logs noise
            item.future.add_done_callback(_consume_exception)
            try:
                self._batcher.submit(item)
            except asyncio.QueueFull:
                _shed["queue_full"].inc()
                raise Overloaded(
                    f"verification queue full "
                    f"({self._batcher._queue.maxsize} deep); retry later"
                ) from None
            self._inflight[key] = item
            _queue_depth.set(self._batcher.depth)
        # outer wait_for is a backstop for coalesced waiters whose own
        # deadline is earlier than the slot's extended one
        try:
            return await asyncio.wait_for(
                asyncio.shield(item.future), timeout
            )
        except asyncio.TimeoutError:
            _shed["deadline"].inc()
            raise DeadlineExceeded(
                f"no verdict within {timeout:.3f}s"
            ) from None

    async def verify_many(self, reqs: Sequence[VerifyRequest],
                          timeout: Optional[float] = None
                          ) -> List[VerifyResult]:
        """Concurrent verify of several claims (they share batches);
        per-item GatewayErrors come back in-place as exceptions."""
        return await asyncio.gather(
            *(self.verify(r, timeout) for r in reqs),
            return_exceptions=True,
        )

    # -- batch flush (BatchScheduler callback) -----------------------------

    def _run_kernel(self, msgs: List[bytes],
                    sigs: List[bytes]) -> List[bool]:
        return self.scheme.verify_chain_batch(self.dist_key, msgs, sigs)

    async def _flush(self, items: List[BatchItem]) -> None:
        loop = asyncio.get_event_loop()
        _queue_depth.set(self._batcher.depth)
        now = loop.time()
        live: List[BatchItem] = []
        for item in items:
            req = item.payload
            self._inflight.pop(req.key(), None)
            if item.deadline is not None and now > item.deadline:
                _shed["deadline"].inc()
                if not item.future.done():
                    item.future.set_exception(DeadlineExceeded(
                        "deadline passed while queued"
                    ))
                continue
            live.append(item)
        if not live:
            return
        msgs = [item.payload.message() for item in live]
        sigs = [item.payload.signature for item in live]
        _batch_size.observe(float(len(live)))
        with _batch_seconds.time():
            verdicts = await loop.run_in_executor(
                self._executor, self._run_kernel, msgs, sigs
            )
        for item, ok in zip(live, verdicts):
            ok = bool(ok)
            _requests["valid" if ok else "invalid"].inc()
            if ok:
                self.cache.add(item.payload.key())
            if not item.future.done():
                item.future.set_result(
                    VerifyResult(valid=ok, batch_size=len(live))
                )
