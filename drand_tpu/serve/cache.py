"""LRU cache of already-verified rounds.

Same role as `beacon/round_cache.py` plays for partials — bounded
per-round state in front of the expensive crypto — but keyed on the
full beacon identity, because the gateway serves arbitrary (round,
signature) claims from untrusted clients, not just the active round.

Only VALID verdicts are cached.  An invalid signature is unbounded
attacker-chosen garbage: caching it would let a flood of junk evict the
real entries, while re-verifying junk just re-charges the attacker the
kernel cost.  A valid beacon, by contrast, is unique per round (BLS is
deterministic), so the cache is naturally bounded by chain length.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Hashable


class VerifiedRoundCache:
    """Bounded LRU set of verified beacon identities.

    Thread-safe: the gateway reads it from the event loop but flush
    callbacks may run completions from executor threads.
    """

    def __init__(self, capacity: int = 4096):
        if capacity < 0:
            raise ValueError("capacity must be >= 0")
        self.capacity = capacity
        self._entries: "OrderedDict[Hashable, None]" = OrderedDict()
        self._lock = threading.Lock()

    def __len__(self) -> int:
        return len(self._entries)

    def __contains__(self, key: Hashable) -> bool:
        return self.hit(key)

    def hit(self, key: Hashable) -> bool:
        """True (and refresh recency) if `key` was verified before."""
        with self._lock:
            if key not in self._entries:
                return False
            self._entries.move_to_end(key)
            return True

    def add(self, key: Hashable) -> None:
        if self.capacity == 0:
            return
        with self._lock:
            self._entries[key] = None
            self._entries.move_to_end(key)
            while len(self._entries) > self.capacity:
                self._entries.popitem(last=False)

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()
