"""Injectable clocks: real time and a fake clock for deterministic tests.

The reference threads `jonboulle/clockwork` fake clocks through every
handler (beacon.Config.Clock /root/reference/beacon/beacon.go:34,
core.Config.clock core/config.go:37) so multi-node protocol tests can
drive rounds without wall time.  This is the asyncio equivalent: awaiting
`clock.sleep(dt)` on a FakeClock parks the task until a test calls
`advance(dt)`.
"""

from __future__ import annotations

import asyncio
import heapq
import time
from typing import List, Tuple


class Clock:
    """Real wall clock."""

    def now(self) -> float:
        return time.time()

    async def sleep(self, seconds: float) -> None:
        await asyncio.sleep(max(0.0, seconds))


class FakeClock(Clock):
    """Deterministic manual clock.

    `advance(dt)` moves time forward and wakes every sleeper whose
    deadline has passed, yielding control so woken tasks run promptly.
    """

    def __init__(self, start: float = 1_700_000_000.0):
        self._now = start
        self._sleepers: List[Tuple[float, int, asyncio.Future]] = []
        self._seq = 0

    def now(self) -> float:
        return self._now

    async def sleep(self, seconds: float) -> None:
        if seconds <= 0:
            await asyncio.sleep(0)
            return
        fut = asyncio.get_running_loop().create_future()
        self._seq += 1
        heapq.heappush(self._sleepers, (self._now + seconds, self._seq, fut))
        await fut

    async def advance(self, seconds: float) -> None:
        """Move time forward, waking sleepers in deadline order."""
        target = self._now + seconds
        while self._sleepers and self._sleepers[0][0] <= target:
            deadline, _, fut = heapq.heappop(self._sleepers)
            self._now = max(self._now, deadline)
            if not fut.done():
                fut.set_result(None)
            # let woken tasks (and anything they spawn) run
            for _ in range(10):
                await asyncio.sleep(0)
        self._now = target
        for _ in range(10):
            await asyncio.sleep(0)

    def pending_sleepers(self) -> int:
        return len([s for s in self._sleepers if not s[2].done()])
