"""Injectable clocks: real time and a fake clock for deterministic tests.

The reference threads `jonboulle/clockwork` fake clocks through every
handler (beacon.Config.Clock /root/reference/beacon/beacon.go:34,
core.Config.clock core/config.go:37) so multi-node protocol tests can
drive rounds without wall time.  This is the asyncio equivalent: awaiting
`clock.sleep(dt)` on a FakeClock parks the task until a test calls
`advance(dt)`.

The simulation harness (drand_tpu/sim/) extends the same clock into a
schedulable event loop: `call_at` registers plain callbacks (the fake
network fabric uses them for message-delivery deadlines) and `advance`
interleaves scheduled callbacks with sleeping tasks in strict deadline
order, so an entire multi-node network runs on one deterministic
timeline.  `SkewedClock` wraps a base clock with a per-node offset —
`now()` lies by `skew` seconds while `sleep` still parks on the shared
timeline — which is how the simulator gives each node its own (wrong)
notion of time without forking the timeline itself.
"""

from __future__ import annotations

import asyncio
import heapq
import time
from typing import Callable, List, Tuple


class Clock:
    """Real wall clock."""

    def now(self) -> float:
        return time.time()

    async def sleep(self, seconds: float) -> None:
        await asyncio.sleep(max(0.0, seconds))


class FakeClock(Clock):
    """Deterministic manual clock.

    `advance(dt)` moves time forward and wakes every sleeper whose
    deadline has passed, yielding control so woken tasks run promptly.
    Scheduled callbacks (`call_at`) share the same deadline ordering:
    ties break by registration order (a monotonically increasing
    sequence number), never by object identity — replays stay
    byte-identical across processes.
    """

    def __init__(self, start: float = 1_700_000_000.0):
        self._now = start
        self._sleepers: List[Tuple[float, int, asyncio.Future]] = []
        #: (deadline, seq, callback, args) — callbacks run synchronously
        #: at their deadline, before any later sleeper wakes
        self._scheduled: List[Tuple[float, int, Callable, tuple]] = []
        self._seq = 0

    def now(self) -> float:
        return self._now

    async def sleep(self, seconds: float) -> None:
        if seconds <= 0:
            await asyncio.sleep(0)
            return
        fut = asyncio.get_running_loop().create_future()
        self._seq += 1
        heapq.heappush(self._sleepers, (self._now + seconds, self._seq, fut))
        await fut

    # -- scheduled callbacks (sim fabric) ---------------------------------

    def call_at(self, when: float, callback: Callable, *args) -> None:
        """Run `callback(*args)` when the clock reaches `when` (clamped to
        now: the past is not a place this clock can deliver to)."""
        self._seq += 1
        heapq.heappush(
            self._scheduled, (max(when, self._now), self._seq, callback, args)
        )

    def fire_due(self) -> int:
        """Run every scheduled callback whose deadline has arrived.
        Returns how many fired (callbacks may schedule more; those run
        too if already due)."""
        fired = 0
        while self._scheduled and self._scheduled[0][0] <= self._now:
            _, _, cb, args = heapq.heappop(self._scheduled)
            cb(*args)
            fired += 1
        return fired

    def _next_deadline(self) -> float:
        """Earliest pending deadline across sleepers and callbacks."""
        deadlines = []
        if self._sleepers:
            deadlines.append(self._sleepers[0][0])
        if self._scheduled:
            deadlines.append(self._scheduled[0][0])
        return min(deadlines) if deadlines else float("inf")

    async def advance(self, seconds: float) -> None:
        """Move time forward, firing callbacks and waking sleepers in
        strict deadline order (registration order breaks ties between a
        callback and a sleeper at the same instant)."""
        target = self._now + seconds
        while True:
            nxt = self._next_deadline()
            if nxt > target:
                break
            self._now = max(self._now, nxt)
            # same-deadline entries: lower seq goes first across BOTH heaps
            take_sleeper = bool(self._sleepers) and \
                self._sleepers[0][0] <= self._now and \
                (not self._scheduled
                 or self._scheduled[0][0] > self._now
                 or self._sleepers[0][1] < self._scheduled[0][1])
            if take_sleeper:
                _, _, fut = heapq.heappop(self._sleepers)
                if not fut.done():
                    fut.set_result(None)
            else:
                _, _, cb, args = heapq.heappop(self._scheduled)
                cb(*args)
            # let woken tasks (and anything they spawn) run
            for _ in range(10):
                await asyncio.sleep(0)
        self._now = target
        for _ in range(10):
            await asyncio.sleep(0)

    async def advance_to(self, when: float) -> None:
        """Advance to an absolute time (no-op if already past it)."""
        if when > self._now:
            await self.advance(when - self._now)

    def pending_sleepers(self) -> int:
        return len([s for s in self._sleepers if not s[2].done()])

    def pending_callbacks(self) -> int:
        return len(self._scheduled)


class SkewedClock(Clock):
    """A per-node view of a shared base clock, offset by `skew` seconds.

    `now()` reports the skewed time (the node *believes* it); `sleep`
    parks on the base clock's timeline, because a wrong wall clock does
    not make real durations pass faster.  The skew is mutable so a
    scenario can drift a node mid-run."""

    def __init__(self, base: Clock, skew: float = 0.0):
        self.base = base
        self.skew = skew

    def now(self) -> float:
        return self.base.now() + self.skew

    async def sleep(self, seconds: float) -> None:
        await self.base.sleep(seconds)
