"""Process-wide metrics registry with Prometheus text exposition.

The reference has no metrics at all — observability is logs only (SURVEY
§5; /root/reference/Makefile runs plain `go test`, no pprof/metrics
endpoints anywhere).  The TPU build does better: counters/gauges/
histograms for the protocol plane (rounds, partials, sync batches) and
per-kernel device timings for the crypto plane, exposed at the REST
gateway's ``/metrics`` in Prometheus text format.

Deliberately dependency-free (no prometheus_client): a few dozen lines
cover everything the daemon needs, and the registry stays importable from
the pure-protocol path without pulling in jax.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional, Set, Tuple, cast

_BUCKETS = (
    0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
    0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0,
)

#: Canonical name registry for every drand_* series the tree emits.
#: deploy/prometheus-alerts.yml and deploy/grafana-dashboard.json match
#: these strings with PromQL regexes the interpreter never sees — a
#: rename at a call site silently rots the alert.  drand-lint's
#: `reg-metric-name` rule resolves every literal registration against
#: this set (and `reg-deploy-metric` checks the deploy files the other
#: way), so renames fail CI instead: add/rename the name here FIRST.
METRIC_NAMES = frozenset({
    # beacon protocol plane
    "drand_beacon_rounds_total", "drand_beacon_rounds_failed_total",
    "drand_beacon_partials_received_total",
    "drand_beacon_partials_rejected_total",
    "drand_beacon_sync_rounds_verified_total",
    "drand_beacon_optimistic_fallbacks_total",
    "drand_beacon_round_seconds", "drand_beacon_head_round",
    "drand_chain_reorgs_total", "drand_sync_failures_total",
    # crypto / device plane
    "drand_device_kernel_seconds", "drand_dkg_phase_seconds",
    # verification gateway + replica ring
    "drand_serve_queue_depth", "drand_serve_batch_size",
    "drand_serve_batch_seconds", "drand_serve_cache_hits_total",
    "drand_serve_coalesced_total", "drand_serve_device_occupancy",
    "drand_serve_mesh_batches_total", "drand_serve_shed_total",
    "drand_serve_requests_total", "drand_serve_client_requests_total",
    "drand_serve_ring_forwarded_total",
    "drand_serve_ring_forward_failures_total",
    "drand_serve_ring_local_fallback_total",
    "drand_serve_ring_evicted_total",
    # SLO engine
    "drand_slo_events_total", "drand_slo_breaches_total",
    "drand_slo_burn_rate", "drand_slo_error_budget_remaining",
    # per-signer contribution ledger
    "drand_peer_partial_latency_seconds",
    "drand_peer_invalid_partials_total",
    "drand_peer_orphaned_beacons_total",
    "drand_peer_missed_rounds_total", "drand_peer_late_partials_total",
    # external chain watchdog
    "drand_watch_polls_total", "drand_watch_verified_rounds_total",
    "drand_watch_bad_beacons_total", "drand_watch_forks_total",
    "drand_watch_reorgs_total", "drand_watch_fork_detected",
    "drand_watch_stalled", "drand_watch_head_round",
    "drand_watch_peer_head_round", "drand_watch_peer_head_lag",
    # fleet aggregation
    "drand_fleet_head_spread", "drand_fleet_quorum_margin",
    "drand_fleet_worst_burn_rate", "drand_fleet_nodes_reachable",
    "drand_fleet_worst_stage_p99_seconds",
    "drand_fleet_dispatch_budget_breaching",
    # performance observatory
    "drand_perf_stage_p99_seconds", "drand_perf_round_dispatches",
    "drand_perf_dispatch_budget_exceeded_total",
    "drand_perf_dispatch_budget_episodes_total",
    "drand_perf_recompiles_suspected_total",
})


def _escape_label_value(v: str) -> str:
    # Prometheus exposition: backslash, double-quote and newline must be
    # escaped inside label values or the line breaks the parser.
    return (str(v).replace("\\", "\\\\")
                  .replace('"', '\\"')
                  .replace("\n", "\\n"))


def _fmt_labels(labels: Tuple[Tuple[str, str], ...]) -> str:
    if not labels:
        return ""
    inner = ",".join(f'{k}="{_escape_label_value(v)}"' for k, v in labels)
    return "{" + inner + "}"


class Counter:
    def __init__(self) -> None:
        self._v = 0.0
        self._lock = threading.Lock()

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self._v += amount

    @property
    def value(self) -> float:
        return self._v


class Gauge:
    def __init__(self) -> None:
        self._v = 0.0
        self._lock = threading.Lock()

    def set(self, v: float) -> None:
        with self._lock:
            self._v = v

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self._v += amount

    def dec(self, amount: float = 1.0) -> None:
        with self._lock:
            self._v -= amount

    @property
    def value(self) -> float:
        return self._v


class Histogram:
    def __init__(self, buckets: Tuple[float, ...] = _BUCKETS) -> None:
        self._buckets = buckets
        self._counts = [0] * (len(buckets) + 1)
        self._sum = 0.0
        self._n = 0
        self._lock = threading.Lock()

    def observe(self, v: float) -> None:
        with self._lock:
            self._sum += v
            self._n += 1
            for i, b in enumerate(self._buckets):
                if v <= b:
                    self._counts[i] += 1
                    return
            self._counts[-1] += 1

    def time(self) -> "_Timer":
        return _Timer(self)

    @property
    def count(self) -> int:
        return self._n

    @property
    def sum(self) -> float:
        return self._sum


class _Timer:
    def __init__(self, h: Histogram) -> None:
        self._h = h

    def __enter__(self) -> "_Timer":
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc: object) -> bool:
        self._h.observe(time.perf_counter() - self._t0)
        return False


_KIND_NAMES: Dict[type, str] = {
    Counter: "counter",
    Gauge: "gauge",
    Histogram: "histogram",
}

_LabelKey = Tuple[str, Tuple[Tuple[str, str], ...]]


class Registry:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._metrics: Dict[_LabelKey, object] = {}
        self._help: Dict[str, Tuple[str, str]] = {}  # name -> (type, help)

    def _get(self, kind: type, name: str, help: str,
             labels: Optional[Dict[str, str]], **kwargs: Any) -> object:
        key = (name, tuple(sorted((labels or {}).items())))
        with self._lock:
            m = self._metrics.get(key)
            if m is None:
                m = kind(**kwargs)
                self._metrics[key] = m
                self._help.setdefault(name, (_KIND_NAMES[kind], help))
            return m

    def counter(self, name: str, help: str = "",
                labels: Optional[Dict[str, str]] = None) -> Counter:
        return cast(Counter, self._get(Counter, name, help, labels))

    def gauge(self, name: str, help: str = "",
              labels: Optional[Dict[str, str]] = None) -> Gauge:
        return cast(Gauge, self._get(Gauge, name, help, labels))

    def histogram(self, name: str, help: str = "",
                  labels: Optional[Dict[str, str]] = None,
                  buckets: Optional[Tuple[float, ...]] = None) -> Histogram:
        """`buckets` overrides the latency-oriented defaults (used for
        size-shaped distributions like batch occupancy); it only applies
        on first registration of a (name, labels) series."""
        if buckets is not None:
            return cast(Histogram, self._get(Histogram, name, help,
                                             labels, buckets=buckets))
        return cast(Histogram, self._get(Histogram, name, help, labels))

    def render(self) -> str:
        """Prometheus text exposition format."""
        with self._lock:
            items = sorted(self._metrics.items())
            helps = dict(self._help)
        lines: List[str] = []
        seen_header: Set[str] = set()
        for (name, labels), m in items:
            if name not in seen_header:
                typ, help = helps.get(name, ("untyped", ""))
                if help:
                    lines.append(f"# HELP {name} {help}")
                lines.append(f"# TYPE {name} {typ}")
                seen_header.add(name)
            lab = _fmt_labels(labels)
            if isinstance(m, Counter):
                lines.append(f"{name}{lab} {m.value}")
            elif isinstance(m, Gauge):
                lines.append(f"{name}{lab} {m.value}")
            elif isinstance(m, Histogram):
                acc = 0
                for b, c in zip(m._buckets, m._counts):
                    acc += c
                    blab = dict(labels)
                    blab["le"] = repr(b)
                    lines.append(
                        f"{name}_bucket"
                        f"{_fmt_labels(tuple(sorted(blab.items())))} {acc}"
                    )
                blab = dict(labels)
                blab["le"] = "+Inf"
                lines.append(
                    f"{name}_bucket"
                    f"{_fmt_labels(tuple(sorted(blab.items())))} {m.count}"
                )
                lines.append(f"{name}_sum{lab} {m.sum}")
                lines.append(f"{name}_count{lab} {m.count}")
        return "\n".join(lines) + "\n"

    def reset(self) -> None:
        with self._lock:
            self._metrics.clear()
            self._help.clear()


#: the default process-wide registry
REGISTRY = Registry()

counter = REGISTRY.counter
gauge = REGISTRY.gauge
histogram = REGISTRY.histogram
render = REGISTRY.render
