"""Small host-side utilities: minimal TOML writer, durations, hex codecs."""

from __future__ import annotations

from typing import Any, Dict, List


def toml_dumps(data: Dict[str, Any]) -> str:
    """Minimal TOML serializer for the subset the key store needs.

    Supports: str/int/float/bool scalars, lists of strings, and lists of
    dicts (rendered as [[table]] arrays).  Read back with stdlib tomllib.
    """
    lines: List[str] = []
    tables: List[str] = []

    def scalar(v) -> str:
        if isinstance(v, bool):
            return "true" if v else "false"
        if isinstance(v, (int, float)):
            return repr(v)
        if isinstance(v, str):
            return '"' + v.replace("\\", "\\\\").replace('"', '\\"') + '"'
        raise TypeError(f"unsupported TOML scalar: {type(v)}")

    for k, v in data.items():
        if isinstance(v, list) and v and isinstance(v[0], dict):
            for item in v:
                tables.append(f"[[{k}]]")
                for ik, iv in item.items():
                    tables.append(f"{ik} = {scalar(iv)}")
                tables.append("")
        elif isinstance(v, list):
            inner = ", ".join(scalar(x) for x in v)
            lines.append(f"{k} = [{inner}]")
        elif isinstance(v, dict):
            tables.append(f"[{k}]")
            for ik, iv in v.items():
                tables.append(f"{ik} = {scalar(iv)}")
            tables.append("")
        else:
            lines.append(f"{k} = {scalar(v)}")
    return "\n".join(lines + [""] + tables)


def parse_duration(s) -> float:
    """'30s' / '1m' / '1h30m' / numeric seconds -> seconds (float).

    Mirrors the Go duration strings used in the reference's group files
    (/root/reference/deploy/latest/group.toml:2 'Period = "1m0s"').
    """
    if isinstance(s, (int, float)):
        return float(s)
    s = s.strip()
    units = {"h": 3600.0, "m": 60.0, "s": 1.0, "ms": 0.001}
    total = 0.0
    num = ""
    i = 0
    while i < len(s):
        c = s[i]
        if c.isdigit() or c == ".":
            num += c
            i += 1
        else:
            u = c
            if i + 1 < len(s) and not s[i + 1].isdigit() and s[i + 1] != ".":
                u += s[i + 1]
                i += 1
            if u not in units or not num:
                raise ValueError(f"bad duration: {s!r}")
            total += float(num) * units[u]
            num = ""
            i += 1
    if num:  # bare number = seconds
        total += float(num)
    return total


def format_duration(seconds: float) -> str:
    """Seconds -> compact Go-style duration string."""
    if seconds != int(seconds):
        return f"{seconds}s"
    seconds = int(seconds)
    h, rem = divmod(seconds, 3600)
    m, s = divmod(rem, 60)
    out = ""
    if h:
        out += f"{h}h"
    if m:
        out += f"{m}m"
    if s or not out:
        out += f"{s}s"
    return out
