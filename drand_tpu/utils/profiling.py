"""JAX profiler integration for the device crypto path.

SURVEY §5: the reference has no tracing/profiling at all; here any
daemon or benchmark run can capture a TensorBoard-compatible device
trace of the pairing/MSM kernels.

Enable with the environment variable
``DRAND_TPU_PROFILE_DIR=/path/to/tracedir`` (checked once at first use)
or explicitly via :func:`profile_span`:

    with profile_span("chain-verify"):
        scheme.verify_chain_batch(...)

Spans nest; when no trace dir is configured they are zero-cost no-ops.
"""

from __future__ import annotations

import contextlib
import os
import threading
from typing import Iterator, Optional

_lock = threading.Lock()
_trace_dir: Optional[str] = None
_active = 0


def trace_dir() -> Optional[str]:
    return os.environ.get("DRAND_TPU_PROFILE_DIR") or None


@contextlib.contextmanager
def profile_span(name: str) -> Iterator[None]:
    """Wrap a block in a named JAX profiler trace (no-op when disabled)."""
    global _active
    tdir = trace_dir()
    if tdir is None:
        yield
        return
    import jax

    with _lock:
        start = _active == 0
        _active += 1
    try:
        if start:
            jax.profiler.start_trace(tdir)
        with jax.profiler.TraceAnnotation(name):
            yield
    finally:
        with _lock:
            _active -= 1
            stop = _active == 0
        if stop:
            jax.profiler.stop_trace()


def start_device_trace(tdir: str) -> bool:
    """Begin an on-demand device trace into `tdir` (obs/profile.py's
    `POST /debug/profile`).  Shares the `_active` refcount with
    `profile_span`, so an env-var span already holding the profiler
    open makes this a joiner rather than a conflicting second trace.
    Returns False when jax (or its profiler) is unavailable."""
    global _active
    try:
        import jax
    except Exception:
        return False
    with _lock:
        start = _active == 0
        _active += 1
    if start:
        try:
            jax.profiler.start_trace(tdir)
        except Exception:
            with _lock:
                _active -= 1
            return False
    return True


def stop_device_trace() -> None:
    """End an on-demand trace begun by `start_device_trace` (the actual
    `stop_trace` fires only when the last holder releases)."""
    global _active
    import jax

    with _lock:
        _active -= 1
        stop = _active == 0
    if stop:
        jax.profiler.stop_trace()
