"""`tomllib` with a Python 3.10 fallback.

The repo targets stdlib-only TOML reading (`import tomllib`, 3.11+).  On
3.10 hosts that import fails, so this module re-exports the stdlib
parser when present and otherwise provides a minimal reader for exactly
the dialect `drand_tpu.utils.toml_dumps` emits (and the hand-written
group files in tests/demos): scalar assignments, lists of scalars,
`[table]` sections and `[[table]]` array-of-table sections.  It is NOT
a general TOML parser — nested tables, inline tables, multi-line
strings and dates are out of scope and raise.

Use it everywhere the repo reads TOML:

    from drand_tpu.utils import tomlcompat as tomllib
"""

from __future__ import annotations

from typing import Any, Dict, List

try:  # Python 3.11+
    from tomllib import TOMLDecodeError, load, loads  # noqa: F401

except ModuleNotFoundError:  # pragma: no cover - exercised on 3.10 hosts

    class TOMLDecodeError(ValueError):
        """Raised on input outside the supported TOML subset."""

    def load(fp) -> Dict[str, Any]:
        """Parse a binary file object (same contract as tomllib.load)."""
        data = fp.read()
        if isinstance(data, bytes):
            data = data.decode("utf-8")
        return loads(data)

    def loads(text: str) -> Dict[str, Any]:
        root: Dict[str, Any] = {}
        target = root  # dict currently receiving assignments

        for lineno, raw in enumerate(text.splitlines(), 1):
            line = _strip_comment(raw).strip()
            if not line:
                continue
            if line.startswith("[[") and line.endswith("]]"):
                name = line[2:-2].strip()
                _check_key(name, lineno)
                target = {}
                root.setdefault(name, []).append(target)
            elif line.startswith("[") and line.endswith("]"):
                name = line[1:-1].strip()
                _check_key(name, lineno)
                target = root.setdefault(name, {})
            elif "=" in line:
                key, _, value = line.partition("=")
                key = key.strip()
                _check_key(key, lineno)
                target[key] = _parse_value(value.strip(), lineno)
            else:
                raise TOMLDecodeError(
                    f"line {lineno}: cannot parse {raw!r}"
                )
        return root

    def _strip_comment(line: str) -> str:
        out = []
        in_str = False
        i = 0
        while i < len(line):
            c = line[i]
            if c == '"' and (i == 0 or line[i - 1] != "\\"):
                in_str = not in_str
            elif c == "#" and not in_str:
                break
            out.append(c)
            i += 1
        return "".join(out)

    def _check_key(key: str, lineno: int) -> None:
        if not key or "." in key or '"' in key or "'" in key:
            raise TOMLDecodeError(f"line {lineno}: bad key {key!r}")

    def _parse_value(value: str, lineno: int) -> Any:
        if value.startswith("[") and value.endswith("]"):
            inner = value[1:-1].strip()
            return [
                _parse_value(part, lineno)
                for part in _split_list(inner, lineno)
            ]
        if value.startswith('"') and value.endswith('"') and len(value) >= 2:
            return _unescape(value[1:-1], lineno)
        if value == "true":
            return True
        if value == "false":
            return False
        try:
            return int(value)
        except ValueError:
            pass
        try:
            return float(value)
        except ValueError:
            pass
        raise TOMLDecodeError(f"line {lineno}: bad value {value!r}")

    def _split_list(inner: str, lineno: int) -> List[str]:
        parts: List[str] = []
        buf = []
        in_str = False
        for i, c in enumerate(inner):
            if c == '"' and (i == 0 or inner[i - 1] != "\\"):
                in_str = not in_str
                buf.append(c)
            elif c == "," and not in_str:
                part = "".join(buf).strip()
                if part:
                    parts.append(part)
                buf = []
            else:
                buf.append(c)
        if in_str:
            raise TOMLDecodeError(f"line {lineno}: unterminated string")
        tail = "".join(buf).strip()
        if tail:
            parts.append(tail)
        return parts

    def _unescape(s: str, lineno: int) -> str:
        out = []
        i = 0
        while i < len(s):
            c = s[i]
            if c == "\\":
                if i + 1 >= len(s):
                    raise TOMLDecodeError(
                        f"line {lineno}: dangling escape"
                    )
                nxt = s[i + 1]
                mapped = {"\\": "\\", '"': '"', "n": "\n", "t": "\t",
                          "r": "\r"}.get(nxt)
                if mapped is None:
                    raise TOMLDecodeError(
                        f"line {lineno}: unsupported escape \\{nxt}"
                    )
                out.append(mapped)
                i += 2
            else:
                out.append(c)
                i += 1
        return "".join(out)
