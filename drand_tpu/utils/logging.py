"""Structured logfmt logging with bound fields.

The reference threads a leveled go-kit logfmt logger through every
handler, binding contextual fields once and emitting machine-parseable
key=value lines (/root/reference/log/log.go:12, bound e.g. at
beacon/beacon.go:91, dkg/dkg.go:159).  This is the same shape over the
stdlib: `get_logger("beacon").bind(node=3)` returns a logger whose every
line carries `node=3`, and per-call keywords add more fields:

    log = get_logger("beacon").bind(node=3)
    log.info("round stored", round=42)
    # ts=2026-07-30T12:00:00Z level=info logger=beacon node=3 round=42
    #   msg="round stored"

Plain stdlib handlers/levels still apply (the formatter is installed on
the package root, so operators can re-route or silence as usual).
"""

from __future__ import annotations

import logging
import time
from typing import Any, Dict

_ROOT = "drand_tpu"


def _quote(v: Any) -> str:
    s = str(v)
    if s == "" or any(c in s for c in ' ="'):
        return '"' + s.replace('\\', '\\\\').replace('"', '\\"') + '"'
    return s


class LogfmtFormatter(logging.Formatter):
    """ts=... level=... logger=... <bound+call fields> msg="..."."""

    converter = time.gmtime

    def format(self, record: logging.LogRecord) -> str:
        ts = time.strftime(
            "%Y-%m-%dT%H:%M:%SZ", self.converter(record.created)
        )
        parts = [
            f"ts={ts}",
            f"level={record.levelname.lower()}",
            f"logger={record.name.removeprefix(_ROOT + '.')}",
        ]
        fields: Dict[str, Any] = getattr(record, "logfmt_fields", None) or {}
        parts.extend(f"{k}={_quote(v)}" for k, v in fields.items())
        parts.append(f"msg={_quote(record.getMessage())}")
        if record.exc_info:
            exc = self.formatException(record.exc_info)
            parts.append(f"exc={_quote(exc.splitlines()[-1])}")
        return " ".join(parts)


class BoundLogger:
    """Immutable field-carrying wrapper; .bind() layers more fields."""

    __slots__ = ("_logger", "_fields")

    def __init__(self, logger: logging.Logger,
                 fields: Dict[str, Any] | None = None):
        self._logger = logger
        self._fields = dict(fields or {})

    def bind(self, **fields: Any) -> "BoundLogger":
        merged = dict(self._fields)
        merged.update(fields)
        return BoundLogger(self._logger, merged)

    def _log(self, level: int, msg: str, exc_info=None,
             **fields: Any) -> None:
        if not self._logger.isEnabledFor(level):
            return
        merged = dict(self._fields)
        merged.update(fields)
        self._logger.log(
            level, msg, exc_info=exc_info,
            extra={"logfmt_fields": merged},
        )

    def debug(self, msg: str, **f: Any) -> None:
        self._log(logging.DEBUG, msg, **f)

    def info(self, msg: str, **f: Any) -> None:
        self._log(logging.INFO, msg, **f)

    def warning(self, msg: str, **f: Any) -> None:
        self._log(logging.WARNING, msg, **f)

    def error(self, msg: str, **f: Any) -> None:
        self._log(logging.ERROR, msg, **f)

    def exception(self, msg: str, **f: Any) -> None:
        self._log(logging.ERROR, msg, exc_info=True, **f)


_configured = False


def setup(level: int = logging.INFO, force: bool = False) -> None:
    """Install the logfmt formatter on the package root logger (idempotent;
    daemons call this at boot, tests/libraries may skip it entirely)."""
    global _configured
    if _configured and not force:
        return
    root = logging.getLogger(_ROOT)
    handler = logging.StreamHandler()
    handler.setFormatter(LogfmtFormatter())
    root.addHandler(handler)
    root.setLevel(level)
    root.propagate = False
    _configured = True


def get_logger(name: str, **fields: Any) -> BoundLogger:
    """Bound logfmt logger under the drand_tpu namespace."""
    return BoundLogger(logging.getLogger(f"{_ROOT}.{name}"), fields)
