"""Protocol invariants checked over simulated nodes' state.

The checks run at every round boundary and once more at the end of a
scenario.  They look only at durable/observable state — the stores the
nodes actually wrote, the contribution ledgers they actually keep, the
doctor verdict over a status document a real `drand status` would show —
never at simulator-internal bookkeeping, so a violation here is a
protocol bug, not a harness artifact.

Invariant catalogue (the `kind` on each Violation):

* ``fork`` — two honest nodes disagree about history: either the same
  round has two different beacons, or one node's chain *bridges over* a
  round another honest node finalized (a gap between consecutive stored
  beacons asserts "those rounds never happened"; an honest peer holding
  one of them proves divergent chains).  Fork resolution makes a
  divergence at ONE checkpoint legal — it may be mid-reorg — so the
  incremental checker only records a fork that persists across two
  consecutive checkpoints (see `InvariantState.checkpoint`).
* ``converged_single_chain`` — post-run only: after the scenario
  settles, every honest up node must hold the SAME chain (byte-equal
  beacons on all common rounds, one common head).  The per-checkpoint
  grace above does not apply here: a fork that survives to the end of
  the run is a resolution failure, not a transient.
* ``chain_linkage`` — a single store's chain doesn't link: some beacon's
  (prev_round, prev_sig) doesn't match the beacon stored before it.
* ``chain_verify`` — a stored beacon's group signature fails pairing
  verification against the distributed public key.
* ``honest_blamed`` — an honest signer accrued invalid-partial charges
  in some honest node's contribution ledger (the blame pass framed the
  wrong peer).
* ``byzantine_unblamed`` — checked only where a scenario demands it:
  a lying signer whose forgeries reached quorum was never charged.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, Iterable, List

from drand_tpu.beacon.chain import beacon_message


@dataclass
class Violation:
    kind: str
    node: str
    round: int
    detail: str

    def to_dict(self) -> dict:
        return {"kind": self.kind, "node": self.node,
                "round": self.round, "detail": self.detail}


def _chain(store) -> List:
    """The node's full stored chain, genesis first."""
    return store.range_from(0)


def check_linkage(addr: str, store) -> List[Violation]:
    out: List[Violation] = []
    chain = _chain(store)
    for prev, b in zip(chain, chain[1:]):
        if b.prev_round != prev.round or b.prev_sig != prev.signature:
            out.append(Violation(
                "chain_linkage", addr, b.round,
                f"beacon {b.round} links prev_round={b.prev_round}, "
                f"store predecessor is round {prev.round}",
            ))
    return out


def check_forks(stores: Dict[str, object]) -> List[Violation]:
    """Cross-node history agreement among HONEST nodes only."""
    out: List[Violation] = []
    chains = {addr: _chain(st) for addr, st in sorted(stores.items())}
    by_round = {addr: {b.round: b for b in ch}
                for addr, ch in chains.items()}
    # (a) same round, different beacon
    addrs = sorted(chains)
    for i, a in enumerate(addrs):
        for b_addr in addrs[i + 1:]:
            common = sorted(set(by_round[a]) & set(by_round[b_addr]))
            for r in common:
                x, y = by_round[a][r], by_round[b_addr][r]
                if (x.signature, x.prev_round, x.prev_sig) != \
                        (y.signature, y.prev_round, y.prev_sig):
                    out.append(Violation(
                        "fork", a, r,
                        f"round {r} differs between {a} and {b_addr}",
                    ))
    # (b) a finalized gap on one node covering a round another node has:
    # consecutive stored beacons (p, b) with b.prev_round == p.round
    # assert every round in (p.round, b.round) was skipped — an honest
    # peer holding one of those rounds proves a forked chain
    for a in addrs:
        ch = chains[a]
        for p, b in zip(ch, ch[1:]):
            if b.round <= p.round + 1:
                continue
            for other in addrs:
                if other == a:
                    continue
                for r in range(p.round + 1, b.round):
                    if r in by_round[other]:
                        out.append(Violation(
                            "fork", a, r,
                            f"{a}'s chain bridges over round {r} "
                            f"({p.round}->{b.round}) but {other} "
                            f"finalized it",
                        ))
    return out


def check_chain_verifies(addr: str, store, scheme, dist_key,
                         from_round: int = 1) -> List[Violation]:
    """Every stored beacon's signature verifies against the distributed
    key over the chained message (one batched pairing check per store
    suffix).  The distributed key is derived straight from the secret
    polynomial by the harness — ground truth the nodes never see."""
    chain = store.range_from(max(1, from_round))
    if not chain:
        return []
    msgs = [beacon_message(b.prev_sig, b.prev_round, b.round)
            for b in chain]
    sigs = [b.signature for b in chain]
    ok = scheme.verify_chain_batch(dist_key, msgs, sigs)
    return [
        Violation("chain_verify", addr, b.round,
                  "group signature fails pairing check")
        for b, good in zip(chain, ok) if not good
    ]


def check_converged_single_chain(
        stores: Dict[str, object]) -> List[Violation]:
    """Post-run convergence: the honest (up) fleet holds ONE chain.

    Byte-level agreement on every common round (via `check_forks`) plus
    a single common head.  Run once after the last checkpoint settles;
    unlike the incremental fork check there is no mid-reorg grace —
    a divergence that outlives the run means resolution failed."""
    out = [
        Violation("converged_single_chain", v.node, v.round, v.detail)
        for v in check_forks(stores)
    ]
    heads = {a: (st.last().round if st.last() else 0)
             for a, st in sorted(stores.items())}
    if heads and len(set(heads.values())) > 1:
        hi = max(heads.values())
        for a in sorted(heads):
            if heads[a] != hi:
                out.append(Violation(
                    "converged_single_chain", a, heads[a],
                    f"{a} ended at head {heads[a]} while the fleet "
                    f"head is {hi}",
                ))
    return out


def check_honest_unblamed(nodes: Iterable,
                          honest: Iterable[str]) -> List[Violation]:
    """No honest node's ledger charges an HONEST signer with invalid
    partials.  Byzantine/faulty peers are allowed (expected, even) to
    be charged."""
    honest = set(honest)
    out: List[Violation] = []
    for node in nodes:
        if node.handler is None or node.address not in honest:
            continue
        snap = node.handler.peer_ledger.snapshot(node.clock.now())
        for peer_addr in sorted(snap):
            st = snap[peer_addr]
            if peer_addr in honest and st.get("invalid", 0):
                out.append(Violation(
                    "honest_blamed", node.address, -1,
                    f"{node.address} charged honest {peer_addr} with "
                    f"{st['invalid']} invalid partials",
                ))
    return out


def check_byzantine_blamed(nodes: Iterable, honest: Iterable[str],
                           liars: Iterable[str]) -> List[Violation]:
    """Every liar whose forged partials reach honest quorums must be
    charged by at least one honest ledger."""
    honest = set(honest)
    out: List[Violation] = []
    for liar in sorted(set(liars)):
        charged = False
        for node in nodes:
            if node.handler is None or node.address not in honest:
                continue
            snap = node.handler.peer_ledger.snapshot(node.clock.now())
            if snap.get(liar, {}).get("invalid", 0):
                charged = True
                break
        if not charged:
            out.append(Violation(
                "byzantine_unblamed", liar, -1,
                f"liar {liar} was never charged an invalid partial "
                f"by any honest node",
            ))
    return out


@dataclass
class InvariantState:
    """Incremental across-checkpoint state: head samples for stall
    detection plus the deduplicated violation log."""
    scheme: object = None
    dist_key: object = None
    seen: set = field(default_factory=set)
    violations: List[Violation] = field(default_factory=list)
    head_samples: List[tuple] = field(default_factory=list)
    verified_to: Dict[str, int] = field(default_factory=dict)
    #: fork keys observed at the PREVIOUS checkpoint — a fork only
    #: becomes a violation when it is still there one checkpoint later
    #: (fork resolution legitimately shows a one-checkpoint divergence
    #: while the losing branch reorgs onto the winner)
    fork_pending: set = field(default_factory=set)

    def _add(self, vs: List[Violation]) -> List[Violation]:
        fresh = []
        for v in vs:
            key = (v.kind, v.node, v.round, v.detail)
            if key not in self.seen:
                self.seen.add(key)
                self.violations.append(v)
                fresh.append(v)
        return fresh

    def checkpoint(self, world, expected_round: int) -> List[Violation]:
        """Run every per-checkpoint invariant; returns NEW violations."""
        honest_nodes = [n for n in world.nodes
                        if n.address in world.honest]
        stores = {n.address: n.store for n in honest_nodes}
        found: List[Violation] = []
        for n in honest_nodes:
            found.extend(check_linkage(n.address, n.store))
            # verify only the suffix this node grew since last check —
            # the pure-python pairing oracle is slow
            frm = self.verified_to.get(n.address, 0) + 1
            found.extend(check_chain_verifies(
                n.address, n.store, self.scheme, self.dist_key,
                from_round=frm))
            head = n.store.last()
            self.verified_to[n.address] = head.round if head else 0
        fork_now = check_forks(stores)
        now_keys = {(v.node, v.round, v.detail) for v in fork_now}
        found.extend(v for v in fork_now
                     if (v.node, v.round, v.detail) in self.fork_pending)
        self.fork_pending = now_keys
        found.extend(check_honest_unblamed(
            [n for n in honest_nodes if n.up and n.handler is not None],
            world.honest))
        heads = [n.store.last().round if n.store.last() else 0
                 for n in honest_nodes]
        self.head_samples.append((expected_round, max(heads, default=0)))
        return self._add(found)

    def stalled(self, min_gap: int = 2) -> bool:
        """The honest chain head stopped advancing while the scheduled
        round kept marching: no head progress across the last three
        checkpoints and the newest head at least `min_gap` rounds
        behind schedule."""
        s = self.head_samples
        if len(s) < 3:
            return False
        (_, h0), (_, h1), (e2, h2) = s[-3], s[-2], s[-1]
        return h0 == h1 == h2 and (e2 - h2) >= min_gap
