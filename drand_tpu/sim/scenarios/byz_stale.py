"""Byzantine stale-head broadcaster.

Node 6 pins the first chain link it ever gossips and keeps re-signing
every later round against it.  Honest receivers drop the partials on
the chain-link mismatch check — dead weight the 9-honest-of-10 margin
absorbs.  The staler is never CHARGED (a mismatched link is a desync
symptom, not proof of forgery) but its missed rounds pile up in every
honest contribution ledger.
"""

from drand_tpu.sim.scenario import Scenario


def build() -> Scenario:
    return Scenario(
        name="byz_stale",
        summary="node 6 re-broadcasts partials signed against a pinned "
                "stale chain link; link-mismatch drops absorb it",
        n=10, threshold=7, rounds=6,
        byzantine={6: "stale_head"},
    )
