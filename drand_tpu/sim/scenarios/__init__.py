"""Scripted chaos scenarios.

Each module exposes `build() -> Scenario`; this package is the registry
the CLI (`drand-tpu sim list / sim run`) and the test suite enumerate.
"""

from __future__ import annotations

from typing import Dict

from drand_tpu.sim.scenario import Scenario

from drand_tpu.sim.scenarios import (  # noqa: E402
    asym_link,
    byz_equivocate,
    byz_liar,
    byz_stale,
    clock_skew,
    crash_restart,
    device_fault,
    fork_stall,
    gateway_kill,
    lossy_link,
    partition,
    reorg_chaos,
)

_MODULES = (
    partition, asym_link, clock_skew, crash_restart, byz_liar,
    byz_stale, byz_equivocate, device_fault, lossy_link, fork_stall,
    gateway_kill, reorg_chaos,
)

SCENARIOS: Dict[str, object] = {m.build().name: m.build for m in _MODULES}


def get_scenario(name: str) -> Scenario:
    try:
        factory = SCENARIOS[name]
    except KeyError:
        raise ValueError(
            f"unknown scenario {name!r}; available: "
            f"{', '.join(sorted(SCENARIOS))}"
        ) from None
    return factory()


def list_scenarios():
    """(name, summary, expect_stall) rows, sorted by name."""
    rows = []
    for name in sorted(SCENARIOS):
        scn = SCENARIOS[name]()
        rows.append((scn.name, scn.summary, scn.expect_stall))
    return rows
