"""Asymmetric link faults: one mute node and one slow direction.

Node 9 is muted (its outbound links block; inbound stays open) for two
rounds — it keeps finalizing from everyone else's partials while the
network tolerates its silence.  On top, the 0->1 direction runs at 3s
latency the whole time, so node 1 always hears node 0 a beat late.
Pure liveness noise: every invariant must hold and everyone converges.
"""

from drand_tpu.sim.scenario import Scenario, SimEvent


def _mute(node, others, on):
    action = "block" if on else "unblock"
    return [SimEvent(at=35.0 if on else 95.0, action=action,
                     args={"src": node, "dst": o}) for o in others]


def build() -> Scenario:
    others = [i for i in range(10) if i != 9]
    return Scenario(
        name="asym_link",
        summary="node 9 muted (outbound blocked, inbound open) for two "
                "rounds; 0->1 direction 3s slow throughout",
        n=10, threshold=7, rounds=7,
        events=[
            SimEvent(at=-5.0, action="set_links",
                     args={"src": 0, "dst": 1, "latency": 3.0}),
            *_mute(9, others, on=True),
            *_mute(9, others, on=False),
        ],
    )
