"""Lossy, duplicating, reordering mesh.

Every link drops 5% of packets silently, duplicates 15%, and delays a
further 30% by up to half a second — UDP weather.  Signer dedup must
absorb the duplicates, the look-ahead buffer the reordering, and the
t=7-of-10 margin the drops.  All invariants hold; everyone converges.
"""

from drand_tpu.sim.scenario import Scenario


def build() -> Scenario:
    return Scenario(
        name="lossy_link",
        summary="5% drop / 15% duplicate / 30% reorder on every link; "
                "dedup and threshold margin absorb the weather",
        n=10, threshold=7, rounds=7,
        default_link={"latency": 0.01, "jitter": 0.05,
                      "drop": 0.05, "dup": 0.15, "reorder": 0.3},
    )
