"""Byzantine equivocator.

Node 2 tells the lexicographically-first half of its peers the truth
and sends the rest structurally-valid forgeries — the classic
split-view attack.  The lied-to half must unmask the forgeries at
finalize and charge node 2; the truthfully-served half keeps counting
its partials.  Both halves still finalize identical rounds: the chain,
not the gossip, is the source of truth.
"""

from drand_tpu.sim.scenario import Scenario, SimEvent


def build() -> Scenario:
    return Scenario(
        name="byz_equivocate",
        summary="node 2 sends honest partials to half the peers and "
                "forged ones to the rest; lied-to half must blame it",
        n=10, threshold=7, rounds=6,
        byzantine={2: "equivocate"},
        events=[
            SimEvent(at=-5.0, action="set_links",
                     args={"src": 2, "latency": 0.001}),
        ],
        expect_blamed=True,
    )
