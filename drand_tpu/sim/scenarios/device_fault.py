"""Injected device fault at finalize.

Node 5's scheme is armed to fail its next finalize with a red
recovered-signature check even though every partial in the quorum is
valid — the signature of a flaky accelerator, not a Byzantine peer.
The handler must abandon the round gracefully (the PR-5 regression
contract), charge NOBODY, and let the node rejoin via catch-up while
the other nine finalize the round on schedule.
"""

from drand_tpu.sim.scenario import Scenario, SimEvent


def build() -> Scenario:
    return Scenario(
        name="device_fault",
        summary="node 5's accelerator fails one finalize (red check, "
                "all partials valid); round abandoned gracefully, "
                "nobody blamed",
        n=10, threshold=7, rounds=7,
        events=[
            SimEvent(at=58.0, action="device_fault",
                     args={"node": 5, "count": 1}),
        ],
    )
