"""Crash mid-round, restart from the surviving store.

Links run at 2s latency so partial collection for a round takes real
(simulated) seconds — node 4 is killed one second into round 3's
collection, with its own partial signed and in flight.  The store (its
disk) survives; 34 seconds later the node restarts, replays catch-up
from the store head, and rejoins as a full signer.  The network never
drops below threshold (9 >= 7) and everyone converges.
"""

from drand_tpu.sim.scenario import Scenario, SimEvent


def build() -> Scenario:
    return Scenario(
        name="crash_restart",
        summary="node 4 killed mid-round-3 collection, restarted 34s "
                "later from its surviving store; rejoins via catch-up",
        n=10, threshold=7, rounds=7,
        default_link={"latency": 2.0},
        events=[
            SimEvent(at=61.0, action="crash", args={"node": 4}),
            SimEvent(at=95.0, action="restart", args={"node": 4}),
        ],
    )
