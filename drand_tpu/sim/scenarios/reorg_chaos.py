"""Reorg under load: a manufactured fork plus repeated partition flips.

`fork_stall` gates the resolution mechanism on its minimal fork.  This
scenario is the endurance version: the same fork cycle runs early (B
and C deaf for one round so only A finalizes it, then the fault flips
to a partition isolating A while B+C finalize a bridging quorum —
forcing A into a reorg), and then the fleet keeps finalizing through
THREE back-to-back partition flips, each isolating a different node
behind a healthy t=2 majority.  Every flip makes the minority node
catch-up-sync while the majority keeps finalizing — exactly the
stale-sync race window (`SyncSuperseded`) and the mid-round head-move
window (`_refresh_round_task`) that used to leave a healed node
trailing the fleet by one round forever.

Judged like fork_stall: no stall, at least one adopted reorg somewhere
in the run, every honest up node converged on ONE verified chain at the
end, and nobody blamed.  Seventeen rounds — the last two quiet — so
convergence is demanded *after* sustained churn, not just after the
scripted fork.
"""

from drand_tpu.sim.scenario import Scenario, SimEvent


def build() -> Scenario:
    return Scenario(
        name="reorg_chaos",
        summary="fork + reorg early, then three partition flips under "
                "continued load; the fleet must keep converging on one "
                "verified chain (endurance test for fork resolution)",
        n=3, threshold=2, rounds=17,
        fixed_topology=True,
        events=[
            # fork cycle (fork_stall's timing): B and C deaf for round
            # 7 (only A finalizes it), then a partition isolates A
            # while B+C finalize a bridging 8-on-6 -> A reorgs
            SimEvent(at=155.0, action="deaf", args={"node": 1}),
            SimEvent(at=155.0, action="deaf", args={"node": 2}),
            SimEvent(at=185.0, action="undeaf", args={"node": 1}),
            SimEvent(at=185.0, action="undeaf", args={"node": 2}),
            SimEvent(at=185.0, action="partition",
                     args={"groups": [[1, 2], [0]]}),
            SimEvent(at=215.0, action="heal", args={}),
            # partition churn: isolate each node in turn behind a
            # finalizing t=2 majority, heal, repeat — every heal races
            # the minority's catch-up sync against live finalizes
            SimEvent(at=275.0, action="partition",
                     args={"groups": [[0, 1], [2]]}),
            SimEvent(at=305.0, action="heal", args={}),
            SimEvent(at=335.0, action="partition",
                     args={"groups": [[0, 2], [1]]}),
            SimEvent(at=365.0, action="heal", args={}),
            SimEvent(at=395.0, action="partition",
                     args={"groups": [[1, 2], [0]]}),
            SimEvent(at=425.0, action="heal", args={}),
        ],
        expect_stall=False,
        require_violations=frozenset(),
        allow_violations=frozenset(),
        require_reorg=True,
        require_converged=True,
        notes="endurance companion to fork_stall",
    )
