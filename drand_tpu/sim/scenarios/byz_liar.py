"""Byzantine invalid-partial liar.

Node 3 signs every wire partial over a corrupted message — structurally
valid, cryptographically garbage.  Its outbound links are near-instant
so the forgery is always inside the first-t optimistic quorum: every
honest finalize must go red, fall back to the batched blame pass,
charge the LIAR's address (never an honest signer), evict, refill, and
still produce the round on time from the 9 honest signers.
"""

from drand_tpu.sim.scenario import Scenario, SimEvent


def build() -> Scenario:
    return Scenario(
        name="byz_liar",
        summary="node 3 broadcasts structurally-valid forged partials "
                "from a fast link; blame pass must charge it every round",
        n=10, threshold=7, rounds=6,
        byzantine={3: "liar"},
        events=[
            SimEvent(at=-5.0, action="set_links",
                     args={"src": 3, "latency": 0.001}),
        ],
        expect_blamed=True,
    )
