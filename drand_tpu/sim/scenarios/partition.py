"""Symmetric partition: a 3-node minority is cut off for two rounds.

The 7-node majority side still meets the threshold (t=7) and keeps the
chain moving; the minority stalls, then pulls the missed segment via
catch-up sync after the heal.  No invariant may fire: partitions must
cost liveness on the small side only, never safety.
"""

from drand_tpu.sim.scenario import Scenario, SimEvent


def build() -> Scenario:
    return Scenario(
        name="partition",
        summary="3-of-10 minority partitioned for two rounds, then "
                "healed; majority keeps finalizing, minority catches up",
        n=10, threshold=7, rounds=7,
        events=[
            SimEvent(at=35.0, action="partition",
                     args={"groups": [[0, 1, 2, 3, 4, 5, 6], [7, 8, 9]]}),
            SimEvent(at=95.0, action="heal", args={}),
        ],
    )
