"""Per-node clock skew within protocol tolerance.

Node 1 runs 3s fast, node 2 runs 3s slow (both well under the one-round
packet window).  Fast tickers sign early — receivers must buffer the
future-round partials in the look-ahead cache; slow tickers sign late —
their partials still land inside the round.  Everything converges and
no invariant fires.  Mid-run, node 3 drifts +4s via a scenario event.
"""

from drand_tpu.sim.scenario import Scenario, SimEvent


def build() -> Scenario:
    return Scenario(
        name="clock_skew",
        summary="nodes skewed +3s/-3s from genesis, one more drifts "
                "+4s mid-run; look-ahead absorbs early signers",
        n=10, threshold=7, rounds=7,
        skews={1: 3.0, 2: -3.0},
        events=[
            SimEvent(at=65.0, action="skew",
                     args={"node": 3, "seconds": 4.0}),
        ],
    )
