"""Chaos: kill one verification-gateway replica mid-load.

The replica ring's failure story, scripted (per ROADMAP: every new
policy lands with a scenario): three gateway replicas share a
consistent-hash ring over round numbers; mid-load the owner of the
hottest rounds dies.  Survivors' forwards to it fail, strike it out
(`fail_evict` consecutive transport failures), and evict it from their
ring views — after which every round it owned is re-owned CONSISTENTLY
by the survivors and traffic keeps flowing with bounded shed.

This scenario drives `serve/` directly rather than `sim.harness`'s
beacon network (the gateway is a read-path subsystem with no rounds of
its own), so it carries its own `run()`; `sim.scenario.run_scenario`
dispatches on that and the report shape is the standard `SimReport`.
Verification is instant here — the chaos under test is topology, not
kernel timing, and sleeping schemes would only add wall-clock noise.
"""

from __future__ import annotations

import json
import random
from dataclasses import dataclass, replace
from typing import List, Optional

from drand_tpu.sim.scenario import SimReport


class _InstantScheme:
    """Verdict = signature[0] == 1, no simulated dispatch cost."""

    def verify_chain_batch(self, pub, msgs, sigs) -> List[bool]:
        return [len(s) > 0 and s[0] == 1 for s in sigs]


@dataclass
class GatewayScenario:
    name: str = "gateway_kill"
    summary: str = ("kill a gateway replica mid-load; the ring re-owns "
                    "its rounds, shed stays bounded")
    expect_stall: bool = False
    fixed_topology: bool = True
    replicas: int = 3
    #: round-number space the workload draws from
    rounds: int = 64
    #: requests per phase (before / after the kill)
    requests: int = 900
    clients: int = 32
    #: acceptable shed fraction in the post-kill phase
    max_shed_frac: float = 0.05

    def overridden(self, nodes: Optional[int] = None,
                   rounds: Optional[int] = None) -> "GatewayScenario":
        if nodes is not None and nodes != self.replicas:
            raise ValueError(
                f"scenario {self.name} has a fixed topology of "
                f"{self.replicas} gateway replicas")
        scn = self
        if rounds is not None and rounds != scn.rounds:
            scn = replace(scn, rounds=rounds)
        return scn

    async def run(self, seed: int) -> SimReport:
        import asyncio

        from drand_tpu.serve import gateway as gw_mod
        from drand_tpu.serve.gateway import VerifyGateway, VerifyRequest
        from drand_tpu.serve.ring import ReplicaRing, inprocess_forwarder

        ids = [f"gw-{i}" for i in range(self.replicas)]
        pool = {}
        forward = inprocess_forwarder(pool)
        rings = {}
        for rid in ids:
            rings[rid] = ReplicaRing(
                rid, [p for p in ids if p != rid], forward=forward)
            pool[rid] = VerifyGateway(
                object(), _InstantScheme(), max_batch=64,
                max_wait=0.001, max_queue=4096, ring=rings[rid])
        for gw in pool.values():
            await gw.start()

        def claim(r: int) -> VerifyRequest:
            return VerifyRequest(
                round=r, prev_round=r - 1, prev_sig=b"\x01" * 96,
                signature=bytes([1]) + r.to_bytes(8, "big"))

        events: List[dict] = []
        failures: List[str] = []
        served = {rid: 0 for rid in ids}
        shed = {"before": 0, "after": 0}

        async def drive(phase: str, targets: List[str], rng) -> None:
            jobs: "asyncio.Queue" = asyncio.Queue()
            for _ in range(self.requests):
                jobs.put_nowait(claim(rng.randrange(1, self.rounds + 1)))

            async def client(cid: int):
                while True:
                    try:
                        req = jobs.get_nowait()
                    except asyncio.QueueEmpty:
                        return
                    rid = targets[rng.randrange(len(targets))]
                    try:
                        res = await pool[rid].verify(
                            req, timeout=30.0, client=f"c{cid}")
                    except gw_mod.GatewayError:
                        shed[phase] += 1
                    else:
                        served[rid] += 1
                        if not res.valid:
                            failures.append(
                                f"{phase}: round {req.round} verdict "
                                f"flipped invalid on {rid}")

            await asyncio.gather(
                *(client(c) for c in range(self.clients)))

        rng = random.Random(seed)
        # phase 1: healthy ring, all replicas take traffic
        await drive("before", ids, rng)

        # the victim: whoever owns round 1 — a round every replica can
        # name identically (stable-assignment property of the ring)
        victim = rings[ids[0]].owner(1)
        owners_before = {
            r: rings[ids[0]].owner(r)
            for r in range(1, self.rounds + 1)}
        victim_rounds = sorted(
            r for r, o in owners_before.items() if o == victim)
        events.append({"event": "kill", "replica": victim,
                       "owned_rounds": len(victim_rounds)})
        await pool[victim].close()
        survivors = [rid for rid in ids if rid != victim]

        # phase 2: clients only reach survivors (a dead replica accepts
        # no connections); forwards to the victim fail, strike, evict
        await drive("after", survivors, rng)

        # -- expectations --------------------------------------------------
        for rid in survivors:
            if victim in rings[rid].ring:
                failures.append(
                    f"{rid} never evicted dead replica {victim}")
        for r in victim_rounds:
            owners = {rings[rid].owner(r) for rid in survivors}
            if victim in owners:
                failures.append(
                    f"round {r} still owned by dead {victim}")
            if len(owners) != 1:
                failures.append(
                    f"survivors disagree on round {r} owner: "
                    f"{sorted(owners)}")
        kept = [r for r in range(1, self.rounds + 1)
                if owners_before[r] != victim
                and rings[survivors[0]].owner(r) != owners_before[r]]
        if kept:
            failures.append(
                f"minimal-movement violated: surviving owners moved "
                f"for rounds {kept[:8]}")
        frac = shed["after"] / max(self.requests, 1)
        if frac > self.max_shed_frac:
            failures.append(
                f"post-kill shed {frac:.1%} exceeds bound "
                f"{self.max_shed_frac:.0%}")

        ring_stats = {rid: rings[rid].stats() for rid in survivors}
        events.append({
            "event": "post_kill",
            "victim": victim,
            "survivor_rings": {
                rid: s["replicas"] for rid, s in ring_stats.items()},
            "evicted": {
                rid: s["evicted"] for rid, s in ring_stats.items()},
            "shed": dict(shed),
            "requests_per_phase": self.requests,
        })

        for gw in pool.values():
            await gw.close()

        return SimReport(
            scenario=self.name, seed=seed, passed=not failures,
            failures=failures, violations=[], stalled=False,
            heads=dict(served), doctor={},
            event_log=json.dumps(events, indent=2, sort_keys=True),
        )


def build() -> GatewayScenario:
    return GatewayScenario()
