"""The known half-partition fork stall (ROADMAP direction 1).

This scenario REPRODUCES A REAL BUG on purpose.  It is the acceptance
gate for the future fork-resolution PR: today it passes by expecting
the fork; when fork resolution lands, flip `expect_stall` to False and
empty the violation sets — the scenario then demands convergence.

The mechanism, on a 3-node t=2 group (A=node 0, B=node 1, C=node 2):

1. B goes deaf (inbound blocked, outbound open) after round 3.  A and C
   keep finalizing rounds 4-5; B's head freezes at 3 while its ticker
   keeps broadcasting stale-linked partials nobody accepts.
2. Just before round 6 the fault flips: B heals, C goes deaf.  Round 6:
   A and C sign against head 5; C's partial reaches A -> A finalizes 6.
   B, seeing round-6 partials ahead of its head, catch-up syncs from A —
   but the sync snapshot was taken BEFORE A stored 6, so B lands on
   head 5.  C, deaf, is stuck at 5 too.
3. Round 7: A signs against 6; B and C both sign against 5 — B's round
   manager pins the stale link, C's matching stale partial arrives, and
   t=2 is met: **B finalizes a forked round 7 with prev_round=5**,
   even though round 6 exists.
4. Nobody shares a chain link anymore.  A rejects B's fork during sync
   ("chain link broken"), B and C can't help each other, and the group
   stalls permanently: the doctor flags `stalled_chain` on every honest
   node, yet no peer ledger charges anyone — every signer was honest.

The run is judged PASSED when the stall occurs, the doctor flags it,
the fork-class invariant fires, and no honest node is blamed.
"""

from drand_tpu.sim.scenario import Scenario, SimEvent


def build() -> Scenario:
    return Scenario(
        name="fork_stall",
        summary="half-partition flip makes a mid-catch-up node finalize "
                "a forked round; permanent stall (known bug, gates the "
                "fork-resolution PR)",
        n=3, threshold=2, rounds=9,
        fixed_topology=True,
        events=[
            SimEvent(at=65.0, action="deaf", args={"node": 1}),
            SimEvent(at=125.0, action="undeaf", args={"node": 1}),
            SimEvent(at=125.0, action="deaf", args={"node": 2}),
        ],
        expect_stall=True,
        require_violations=frozenset({"chain_linkage"}),
        allow_violations=frozenset({"chain_linkage", "fork"}),
        notes="flip expect_stall/violations when fork resolution lands",
    )
