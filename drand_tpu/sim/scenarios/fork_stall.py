"""The partition fork — now the fork-RESOLUTION acceptance gate.

This scenario used to REPRODUCE A REAL BUG (ROADMAP direction 1): a
fault timeline that manufactures two valid branches used to stall the
group permanently with every signer honest.  Fork resolution
(highest-round fully-verified chain wins, `BeaconHandler._resolve_fork`)
turned that permanent failure into a self-healing event, so the
expectations flipped: the run is judged PASSED when the SAME class of
fault ends with every node converged on one verified chain, at least
one adopted reorg in the log, and nobody blamed.

The fork mechanism, on a 3-node t=2 group (A=node 0, B=node 1,
C=node 2) — quorum-intersection says any two quorums share a node, so
the fork is built from shared nodes signing against different links,
which the fault windows make honest (event offsets are seconds after
genesis; round k opens at genesis + (k-1)*period):

1. Round 7 (opens +180): B and C are deaf (inbound blocked, outbound
   open).  All three sign 7-on-6; B's and C's partials still reach A,
   so **only A finalizes round 7** — B and C never hear the result and
   stay at head 6.
2. Round 8 (opens +210): B and C heal, but the fault flips to a
   partition isolating A.  B and C both sign 8 against their head 6,
   exchange partials, and meet t=2: **a fully-valid round 8 with
   prev_round=6**, bridging over the round 7 that A finalized.  A,
   alone with its 8-on-7 partial, cannot finalize — two verified
   branches now exist: A's ``..6,7`` vs B/C's ``..6,8``.
3. Resolution: the partition heals before round 9 (opens +240).  B/C's
   round-9 partials advertise a link (8) ahead of A's head — A
   resyncs, hits "chain link broken" on the 8-on-6 beacon, walks back
   to the divergence point (round 6), batch-verifies the competitor
   branch, and adopts it: A rolls back its orphaned 7 and takes
   ``8,9`` (highest verified head wins, a depth-1 reorg).  Round 7
   ends up orphaned on every chain; the fleet converges at head 9.

The per-checkpoint fork invariant tolerates the one-checkpoint
transient while A still holds its orphaned 7; nothing may persist.  The
attached watchdog (`--watch` runs) follows the reorg instead of paging
`watch_fork` forever — `tests/test_sim.py` and `tests/test_watch.py`
pin both behaviors.
"""

from drand_tpu.sim.scenario import Scenario, SimEvent


def build() -> Scenario:
    return Scenario(
        name="fork_stall",
        summary="deaf round + partition flip forks the chain between "
                "two honest quorums; the fleet must reorg onto the "
                "highest verified branch and converge (gates fork "
                "resolution)",
        n=3, threshold=2, rounds=9,
        fixed_topology=True,
        events=[
            # round 7 (opens +180): B and C deaf -> only A finalizes 7
            SimEvent(at=155.0, action="deaf", args={"node": 1}),
            SimEvent(at=155.0, action="deaf", args={"node": 2}),
            # round 8 (opens +210): B and C heal behind a partition
            # that isolates A -> B+C finalize a valid 8-on-6
            SimEvent(at=185.0, action="undeaf", args={"node": 1}),
            SimEvent(at=185.0, action="undeaf", args={"node": 2}),
            SimEvent(at=185.0, action="partition",
                     args={"groups": [[1, 2], [0]]}),
            # heal before round 9 (opens +240): A discovers the higher
            # verified branch and must reorg its 7 away
            SimEvent(at=215.0, action="heal", args={}),
        ],
        expect_stall=False,
        require_violations=frozenset(),
        allow_violations=frozenset(),
        require_reorg=True,
        require_converged=True,
        notes="was the known-bug repro; now demands self-healing",
    )
