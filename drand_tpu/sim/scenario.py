"""Scenario DSL and the deterministic scenario runner.

A `Scenario` is a declarative chaos script: network shape (n, threshold,
period), a fault timeline (`SimEvent`s at offsets from genesis), static
per-node attributes (clock skew, Byzantine strategy), and the
expectations the run is judged against (converge vs. stall, which
invariant violations are *supposed* to appear).  `run_scenario` executes
it on `sim.harness.SimWorld`, checking `sim.invariants` at every round
boundary, and returns a `SimReport` whose `event_log` is byte-identical
for the same (scenario, seed) — the flight-recorder JSON is the replay
artifact the acceptance gate diffs.

The runner's timeline is a sorted list of stop points: every scheduled
fault event plus one invariant checkpoint per round (at round-open +
`settle_margin`, when all honest deliveries for the round have landed).
Between stops the world advances in simulated time only — a fast-tier
scenario with 10 nodes and 7 rounds never sleeps a wall-clock second.
"""

from __future__ import annotations

import json
from dataclasses import asdict, dataclass, field, replace
from typing import Dict, FrozenSet, List, Optional

from drand_tpu.beacon.chain import current_round
from drand_tpu.sim.harness import SimWorld
from drand_tpu.sim.invariants import (
    InvariantState,
    check_byzantine_blamed,
    check_converged_single_chain,
)


@dataclass
class SimEvent:
    """One scripted fault: `at` is seconds after genesis."""
    at: float
    action: str
    args: dict = field(default_factory=dict)


@dataclass
class Scenario:
    name: str
    summary: str
    n: int = 10
    threshold: int = 7
    period: float = 30.0
    rounds: int = 6
    events: List[SimEvent] = field(default_factory=list)
    #: node index -> strategy name (sim.fabric.BYZANTINE_STRATEGIES)
    byzantine: Dict[int, str] = field(default_factory=dict)
    #: node index -> clock skew seconds (applied from the start)
    skews: Dict[int, float] = field(default_factory=dict)
    sync_batch: int = 64
    #: base properties for every link (latency/jitter/drop/dup/reorder)
    default_link: dict = field(default_factory=dict)
    #: invariant checkpoint offset after each round opens; must exceed
    #: worst-case delivery latency + |skew| so the round has settled
    settle_margin: float = 15.0
    #: the scenario is SUPPOSED to end stalled (doctor flags it)
    expect_stall: bool = False
    #: violation kinds that MUST appear (the scenario documents a bug)
    require_violations: FrozenSet[str] = frozenset()
    #: violation kinds tolerated in addition to the required ones
    allow_violations: FrozenSet[str] = frozenset()
    #: every lying Byzantine node must be charged invalid partials by
    #: some honest ledger before the run ends
    expect_blamed: bool = False
    #: at least one honest node must ADOPT a chain reorg during the run
    #: (a `chain_reorg` event in the log; the scenario manufactures a
    #: fork and demands it be resolved, not merely detected)
    require_reorg: bool = False
    #: post-run `converged_single_chain` invariant: every honest up node
    #: ends holding the same chain with one common head
    require_converged: bool = False
    #: scenario scripts exact node indexes/links; --nodes is refused
    fixed_topology: bool = False
    notes: str = ""

    def _max_scripted_index(self) -> int:
        """Highest node index named anywhere in the script: static
        byzantine/skew maps plus every event's node/src/dst/groups."""
        hi = max(max(self.byzantine, default=-1),
                 max(self.skews, default=-1))
        for ev in self.events:
            for key in ("node", "src", "dst"):
                v = ev.args.get(key)
                if isinstance(v, int):
                    hi = max(hi, v)
            for grp in ev.args.get("groups", []):
                hi = max(hi, max(grp, default=-1))
        return hi

    def overridden(self, nodes: Optional[int] = None,
                   rounds: Optional[int] = None) -> "Scenario":
        """CLI-level overrides; scenarios with hand-built topologies
        (fork_stall) set `fixed_topology` and refuse node overrides."""
        scn = self
        if nodes is not None and nodes != scn.n:
            if scn.fixed_topology:
                raise ValueError(
                    f"scenario {scn.name} has a fixed topology of "
                    f"{scn.n} nodes")
            hi = scn._max_scripted_index()
            if nodes <= hi:
                raise ValueError(
                    f"scenario {scn.name} scripts node indexes up to "
                    f"{hi}; --nodes must exceed that")
            scn = replace(scn, n=nodes,
                          threshold=max(2, (2 * nodes) // 3))
        if rounds is not None and rounds != scn.rounds:
            scn = replace(scn, rounds=rounds)
        return scn


@dataclass
class SimReport:
    scenario: str
    seed: int
    passed: bool
    failures: List[str]
    violations: List[dict]
    stalled: bool
    heads: Dict[str, int]
    doctor: Dict[str, list]
    event_log: str
    #: attached observer's verdict (`ChainWatcher.snapshot()`) when the
    #: run was made with watch=True; None otherwise
    watch: Optional[dict] = None
    #: wall-clock performance envelope of the run (obs.perf snapshot of
    #: the spans the simulated nodes emitted): per-stage p50/p95/p99 and
    #: kernel tails.  Deliberately NOT part of `event_log` — wall-clock
    #: timings vary run to run and would break byte-identical replay.
    perf: Optional[dict] = None

    def to_dict(self) -> dict:
        d = asdict(self)
        # the event log is a document of its own, not a summary field
        d.pop("event_log")
        if d.get("watch") is None:
            d.pop("watch", None)
        if d.get("perf") is None:
            d.pop("perf", None)
        return d

    def to_json(self, indent: Optional[int] = 2) -> str:
        return json.dumps(self.to_dict(), indent=indent, sort_keys=True)


def _node_status(node, genesis: int, period: float) -> dict:
    """Synthesize the status document `drand-tpu doctor` would fetch
    from this node, from the node's own (possibly skewed) viewpoint."""
    now = node.clock.now()
    head = node.store.last()
    handler = node.handler
    return {
        "chain": {
            "head_round": head.round if head else 0,
            "expected_round": current_round(now, period, genesis),
            "running": bool(handler is not None
                            and getattr(handler, "_running", False)),
        },
        "suspects": (handler.peer_ledger.suspects(now)
                     if handler is not None else []),
    }


async def _run(scn: Scenario, seed: int, watch: bool = False) -> SimReport:
    # a run-local performance observatory fed from the same spans the
    # global one watches: the report's `perf` envelope covers THIS run
    # only, without resetting process-global state other tests share
    from drand_tpu.obs import flight as obs_flight
    from drand_tpu.obs import perf as obs_perf
    from drand_tpu.obs import trace as obs_trace

    # a private flight ring: sentinel transitions from the local
    # observatory must not land in the process recorder (or the log)
    run_perf = obs_perf.PerfObservatory(
        recorder=obs_flight.FlightRecorder(capacity=64))

    def _perf_sink(span: dict) -> None:
        dur = span.get("duration")
        if dur is None:
            return
        name = span.get("name", "")
        if name.startswith("kernel."):
            run_perf.observe_kernel(name[len("kernel."):], dur)
        elif name.startswith(("beacon.", "dkg.", "gateway.")):
            run_perf.observe_stage(name, dur)

    obs_trace.TRACER.add_sink(_perf_sink)
    try:
        report = await _run_world(scn, seed, watch=watch)
    finally:
        obs_trace.TRACER.remove_sink(_perf_sink)
    perf_doc = run_perf.snapshot()
    if perf_doc.get("stages") or perf_doc.get("kernels"):
        report.perf = perf_doc
    return report


async def _run_world(scn: Scenario, seed: int,
                     watch: bool = False) -> SimReport:
    world = SimWorld(
        n=scn.n, threshold=scn.threshold, period=scn.period, seed=seed,
        skews=scn.skews, byzantine=scn.byzantine,
        sync_batch=scn.sync_batch, default_link=scn.default_link,
    )
    inv = InvariantState(scheme=world.scheme, dist_key=world.dist_key)
    if watch:
        world.attach_watcher()
    await world.start_all()
    genesis = world.group.genesis_time
    period = world.group.period

    # the timeline: fault events + one checkpoint per round, in time
    # order; at equal times fault events apply before the checkpoint.
    # With a watcher attached, two extra checkpoints past the last round
    # give its stall detector the missed-period window it needs.
    checkpoints = scn.rounds + (2 if watch else 0)
    stops = [(genesis + ev.at, 0, i, ("event", ev))
             for i, ev in enumerate(scn.events)]
    stops += [(genesis + (k - 1) * period + scn.settle_margin, 1, k,
               ("checkpoint", k))
              for k in range(1, checkpoints + 1)]
    stops.sort(key=lambda s: (s[0], s[1], s[2]))

    for when, _, _, (kind, payload) in stops:
        await world.advance_to(when)
        if kind == "event":
            await world.apply(payload.action, payload.args)
            await world.settle()
        else:
            if payload <= scn.rounds:
                fresh = inv.checkpoint(world, expected_round=payload)
                heads = sorted(
                    (n.address,
                     n.store.last().round if n.store.last() else 0)
                    for n in world.nodes if n.address in world.honest)
                world.recorder.record(
                    "invariant_check", round=payload,
                    new_violations=len(fresh), heads=dict(heads))
            if world.watcher is not None:
                await world.watcher.poll()

    stalled = inv.stalled()

    # doctor verdicts over synthesized status documents (sim nodes have
    # no HTTP plane; `diagnose` is pure over the same shape)
    from drand_tpu.cli import diagnose
    doctor: Dict[str, list] = {}
    for node in world.nodes:
        if node.address not in world.honest or not node.up:
            continue
        doctor[node.address] = diagnose(
            _node_status(node, genesis, period), {}, [])
    stall_flagged = sorted(
        addr for addr, findings in doctor.items()
        if any(f["kind"] == "stalled_chain"
               and f["severity"] == "critical" for f in findings))

    failures: List[str] = []
    kinds = {v.kind for v in inv.violations}
    missing = set(scn.require_violations) - kinds
    if missing:
        failures.append(
            f"required violations never occurred: {sorted(missing)}")
    unexpected = kinds - set(scn.require_violations) \
        - set(scn.allow_violations)
    if unexpected:
        failures.append(
            f"unexpected invariant violations: {sorted(unexpected)}")

    if scn.expect_stall:
        if not stalled:
            failures.append("expected the chain to stall; it advanced")
        if not stall_flagged:
            failures.append("doctor never flagged stalled_chain on any "
                            "honest node")
    else:
        if stalled:
            failures.append("chain stalled unexpectedly")
        for node in world.nodes:
            if node.address not in world.honest or not node.up:
                continue
            head = node.store.last()
            head_round = head.round if head else 0
            if head_round < scn.rounds - 1:
                failures.append(
                    f"{node.address} did not converge: head "
                    f"{head_round} < {scn.rounds - 1}")

    if scn.require_converged:
        up_stores = {n.address: n.store for n in world.nodes
                     if n.address in world.honest and n.up}
        for v in check_converged_single_chain(up_stores):
            failures.append(f"converged_single_chain: {v.detail}")
    if scn.require_reorg:
        reorgs = sum(1 for ev in world.recorder.snapshot()
                     if ev.get("kind") == "chain_reorg")
        if not reorgs:
            failures.append(
                "expected at least one adopted chain reorg; none "
                "happened")

    if scn.expect_blamed:
        liars = [world.nodes[i].address
                 for i, strat in sorted(scn.byzantine.items())
                 if strat in ("liar", "equivocate")]
        for v in check_byzantine_blamed(world.nodes, world.honest,
                                        liars):
            failures.append(v.detail)

    heads = {n.address: (n.store.last().round if n.store.last() else 0)
             for n in world.nodes}
    world.recorder.record(
        "sim_end", stalled=stalled,
        stall_flagged=stall_flagged,
        violations=[v.to_dict() for v in inv.violations],
        heads={a: heads[a] for a in sorted(heads)},
        failures=list(failures),
    )
    watch_snap = (world.watcher.snapshot()
                  if world.watcher is not None else None)
    await world.stop_all()

    return SimReport(
        scenario=scn.name, seed=seed, passed=not failures,
        failures=failures,
        violations=[v.to_dict() for v in inv.violations],
        stalled=stalled, heads=heads, doctor=doctor,
        event_log=world.recorder.dump(),
        watch=watch_snap,
    )


def run_scenario(scenario, seed: int = 1,
                 nodes: Optional[int] = None,
                 rounds: Optional[int] = None,
                 watch: bool = False) -> SimReport:
    """Run a scenario (by name or `Scenario` object) to completion.

    Same (scenario, seed) -> byte-identical `SimReport.event_log`,
    across processes and PYTHONHASHSEED values.  `watch=True` attaches
    an external `ChainWatcher` to the fabric: its verified verdict
    lands in `SimReport.watch` and its typed events (plus per-node
    tracer spans) join the event log — a different, richer log than the
    plain run's, equally deterministic per (scenario, seed, watch).
    """
    import asyncio

    if isinstance(scenario, str):
        from drand_tpu.sim.scenarios import get_scenario
        scenario = get_scenario(scenario)
    scenario = scenario.overridden(nodes=nodes, rounds=rounds)
    # self-running scenarios (e.g. the gateway-replica chaos script)
    # exercise subsystems other than SimWorld but return the same
    # SimReport shape; the registry and CLI treat them uniformly
    runner = getattr(scenario, "run", None)
    if runner is not None:
        if watch:
            raise ValueError(
                f"scenario {scenario.name} runs outside SimWorld and "
                "cannot attach a fabric watcher")
        return asyncio.run(runner(seed))
    return asyncio.run(_run(scenario, seed, watch=watch))
