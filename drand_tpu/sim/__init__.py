"""drand_tpu.sim — deterministic multi-node simulation harness.

FoundationDB-style simulation testing for the beacon protocol: tens of
nodes, one process, one event loop, one schedulable fake clock, a fake
network fabric with scripted faults (partitions, latency, loss,
Byzantine signers, device faults), protocol invariants checked at every
round boundary, and byte-identical replay from a seed.

Entry points:

    from drand_tpu.sim import run_scenario, SCENARIOS
    report = run_scenario("fork_stall", seed=7)

or `drand-tpu sim run --scenario fork_stall --seed 7` from the CLI.
"""

from drand_tpu.sim.scenario import (
    Scenario,
    SimEvent,
    SimReport,
    run_scenario,
)
from drand_tpu.sim.scenarios import SCENARIOS, get_scenario, list_scenarios

__all__ = [
    "Scenario",
    "SimEvent",
    "SimReport",
    "SCENARIOS",
    "get_scenario",
    "list_scenarios",
    "run_scenario",
]
