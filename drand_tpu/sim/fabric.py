"""In-memory network fabric with scripted faults.

The simulator's replacement for `net/transport.py`: every node holds a
`FabricClient` (the `net/interface.ProtocolClient` contract) whose sends
go through one shared `SimFabric`.  The fabric owns per-directed-link
state — blocked flags (partitions, half-partitions), base latency,
jitter, drop/duplicate probabilities, reorder spread — and delivers
packets by scheduling callbacks on the shared simulated clock
(`FakeClock.call_at`), so message arrival order is a pure function of
the scenario seed.

Determinism rules this module lives by:

* every probabilistic decision draws from a per-directed-link
  `random.Random` seeded from `(run seed, src, dst)` — link streams
  never interleave, so adding chatter on one link cannot shift another
  link's draws;
* seeds are strings (hashed with sha512 inside `random.seed`), never
  Python `hash()` — replays are byte-identical across processes
  regardless of PYTHONHASHSEED;
* timestamps come from the sim clock only.

Byzantine signer strategies are outbound-client wrappers (`LiarClient`,
`StaleHeadClient`, `EquivocatorClient`): the node's handler stays
honest to itself while its wire traffic lies, which is exactly the
adversary model — you can't trust what a peer *sends*, only what
verifies.  `FaultScheme` wraps a real `Scheme` to inject device faults
(a red recovered-signature check with every partial valid).
"""

from __future__ import annotations

import asyncio
import random
from typing import Dict, Optional, Set, Tuple

from drand_tpu.beacon.chain import beacon_message
from drand_tpu.crypto import tbls
from drand_tpu.net.interface import BeaconPacket, ProtocolClient
from drand_tpu.utils.clock import FakeClock


class Link:
    """State of one DIRECTED link (src -> dst)."""

    __slots__ = ("latency", "jitter", "drop", "dup", "reorder",
                 "reorder_spread", "blocked")

    def __init__(self, latency: float = 0.01, jitter: float = 0.0,
                 drop: float = 0.0, dup: float = 0.0,
                 reorder: float = 0.0, reorder_spread: float = 0.5,
                 blocked: bool = False):
        self.latency = latency
        self.jitter = jitter
        self.drop = drop
        self.dup = dup
        self.reorder = reorder            # probability of extra delay
        self.reorder_spread = reorder_spread  # max extra seconds
        self.blocked = blocked

    def configure(self, **kw) -> None:
        for k, v in kw.items():
            if k not in self.__slots__:
                raise ValueError(f"unknown link property {k!r}")
            setattr(self, k, v)


class SimFabric:
    """The one message bus every simulated node sends through."""

    def __init__(self, clock: FakeClock, seed: int, recorder=None,
                 default_link: Optional[dict] = None):
        self.clock = clock
        self.seed = seed
        self.recorder = recorder
        self.nodes: Dict[str, object] = {}       # addr -> SimNode
        self._links: Dict[Tuple[str, str], Link] = {}
        self._rngs: Dict[Tuple[str, str], random.Random] = {}
        self._default_link = dict(default_link or {})
        #: live ingest tasks — the settle loop drains these
        self._tasks: Set[asyncio.Task] = set()

    # -- topology ----------------------------------------------------------

    def register(self, node) -> None:
        self.nodes[node.address] = node

    def link(self, src: str, dst: str) -> Link:
        key = (src, dst)
        ln = self._links.get(key)
        if ln is None:
            ln = self._links[key] = Link(**self._default_link)
        return ln

    def _rng(self, src: str, dst: str) -> random.Random:
        key = (src, dst)
        rng = self._rngs.get(key)
        if rng is None:
            # string seed -> sha512 path in random.seed: identical
            # across processes, independent per directed link
            rng = self._rngs[key] = random.Random(
                f"drand-sim:{self.seed}:link:{src}->{dst}"
            )
        return rng

    def set_links(self, src: Optional[str] = None,
                  dst: Optional[str] = None, **kw) -> None:
        """Configure link properties; None matches every node on that
        side (src=None, dst=None configures the whole mesh, including
        links not yet materialised — by touching all known pairs)."""
        addrs = sorted(self.nodes)
        for s in addrs if src is None else [src]:
            for d in addrs if dst is None else [dst]:
                if s != d:
                    self.link(s, d).configure(**kw)

    def block(self, src: str, dst: str) -> None:
        self.link(src, dst).blocked = True

    def unblock(self, src: str, dst: str) -> None:
        self.link(src, dst).blocked = False

    def deaf(self, addr: str) -> None:
        """Half-partition: `addr` can send, cannot receive."""
        for other in sorted(self.nodes):
            if other != addr:
                self.block(other, addr)

    def undeaf(self, addr: str) -> None:
        for other in sorted(self.nodes):
            if other != addr:
                self.unblock(other, addr)

    def partition(self, *groups) -> None:
        """Symmetric partition: links BETWEEN groups are blocked (links
        within a group are left untouched)."""
        sets = [set(g) for g in groups]
        for i, a in enumerate(sets):
            for b in sets[i + 1:]:
                for x in sorted(a):
                    for y in sorted(b):
                        self.block(x, y)
                        self.block(y, x)

    def heal(self) -> None:
        """Unblock every link (latency/drop settings survive)."""
        for ln in self._links.values():
            ln.blocked = False

    def blocked(self, src: str, dst: str) -> bool:
        return self.link(src, dst).blocked

    # -- delivery ----------------------------------------------------------

    def _log(self, kind: str, **fields) -> None:
        if self.recorder is not None:
            self.recorder.record(kind, **fields)

    def _node_up(self, addr: str) -> bool:
        node = self.nodes.get(addr)
        return node is not None and node.up

    async def send_beacon(self, src: str, dst: str,
                          packet: BeaconPacket) -> None:
        """Fire-and-forget partial broadcast: raises only when the
        sender could KNOW the send failed (peer down / link blocked at
        send time); loss in flight is silent, like UDP-flavored reality."""
        if not self._node_up(dst):
            raise ConnectionError(f"{dst} unreachable (down)")
        if self.blocked(src, dst):
            raise ConnectionError(f"{src}->{dst} partitioned")
        link = self.link(src, dst)
        rng = self._rng(src, dst)
        if link.drop and rng.random() < link.drop:
            self._log("net_drop", src=src, dst=dst, round=packet.round)
            return
        copies = 2 if (link.dup and rng.random() < link.dup) else 1
        if copies == 2:
            self._log("net_dup", src=src, dst=dst, round=packet.round)
        for _ in range(copies):
            delay = link.latency
            if link.jitter:
                delay += rng.random() * link.jitter
            if link.reorder and rng.random() < link.reorder:
                delay += rng.random() * link.reorder_spread
            self.clock.call_at(self.clock.now() + delay,
                               self._deliver, src, dst, packet)

    def _deliver(self, src: str, dst: str, packet: BeaconPacket) -> None:
        # delivery-time re-check: a partition that started after the
        # send swallows in-flight messages too
        if not self._node_up(dst) or self.blocked(src, dst):
            self._log("net_lost", src=src, dst=dst, round=packet.round)
            return
        node = self.nodes[dst]
        task = asyncio.ensure_future(self._ingest(node, packet))
        self._tasks.add(task)
        task.add_done_callback(self._tasks.discard)

    async def _ingest(self, node, packet: BeaconPacket) -> None:
        handler = node.handler
        if handler is None:
            return
        try:
            await handler.process_beacon(packet)
        except Exception:
            # window rejects / structural rejects are the handler's
            # business; the fabric just moves bytes
            pass

    def active_tasks(self) -> int:
        return len([t for t in self._tasks if not t.done()])

    # -- chain sync --------------------------------------------------------

    async def sync_stream(self, src: str, dst: str, from_round: int):
        """Async generator for `sync_chain`: serves the peer's chain
        snapshot with per-beacon stream latency; breaks (ConnectionError)
        if either direction blocks or the peer dies mid-stream."""
        if not self._node_up(dst):
            raise ConnectionError(f"{dst} unreachable (down)")
        if self.blocked(src, dst) or self.blocked(dst, src):
            raise ConnectionError(f"sync {src}<->{dst} partitioned")
        node = self.nodes[dst]
        if node.handler is None:
            raise ConnectionError(f"{dst} not serving")
        link = self.link(dst, src)  # data flows dst -> src
        for b in list(node.handler.sync_chain_from(from_round)):
            await self.clock.sleep(link.latency)
            if not self._node_up(dst) or self.blocked(dst, src) \
                    or self.blocked(src, dst):
                raise ConnectionError(f"sync stream {dst}->{src} broken")
            yield b


class FabricClient(ProtocolClient):
    """One node's outbound transport over the shared fabric."""

    def __init__(self, fabric: SimFabric, address: str):
        self.fabric = fabric
        self.address = address

    async def new_beacon(self, peer, packet: BeaconPacket) -> None:
        await self.fabric.send_beacon(self.address, peer.address, packet)

    def sync_chain(self, peer, from_round: int):
        return self.fabric.sync_stream(self.address, peer.address,
                                       from_round)


# -- Byzantine outbound strategies ----------------------------------------


def _flip(b: bytes) -> bytes:
    return (b[:-1] + bytes([b[-1] ^ 1])) if b else b"\x01"


class LiarClient(ProtocolClient):
    """Invalid-partial liar: every outgoing partial is a structurally
    valid G2 point signed over the WRONG message (the chain link's
    prev_sig with a flipped byte).  Receivers admit it optimistically;
    the finalize blame pass must unmask it and charge THIS sender."""

    def __init__(self, inner: ProtocolClient, scheme: tbls.Scheme, share):
        self.inner = inner
        self.scheme = scheme
        self.share = share
        self._cache: dict = {}  # round -> forged partial

    def _forge(self, packet: BeaconPacket) -> bytes:
        forged = self._cache.get(packet.round)
        if forged is None:
            bad_msg = beacon_message(_flip(packet.prev_sig),
                                     packet.prev_round, packet.round)
            forged = self.scheme.partial_sign(self.share, bad_msg)
            self._cache = {packet.round: forged}  # keep exactly one round
        return forged

    async def new_beacon(self, peer, packet: BeaconPacket) -> None:
        lie = BeaconPacket(
            from_address=packet.from_address, round=packet.round,
            prev_round=packet.prev_round, prev_sig=packet.prev_sig,
            partial_sig=self._forge(packet), trace_id=packet.trace_id,
            sent_at=packet.sent_at,
        )
        await self.inner.new_beacon(peer, lie)

    def sync_chain(self, peer, from_round: int):
        return self.inner.sync_chain(peer, from_round)


class StaleHeadClient(ProtocolClient):
    """Stale-head broadcaster: pins the first chain link it ever
    gossips and keeps signing every later round against it.  Honest
    receivers drop the partials on the link-mismatch check — the
    threshold margin must absorb the dead weight."""

    def __init__(self, inner: ProtocolClient, scheme: tbls.Scheme, share):
        self.inner = inner
        self.scheme = scheme
        self.share = share
        self._pinned = None  # (prev_round, prev_sig)
        self._cache: dict = {}

    async def new_beacon(self, peer, packet: BeaconPacket) -> None:
        if self._pinned is None:
            self._pinned = (packet.prev_round, packet.prev_sig)
            await self.inner.new_beacon(peer, packet)
            return
        prev_round, prev_sig = self._pinned
        forged = self._cache.get(packet.round)
        if forged is None:
            msg = beacon_message(prev_sig, prev_round, packet.round)
            forged = self.scheme.partial_sign(self.share, msg)
            self._cache = {packet.round: forged}
        stale = BeaconPacket(
            from_address=packet.from_address, round=packet.round,
            prev_round=prev_round, prev_sig=prev_sig,
            partial_sig=forged, trace_id=packet.trace_id,
            sent_at=packet.sent_at,
        )
        await self.inner.new_beacon(peer, stale)

    def sync_chain(self, peer, from_round: int):
        return self.inner.sync_chain(peer, from_round)


class EquivocatorClient(ProtocolClient):
    """Equivocator: honest packets to the lexicographically-first half
    of the peers, forged partials (LiarClient-style) to the rest — the
    two halves see a different story from the same signer index."""

    def __init__(self, inner: ProtocolClient, scheme: tbls.Scheme, share,
                 peers):
        self.inner = inner
        self._liar = LiarClient(inner, scheme, share)
        half = len(peers) // 2
        self._honest_half = set(sorted(peers)[:half])

    async def new_beacon(self, peer, packet: BeaconPacket) -> None:
        if peer.address in self._honest_half:
            await self.inner.new_beacon(peer, packet)
        else:
            await self._liar.new_beacon(peer, packet)

    def sync_chain(self, peer, from_round: int):
        return self.inner.sync_chain(peer, from_round)


#: strategy name -> wrapper factory(inner, scheme, share, peer_addrs)
BYZANTINE_STRATEGIES = {
    "liar": lambda inner, scheme, share, peers:
        LiarClient(inner, scheme, share),
    "stale_head": lambda inner, scheme, share, peers:
        StaleHeadClient(inner, scheme, share),
    "equivocate": lambda inner, scheme, share, peers:
        EquivocatorClient(inner, scheme, share, peers),
}


class FaultScheme:
    """Scheme wrapper injecting device faults: while armed, the
    recovered-signature check reports red even though every partial is
    valid — the exact signature of a flaky accelerator.  The handler
    must abandon the round gracefully (PR 5 regression contract), and
    the chain must absorb the skipped round."""

    def __init__(self, inner: tbls.Scheme):
        self.inner = inner
        self._armed = 0

    def arm(self, count: int = 1) -> None:
        self._armed += count

    def _maybe_fault(self) -> None:
        if self._armed > 0:
            self._armed -= 1
            raise tbls.ThresholdError("injected device fault")

    def finalize_round_optimistic(self, *a, **kw):
        self._maybe_fault()
        return self.inner.finalize_round_optimistic(*a, **kw)

    def finalize_round(self, *a, **kw):
        self._maybe_fault()
        return self.inner.finalize_round(*a, **kw)

    def __getattr__(self, name):
        return getattr(self.inner, name)
