"""Deterministic multi-node simulation harness.

Builds an n-node beacon network entirely in one process and one asyncio
event loop: shares come from direct polynomial math (no DKG round-trip),
transport is `sim.fabric.SimFabric`, and time is a single schedulable
`FakeClock` that every node shares — each through its own `SkewedClock`
lens so per-node clock skew is just a scenario parameter.

Determinism contract (what makes `--seed N` byte-replayable):

* heavy crypto runs through an INLINE offload instead of
  `asyncio.to_thread`, so the whole network is cooperatively scheduled
  on one thread — no OS scheduler in the loop;
* every RNG is seeded from the scenario seed with string keys
  (sha512-based, PYTHONHASHSEED-proof): one stream per directed link,
  one per node incarnation, one for key generation;
* the event log's timestamps come from the sim clock
  (`FlightRecorder(now_fn=clock.now)`);
* event-ordering code iterates sorted lists, never bare sets.

Crash-restart keeps the node's `BeaconStore` object across the "process
death" (it is the durable disk) and rebuilds handler + client from
scratch with a bumped incarnation, exactly what a real restart does.
"""

from __future__ import annotations

import asyncio
import random
from typing import Dict, List, Optional

from drand_tpu.beacon.handler import BeaconConfig, BeaconHandler
from drand_tpu.beacon.store import BeaconStore
from drand_tpu.crypto import refimpl as ref
from drand_tpu.crypto import tbls
from drand_tpu.key import Group, Pair, Share
from drand_tpu.crypto.poly import PriPoly
from drand_tpu.obs import trace as obs_trace
from drand_tpu.obs.flight import FlightRecorder
from drand_tpu.obs.watch import ChainWatcher
from drand_tpu.sim.fabric import (
    BYZANTINE_STRATEGIES,
    FabricClient,
    FaultScheme,
    SimFabric,
)
from drand_tpu.utils.clock import FakeClock, SkewedClock

#: sim nodes join at genesis with 10s of slack, like the tier-2 tests
GENESIS_DELAY = 10


async def _inline_offload(fn, *args, **kwargs):
    """The simulator's replacement for asyncio.to_thread: run the
    "heavy" call right here on the event loop.  Wall time stops
    mattering (the sim clock is the only clock) and thread wake-up
    nondeterminism disappears with the threads."""
    return fn(*args, **kwargs)


class SimNode:
    """One simulated beacon node: keys, share, durable store, skewed
    clock lens, fabric client (possibly wrapped by a Byzantine
    strategy), and the live handler (None while crashed)."""

    def __init__(self, index: int, pair: Pair, share: Share,
                 world: "SimWorld", skew: float = 0.0,
                 byzantine: Optional[str] = None):
        self.index = index
        self.pair = pair
        self.share = share
        self.world = world
        self.address = pair.public.address
        self.clock = SkewedClock(world.clock, skew)
        self.store = BeaconStore()  # in-memory sqlite == the node's disk
        self.byzantine = byzantine
        self.fault_scheme = FaultScheme(world.scheme)
        self.incarnation = 0
        self.up = True
        self.handler: Optional[BeaconHandler] = None

    def _build_client(self):
        client = FabricClient(self.world.fabric, self.address)
        if self.byzantine:
            peers = [n.address for n in self.world.group.nodes
                     if n.address != self.address]
            client = BYZANTINE_STRATEGIES[self.byzantine](
                client, self.world.scheme, self.share.share, peers)
        return client

    def build_handler(self) -> BeaconHandler:
        cfg = BeaconConfig(
            group=self.world.group,
            public=self.pair.public,
            share=self.share,
            scheme=self.fault_scheme,
            clock=self.clock,
            sync_batch=self.world.sync_batch,
            offload=_inline_offload,
            rng=random.Random(
                f"drand-sim:{self.world.seed}:node:{self.address}"
                f":{self.incarnation}"
            ),
        )
        self.handler = BeaconHandler(cfg, self.store, self._build_client())
        self.handler.add_callback(self._on_stored)
        self.handler.add_reorg_callback(self._on_reorg)
        return self.handler

    def _on_stored(self, beacon) -> None:
        self.world.recorder.record(
            "round_stored", node=self.address, round=beacon.round,
            prev_round=beacon.prev_round,
            sig=beacon.signature[:8].hex(),
            incarnation=self.incarnation,
        )

    def _on_reorg(self, ev: dict) -> None:
        # every field the handler passes is deterministic (rounds and
        # addresses, no wall-clock), so the event joins the
        # byte-identical replay log
        self.world.recorder.record(
            "chain_reorg", node=self.address,
            peer=ev.get("peer", ""), via=ev.get("via", ""),
            divergence_round=ev.get("divergence_round"),
            depth=ev.get("depth"),
            old_head=ev.get("old_head"), new_head=ev.get("new_head"),
            incarnation=self.incarnation,
        )

    async def start(self) -> None:
        self.build_handler()
        await self.handler.start()

    async def crash(self) -> None:
        """Kill the process; the store (the disk) survives."""
        if self.handler is not None:
            await self.handler.stop()
        self.handler = None
        self.up = False
        self.world.recorder.record("node_crash", node=self.address,
                                   incarnation=self.incarnation)

    async def restart(self) -> None:
        """Come back as a fresh process over the surviving store."""
        self.incarnation += 1
        self.up = True
        self.build_handler()
        self.world.recorder.record("node_restart", node=self.address,
                                   incarnation=self.incarnation)
        await self.handler.catchup()


class SimWatcher:
    """A third-party `ChainWatcher` riding the sim fabric.

    Registered like a node (so partitions and deafness apply to it — an
    observer loses sight of a node the network can't reach), but it
    holds no share, serves no handler, and never sends: it only drains
    `sync_stream` from each peer and feeds the verified beacons to the
    wrapped `ChainWatcher`.  Its own links are pinned to zero
    latency/loss before every poll so an observation pass completes at
    one sim instant regardless of what the scenario did to the mesh —
    the runner awaits `poll()` directly and nothing else would advance
    the clock the watcher would otherwise sleep on."""

    address = "watch00"

    def __init__(self, world: "SimWorld", stall_periods: int = 3):
        self.world = world
        self.up = True
        self.handler = None  # never serves; fabric treats us as silent
        sources = {
            node.address: self._fetcher(node.address)
            for node in world.nodes
        }
        self.chain_watcher = ChainWatcher(
            world.dist_key, world.scheme,
            period=world.group.period,
            genesis_time=world.group.genesis_time,
            sources=sources,
            clock=SkewedClock(world.clock, 0.0),
            recorder=world.recorder,
            stall_periods=stall_periods,
        )

    def _fetcher(self, addr: str):
        async def fetch(from_round: int):
            out = []
            async for b in self.world.fabric.sync_stream(
                    self.address, addr, from_round):
                out.append(b)
            return out
        return fetch

    def _pin_links(self) -> None:
        for node in self.world.nodes:
            for src, dst in ((self.address, node.address),
                             (node.address, self.address)):
                self.world.fabric.link(src, dst).configure(
                    latency=0.0, jitter=0.0, drop=0.0, dup=0.0,
                    reorder=0.0)

    async def poll(self) -> dict:
        self._pin_links()
        return await self.chain_watcher.poll()

    def snapshot(self) -> dict:
        return self.chain_watcher.snapshot()


class SimWorld:
    """The whole simulated network plus its ground truth (the secret
    polynomial) and the scenario event log."""

    def __init__(self, n: int, threshold: int, period: float, seed: int,
                 skews: Optional[Dict[int, float]] = None,
                 byzantine: Optional[Dict[int, str]] = None,
                 sync_batch: int = 64,
                 default_link: Optional[dict] = None,
                 scheme: Optional[tbls.Scheme] = None,
                 start_time: float = 1_700_000_000.0):
        self.seed = seed
        self.n = n
        self.sync_batch = sync_batch
        self.clock = FakeClock(start=start_time)
        self.recorder = FlightRecorder(capacity=1 << 16,
                                       now_fn=self.clock.now)
        self.fabric = SimFabric(self.clock, seed, recorder=self.recorder,
                                default_link=default_link)
        self.scheme = scheme or tbls._native_scheme_or_ref()

        keyrng = random.Random(f"drand-sim:{seed}:keys")
        pairs = [
            Pair.generate(f"sim{i:02d}", rng=keyrng.randbytes)
            for i in range(n)
        ]
        self.group = Group(
            nodes=[p.public for p in pairs],
            threshold=threshold,
            period=period,
            genesis_time=int(self.clock.now()) + GENESIS_DELAY,
        )
        self.poly = PriPoly.random(threshold, rng=keyrng.randbytes)
        commits = self.poly.commit().commits
        #: ground-truth distributed public key, straight from the secret
        self.dist_key = ref.g1_mul(ref.G1_GEN, self.poly.secret())

        byzantine = byzantine or {}
        skews = skews or {}
        self.nodes: List[SimNode] = []
        for i, pair in enumerate(pairs):
            node = SimNode(
                i, pair,
                Share(commits=commits, share=self.poly.eval(i)),
                self, skew=skews.get(i, 0.0),
                byzantine=byzantine.get(i),
            )
            self.fabric.register(node)
            self.nodes.append(node)
        #: addresses whose SIGNING behavior is honest (Byzantine wrappers
        #: corrupt the wire, so their owners are excluded from the
        #: cross-store and blame invariants)
        self.honest = {n.address for n in self.nodes if not n.byzantine}
        #: background scenario actions (a restarting node's catch-up
        #: needs the clock to keep advancing, so it must not block the
        #: runner that advances it)
        self._bg: set = set()
        #: attached third-party observer (attach_watcher); None by
        #: default so plain runs stay byte-identical to earlier seeds
        self.watcher: Optional[SimWatcher] = None
        self._span_lens = None

    # -- observatory -------------------------------------------------------

    def attach_watcher(self, stall_periods: int = 3) -> SimWatcher:
        """Attach an external `ChainWatcher` to the fabric and start
        merging per-node tracer spans into the event log.

        The watcher is a fabric citizen (deafness/partitions apply),
        its typed `watch_*` events land in `self.recorder` next to the
        nodes' own events, and the span lens adds one `node_span` event
        per finished beacon-stage span — together they make the event
        log a single cross-node timeline (`cli sim inspect`)."""
        if self.watcher is not None:
            return self.watcher
        self.watcher = SimWatcher(self, stall_periods=stall_periods)
        self.fabric.register(self.watcher)

        def _lens(d: dict) -> None:
            attrs = d.get("attrs") or {}
            node = attrs.get("node")
            if node is None or not d.get("name", "").startswith("beacon."):
                return
            fields = {"name": d["name"], "node": node,
                      "status": d.get("status", "ok")}
            for key in ("round", "peer", "from_round", "to_round"):
                if key in attrs:
                    fields[key] = attrs[key]
            # deliberately NO trace ids or durations: sync spans carry
            # random trace ids and durations are wall-clock — either
            # would break byte-identical replay
            self.recorder.record("node_span", **fields)

        self._span_lens = _lens
        obs_trace.TRACER.add_sink(_lens)
        return self.watcher

    # -- lifecycle ---------------------------------------------------------

    async def start_all(self) -> None:
        for node in self.nodes:
            await node.start()
        self.recorder.record("sim_start", nodes=self.n,
                             threshold=self.group.threshold,
                             genesis=self.group.genesis_time,
                             seed=self.seed)

    async def stop_all(self) -> None:
        if self._span_lens is not None:
            obs_trace.TRACER.remove_sink(self._span_lens)
            self._span_lens = None
        for task in list(self._bg):
            if not task.done():
                task.cancel()
        for node in self.nodes:
            if node.handler is not None:
                await node.handler.stop()
        await self.settle()

    def _spawn(self, coro, label: str) -> None:
        task = asyncio.ensure_future(coro)
        self._bg.add(task)

        def _done(t, label=label):
            self._bg.discard(t)
            if t.cancelled():
                return
            exc = t.exception()
            if exc is not None:
                self.recorder.record("action_failed", action=label,
                                     error=repr(exc))

        task.add_done_callback(_done)

    # -- time --------------------------------------------------------------

    async def settle(self, max_spins: int = 500) -> None:
        """Drain every zero-sim-time consequence: due clock callbacks,
        fabric ingest tasks, and whatever they spawn, until the network
        is quiescent at the current sim instant."""
        for _ in range(max_spins):
            self.clock.fire_due()
            if self.fabric.active_tasks() == 0:
                # a few clean yields: just-delivered partials may be
                # waking round tasks that finalize + store inline
                for _ in range(10):
                    await asyncio.sleep(0)
                if self.fabric.active_tasks() == 0 \
                        and self.clock.fire_due() == 0:
                    return
            else:
                await asyncio.sleep(0)

    async def advance_to(self, when: float) -> None:
        await self.clock.advance_to(when)
        await self.settle()

    # -- scenario actions --------------------------------------------------

    def _addr(self, idx: int) -> str:
        return self.nodes[idx].address

    async def apply(self, action: str, args: dict) -> None:
        """Execute one scenario fault event at the current sim time."""
        self.recorder.record("fault_event", action=action,
                             **{k: v for k, v in sorted(args.items())})
        if action == "deaf":
            self.fabric.deaf(self._addr(args["node"]))
        elif action == "undeaf":
            self.fabric.undeaf(self._addr(args["node"]))
        elif action == "partition":
            groups = [[self._addr(i) for i in g] for g in args["groups"]]
            self.fabric.partition(*groups)
        elif action == "heal":
            self.fabric.heal()
        elif action == "block":
            self.fabric.block(self._addr(args["src"]),
                              self._addr(args["dst"]))
        elif action == "unblock":
            self.fabric.unblock(self._addr(args["src"]),
                                self._addr(args["dst"]))
        elif action == "set_links":
            kw = dict(args)
            src = kw.pop("src", None)
            dst = kw.pop("dst", None)
            self.fabric.set_links(
                None if src is None else self._addr(src),
                None if dst is None else self._addr(dst), **kw)
        elif action == "crash":
            await self.nodes[args["node"]].crash()
        elif action == "restart":
            # runs in the background: catch-up sync sleeps on the sim
            # clock, which only moves while the runner keeps advancing
            self._spawn(self.nodes[args["node"]].restart(),
                        f"restart:{args['node']}")
        elif action == "skew":
            self.nodes[args["node"]].clock.skew = args["seconds"]
        elif action == "device_fault":
            self.nodes[args["node"]].fault_scheme.arm(
                args.get("count", 1))
        else:
            raise ValueError(f"unknown scenario action {action!r}")
