"""Operator CLI.

Mirrors the reference's urfave/cli surface (/root/reference/main.go:189-378
and daemon.go/control.go/public.go):

  drand-tpu generate-keypair <address>     create the long-term keypair
  drand-tpu group <key files...>           build a group.toml
  drand-tpu check-group <group.toml>       probe reachability of all nodes
  drand-tpu start                          run the daemon
  drand-tpu warmup                         pre-compile device kernels into
                                           the persistent XLA cache
  drand-tpu verify-serve --distkey <hex>   standalone dynamic-batching
                                           verification gateway
  drand-tpu stop                           stop via the control port
  drand-tpu share <group.toml> [--leader]  run the DKG (or reshare with
                                           --from-group)
  drand-tpu get public|private <group.toml> --node <addr>
  drand-tpu ping                           control-port liveness
  drand-tpu show share|group|public|private|cokey
  drand-tpu reset                          wipe beacon + share state
  drand-tpu status                         health snapshot (/v1/status)
  drand-tpu trace <round>                  span tree of one beacon round
  drand-tpu doctor                         ranked diagnosis from /v1/slo
                                           + /v1/status + /debug/flight
  drand-tpu fleet --nodes a,b,c            aggregate N nodes into one
                                           fleet view (GET /v1/fleet
                                           with --serve)
  drand-tpu watch --nodes a,b,c            third-party chain watchdog:
                                           verify everything, report
                                           forks/stalls/lag
  drand-tpu sim run|list|inspect           deterministic chaos scenarios
                                           + merged timeline viewer
  drand-tpu bench diff OLD NEW             stage-by-stage bench artifact
                                           comparison; exits 1 on
                                           regression (CI gate)

Run as `python -m drand_tpu.cli ...`.
"""

from __future__ import annotations

import argparse
import asyncio
import os
import shutil
import sys
import time
from drand_tpu.utils import tomlcompat as tomllib
from pathlib import Path

from drand_tpu.key import (
    FileStore,
    Group,
    Identity,
    Pair,
    default_threshold,
)
from drand_tpu.key.store import KeyNotFound
from drand_tpu.utils import parse_duration, toml_dumps

DEFAULT_FOLDER = "~/.drand-tpu"
DEFAULT_CONTROL = 8888


def _store(args) -> FileStore:
    return FileStore(os.path.expanduser(args.folder))


def cmd_generate_keypair(args) -> int:
    store = _store(args)
    pair = Pair.generate(args.address, tls=args.tls)
    store.save_key_pair(pair)
    pub_path = Path(os.path.expanduser(args.folder)) / "key" / "public.toml"
    pub_path.write_text(toml_dumps(pair.public.to_dict()))
    print(f"generated keypair for {args.address}")
    print(f"public key file: {pub_path}")
    return 0


def cmd_group(args) -> int:
    nodes = []
    for path in args.keys:
        with open(path, "rb") as fh:
            nodes.append(Identity.from_dict(tomllib.load(fh)))
    threshold = args.threshold or default_threshold(len(nodes))
    genesis = args.genesis or int(time.time()) + 60
    group = Group(
        nodes=nodes,
        threshold=threshold,
        period=parse_duration(args.period),
        genesis_time=genesis,
    )
    group.get_genesis_seed()
    out = args.out or "group.toml"
    Path(out).write_text(toml_dumps(group.to_dict()))
    print(f"wrote {out}: {len(nodes)} nodes, threshold {threshold}, "
          f"period {args.period}, genesis {genesis}")
    return 0


def cmd_check_group(args) -> int:
    from drand_tpu.net import CertManager, GrpcClient

    with open(args.group, "rb") as fh:
        group = Group.from_dict(tomllib.load(fh))

    certs = CertManager()
    n = _load_certs_dir(certs, getattr(args, "certs_dir", None))
    if n:
        print(f"trusting {n} certificate(s) from {args.certs_dir}")

    async def probe() -> int:
        client = GrpcClient(certs)
        failures = 0
        for node in group.nodes:
            try:
                await client.home(node)
                print(f"  ok    {node.address}")
            except Exception as exc:
                print(f"  FAIL  {node.address}: {exc}")
                failures += 1
        await client.close()
        return failures

    bad = asyncio.run(probe())
    print(f"{len(group.nodes) - bad}/{len(group.nodes)} nodes reachable")
    return 1 if bad else 0


def _load_certs_dir(cert_manager, certs_dir) -> int:
    """Seed the trust pool with every PEM in a directory (reference
    CertManager, net/certs.go:14-43)."""
    n = 0
    if certs_dir:
        d = Path(certs_dir)
        if not d.is_dir():
            raise SystemExit(
                f"--certs-dir {certs_dir}: not a directory"
            )
        for p in sorted(d.iterdir()):
            if p.suffix.lower() in (".pem", ".crt", ".cert"):
                cert_manager.add_file(str(p))
                n += 1
    return n


def _apply_compile_cache(args) -> None:
    """Publish --compile-cache as DRAND_TPU_COMPILE_CACHE before any
    scheme is built: JaxScheme.__init__ (and ops import) re-reads the
    env via ops.configure_compile_cache, so the flag takes effect even
    though jax may already be imported."""
    if getattr(args, "compile_cache", None):
        os.environ["DRAND_TPU_COMPILE_CACHE"] = args.compile_cache


def cmd_start(args) -> int:
    import signal

    from drand_tpu.core import Config, Drand
    from drand_tpu.crypto import tbls
    from drand_tpu.obs import flight, install_crash_handler

    _apply_compile_cache(args)

    async def run():
        store = _store(args)
        pair = store.load_key_pair()
        # post-mortem evidence next to the keys: an unhandled exception
        # (and SIGTERM below) dumps the flight-recorder ring buffer
        # before exit.  Named per node identity — in-process multi-node
        # setups must not clobber one another's dump.
        install_crash_handler(os.path.join(
            os.path.expanduser(args.folder),
            flight.dump_filename(pair.public.address),
        ))
        tls_cert = tls_key = None
        if args.tls_cert or args.tls_key:
            if not (args.tls_cert and args.tls_key):
                raise SystemExit(
                    "--tls-cert and --tls-key must be given together"
                )
            tls_cert = Path(args.tls_cert).read_bytes()
            tls_key = Path(args.tls_key).read_bytes()
        cfg = Config(
            base_folder=args.folder,
            listen_addr=args.listen or pair.public.address,
            control_port=args.control,
            rest_port=args.rest_port,
            mux_port=args.mux_port,
            scheme=tbls.default_scheme(args.backend),
            tls_cert=tls_cert,
            tls_key=tls_key,
            insecure=tls_cert is None,
            partial_verify=args.partial_verify,
        )
        n = _load_certs_dir(cfg.cert_manager, args.certs_dir)
        if n:
            print(f"trusting {n} certificate(s) from {args.certs_dir}")
        if tls_cert is not None:
            print("TLS enabled (gRPC + REST)")
        try:
            store.load_group()
            daemon = await Drand.load(cfg, pair)
            print("loaded existing beacon state; catching up")
        except KeyNotFound:
            daemon = await Drand.new(cfg, pair)
            print("fresh node: waiting for DKG "
                  f"(control port {args.control})")

        def _graceful(signame: str) -> None:
            flight.RECORDER.record("signal", signal=signame)
            # request_shutdown retains the stop task in the daemon's
            # task set — ensure_future here would drop the only handle
            daemon.request_shutdown()

        loop = asyncio.get_running_loop()
        for s in (signal.SIGINT, signal.SIGTERM):
            try:
                loop.add_signal_handler(s, _graceful, s.name)
            except NotImplementedError:
                pass
        await daemon.wait_exit()

    asyncio.run(run())
    return 0


def cmd_warmup(args) -> int:
    """Pre-populate the persistent XLA compile cache for the daemon's
    standard kernel shapes, so a fresh deployment's first verify doesn't
    stall for minutes on a cold Pallas/XLA compile.

    Exercises exactly the jit entry points the daemon hits (same shape
    buckets as JaxScheme): batched hashed chain verify, partial-flood
    verify, device sign, and MSM recovery at each requested threshold.
    The reference has no equivalent because Go compiles ahead of time;
    this is the TPU-native answer to the same operational need.
    """
    import subprocess
    import time as _time

    _apply_compile_cache(args)

    # A broken ambient accelerator backend can raise OR hang inside JAX
    # init; probe it in a subprocess (same self-healing contract as
    # bench.py) and warm the CPU op-graph path instead when it's dead —
    # a daemon on the same host will make the same auto fallback.
    if os.environ.get("DRAND_TPU_WARMUP_FALLBACK") != "1" \
            and os.environ.get("JAX_PLATFORMS", "") != "cpu":
        try:
            probe = subprocess.run(
                [sys.executable, "-c", "import jax; jax.devices()"],
                # same knob + default as bench.py: a loaded single-core
                # host can legitimately take minutes to answer the probe
                timeout=float(os.environ.get("BENCH_PROBE_TIMEOUT", "240")),
                capture_output=True,
            )
            alive = probe.returncode == 0
        except (subprocess.TimeoutExpired, OSError):
            alive = False
        if not alive:
            print("warmup: ambient accelerator backend is broken; "
                  "warming the CPU path", flush=True)
            env = dict(os.environ)
            env["DRAND_TPU_WARMUP_FALLBACK"] = "1"
            env["JAX_PLATFORMS"] = "cpu"
            env.pop("PALLAS_AXON_POOL_IPS", None)
            # re-exec via -m: under `python -m drand_tpu.cli` sys.argv[0]
            # is this file's path, and exec'ing it as a script would lose
            # the cwd import root the package is loaded from
            os.execve(
                sys.executable,
                [sys.executable, "-m", "drand_tpu.cli"] + sys.argv[1:],
                env,
            )

    from drand_tpu.crypto import refimpl as ref
    from drand_tpu.crypto import tbls
    from drand_tpu.crypto.poly import PriPoly

    t0 = _time.monotonic()
    print("warmup: initializing device backend ...", flush=True)
    scheme = tbls.JaxScheme()
    thresholds = sorted(set(args.thresholds or [2, 3]))
    poly = PriPoly.random(max(thresholds))
    pub = poly.commit()
    pk = pub.commit()
    sk = poly.secret()
    msg = b"drand-tpu warmup"
    sig = ref.g2_to_bytes(ref.g2_mul(ref.hash_to_g2(msg), sk))

    def step(label, fn):
        t = _time.monotonic()
        fn()
        print(f"warmup: {label}: {_time.monotonic() - t:.1f}s", flush=True)

    # one batch <= the kernel block compiles the whole verify pipeline
    step("chain verify kernel (hashed pairing product)",
         lambda: scheme.verify_chain_batch(pk, [msg], [sig]))
    step("device sign (h2c + G2 scalar mult)",
         lambda: scheme.partial_sign(poly.eval(0), msg))
    for t in thresholds:
        shares = [poly.eval(i) for i in range(t)]
        partials = [scheme.partial_sign(s, msg) for s in shares]
        step(f"partial flood verify (t={t})",
             lambda: scheme.verify_partials_batch(pub, msg, partials))
        step(f"MSM recovery (t={t})",
             lambda: scheme.recover(pub, msg, partials, t, t))
    print(f"warmup: done in {_time.monotonic() - t0:.1f}s")
    return 0


def cmd_verify_serve(args) -> int:
    """Standalone verification gateway: no daemon, no group membership —
    just the distributed key, the batching kernel and an HTTP front end
    (POST /v1/verify + /metrics).  The serving analogue of `get public`:
    anyone holding the collective key can offer verification-as-a-
    service for the chain."""
    import signal

    from drand_tpu.crypto import refimpl as ref
    from drand_tpu.crypto import tbls
    from drand_tpu.net.rest import build_verify_app, start_rest
    from drand_tpu.serve import VerifyGateway

    _apply_compile_cache(args)
    try:
        # schemes take the collective key as a decoded G1 point (the
        # same shape DistPublic.key() hands the daemon), not wire bytes
        dist_key = ref.g1_from_bytes(bytes.fromhex(args.distkey))
    except ValueError as e:
        print(f"bad --distkey: {e}", file=sys.stderr)
        return 1
    if dist_key is None:
        print("bad --distkey: identity point", file=sys.stderr)
        return 1

    async def run() -> int:
        ring = None
        if args.ring:
            from drand_tpu.net.transport import GrpcClient
            from drand_tpu.serve import ReplicaRing, grpc_forwarder

            peers = [p.strip() for p in args.ring.split(",") if p.strip()]
            self_id = args.replica_id or f"127.0.0.1:{args.port}"
            ring = ReplicaRing(
                self_id, [p for p in peers if p != self_id],
                forward=grpc_forwarder(GrpcClient()),
            )
        gateway = VerifyGateway(
            dist_key,
            tbls.default_scheme(args.backend),
            max_batch=args.max_batch,
            max_wait=args.max_wait,
            max_queue=args.max_queue,
            cache_size=args.cache_size,
            client_max_inflight=args.client_max_inflight,
            mesh_devices=args.mesh_devices,
            ring=ring,
        )
        await gateway.start()
        runner, port = await start_rest(
            build_verify_app(gateway), args.port
        )
        mesh = gateway.stats()["mesh"]
        print(f"verify gateway on :{port} "
              f"(max_batch={args.max_batch}, max_wait={args.max_wait}s, "
              f"queue={args.max_queue}, "
              f"backend={type(gateway.scheme).__name__}, "
              f"mesh={mesh['devices']}x{mesh['backend'] or '-'}"
              + (f", ring={ring.ring.members()}" if ring else "")
              + ")", flush=True)
        stop = asyncio.Event()
        loop = asyncio.get_running_loop()
        for sig in (signal.SIGINT, signal.SIGTERM):
            try:
                loop.add_signal_handler(sig, stop.set)
            except NotImplementedError:
                pass
        await stop.wait()
        await runner.cleanup()
        await gateway.close()
        return 0

    return asyncio.run(run())


def _control(args):
    from drand_tpu.net import ControlClient

    return ControlClient(args.control)


def cmd_stop(args) -> int:
    async def run():
        c = _control(args)
        await c.shutdown()
        await c.close()

    asyncio.run(run())
    print("daemon stopped")
    return 0


def cmd_ping(args) -> int:
    async def run():
        c = _control(args)
        await c.ping()
        await c.close()

    asyncio.run(run())
    print("pong")
    return 0


def cmd_share(args) -> int:
    group_toml = Path(args.group).read_text()
    entropy = None
    if getattr(args, "source", None):
        from drand_tpu.entropy import get_random

        entropy = get_random(32, args.source)

    async def run() -> str:
        c = _control(args)
        try:
            if args.from_group:
                old_toml = Path(args.from_group).read_text()
                return await c.init_reshare(
                    new_group_toml=group_toml,
                    old_group_toml=old_toml,
                    is_leader=args.leader,
                    timeout=args.timeout,
                    entropy=entropy,
                )
            if args.reshare:
                return await c.init_reshare(
                    new_group_toml=group_toml,
                    is_leader=args.leader,
                    timeout=args.timeout,
                    entropy=entropy,
                )
            return await c.init_dkg(
                group_toml, is_leader=args.leader, timeout=args.timeout,
                entropy=entropy,
            )
        finally:
            await c.close()

    dist = asyncio.run(run())
    if dist:
        print(f"distributed key: {dist}")
    else:
        print("done (this node holds no share in the new group)")
    return 0


def cmd_get(args) -> int:
    from drand_tpu.core import DrandClient
    from drand_tpu.crypto import refimpl as ref

    with open(args.group, "rb") as fh:
        group = Group.from_dict(tomllib.load(fh))
    node = None
    for n in group.nodes:
        if args.node in (None, n.address):
            node = n
            break
    if node is None:
        print(f"node {args.node} not in group", file=sys.stderr)
        return 1

    async def run() -> int:
        if args.kind == "private":
            client = DrandClient(dist_key=None)
            out = await client.private(node)
            print(out.hex())
            await client.close()
            return 0
        # public randomness requires the distributed key to verify
        if not args.distkey:
            print("--distkey <hex> required for verified public "
                  "randomness", file=sys.stderr)
            return 1
        dist = ref.g1_from_bytes(bytes.fromhex(args.distkey))
        client = DrandClient(dist)
        b = (await client.public(node, args.round) if args.round
             else await client.last_public(node))
        print(toml_dumps({
            "Round": b.round,
            "Signature": b.signature.hex(),
            "Randomness": b.randomness().hex(),
        }))
        await client.close()
        return 0

    return asyncio.run(run())


def cmd_show(args) -> int:
    async def run() -> int:
        c = _control(args)
        try:
            if args.what == "share":
                idx, hexv = await c.share()
                print(toml_dumps({"Index": idx, "Share": hexv}))
            elif args.what == "group":
                print(await c.group_file())
            elif args.what == "public":
                print(await c.public_key())
            elif args.what == "private":
                print(await c.private_key())
            elif args.what == "cokey":
                for coeff in await c.collective_key():
                    print(coeff)
            return 0
        finally:
            await c.close()

    return asyncio.run(run())


def cmd_reset(args) -> int:
    base = Path(os.path.expanduser(args.folder))
    removed = []
    for rel in ["db", "groups/dist_key.public.toml",
                "key/dist_key.private.toml", "groups/drand_group.toml"]:
        p = base / rel
        if p.is_dir():
            shutil.rmtree(p)
            removed.append(rel)
        elif p.exists():
            p.unlink()
            removed.append(rel)
    print(f"reset: removed {removed or 'nothing'}")
    return 0


def _http_get_json(url: str):
    import json
    import urllib.request

    with urllib.request.urlopen(url, timeout=10) as resp:
        return json.loads(resp.read().decode("utf-8"))


def _print_kv(d: dict, indent: int = 0) -> None:
    for k in sorted(d):
        v = d[k]
        if isinstance(v, dict):
            print(f"{'  ' * indent}{k}:")
            _print_kv(v, indent + 1)
        else:
            print(f"{'  ' * indent}{k}: {v}")


def cmd_status(args) -> int:
    import json

    st = _http_get_json(f"{args.url.rstrip('/')}/v1/status")
    if args.json:
        print(json.dumps(st, indent=2, sort_keys=True))
    else:
        _print_kv(st)
    return 0


def _print_span_tree(spans) -> None:
    """Indent spans under their parents; a span whose parent is not in
    this trace (evicted, or recorded on another node) prints as a root."""
    ids = {s["span_id"] for s in spans}
    children: dict = {}
    for s in spans:
        parent = s.get("parent_id")
        children.setdefault(parent if parent in ids else None,
                            []).append(s)

    def walk(parent, depth):
        for s in sorted(children.get(parent, []),
                        key=lambda s: s["start"]):
            dur = s.get("duration")
            ms = "       ?" if dur is None else f"{dur * 1e3:8.2f}ms"
            attrs = " ".join(
                f"{k}={v}" for k, v in sorted(s.get("attrs", {}).items())
            )
            err = "" if s.get("status") == "ok" else f"  [{s['status']}]"
            print(f"  {ms}  {'  ' * depth}{s['name']}"
                  f"{'  ' + attrs if attrs else ''}{err}")
            walk(s["span_id"], depth + 1)

    walk(None, 0)


def cmd_trace(args) -> int:
    base = args.url.rstrip("/")
    data = _http_get_json(f"{base}/debug/traces?round={args.round}")
    traces = data.get("traces", [])
    if not traces:
        print(f"no trace recorded for round {args.round}")
        return 1
    for t in traces:
        print(f"trace {t['trace_id']} ({len(t['spans'])} spans)")
        _print_span_tree(t["spans"])
    return 0


# doctor severity ranks (findings print most severe first)
_SEV = {"critical": 0, "warning": 1, "info": 2}


def diagnose(status, slo_doc, flight_events) -> list:
    """Pure diagnosis over the three observability documents: returns
    findings as {severity, kind, summary, detail} dicts ranked most
    severe first.  Pure so tests (and other front ends) can run it on
    captured documents without HTTP."""
    findings = []

    def add(severity, kind, summary, detail=""):
        findings.append({"severity": severity, "kind": kind,
                         "summary": summary, "detail": detail})

    status = status or {}
    slo_doc = slo_doc or {}
    flight_events = flight_events or []

    # -- chain progress ---------------------------------------------------
    chain = status.get("chain") or {}
    head = chain.get("head_round")
    expected = chain.get("expected_round")
    if chain:
        if not chain.get("running"):
            add("critical", "stalled_chain",
                "beacon loop is not running",
                f"chain head is round {head}")
        elif head is not None and expected is not None \
                and head + 1 < expected:
            add("critical", "stalled_chain",
                f"chain is stalled: head round {head}, clock expects "
                f"round {expected}",
                f"{expected - head} round(s) behind — the network is "
                "not reaching its threshold (check suspects below) or "
                "this node cannot sync")
    elif status.get("state") == "waiting for DKG":
        add("info", "no_chain", "node is waiting for DKG; no chain yet")

    # -- peer health ------------------------------------------------------
    for s in status.get("suspects") or []:
        reasons = "; ".join(s.get("reasons") or []) or "composite score"
        add("warning", "lagging_peer",
            f"peer {s.get('peer')} is suspect "
            f"(score {s.get('score')})", reasons)

    # -- SLO burn ---------------------------------------------------------
    for name, obj in sorted((slo_doc.get("objectives") or {}).items()):
        for alarm in obj.get("breaching") or []:
            add("critical", "slo_burn",
                f"SLO {name} is burning error budget "
                f"{alarm.get('long_burn')}x over {alarm.get('window')} "
                f"(alert factor {alarm.get('factor')})",
                obj.get("description", ""))
        remaining = obj.get("budget_remaining")
        if remaining is not None and remaining < 0.25 \
                and not obj.get("breaching"):
            add("warning", "slo_budget",
                f"SLO {name} has {remaining:.0%} error budget left",
                obj.get("description", ""))

    # -- gateway pressure -------------------------------------------------
    serve = status.get("serve") or {}
    depth, max_q = serve.get("queue_depth"), serve.get("max_queue")
    if depth and max_q and depth >= max_q * 0.8:
        add("warning", "gateway_pressure",
            f"verify gateway queue at {depth}/{max_q} — sheds imminent")

    # -- cold compile cache ----------------------------------------------
    for op, st in sorted((status.get("kernels") or {}).items()):
        n = st.get("dispatches", 0)
        first = st.get("first_seconds", 0.0)
        if n >= 2 and first >= 0.5:
            steady = (st.get("seconds_total", 0.0) - first) / (n - 1)
            if first > max(10 * steady, 0.5):
                add("info", "cold_compile",
                    f"kernel {op}: first dispatch took {first:.2f}s vs "
                    f"{steady * 1e3:.1f}ms steady-state — cold XLA "
                    "compile; pre-warm with `drand-tpu warmup`")

    # -- performance observatory ------------------------------------------
    perf_doc = status.get("perf") or {}
    rounds = perf_doc.get("rounds") or {}
    if rounds.get("breaching"):
        add("critical", "dispatch_budget_regression",
            f"honest rounds are exceeding the dispatch budget: last "
            f"round spent {rounds.get('last_dispatches')} device "
            f"dispatches (budget {rounds.get('budget')})",
            f"{rounds.get('exceeded_total', 0)} offense(s) over "
            f"{rounds.get('episodes', 0)} episode(s) — the optimistic "
            "finalize path is doing extra device work; check for a "
            "scheme regression or silent fallback re-verification")
    recompiles = perf_doc.get("recompiles") or {}
    if recompiles.get("storm"):
        add("warning", "recompile_storm",
            f"{recompiles.get('recent')} suspected jit recompile(s) in "
            f"the last {recompiles.get('window_seconds')}s",
            "dispatches are hitting fresh XLA compiles outside warmup — "
            "look for unstable shapes or a cold/dropped compile cache")
    for op, st in sorted((perf_doc.get("kernels") or {}).items()):
        p50, p99 = st.get("p50"), st.get("p99")
        if st.get("count", 0) >= 50 and p50 and p99 \
                and p99 > max(10 * p50, 0.001):
            add("warning", "kernel_latency_regression",
                f"kernel {op}: p99 {p99 * 1e3:.1f}ms is "
                f"{p99 / p50:.0f}x its p50 {p50 * 1e3:.1f}ms over "
                f"{st['count']} dispatches",
                "heavy-tailed kernel latency — host contention, "
                "recompiles, or an input-dependent slow path")

    # -- flight recorder -------------------------------------------------
    crashes = [e for e in flight_events
               if e.get("kind") in ("crash", "signal")]
    if crashes:
        last = crashes[-1]
        add("warning", "recent_crash",
            f"flight recorder holds a {last.get('kind')} event",
            str({k: v for k, v in last.items() if k != "kind"}))
    starved = [e for e in flight_events if e.get("kind") == "sync_starved"]
    if starved:
        last = starved[-1]
        add("warning", "sync_starved",
            f"catch-up starved: every peer failed a full resync pass "
            f"({last.get('peers_tried')} tried) with the head at "
            f"{last.get('head_round')} vs scheduled round "
            f"{last.get('current_round')}",
            "check peer reachability and drand_sync_failures_total "
            "reasons; a reorg_beyond_cap reason means a fork diverged "
            "deeper than the reorg depth cap and needs operator action")
    refused = [e for e in flight_events
               if e.get("kind") == "chain.reorg_refused"]
    if refused:
        last = refused[-1]
        add("critical", "reorg_beyond_cap",
            f"a competing chain from {last.get('peer')} diverges "
            f"{last.get('depth')} rounds back — beyond the reorg depth "
            f"cap {last.get('cap')}; the node cannot self-heal",
            "the fleet has forked deeper than rollback allows: decide "
            "the canonical branch and re-seed the losing nodes' stores "
            "(see README 'Fork resolution & reorgs')")

    if not findings:
        add("info", "healthy", "no problems detected")
    findings.sort(key=lambda f: _SEV.get(f["severity"], 3))
    return findings


#: `doctor --json` document version: the envelope (schema/url/critical/
#: findings) and each finding's {severity, kind, summary, detail} keys
#: are a stable contract for CI and the fleet aggregator; additions bump
#: the suffix, existing keys never change meaning
DOCTOR_SCHEMA = "drand-tpu.doctor.v1"


def cmd_doctor(args) -> int:
    """Pull the three observability documents and print the ranked
    diagnosis; exit 1 when anything critical was found."""
    import json

    base = args.url.rstrip("/")
    status = _http_get_json(f"{base}/v1/status")
    slo_doc = _http_get_json(f"{base}/v1/slo")
    try:
        flight_doc = _http_get_json(f"{base}/debug/flight")
    except Exception:
        flight_doc = []
    events = (flight_doc.get("events", flight_doc)
              if isinstance(flight_doc, dict) else flight_doc)

    findings = diagnose(status, slo_doc, events)
    critical = any(f["severity"] == "critical" for f in findings)
    if args.json:
        print(json.dumps({
            "schema": DOCTOR_SCHEMA,
            "url": base,
            "critical": critical,
            "findings": findings,
        }, indent=2, sort_keys=True))
    else:
        marks = {"critical": "!!", "warning": " !", "info": "  "}
        for f in findings:
            print(f"{marks.get(f['severity'], '  ')} "
                  f"[{f['severity']}] {f['kind']}: {f['summary']}")
            if f.get("detail"):
                print(f"       {f['detail']}")
    return 1 if critical else 0


def _parse_node_urls(spec: str) -> dict:
    """--nodes a,b,c -> {name: base_url}; names are the host:port part
    so the fleet table stays readable."""
    out = {}
    for raw in spec.split(","):
        url = raw.strip().rstrip("/")
        if not url:
            continue
        if "://" not in url:
            url = f"http://{url}"
        name = url.split("://", 1)[1]
        out[name] = url
    if not out:
        raise SystemExit("--nodes: no URLs given")
    return out


def _fetch_node_docs(urls: dict) -> dict:
    """One synchronous poll of every node's status + SLO documents."""
    docs = {}
    for name, base in sorted(urls.items()):
        try:
            docs[name] = {
                "status": _http_get_json(f"{base}/v1/status"),
                "slo": _http_get_json(f"{base}/v1/slo"),
            }
        except Exception as exc:
            docs[name] = {"error": str(exc)[:160]}
    return docs


def cmd_fleet(args) -> int:
    """Aggregate N nodes' observability documents into one fleet view
    (obs.fleet.aggregate): head spread, quorum margin, worst burn rate,
    suspect consensus.  One-shot by default; --interval loops a live TTY
    view; --serve exposes the same document at GET /v1/fleet."""
    import json

    from drand_tpu.obs.fleet import (
        FleetAggregator,
        aggregate,
        render_fleet,
    )

    urls = _parse_node_urls(args.nodes)

    if args.serve is not None:
        from drand_tpu.net.rest import build_fleet_app, start_rest

        def make_source(base):
            async def source():
                return await asyncio.to_thread(lambda: {
                    "status": _http_get_json(f"{base}/v1/status"),
                    "slo": _http_get_json(f"{base}/v1/slo"),
                })
            return source

        async def serve() -> int:
            agg = FleetAggregator(
                {n: make_source(b) for n, b in urls.items()})
            runner, port = await start_rest(build_fleet_app(agg),
                                            args.serve)
            print(f"fleet observatory on :{port} "
                  f"({len(urls)} nodes: {', '.join(sorted(urls))})",
                  flush=True)
            try:
                while True:
                    await asyncio.sleep(3600)
            finally:
                await runner.cleanup()

        return asyncio.run(serve())

    while True:
        doc = aggregate(_fetch_node_docs(urls))
        if args.json:
            print(json.dumps(doc, indent=2, sort_keys=True, default=repr))
        else:
            print(render_fleet(doc))
        if not args.interval:
            return 0
        time.sleep(args.interval)
        print()


def _watch_schedule(base: str, period, genesis):
    """Bootstrap (period, genesis_time) for the watcher from a node.

    Prefer the public chain API's group document (`/api/info/group`) —
    a third-party watcher should not need the operator plane — and fall
    back to `/v1/status` for nodes that predate the group route."""
    import urllib.request

    try:
        from drand_tpu.utils import parse_duration
        from drand_tpu.utils import tomlcompat as tomllib

        with urllib.request.urlopen(f"{base}/api/info/group",
                                    timeout=10) as resp:
            doc = tomllib.loads(resp.read().decode("utf-8"))
        period = period or parse_duration(doc["Period"])
        genesis = genesis or doc["GenesisTime"]
    except Exception:
        chain = _http_get_json(f"{base}/v1/status")["chain"]
        period = period or chain["period"]
        genesis = genesis or chain["genesis_time"]
    return period, genesis


def cmd_watch(args) -> int:
    """Follow one or more nodes' chains as an untrusted third party
    (obs.watch.ChainWatcher): every fetched beacon is verified against
    the distributed key, and fork/stall/lag events print as they fire.

    The distributed key comes from --distkey (hex) or, trust-on-first-
    fetch, from the first reachable node's /api/info/distkey — fine for
    operations against your own fleet, NOT for adversarial settings."""
    import json

    from drand_tpu.crypto import refimpl as ref
    from drand_tpu.crypto import tbls
    from drand_tpu.obs.watch import ChainWatcher, rest_source

    urls = _parse_node_urls(args.nodes)

    dist_key = None
    if args.distkey:
        dist_key = ref.g1_from_bytes(bytes.fromhex(args.distkey))
        if dist_key is None:
            print("bad --distkey: identity point", file=sys.stderr)
            return 1
    period, genesis = args.period, args.genesis
    for name, base in sorted(urls.items()):
        try:
            if dist_key is None:
                coeffs = _http_get_json(
                    f"{base}/api/info/distkey")["coefficients"]
                dist_key = ref.g1_from_bytes(bytes.fromhex(coeffs[0]))
                print(f"# distributed key from {name} "
                      "(trust-on-first-fetch; pass --distkey to pin)")
            if period is None or genesis is None:
                period, genesis = _watch_schedule(base, period, genesis)
            break
        except Exception as exc:
            print(f"# bootstrap via {name} failed: {exc}",
                  file=sys.stderr)
    if dist_key is None or period is None or genesis is None:
        print("no reachable node to bootstrap from; pass --distkey, "
              "--period and --genesis", file=sys.stderr)
        return 1

    watcher = ChainWatcher(
        dist_key, tbls.default_scheme(), period=period,
        genesis_time=genesis,
        sources={n: rest_source(b) for n, b in urls.items()},
    )

    async def run() -> int:
        printed = 0
        while True:
            snap = await watcher.poll()
            for ev in watcher.events[printed:]:
                print(json.dumps(ev, sort_keys=True) if args.json
                      else _render_watch_event(ev))
            printed = len(watcher.events)
            if not args.json:
                heads = " ".join(
                    f"{p}={v['head']}{'!' if v['status'] != 'ok' else ''}"
                    for p, v in sorted(snap["peers"].items()))
                print(f"\rheads: {heads}  expected={snap['expected_round']}"
                      f"  forks={len(snap['forks'])}"
                      f"  stalled={snap['stalled']}", flush=True)
            if args.once:
                return 1 if (snap["forks"] or snap["stalled"]) else 0
            await asyncio.sleep(args.interval)

    return asyncio.run(run())


def _render_watch_event(ev: dict) -> str:
    rest = " ".join(f"{k}={ev[k]}" for k in sorted(ev)
                    if k not in ("kind", "ts"))
    return f"[{ev.get('ts', 0):.0f}] {ev['kind']}: {rest}"


def cmd_bench_diff(args) -> int:
    """Compare two bench artifacts stage by stage and gate on
    regressions (obs.perf.diff_stages): latency/throughput stages fail
    beyond --tolerance, dispatch counts fail on ANY increase — they are
    backend-independent, so a third dispatch on CPU means a third
    dispatch on TPU.  --warn-only downgrades latency/throughput
    regressions to warnings (for noisy CI hosts) but still fails on
    dispatch regressions."""
    import json

    from drand_tpu.obs import perf

    try:
        old_doc = perf.load_artifact(args.old)
        new_doc = perf.load_artifact(args.new)
    except (OSError, ValueError) as exc:
        print(f"bench diff: {exc}", file=sys.stderr)
        return 2
    rows = perf.diff_stages(perf.extract_stages(old_doc),
                            perf.extract_stages(new_doc),
                            tolerance=args.tolerance)
    regressions = [r for r in rows if r["verdict"] == "regression"]
    hard = [r for r in regressions
            if not args.warn_only or r["kind"] == "dispatch"]
    if args.json:
        print(json.dumps({
            "schema": "drand-tpu.bench-diff.v1",
            "old": args.old,
            "new": args.new,
            "tolerance": args.tolerance,
            "regression": bool(hard),
            "rows": rows,
        }, indent=2, sort_keys=True))
    else:
        for r in rows:
            delta = ("" if r["delta_pct"] is None
                     else f"{r['delta_pct']:+7.1f}%")
            mark = {"regression": "!!", "improved": "++"}.get(
                r["verdict"], "  ")
            print(f"{mark} {r['verdict']:10s} {r['stage']:44s} "
                  f"{r['old']} -> {r['new']}  {delta}")
        lineage = (new_doc.get("lineage")
                   or (new_doc.get("detail") or {}).get("lineage"))
        if lineage:
            print(f"-- new artifact: backend={lineage.get('backend')} "
                  f"device={lineage.get('device')} "
                  f"rev={lineage.get('git_rev')} "
                  f"degraded={lineage.get('degraded')}")
        print(f"-- {len(rows)} stage(s), {len(regressions)} "
              f"regression(s)"
              + (f" ({len(hard)} gating)" if args.warn_only else ""))
    return 1 if hard else 0


def cmd_lint(args) -> int:
    """Run drand-lint (project-invariant static analysis): hot-path
    purity, sim determinism, asyncio discipline, registry drift.  Thin
    shim over ``python -m tools.drandlint`` — the linter lives in the
    repo checkout (tools/), not the installed package, because it lints
    the tree, not the wheel."""
    try:
        from tools.drandlint.__main__ import main as lint_main
    except ImportError:
        print("lint: tools/drandlint not importable — run from a repo "
              "checkout (or set PYTHONPATH to one)", file=sys.stderr)
        return 2
    argv = list(args.paths)
    argv += ["--root", args.root]
    if args.json:
        argv.append("--json")
    if args.baseline:
        argv += ["--baseline", args.baseline]
    if args.write_baseline:
        argv.append("--write-baseline")
    if args.show_suppressed:
        argv.append("--show-suppressed")
    if args.list_rules:
        argv.append("--list-rules")
    return lint_main(argv)


def cmd_sim_inspect(args) -> int:
    """Render a simulation event log (`sim run --out events.json`) as a
    merged cross-node timeline: every fabric/handler/watcher/invariant
    event on one time axis, offsets relative to genesis.  With a
    watcher-attached run the `watch_*` and `node_span` rows interleave
    with the nodes' own events — the time-travel debugger view of a
    chaos scenario."""
    import json

    try:
        with open(args.events) as f:
            doc = json.load(f)
        events = doc["events"] if isinstance(doc, dict) else doc
        assert isinstance(events, list)
    except (OSError, ValueError, KeyError, AssertionError) as exc:
        print(f"{args.events}: not a sim event log ({exc!r})",
              file=sys.stderr)
        return 1

    genesis = None
    for ev in events:
        if ev.get("kind") == "sim_start":
            genesis = ev.get("genesis")
            break

    def _actor(ev: dict) -> str:
        if "node" in ev:
            return str(ev["node"])
        if "peer" in ev:
            return str(ev["peer"])
        if "src" in ev and "dst" in ev:
            return f"{ev['src']}->{ev['dst']}"
        return "-"

    def _round_of(ev: dict):
        for key in ("round", "divergence_round"):
            if key in ev:
                return ev[key]
        return None

    shown = 0
    skip = {"kind", "ts", "seq", "node", "peer", "src", "dst"}
    for ev in events:
        if args.round is not None and _round_of(ev) != args.round:
            continue
        ts = ev.get("ts", 0)
        off = ts - genesis if genesis is not None else ts
        star = "*" if str(ev.get("kind", "")).startswith("watch_") else " "
        rest = " ".join(
            f"{k}={ev[k]}" for k in sorted(ev) if k not in skip)
        print(f"{star}{off:+10.2f}s  {_actor(ev):16s} "
              f"{ev.get('kind', '?'):18s} {rest}")
        shown += 1
    label = (f"round {args.round}" if args.round is not None
             else "all rounds")
    print(f"-- {shown}/{len(events)} events ({label}; "
          f"offsets relative to "
          f"{'genesis' if genesis is not None else 'epoch'})")
    return 0


def cmd_sim_list(args) -> int:
    """List the scripted chaos scenarios the simulator knows."""
    from drand_tpu.sim import list_scenarios

    for name, summary, expect_stall in list_scenarios():
        tag = " [expects stall]" if expect_stall else ""
        print(f"{name:16s} {summary}{tag}")
    return 0


def cmd_sim_run(args) -> int:
    """Run one deterministic simulation scenario.

    Same --scenario and --seed produce a byte-identical event log, so a
    failing nightly seed replays exactly with this command.  Exit 0 when
    the scenario's expectations hold (including scenarios that EXPECT a
    stall, like fork_stall), 1 otherwise.
    """
    import json

    from drand_tpu.sim import run_scenario

    report = run_scenario(args.scenario, seed=args.seed,
                          nodes=args.nodes, rounds=args.rounds,
                          watch=args.watch)
    if args.out:
        with open(args.out, "w") as f:
            f.write(report.event_log)
    if args.json:
        print(report.to_json())
    else:
        verdict = "PASSED" if report.passed else "FAILED"
        print(f"{verdict} scenario={report.scenario} seed={report.seed}")
        heads = " ".join(f"{a}={r}" for a, r in sorted(report.heads.items()))
        print(f"  heads: {heads}")
        print(f"  stalled: {report.stalled}  "
              f"violations: {len(report.violations)}")
        if report.watch is not None:
            w = report.watch
            vheads = " ".join(
                f"{p}={v['head']}" for p, v in sorted(w["peers"].items()))
            print(f"  watcher: verified heads {vheads}  "
                  f"stalled={w['stalled']}  forks={len(w['forks'])}")
            for f in w["forks"]:
                print(f"  watcher fork @ round {f['divergence_round']} "
                      f"({f['peer']}): {f['detail']}")
        for v in report.violations:
            print(f"  violation [{v['kind']}] node={v['node']} "
                  f"round={v['round']}: {v['detail']}")
        for f in report.failures:
            print(f"  FAIL: {f}")
        if args.out:
            print(f"  event log: {args.out}")
    return 0 if report.passed else 1


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="drand-tpu",
        description="TPU-native distributed randomness beacon",
    )
    p.add_argument("--folder", default=DEFAULT_FOLDER,
                   help="base config folder")
    p.add_argument("--control", type=int, default=DEFAULT_CONTROL,
                   help="control port")
    p.add_argument("--verbose", action="store_const", const=10,
                   dest="log_level", help="debug-level logfmt output")
    sub = p.add_subparsers(dest="cmd", required=True)

    g = sub.add_parser("generate-keypair")
    g.add_argument("address")
    g.add_argument("--tls", action="store_true")
    g.set_defaults(fn=cmd_generate_keypair)

    g = sub.add_parser("group")
    g.add_argument("keys", nargs="+", help="public key TOML files")
    g.add_argument("--threshold", type=int)
    g.add_argument("--period", default="1m")
    g.add_argument("--genesis", type=int)
    g.add_argument("--out")
    g.set_defaults(fn=cmd_group)

    g = sub.add_parser("check-group")
    g.add_argument("group")
    g.add_argument("--certs-dir",
                   help="directory of PEM roots for probing TLS nodes")
    g.set_defaults(fn=cmd_check_group)

    g = sub.add_parser("start")
    g.add_argument("--listen")
    g.add_argument("--rest-port", type=int)
    g.add_argument("--mux-port", type=int,
                   help="serve gRPC AND REST on this one port (the "
                        "reference's cmux listener); TLS applies to it")
    g.add_argument("--tls-cert",
                   help="PEM certificate; enables TLS on gRPC + REST")
    g.add_argument("--tls-key", help="PEM private key")
    g.add_argument("--certs-dir",
                   help="directory of PEM roots to trust when dialing "
                        "TLS peers")
    env_backend = os.environ.get("DRAND_TPU_BACKEND", "auto")
    if env_backend not in ("auto", "ref", "jax", "native"):
        raise SystemExit(
            f"DRAND_TPU_BACKEND={env_backend!r}: must be auto, ref, jax "
            "or native"
        )
    g.add_argument(
        "--backend", choices=["auto", "ref", "jax", "native"],
        default=env_backend,
        help="crypto backend: auto = device kernels when an accelerator "
             "is present, C++ host backend otherwise (default; "
             "DRAND_TPU_BACKEND overrides); native = C++ host backend; "
             "ref = pure-Python oracle",
    )
    g.add_argument(
        "--compile-cache", metavar="DIR",
        help="persistent XLA compile cache directory (default "
             "~/.cache/drand_tpu_xla; DRAND_TPU_COMPILE_CACHE overrides; "
             "'off' disables)",
    )
    env_pv = os.environ.get("DRAND_TPU_PARTIAL_VERIFY", "optimistic")
    if env_pv not in ("eager", "optimistic"):
        raise SystemExit(
            f"DRAND_TPU_PARTIAL_VERIFY={env_pv!r}: must be eager or "
            "optimistic"
        )
    g.add_argument(
        "--partial-verify", choices=["eager", "optimistic"],
        default=env_pv, dest="partial_verify",
        help="inbound partial policy: optimistic = structural admit + "
             "one recovered-signature check at quorum with a batched "
             "blame fallback (default; DRAND_TPU_PARTIAL_VERIFY "
             "overrides); eager = pairing check per partial at arrival",
    )
    g.set_defaults(fn=cmd_start)

    g = sub.add_parser("warmup")
    g.add_argument(
        "--threshold", dest="thresholds", type=int, action="append",
        help="warm the MSM/flood kernels for this committee threshold "
             "(repeatable; default 2 and 3)",
    )
    g.add_argument(
        "--compile-cache", metavar="DIR",
        help="persistent XLA compile cache directory to populate "
             "(same semantics as `start --compile-cache`)",
    )
    g.set_defaults(fn=cmd_warmup)

    g = sub.add_parser(
        "verify-serve",
        help="standalone dynamic-batching verification gateway "
             "(POST /v1/verify)",
    )
    g.add_argument("--distkey", required=True,
                   help="48-byte compressed collective G1 key (hex)")
    g.add_argument("--port", type=int, default=8080)
    g.add_argument("--max-batch", type=int, default=128,
                   help="requests per kernel batch (one Pallas block)")
    g.add_argument("--max-wait", type=float, default=0.005,
                   help="seconds to hold a partial batch before flushing")
    g.add_argument("--max-queue", type=int, default=1024,
                   help="admission bound; beyond it requests get HTTP 429")
    g.add_argument("--cache-size", type=int, default=4096,
                   help="verified-round LRU entries")
    g.add_argument(
        "--client-max-inflight", type=int, default=None,
        help="per-client in-flight cap for identified callers (default "
             "3/4 of --max-queue); beyond it HTTP 429 with reason "
             "client_quota",
    )
    g.add_argument(
        "--backend", choices=["auto", "ref", "jax", "native"],
        default=os.environ.get("DRAND_TPU_BACKEND", "auto"),
        help="crypto backend (same semantics as `start --backend`)",
    )
    g.add_argument(
        "--compile-cache", metavar="DIR",
        help="persistent XLA compile cache directory "
             "(same semantics as `start --compile-cache`)",
    )
    g.add_argument(
        "--mesh-devices", type=int, default=1,
        help="device lanes per flush: > 1 dispatches each batch as ONE "
             "mesh-sharded pairing program (8 virtual CPU devices via "
             "XLA_FLAGS=--xla_force_host_platform_device_count=8)",
    )
    g.add_argument(
        "--ring", metavar="PEERS",
        help="comma-separated gateway replica addresses forming a "
             "consistent-hash ring over round numbers; off-owner "
             "requests forward once over gRPC and fall back to local "
             "serving on failure",
    )
    g.add_argument(
        "--replica-id", metavar="ADDR",
        help="this replica's own address in --ring "
             "(default 127.0.0.1:<port>)",
    )
    g.set_defaults(fn=cmd_verify_serve)

    g = sub.add_parser("stop")
    g.set_defaults(fn=cmd_stop)

    g = sub.add_parser("ping")
    g.set_defaults(fn=cmd_ping)

    g = sub.add_parser("share")
    g.add_argument("group")
    g.add_argument("--leader", action="store_true")
    g.add_argument("--timeout", type=float)
    g.add_argument("--reshare", action="store_true",
                   help="reshare using the daemon's stored group")
    g.add_argument("--from-group", help="old group TOML (reshare)")
    g.add_argument("--source",
                   help="executable whose stdout supplies extra DKG "
                        "entropy, mixed with the OS CSPRNG (reference: "
                        "entropy.ScriptReader, main.go --source flag)")
    g.set_defaults(fn=cmd_share)

    g = sub.add_parser("get")
    g.add_argument("kind", choices=["public", "private"])
    g.add_argument("group")
    g.add_argument("--node")
    g.add_argument("--round", type=int, default=0)
    g.add_argument("--distkey")
    g.set_defaults(fn=cmd_get)

    g = sub.add_parser("show")
    g.add_argument("what",
                   choices=["share", "group", "public", "private", "cokey"])
    g.set_defaults(fn=cmd_show)

    g = sub.add_parser("reset")
    g.set_defaults(fn=cmd_reset)

    g = sub.add_parser(
        "status", help="daemon health snapshot (GET /v1/status)"
    )
    g.add_argument("--url", default="http://127.0.0.1:8080",
                   help="REST base URL of the node")
    g.add_argument("--json", action="store_true",
                   help="print the raw JSON document")
    g.set_defaults(fn=cmd_status)

    g = sub.add_parser(
        "trace",
        help="span tree of one beacon round (GET /debug/traces?round=N)",
    )
    g.add_argument("round", type=int)
    g.add_argument("--url", default="http://127.0.0.1:8080",
                   help="REST base URL of the node")
    g.set_defaults(fn=cmd_trace)

    g = sub.add_parser(
        "doctor",
        help="ranked diagnosis: stalled chain, lagging peers, SLO "
             "burn-rate alarms, cold compile cache",
    )
    g.add_argument("--url", default="http://127.0.0.1:8080",
                   help="REST base URL of the node")
    g.add_argument("--json", action="store_true",
                   help="machine-readable document (schema "
                        "drand-tpu.doctor.v1); exit code is unchanged")
    g.set_defaults(fn=cmd_doctor)

    g = sub.add_parser(
        "fleet",
        help="aggregate N nodes' status/SLO documents into one fleet "
             "view (head spread, quorum margin, worst burn rate)",
    )
    g.add_argument("--nodes", required=True,
                   help="comma-separated REST base URLs of the nodes")
    g.add_argument("--json", action="store_true",
                   help="print the aggregated document as JSON")
    g.add_argument("--interval", type=float, default=0.0,
                   help="refresh every N seconds (default: one shot)")
    g.add_argument("--serve", type=int, metavar="PORT",
                   help="serve the aggregate at GET /v1/fleet instead "
                        "of printing it")
    g.set_defaults(fn=cmd_fleet)

    g = sub.add_parser(
        "watch",
        help="follow nodes' chains as an untrusted third party: verify "
             "every beacon against the distributed key, report "
             "forks/stalls/lag as they happen",
    )
    g.add_argument("--nodes", required=True,
                   help="comma-separated REST base URLs of the nodes")
    g.add_argument("--distkey",
                   help="48-byte compressed collective G1 key (hex); "
                        "default: trust-on-first-fetch from "
                        "/api/info/distkey")
    g.add_argument("--period", type=float,
                   help="beacon period seconds (default: from "
                        "/v1/status)")
    g.add_argument("--genesis", type=int,
                   help="genesis unix time (default: from /v1/status)")
    g.add_argument("--interval", type=float, default=5.0,
                   help="poll interval seconds (default 5)")
    g.add_argument("--once", action="store_true",
                   help="one observation pass; exit 1 if a fork or "
                        "stall is currently detected")
    g.add_argument("--json", action="store_true",
                   help="print watch events as JSON lines")
    g.set_defaults(fn=cmd_watch)

    g = sub.add_parser(
        "bench",
        help="benchmark artifact tooling (regression gating)",
    )
    bench_sub = g.add_subparsers(dest="bench_cmd", required=True)

    b = bench_sub.add_parser(
        "diff",
        help="compare two bench artifacts; exit 1 on regression",
    )
    b.add_argument("old", help="baseline artifact (JSON / JSONL)")
    b.add_argument("new", help="candidate artifact (JSON / JSONL)")
    b.add_argument("--tolerance", type=float, default=0.25,
                   help="allowed fractional slip for latency/throughput "
                        "stages (default 0.25); dispatch counts always "
                        "gate at zero tolerance")
    b.add_argument("--warn-only", action="store_true",
                   help="report latency/throughput regressions without "
                        "failing (noisy CI hosts); dispatch regressions "
                        "still fail")
    b.add_argument("--json", action="store_true",
                   help="machine-readable diff document")
    b.set_defaults(fn=cmd_bench_diff)

    g = sub.add_parser(
        "lint",
        help="project-invariant static analysis (exit 1 on violations)",
    )
    g.add_argument("paths", nargs="*",
                   help="files/directories to lint "
                        "(default: <root>/drand_tpu)")
    g.add_argument("--root", default=".",
                   help="repository root (default: cwd)")
    g.add_argument("--json", action="store_true",
                   help="machine-readable report on stdout")
    g.add_argument("--baseline", metavar="FILE",
                   help="ratchet file: per-rule counts may only decrease")
    g.add_argument("--write-baseline", action="store_true",
                   help="rewrite --baseline with current counts")
    g.add_argument("--show-suppressed", action="store_true",
                   help="also print suppressed violations")
    g.add_argument("--list-rules", action="store_true",
                   help="print the rule catalog and exit")
    g.set_defaults(fn=cmd_lint)

    g = sub.add_parser(
        "sim",
        help="deterministic multi-node simulation (chaos scenarios)",
    )
    sim_sub = g.add_subparsers(dest="sim_cmd", required=True)

    s = sim_sub.add_parser("list", help="list available scenarios")
    s.set_defaults(fn=cmd_sim_list)

    s = sim_sub.add_parser(
        "run",
        help="run a scenario; same --seed replays byte-identically",
    )
    s.add_argument("--scenario", required=True,
                   help="scenario name (see `sim list`)")
    s.add_argument("--seed", type=int, default=1,
                   help="determinism seed (default 1)")
    s.add_argument("--nodes", type=int,
                   help="override node count (fixed-topology scenarios "
                        "refuse this)")
    s.add_argument("--rounds", type=int,
                   help="override how many rounds to simulate")
    s.add_argument("--out",
                   help="write the replayable event log (JSON) here")
    s.add_argument("--json", action="store_true",
                   help="print the full report as JSON")
    s.add_argument("--watch", action="store_true",
                   help="attach an external ChainWatcher to the fabric; "
                        "its verified verdict joins the report and its "
                        "events the log")
    s.set_defaults(fn=cmd_sim_run)

    s = sim_sub.add_parser(
        "inspect",
        help="render a sim event log as one merged cross-node timeline",
    )
    s.add_argument("events", help="event log JSON from `sim run --out`")
    s.add_argument("--round", type=int,
                   help="only events for this round")
    s.set_defaults(fn=cmd_sim_inspect)
    return p


def main(argv=None) -> int:
    from drand_tpu.utils.logging import setup as setup_logging

    args = build_parser().parse_args(argv)
    setup_logging(getattr(args, "log_level", None) or 20)  # INFO
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
