"""Protocol-plane transport interface (dependency-free).

Extracted from `beacon/handler.py` so every transport — the gRPC client
in `net/transport.py`, the loopback nets in tests, and the simulator's
fault-injecting fabric (`drand_tpu/sim/fabric.py`) — implements one
contract the beacon handler is written against.  This module must stay
stdlib-only: the simulator imports it without dragging grpc in, and
`net/__init__` lazy-loads the heavy transport module for the same
reason.

`BeaconPacket` is the wire content of a partial-signature broadcast
(the NewBeacon RPC); `ProtocolClient` is the outbound half every node
holds.  The gRPC servicers in `net/transport.py` are the inbound half
and need no interface here — they call straight into the daemon facade.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import TYPE_CHECKING, AsyncIterator

if TYPE_CHECKING:  # only for signatures; no runtime import cost
    from drand_tpu.beacon.chain import Beacon
    from drand_tpu.key import Identity


@dataclass
class BeaconPacket:
    """Wire content of a partial-signature broadcast (NewBeacon RPC)."""

    from_address: str
    round: int
    prev_round: int
    prev_sig: bytes
    partial_sig: bytes
    #: distributed-trace id of the round this partial belongs to; every
    #: group member derives the same value, but carrying it on the wire
    #: lets out-of-group observers stitch too (and survives seed drift)
    trace_id: str = ""
    #: sender's clock at send time (unix seconds; 0 = not carried) — the
    #: receiver's peer ledger estimates clock skew from recv - sent_at
    sent_at: float = 0.0

    def to_dict(self) -> dict:
        return {
            "from_address": self.from_address,
            "round": self.round,
            "prev_round": self.prev_round,
            "prev_sig": self.prev_sig.hex(),
            "partial_sig": self.partial_sig.hex(),
            "trace_id": self.trace_id,
            "sent_at": self.sent_at,
        }

    @classmethod
    def from_dict(cls, d: dict) -> "BeaconPacket":
        return cls(
            from_address=d["from_address"],
            round=int(d["round"]),
            prev_round=int(d["prev_round"]),
            prev_sig=bytes.fromhex(d["prev_sig"]),
            partial_sig=bytes.fromhex(d["partial_sig"]),
            trace_id=d.get("trace_id", ""),
            sent_at=float(d.get("sent_at", 0.0)),
        )


class ProtocolClient:
    """Outbound protocol-plane transport (gRPC, loopback, or sim fabric)."""

    async def new_beacon(self, peer: "Identity",
                         packet: BeaconPacket) -> None:
        raise NotImplementedError

    def sync_chain(self, peer: "Identity",
                   from_round: int) -> "AsyncIterator[Beacon]":
        raise NotImplementedError
