"""Single-port gRPC + REST demultiplexer (the reference's cmux).

The reference serves gRPC and JSON/REST on ONE public port: cmux sniffs
the connection for insecure listeners and an http.Handler dispatches on
the h2 content-type for TLS listeners
(/root/reference/net/listener_grpc.go:23-97,230-242).

Here the same capability is an asyncio front listener: every accepted
connection is classified by its first bytes — an HTTP/2 client
connection preface (``PRI * HTTP/2.0``) means gRPC, anything else is
HTTP/1.x for the REST gateway — and then spliced byte-for-byte onto the
matching loopback backend.  With TLS, the mux terminates the handshake
itself (ALPN h2 + http/1.1, which gRPC clients require) and forwards
plaintext; the backends bind 127.0.0.1 only.
"""

from __future__ import annotations

import asyncio
from typing import Optional, Set

#: HTTP/2 client connection preface, RFC 7540 §3.5.  gRPC always opens
#: with it; no HTTP/1.x method shares the first four bytes.
_H2_PREFACE_HEAD = b"PRI "


class MuxServer:
    """Front listener splicing connections to gRPC / REST backends."""

    def __init__(self, server: asyncio.base_events.Server,
                 tasks: Set[asyncio.Task]):
        self._server = server
        self._tasks = tasks

    @property
    def port(self) -> int:
        return self._server.sockets[0].getsockname()[1]

    async def cleanup(self) -> None:
        """Close the listener and all spliced connections (duck-typed to
        slot into Drand._servers next to aiohttp runners)."""
        self._server.close()
        await self._server.wait_closed()
        for t in list(self._tasks):
            t.cancel()
        if self._tasks:
            await asyncio.gather(*self._tasks, return_exceptions=True)


async def _splice(reader: asyncio.StreamReader,
                  writer: asyncio.StreamWriter) -> None:
    try:
        while True:
            data = await reader.read(1 << 16)
            if not data:
                break
            writer.write(data)
            await writer.drain()
    except (OSError, asyncio.IncompleteReadError):
        # OSError covers ssl.SSLError: an unclean TLS abort (no
        # close_notify) must not surface as an unretrieved task exception
        pass
    finally:
        # propagate FIN so half-closed gRPC/HTTP streams finish cleanly
        try:
            if writer.can_write_eof():
                writer.write_eof()
        except (OSError, RuntimeError):
            pass


async def _close(writer: asyncio.StreamWriter) -> None:
    try:
        writer.close()
        await writer.wait_closed()
    except (ConnectionError, OSError):
        pass


async def start_mux(port: int, grpc_port: int, rest_port: int,
                    host: str = "0.0.0.0",
                    ssl_context=None,
                    sniff_timeout: float = 10.0) -> MuxServer:
    """Serve `port`, splicing gRPC to 127.0.0.1:grpc_port and everything
    else to 127.0.0.1:rest_port.  `ssl_context` (server-side, ALPN is
    configured here) makes the single port TLS like the reference's
    NewTLSGrpcListener."""
    if ssl_context is not None:
        # server-preference order matters: OpenSSL selects the FIRST
        # server protocol the client also offers.  http/1.1 first sends
        # browsers/curl (which offer both h2 and http/1.1) to the REST
        # plane, while gRPC clients offer ONLY h2 and still negotiate it
        # — without this ordering every h2-capable HTTP client would
        # sniff as gRPC and never reach /api or /web.
        ssl_context.set_alpn_protocols(["http/1.1", "h2"])
    tasks: Set[asyncio.Task] = set()

    async def handle(reader: asyncio.StreamReader,
                     writer: asyncio.StreamWriter) -> None:
        try:
            # readexactly: a preface split across TCP segments/TLS records
            # must not be classified on a short read
            head = await asyncio.wait_for(
                reader.readexactly(4), timeout=sniff_timeout
            )
        except (asyncio.TimeoutError, asyncio.IncompleteReadError,
                OSError):
            await _close(writer)
            return
        backend = grpc_port if head == _H2_PREFACE_HEAD else rest_port
        try:
            br, bw = await asyncio.open_connection("127.0.0.1", backend)
        except OSError:
            await _close(writer)
            return
        bw.write(head)
        up = asyncio.ensure_future(_splice(reader, bw))
        down = asyncio.ensure_future(_splice(br, writer))
        try:
            # once the backend stops sending, the response is complete.
            # TLS transports cannot half-close (can_write_eof() is
            # False), so a client reading to EOF would wait forever on
            # an EOF the mux cannot send — stop splicing and fully close.
            await down
            try:
                half_close = writer.can_write_eof()
            except (OSError, RuntimeError):
                half_close = False
            if not half_close:
                up.cancel()
            await asyncio.gather(up, down, return_exceptions=True)
        finally:
            await _close(bw)
            await _close(writer)

    def track(reader, writer):
        t = asyncio.ensure_future(handle(reader, writer))
        tasks.add(t)
        t.add_done_callback(tasks.discard)

    server = await asyncio.start_server(
        track, host, port, ssl=ssl_context
    )
    return MuxServer(server, tasks)
