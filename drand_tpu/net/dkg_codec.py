"""Typed wire codec for DKG packets.

Round 1 shipped deals/responses as JSON blobs inside a bytes field; the
reference carries typed proto messages
(/root/reference/protobuf/crypto/dkg/dkg.proto:210-248, justification at
protobuf/crypto/vss/vss.proto:60-69).  This codec maps the engine's
in-memory packet dicts (drand_tpu.dkg.pedersen to_dict/from_dict forms)
onto the typed `DKGPacketMsg` oneof, so the wire schema is
self-describing and length-checked by protobuf instead of free-form
JSON.
"""

from __future__ import annotations

from drand_tpu.net import drand_tpu_pb2 as pb


class CodecError(ValueError):
    pass


def packet_to_msg(packet: dict, group_hash: bytes) -> "pb.DKGPacketMsg":
    """Engine packet dict -> typed wire message."""
    msg = pb.DKGPacketMsg(group_hash=group_hash)
    if "dkg_deal" in packet:
        d = packet["dkg_deal"]
        msg.deal.CopyFrom(pb.DealMsg(
            dealer_index=int(d["dealer_index"]),
            recipient_index=int(d["recipient_index"]),
            commits=[bytes.fromhex(h) for h in d["commits"]],
            encrypted_share=bytes.fromhex(d["encrypted_share"]),
            signature=bytes.fromhex(d.get("signature", "")),
        ))
    elif "dkg_response" in packet:
        r = packet["dkg_response"]
        msg.response.CopyFrom(pb.ResponseMsg(
            dealer_index=int(r["dealer_index"]),
            verifier_index=int(r["verifier_index"]),
            approved=bool(r["approved"]),
            signature=bytes.fromhex(r.get("signature", "")),
        ))
    elif "dkg_justification" in packet:
        j = packet["dkg_justification"]
        msg.justification.CopyFrom(pb.JustificationMsg(
            dealer_index=int(j["dealer_index"]),
            verifier_index=int(j["verifier_index"]),
            share_value=bytes.fromhex(j["share_value"]),
            commits=[bytes.fromhex(h) for h in j["commits"]],
            signature=bytes.fromhex(j.get("signature", "")),
        ))
    else:
        raise CodecError(f"unknown DKG packet keys: {sorted(packet)}")
    return msg


def msg_to_packet(msg: "pb.DKGPacketMsg") -> dict:
    """Typed wire message -> engine packet dict."""
    body = msg.WhichOneof("body")
    if body == "deal":
        d = msg.deal
        return {"dkg_deal": {
            "dealer_index": d.dealer_index,
            "recipient_index": d.recipient_index,
            "commits": [c.hex() for c in d.commits],
            "encrypted_share": d.encrypted_share.hex(),
            "signature": d.signature.hex(),
        }}
    if body == "response":
        r = msg.response
        return {"dkg_response": {
            "dealer_index": r.dealer_index,
            "verifier_index": r.verifier_index,
            "approved": r.approved,
            "signature": r.signature.hex(),
        }}
    if body == "justification":
        j = msg.justification
        if len(j.share_value) != 32:
            raise CodecError("justification share must be 32 bytes")
        return {"dkg_justification": {
            "dealer_index": j.dealer_index,
            "verifier_index": j.verifier_index,
            "share_value": j.share_value.hex(),
            "commits": [c.hex() for c in j.commits],
            "signature": j.signature.hex(),
        }}
    raise CodecError("DKG packet carries no body")
