"""REST gateway: JSON views of the public API over aiohttp.

Mirrors the reference's grpc-gateway with hex-JSON marshalling
(/root/reference/net/listener_grpc.go + net/json_marshaller.go):

  GET  /api/public            latest beacon
  GET  /api/public/{round}    beacon by round
  POST /api/private           ECIES private randomness
  GET  /api/info/group        group TOML
  GET  /api/info/distkey      collective key coefficients
  GET  /metrics               Prometheus metrics (beyond the reference,
                              which has no observability endpoints)
  GET  /                      home/status

Divergence from the reference: the reference cmux-shares one port between
gRPC and REST; here REST listens on its own port (core.Config.rest_port).
"""

from __future__ import annotations

from aiohttp import web


def build_rest_app(daemon) -> web.Application:
    routes = web.RouteTableDef()

    def beacon_json(b):
        return {
            "round": b.round,
            "previous_round": b.prev_round,
            "previous": b.prev_sig.hex(),
            "signature": b.signature.hex(),
            "randomness": b.randomness().hex(),
        }

    @routes.get("/")
    async def home(request):
        return web.json_response({"status": daemon.home_status()})

    @routes.get("/api/public")
    async def latest(request):
        try:
            b = daemon.fetch_public_rand(0)
        except KeyError as exc:
            raise web.HTTPNotFound(text=str(exc))
        return web.json_response(beacon_json(b))

    @routes.get("/api/public/{round}")
    async def by_round(request):
        try:
            rnd = int(request.match_info["round"])
        except ValueError:
            raise web.HTTPBadRequest(text="round must be an integer")
        try:
            b = daemon.fetch_public_rand(rnd)
        except KeyError as exc:
            raise web.HTTPNotFound(text=str(exc))
        return web.json_response(beacon_json(b))

    @routes.post("/api/private")
    async def private(request):
        body = await request.json()
        try:
            blob = bytes.fromhex(body.get("request", ""))
            out = daemon.serve_private_rand(blob)
        except Exception as exc:
            raise web.HTTPBadRequest(text=str(exc))
        return web.json_response({"response": out.hex()})

    @routes.get("/api/info/group")
    async def group(request):
        toml = daemon.group_toml()
        if toml is None:
            raise web.HTTPNotFound(text="no group configured")
        return web.Response(text=toml, content_type="application/toml")

    @routes.get("/metrics")
    async def metrics_endpoint(request):
        from drand_tpu.utils import metrics

        return web.Response(
            text=metrics.render(),
            content_type="text/plain",
            charset="utf-8",
        )

    @routes.get("/api/info/distkey")
    async def distkey(request):
        try:
            coeffs = daemon.collective_key_hex()
        except Exception as exc:
            raise web.HTTPNotFound(text=str(exc))
        return web.json_response({"coefficients": coeffs})

    app = web.Application()
    app.add_routes(routes)
    return app


async def start_rest(app: web.Application, port: int,
                     host: str = "0.0.0.0",
                     ssl_context=None) -> web.AppRunner:
    """Serve the gateway; pass an `ssl.SSLContext` to serve HTTPS (the
    reference serves REST through the same TLS listener as gRPC,
    net/listener_grpc.go:108-168 — here it is the same certificate on
    the REST port)."""
    runner = web.AppRunner(app)
    await runner.setup()
    site = web.TCPSite(runner, host, port, ssl_context=ssl_context)
    await site.start()
    return runner
