"""REST gateway: JSON views of the public API over aiohttp.

Mirrors the reference's grpc-gateway with hex-JSON marshalling
(/root/reference/net/listener_grpc.go + net/json_marshaller.go):

  GET  /api/public            latest beacon
  GET  /api/public/{round}    beacon by round
  POST /api/private           ECIES private randomness
  GET  /api/info/group        group TOML
  GET  /api/info/distkey      collective key coefficients
  GET  /metrics               Prometheus metrics (beyond the reference,
                              which has no observability endpoints)
  GET  /                      home/status

Divergence from the reference: the reference cmux-shares one port between
gRPC and REST; here REST listens on its own port (core.Config.rest_port).
"""

from __future__ import annotations

from aiohttp import web

#: Live beacon dashboard (TPU-native stand-in for the reference's Hugo
#: site under /root/reference/web/ — there it is a static marketing/docs
#: site; here the useful part: watch the chain advance, inspect the
#: group, fetch any round, all against the node's own REST API).
_DASHBOARD_HTML = """<!doctype html>
<html><head><meta charset="utf-8"><title>drand-tpu</title>
<style>
 body{font-family:ui-monospace,Menlo,monospace;background:#101418;
      color:#d7dde3;max-width:60rem;margin:2rem auto;padding:0 1rem}
 h1{font-size:1.2rem} .k{color:#7da7d9} .v{word-break:break-all}
 table{border-collapse:collapse;width:100%} td{padding:.25rem .5rem;
 border-bottom:1px solid #2a3138;vertical-align:top}
 input{background:#1a2026;color:inherit;border:1px solid #2a3138;
 padding:.25rem .5rem} .err{color:#e08080}
</style></head><body>
<h1>drand-tpu beacon</h1>
<table id="t"><tr><td class="k">status</td><td class="v" id="s">connecting…
</td></tr></table>
<p>round: <input id="r" size="10" placeholder="latest">
<button onclick="load()">fetch</button></p>
<script>
async function j(p){const r=await fetch(p);if(!r.ok)throw new Error(
  r.status+" "+await r.text());return r.json()}
function row(k,v){return '<tr><td class="k">'+k+'</td><td class="v">'+v+
  '</td></tr>'}
async function load(){
  const t=document.getElementById('t'),n=document.getElementById('r').value;
  try{
    const b=await j(n?'/api/public/'+n:'/api/public');
    let h=row('round',b.round)+row('randomness',b.randomness)+
          row('signature',b.signature)+row('previous round',
          b.previous_round)+row('previous sig',b.previous);
    try{const d=await j('/api/info/distkey');
        h+=row('collective key',d.coefficients[0])}catch(e){}
    t.innerHTML=h;
  }catch(e){t.innerHTML=row('status','<span class="err">'+e+'</span>')}
}
load();setInterval(()=>{if(!document.getElementById('r').value)load()},2000);
</script></body></html>
"""


def build_rest_app(daemon) -> web.Application:
    routes = web.RouteTableDef()

    def beacon_json(b):
        return {
            "round": b.round,
            "previous_round": b.prev_round,
            "previous": b.prev_sig.hex(),
            "signature": b.signature.hex(),
            "randomness": b.randomness().hex(),
        }

    @routes.get("/")
    async def home(request):
        return web.json_response({"status": daemon.home_status()})

    @routes.get("/api/public")
    async def latest(request):
        try:
            b = daemon.fetch_public_rand(0)
        except KeyError as exc:
            raise web.HTTPNotFound(text=str(exc))
        return web.json_response(beacon_json(b))

    @routes.get("/api/public/{round}")
    async def by_round(request):
        try:
            rnd = int(request.match_info["round"])
        except ValueError:
            raise web.HTTPBadRequest(text="round must be an integer")
        try:
            b = daemon.fetch_public_rand(rnd)
        except KeyError as exc:
            raise web.HTTPNotFound(text=str(exc))
        return web.json_response(beacon_json(b))

    @routes.post("/api/private")
    async def private(request):
        body = await request.json()
        try:
            blob = bytes.fromhex(body.get("request", ""))
            out = daemon.serve_private_rand(blob)
        except Exception as exc:
            raise web.HTTPBadRequest(text=str(exc))
        return web.json_response({"response": out.hex()})

    @routes.get("/api/info/group")
    async def group(request):
        toml = daemon.group_toml()
        if toml is None:
            raise web.HTTPNotFound(text="no group configured")
        return web.Response(text=toml, content_type="application/toml")

    @routes.get("/metrics")
    async def metrics_endpoint(request):
        from drand_tpu.utils import metrics

        return web.Response(
            text=metrics.render(),
            content_type="text/plain",
            charset="utf-8",
        )

    @routes.get("/api/info/distkey")
    async def distkey(request):
        try:
            coeffs = daemon.collective_key_hex()
        except Exception as exc:
            raise web.HTTPNotFound(text=str(exc))
        return web.json_response({"coefficients": coeffs})

    @routes.get("/web")
    async def dashboard(request):
        return web.Response(text=_DASHBOARD_HTML,
                            content_type="text/html", charset="utf-8")

    app = web.Application()
    app.add_routes(routes)
    return app


async def start_rest(app: web.Application, port: int,
                     host: str = "0.0.0.0",
                     ssl_context=None):
    """Serve the gateway; pass an `ssl.SSLContext` to serve HTTPS (the
    reference serves REST through the same TLS listener as gRPC,
    net/listener_grpc.go:108-168 — with `core.Config.mux_port` that is
    literally the same port; standalone it is the same certificate on
    the REST port).  Returns ``(runner, bound_port)``."""
    runner = web.AppRunner(app)
    await runner.setup()
    site = web.TCPSite(runner, host, port, ssl_context=ssl_context)
    await site.start()
    bound = runner.addresses[0][1]
    return runner, bound
