"""REST gateway: JSON views of the public API over aiohttp.

Mirrors the reference's grpc-gateway with hex-JSON marshalling
(/root/reference/net/listener_grpc.go + net/json_marshaller.go):

  GET  /api/public            latest beacon
  GET  /api/public/{round}    beacon by round
  POST /api/private           ECIES private randomness
  GET  /api/info/group        group TOML
  GET  /api/info/distkey      collective key coefficients
  POST /v1/verify             batched beacon verification through the
                              serve/ gateway (single claim or
                              {"items": [...]}; 429 on shed, 504 on
                              deadline — never silent queueing)
  GET  /metrics               Prometheus metrics (beyond the reference,
                              which has no observability endpoints)
  GET  /                      home/status

Divergence from the reference: the reference cmux-shares one port between
gRPC and REST; here REST listens on its own port (core.Config.rest_port).
"""

from __future__ import annotations

from aiohttp import web

#: Live beacon dashboard (TPU-native stand-in for the reference's Hugo
#: site under /root/reference/web/ — there it is a static marketing/docs
#: site; here the useful part: watch the chain advance, inspect the
#: group, fetch any round, all against the node's own REST API).
_DASHBOARD_HTML = """<!doctype html>
<html><head><meta charset="utf-8"><title>drand-tpu</title>
<style>
 body{font-family:ui-monospace,Menlo,monospace;background:#101418;
      color:#d7dde3;max-width:60rem;margin:2rem auto;padding:0 1rem}
 h1{font-size:1.2rem} .k{color:#7da7d9} .v{word-break:break-all}
 table{border-collapse:collapse;width:100%} td{padding:.25rem .5rem;
 border-bottom:1px solid #2a3138;vertical-align:top}
 input{background:#1a2026;color:inherit;border:1px solid #2a3138;
 padding:.25rem .5rem} .err{color:#e08080}
</style></head><body>
<h1>drand-tpu beacon</h1>
<table id="t"><tr><td class="k">status</td><td class="v" id="s">connecting…
</td></tr></table>
<p>round: <input id="r" size="10" placeholder="latest">
<button onclick="load()">fetch</button></p>
<script>
async function j(p){const r=await fetch(p);if(!r.ok)throw new Error(
  r.status+" "+await r.text());return r.json()}
function row(k,v){return '<tr><td class="k">'+k+'</td><td class="v">'+v+
  '</td></tr>'}
async function load(){
  const t=document.getElementById('t'),n=document.getElementById('r').value;
  try{
    const b=await j(n?'/api/public/'+n:'/api/public');
    let h=row('round',b.round)+row('randomness',b.randomness)+
          row('signature',b.signature)+row('previous round',
          b.previous_round)+row('previous sig',b.previous);
    try{const d=await j('/api/info/distkey');
        h+=row('collective key',d.coefficients[0])}catch(e){}
    t.innerHTML=h;
  }catch(e){t.innerHTML=row('status','<span class="err">'+e+'</span>')}
}
load();setInterval(()=>{if(!document.getElementById('r').value)load()},2000);
</script></body></html>
"""


def _parse_verify_claim(j: dict):
    from drand_tpu.serve import VerifyRequest

    try:
        # "previous_signature" matches the gRPC VerifyBeaconRequest field;
        # "previous" is accepted as the short REST-ism
        prev = j.get("previous_signature", j.get("previous", ""))
        return VerifyRequest(
            round=int(j["round"]),
            prev_round=int(j.get("previous_round", 0)),
            prev_sig=bytes.fromhex(prev),
            signature=bytes.fromhex(j["signature"]),
        )
    except (KeyError, TypeError, ValueError) as exc:
        raise web.HTTPBadRequest(
            text=f"bad verify claim: {exc!r}"
        ) from None


def _verify_result_json(res) -> dict:
    return {"valid": res.valid, "cached": res.cached,
            "batch_size": res.batch_size}


def _shed_body(error: str, exc) -> str:
    """JSON body for an explicit gateway rejection: the reason, the
    human detail, and — when the gateway stamped one — the request
    span's trace id, so a shed client can pull its own trace from
    `/debug/traces` instead of filing an anonymous 429."""
    import json

    body = {"error": error, "detail": str(exc)}
    tid = getattr(exc, "trace_id", None)
    if tid:
        body["trace_id"] = tid
    return json.dumps(body)


async def handle_verify(gateway, request):
    """POST /v1/verify body: one claim {round, previous_round, previous,
    signature[, timeout]} -> {valid, cached, batch_size}; or
    {"items": [claim, ...][, timeout]} -> {"items": [...]} where a shed/
    expired item carries {"error": ...} instead of a verdict.  Explicit
    backpressure: HTTP 429 when the queue sheds, 504 when the deadline
    passes — a claim is never silently served late."""
    from drand_tpu import serve

    try:
        body = await request.json()
    except Exception:
        raise web.HTTPBadRequest(text="body must be JSON")
    if not isinstance(body, dict):
        raise web.HTTPBadRequest(text="body must be a JSON object")
    timeout = body.get("timeout")
    if timeout is not None:
        try:
            timeout = float(timeout)
        except (TypeError, ValueError):
            raise web.HTTPBadRequest(text="timeout must be a number")

    # caller identity for per-client metrics: explicit header first,
    # socket peer otherwise; trace header joins a distributed trace
    client = request.headers.get("X-Client-Id") or request.remote
    trace_id = request.headers.get("X-Trace-Id", "")
    # ring forward-once marker: set by a sibling replica — the owner
    # serves locally and never re-forwards (no routing loops)
    forwarded = request.headers.get("X-Drand-Forwarded") is not None

    if "items" in body:
        reqs = [_parse_verify_claim(j) for j in body["items"]]
        results = await gateway.verify_many(reqs, timeout, client=client)
        items = []
        for res in results:
            if isinstance(res, serve.Oversize):
                err = {"error": "oversize"}
            elif isinstance(res, serve.Overloaded):
                err = {"error": "overloaded"}
            elif isinstance(res, serve.DeadlineExceeded):
                err = {"error": "deadline exceeded"}
            elif isinstance(res, BaseException):
                raise res
            else:
                items.append(_verify_result_json(res))
                continue
            tid = getattr(res, "trace_id", None)
            if tid:
                err["trace_id"] = tid
            items.append(err)
        return web.json_response({"items": items})

    req = _parse_verify_claim(body)
    try:
        res = await gateway.verify(req, timeout, client=client,
                                   trace_id=trace_id or None,
                                   forwarded=forwarded)
    except serve.Oversize as exc:
        raise web.HTTPRequestEntityTooLarge(
            max_size=exc.limit, actual_size=exc.actual,
            text=_shed_body("oversize", exc),
            content_type="application/json",
        )
    except serve.Overloaded as exc:
        raise web.HTTPTooManyRequests(
            text=_shed_body("overloaded", exc),
            content_type="application/json",
            headers={"Retry-After": "1"},
        )
    except serve.DeadlineExceeded as exc:
        raise web.HTTPGatewayTimeout(
            text=_shed_body("deadline exceeded", exc),
            content_type="application/json",
        )
    except serve.GatewayClosed as exc:
        raise web.HTTPServiceUnavailable(
            text=_shed_body("closed", exc),
            content_type="application/json",
        )
    return web.json_response(_verify_result_json(res))


def _dumps_repr(obj) -> str:
    import json

    return json.dumps(obj, default=repr)


def _profile_authorized(request) -> bool:
    """`POST /debug/profile` is control-plane surface: device profiling
    costs real throughput, so it is limited to loopback callers unless
    the operator set `DRAND_TPU_PROFILE_TOKEN` and the caller presents
    it in `X-Drand-Profile-Token`."""
    import os

    token = os.environ.get("DRAND_TPU_PROFILE_TOKEN")
    if token and request.headers.get("X-Drand-Profile-Token") == token:
        return True
    return request.remote in ("127.0.0.1", "::1", "localhost", None)


def _add_obs_routes(routes: web.RouteTableDef, status_fn,
                    slo_fn=None) -> None:
    """Introspection surface shared by both apps: health JSON, SLO
    document, perf baselines, recent traces, the live flight-recorder
    buffer and on-demand device profiling."""
    from drand_tpu.obs import flight, perf, profile, slo, trace

    @routes.get("/v1/status")
    async def status(request):
        return web.json_response(status_fn())

    @routes.get("/v1/slo")
    async def slo_doc(request):
        fn = slo_fn or slo.ENGINE.snapshot
        return web.json_response(fn())

    @routes.get("/v1/perf")
    async def perf_doc(request):
        # streaming per-stage/per-kernel latency baselines + per-round
        # dispatch accounting (the /v1/status "perf" section, standalone)
        return web.json_response(perf.snapshot(), dumps=_dumps_repr)

    @routes.post("/debug/profile")
    async def profile_start(request):
        if not _profile_authorized(request):
            raise web.HTTPForbidden(
                text="profiling is loopback/token gated"
            )
        try:
            seconds = float(
                request.query.get("seconds", profile.DEFAULT_SECONDS)
            )
        except ValueError:
            raise web.HTTPBadRequest(text="seconds must be a number")
        result = await profile.CAPTURE.capture(seconds)
        return web.json_response(result, dumps=_dumps_repr)

    @routes.get("/debug/profile")
    async def profile_status(request):
        return web.json_response(profile.CAPTURE.status(),
                                 dumps=_dumps_repr)

    @routes.get("/debug/traces")
    async def traces(request):
        if "round" in request.query:
            try:
                rnd = int(request.query["round"])
            except ValueError:
                raise web.HTTPBadRequest(text="round must be an integer")
            return web.json_response(
                {"traces": trace.TRACER.find_round(rnd)}
            )
        try:
            limit = int(request.query.get("limit", "20"))
        except ValueError:
            raise web.HTTPBadRequest(text="limit must be an integer")
        # deterministic contract: most-recently-updated trace first,
        # at most `limit` of them (tests/test_obs_trace.py pins this)
        return web.json_response(
            {"traces": trace.TRACER.recent(max(0, limit))}
        )

    @routes.get("/debug/flight")
    async def flight_dump(request):
        return web.Response(text=flight.RECORDER.dump(),
                            content_type="application/json")


def build_verify_app(gateway) -> web.Application:
    """Standalone verification-gateway app (`cli.py verify-serve`): just
    /v1/verify, /metrics, the obs surface and a status page — no daemon
    behind it."""
    routes = web.RouteTableDef()

    @routes.get("/")
    async def home(request):
        return web.json_response({
            "status": "verify gateway",
            "backend": type(gateway.scheme).__name__,
            "cache_entries": len(gateway.cache),
        })

    @routes.post("/v1/verify")
    async def verify(request):
        return await handle_verify(gateway, request)

    @routes.get("/metrics")
    async def metrics_endpoint(request):
        from drand_tpu.utils import metrics

        return web.Response(text=metrics.render(),
                            content_type="text/plain", charset="utf-8")

    _add_obs_routes(routes, gateway.stats)

    app = web.Application()
    app.add_routes(routes)
    return app


def build_fleet_app(aggregator) -> web.Application:
    """Fleet observatory app (`cli fleet --serve`): one aggregated view
    over N nodes' status/SLO documents plus this process's metrics
    (which include the `drand_fleet_*` and `drand_watch_*` series)."""
    routes = web.RouteTableDef()

    @routes.get("/")
    async def home(request):
        return web.json_response({
            "status": "fleet observatory",
            "nodes": sorted(aggregator.sources),
        })

    @routes.get("/v1/fleet")
    async def fleet_doc(request):
        doc = await aggregator.poll()
        return web.json_response(doc, dumps=_dumps_repr)

    @routes.get("/metrics")
    async def metrics_endpoint(request):
        from drand_tpu.utils import metrics

        return web.Response(text=metrics.render(),
                            content_type="text/plain", charset="utf-8")

    app = web.Application()
    app.add_routes(routes)
    return app


def build_rest_app(daemon) -> web.Application:
    routes = web.RouteTableDef()

    def beacon_json(b):
        return {
            "round": b.round,
            "previous_round": b.prev_round,
            "previous": b.prev_sig.hex(),
            "signature": b.signature.hex(),
            "randomness": b.randomness().hex(),
        }

    @routes.get("/")
    async def home(request):
        return web.json_response({"status": daemon.home_status()})

    @routes.get("/api/public")
    async def latest(request):
        try:
            b = daemon.fetch_public_rand(0)
        except KeyError as exc:
            raise web.HTTPNotFound(text=str(exc))
        return web.json_response(beacon_json(b))

    @routes.get("/api/public/{round}")
    async def by_round(request):
        try:
            rnd = int(request.match_info["round"])
        except ValueError:
            raise web.HTTPBadRequest(text="round must be an integer")
        try:
            b = daemon.fetch_public_rand(rnd)
        except KeyError as exc:
            raise web.HTTPNotFound(text=str(exc))
        return web.json_response(beacon_json(b))

    @routes.post("/api/private")
    async def private(request):
        body = await request.json()
        try:
            blob = bytes.fromhex(body.get("request", ""))
            out = daemon.serve_private_rand(blob)
        except Exception as exc:
            raise web.HTTPBadRequest(text=str(exc))
        return web.json_response({"response": out.hex()})

    @routes.get("/api/info/group")
    async def group(request):
        toml = daemon.group_toml()
        if toml is None:
            raise web.HTTPNotFound(text="no group configured")
        return web.Response(text=toml, content_type="application/toml")

    @routes.post("/v1/verify")
    async def verify(request):
        try:
            gateway = await daemon.verify_gateway()
        except RuntimeError as exc:
            raise web.HTTPServiceUnavailable(text=str(exc))
        return await handle_verify(gateway, request)

    @routes.get("/metrics")
    async def metrics_endpoint(request):
        from drand_tpu.utils import metrics

        return web.Response(
            text=metrics.render(),
            content_type="text/plain",
            charset="utf-8",
        )

    @routes.get("/api/info/distkey")
    async def distkey(request):
        try:
            coeffs = daemon.collective_key_hex()
        except Exception as exc:
            raise web.HTTPNotFound(text=str(exc))
        return web.json_response({"coefficients": coeffs})

    @routes.get("/web")
    async def dashboard(request):
        return web.Response(text=_DASHBOARD_HTML,
                            content_type="text/html", charset="utf-8")

    def _status() -> dict:
        # daemon is duck-typed here (test stubs, partially-booted
        # daemons): fall back to the introspector, which guards every
        # attribute itself
        fn = getattr(daemon, "status_json", None)
        if fn is not None:
            return fn()
        from drand_tpu.obs.introspect import daemon_status

        return daemon_status(daemon)

    _add_obs_routes(routes, _status,
                    slo_fn=getattr(daemon, "slo_json", None))

    app = web.Application()
    app.add_routes(routes)
    return app


async def start_rest(app: web.Application, port: int,
                     host: str = "0.0.0.0",
                     ssl_context=None):
    """Serve the gateway; pass an `ssl.SSLContext` to serve HTTPS (the
    reference serves REST through the same TLS listener as gRPC,
    net/listener_grpc.go:108-168 — with `core.Config.mux_port` that is
    literally the same port; standalone it is the same certificate on
    the REST port).  Returns ``(runner, bound_port)``."""
    runner = web.AppRunner(app)
    await runner.setup()
    site = web.TCPSite(runner, host, port, ssl_context=ssl_context)
    await site.start()
    bound = runner.addresses[0][1]
    return runner, bound
