"""TLS material: self-signed certificate generation + trust pool.

Mirrors /root/reference/net/certs.go (CertManager seeded with manually
added PEMs for self-signed deployments) and the reference's use of
kabukky/httpscerts to fabricate test certificates
(core/drand_test.go:577-590).
"""

from __future__ import annotations

import datetime
import ipaddress
from pathlib import Path
from typing import List, Optional, Tuple

try:  # optional dependency: only needed to MINT certificates
    from cryptography import x509
    from cryptography.hazmat.primitives import hashes, serialization
    from cryptography.hazmat.primitives.asymmetric import ec
    from cryptography.x509.oid import NameOID
except ModuleNotFoundError:  # insecure/plaintext deployments don't need it
    x509 = None


def generate_self_signed(host: str,
                         common_name: Optional[str] = None
                         ) -> Tuple[bytes, bytes]:
    """Return (cert_pem, key_pem) for a host ('127.0.0.1' or DNS name).

    `common_name` should be UNIQUE per node when many self-signed certs
    share one trust pool: issuer lookup is by subject name, and several
    roots with identical names make the TLS stack pick an arbitrary one
    (handshakes then fail with CERTIFICATE_VERIFY_FAILED).
    """
    if x509 is None:
        raise RuntimeError(
            "TLS certificate generation needs the 'cryptography' package"
        )
    key = ec.generate_private_key(ec.SECP256R1())
    name = x509.Name(
        [x509.NameAttribute(NameOID.COMMON_NAME, common_name or host)]
    )
    try:
        san: x509.GeneralName = x509.IPAddress(
            ipaddress.ip_address(host)
        )
    except ValueError:
        san = x509.DNSName(host)
    now = datetime.datetime.now(datetime.timezone.utc)
    cert = (
        x509.CertificateBuilder()
        .subject_name(name)
        .issuer_name(name)
        .public_key(key.public_key())
        .serial_number(x509.random_serial_number())
        .not_valid_before(now - datetime.timedelta(minutes=5))
        .not_valid_after(now + datetime.timedelta(days=365))
        .add_extension(
            x509.SubjectAlternativeName([san]), critical=False
        )
        .sign(key, hashes.SHA256())
    )
    cert_pem = cert.public_bytes(serialization.Encoding.PEM)
    key_pem = key.private_bytes(
        serialization.Encoding.PEM,
        serialization.PrivateFormat.PKCS8,
        serialization.NoEncryption(),
    )
    return cert_pem, key_pem


class CertManager:
    """Trust pool of PEM roots for dialing TLS peers."""

    def __init__(self):
        self._pems: List[bytes] = []

    def add(self, cert_pem: bytes) -> None:
        self._pems.append(cert_pem)

    def add_file(self, path: str) -> None:
        self.add(Path(path).read_bytes())

    def pool(self) -> Optional[bytes]:
        """Concatenated PEM bundle (None = system roots)."""
        if not self._pems:
            return None
        return b"".join(self._pems)
