"""Networking: gRPC services, TLS, REST gateway, control plane.

Equivalent of the reference's `net/` package: `Gateway` (public gRPC+REST
listener), `ControlListener` (localhost control port), connection-cached
clients, and the certificate manager (/root/reference/net/).

Attribute access is lazy (PEP 562): `net/transport.py` imports grpc and
the generated protobufs, which the dependency-free consumers of
`net/interface.py` (the beacon handler, the simulator) must not pay
for — or cycle through, since transport itself imports the handler's
packet types from the interface module.
"""

_LAZY = {
    "ControlClient": "drand_tpu.net.transport",
    "GrpcClient": "drand_tpu.net.transport",
    "build_control_server": "drand_tpu.net.transport",
    "build_public_server": "drand_tpu.net.transport",
    "CertManager": "drand_tpu.net.tls",
    "generate_self_signed": "drand_tpu.net.tls",
    "BeaconPacket": "drand_tpu.net.interface",
    "ProtocolClient": "drand_tpu.net.interface",
}

__all__ = sorted(_LAZY)


def __getattr__(name: str):
    target = _LAZY.get(name)
    if target is None:
        raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
    import importlib

    mod = importlib.import_module(target)
    value = getattr(mod, name)
    globals()[name] = value  # cache for the next access
    return value
