"""Networking: gRPC services, TLS, REST gateway, control plane.

Equivalent of the reference's `net/` package: `Gateway` (public gRPC+REST
listener), `ControlListener` (localhost control port), connection-cached
clients, and the certificate manager (/root/reference/net/)."""

from drand_tpu.net.transport import (  # noqa: F401
    ControlClient,
    GrpcClient,
    build_control_server,
    build_public_server,
)
from drand_tpu.net.tls import CertManager, generate_self_signed  # noqa: F401
