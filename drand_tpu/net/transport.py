"""gRPC transport: hand-wired servicers and connection-cached clients.

Mirrors /root/reference/net/client_grpc.go (per-call deadlines, cached
channels, streaming sync) and net/listener_grpc.go / net/control.go (the
public gateway and the localhost-only control listener).  Method handlers
are registered through grpc's generic-handler API because only protoc's
message codegen is available in this environment — the service surface is
defined by the `_METHODS` tables below.
"""

from __future__ import annotations

import asyncio
from typing import AsyncIterator, Dict, Optional

import grpc
import grpc.aio

from drand_tpu.beacon.chain import Beacon
from drand_tpu.net.interface import BeaconPacket, ProtocolClient
from drand_tpu.key import Identity
from drand_tpu.net import dkg_codec
from drand_tpu.net import drand_tpu_pb2 as pb
from drand_tpu.net.tls import CertManager

# The reference uses a 1s per-RPC deadline (beacon/beacon.go:89); ours is
# longer because peers may be busy in Python crypto on small hosts.
RPC_TIMEOUT = 5.0
CONTROL_TIMEOUT = 10.0

PUBLIC_SERVICE = "drandtpu.Public"
PROTOCOL_SERVICE = "drandtpu.Protocol"
CONTROL_SERVICE = "drandtpu.Control"


def _beacon_to_record(b: Beacon) -> pb.BeaconRecord:
    return pb.BeaconRecord(
        round=b.round,
        previous_round=b.prev_round,
        previous_signature=b.prev_sig,
        signature=b.signature,
    )


def _record_to_beacon(r: pb.BeaconRecord) -> Beacon:
    return Beacon(
        round=r.round,
        prev_round=r.previous_round,
        prev_sig=r.previous_signature,
        signature=r.signature,
    )


# ---------------------------------------------------------------------------
# Servers.  `daemon` is a core.Drand (duck-typed; see core/daemon.py).
# ---------------------------------------------------------------------------


def build_public_server(daemon, address: str,
                        tls: Optional[tuple] = None):
    """The node-to-node + public gateway (Public and Protocol services).

    Returns ``(server, bound_port)`` — the port matters when binding
    ``:0`` (loopback backends behind the single-port mux)."""

    async def public_rand(request, context):
        try:
            b = daemon.fetch_public_rand(request.round)
        except KeyError as exc:
            await context.abort(grpc.StatusCode.NOT_FOUND, str(exc))
        return pb.PublicRandResponse(
            round=b.round,
            previous_round=b.prev_round,
            previous_signature=b.prev_sig,
            signature=b.signature,
            randomness=b.randomness(),
        )

    async def public_rand_stream(request, context):
        queue = daemon.subscribe_beacons()
        try:
            while True:
                b = await queue.get()
                yield pb.PublicRandResponse(
                    round=b.round,
                    previous_round=b.prev_round,
                    previous_signature=b.prev_sig,
                    signature=b.signature,
                    randomness=b.randomness(),
                )
        finally:
            daemon.unsubscribe_beacons(queue)

    async def private_rand(request, context):
        try:
            out = daemon.serve_private_rand(request.request)
        except Exception as exc:
            await context.abort(
                grpc.StatusCode.INVALID_ARGUMENT, str(exc)
            )
        return pb.PrivateRandResponse(response=out)

    async def group(request, context):
        toml = daemon.group_toml()
        if toml is None:
            await context.abort(grpc.StatusCode.NOT_FOUND, "no group")
        return pb.GroupResponse(group_toml=toml)

    async def home(request, context):
        return pb.HomeResponse(status=daemon.home_status())

    async def new_beacon(request, context):
        # trace propagation: proto field first, gRPC metadata fallback
        # (an out-of-tree relay may only set the header)
        trace_id = request.trace_id
        if not trace_id:
            md = dict(context.invocation_metadata() or ())
            trace_id = md.get("x-drand-trace-id", "")
        packet = BeaconPacket(
            from_address=request.from_address,
            round=request.round,
            prev_round=request.previous_round,
            prev_sig=request.previous_signature,
            partial_sig=request.partial_signature,
            trace_id=trace_id,
            sent_at=request.sent_at,
        )
        try:
            await daemon.process_beacon_packet(packet)
        except Exception as exc:
            await context.abort(
                grpc.StatusCode.INVALID_ARGUMENT, str(exc)
            )
        return pb.Empty()

    async def sync_chain(request, context):
        for b in daemon.serve_sync_chain(request.from_round):
            yield _beacon_to_record(b)

    async def _verify_gateway(context):
        # serve/ pulls in the crypto backend; keep the import off the
        # transport module path
        try:
            return await daemon.verify_gateway()
        except RuntimeError as exc:
            await context.abort(
                grpc.StatusCode.FAILED_PRECONDITION, str(exc)
            )

    async def verify_beacon(request, context):
        from drand_tpu import serve

        gw = await _verify_gateway(context)
        req = serve.VerifyRequest(
            round=request.round,
            prev_round=request.previous_round,
            prev_sig=request.previous_signature,
            signature=request.signature,
        )
        # ring forward-once marker: a forwarded request must be served
        # locally by the owner, never re-forwarded (no routing loops)
        forwarded = any(
            k == "x-drand-forwarded"
            for k, _ in (context.invocation_metadata() or ())
        )
        def _shed_trailer(exc) -> None:
            # a rejection carries the request span's id as trailing
            # metadata so the shed client can correlate with
            # /debug/traces (REST sheds carry the same id in the body)
            tid = getattr(exc, "trace_id", None)
            if tid:
                context.set_trailing_metadata(
                    (("x-drand-trace-id", tid),)
                )

        try:
            res = await gw.verify(
                req, request.timeout_seconds or None,
                client=context.peer(),
                trace_id=request.trace_id or None,
                forwarded=forwarded,
            )
        except serve.Oversize as exc:
            _shed_trailer(exc)
            await context.abort(
                grpc.StatusCode.INVALID_ARGUMENT, str(exc)
            )
        except serve.Overloaded as exc:
            _shed_trailer(exc)
            await context.abort(
                grpc.StatusCode.RESOURCE_EXHAUSTED, str(exc)
            )
        except serve.DeadlineExceeded as exc:
            _shed_trailer(exc)
            await context.abort(
                grpc.StatusCode.DEADLINE_EXCEEDED, str(exc)
            )
        except serve.GatewayClosed as exc:
            _shed_trailer(exc)
            await context.abort(grpc.StatusCode.UNAVAILABLE, str(exc))
        return pb.VerifyBeaconResponse(
            valid=res.valid, cached=res.cached, batch_size=res.batch_size
        )

    async def verify_beacon_batch(request, context):
        from drand_tpu import serve

        gw = await _verify_gateway(context)
        reqs = [
            serve.VerifyRequest(
                round=item.round,
                prev_round=item.previous_round,
                prev_sig=item.previous_signature,
                signature=item.signature,
            )
            for item in request.items
        ]
        results = await gw.verify_many(
            reqs, request.timeout_seconds or None, client=context.peer()
        )
        out = []
        for res in results:
            if isinstance(res, serve.Oversize):
                out.append(pb.VerifyBeaconResponse(error="oversize"))
            elif isinstance(res, serve.Overloaded):
                out.append(pb.VerifyBeaconResponse(error="overloaded"))
            elif isinstance(res, serve.DeadlineExceeded):
                out.append(
                    pb.VerifyBeaconResponse(error="deadline exceeded")
                )
            elif isinstance(res, BaseException):
                await context.abort(grpc.StatusCode.INTERNAL, repr(res))
            else:
                out.append(pb.VerifyBeaconResponse(
                    valid=res.valid, cached=res.cached,
                    batch_size=res.batch_size,
                ))
        return pb.VerifyBeaconBatchResponse(items=out)

    async def verify_beacon_stream(request_iterator, context):
        """Bidirectional verification pipeline: relays push claims as
        fast as they arrive and read results as they resolve, no
        per-request HTTP/unary framing in between.  Each claim carries a
        client-chosen `claim_id`; responses demux by it and may come
        back OUT OF ORDER — a claim that hits the verified-round cache
        answers immediately while an earlier one waits on its batch."""
        from drand_tpu import serve

        gw = await _verify_gateway(context)
        client = context.peer()
        results: asyncio.Queue = asyncio.Queue()
        _DONE = object()

        async def run_one(msg):
            req = serve.VerifyRequest(
                round=msg.round,
                prev_round=msg.previous_round,
                prev_sig=msg.previous_signature,
                signature=msg.signature,
            )
            try:
                res = await gw.verify(
                    req, msg.timeout_seconds or None, client=client,
                    trace_id=msg.trace_id or None,
                )
                resp = pb.VerifyBeaconResponse(
                    claim_id=msg.claim_id, valid=res.valid,
                    cached=res.cached, batch_size=res.batch_size,
                )
            except serve.Oversize:
                resp = pb.VerifyBeaconResponse(
                    claim_id=msg.claim_id, error="oversize")
            except serve.Overloaded:
                resp = pb.VerifyBeaconResponse(
                    claim_id=msg.claim_id, error="overloaded")
            except serve.DeadlineExceeded:
                resp = pb.VerifyBeaconResponse(
                    claim_id=msg.claim_id, error="deadline exceeded")
            except serve.GatewayClosed:
                resp = pb.VerifyBeaconResponse(
                    claim_id=msg.claim_id, error="unavailable")
            await results.put(resp)

        async def pump():
            inflight = set()
            try:
                async for msg in request_iterator:
                    t = asyncio.create_task(run_one(msg))
                    inflight.add(t)
                    t.add_done_callback(inflight.discard)
                if inflight:
                    await asyncio.gather(*inflight,
                                         return_exceptions=True)
            finally:
                await results.put(_DONE)

        pump_task = asyncio.create_task(pump())
        try:
            while True:
                resp = await results.get()
                if resp is _DONE:
                    break
                yield resp
        finally:
            pump_task.cancel()

    async def setup(request, context):
        await _dkg_inbound(daemon, request, context, reshare=False)
        return pb.Empty()

    async def reshare(request, context):
        await _dkg_inbound(daemon, request, context, reshare=True)
        return pb.Empty()

    public_handlers = {
        "PublicRand": grpc.unary_unary_rpc_method_handler(
            public_rand,
            request_deserializer=pb.PublicRandRequest.FromString,
            response_serializer=pb.PublicRandResponse.SerializeToString,
        ),
        "PublicRandStream": grpc.unary_stream_rpc_method_handler(
            public_rand_stream,
            request_deserializer=pb.PublicRandRequest.FromString,
            response_serializer=pb.PublicRandResponse.SerializeToString,
        ),
        "PrivateRand": grpc.unary_unary_rpc_method_handler(
            private_rand,
            request_deserializer=pb.PrivateRandRequest.FromString,
            response_serializer=pb.PrivateRandResponse.SerializeToString,
        ),
        "Group": grpc.unary_unary_rpc_method_handler(
            group,
            request_deserializer=pb.GroupRequest.FromString,
            response_serializer=pb.GroupResponse.SerializeToString,
        ),
        "Home": grpc.unary_unary_rpc_method_handler(
            home,
            request_deserializer=pb.HomeRequest.FromString,
            response_serializer=pb.HomeResponse.SerializeToString,
        ),
        "VerifyBeacon": grpc.unary_unary_rpc_method_handler(
            verify_beacon,
            request_deserializer=pb.VerifyBeaconRequest.FromString,
            response_serializer=pb.VerifyBeaconResponse.SerializeToString,
        ),
        "VerifyBeaconBatch": grpc.unary_unary_rpc_method_handler(
            verify_beacon_batch,
            request_deserializer=pb.VerifyBeaconBatchRequest.FromString,
            response_serializer=(
                pb.VerifyBeaconBatchResponse.SerializeToString
            ),
        ),
        "VerifyBeaconStream": grpc.stream_stream_rpc_method_handler(
            verify_beacon_stream,
            request_deserializer=pb.VerifyBeaconRequest.FromString,
            response_serializer=pb.VerifyBeaconResponse.SerializeToString,
        ),
    }
    protocol_handlers = {
        "NewBeacon": grpc.unary_unary_rpc_method_handler(
            new_beacon,
            request_deserializer=pb.BeaconPacketMsg.FromString,
            response_serializer=pb.Empty.SerializeToString,
        ),
        "SyncChain": grpc.unary_stream_rpc_method_handler(
            sync_chain,
            request_deserializer=pb.SyncRequest.FromString,
            response_serializer=pb.BeaconRecord.SerializeToString,
        ),
        "Setup": grpc.unary_unary_rpc_method_handler(
            setup,
            request_deserializer=pb.DKGPacketMsg.FromString,
            response_serializer=pb.Empty.SerializeToString,
        ),
        "Reshare": grpc.unary_unary_rpc_method_handler(
            reshare,
            request_deserializer=pb.DKGPacketMsg.FromString,
            response_serializer=pb.Empty.SerializeToString,
        ),
    }
    server = grpc.aio.server()
    server.add_generic_rpc_handlers((
        grpc.method_handlers_generic_handler(
            PUBLIC_SERVICE, public_handlers
        ),
        grpc.method_handlers_generic_handler(
            PROTOCOL_SERVICE, protocol_handlers
        ),
    ))
    if tls is not None:
        cert_pem, key_pem = tls
        creds = grpc.ssl_server_credentials([(key_pem, cert_pem)])
        port = server.add_secure_port(address, creds)
    else:
        port = server.add_insecure_port(address)
    return server, port


async def _dkg_inbound(daemon, request, context, reshare: bool):
    try:
        payload = dkg_codec.msg_to_packet(request)
    except (dkg_codec.CodecError, ValueError):
        await context.abort(grpc.StatusCode.INVALID_ARGUMENT, "bad packet")
        return
    try:
        await daemon.process_dkg_packet(
            payload, reshare=reshare, group_hash=request.group_hash
        )
    except Exception as exc:
        await context.abort(grpc.StatusCode.FAILED_PRECONDITION, str(exc))


def build_control_server(daemon, port: int) -> grpc.aio.Server:
    """Localhost-only control service (reference net/control.go:21)."""

    async def ping(request, context):
        return pb.PingResponse()

    async def init_dkg(request, context):
        try:
            dist = await daemon.init_dkg(
                group_toml=request.group_toml,
                is_leader=request.is_leader,
                timeout=request.timeout_seconds or None,
                entropy=request.entropy or None,
            )
        except Exception as exc:
            await context.abort(grpc.StatusCode.FAILED_PRECONDITION,
                                repr(exc))
        return pb.InitResponse(dist_key_hex=dist)

    async def init_reshare(request, context):
        try:
            dist = await daemon.init_reshare(
                old_group_toml=request.old_group_toml or None,
                new_group_toml=request.new_group_toml,
                is_leader=request.is_leader,
                timeout=request.timeout_seconds or None,
                entropy=request.entropy or None,
            )
        except Exception as exc:
            await context.abort(grpc.StatusCode.FAILED_PRECONDITION,
                                repr(exc))
        return pb.InitResponse(dist_key_hex=dist)

    async def share(request, context):
        try:
            idx, hexv = daemon.share_info()
        except Exception as exc:
            await context.abort(grpc.StatusCode.NOT_FOUND, repr(exc))
        return pb.ShareResponse(index=idx, share_hex=hexv)

    async def public_key(request, context):
        return pb.KeyResponse(key_hex=daemon.public_key_hex())

    async def private_key(request, context):
        return pb.KeyResponse(key_hex=daemon.private_key_hex())

    async def collective_key(request, context):
        try:
            coeffs = daemon.collective_key_hex()
        except Exception as exc:
            await context.abort(grpc.StatusCode.NOT_FOUND, repr(exc))
        return pb.CollectiveKeyResponse(coefficients_hex=coeffs)

    async def group_file(request, context):
        toml = daemon.group_toml()
        if toml is None:
            await context.abort(grpc.StatusCode.NOT_FOUND, "no group")
        return pb.GroupResponse(group_toml=toml)

    async def shutdown(request, context):
        asyncio.get_running_loop().call_soon(daemon.request_shutdown)
        return pb.ShutdownResponse()

    handlers = {
        "PingPong": grpc.unary_unary_rpc_method_handler(
            ping,
            request_deserializer=pb.PingRequest.FromString,
            response_serializer=pb.PingResponse.SerializeToString,
        ),
        "InitDKG": grpc.unary_unary_rpc_method_handler(
            init_dkg,
            request_deserializer=pb.InitDKGRequest.FromString,
            response_serializer=pb.InitResponse.SerializeToString,
        ),
        "InitReshare": grpc.unary_unary_rpc_method_handler(
            init_reshare,
            request_deserializer=pb.InitReshareRequest.FromString,
            response_serializer=pb.InitResponse.SerializeToString,
        ),
        "Share": grpc.unary_unary_rpc_method_handler(
            share,
            request_deserializer=pb.ShareRequest.FromString,
            response_serializer=pb.ShareResponse.SerializeToString,
        ),
        "PublicKey": grpc.unary_unary_rpc_method_handler(
            public_key,
            request_deserializer=pb.KeyRequest.FromString,
            response_serializer=pb.KeyResponse.SerializeToString,
        ),
        "PrivateKey": grpc.unary_unary_rpc_method_handler(
            private_key,
            request_deserializer=pb.KeyRequest.FromString,
            response_serializer=pb.KeyResponse.SerializeToString,
        ),
        "CollectiveKey": grpc.unary_unary_rpc_method_handler(
            collective_key,
            request_deserializer=pb.KeyRequest.FromString,
            response_serializer=pb.CollectiveKeyResponse.SerializeToString,
        ),
        "GroupFile": grpc.unary_unary_rpc_method_handler(
            group_file,
            request_deserializer=pb.GroupFileRequest.FromString,
            response_serializer=pb.GroupResponse.SerializeToString,
        ),
        "Shutdown": grpc.unary_unary_rpc_method_handler(
            shutdown,
            request_deserializer=pb.ShutdownRequest.FromString,
            response_serializer=pb.ShutdownResponse.SerializeToString,
        ),
    }
    server = grpc.aio.server()
    server.add_generic_rpc_handlers((
        grpc.method_handlers_generic_handler(CONTROL_SERVICE, handlers),
    ))
    server.add_insecure_port(f"127.0.0.1:{port}")
    return server


# ---------------------------------------------------------------------------
# Clients.
# ---------------------------------------------------------------------------


class _ChannelCache:
    def __init__(self, certs: Optional[CertManager] = None):
        self.certs = certs or CertManager()
        self._channels: Dict[tuple, grpc.aio.Channel] = {}

    def get(self, address: str, tls: bool) -> grpc.aio.Channel:
        key = (address, tls)
        ch = self._channels.get(key)
        if ch is None:
            if tls:
                creds = grpc.ssl_channel_credentials(
                    root_certificates=self.certs.pool()
                )
                # self-signed deployment certs carry the peer IP/host in
                # SAN; grpc validates against the dial target
                ch = grpc.aio.secure_channel(address, creds)
            else:
                ch = grpc.aio.insecure_channel(address)
            self._channels[key] = ch
        return ch

    async def close(self):
        for ch in self._channels.values():
            await ch.close()
        self._channels.clear()


class GrpcClient(ProtocolClient):
    """Protocol-plane client: beacon broadcast, chain sync, DKG packets.

    Implements beacon.ProtocolClient and (via `send_dkg`) dkg.DKGNetwork.
    """

    def __init__(self, certs: Optional[CertManager] = None):
        self._cache = _ChannelCache(certs)
        self.dkg_context: Optional[tuple] = None  # (reshare, group_hash)

    async def close(self):
        await self._cache.close()

    def _method(self, peer: Identity, name: str, req_ser, resp_des,
                stream=False):
        ch = self._cache.get(peer.address, peer.tls)
        factory = ch.unary_stream if stream else ch.unary_unary
        return factory(
            name, request_serializer=req_ser,
            response_deserializer=resp_des,
        )

    async def new_beacon(self, peer: Identity,
                         packet: BeaconPacket) -> None:
        call = self._method(
            peer, f"/{PROTOCOL_SERVICE}/NewBeacon",
            pb.BeaconPacketMsg.SerializeToString, pb.Empty.FromString,
        )
        msg = pb.BeaconPacketMsg(
            from_address=packet.from_address,
            round=packet.round,
            previous_round=packet.prev_round,
            previous_signature=packet.prev_sig,
            partial_signature=packet.partial_sig,
            trace_id=packet.trace_id,
            sent_at=packet.sent_at,
        )
        # the trace id rides BOTH the proto field and gRPC metadata, so
        # middleboxes that only read headers can still stitch the round
        kwargs = {"timeout": RPC_TIMEOUT}
        if packet.trace_id:
            kwargs["metadata"] = (("x-drand-trace-id", packet.trace_id),)
        try:
            await call(msg, **kwargs)
        except grpc.aio.AioRpcError as exc:
            if exc.code() == grpc.StatusCode.INVALID_ARGUMENT:
                raise  # peer rejected the partial — no point retrying
            # retry once (reference net/client_grpc.go:200-206): the peer
            # may have been busy past the deadline
            await asyncio.sleep(0.2)
            await call(msg, **kwargs)

    async def sync_chain(self, peer: Identity,
                         from_round: int) -> AsyncIterator[Beacon]:
        call = self._method(
            peer, f"/{PROTOCOL_SERVICE}/SyncChain",
            pb.SyncRequest.SerializeToString, pb.BeaconRecord.FromString,
            stream=True,
        )
        async for rec in call(pb.SyncRequest(from_round=from_round),
                              timeout=30.0):
            yield _record_to_beacon(rec)

    async def send_dkg(self, peer: Identity, packet: dict) -> None:
        """DKG packets must not be lost (full certification needs every
        deal/response): retry a few times with backoff — the reference
        relies on operator retry plus threshold certification; we retry
        at the transport (cf. net/client_grpc.go:200-206 reconnect-once).
        """
        reshare, group_hash = self.dkg_context or (False, b"")
        name = "Reshare" if reshare else "Setup"
        call = self._method(
            peer, f"/{PROTOCOL_SERVICE}/{name}",
            pb.DKGPacketMsg.SerializeToString, pb.Empty.FromString,
        )
        msg = dkg_codec.packet_to_msg(packet, group_hash)
        last_exc = None
        for attempt in range(4):
            try:
                await call(msg, timeout=20.0)
                return
            except grpc.aio.AioRpcError as exc:
                last_exc = exc
                if exc.code() in (
                    grpc.StatusCode.FAILED_PRECONDITION,
                    grpc.StatusCode.INVALID_ARGUMENT,
                ):
                    # peer hasn't initialized its DKG yet (or rejected us):
                    # wait and retry; give up on hard rejections last
                    await asyncio.sleep(0.5 * (attempt + 1))
                else:
                    await asyncio.sleep(0.2 * (attempt + 1))
        raise last_exc

    # -- public API (used by the client library / CLI) --------------------

    async def public_rand(self, peer: Identity, round: int = 0):
        call = self._method(
            peer, f"/{PUBLIC_SERVICE}/PublicRand",
            pb.PublicRandRequest.SerializeToString,
            pb.PublicRandResponse.FromString,
        )
        return await call(pb.PublicRandRequest(round=round),
                          timeout=CONTROL_TIMEOUT)

    async def public_rand_stream(self, peer: Identity):
        call = self._method(
            peer, f"/{PUBLIC_SERVICE}/PublicRandStream",
            pb.PublicRandRequest.SerializeToString,
            pb.PublicRandResponse.FromString,
            stream=True,
        )
        async for resp in call(pb.PublicRandRequest()):
            yield resp

    async def private_rand(self, peer: Identity, blob: bytes) -> bytes:
        call = self._method(
            peer, f"/{PUBLIC_SERVICE}/PrivateRand",
            pb.PrivateRandRequest.SerializeToString,
            pb.PrivateRandResponse.FromString,
        )
        resp = await call(pb.PrivateRandRequest(request=blob),
                          timeout=CONTROL_TIMEOUT)
        return resp.response

    async def group(self, peer: Identity) -> str:
        call = self._method(
            peer, f"/{PUBLIC_SERVICE}/Group",
            pb.GroupRequest.SerializeToString, pb.GroupResponse.FromString,
        )
        resp = await call(pb.GroupRequest(), timeout=CONTROL_TIMEOUT)
        return resp.group_toml

    async def home(self, peer: Identity) -> str:
        call = self._method(
            peer, f"/{PUBLIC_SERVICE}/Home",
            pb.HomeRequest.SerializeToString, pb.HomeResponse.FromString,
        )
        resp = await call(pb.HomeRequest(), timeout=CONTROL_TIMEOUT)
        return resp.status

    async def verify_beacon(self, peer: Identity, *, round: int,
                            prev_round: int, prev_sig: bytes,
                            signature: bytes,
                            timeout: Optional[float] = None,
                            trace_id: str = "",
                            forwarded: bool = False
                            ) -> "pb.VerifyBeaconResponse":
        """Remote verification of one chain link through the peer's
        serve/ gateway.  The peer sheds with RESOURCE_EXHAUSTED /
        DEADLINE_EXCEEDED instead of holding the call open.

        `forwarded=True` marks a ring forward (metadata
        `x-drand-forwarded`): the receiving owner serves locally and
        never re-forwards, so a stale ring view cannot loop."""
        call = self._method(
            peer, f"/{PUBLIC_SERVICE}/VerifyBeacon",
            pb.VerifyBeaconRequest.SerializeToString,
            pb.VerifyBeaconResponse.FromString,
        )
        req = pb.VerifyBeaconRequest(
            round=round, previous_round=prev_round,
            previous_signature=prev_sig, signature=signature,
            timeout_seconds=timeout or 0.0,
            trace_id=trace_id,
        )
        kwargs = {"timeout": (timeout or 0.0) + CONTROL_TIMEOUT}
        if forwarded:
            kwargs["metadata"] = (("x-drand-forwarded", "1"),)
        return await call(req, **kwargs)

    async def verify_beacon_batch(self, peer: Identity, items,
                                  timeout: Optional[float] = None
                                  ) -> list:
        """Batch variant: `items` is an iterable of dicts with keys
        round/prev_round/prev_sig/signature; returns the response items
        in order (shed ones carry `.error`)."""
        call = self._method(
            peer, f"/{PUBLIC_SERVICE}/VerifyBeaconBatch",
            pb.VerifyBeaconBatchRequest.SerializeToString,
            pb.VerifyBeaconBatchResponse.FromString,
        )
        req = pb.VerifyBeaconBatchRequest(
            items=[
                pb.VerifyBeaconRequest(
                    round=i["round"],
                    previous_round=i["prev_round"],
                    previous_signature=i["prev_sig"],
                    signature=i["signature"],
                )
                for i in items
            ],
            timeout_seconds=timeout or 0.0,
        )
        resp = await call(
            req, timeout=(timeout or 0.0) + CONTROL_TIMEOUT
        )
        return list(resp.items)

    async def verify_beacon_stream(self, peer: Identity, items,
                                   timeout: Optional[float] = None):
        """Pipelined verification: `items` is an (async or sync)
        iterable of dicts with keys claim_id/round/prev_round/prev_sig/
        signature.  Claims stream into the peer's batcher as they are
        produced; responses are yielded AS THEY RESOLVE, demuxed by the
        client-supplied `claim_id` (order is not preserved — that is the
        point: a cache hit answers while an earlier claim still batches).
        """
        ch = self._cache.get(peer.address, peer.tls)
        call = ch.stream_stream(
            f"/{PUBLIC_SERVICE}/VerifyBeaconStream",
            request_serializer=pb.VerifyBeaconRequest.SerializeToString,
            response_deserializer=pb.VerifyBeaconResponse.FromString,
        )

        async def requests():
            if hasattr(items, "__aiter__"):
                async for i in items:
                    yield _stream_claim(i, timeout)
            else:
                for i in items:
                    yield _stream_claim(i, timeout)

        async for resp in call(requests()):
            yield resp


def _stream_claim(i: dict, timeout: Optional[float]):
    return pb.VerifyBeaconRequest(
        claim_id=i["claim_id"],
        round=i["round"],
        previous_round=i["prev_round"],
        previous_signature=i["prev_sig"],
        signature=i["signature"],
        timeout_seconds=timeout or 0.0,
    )


class ControlClient:
    """Client of the localhost control port (reference net/control.go:46)."""

    def __init__(self, port: int):
        self._channel = grpc.aio.insecure_channel(f"127.0.0.1:{port}")

    async def close(self):
        await self._channel.close()

    def _call(self, name, req_ser, resp_des):
        return self._channel.unary_unary(
            f"/{CONTROL_SERVICE}/{name}",
            request_serializer=req_ser, response_deserializer=resp_des,
        )

    async def ping(self) -> None:
        await self._call(
            "PingPong", pb.PingRequest.SerializeToString,
            pb.PingResponse.FromString,
        )(pb.PingRequest(), timeout=CONTROL_TIMEOUT)

    async def init_dkg(self, group_toml: str, is_leader: bool,
                       timeout: Optional[float] = None,
                       entropy: Optional[bytes] = None,
                       rpc_timeout: float = 600.0) -> str:
        resp = await self._call(
            "InitDKG", pb.InitDKGRequest.SerializeToString,
            pb.InitResponse.FromString,
        )(
            pb.InitDKGRequest(
                group_toml=group_toml, is_leader=is_leader,
                timeout_seconds=timeout or 0.0, entropy=entropy or b"",
            ),
            timeout=rpc_timeout,
        )
        return resp.dist_key_hex

    async def init_reshare(self, new_group_toml: str, is_leader: bool,
                           old_group_toml: Optional[str] = None,
                           timeout: Optional[float] = None,
                           entropy: Optional[bytes] = None,
                           rpc_timeout: float = 600.0) -> str:
        resp = await self._call(
            "InitReshare", pb.InitReshareRequest.SerializeToString,
            pb.InitResponse.FromString,
        )(
            pb.InitReshareRequest(
                old_group_toml=old_group_toml or "",
                new_group_toml=new_group_toml,
                is_leader=is_leader, timeout_seconds=timeout or 0.0,
                entropy=entropy or b"",
            ),
            timeout=rpc_timeout,
        )
        return resp.dist_key_hex

    async def share(self):
        resp = await self._call(
            "Share", pb.ShareRequest.SerializeToString,
            pb.ShareResponse.FromString,
        )(pb.ShareRequest(), timeout=CONTROL_TIMEOUT)
        return resp.index, resp.share_hex

    async def public_key(self) -> str:
        resp = await self._call(
            "PublicKey", pb.KeyRequest.SerializeToString,
            pb.KeyResponse.FromString,
        )(pb.KeyRequest(), timeout=CONTROL_TIMEOUT)
        return resp.key_hex

    async def private_key(self) -> str:
        resp = await self._call(
            "PrivateKey", pb.KeyRequest.SerializeToString,
            pb.KeyResponse.FromString,
        )(pb.KeyRequest(), timeout=CONTROL_TIMEOUT)
        return resp.key_hex

    async def collective_key(self) -> list:
        resp = await self._call(
            "CollectiveKey", pb.KeyRequest.SerializeToString,
            pb.CollectiveKeyResponse.FromString,
        )(pb.KeyRequest(), timeout=CONTROL_TIMEOUT)
        return list(resp.coefficients_hex)

    async def group_file(self) -> str:
        resp = await self._call(
            "GroupFile", pb.GroupFileRequest.SerializeToString,
            pb.GroupResponse.FromString,
        )(pb.GroupFileRequest(), timeout=CONTROL_TIMEOUT)
        return resp.group_toml

    async def shutdown(self) -> None:
        await self._call(
            "Shutdown", pb.ShutdownRequest.SerializeToString,
            pb.ShutdownResponse.FromString,
        )(pb.ShutdownRequest(), timeout=CONTROL_TIMEOUT)
