// Native embedded beacon-chain store.
//
// TPU-native equivalent of the reference's boltdb beacon store
// (/root/reference/beacon/store.go:22-45,62): an embedded, durable,
// round-keyed store with ordered-cursor iteration, implemented as an
// append-only record log plus an in-memory ordered index.  The daemon's
// storage hot path (one Put per round, range scans for chain sync) stays
// off the Python heap; Python talks to it through a small C ABI (ctypes).
//
// File format:
//   header:  8 bytes magic "DTCSTOR1"
//   record:  [u32 crc][u32 payload_len][payload]
//   payload: [u64 round][u64 prev_round][u32 prev_sig_len][u32 sig_len]
//            [prev_sig bytes][sig bytes]
// crc32 covers the payload.  Records only append; a Put for an existing
// round appends a superseding record (the index keeps the newest offset).
// On open the log is scanned to rebuild the index; a torn tail record
// (crash mid-write) fails its crc and the file is truncated there —
// restart-safe by construction, mirroring the reference's transactional
// Put (store.go:103).

#include <cerrno>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <iterator>
#include <map>
#include <mutex>
#include <string>
#include <vector>

#include <fcntl.h>
#include <sys/file.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

constexpr char kMagic[8] = {'D', 'T', 'C', 'S', 'T', 'O', 'R', '1'};

// A rollback appends a *truncate record*: a normal crc-framed record whose
// round is this sentinel and whose prev_round carries the rollback target.
// Replay applies records in log order, so "put 6, truncate >5, put 7"
// rebuilds the post-reorg index no matter where a crash interrupts —
// rollback durability costs one append, never a rewrite of the log.
constexpr uint64_t kTruncSentinel = 0xFFFFFFFFFFFFFFFFull;

uint32_t crc_table[256];
bool crc_init_done = false;

void crc_init() {
  if (crc_init_done) return;
  for (uint32_t i = 0; i < 256; i++) {
    uint32_t c = i;
    for (int k = 0; k < 8; k++)
      c = (c & 1) ? 0xEDB88320u ^ (c >> 1) : c >> 1;
    crc_table[i] = c;
  }
  crc_init_done = true;
}

uint32_t crc32(const uint8_t* buf, size_t len) {
  crc_init();
  uint32_t c = 0xFFFFFFFFu;
  for (size_t i = 0; i < len; i++)
    c = crc_table[(c ^ buf[i]) & 0xFF] ^ (c >> 8);
  return c ^ 0xFFFFFFFFu;
}

struct Record {
  uint64_t round;
  uint64_t prev_round;
  std::vector<uint8_t> prev_sig;
  std::vector<uint8_t> sig;
};

struct Store {
  std::mutex mu;
  int fd = -1;            // -1 => pure in-memory store
  bool fsync_puts = false;
  std::map<uint64_t, Record> index;  // round -> newest record
};

void put_u32(std::vector<uint8_t>& v, uint32_t x) {
  for (int i = 0; i < 4; i++) v.push_back((x >> (8 * i)) & 0xFF);
}
void put_u64(std::vector<uint8_t>& v, uint64_t x) {
  for (int i = 0; i < 8; i++) v.push_back((x >> (8 * i)) & 0xFF);
}
uint32_t get_u32(const uint8_t* p) {
  uint32_t x = 0;
  for (int i = 0; i < 4; i++) x |= uint32_t(p[i]) << (8 * i);
  return x;
}
uint64_t get_u64(const uint8_t* p) {
  uint64_t x = 0;
  for (int i = 0; i < 8; i++) x |= uint64_t(p[i]) << (8 * i);
  return x;
}

std::vector<uint8_t> encode_payload(const Record& r) {
  std::vector<uint8_t> p;
  p.reserve(24 + r.prev_sig.size() + r.sig.size());
  put_u64(p, r.round);
  put_u64(p, r.prev_round);
  put_u32(p, uint32_t(r.prev_sig.size()));
  put_u32(p, uint32_t(r.sig.size()));
  p.insert(p.end(), r.prev_sig.begin(), r.prev_sig.end());
  p.insert(p.end(), r.sig.begin(), r.sig.end());
  return p;
}

bool decode_payload(const uint8_t* p, size_t len, Record* out) {
  if (len < 24) return false;
  out->round = get_u64(p);
  out->prev_round = get_u64(p + 8);
  uint32_t psl = get_u32(p + 16);
  uint32_t sl = get_u32(p + 20);
  if (24 + uint64_t(psl) + uint64_t(sl) != len) return false;
  out->prev_sig.assign(p + 24, p + 24 + psl);
  out->sig.assign(p + 24 + psl, p + 24 + psl + sl);
  return true;
}

// Scan the log, rebuilding the index; truncate at the first bad record.
bool load(Store* s) {
  off_t size = lseek(s->fd, 0, SEEK_END);
  if (size < 0) return false;
  if (size == 0) {
    if (pwrite(s->fd, kMagic, 8, 0) != 8) return false;
    if (::fsync(s->fd) != 0) return false;  // header durable before use
    return true;
  }
  char magic[8];
  if (pread(s->fd, magic, 8, 0) != 8 || memcmp(magic, kMagic, 8) != 0)
    return false;
  off_t off = 8;
  std::vector<uint8_t> buf;
  while (off + 8 <= size) {
    uint8_t hdr[8];
    if (pread(s->fd, hdr, 8, off) != 8) break;
    uint32_t crc = get_u32(hdr);
    uint32_t len = get_u32(hdr + 4);
    if (len > (64u << 20) || off + 8 + off_t(len) > size) break;
    buf.resize(len);
    if (pread(s->fd, buf.data(), len, off + 8) != ssize_t(len)) break;
    if (crc32(buf.data(), len) != crc) break;
    Record r;
    if (!decode_payload(buf.data(), len, &r)) break;
    if (r.round == kTruncSentinel) {
      // truncate record: drop every index entry above the target round
      s->index.erase(s->index.upper_bound(r.prev_round), s->index.end());
    } else {
      s->index[r.round] = std::move(r);
    }
    off += 8 + len;
  }
  if (off < size) {
    // torn tail from a crash mid-append: drop it (durably, so a second
    // crash cannot resurrect the garbage)
    if (ftruncate(s->fd, off) != 0) return false;
    if (::fsync(s->fd) != 0) return false;
  }
  return true;
}

// Append one crc-framed record to the log (caller holds s->mu).
bool append_record(Store* s, const Record& r) {
  std::vector<uint8_t> payload = encode_payload(r);
  std::vector<uint8_t> rec;
  put_u32(rec, crc32(payload.data(), payload.size()));
  put_u32(rec, uint32_t(payload.size()));
  rec.insert(rec.end(), payload.begin(), payload.end());
  off_t off = lseek(s->fd, 0, SEEK_END);
  ssize_t n = pwrite(s->fd, rec.data(), rec.size(), off);
  if (n != ssize_t(rec.size())) {
    // keep the log consistent: drop the partial append
    if (n > 0) (void)!ftruncate(s->fd, off);
    return false;
  }
  if (s->fsync_puts) ::fsync(s->fd);
  return true;
}

int fill(const Record& r, uint64_t* round, uint64_t* prev_round,
         uint8_t* prev_sig, uint32_t* psl, uint8_t* sig, uint32_t* sl) {
  if (r.prev_sig.size() > *psl || r.sig.size() > *sl) return -2;
  *round = r.round;
  *prev_round = r.prev_round;
  memcpy(prev_sig, r.prev_sig.data(), r.prev_sig.size());
  *psl = uint32_t(r.prev_sig.size());
  memcpy(sig, r.sig.data(), r.sig.size());
  *sl = uint32_t(r.sig.size());
  return 0;
}

}  // namespace

extern "C" {

// path == NULL or "" => in-memory store.  fsync_puts != 0 => fsync after
// every Put (durable against power loss, not just process crash).
void* dtcs_open(const char* path, int fsync_puts) {
  Store* s = new Store();
  s->fsync_puts = fsync_puts != 0;
  if (path != nullptr && path[0] != '\0') {
    s->fd = ::open(path, O_RDWR | O_CREAT, 0600);
    // single-writer discipline (the reference's boltdb flocks its DB):
    // a second process opening the same log would interleave appends
    // against a divergent in-memory index
    if (s->fd >= 0 && flock(s->fd, LOCK_EX | LOCK_NB) != 0) {
      ::close(s->fd);
      delete s;
      return nullptr;
    }
    if (s->fd < 0 || !load(s)) {
      if (s->fd >= 0) ::close(s->fd);
      delete s;
      return nullptr;
    }
  }
  return s;
}

void dtcs_close(void* h) {
  Store* s = static_cast<Store*>(h);
  if (s == nullptr) return;
  {
    std::lock_guard<std::mutex> g(s->mu);
    if (s->fd >= 0) {
      ::fsync(s->fd);
      ::close(s->fd);
      s->fd = -1;
    }
  }
  delete s;
}

int dtcs_put(void* h, uint64_t round, uint64_t prev_round,
             const uint8_t* prev_sig, uint32_t psl,
             const uint8_t* sig, uint32_t sl) {
  Store* s = static_cast<Store*>(h);
  if (round == kTruncSentinel) return -4;  // reserved for truncate records
  Record r;
  r.round = round;
  r.prev_round = prev_round;
  r.prev_sig.assign(prev_sig, prev_sig + psl);
  r.sig.assign(sig, sig + sl);
  std::lock_guard<std::mutex> g(s->mu);
  if (s->fd >= 0 && !append_record(s, r)) return -1;
  s->index[round] = std::move(r);
  return 0;
}

// Drop every beacon with round > `round` (chain reorg).  max_depth < 0
// means unbounded; otherwise refuse (rc -3, store untouched) when more
// than max_depth rounds would be dropped.  Durability: a single truncate
// record is appended before the in-memory erase, so a crash at any point
// replays to either the pre- or post-rollback chain, never a mix.
// Returns the number of rounds dropped, or a negative error code.
int64_t dtcs_rollback(void* h, uint64_t round, int64_t max_depth) {
  Store* s = static_cast<Store*>(h);
  std::lock_guard<std::mutex> g(s->mu);
  auto from = s->index.upper_bound(round);
  int64_t depth = int64_t(std::distance(from, s->index.end()));
  if (depth == 0) return 0;
  if (max_depth >= 0 && depth > max_depth) return -3;
  if (s->fd >= 0) {
    Record t;
    t.round = kTruncSentinel;
    t.prev_round = round;
    if (!append_record(s, t)) return -1;
  }
  s->index.erase(from, s->index.end());
  return depth;
}

int64_t dtcs_count(void* h) {
  Store* s = static_cast<Store*>(h);
  std::lock_guard<std::mutex> g(s->mu);
  return int64_t(s->index.size());
}

// All lookups return 0 on hit, -1 on miss, -2 if a buffer is too small.
// psl/sl are in/out: capacity in, actual length out.

int dtcs_get(void* h, uint64_t want, uint64_t* round, uint64_t* prev_round,
             uint8_t* prev_sig, uint32_t* psl, uint8_t* sig, uint32_t* sl) {
  Store* s = static_cast<Store*>(h);
  std::lock_guard<std::mutex> g(s->mu);
  auto it = s->index.find(want);
  if (it == s->index.end()) return -1;
  return fill(it->second, round, prev_round, prev_sig, psl, sig, sl);
}

int dtcs_first(void* h, uint64_t* round, uint64_t* prev_round,
               uint8_t* prev_sig, uint32_t* psl,
               uint8_t* sig, uint32_t* sl) {
  Store* s = static_cast<Store*>(h);
  std::lock_guard<std::mutex> g(s->mu);
  if (s->index.empty()) return -1;
  return fill(s->index.begin()->second, round, prev_round, prev_sig, psl,
              sig, sl);
}

int dtcs_last(void* h, uint64_t* round, uint64_t* prev_round,
              uint8_t* prev_sig, uint32_t* psl,
              uint8_t* sig, uint32_t* sl) {
  Store* s = static_cast<Store*>(h);
  std::lock_guard<std::mutex> g(s->mu);
  if (s->index.empty()) return -1;
  return fill(s->index.rbegin()->second, round, prev_round, prev_sig, psl,
              sig, sl);
}

// Smallest round >= want (cursor Seek; Next is seek(cur + 1)).
int dtcs_seek(void* h, uint64_t want, uint64_t* round, uint64_t* prev_round,
              uint8_t* prev_sig, uint32_t* psl,
              uint8_t* sig, uint32_t* sl) {
  Store* s = static_cast<Store*>(h);
  std::lock_guard<std::mutex> g(s->mu);
  auto it = s->index.lower_bound(want);
  if (it == s->index.end()) return -1;
  return fill(it->second, round, prev_round, prev_sig, psl, sig, sl);
}

}  // extern "C"
