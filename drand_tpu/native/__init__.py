"""Native (C++) runtime components, built on demand with the system g++.

The reference's runtime is native Go end to end; here the Python protocol
plane delegates its storage hot path to a C++ embedded store
(`chainstore.cc`), loaded through ctypes.  Build artifacts are cached next
to the sources and rebuilt whenever a source file changes.
"""

from __future__ import annotations

import hashlib
import os
import subprocess
import threading
from pathlib import Path
from typing import Optional

_SRC_DIR = Path(__file__).resolve().parent
_BUILD_DIR = _SRC_DIR / "_build"
_LOCK = threading.Lock()
_BUILD_ERROR: Optional[str] = None


def _source_digest(src: Path) -> str:
    """Digest over source AND target platform: a cached artifact built
    for another architecture must never be picked up."""
    import platform

    tag = f"{platform.system()}-{platform.machine()}".encode()
    return hashlib.sha256(src.read_bytes() + b"\0" + tag).hexdigest()[:16]


def sanitize_enabled() -> bool:
    """ASan+UBSan build mode (`DRAND_NATIVE_SAN=1`): the C++ backends
    are rebuilt with -fsanitize=address,undefined so the native test
    suites catch heap corruption / UB that a plain -O2 build silently
    survives.  Loading such a .so into an uninstrumented python needs
    libasan preloaded — `make test-native-san` (tools/native_san.py)
    sets that up; flipping the env var alone will abort at dlopen."""
    return os.environ.get("DRAND_NATIVE_SAN", "") not in ("", "0")


def shared_lib(name: str) -> Optional[str]:
    """Path to the built shared library for `name`.cc, compiling if
    needed.  Returns None (and remembers why) if no compiler is usable —
    callers fall back to their pure-Python/sqlite implementations."""
    global _BUILD_ERROR
    src = _SRC_DIR / f"{name}.cc"
    tag = _source_digest(src)
    san = sanitize_enabled()
    # sanitized artifacts live under a distinct name so a san run never
    # poisons the production cache (and vice versa)
    out = _BUILD_DIR / f"{name}-{tag}{'-san' if san else ''}.so"
    if out.exists():
        return str(out)
    with _LOCK:
        if out.exists():
            return str(out)
        if _BUILD_ERROR is not None:
            return None
        _BUILD_DIR.mkdir(parents=True, exist_ok=True)
        # per-pid temp name: concurrent daemon processes may race to
        # build the same digest; os.replace makes the publish atomic
        tmp = out.with_suffix(f".so.{os.getpid()}.tmp")
        if san:
            flags = [
                # -O1 keeps stack traces honest; recover=off turns every
                # UB finding into a hard abort the test run can't miss
                "-O1", "-g", "-fno-omit-frame-pointer",
                "-fsanitize=address,undefined",
                "-fno-sanitize-recover=undefined",
            ]
        else:
            flags = ["-O2"]
        cmd = [
            os.environ.get("CXX", "g++"),
            *flags, "-std=c++17", "-shared", "-fPIC",
            str(src), "-o", str(tmp),
        ]
        try:
            proc = subprocess.run(
                cmd, capture_output=True, text=True, timeout=120
            )
        except (OSError, subprocess.TimeoutExpired) as exc:
            _BUILD_ERROR = f"{cmd[0]}: {exc}"
            return None
        if proc.returncode != 0:
            _BUILD_ERROR = proc.stderr[-2000:]
            return None
        os.replace(tmp, out)
    return str(out)


def build_error() -> Optional[str]:
    return _BUILD_ERROR
