// BLS12-381 host-side native backend (threshold-BLS hot path).
//
// SURVEY.md section 2's rule: where something can't run on the TPU it gets a
// C++ host-side equivalent, not a Python stand-in.  This file is that
// equivalent for the crypto plane: the reference daemon's pairing suite
// (selected at /root/reference/key/curve.go:12-30, consumed by
// /root/reference/beacon/beacon.go:433,488) runs native Go; a CPU-only
// drand_tpu daemon previously fell back to the pure-Python oracle at
// 10-30 s per beacon round.  This backend is semantically identical to
// drand_tpu/crypto/refimpl.py — same tower, same SVDW hash-to-curve with the
// DRANDTPU-V01 DSTs, same compressed codecs — and is cross-checked
// byte-for-byte against it in tests/test_native_bls.py.
//
// Design notes:
//  * Fp: 6x64-bit Montgomery (CIOS).  All derived exponents ((p-1)/6,
//    (p+1)/4, ...) and tower/Frobenius/psi constants are COMPUTED at init
//    from p and x rather than pasted as magic tables, mirroring
//    refimpl.py's derive-then-verify ethos; dbls_selfcheck() re-verifies.
//  * Pairing: optimal ate, homogeneous projective Miller steps with sparse
//    (c00, c11 w^3, c12 w^5) line multiplication; exact final
//    exponentiation via hard = d*(x+p)*(x^2+p^2-1)+1, d = (x-1)^2/3 = H1
//    (verified exactly against refimpl's naive pow in tests).
//  * Lines are scaled by Fp2 factors only (killed by the p^6-1 easy part),
//    so GT outputs equal refimpl's exactly.
//
// Build: g++ -O2 -std=c++17 -shared -fPIC (drand_tpu/native/__init__.py).

#include <cstdint>
#include <cstring>

typedef uint64_t u64;
typedef unsigned __int128 u128;

// ---------------------------------------------------------------------------
// Fp: 6x64 little-endian limbs, Montgomery form (R = 2^384).
// ---------------------------------------------------------------------------

struct fp { u64 l[6]; };

static const u64 P_L[6] = {
    0xb9feffffffffaaabULL, 0x1eabfffeb153ffffULL, 0x6730d2a0f6b0f624ULL,
    0x64774b84f38512bfULL, 0x4b1ba7b6434bacd7ULL, 0x1a0111ea397fe69aULL,
};
static const u64 N0_INV = 0x89f3fffcfffcfffdULL;  // -p^-1 mod 2^64
static const fp R2 = {{  // 2^768 mod p (to-Montgomery factor)
    0xf4df1f341c341746ULL, 0x0a76e6a609d104f1ULL, 0x8de5476c4c95b6d5ULL,
    0x67eb88a9939d83c0ULL, 0x9a793e85b519952dULL, 0x11988fe592cae3aaULL,
}};

// |x| for the BLS parameter x = -0xD201000000010000
static const u64 X_ABS = 0xD201000000010000ULL;

// scalar field r = x^4 - x^2 + 1 (4x64 LE limbs)
static const u64 R_L[4] = {
    0xffffffff00000001ULL, 0x53bda402fffe5bfeULL,
    0x3339d80809a1d805ULL, 0x73eda753299d7d48ULL,
};

static inline int fp_cmp_raw(const u64* a, const u64* b, int n) {
    for (int i = n - 1; i >= 0; --i) {
        if (a[i] < b[i]) return -1;
        if (a[i] > b[i]) return 1;
    }
    return 0;
}

static inline u64 add_limbs(u64* r, const u64* a, const u64* b, int n) {
    u128 c = 0;
    for (int i = 0; i < n; ++i) {
        u128 s = (u128)a[i] + b[i] + c;
        r[i] = (u64)s;
        c = s >> 64;
    }
    return (u64)c;
}

static inline u64 sub_limbs(u64* r, const u64* a, const u64* b, int n) {
    u128 borrow = 0;
    for (int i = 0; i < n; ++i) {
        u128 d = (u128)a[i] - b[i] - borrow;
        r[i] = (u64)d;
        borrow = (d >> 64) & 1;
    }
    return (u64)borrow;
}

static inline void fp_add(fp& r, const fp& a, const fp& b) {
    u64 t[6];
    add_limbs(t, a.l, b.l, 6);
    if (fp_cmp_raw(t, P_L, 6) >= 0) sub_limbs(t, t, P_L, 6);
    memcpy(r.l, t, sizeof t);
}

static inline void fp_sub(fp& r, const fp& a, const fp& b) {
    u64 t[6];
    if (sub_limbs(t, a.l, b.l, 6)) add_limbs(t, t, P_L, 6);
    memcpy(r.l, t, sizeof t);
}

static inline void fp_neg(fp& r, const fp& a) {
    bool z = true;
    for (int i = 0; i < 6; ++i) if (a.l[i]) { z = false; break; }
    if (z) { r = a; return; }
    sub_limbs(r.l, P_L, a.l, 6);
}

static inline bool fp_is_zero(const fp& a) {
    for (int i = 0; i < 6; ++i) if (a.l[i]) return false;
    return true;
}

static inline bool fp_eq(const fp& a, const fp& b) {
    return memcmp(a.l, b.l, sizeof a.l) == 0;
}

// CIOS Montgomery multiplication.
static void fp_mul(fp& r, const fp& a, const fp& b) {
    u64 t[7] = {0, 0, 0, 0, 0, 0, 0};
    for (int i = 0; i < 6; ++i) {
        u128 c = 0;
        u64 ai = a.l[i];
        for (int j = 0; j < 6; ++j) {
            u128 s = (u128)t[j] + (u128)ai * b.l[j] + c;
            t[j] = (u64)s;
            c = s >> 64;
        }
        u64 t6 = t[6] + (u64)c;  // cannot overflow: t stays < 2p*2^384
        u64 m = t[0] * N0_INV;
        u128 s = (u128)t[0] + (u128)m * P_L[0];
        c = s >> 64;
        for (int j = 1; j < 6; ++j) {
            s = (u128)t[j] + (u128)m * P_L[j] + c;
            t[j - 1] = (u64)s;
            c = s >> 64;
        }
        s = (u128)t6 + c;
        t[5] = (u64)s;
        t[6] = (u64)(s >> 64);
    }
    if (t[6] || fp_cmp_raw(t, P_L, 6) >= 0) sub_limbs(t, t, P_L, 6);
    memcpy(r.l, t, 6 * sizeof(u64));
}

static inline void fp_sqr(fp& r, const fp& a) { fp_mul(r, a, a); }

static fp FP_ZERO;      // all zero
static fp FP_ONE_MONT;  // R mod p (Montgomery 1), set in init

static void fp_from_u64(fp& r, u64 v) {
    fp t = {{v, 0, 0, 0, 0, 0}};
    fp_mul(r, t, R2);
}

static void fp_from_mont(u64 out[6], const fp& a) {
    fp one_raw = {{1, 0, 0, 0, 0, 0}};
    fp t;
    fp_mul(t, a, one_raw);
    memcpy(out, t.l, sizeof t.l);
}

// canonical big-endian 48 bytes <-> Montgomery fp
static void fp_to_bytes(uint8_t out[48], const fp& a) {
    u64 c[6];
    fp_from_mont(c, a);
    for (int i = 0; i < 6; ++i)
        for (int j = 0; j < 8; ++j)
            out[48 - 8 * (i + 1) + (7 - j)] = (uint8_t)(c[i] >> (8 * j));
}

static int fp_from_bytes(fp& r, const uint8_t in[48]) {
    u64 c[6] = {0, 0, 0, 0, 0, 0};
    for (int i = 0; i < 6; ++i)
        for (int j = 0; j < 8; ++j)
            c[i] |= (u64)in[48 - 8 * (i + 1) + (7 - j)] << (8 * j);
    if (fp_cmp_raw(c, P_L, 6) >= 0) return -1;
    fp t;
    memcpy(t.l, c, sizeof c);
    fp_mul(r, t, R2);
    return 0;
}

// generic MSB-first pow over limb exponents (nl limbs little-endian)
static void fp_pow_limbs(fp& r, const fp& base, const u64* e, int nl) {
    int top = -1;
    for (int i = nl - 1; i >= 0 && top < 0; --i)
        if (e[i]) for (int b = 63; b >= 0; --b)
            if ((e[i] >> b) & 1) { top = i * 64 + b; break; }
    if (top < 0) { r = FP_ONE_MONT; return; }
    fp acc = base;
    for (int k = top - 1; k >= 0; --k) {
        fp_sqr(acc, acc);
        if ((e[k / 64] >> (k % 64)) & 1) fp_mul(acc, acc, base);
    }
    r = acc;
}

// derived exponents (set in init from P_L)
static u64 EXP_P_MINUS_2[6];   // inversion
static u64 EXP_SQRT[6];        // (p+1)/4
static u64 EXP_QR[6];          // (p-1)/2
static u64 EXP_P16[6];         // (p-1)/6  (Frobenius base constant)
static u64 HALF_P[6];          // (p-1)/2 as plain limbs for sign compare
static u64 D_EXP[2];           // (x-1)^2/3 = H1 = final-exp d  (126-bit)

static void shr_limbs(u64* a, int n, int k) {  // k in {1,2}
    for (int i = 0; i < n; ++i) {
        a[i] >>= k;
        if (i + 1 < n) a[i] |= a[i + 1] << (64 - k);
    }
}

static void div_small(u64* a, int n, u64 d) {
    u128 rem = 0;
    for (int i = n - 1; i >= 0; --i) {
        u128 cur = (rem << 64) | a[i];
        a[i] = (u64)(cur / d);
        rem = cur % d;
    }
}

static inline void fp_inv(fp& r, const fp& a) {
    fp_pow_limbs(r, a, EXP_P_MINUS_2, 6);
}

static bool fp_is_square(const fp& a) {
    if (fp_is_zero(a)) return true;
    fp t;
    fp_pow_limbs(t, a, EXP_QR, 6);
    return fp_eq(t, FP_ONE_MONT);
}

static bool fp_sqrt(fp& r, const fp& a) {
    if (fp_is_zero(a)) { r = FP_ZERO; return true; }
    fp s, chk;
    fp_pow_limbs(s, a, EXP_SQRT, 6);
    fp_sqr(chk, s);
    if (!fp_eq(chk, a)) return false;
    r = s;
    return true;
}

static int fp_sgn0(const fp& a) {
    u64 c[6];
    fp_from_mont(c, a);
    return (int)(c[0] & 1);
}

// canonical y > (p-1)/2 ?
static bool fp_is_high(const fp& a) {
    u64 c[6];
    fp_from_mont(c, a);
    return fp_cmp_raw(c, HALF_P, 6) > 0;
}

// ---------------------------------------------------------------------------
// Fp2 = Fp[u]/(u^2+1)
// ---------------------------------------------------------------------------

struct fp2 { fp c0, c1; };

static fp2 FP2_ZERO_, FP2_ONE_, XI_;  // XI = 1 + u

static inline void fp2_add(fp2& r, const fp2& a, const fp2& b) {
    fp_add(r.c0, a.c0, b.c0); fp_add(r.c1, a.c1, b.c1);
}
static inline void fp2_sub(fp2& r, const fp2& a, const fp2& b) {
    fp_sub(r.c0, a.c0, b.c0); fp_sub(r.c1, a.c1, b.c1);
}
static inline void fp2_neg(fp2& r, const fp2& a) {
    fp_neg(r.c0, a.c0); fp_neg(r.c1, a.c1);
}
static inline void fp2_conj(fp2& r, const fp2& a) {
    r.c0 = a.c0; fp_neg(r.c1, a.c1);
}
static inline bool fp2_is_zero(const fp2& a) {
    return fp_is_zero(a.c0) && fp_is_zero(a.c1);
}
static inline bool fp2_eq(const fp2& a, const fp2& b) {
    return fp_eq(a.c0, b.c0) && fp_eq(a.c1, b.c1);
}

static void fp2_mul(fp2& r, const fp2& a, const fp2& b) {
    // Karatsuba: 3 fp muls
    fp t0, t1, s0, s1, m;
    fp_mul(t0, a.c0, b.c0);
    fp_mul(t1, a.c1, b.c1);
    fp_add(s0, a.c0, a.c1);
    fp_add(s1, b.c0, b.c1);
    fp_mul(m, s0, s1);          // (a0+a1)(b0+b1)
    fp r0;
    fp_sub(r0, t0, t1);         // a0b0 - a1b1
    fp_sub(m, m, t0);
    fp_sub(r.c1, m, t1);        // a0b1 + a1b0
    r.c0 = r0;
}

static void fp2_sqr(fp2& r, const fp2& a) {
    fp s, d, m;
    fp_add(s, a.c0, a.c1);
    fp_sub(d, a.c0, a.c1);
    fp_mul(m, a.c0, a.c1);
    fp_mul(r.c0, s, d);
    fp_add(r.c1, m, m);
}

static inline void fp2_mul_fp(fp2& r, const fp2& a, const fp& s) {
    fp_mul(r.c0, a.c0, s); fp_mul(r.c1, a.c1, s);
}

static inline void fp2_mul_xi(fp2& r, const fp2& a) {
    // (a0 + a1 u)(1 + u) = (a0 - a1) + (a0 + a1) u
    fp t0, t1;
    fp_sub(t0, a.c0, a.c1);
    fp_add(t1, a.c0, a.c1);
    r.c0 = t0; r.c1 = t1;
}

static void fp2_inv(fp2& r, const fp2& a) {
    fp n, t, i;
    fp_sqr(n, a.c0);
    fp_sqr(t, a.c1);
    fp_add(n, n, t);
    fp_inv(i, n);
    fp_mul(r.c0, a.c0, i);
    fp_mul(t, a.c1, i);
    fp_neg(r.c1, t);
}

static bool fp2_is_square(const fp2& a) {
    fp n, t;
    fp_sqr(n, a.c0);
    fp_sqr(t, a.c1);
    fp_add(n, n, t);
    return fp_is_square(n);
}

static bool fp2_sqrt(fp2& r, const fp2& a) {
    // 'complex' method, mirroring refimpl.fp2_sqrt
    if (fp_is_zero(a.c1)) {
        fp s;
        if (fp_sqrt(s, a.c0)) { r.c0 = s; r.c1 = FP_ZERO; return true; }
        fp na;
        fp_neg(na, a.c0);
        if (!fp_sqrt(s, na)) return false;
        r.c0 = FP_ZERO; r.c1 = s;
        return true;
    }
    fp n, t, s, inv2, x0sq, x0;
    fp_sqr(n, a.c0);
    fp_sqr(t, a.c1);
    fp_add(n, n, t);
    if (!fp_sqrt(s, n)) return false;
    fp two;
    fp_add(two, FP_ONE_MONT, FP_ONE_MONT);
    fp_inv(inv2, two);
    fp_add(x0sq, a.c0, s);
    fp_mul(x0sq, x0sq, inv2);
    if (!fp_sqrt(x0, x0sq)) {
        fp_sub(x0sq, a.c0, s);
        fp_mul(x0sq, x0sq, inv2);
        if (!fp_sqrt(x0, x0sq)) return false;
    }
    fp denom, dinv;
    fp_add(denom, x0, x0);
    if (fp_is_zero(denom)) return false;
    fp_inv(dinv, denom);
    r.c0 = x0;
    fp_mul(r.c1, a.c1, dinv);
    fp2 chk;
    fp2_sqr(chk, r);
    return fp2_eq(chk, a);
}

static int fp2_sgn0(const fp2& a) {
    u64 c[6];
    fp_from_mont(c, a.c0);
    int s0 = (int)(c[0] & 1);
    bool z0 = true;
    for (int i = 0; i < 6; ++i) if (c[i]) { z0 = false; break; }
    fp_from_mont(c, a.c1);
    int s1 = (int)(c[0] & 1);
    return s0 | ((z0 ? 1 : 0) & s1);
}

// lexicographically-largest on (c1, c0) — refimpl._fp2_is_larger
static bool fp2_is_high(const fp2& y) {
    u64 y1[6], n1[6];
    fp ny0f, ny1f;
    fp_neg(ny0f, y.c0);
    fp_neg(ny1f, y.c1);
    fp_from_mont(y1, y.c1);
    fp_from_mont(n1, ny1f);
    int c = fp_cmp_raw(y1, n1, 6);
    if (c != 0) return c > 0;
    u64 y0[6], n0[6];
    fp_from_mont(y0, y.c0);
    fp_from_mont(n0, ny0f);
    return fp_cmp_raw(y0, n0, 6) > 0;
}

static void fp2_pow_limbs(fp2& r, const fp2& base, const u64* e, int nl) {
    int top = -1;
    for (int i = nl - 1; i >= 0 && top < 0; --i)
        if (e[i]) for (int b = 63; b >= 0; --b)
            if ((e[i] >> b) & 1) { top = i * 64 + b; break; }
    if (top < 0) { r = FP2_ONE_; return; }
    fp2 acc = base;
    for (int k = top - 1; k >= 0; --k) {
        fp2_sqr(acc, acc);
        if ((e[k / 64] >> (k % 64)) & 1) fp2_mul(acc, acc, base);
    }
    r = acc;
}

// ---------------------------------------------------------------------------
// Fp6 = Fp2[v]/(v^3 - xi), Fp12 = Fp6[w]/(w^2 - v)   (refimpl tower)
// ---------------------------------------------------------------------------

struct fp6 { fp2 c0, c1, c2; };
struct fp12 { fp6 c0, c1; };

static fp6 FP6_ZERO_, FP6_ONE_;
static fp12 FP12_ONE_;

static inline void fp6_add(fp6& r, const fp6& a, const fp6& b) {
    fp2_add(r.c0, a.c0, b.c0);
    fp2_add(r.c1, a.c1, b.c1);
    fp2_add(r.c2, a.c2, b.c2);
}
static inline void fp6_sub(fp6& r, const fp6& a, const fp6& b) {
    fp2_sub(r.c0, a.c0, b.c0);
    fp2_sub(r.c1, a.c1, b.c1);
    fp2_sub(r.c2, a.c2, b.c2);
}
static inline void fp6_neg(fp6& r, const fp6& a) {
    fp2_neg(r.c0, a.c0); fp2_neg(r.c1, a.c1); fp2_neg(r.c2, a.c2);
}
static inline bool fp6_eq(const fp6& a, const fp6& b) {
    return fp2_eq(a.c0, b.c0) && fp2_eq(a.c1, b.c1) && fp2_eq(a.c2, b.c2);
}

static void fp6_mul(fp6& r, const fp6& a, const fp6& b) {
    fp2 t00, t11, t22, m, s, acc;
    fp2_mul(t00, a.c0, b.c0);
    fp2_mul(t11, a.c1, b.c1);
    fp2_mul(t22, a.c2, b.c2);
    // c0 = t00 + xi*(a1 b2 + a2 b1)
    fp2_mul(m, a.c1, b.c2);
    fp2_mul(s, a.c2, b.c1);
    fp2_add(m, m, s);
    fp2_mul_xi(m, m);
    fp2 r0; fp2_add(r0, t00, m);
    // c1 = a0 b1 + a1 b0 + xi t22
    fp2_mul(m, a.c0, b.c1);
    fp2_mul(s, a.c1, b.c0);
    fp2_add(acc, m, s);
    fp2_mul_xi(m, t22);
    fp2 r1; fp2_add(r1, acc, m);
    // c2 = a0 b2 + a2 b0 + t11
    fp2_mul(m, a.c0, b.c2);
    fp2_mul(s, a.c2, b.c0);
    fp2_add(acc, m, s);
    fp2 r2; fp2_add(r2, acc, t11);
    r.c0 = r0; r.c1 = r1; r.c2 = r2;
}

static inline void fp6_mul_by_v(fp6& r, const fp6& a) {
    fp2 t;
    fp2_mul_xi(t, a.c2);
    fp2 a0 = a.c0, a1 = a.c1;
    r.c0 = t; r.c1 = a0; r.c2 = a1;
}

// A * (s00, 0, 0)
static inline void fp6_mul_by_c0(fp6& r, const fp6& a, const fp2& s00) {
    fp2_mul(r.c0, a.c0, s00);
    fp2_mul(r.c1, a.c1, s00);
    fp2_mul(r.c2, a.c2, s00);
}

// A * (0, b, c)
static void fp6_mul_by_c12(fp6& r, const fp6& a, const fp2& b, const fp2& c) {
    fp2 t, s;
    fp2_mul(t, a.c1, c);
    fp2_mul(s, a.c2, b);
    fp2_add(t, t, s);
    fp2 r0; fp2_mul_xi(r0, t);
    fp2_mul(t, a.c2, c);
    fp2_mul_xi(t, t);
    fp2_mul(s, a.c0, b);
    fp2 r1; fp2_add(r1, t, s);
    fp2_mul(t, a.c0, c);
    fp2_mul(s, a.c1, b);
    fp2 r2; fp2_add(r2, t, s);
    r.c0 = r0; r.c1 = r1; r.c2 = r2;
}

static void fp6_inv(fp6& r, const fp6& a) {
    fp2 t0, t1, t2, m, s, norm, ninv;
    fp2_sqr(t0, a.c0);
    fp2_mul(m, a.c1, a.c2);
    fp2_mul_xi(m, m);
    fp2_sub(t0, t0, m);                 // a0^2 - xi a1 a2
    fp2_sqr(t1, a.c2);
    fp2_mul_xi(t1, t1);
    fp2_mul(m, a.c0, a.c1);
    fp2_sub(t1, t1, m);                 // xi a2^2 - a0 a1
    fp2_sqr(t2, a.c1);
    fp2_mul(m, a.c0, a.c2);
    fp2_sub(t2, t2, m);                 // a1^2 - a0 a2
    fp2_mul(m, a.c2, t1);
    fp2_mul(s, a.c1, t2);
    fp2_add(m, m, s);
    fp2_mul_xi(m, m);
    fp2_mul(s, a.c0, t0);
    fp2_add(norm, s, m);
    fp2_inv(ninv, norm);
    fp2_mul(r.c0, t0, ninv);
    fp2_mul(r.c1, t1, ninv);
    fp2_mul(r.c2, t2, ninv);
}

static inline void fp12_conj(fp12& r, const fp12& a) {
    r.c0 = a.c0; fp6_neg(r.c1, a.c1);
}
static inline bool fp12_eq(const fp12& a, const fp12& b) {
    return fp6_eq(a.c0, b.c0) && fp6_eq(a.c1, b.c1);
}

static void fp12_mul(fp12& r, const fp12& a, const fp12& b) {
    fp6 t0, t1, sa, sb, m;
    fp6_mul(t0, a.c0, b.c0);
    fp6_mul(t1, a.c1, b.c1);
    fp6_add(sa, a.c0, a.c1);
    fp6_add(sb, b.c0, b.c1);
    fp6_mul(m, sa, sb);
    fp6_sub(m, m, t0);
    fp6 r1; fp6_sub(r1, m, t1);
    fp6 vt; fp6_mul_by_v(vt, t1);
    fp6 r0; fp6_add(r0, t0, vt);
    r.c0 = r0; r.c1 = r1;
}

static void fp12_sqr(fp12& r, const fp12& a) {
    // complex squaring: c0 = (a0+a1)(a0+v a1) - t - v t, c1 = 2t, t = a0 a1
    fp6 t, s0, va1, s1, m, vt;
    fp6_mul(t, a.c0, a.c1);
    fp6_add(s0, a.c0, a.c1);
    fp6_mul_by_v(va1, a.c1);
    fp6_add(s1, a.c0, va1);
    fp6_mul(m, s0, s1);
    fp6_sub(m, m, t);
    fp6_mul_by_v(vt, t);
    fp6_sub(m, m, vt);
    r.c0 = m;
    fp6_add(r.c1, t, t);
}

static void fp12_inv(fp12& r, const fp12& a) {
    fp6 t0, t1, norm, ninv;
    fp6_mul(t0, a.c0, a.c0);
    fp6_mul(t1, a.c1, a.c1);
    fp6 vt; fp6_mul_by_v(vt, t1);
    fp6_sub(norm, t0, vt);
    fp6_inv(ninv, norm);
    fp6_mul(r.c0, a.c0, ninv);
    fp6 na; fp6_neg(na, a.c1);
    fp6_mul(r.c1, na, ninv);
}

// sparse mul by line (c00; 0; 0 | 0; c11; c12)
static void fp12_mul_sparse(fp12& r, const fp12& f,
                            const fp2& s00, const fp2& s11, const fp2& s12) {
    fp6 t0, t1, sum, fs, m;
    fp6_mul_by_c0(t0, f.c0, s00);
    fp6_mul_by_c12(t1, f.c1, s11, s12);
    fp6 vt; fp6_mul_by_v(vt, t1);
    fp6 r0; fp6_add(r0, t0, vt);
    sum.c0 = s00; sum.c1 = s11; sum.c2 = s12;
    fp6_add(fs, f.c0, f.c1);
    fp6_mul(m, fs, sum);
    fp6_sub(m, m, t0);
    fp6 r1; fp6_sub(r1, m, t1);
    r.c0 = r0; r.c1 = r1;
}

// Frobenius: FR1[i] = xi^(i(p-1)/6) in Fp2; FR2[i] = xi^(i(p^2-1)/6) in Fp.
static fp2 FR1[6];
static fp FR2[6];
static fp2 PSI_CX_, PSI_CY_;  // psi constants: inv(FR1[2]), inv(FR1[3])

// a^(p): conjugate Fp2 coefficients, multiply basis v^j w^k by FR1[2j+k]
static void fp12_frob1(fp12& r, const fp12& a) {
    fp2 t;
    fp2_conj(t, a.c0.c0); fp2_mul(r.c0.c0, t, FR1[0]);
    fp2_conj(t, a.c0.c1); fp2_mul(r.c0.c1, t, FR1[2]);
    fp2_conj(t, a.c0.c2); fp2_mul(r.c0.c2, t, FR1[4]);
    fp2_conj(t, a.c1.c0); fp2_mul(r.c1.c0, t, FR1[1]);
    fp2_conj(t, a.c1.c1); fp2_mul(r.c1.c1, t, FR1[3]);
    fp2_conj(t, a.c1.c2); fp2_mul(r.c1.c2, t, FR1[5]);
}

// a^(p^2): multiply basis v^j w^k by the Fp scalar FR2[2j+k]
static void fp12_frob2(fp12& r, const fp12& a) {
    fp2_mul_fp(r.c0.c0, a.c0.c0, FR2[0]);
    fp2_mul_fp(r.c0.c1, a.c0.c1, FR2[2]);
    fp2_mul_fp(r.c0.c2, a.c0.c2, FR2[4]);
    fp2_mul_fp(r.c1.c0, a.c1.c0, FR2[1]);
    fp2_mul_fp(r.c1.c1, a.c1.c1, FR2[3]);
    fp2_mul_fp(r.c1.c2, a.c1.c2, FR2[5]);
}

static void fp12_pow_limbs(fp12& r, const fp12& base, const u64* e, int nl) {
    int top = -1;
    for (int i = nl - 1; i >= 0 && top < 0; --i)
        if (e[i]) for (int b = 63; b >= 0; --b)
            if ((e[i] >> b) & 1) { top = i * 64 + b; break; }
    if (top < 0) { r = FP12_ONE_; return; }
    fp12 acc = base;
    for (int k = top - 1; k >= 0; --k) {
        fp12_sqr(acc, acc);
        if ((e[k / 64] >> (k % 64)) & 1) fp12_mul(acc, acc, base);
    }
    r = acc;
}

// f^|x| (cyclotomic input; plain squarings keep it simple and safe)
static void fp12_pow_x_abs(fp12& r, const fp12& f) {
    u64 e[1] = {X_ABS};
    fp12_pow_limbs(r, f, e, 1);
}

// Exact final exponentiation: easy part, then
// hard = d*(x+p)*(x^2+p^2-1) + 1 with d = (x-1)^2/3 (checked vs refimpl).
static void final_exponentiation(fp12& r, const fp12& f) {
    fp12 t, inv, fr;
    fp12_conj(t, f);
    fp12_inv(inv, f);
    fp12_mul(t, t, inv);          // f^(p^6-1)
    fp12_frob2(fr, t);
    fp12_mul(t, fr, t);           // ^(p^2+1): easy part done; cyclotomic now
    fp12 g;
    fp12_pow_limbs(g, t, D_EXP, 2);          // t^d
    fp12 gx, gp;
    fp12_pow_x_abs(gx, g);
    fp12_conj(gx, gx);                       // g^x  (x negative)
    fp12_frob1(gp, g);                       // g^p
    fp12 g2_; fp12_mul(g2_, gx, gp);         // g^(x+p)
    fp12 gxx, h;
    fp12_pow_x_abs(gxx, g2_);
    fp12_pow_x_abs(gxx, gxx);                // g2^(x^2)  (sign^2 = +)
    fp12_frob2(h, g2_);
    fp12_mul(gxx, gxx, h);                   // * g2^(p^2)
    fp12_conj(h, g2_);                       // g2^(-1) (cyclotomic)
    fp12_mul(gxx, gxx, h);                   // g2^(x^2+p^2-1)
    fp12_mul(r, gxx, t);                     // * t  (the +1)
}

// ---------------------------------------------------------------------------
// Curve points: homogeneous projective over Fp (G1) and Fp2 (G2).
// Generic via templates; b coefficients set at init.
// ---------------------------------------------------------------------------

struct OpsFp {
    typedef fp El;
    static void add(El& r, const El& a, const El& b) { fp_add(r, a, b); }
    static void sub(El& r, const El& a, const El& b) { fp_sub(r, a, b); }
    static void mul(El& r, const El& a, const El& b) { fp_mul(r, a, b); }
    static void sqr(El& r, const El& a) { fp_sqr(r, a); }
    static void neg(El& r, const El& a) { fp_neg(r, a); }
    static void inv(El& r, const El& a) { fp_inv(r, a); }
    static bool is_zero(const El& a) { return fp_is_zero(a); }
    static bool eq(const El& a, const El& b) { return fp_eq(a, b); }
    static El zero() { return FP_ZERO; }
    static El one() { return FP_ONE_MONT; }
    static El curve_b;
};
struct OpsFp2 {
    typedef fp2 El;
    static void add(El& r, const El& a, const El& b) { fp2_add(r, a, b); }
    static void sub(El& r, const El& a, const El& b) { fp2_sub(r, a, b); }
    static void mul(El& r, const El& a, const El& b) { fp2_mul(r, a, b); }
    static void sqr(El& r, const El& a) { fp2_sqr(r, a); }
    static void neg(El& r, const El& a) { fp2_neg(r, a); }
    static void inv(El& r, const El& a) { fp2_inv(r, a); }
    static bool is_zero(const El& a) { return fp2_is_zero(a); }
    static bool eq(const El& a, const El& b) { return fp2_eq(a, b); }
    static El zero() { return FP2_ZERO_; }
    static El one() { return FP2_ONE_; }
    static El curve_b;
};
fp OpsFp::curve_b;
fp2 OpsFp2::curve_b;

template <class O> struct pt {
    typename O::El X, Y, Z;
    bool inf;
};

template <class O> static pt<O> pt_infinity() {
    pt<O> p;
    p.X = O::zero(); p.Y = O::one(); p.Z = O::zero(); p.inf = true;
    return p;
}

template <class O>
static pt<O> pt_from_affine(const typename O::El& x, const typename O::El& y) {
    pt<O> p;
    p.X = x; p.Y = y; p.Z = O::one(); p.inf = false;
    return p;
}

template <class O>
static void pt_to_affine(typename O::El& x, typename O::El& y, const pt<O>& p) {
    typename O::El zi;
    O::inv(zi, p.Z);
    O::mul(x, p.X, zi);
    O::mul(y, p.Y, zi);
}

// projective doubling, a = 0 curve
template <class O> static void pt_dbl(pt<O>& r, const pt<O>& p) {
    if (p.inf || O::is_zero(p.Y)) { r = pt_infinity<O>(); return; }
    typedef typename O::El El;
    El XX, W, S, B, H, t, t2, YY, SS;
    O::sqr(XX, p.X);
    O::add(W, XX, XX); O::add(W, W, XX);          // 3X^2
    O::mul(S, p.Y, p.Z);                          // YZ
    O::mul(B, p.X, p.Y); O::mul(B, B, S);         // XY*S
    O::sqr(H, W);
    O::add(t, B, B); O::add(t, t, t); O::add(t2, t, t);  // 8B
    O::sub(H, H, t2);                             // W^2 - 8B
    O::mul(r.X, H, S); O::add(r.X, r.X, r.X);     // 2HS
    O::sqr(YY, p.Y);
    O::sqr(SS, S);
    O::sub(t, t, H);                              // 4B - H
    O::mul(t, W, t);
    O::mul(t2, YY, SS);
    O::add(t2, t2, t2); O::add(t2, t2, t2); O::add(t2, t2, t2);  // 8 Y^2 S^2
    O::sub(r.Y, t, t2);
    El S3;
    O::mul(S3, SS, S);
    O::add(r.Z, S3, S3); O::add(r.Z, r.Z, r.Z); O::add(r.Z, r.Z, r.Z);  // 8S^3
    r.inf = false;
    if (O::is_zero(r.Z)) r = pt_infinity<O>();
}

// mixed addition: p (projective) + q (affine)
template <class O>
static void pt_add_affine(pt<O>& r, const pt<O>& p,
                          const typename O::El& qx, const typename O::El& qy) {
    typedef typename O::El El;
    if (p.inf) { r = pt_from_affine<O>(qx, qy); return; }
    El u, v, t;
    O::mul(u, qy, p.Z); O::sub(u, u, p.Y);        // yQ Z - Y
    O::mul(v, qx, p.Z); O::sub(v, v, p.X);        // xQ Z - X
    if (O::is_zero(v)) {
        if (O::is_zero(u)) { pt_dbl(r, p); return; }
        r = pt_infinity<O>();
        return;
    }
    El vv, vvv, R_, A, uu;
    O::sqr(vv, v);
    O::mul(vvv, vv, v);
    O::mul(R_, vv, p.X);
    O::sqr(uu, u);
    O::mul(A, uu, p.Z);
    O::sub(A, A, vvv);
    O::add(t, R_, R_);
    O::sub(A, A, t);                              // u^2 Z - v^3 - 2 v^2 X
    O::mul(r.X, v, A);
    O::sub(t, R_, A);
    O::mul(t, u, t);
    El t2;
    O::mul(t2, vvv, p.Y);
    O::sub(r.Y, t, t2);
    O::mul(r.Z, vvv, p.Z);
    r.inf = false;
    if (O::is_zero(r.Z)) r = pt_infinity<O>();
}

template <class O> static void pt_add(pt<O>& r, const pt<O>& p, const pt<O>& q) {
    if (q.inf) { r = p; return; }
    if (p.inf) { r = q; return; }
    typename O::El qx, qy;
    pt_to_affine(qx, qy, q);   // simple + rare in hot paths (buckets use mixed)
    pt_add_affine(r, p, qx, qy);
}

// scalar mult, MSB-first double-and-add over limb scalar
template <class O>
static void pt_mul_limbs(pt<O>& r, const pt<O>& p, const u64* e, int nl) {
    pt<O> acc = pt_infinity<O>();
    int top = -1;
    for (int i = nl - 1; i >= 0 && top < 0; --i)
        if (e[i]) for (int b = 63; b >= 0; --b)
            if ((e[i] >> b) & 1) { top = i * 64 + b; break; }
    if (top < 0 || p.inf) { r = acc; return; }
    typename O::El px, py;
    pt_to_affine(px, py, p);
    acc = pt_from_affine<O>(px, py);
    for (int k = top - 1; k >= 0; --k) {
        pt_dbl(acc, acc);
        if ((e[k / 64] >> (k % 64)) & 1) pt_add_affine(acc, acc, px, py);
    }
    r = acc;
}

template <class O> static bool pt_on_curve_affine(const typename O::El& x,
                                                  const typename O::El& y) {
    typename O::El lhs, rhs;
    O::sqr(lhs, y);
    O::sqr(rhs, x);
    O::mul(rhs, rhs, x);
    O::add(rhs, rhs, O::curve_b);
    return O::eq(lhs, rhs);
}

typedef pt<OpsFp> g1pt;
typedef pt<OpsFp2> g2pt;

static fp G1_GX, G1_GY;    // generator affine (set in init)
static fp2 G2_GX, G2_GY;

// ---------------------------------------------------------------------------
// Miller loop (optimal ate) and pairing products.
// P in G1 affine (Fp), Q in G2 affine (Fp2).  Lines are scaled by Fp2
// factors only (see header), so final-exp output matches refimpl exactly.
// ---------------------------------------------------------------------------

struct g1aff { fp x, y; bool inf; };
struct g2aff { fp2 x, y; bool inf; };

// doubling step: updates T, emits line coefficients evaluated at P
static void dbl_step(g2pt& T, fp2& l00, fp2& l11, fp2& l12,
                     const fp& px, const fp& py) {
    fp2 XX, W, YY, S, SS, t;
    fp2_sqr(XX, T.X);
    fp2_add(W, XX, XX); fp2_add(W, W, XX);        // 3X^2
    fp2_sqr(YY, T.Y);
    fp2_mul(S, T.Y, T.Z);                         // YZ
    fp2_sqr(SS, S);
    // l11 = 3X^3 - 2Y^2 Z
    fp2 X3, Y2Z;
    fp2_mul(X3, XX, T.X);
    fp2_add(t, X3, X3); fp2_add(X3, t, X3);       // 3X^3
    fp2_mul(Y2Z, YY, T.Z);
    fp2_add(Y2Z, Y2Z, Y2Z);                       // 2Y^2 Z
    fp2_sub(l11, X3, Y2Z);
    // l12 = -(3X^2 Z) * xP
    fp2 WZ;
    fp2_mul(WZ, W, T.Z);
    fp2_mul_fp(WZ, WZ, px);
    fp2_neg(l12, WZ);
    // l00 = xi * (2 Y Z^2) * yP       (2YZ^2 = 2 S Z)
    fp2 SZ;
    fp2_mul(SZ, S, T.Z);
    fp2_add(SZ, SZ, SZ);
    fp2_mul_fp(SZ, SZ, py);
    fp2_mul_xi(l00, SZ);
    // point doubling (same as pt_dbl, reusing XX/W/S/YY/SS)
    fp2 B, H, t8b, Ynew;
    fp2_mul(B, T.X, T.Y); fp2_mul(B, B, S);
    fp2_sqr(H, W);
    fp2_add(t, B, B); fp2_add(t, t, t);           // 4B
    fp2_add(t8b, t, t);                           // 8B
    fp2_sub(H, H, t8b);
    fp2_mul(T.X, H, S); fp2_add(T.X, T.X, T.X);
    fp2_sub(t, t, H);                             // 4B - H
    fp2_mul(Ynew, W, t);
    fp2_mul(t, YY, SS);
    fp2_add(t, t, t); fp2_add(t, t, t); fp2_add(t, t, t);
    fp2_sub(T.Y, Ynew, t);
    fp2 S3;
    fp2_mul(S3, SS, S);
    fp2_add(T.Z, S3, S3); fp2_add(T.Z, T.Z, T.Z); fp2_add(T.Z, T.Z, T.Z);
}

// addition step: T += Q, line through T and Q evaluated at P
static void add_step(g2pt& T, fp2& l00, fp2& l11, fp2& l12,
                     const g2aff& Q, const fp& px, const fp& py) {
    fp2 theta, mu, t;
    fp2_mul(theta, Q.y, T.Z); fp2_sub(theta, T.Y, theta);  // Y - yQ Z
    fp2_mul(mu, Q.x, T.Z); fp2_sub(mu, T.X, mu);           // X - xQ Z
    // l11 = theta xQ - mu yQ ; l12 = -theta xP ; l00 = xi mu yP
    fp2 a, b;
    fp2_mul(a, theta, Q.x);
    fp2_mul(b, mu, Q.y);
    fp2_sub(l11, a, b);
    fp2_mul_fp(t, theta, px);
    fp2_neg(l12, t);
    fp2_mul_fp(t, mu, py);
    fp2_mul_xi(l00, t);
    // T += Q (mixed, u = -theta, v = -mu)
    fp2 u, v;
    fp2_neg(u, theta);
    fp2_neg(v, mu);
    fp2 vv, vvv, R_, A, uu, t2;
    fp2_sqr(vv, v);
    fp2_mul(vvv, vv, v);
    fp2_mul(R_, vv, T.X);
    fp2_sqr(uu, u);
    fp2_mul(A, uu, T.Z);
    fp2_sub(A, A, vvv);
    fp2_add(t, R_, R_);
    fp2_sub(A, A, t);
    fp2_mul(T.X, v, A);
    fp2_sub(t, R_, A);
    fp2_mul(t, u, t);
    fp2_mul(t2, vvv, T.Y);
    fp2_sub(T.Y, t, t2);
    fp2_mul(t, vvv, T.Z);
    T.Z = t;
}

// f *= miller(P, Q); skips infinity inputs (contributes 1, as refimpl).
static void miller_accumulate(fp12& f, const g1aff& P, const g2aff& Q) {
    if (P.inf || Q.inf) return;
    g2pt T = pt_from_affine<OpsFp2>(Q.x, Q.y);
    fp2 l00, l11, l12;
    bool first = true;
    fp12 g = FP12_ONE_;
    for (int k = 62; k >= 0; --k) {       // bits of |x| below the top bit
        if (!first) fp12_sqr(g, g);
        dbl_step(T, l00, l11, l12, P.x, P.y);
        fp12_mul_sparse(g, g, l00, l11, l12);
        if ((X_ABS >> k) & 1) {
            add_step(T, l00, l11, l12, Q, P.x, P.y);
            fp12_mul_sparse(g, g, l00, l11, l12);
        }
        first = false;
    }
    fp12_conj(g, g);                      // x < 0
    fp12 nf;
    fp12_mul(nf, f, g);
    f = nf;
}

static void pairing_full(fp12& out, const g1aff& P, const g2aff& Q) {
    fp12 f = FP12_ONE_;
    miller_accumulate(f, P, Q);
    final_exponentiation(out, f);
}

// ---------------------------------------------------------------------------
// psi endomorphism + subgroup checks + cofactor clearing.
// ---------------------------------------------------------------------------

static void g2_psi_aff(g2aff& r, const g2aff& p) {
    if (p.inf) { r = p; return; }
    fp2 cx, cy;
    fp2_conj(cx, p.x);
    fp2_conj(cy, p.y);
    fp2_mul(r.x, PSI_CX_, cx);
    fp2_mul(r.y, PSI_CY_, cy);
    r.inf = false;
}

static g2aff g2_to_aff(const g2pt& p) {
    g2aff r;
    if (p.inf) { r.inf = true; r.x = FP2_ZERO_; r.y = FP2_ZERO_; return r; }
    pt_to_affine(r.x, r.y, p);
    r.inf = false;
    return r;
}

static g1aff g1_to_aff(const g1pt& p) {
    g1aff r;
    if (p.inf) { r.inf = true; r.x = FP_ZERO; r.y = FP_ZERO; return r; }
    pt_to_affine(r.x, r.y, p);
    r.inf = false;
    return r;
}

static bool g1_in_subgroup(const g1pt& p) {
    g1pt t;
    pt_mul_limbs(t, p, R_L, 4);
    return t.inf;
}

static bool g2_in_subgroup(const g2pt& p) {
    g2pt t;
    pt_mul_limbs(t, p, R_L, 4);
    return t.inf;
}

// [x]P for the negative BLS parameter (refimpl._g2_mul_x): -[|x|]P
static void g2_mul_x(g2pt& r, const g2pt& p) {
    u64 e[1] = {X_ABS};
    g2pt t;
    pt_mul_limbs(t, p, e, 1);
    if (!t.inf) fp2_neg(t.Y, t.Y);
    r = t;
}

// Budroni–Pintore: h_eff P = [x^2-x-1]P + [x-1]psi(P) + psi(psi([2]P))
static void g2_clear_cofactor(g2pt& r, const g2pt& p) {
    g2pt xp, x2p, t, part1, part2, part3;
    g2_mul_x(xp, p);
    g2_mul_x(x2p, xp);
    pt_add(t, xp, p);
    if (!t.inf) fp2_neg(t.Y, t.Y);
    pt_add(part1, x2p, t);                       // [x^2 - x - 1] P
    g2aff pa = g2_to_aff(p), psip_a;
    g2_psi_aff(psip_a, pa);
    g2pt psip = psip_a.inf ? pt_infinity<OpsFp2>()
                           : pt_from_affine<OpsFp2>(psip_a.x, psip_a.y);
    g2pt xpsip, npsip;
    g2_mul_x(xpsip, psip);
    npsip = psip;
    if (!npsip.inf) fp2_neg(npsip.Y, npsip.Y);
    pt_add(part2, xpsip, npsip);                 // [x-1] psi(P)
    g2pt dp;
    pt_dbl(dp, p);
    g2aff dpa = g2_to_aff(dp), ps1, ps2;
    g2_psi_aff(ps1, dpa);
    g2_psi_aff(ps2, ps1);
    part3 = ps2.inf ? pt_infinity<OpsFp2>()
                    : pt_from_affine<OpsFp2>(ps2.x, ps2.y);
    pt_add(t, part1, part2);
    pt_add(r, t, part3);
}

static void g1_clear_cofactor(g1pt& r, const g1pt& p) {
    pt_mul_limbs(r, p, D_EXP, 2);                // H1 = (x-1)^2/3
}

// ---------------------------------------------------------------------------
// SHA-256 (compact, for expand_message_xmd)
// ---------------------------------------------------------------------------

struct sha256_ctx { uint32_t h[8]; uint8_t buf[64]; u64 len; size_t fill; };

static const uint32_t SHA_K[64] = {
    0x428a2f98,0x71374491,0xb5c0fbcf,0xe9b5dba5,0x3956c25b,0x59f111f1,
    0x923f82a4,0xab1c5ed5,0xd807aa98,0x12835b01,0x243185be,0x550c7dc3,
    0x72be5d74,0x80deb1fe,0x9bdc06a7,0xc19bf174,0xe49b69c1,0xefbe4786,
    0x0fc19dc6,0x240ca1cc,0x2de92c6f,0x4a7484aa,0x5cb0a9dc,0x76f988da,
    0x983e5152,0xa831c66d,0xb00327c8,0xbf597fc7,0xc6e00bf3,0xd5a79147,
    0x06ca6351,0x14292967,0x27b70a85,0x2e1b2138,0x4d2c6dfc,0x53380d13,
    0x650a7354,0x766a0abb,0x81c2c92e,0x92722c85,0xa2bfe8a1,0xa81a664b,
    0xc24b8b70,0xc76c51a3,0xd192e819,0xd6990624,0xf40e3585,0x106aa070,
    0x19a4c116,0x1e376c08,0x2748774c,0x34b0bcb5,0x391c0cb3,0x4ed8aa4a,
    0x5b9cca4f,0x682e6ff3,0x748f82ee,0x78a5636f,0x84c87814,0x8cc70208,
    0x90befffa,0xa4506ceb,0xbef9a3f7,0xc67178f2,
};

static inline uint32_t rotr32(uint32_t x, int n) {
    return (x >> n) | (x << (32 - n));
}

static void sha256_init(sha256_ctx& c) {
    static const uint32_t H0[8] = {
        0x6a09e667,0xbb67ae85,0x3c6ef372,0xa54ff53a,
        0x510e527f,0x9b05688c,0x1f83d9ab,0x5be0cd19,
    };
    memcpy(c.h, H0, sizeof H0);
    c.len = 0; c.fill = 0;
}

static void sha256_block(sha256_ctx& c, const uint8_t* p) {
    uint32_t w[64];
    for (int i = 0; i < 16; ++i)
        w[i] = ((uint32_t)p[4*i] << 24) | ((uint32_t)p[4*i+1] << 16) |
               ((uint32_t)p[4*i+2] << 8) | p[4*i+3];
    for (int i = 16; i < 64; ++i) {
        uint32_t s0 = rotr32(w[i-15],7) ^ rotr32(w[i-15],18) ^ (w[i-15] >> 3);
        uint32_t s1 = rotr32(w[i-2],17) ^ rotr32(w[i-2],19) ^ (w[i-2] >> 10);
        w[i] = w[i-16] + s0 + w[i-7] + s1;
    }
    uint32_t a=c.h[0],b=c.h[1],cc=c.h[2],d=c.h[3],
             e=c.h[4],f=c.h[5],g=c.h[6],h=c.h[7];
    for (int i = 0; i < 64; ++i) {
        uint32_t S1 = rotr32(e,6) ^ rotr32(e,11) ^ rotr32(e,25);
        uint32_t ch = (e & f) ^ (~e & g);
        uint32_t t1 = h + S1 + ch + SHA_K[i] + w[i];
        uint32_t S0 = rotr32(a,2) ^ rotr32(a,13) ^ rotr32(a,22);
        uint32_t mj = (a & b) ^ (a & cc) ^ (b & cc);
        uint32_t t2 = S0 + mj;
        h=g; g=f; f=e; e=d+t1; d=cc; cc=b; b=a; a=t1+t2;
    }
    c.h[0]+=a; c.h[1]+=b; c.h[2]+=cc; c.h[3]+=d;
    c.h[4]+=e; c.h[5]+=f; c.h[6]+=g; c.h[7]+=h;
}

static void sha256_update(sha256_ctx& c, const uint8_t* p, size_t n) {
    c.len += n;
    while (n) {
        size_t take = 64 - c.fill;
        if (take > n) take = n;
        memcpy(c.buf + c.fill, p, take);
        c.fill += take; p += take; n -= take;
        if (c.fill == 64) { sha256_block(c, c.buf); c.fill = 0; }
    }
}

static void sha256_final(sha256_ctx& c, uint8_t out[32]) {
    u64 bits = c.len * 8;
    uint8_t pad = 0x80;
    sha256_update(c, &pad, 1);
    uint8_t z = 0;
    while (c.fill != 56) sha256_update(c, &z, 1);
    uint8_t lb[8];
    for (int i = 0; i < 8; ++i) lb[i] = (uint8_t)(bits >> (8 * (7 - i)));
    sha256_update(c, lb, 8);
    for (int i = 0; i < 8; ++i)
        for (int j = 0; j < 4; ++j)
            out[4*i + j] = (uint8_t)(c.h[i] >> (8 * (3 - j)));
}

// ---------------------------------------------------------------------------
// expand_message_xmd + hash_to_field (RFC 9380 §5, SHA-256), DSTs pinned to
// refimpl.DST_G1/DST_G2.
// ---------------------------------------------------------------------------

static const char DST_G2_S[] = "DRANDTPU-V01-CS01-BLS12381G2_XMD:SHA-256_SVDW_RO_";
static const char DST_G1_S[] = "DRANDTPU-V01-CS01-BLS12381G1_XMD:SHA-256_SVDW_RO_";

static void expand_message_xmd(uint8_t* out, size_t len_in_bytes,
                               const uint8_t* msg, size_t msg_len,
                               const uint8_t* dst, size_t dst_len) {
    const size_t b_in_bytes = 32, s_in_bytes = 64;
    size_t ell = (len_in_bytes + b_in_bytes - 1) / b_in_bytes;
    uint8_t dst_prime[256];
    memcpy(dst_prime, dst, dst_len);
    dst_prime[dst_len] = (uint8_t)dst_len;
    size_t dpl = dst_len + 1;
    uint8_t zpad[s_in_bytes];
    memset(zpad, 0, sizeof zpad);
    uint8_t lib[2] = {(uint8_t)(len_in_bytes >> 8), (uint8_t)len_in_bytes};
    uint8_t zero = 0;
    sha256_ctx c;
    uint8_t b0[32], bi[32];
    sha256_init(c);
    sha256_update(c, zpad, s_in_bytes);
    sha256_update(c, msg, msg_len);
    sha256_update(c, lib, 2);
    sha256_update(c, &zero, 1);
    sha256_update(c, dst_prime, dpl);
    sha256_final(c, b0);
    uint8_t ctr = 1;
    sha256_init(c);
    sha256_update(c, b0, 32);
    sha256_update(c, &ctr, 1);
    sha256_update(c, dst_prime, dpl);
    sha256_final(c, bi);
    size_t off = 0;
    for (size_t i = 1; ; ++i) {
        size_t take = len_in_bytes - off;
        if (take > 32) take = 32;
        memcpy(out + off, bi, take);
        off += take;
        if (off >= len_in_bytes || i >= ell) break;
        uint8_t x[32];
        for (int j = 0; j < 32; ++j) x[j] = b0[j] ^ bi[j];
        ctr = (uint8_t)(i + 1);
        sha256_init(c);
        sha256_update(c, x, 32);
        sha256_update(c, &ctr, 1);
        sha256_update(c, dst_prime, dpl);
        sha256_final(c, bi);
    }
}

// reduce 64 big-endian bytes mod p, to Montgomery form
static void fp_from_wide_be(fp& r, const uint8_t in[64]) {
    // value = hi(16 bytes) * 2^384 + lo(48 bytes)
    u64 lo[6] = {0}, hi[6] = {0};
    for (int i = 0; i < 6; ++i)
        for (int j = 0; j < 8; ++j)
            lo[i] |= (u64)in[64 - 8 * (i + 1) + (7 - j)] << (8 * j);
    for (int i = 0; i < 2; ++i)
        for (int j = 0; j < 8; ++j)
            hi[i] |= (u64)in[16 - 8 * (i + 1) + (7 - j)] << (8 * j);
    while (fp_cmp_raw(lo, P_L, 6) >= 0) sub_limbs(lo, lo, P_L, 6);
    fp lo_f, hi_f, hi_mont, hi_shift;
    memcpy(lo_f.l, lo, sizeof lo);
    memcpy(hi_f.l, hi, sizeof hi);
    fp_mul(lo_f, lo_f, R2);        // to_mont(lo) = lo·R
    fp_mul(hi_mont, hi_f, R2);     // to_mont(hi) = hi·R
    fp_mul(hi_shift, hi_mont, R2); // (hi·R)·R²/R = hi·R² = to_mont(hi·2^384)
    fp_add(r, lo_f, hi_shift);
}

static void hash_to_field_fp2_2(fp2 u[2], const uint8_t* msg, size_t len) {
    uint8_t buf[4 * 64];
    expand_message_xmd(buf, sizeof buf, msg, len,
                       (const uint8_t*)DST_G2_S, sizeof(DST_G2_S) - 1);
    for (int i = 0; i < 2; ++i) {
        fp_from_wide_be(u[i].c0, buf + i * 128);
        fp_from_wide_be(u[i].c1, buf + i * 128 + 64);
    }
}

static void hash_to_field_fp_2(fp u[2], const uint8_t* msg, size_t len) {
    uint8_t buf[2 * 64];
    expand_message_xmd(buf, sizeof buf, msg, len,
                       (const uint8_t*)DST_G1_S, sizeof(DST_G1_S) - 1);
    fp_from_wide_be(u[0], buf);
    fp_from_wide_be(u[1], buf + 64);
}

// ---------------------------------------------------------------------------
// SVDW map (RFC 9380 §6.6.1), constants derived at init from the pinned Z
// (Z_G1 = -3, Z_G2 = u — the values refimpl's small-magnitude search finds;
// init asserts the SVDW preconditions, tests pin byte equality).
// ---------------------------------------------------------------------------

template <class O> struct svdw {
    typename O::El Z, c1, c2, c3, c4;
};

static svdw<OpsFp> SVDW1;
static svdw<OpsFp2> SVDW2;

template <class O>
static bool svdw_init(svdw<O>& s, const typename O::El& z,
                      bool (*is_square)(const typename O::El&),
                      bool (*sqrt_fn)(typename O::El&, const typename O::El&),
                      int (*sgn0_fn)(const typename O::El&)) {
    typedef typename O::El El;
    s.Z = z;
    El zz, gz, t, h;
    O::sqr(zz, z);
    O::mul(gz, zz, z);
    O::add(gz, gz, O::curve_b);               // g(Z)
    if (O::is_zero(gz)) return false;
    s.c1 = gz;
    El two, inv2;
    O::add(two, O::one(), O::one());
    O::inv(inv2, two);
    O::mul(t, z, inv2);
    O::neg(s.c2, t);                          // -Z/2
    O::add(h, zz, zz); O::add(h, h, zz);      // 3Z^2
    if (O::is_zero(h)) return false;
    El gh, c3;
    O::mul(gh, gz, h);
    O::neg(gh, gh);
    if (!sqrt_fn(c3, gh)) return false;       // sqrt(-g(Z)·3Z^2)
    if (sgn0_fn(c3) == 1) O::neg(c3, c3);
    s.c3 = c3;
    El num, hinv;
    O::add(num, gz, gz); O::add(num, num, num);  // 4 g(Z)
    O::neg(num, num);
    O::inv(hinv, h);
    O::mul(s.c4, num, hinv);                  // -4 g(Z) / (3Z^2)
    return true;
}

template <class O>
static void svdw_map(typename O::El& x, typename O::El& y, const svdw<O>& s,
                     const typename O::El& u,
                     bool (*is_square)(const typename O::El&),
                     bool (*sqrt_fn)(typename O::El&, const typename O::El&),
                     int (*sgn0_fn)(const typename O::El&)) {
    typedef typename O::El El;
    El tv1, tv2, tv3, tv4, x1, x2, x3, gx, t;
    O::sqr(tv1, u);
    O::mul(tv1, tv1, s.c1);                   // u^2 g(Z)
    O::add(tv2, O::one(), tv1);               // 1 + tv1
    O::sub(tv1, O::one(), tv1);               // 1 - tv1
    O::mul(tv3, tv1, tv2);
    if (O::is_zero(tv3)) tv3 = O::zero(); else O::inv(tv3, tv3);
    O::mul(tv4, u, tv1);
    O::mul(tv4, tv4, tv3);
    O::mul(tv4, tv4, s.c3);
    O::sub(x1, s.c2, tv4);
    O::add(x2, s.c2, tv4);
    O::sqr(t, tv2);
    O::mul(t, t, tv3);
    O::sqr(t, t);
    O::mul(t, t, s.c4);
    O::add(x3, t, s.Z);
    // pick first x with square g(x)
    O::sqr(gx, x1); O::mul(gx, gx, x1); O::add(gx, gx, O::curve_b);
    if (is_square(gx)) { x = x1; }
    else {
        O::sqr(gx, x2); O::mul(gx, gx, x2); O::add(gx, gx, O::curve_b);
        if (is_square(gx)) { x = x2; }
        else { x = x3; O::sqr(gx, x3); O::mul(gx, gx, x3); O::add(gx, gx, O::curve_b); }
    }
    bool ok = sqrt_fn(y, gx);
    (void)ok;  // guaranteed square by construction
    if (sgn0_fn(u) != sgn0_fn(y)) O::neg(y, y);
}

static void hash_to_g2_point(g2pt& out, const uint8_t* msg, size_t len) {
    fp2 u[2], x0, y0, x1, y1;
    hash_to_field_fp2_2(u, msg, len);
    svdw_map<OpsFp2>(x0, y0, SVDW2, u[0], fp2_is_square, fp2_sqrt, fp2_sgn0);
    svdw_map<OpsFp2>(x1, y1, SVDW2, u[1], fp2_is_square, fp2_sqrt, fp2_sgn0);
    g2pt q0 = pt_from_affine<OpsFp2>(x0, y0);
    pt_add_affine(q0, q0, x1, y1);
    g2_clear_cofactor(out, q0);
}

static void hash_to_g1_point(g1pt& out, const uint8_t* msg, size_t len) {
    fp u[2], x0, y0, x1, y1;
    hash_to_field_fp_2(u, msg, len);
    svdw_map<OpsFp>(x0, y0, SVDW1, u[0], fp_is_square, fp_sqrt, fp_sgn0);
    svdw_map<OpsFp>(x1, y1, SVDW1, u[1], fp_is_square, fp_sqrt, fp_sgn0);
    g1pt q0 = pt_from_affine<OpsFp>(x0, y0);
    pt_add_affine(q0, q0, x1, y1);
    g1_clear_cofactor(out, q0);
}

// ---------------------------------------------------------------------------
// Serialization (48/96-byte compressed, flags in top 3 bits — refimpl format)
// ---------------------------------------------------------------------------

static const uint8_t FLAG_COMPRESSED = 0x80;
static const uint8_t FLAG_INFINITY = 0x40;
static const uint8_t FLAG_SIGN = 0x20;

static void g1_serialize(uint8_t out[48], const g1aff& p) {
    if (p.inf) {
        memset(out, 0, 48);
        out[0] = FLAG_COMPRESSED | FLAG_INFINITY;
        return;
    }
    fp_to_bytes(out, p.x);
    out[0] |= FLAG_COMPRESSED;
    if (fp_is_high(p.y)) out[0] |= FLAG_SIGN;
}

static int g1_deserialize(g1aff& p, const uint8_t in[48], int subgroup_check) {
    uint8_t flags = in[0];
    if (!(flags & FLAG_COMPRESSED)) return -1;
    if (flags & FLAG_INFINITY) {
        if (flags & ~(FLAG_COMPRESSED | FLAG_INFINITY)) return -1;
        for (int i = 1; i < 48; ++i) if (in[i]) return -1;
        if (in[0] != (FLAG_COMPRESSED | FLAG_INFINITY)) return -1;
        p.inf = true; p.x = FP_ZERO; p.y = FP_ZERO;
        return 0;
    }
    uint8_t buf[48];
    memcpy(buf, in, 48);
    buf[0] &= 0x1F;
    fp x;
    if (fp_from_bytes(x, buf) != 0) return -1;
    fp rhs, y;
    fp_sqr(rhs, x);
    fp_mul(rhs, rhs, x);
    fp_add(rhs, rhs, OpsFp::curve_b);
    if (!fp_sqrt(y, rhs)) return -2;
    bool want_high = (flags & FLAG_SIGN) != 0;
    if (fp_is_high(y) != want_high) fp_neg(y, y);
    p.x = x; p.y = y; p.inf = false;
    if (subgroup_check) {
        g1pt pp = pt_from_affine<OpsFp>(x, y);
        if (!g1_in_subgroup(pp)) return -3;
    }
    return 0;
}

static void g2_serialize(uint8_t out[96], const g2aff& p) {
    if (p.inf) {
        memset(out, 0, 96);
        out[0] = FLAG_COMPRESSED | FLAG_INFINITY;
        return;
    }
    fp_to_bytes(out, p.x.c1);        // x1 first (refimpl order)
    fp_to_bytes(out + 48, p.x.c0);
    out[0] |= FLAG_COMPRESSED;
    if (fp2_is_high(p.y)) out[0] |= FLAG_SIGN;
}

static int g2_deserialize(g2aff& p, const uint8_t in[96], int subgroup_check) {
    uint8_t flags = in[0];
    if (!(flags & FLAG_COMPRESSED)) return -1;
    if (flags & FLAG_INFINITY) {
        if (flags & ~(FLAG_COMPRESSED | FLAG_INFINITY)) return -1;
        for (int i = 1; i < 96; ++i) if (in[i]) return -1;
        if (in[0] != (FLAG_COMPRESSED | FLAG_INFINITY)) return -1;
        p.inf = true; p.x = FP2_ZERO_; p.y = FP2_ZERO_;
        return 0;
    }
    uint8_t buf[48];
    memcpy(buf, in, 48);
    buf[0] &= 0x1F;
    fp2 x;
    if (fp_from_bytes(x.c1, buf) != 0) return -1;
    if (fp_from_bytes(x.c0, in + 48) != 0) return -1;
    fp2 rhs, y;
    fp2_sqr(rhs, x);
    fp2_mul(rhs, rhs, x);
    fp2_add(rhs, rhs, OpsFp2::curve_b);
    if (!fp2_sqrt(y, rhs)) return -2;
    bool want_high = (flags & FLAG_SIGN) != 0;
    if (fp2_is_high(y) != want_high) fp2_neg(y, y);
    p.x = x; p.y = y; p.inf = false;
    if (subgroup_check) {
        g2pt pp = pt_from_affine<OpsFp2>(x, y);
        if (!g2_in_subgroup(pp)) return -3;
    }
    return 0;
}

// scalar: 32 big-endian bytes -> 4x64 LE limbs, reduced mod r
static void scalar_from_bytes(u64 out[4], const uint8_t in[32]) {
    for (int i = 0; i < 4; ++i) {
        out[i] = 0;
        for (int j = 0; j < 8; ++j)
            out[i] |= (u64)in[32 - 8 * (i + 1) + (7 - j)] << (8 * j);
    }
    while (fp_cmp_raw(out, R_L, 4) >= 0) sub_limbs(out, out, R_L, 4);
}

// ---------------------------------------------------------------------------
// Pippenger MSM (window 4) over either group.
// ---------------------------------------------------------------------------

template <class O>
static void msm_run(pt<O>& result, const typename O::El* xs,
                    const typename O::El* ys, const bool* infs,
                    const u64 (*scalars)[4], size_t n) {
    const int W = 4, NWIN = 256 / W;
    pt<O> acc = pt_infinity<O>();
    for (int w = NWIN - 1; w >= 0; --w) {
        if (!acc.inf)
            for (int k = 0; k < W; ++k) pt_dbl(acc, acc);
        pt<O> buckets[15];
        for (int b = 0; b < 15; ++b) buckets[b] = pt_infinity<O>();
        int bit = w * W;
        for (size_t i = 0; i < n; ++i) {
            if (infs[i]) continue;
            int limb = bit / 64, off = bit % 64;
            u64 d = (scalars[i][limb] >> off) & 0xF;
            if (d) pt_add_affine(buckets[d - 1], buckets[d - 1], xs[i], ys[i]);
        }
        pt<O> running = pt_infinity<O>(), sum = pt_infinity<O>();
        for (int b = 14; b >= 0; --b) {
            pt_add(running, running, buckets[b]);
            pt_add(sum, sum, running);
        }
        pt_add(acc, acc, sum);
    }
    result = acc;
}

// ---------------------------------------------------------------------------
// init: derive all constants; returns 0 on success.
// ---------------------------------------------------------------------------

static bool INIT_DONE = false;
static int INIT_STATUS = -100;

static int do_init() {
    memset(&FP_ZERO, 0, sizeof FP_ZERO);
    fp_from_u64(FP_ONE_MONT, 1);
    FP2_ZERO_.c0 = FP_ZERO; FP2_ZERO_.c1 = FP_ZERO;
    FP2_ONE_.c0 = FP_ONE_MONT; FP2_ONE_.c1 = FP_ZERO;
    XI_.c0 = FP_ONE_MONT; XI_.c1 = FP_ONE_MONT;
    FP6_ZERO_.c0 = FP2_ZERO_; FP6_ZERO_.c1 = FP2_ZERO_; FP6_ZERO_.c2 = FP2_ZERO_;
    FP6_ONE_.c0 = FP2_ONE_; FP6_ONE_.c1 = FP2_ZERO_; FP6_ONE_.c2 = FP2_ZERO_;
    FP12_ONE_.c0 = FP6_ONE_; FP12_ONE_.c1 = FP6_ZERO_;
    // exponents from p
    u64 two[6] = {2, 0, 0, 0, 0, 0}, one[6] = {1, 0, 0, 0, 0, 0};
    sub_limbs(EXP_P_MINUS_2, P_L, two, 6);
    memcpy(EXP_QR, P_L, sizeof EXP_QR);
    sub_limbs(EXP_QR, EXP_QR, one, 6);
    shr_limbs(EXP_QR, 6, 1);                      // (p-1)/2
    memcpy(HALF_P, EXP_QR, sizeof HALF_P);
    memcpy(EXP_SQRT, P_L, sizeof EXP_SQRT);
    add_limbs(EXP_SQRT, EXP_SQRT, one, 6);
    shr_limbs(EXP_SQRT, 6, 2);                    // (p+1)/4
    memcpy(EXP_P16, P_L, sizeof EXP_P16);
    sub_limbs(EXP_P16, EXP_P16, one, 6);
    div_small(EXP_P16, 6, 6);                     // (p-1)/6
    // d = (x-1)^2 / 3 = (|x|+1)^2 / 3 (126-bit)
    u128 xm1 = (u128)X_ABS + 1;                   // |x - 1|
    u128 d = 0;
    {
        // (|x|+1)^2 = hi*2^64 + lo pieces via u128 school mult
        u64 a = (u64)(xm1 >> 64), b = (u64)xm1;   // a = 0 here but keep general
        (void)a;
        u128 lo = (u128)b * b;                    // fits: b < 2^64
        d = lo / 3;                               // (x-1)^2 < 2^128, exact /3
        // note: for BLS12-381, (|x|+1) < 2^64 so lo is the whole square;
        // exactness checked below
        if (lo % 3 != 0) return -90;
    }
    D_EXP[0] = (u64)d;
    D_EXP[1] = (u64)(d >> 64);
    // curve b constants
    fp four;
    fp_from_u64(four, 4);
    OpsFp::curve_b = four;
    OpsFp2::curve_b.c0 = four;
    OpsFp2::curve_b.c1 = four;                    // 4(1+u)
    // Frobenius constants: FR1[1] = xi^((p-1)/6); FR1[i] = FR1[1]^i
    fp2 base;
    fp2_pow_limbs(base, XI_, EXP_P16, 6);
    FR1[0] = FP2_ONE_;
    for (int i = 1; i < 6; ++i) fp2_mul(FR1[i], FR1[i - 1], base);
    // FR2[1] = norm(FR1[1]) in Fp; FR2[i] = FR2[1]^i
    fp2 cj, n;
    fp2_conj(cj, base);
    fp2_mul(n, base, cj);
    if (!fp_is_zero(n.c1)) return -91;
    FR2[0] = FP_ONE_MONT;
    for (int i = 1; i < 6; ++i) fp_mul(FR2[i], FR2[i - 1], n.c0);
    // psi constants
    fp2_inv(PSI_CX_, FR1[2]);
    fp2_inv(PSI_CY_, FR1[3]);
    // generators (canonical constants, checked on curve + subgroup below)
    static const uint8_t G1X[48] = {
        0x17,0xf1,0xd3,0xa7,0x31,0x97,0xd7,0x94,0x26,0x95,0x63,0x8c,
        0x4f,0xa9,0xac,0x0f,0xc3,0x68,0x8c,0x4f,0x97,0x74,0xb9,0x05,
        0xa1,0x4e,0x3a,0x3f,0x17,0x1b,0xac,0x58,0x6c,0x55,0xe8,0x3f,
        0xf9,0x7a,0x1a,0xef,0xfb,0x3a,0xf0,0x0a,0xdb,0x22,0xc6,0xbb};
    static const uint8_t G1Y[48] = {
        0x08,0xb3,0xf4,0x81,0xe3,0xaa,0xa0,0xf1,0xa0,0x9e,0x30,0xed,
        0x74,0x1d,0x8a,0xe4,0xfc,0xf5,0xe0,0x95,0xd5,0xd0,0x0a,0xf6,
        0x00,0xdb,0x18,0xcb,0x2c,0x04,0xb3,0xed,0xd0,0x3c,0xc7,0x44,
        0xa2,0x88,0x8a,0xe4,0x0c,0xaa,0x23,0x29,0x46,0xc5,0xe7,0xe1};
    static const uint8_t G2X0[48] = {
        0x02,0x4a,0xa2,0xb2,0xf0,0x8f,0x0a,0x91,0x26,0x08,0x05,0x27,
        0x2d,0xc5,0x10,0x51,0xc6,0xe4,0x7a,0xd4,0xfa,0x40,0x3b,0x02,
        0xb4,0x51,0x0b,0x64,0x7a,0xe3,0xd1,0x77,0x0b,0xac,0x03,0x26,
        0xa8,0x05,0xbb,0xef,0xd4,0x80,0x56,0xc8,0xc1,0x21,0xbd,0xb8};
    static const uint8_t G2X1[48] = {
        0x13,0xe0,0x2b,0x60,0x52,0x71,0x9f,0x60,0x7d,0xac,0xd3,0xa0,
        0x88,0x27,0x4f,0x65,0x59,0x6b,0xd0,0xd0,0x99,0x20,0xb6,0x1a,
        0xb5,0xda,0x61,0xbb,0xdc,0x7f,0x50,0x49,0x33,0x4c,0xf1,0x12,
        0x13,0x94,0x5d,0x57,0xe5,0xac,0x7d,0x05,0x5d,0x04,0x2b,0x7e};
    static const uint8_t G2Y0[48] = {
        0x0c,0xe5,0xd5,0x27,0x72,0x7d,0x6e,0x11,0x8c,0xc9,0xcd,0xc6,
        0xda,0x2e,0x35,0x1a,0xad,0xfd,0x9b,0xaa,0x8c,0xbd,0xd3,0xa7,
        0x6d,0x42,0x9a,0x69,0x51,0x60,0xd1,0x2c,0x92,0x3a,0xc9,0xcc,
        0x3b,0xac,0xa2,0x89,0xe1,0x93,0x54,0x86,0x08,0xb8,0x28,0x01};
    static const uint8_t G2Y1[48] = {
        0x06,0x06,0xc4,0xa0,0x2e,0xa7,0x34,0xcc,0x32,0xac,0xd2,0xb0,
        0x2b,0xc2,0x8b,0x99,0xcb,0x3e,0x28,0x7e,0x85,0xa7,0x63,0xaf,
        0x26,0x74,0x92,0xab,0x57,0x2e,0x99,0xab,0x3f,0x37,0x0d,0x27,
        0x5c,0xec,0x1d,0xa1,0xaa,0xa9,0x07,0x5f,0xf0,0x5f,0x79,0xbe};
    if (fp_from_bytes(G1_GX, G1X) || fp_from_bytes(G1_GY, G1Y)) return -92;
    if (fp_from_bytes(G2_GX.c0, G2X0) || fp_from_bytes(G2_GX.c1, G2X1) ||
        fp_from_bytes(G2_GY.c0, G2Y0) || fp_from_bytes(G2_GY.c1, G2Y1))
        return -92;
    if (!pt_on_curve_affine<OpsFp>(G1_GX, G1_GY)) return -93;
    if (!pt_on_curve_affine<OpsFp2>(G2_GX, G2_GY)) return -94;
    {
        g1pt g = pt_from_affine<OpsFp>(G1_GX, G1_GY);
        if (!g1_in_subgroup(g)) return -95;
        g2pt h = pt_from_affine<OpsFp2>(G2_GX, G2_GY);
        if (!g2_in_subgroup(h)) return -96;
    }
    // SVDW: Z_G1 = -3, Z_G2 = u (what refimpl's search finds; asserted here)
    fp three, zg1;
    fp_from_u64(three, 3);
    fp_neg(zg1, three);
    if (!svdw_init<OpsFp>(SVDW1, zg1, fp_is_square, fp_sqrt, fp_sgn0))
        return -97;
    fp2 zg2;
    zg2.c0 = FP_ZERO; zg2.c1 = FP_ONE_MONT;
    if (!svdw_init<OpsFp2>(SVDW2, zg2, fp2_is_square, fp2_sqrt, fp2_sgn0))
        return -98;
    return 0;
}

static int ensure_init() {
    if (!INIT_DONE) {
        INIT_STATUS = do_init();
        INIT_DONE = true;
    }
    return INIT_STATUS;
}

// ---------------------------------------------------------------------------
// C ABI
// ---------------------------------------------------------------------------

extern "C" {

// 0 on success (library functional)
int dbls_init() { return ensure_init(); }

int dbls_hash_to_g2(const uint8_t* msg, u64 len, uint8_t out[96]) {
    if (ensure_init()) return -100;
    g2pt q;
    hash_to_g2_point(q, msg, (size_t)len);
    g2aff a = g2_to_aff(q);
    g2_serialize(out, a);
    return 0;
}

int dbls_hash_to_g1(const uint8_t* msg, u64 len, uint8_t out[48]) {
    if (ensure_init()) return -100;
    g1pt q;
    hash_to_g1_point(q, msg, (size_t)len);
    g1aff a = g1_to_aff(q);
    g1_serialize(out, a);
    return 0;
}

// sig = sk * H(msg); sk is 32 big-endian bytes (mod r)
int dbls_sign(const uint8_t* msg, u64 len, const uint8_t sk[32],
              uint8_t out[96]) {
    if (ensure_init()) return -100;
    g2pt h, s;
    hash_to_g2_point(h, msg, (size_t)len);
    u64 e[4];
    scalar_from_bytes(e, sk);
    pt_mul_limbs(s, h, e, 4);
    g2aff a = g2_to_aff(s);
    g2_serialize(out, a);
    return 0;
}

// 1 = valid, 0 = invalid signature, <0 = malformed encodings
int dbls_verify(const uint8_t pk[48], const uint8_t* msg, u64 len,
                const uint8_t sig[96]) {
    if (ensure_init()) return -100;
    g1aff pka;
    int rc = g1_deserialize(pka, pk, 1);
    if (rc) return rc;
    g2aff siga;
    rc = g2_deserialize(siga, sig, 1);
    if (rc) return rc;
    if (siga.inf) return 0;                       // identity sig rejected
    g2pt h;
    hash_to_g2_point(h, msg, (size_t)len);
    g2aff ha = g2_to_aff(h);
    // e(-G1, sig) * e(pk, H(m)) == 1
    g1aff ng;
    ng.x = G1_GX; fp_neg(ng.y, G1_GY); ng.inf = false;
    fp12 f = FP12_ONE_, res;
    miller_accumulate(f, ng, siga);
    miller_accumulate(f, pka, ha);
    final_exponentiation(res, f);
    return fp12_eq(res, FP12_ONE_) ? 1 : 0;
}

// verify with a precomputed (trusted, already-subgroup) H(m) point
int dbls_verify_pre(const uint8_t pk[48], const uint8_t hm[96],
                    const uint8_t sig[96]) {
    if (ensure_init()) return -100;
    g1aff pka;
    int rc = g1_deserialize(pka, pk, 1);
    if (rc) return rc;
    g2aff siga, ha;
    rc = g2_deserialize(siga, sig, 1);
    if (rc) return rc;
    rc = g2_deserialize(ha, hm, 0);               // trusted: skip subgroup
    if (rc) return rc;
    if (siga.inf) return 0;
    g1aff ng;
    ng.x = G1_GX; fp_neg(ng.y, G1_GY); ng.inf = false;
    fp12 f = FP12_ONE_, res;
    miller_accumulate(f, ng, siga);
    miller_accumulate(f, pka, ha);
    final_exponentiation(res, f);
    return fp12_eq(res, FP12_ONE_) ? 1 : 0;
}

// out = sum scalars[i] * points[i]; points 48B compressed, scalars 32B BE.
// check!=0 validates each point's subgroup membership.
int dbls_g1_msm(const uint8_t* pts, const uint8_t* scalars, u64 n, int check,
                uint8_t out[48]) {
    if (ensure_init()) return -100;
    if (n == 0 || n > 100000) return -1;
    fp* xs = new fp[n];
    fp* ys = new fp[n];
    bool* infs = new bool[n];
    u64 (*es)[4] = new u64[n][4];
    int rc = 0;
    for (u64 i = 0; i < n && rc == 0; ++i) {
        g1aff a;
        rc = g1_deserialize(a, pts + i * 48, check);
        if (rc) break;
        xs[i] = a.x; ys[i] = a.y; infs[i] = a.inf;
        scalar_from_bytes(es[i], scalars + i * 32);
    }
    if (rc == 0) {
        g1pt res;
        msm_run<OpsFp>(res, xs, ys, infs, es, (size_t)n);
        g1aff a = g1_to_aff(res);
        g1_serialize(out, a);
    }
    delete[] xs; delete[] ys; delete[] infs; delete[] es;
    return rc;
}

int dbls_g2_msm(const uint8_t* pts, const uint8_t* scalars, u64 n, int check,
                uint8_t out[96]) {
    if (ensure_init()) return -100;
    if (n == 0 || n > 100000) return -1;
    fp2* xs = new fp2[n];
    fp2* ys = new fp2[n];
    bool* infs = new bool[n];
    u64 (*es)[4] = new u64[n][4];
    int rc = 0;
    for (u64 i = 0; i < n && rc == 0; ++i) {
        g2aff a;
        rc = g2_deserialize(a, pts + i * 96, check);
        if (rc) break;
        xs[i] = a.x; ys[i] = a.y; infs[i] = a.inf;
        scalar_from_bytes(es[i], scalars + i * 32);
    }
    if (rc == 0) {
        g2pt res;
        msm_run<OpsFp2>(res, xs, ys, infs, es, (size_t)n);
        g2aff a = g2_to_aff(res);
        g2_serialize(out, a);
    }
    delete[] xs; delete[] ys; delete[] infs; delete[] es;
    return rc;
}

// out = scalar * point (point NULL -> group generator)
int dbls_g1_mul(const uint8_t* pt48, const uint8_t sk[32], uint8_t out[48]) {
    if (ensure_init()) return -100;
    g1aff a;
    if (pt48) {
        int rc = g1_deserialize(a, pt48, 1);
        if (rc) return rc;
    } else {
        a.x = G1_GX; a.y = G1_GY; a.inf = false;
    }
    u64 e[4];
    scalar_from_bytes(e, sk);
    g1pt p = a.inf ? pt_infinity<OpsFp>() : pt_from_affine<OpsFp>(a.x, a.y);
    g1pt r;
    pt_mul_limbs(r, p, e, 4);
    g1aff ra = g1_to_aff(r);
    g1_serialize(out, ra);
    return 0;
}

int dbls_g2_mul(const uint8_t* pt96, const uint8_t sk[32], uint8_t out[96]) {
    if (ensure_init()) return -100;
    g2aff a;
    if (pt96) {
        int rc = g2_deserialize(a, pt96, 1);
        if (rc) return rc;
    } else {
        a.x = G2_GX; a.y = G2_GY; a.inf = false;
    }
    u64 e[4];
    scalar_from_bytes(e, sk);
    g2pt p = a.inf ? pt_infinity<OpsFp2>() : pt_from_affine<OpsFp2>(a.x, a.y);
    g2pt r;
    pt_mul_limbs(r, p, e, 4);
    g2aff ra = g2_to_aff(r);
    g2_serialize(out, ra);
    return 0;
}

// point validation: 0 ok (incl. infinity), <0 malformed/off-curve/subgroup
int dbls_g1_check(const uint8_t pt48[48]) {
    if (ensure_init()) return -100;
    g1aff a;
    return g1_deserialize(a, pt48, 1);
}

int dbls_g2_check(const uint8_t pt96[96]) {
    if (ensure_init()) return -100;
    g2aff a;
    return g2_deserialize(a, pt96, 1);
}

// g1 + g1 / g2 + g2 (compressed in/out) — protocol-plane group ops
int dbls_g1_add(const uint8_t a48[48], const uint8_t b48[48],
                uint8_t out[48]) {
    if (ensure_init()) return -100;
    g1aff a, b;
    int rc = g1_deserialize(a, a48, 0);
    if (rc) return rc;
    rc = g1_deserialize(b, b48, 0);
    if (rc) return rc;
    g1pt pa = a.inf ? pt_infinity<OpsFp>() : pt_from_affine<OpsFp>(a.x, a.y);
    if (!b.inf) pt_add_affine(pa, pa, b.x, b.y);
    g1aff ra = g1_to_aff(pa);
    g1_serialize(out, ra);
    return 0;
}

int dbls_g2_add(const uint8_t a96[96], const uint8_t b96[96],
                uint8_t out[96]) {
    if (ensure_init()) return -100;
    g2aff a, b;
    int rc = g2_deserialize(a, a96, 0);
    if (rc) return rc;
    rc = g2_deserialize(b, b96, 0);
    if (rc) return rc;
    g2pt pa = a.inf ? pt_infinity<OpsFp2>() : pt_from_affine<OpsFp2>(a.x, a.y);
    if (!b.inf) pt_add_affine(pa, pa, b.x, b.y);
    g2aff ra = g2_to_aff(pa);
    g2_serialize(out, ra);
    return 0;
}

// full pairing e(P,Q) -> canonical 576-byte GT (12 x 48B BE, tower order
// c0.c0.c0, c0.c0.c1, c0.c1.c0, ..., c1.c2.c1) — refimpl cross-check hook
int dbls_pairing(const uint8_t p48[48], const uint8_t q96[96],
                 uint8_t out[576]) {
    if (ensure_init()) return -100;
    g1aff p;
    int rc = g1_deserialize(p, p48, 1);
    if (rc) return rc;
    g2aff q;
    rc = g2_deserialize(q, q96, 1);
    if (rc) return rc;
    fp12 g;
    pairing_full(g, p, q);
    const fp2* cs[6] = {&g.c0.c0, &g.c0.c1, &g.c0.c2,
                        &g.c1.c0, &g.c1.c1, &g.c1.c2};
    for (int i = 0; i < 6; ++i) {
        fp_to_bytes(out + i * 96, cs[i]->c0);
        fp_to_bytes(out + i * 96 + 48, cs[i]->c1);
    }
    return 0;
}

// internal coherence check: bilinearity + hash/codec round trips.
int dbls_selfcheck() {
    if (ensure_init()) return -100;
    // pairing bilinearity: e(aG1, bG2) == e(G1, G2)^(ab), via e(aG1,bG2) ==
    // e(abG1, G2) and non-degeneracy
    uint8_t a_sc[32], b_sc[32], ab_sc[32];
    memset(a_sc, 0, 32); memset(b_sc, 0, 32); memset(ab_sc, 0, 32);
    a_sc[31] = 5; b_sc[31] = 7; ab_sc[31] = 35;
    uint8_t pa[48], qb[96], pab[48], g1b[48], g2b[96];
    g1aff g1g; g1g.x = G1_GX; g1g.y = G1_GY; g1g.inf = false;
    g2aff g2g; g2g.x = G2_GX; g2g.y = G2_GY; g2g.inf = false;
    g1_serialize(g1b, g1g);
    g2_serialize(g2b, g2g);
    if (dbls_g1_mul(nullptr, a_sc, pa)) return -1;
    if (dbls_g2_mul(nullptr, b_sc, qb)) return -2;
    if (dbls_g1_mul(nullptr, ab_sc, pab)) return -3;
    uint8_t e1[576], e2[576], e3[576];
    if (dbls_pairing(pa, qb, e1)) return -4;
    if (dbls_pairing(pab, g2b, e2)) return -5;
    if (memcmp(e1, e2, 576) != 0) return -6;
    if (dbls_pairing(g1b, g2b, e3)) return -7;
    if (memcmp(e1, e3, 576) == 0) return -8;      // non-degeneracy
    // sign/verify round trip
    uint8_t sk[32];
    memset(sk, 0, 32);
    sk[31] = 42; sk[0] = 1;
    uint8_t pk[48], sig[96];
    if (dbls_g1_mul(nullptr, sk, pk)) return -9;
    const uint8_t msg[] = "dbls-selfcheck";
    if (dbls_sign(msg, sizeof msg - 1, sk, sig)) return -10;
    if (dbls_verify(pk, msg, sizeof msg - 1, sig) != 1) return -11;
    sig[95] ^= 1;
    int rc = dbls_verify(pk, msg, sizeof msg - 1, sig);
    if (rc == 1) return -12;                      // tampered must not verify
    return 0;
}

}  // extern "C"
