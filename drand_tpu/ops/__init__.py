"""TPU-native BLS12-381 kernels (JAX / XLA / Pallas).

This package replaces the reference's external crypto hot path
(`github.com/drand/bls12-381` + `github.com/drand/kyber`, selected at
/root/reference/key/curve.go:12-30) with batched, fixed-shape JAX
computations suitable for the MXU/VPU:

- :mod:`drand_tpu.ops.fp`      — base field Fp as 34x12-bit int32 limb vectors
                                  (Montgomery arithmetic, lazy carries)
- :mod:`drand_tpu.ops.tower`   — Fp2 / Fp6 / Fp12 extension tower + Frobenius
- :mod:`drand_tpu.ops.curve`   — G1/G2 complete projective point arithmetic
- :mod:`drand_tpu.ops.pairing` — optimal-ate Miller loop + final exponentiation
- :mod:`drand_tpu.ops.msm`     — multi-scalar multiplication (Lagrange recovery)

Everything is jit/vmap-compatible with static shapes: scalar loops are
`lax.scan` / unrolled constant-trip loops, carries are fixed-pass parallel
sweeps, there is no data-dependent control flow.

Importing this package enables JAX's persistent compilation cache (set
``DRAND_TPU_COMPILE_CACHE`` — or the older ``DRAND_TPU_XLA_CACHE`` — to
relocate it, or to ``off`` to disable): the pairing pipeline costs
minutes of XLA compile time per shape on a small host but milliseconds
to reload from cache.

Every entry point is dispatched through ``obs.kernels.kernel_span`` by
the crypto backends (crypto/tbls.py): block-until-ready wall timings with
batch/padded-shape attributes feed the ``drand_device_kernel_seconds``
histograms, the round trace and the flight recorder.
"""

import os as _os

#: kernel families the observability plane times (obs/kernels.py);
#: `kernel.<op>` spans and per-op histogram series use these names
INSTRUMENTED_KERNELS = ("pairing_check", "msm_recover", "g2_sign", "h2c")

import jax as _jax


def configure_compile_cache(path=None):
    """Point JAX's persistent compilation cache at a directory.

    Resolution order: explicit `path` argument, then
    ``DRAND_TPU_COMPILE_CACHE`` (the documented operator knob), then
    ``DRAND_TPU_XLA_CACHE`` (the original name, kept for compat), then
    ``~/.cache/drand_tpu_xla``.  The value ``off`` disables the cache.
    Returns the directory in use, or None when disabled.

    Runs once at package import, and again from `JaxScheme.__init__` /
    `cli.py --compile-cache` so an env var or flag set after this module
    was first imported still takes effect before anything compiles —
    the multi-minute Mosaic/XLA compiles are then paid once per host,
    not once per process.
    """
    cache = path or _os.environ.get("DRAND_TPU_COMPILE_CACHE", "") \
        or _os.environ.get("DRAND_TPU_XLA_CACHE", "")
    if cache == "off":
        return None
    if not cache:
        cache = _os.path.join(
            _os.path.expanduser("~"), ".cache", "drand_tpu_xla"
        )
    _os.makedirs(cache, exist_ok=True)
    _jax.config.update("jax_compilation_cache_dir", cache)
    _jax.config.update("jax_persistent_cache_min_compile_time_secs", 5.0)
    _jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    return cache


COMPILE_CACHE_DIR = configure_compile_cache()
