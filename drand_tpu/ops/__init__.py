"""TPU-native BLS12-381 kernels (JAX / XLA / Pallas).

This package replaces the reference's external crypto hot path
(`github.com/drand/bls12-381` + `github.com/drand/kyber`, selected at
/root/reference/key/curve.go:12-30) with batched, fixed-shape JAX
computations suitable for the MXU/VPU:

- :mod:`drand_tpu.ops.fp`      — base field Fp as 34x12-bit int32 limb vectors
                                  (Montgomery arithmetic, lazy carries)
- :mod:`drand_tpu.ops.tower`   — Fp2 / Fp6 / Fp12 extension tower + Frobenius
- :mod:`drand_tpu.ops.curve`   — G1/G2 complete projective point arithmetic
- :mod:`drand_tpu.ops.pairing` — optimal-ate Miller loop + final exponentiation
- :mod:`drand_tpu.ops.msm`     — multi-scalar multiplication (Lagrange recovery)

Everything is jit/vmap-compatible with static shapes: scalar loops are
`lax.scan` / unrolled constant-trip loops, carries are fixed-pass parallel
sweeps, there is no data-dependent control flow.

Importing this package enables JAX's persistent compilation cache (set
``DRAND_TPU_XLA_CACHE`` to relocate it, or to ``off`` to disable): the
pairing pipeline costs minutes of XLA compile time per shape on a small
host but milliseconds to reload from cache.

Every entry point is dispatched through ``obs.kernels.kernel_span`` by
the crypto backends (crypto/tbls.py): block-until-ready wall timings with
batch/padded-shape attributes feed the ``drand_device_kernel_seconds``
histograms, the round trace and the flight recorder.
"""

import os as _os

#: kernel families the observability plane times (obs/kernels.py);
#: `kernel.<op>` spans and per-op histogram series use these names
INSTRUMENTED_KERNELS = ("pairing_check", "msm_recover", "g2_sign", "h2c")

import jax as _jax

_cache = _os.environ.get("DRAND_TPU_XLA_CACHE", "")
if _cache != "off":
    if not _cache:
        _cache = _os.path.join(
            _os.path.expanduser("~"), ".cache", "drand_tpu_xla"
        )
    _os.makedirs(_cache, exist_ok=True)
    _jax.config.update("jax_compilation_cache_dir", _cache)
    _jax.config.update("jax_persistent_cache_min_compile_time_secs", 5.0)
    _jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
